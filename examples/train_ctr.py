"""Complete day/pass CTR training workflow — the user-facing shape of the
framework, end to end:

  slot-text files → SlotDataset (load + shuffle) → day loop of passes
  (BoxPS lifecycle, join/update phase flip, per-pass AUC + cmatch metrics)
  → crash-safe per-pass snapshots (PassCheckpointer: atomic manifested
  base/delta chain) + day-end base models with donefiles (FleetUtil) →
  crash recovery via both paths → serving export (Predictor scores the
  eval slice) → online serving (every end_pass publishes a versioned
  base/delta artifact; a ServingServer tails the donefile, hot-swaps it
  in, and a BatchingFrontend scores at concurrency — README "Serving
  runbook").

Runs hardware-free on the 8-virtual-device CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_ctr.py

On a TPU host, drop the env vars — the same script trains on the chips.
This mirrors the reference's user workflow (dataset.set_date / begin_pass /
train_from_dataset / end_pass / fleet_util.save_*_model — SURVEY.md §3.4).

Observability (the telemetry-hub quickstart, README "Observability"):
``PBTPU_TELEMETRY_DIR=/some/dir`` turns the hub's event stream on — a
JSONL event file (``events.jsonl``: tagged events/spans + one flight
record per pass), log_for_profile-parity pass lines on stdout, a
Prometheus text exposition (``metrics.prom``), and a chrome trace
(``trace.json``) with pass-boundary / checkpoint-commit markers.
``--short`` trains one day instead of two (the tier-1 telemetry smoke
runs this path).

``--multihost`` demos the ISSUE-5 whole-world crash recovery instead: a
2-process world (FileStore control plane, run-scoped heartbeats +
watchdog, lockstep pass barriers, per-rank crash-safe snapshots) loses
rank 1 to a hard kill mid-run; the relaunched world runs the COORDINATED
resume election — every rank publishes its intact snapshot cursors, the
highest cursor every rank holds intact wins — and finishes training from
the same cursor on every rank.
"""

from __future__ import annotations

import os
import sys
import tempfile

import numpy as np


def synth_files(root: str, schema, n_files: int = 4, lines: int = 512,
                seed: int = 0) -> list[str]:
    """Write Criteo-like MultiSlot text: label, dense floats, id slots —
    with real signal (ids carry latent weights)."""
    rng = np.random.default_rng(seed)
    S = len(schema.sparse_slots)
    F = len(schema.float_slots) - 1
    id_w = np.random.default_rng(99).normal(size=(S, 1000)) * 1.2
    files = []
    for f in range(n_files):
        rows = []
        for _ in range(lines):
            ids = rng.integers(0, 1000, size=S)
            logit = id_w[np.arange(S), ids].sum() * 0.7
            label = float(rng.random() < 1 / (1 + np.exp(-logit)))
            parts = [f"1 {label}"]
            parts += [f"1 {rng.normal():.4f}" for _ in range(F)]
            parts += [f"1 {int(i) + s * 1000003}"
                      for s, i in enumerate(ids)]
            rows.append(" ".join(parts))
        p = os.path.join(root, f"part-{f:03d}.txt")
        with open(p, "w") as fh:
            fh.write("\n".join(rows) + "\n")
        files.append(p)
    return files


def _multihost_worker() -> int:
    """One rank of the --multihost recovery demo (spawned by launch)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.distributed import HeartbeatMonitor, RoleMaker
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer

    rm = RoleMaker.from_env()
    col = rm.collectives(timeout_s=120)
    # col.store is already run-id-namespaced by RoleMaker
    hb = HeartbeatMonitor(col.store, rm.rank, rm.world_size,
                          interval_s=1.0)
    col.watchdog = hb          # barrier waits fail with NAMED dead ranks

    num_slots = 4
    schema = DataFeedSchema.ctr(num_sparse=num_slots, num_float=1,
                                batch_size=64, max_len=1)
    data_dir = tempfile.mkdtemp(prefix=f"pbtpu_mh_rank{rm.rank}_")
    files = synth_files(data_dir, schema, n_files=2, lines=256,
                        seed=100 + rm.rank)      # per-rank shard
    ds = SlotDataset(schema)
    ds.set_filelist(files)
    ds.load_into_memory(global_shuffle=False)

    store = HostEmbeddingStore(EmbeddingConfig(dim=4, learning_rate=0.1))
    tr = Trainer(DNNCTRModel(num_slots=num_slots, emb_dim=4, dense_dim=1,
                             hidden=(16,)),
                 store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=64, dense_lr=3e-3,
                               auc_buckets=1 << 10), seed=7 + rm.rank)
    box = BoxPS(store)
    box.set_date(20260803)
    box.attach_collectives(col, heartbeat=hb)    # lockstep pass barriers
    ckpt = PassCheckpointer(
        os.path.join(os.environ["PBTPU_MH_ROOT"], f"rank{rm.rank}"),
        keep_last_n=3, base_every=2)

    # coordinated resume election: all ranks restore the SAME cursor
    cursor = tr.resume(ckpt, box=box, collectives=col)
    start = (int(cursor["pass_id"]) if cursor is not None else 0) + 1
    print(f"[rank {rm.rank}] elected cursor: "
          f"{None if cursor is None else cursor.get('elected')} "
          f"-> entering pass {start}", flush=True)
    for p in range(start, 4):
        box.begin_pass()
        stats = tr.train_pass(ds)
        box.end_pass(checkpointer=ckpt, trainer=tr, dataset=ds)
        print(f"[rank {rm.rank}] pass {box.pass_id}: "
              f"auc={stats['auc']:.3f}", flush=True)
        if (p == 2 and rm.rank == 1
                and os.environ.get("PBTPU_MH_KILL") == "1"):
            print("[rank 1] simulating preemption: hard kill, no cleanup",
                  flush=True)
            os._exit(137)
    hb.close()
    print(f"[rank {rm.rank}] done", flush=True)
    return 0


def _multihost_demo() -> int:
    """Parent of the --multihost demo: world 1 loses rank 1 mid-run; the
    relaunched world 2 elects the newest snapshot every rank holds intact
    and finishes from it."""
    from paddlebox_tpu.distributed.launch import launch
    root = tempfile.mkdtemp(prefix="pbtpu_mh_demo_")
    env = {"PBTPU_MH_ROOT": root, "JAX_PLATFORMS": "cpu"}
    print("== world 1: rank 1 will be hard-killed after pass 2 ==")
    code = launch(2, [sys.executable, os.path.abspath(__file__),
                      "--mh-worker"], base_env=dict(env, PBTPU_MH_KILL="1"))
    print(f"== world 1 fail-stopped (exit {code}) ==")
    print("== world 2: coordinated resume election ==")
    code = launch(2, [sys.executable, os.path.abspath(__file__),
                      "--mh-worker"], base_env=env)
    print(f"== world 2 finished (exit {code}) ==")
    assert code == 0, "resumed world failed"
    print("multihost recovery demo complete:", root)
    return 0


def main() -> int:
    import jax
    from paddlebox_tpu import monitor
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.fleet import BoxPS, FleetUtil
    from paddlebox_tpu.inference import Predictor, save_inference_model
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig
    from paddlebox_tpu.utils import profiler

    short = "--short" in sys.argv
    telemetry_dir = os.environ.get("PBTPU_TELEMETRY_DIR")
    if telemetry_dir:
        # observability quickstart: JSONL event stream + parity stdout
        # lines; host spans collected for the chrome trace exported below
        os.makedirs(telemetry_dir, exist_ok=True)
        monitor.hub().enable(
            monitor.JsonlSink(os.path.join(telemetry_dir, "events.jsonl")),
            monitor.ParityLogSink())
        profiler.enable_profiler()

    work = tempfile.mkdtemp(prefix="pbtpu_example_")
    out_root = os.path.join(work, "output")
    num_slots, emb_dim = 8, 8
    schema = DataFeedSchema.ctr(num_sparse=num_slots, num_float=2,
                                batch_size=128, max_len=1)
    files = synth_files(work, schema)

    store = HostEmbeddingStore(EmbeddingConfig(dim=emb_dim,
                                               optimizer="adagrad",
                                               learning_rate=0.1))
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer

    box = BoxPS(store)
    box.init_metric("auc", method="plain")
    fleet = FleetUtil(out_root)
    # crash-safe pass snapshots: atomic manifested base/delta chain +
    # dense/optimizer/metric planes + cursor; resume() falls back past a
    # torn newest snapshot by checksum
    ckpt = PassCheckpointer(os.path.join(work, "snapshots"),
                            keep_last_n=3, base_every=2)
    mesh = make_mesh(min(8, len(jax.devices())))
    model = DeepFMModel(num_slots=num_slots, emb_dim=emb_dim, dense_dim=2,
                        hidden=(64, 32))
    tr = Trainer(model, store, schema, mesh,
                 TrainerConfig(global_batch_size=128, dense_lr=3e-3,
                               auc_buckets=1 << 12))

    ds = SlotDataset(schema)
    ds.set_filelist(files)

    # online serving publisher (ISSUE 7): every end_pass below also
    # ships this pass's model to the serving root — a full base every
    # publish_base_every passes, an exact key-delta otherwise, cold rows
    # int8, announced by donefile only after a verified commit
    from paddlebox_tpu.serving import (BatchingFrontend, ServingPublisher,
                                       ServingServer)
    serve_root = os.path.join(work, "serving")
    pub = ServingPublisher(serve_root, model, schema,
                           publish_base_every=2, quant="int8",
                           hot_top_k=256)

    days = [20260729] if short else [20260729, 20260730]
    passes_per_day = 2
    for day in days:
        box.set_date(day)
        for p in range(passes_per_day):
            ds.load_into_memory(global_shuffle=False)
            box.begin_pass()
            stats = tr.train_pass(ds, metrics=box.metrics)
            # single delta writer per store: save_delta consumes the
            # dirty mask, so per-pass persistence belongs to ONE owner —
            # here the crash-safe checkpointer. (Stacking
            # fleet.save_delta_model on top would write EMPTY fleet
            # deltas; the day-end fleet base below is a full snapshot
            # and stays exact regardless.)
            info = box.end_pass(checkpointer=ckpt, trainer=tr,
                                publisher=pub)
            last_snapshot_keys = len(store)
            msg = box.get_metric_msg("auc")
            pinfo = info.get("publish", {})
            print(f"day {day} pass {box.pass_id}: "
                  f"auc={stats['auc']:.3f} "
                  f"registry_auc={msg.get('auc', float('nan')):.3f} "
                  f"loss={stats['loss_mean']:.4f} "
                  f"({info['seconds']:.1f}s) → published "
                  f"v{pinfo.get('version')} ({pinfo.get('kind')}, "
                  f"{pinfo.get('bytes', 0)} bytes)")
        # end of day: table hygiene, then persist the base model — the
        # saved base must reflect the post-shrink table so recovery
        # reproduces the live store exactly
        evicted = box.shrink_table(min_show=0.5, decay=0.98)
        fleet.save_model(store, tr.eval_params(), day)
        print(f"day {day}: shrink evicted {evicted}, base model saved")

    # ---- crash recovery path 1: rebuild from the newest donefiles ----
    store2, dense2, rec_day = fleet.load_model(tr.eval_params())
    print(f"recovered day {rec_day}: {len(store2)} keys "
          f"(live {len(store)})")
    assert len(store2) == len(store)

    # ---- crash recovery path 2: resume-from-pass (PassCheckpointer) ----
    # A preempted worker restarts, resumes every plane from the newest
    # verified snapshot, and re-enters the pass loop at the cursor.
    store3 = HostEmbeddingStore(EmbeddingConfig(dim=emb_dim,
                                                optimizer="adagrad",
                                                learning_rate=0.1))
    box3 = BoxPS(store3)
    box3.init_metric("auc", method="plain")
    tr3 = Trainer(model, store3, schema, mesh,
                  TrainerConfig(global_batch_size=128, dense_lr=3e-3,
                                auc_buckets=1 << 12), seed=123)
    cursor = tr3.resume(ckpt, box=box3)
    print(f"resumed at cursor {cursor}: {len(store3)} keys, "
          f"next pass {box3.pass_id + 1}")
    assert cursor["pass_id"] == box.pass_id
    # the snapshot is pass-granular: it captures the table as of the last
    # end_pass, i.e. BEFORE the day-end shrink that followed it
    assert len(store3) == last_snapshot_keys

    # ---- serving ----
    export = os.path.join(work, "export")
    save_inference_model(export, model, tr.eval_params(), store, schema)
    pred = Predictor.load(export)
    pb = next(iter(ds.batches(batch_size=128)))
    probs = pred.predict_batch(pb)
    labels, _ = tr.split_floats(pb.floats)
    order = np.argsort(probs)
    ranks = np.empty(len(probs)); ranks[order] = np.arange(len(probs))
    pos = labels > 0.5
    auc = ((ranks[pos].mean() - ranks[~pos].mean()) / len(probs) + 0.5
           if pos.any() and (~pos).any() else float("nan"))
    print(f"serving: scored {len(probs)} examples, AUC={auc:.3f}")
    assert auc > 0.6, "serving scores lost the training signal"

    # ---- online serving: tail the donefile, hot-swap, score at
    # concurrency (README "Serving runbook"; the same server runs
    # standalone as `python -m paddlebox_tpu.serving.server ROOT`) ----
    srv = ServingServer(serve_root, poll_s=0.1)
    applied = srv.poll_once()
    h = srv.health()
    print(f"serving host: applied {applied} published versions, "
          f"status={h['status']} v{h['active_version']} "
          f"(pass {h['active_pass']}, {h['table_keys']} keys, "
          f"{h['hot_cached_keys']} hot-cached, "
          f"swap pause {h['last_swap_pause_ms']}ms)")
    assert h["status"] == "ok" and h["active_pass"] == box.pass_id
    served = srv.predict_batch(pb)
    # published artifacts quantize cold rows int8 and the publish ran
    # BEFORE the day-end shrink — served scores track the live export
    # within that bounded skew, and must carry the same ranking signal
    assert np.corrcoef(probs, served)[0, 1] > 0.98
    fe = BatchingFrontend(srv, max_batch=64, max_wait_s=0.005).start()
    try:
        lc, lw, _ = schema.float_split_cols("label")
        floats = np.concatenate([pb.floats[:, :lc], pb.floats[:, lc + lw:]],
                                axis=1)
        futs = [fe.submit(pb.ids[i].astype(np.uint64), pb.mask[i],
                          floats[i]) for i in range(32)]
        got = np.asarray([f.result(timeout=300) for f in futs])
        st = fe.stats()
        np.testing.assert_allclose(got, served[:32], rtol=1e-5, atol=1e-6)
        assert st["failures"] == 0
        print(f"frontend: {st['count']} requests in {st['batches']} "
              f"batches, p50={st['p50_ms']}ms p99={st['p99_ms']}ms, "
              f"0 failures")
    finally:
        fe.stop()
        srv.stop()

    if telemetry_dir:
        # flush the event stream, write the Prometheus exposition, and
        # export the chrome trace (pass_begin/pass_end +
        # checkpoint_commit instant markers — open it in Perfetto)
        n_spans = profiler.export_chrome_trace(
            os.path.join(telemetry_dir, "trace.json"))
        with open(os.path.join(telemetry_dir, "metrics.prom"), "w") as f:
            f.write(monitor.hub().prometheus_text())
        flights = monitor.hub().flight_records()
        # run-doctor verdict over this run's records (the same analysis
        # `python -m paddlebox_tpu.monitor.doctor <dir>` runs offline —
        # README "Run doctor")
        from paddlebox_tpu.monitor import doctor as doctor_lib
        verdict = doctor_lib.diagnose_hub(monitor.hub())["verdict"]
        monitor.hub().disable()
        profiler.disable_profiler()
        print(f"telemetry: {len(flights)} flight records, {n_spans} trace "
              f"events -> {telemetry_dir}")
        print(f"doctor: {verdict}")
        from paddlebox_tpu.config import flags as _flags
        if _flags.trace:
            # world trace (PBTPU_TRACE=1): merge this rank's stream into
            # the Perfetto timeline — multi-rank runs merge every rank's
            # dir with `python -m paddlebox_tpu.monitor.trace` instead
            from paddlebox_tpu.monitor import trace as trace_lib
            wt = trace_lib.merge_roots([telemetry_dir])
            trace_lib.write_trace(
                wt, os.path.join(telemetry_dir, "world_trace.json"))
            s = trace_lib.summarize(wt)
            print(f"world trace: {s['spans']} spans, "
                  f"{len(s['flow_edges'])} flow edges -> "
                  f"{telemetry_dir}/world_trace.json")
    print("example complete:", work)
    return 0


if __name__ == "__main__":
    # runnable as a plain script (and as its own --mh-worker subprocess)
    # without an installed package or PYTHONPATH
    _REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    if "--mh-worker" in sys.argv:
        sys.exit(_multihost_worker())
    if "--multihost" in sys.argv:
        sys.exit(_multihost_demo())
    sys.exit(main())
