"""Benchmark: DeepFM training throughput on the available chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "examples/sec/chip", "vs_baseline": N}

vs_baseline is measured against the north-star target of 1M examples/sec/chip
(BASELINE.md; the reference publishes no numbers of its own). The measured
path is the full jitted train step: routed embedding lookup (all_to_all on
multi-chip meshes, direct gather on one), DeepFM forward/backward, dense-grad
pmean, sparse push with in-table adagrad, exactly as `Trainer` runs it.
Host-side batch translate is pre-staged (the reference's log_for_profile
likewise separates read/trans from cal time; boxps_worker.cc:746-759).
"""

from __future__ import annotations

import json
import time

import numpy as np

TARGET_PER_CHIP = 1_000_000.0  # BASELINE.md north star


def main() -> None:
    import os

    import jax

    small = os.environ.get("PBTPU_BENCH_SMALL") == "1"  # CPU smoke mode
    if small:
        jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    n_dev = len(devices)

    from paddlebox_tpu.data import DataFeedSchema
    from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                         PassWorkingSet)
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.parallel import make_mesh, mesh as mesh_lib
    from paddlebox_tpu.train import Trainer, TrainerConfig

    # Criteo-like geometry: 26 categorical slots (L=1) + 13 dense floats
    num_slots, emb_dim = 26, 8
    batch = (256 if small else 8192) * n_dev
    schema = DataFeedSchema.ctr(num_sparse=num_slots, num_float=13,
                                batch_size=batch, max_len=1)
    emb_cfg = EmbeddingConfig(dim=emb_dim, optimizer="adagrad",
                              learning_rate=0.05)
    store = HostEmbeddingStore(emb_cfg)
    mesh = make_mesh(n_dev)
    model = DeepFMModel(num_slots=num_slots, emb_dim=emb_dim, dense_dim=13,
                        hidden=(400, 400, 400))
    tr = Trainer(model, store, schema, mesh,
                 TrainerConfig(global_batch_size=batch, auc_buckets=1 << 16))

    import sys, time as _t
    _t0 = _t.time()
    def _mark(msg):
        print(f"# bench [{_t.time()-_t0:6.1f}s] {msg}", file=sys.stderr,
              flush=True)
    rng = np.random.default_rng(0)
    n_keys = 1 << (14 if small else 19)
    keys = rng.choice(1 << 50, n_keys, replace=False).astype(np.uint64)
    _mark("keys ready")
    ws = PassWorkingSet.begin_pass(store, keys, mesh)
    _mark("begin_pass done")
    T = tr.layout.total_len
    sh = mesh_lib.batch_sharding(mesh)

    # pre-staged batches (device-path throughput)
    n_staged = 4
    staged = []
    for _ in range(n_staged):
        raw = rng.choice(keys, size=(batch, T))
        mask = np.ones((batch, T), dtype=bool)
        idx = ws.translate(raw, mask)
        dense = rng.normal(size=(batch, 13)).astype(np.float32)
        labels = (rng.random(batch) < 0.25).astype(np.float32)
        staged.append(tuple(jax.device_put(a, sh) for a in
                            (idx, mask, dense, labels)))

    _mark("staged batches on device")
    table, params, opt = ws.table, tr.params, tr.opt_state
    # warmup/compile
    table, params, opt, loss, preds = tr._step_fn(table, params, opt,
                                                  *staged[0])
    jax.block_until_ready(loss)

    # second warmup step: the first fed-back step settles any layout change
    table, params, opt, loss, preds = tr._step_fn(table, params, opt,
                                                  *staged[1])
    jax.block_until_ready(loss)

    _mark("warmup/compile done")
    n_steps = 5 if small else 200
    windows = []
    for _ in range(1 if small else 3):
        t0 = time.perf_counter()
        for i in range(n_steps):
            table, params, opt, loss, preds = tr._step_fn(
                table, params, opt, *staged[i % n_staged])
        jax.block_until_ready((table, params, opt, loss, preds))
        windows.append(time.perf_counter() - t0)
    dt = min(windows)  # best sustained window (tunnel jitter is external)

    eps = n_steps * batch / dt
    eps_chip = eps / n_dev
    print(json.dumps({
        "metric": "deepfm_train_examples_per_sec_per_chip",
        "value": round(eps_chip, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(eps_chip / TARGET_PER_CHIP, 4),
        "detail": {
            "devices": n_dev,
            "global_batch": batch,
            "steps": n_steps,
            "seconds": round(dt, 3),
            "window_seconds": [round(w, 3) for w in windows],
            "working_set_keys": n_keys,
            "loss_final": float(loss),
        },
    }))


if __name__ == "__main__":
    main()
