"""Benchmark: DeepFM training throughput on the available chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "examples/sec/chip", "vs_baseline": N}

Two measurements, reported side by side (VERDICT r1 #2):

1. **device_step** — the jitted train step alone (routed embedding lookup,
   DeepFM fwd/bwd, dense pmean, sparse push with in-table adagrad), batches
   pre-staged on device. This is the device-path microbenchmark, the
   analogue of the reference's `cal` time in log_for_profile
   (boxps_worker.cc:746-759). It is NOT full-pipeline training throughput.
2. **e2e** — full `Trainer.train_pass` over TWO passes from a pre-built
   `.pbar` archive: working-set build (incremental on pass 2), per-batch
   translate, H2D, step, AUC — everything except parse (archive is
   pre-parsed, matching the reference's `read`/`trans`/`cal` split).

**Timing discipline**: every window is terminated by a real D2H
`device_get` of the final step's loss. `jax.block_until_ready` returns
EARLY over the axon tunnel (measured: a 55-TFLOP matmul chain "completed"
in 5ms, then the actual result took 2.9s to materialize), so any number
blocked on it alone is fiction — including this bench's own round-1 output.

**Self-audit**: the device-step number carries analytic FLOPs/step and
HBM bytes/step, and the implied MFU / HBM fractions against the detected
chip's peaks. An implied MFU > 60% means the measurement window is broken,
not that the code is fast — the bench then exits non-zero.

vs_baseline is measured against the north-star target of 1M examples/sec
per chip (BASELINE.md; the reference publishes no numbers of its own).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

TARGET_PER_CHIP = 1_000_000.0  # BASELINE.md north star

# (bf16 matmul FLOP/s, HBM bytes/s) per device_kind substring
PEAKS = {
    "v5 lite": (197e12, 819e9),
    "v5e": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v6 lite": (918e12, 1640e9),
    "v6e": (918e12, 1640e9),
    "v4": (275e12, 1228e9),
}


_STARTUP_SPLITS: list = []


def _startup_splits() -> int:
    """flags.binned_push_splits as configured at bench start (env
    override included), captured before any matrix point mutates it."""
    if not _STARTUP_SPLITS:
        from paddlebox_tpu.config import flags as config_flags
        _STARTUP_SPLITS.append(config_flags.binned_push_splits)
    return _STARTUP_SPLITS[0]


_STARTUP_FLAGS: dict = {}


def _startup_flag(name: str):
    """A flag's value at bench start, captured before any matrix point
    overrides it (the _startup_splits discipline, generalized for the
    sharded-exchange points' table_layout/exchange_wire overrides)."""
    if name not in _STARTUP_FLAGS:
        from paddlebox_tpu.config import flags as config_flags
        _STARTUP_FLAGS[name] = config_flags.get(name)
    return _STARTUP_FLAGS[name]


def _peaks(device_kind: str):
    dk = device_kind.lower()
    for key, val in PEAKS.items():
        if key in dk:
            return val
    return None


# ---------------------------------------------------------------------------
# Round-over-round regression gate (the discipline round 5 lacked: a 1.87x
# headline regression shipped inside a green artifact). BENCH_BEST.json
# holds the best RECORDED value per metric per matrix point; every number
# this run produces is compared against it, every point gets an explicit
# ok/REGRESS line in the artifact AND the compact tail, and an unwaived
# >threshold regression fails audit_ok + the process exit code.
# ---------------------------------------------------------------------------

GATE_THRESHOLD = 0.10


def load_bench_best() -> dict | None:
    """BENCH_BEST.json next to this file (PBTPU_BENCH_BEST overrides —
    tests inject synthetic bests through it). None when absent."""
    path = os.environ.get(
        "PBTPU_BENCH_BEST",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_BEST.json"))
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def collect_gate_metrics(eps_chip: float, detail: dict) -> dict:
    """Flatten this run's recorded numbers into the gate's metric
    namespace. Throughput metrics are higher-is-better; names ending in
    ``_ms``/``_seconds``/bare ``_s`` (the serving drills' latency and
    convergence points — ``_per_s`` stays throughput) are
    lower-is-better — apply_regression_gate keys the direction off the
    suffix."""
    m = {"headline_eps": eps_chip}
    for name, point in (detail.get("matrix") or {}).items():
        if isinstance(point, dict) and \
                "examples_per_sec_per_chip" in point:
            m[f"matrix.{name}"] = point["examples_per_sec_per_chip"]
    srv = (detail.get("matrix") or {}).get("serving")
    if isinstance(srv, dict):
        # the train→publish→serve loop's operator-facing numbers: how
        # long a publish takes, how long the hot-swap pauses requests,
        # and the tail latency the frontend holds under load
        for k in ("publish_seconds", "swap_pause_ms", "p99_ms"):
            if isinstance(srv.get(k), (int, float)):
                m[f"serving.{k}"] = srv[k]
    ss = (detail.get("matrix") or {}).get("serving_split")
    if isinstance(ss, dict):
        # version-split point (ISSUE 19): the served tail latency while
        # shadow scoring doubles the predictor work per request —
        # lower-is-better off the _ms suffix like the serving points
        if isinstance(ss.get("shadow_p99_ms"), (int, float)):
            m["serving_split.shadow_p99_ms"] = ss["shadow_p99_ms"]
    sf = (detail.get("matrix") or {}).get("serving_fleet")
    if isinstance(sf, dict):
        # fleet point (ISSUE 20): the routed tail latency while one
        # replica is injected slow — hedging must hold this gate — and
        # the wall from a version publish to EVERY replica serving it
        # (``_s`` suffix without ``_per_s`` is lower-is-better)
        for k in ("p99_ms", "swap_convergence_s"):
            if isinstance(sf.get(k), (int, float)):
                m[f"serving_fleet.{k}"] = sf[k]
    sp = (detail.get("matrix") or {}).get("spill_10x")
    if isinstance(sp, dict):
        # tiered-table point: cold-tier fetch throughput + the hot-tier
        # hit rate the admission policy holds under the 10x working set
        # (both higher-is-better; gate-held like every other point)
        for k in ("fetch_keys_per_s", "hot_hit_rate"):
            if isinstance(sp.get(k), (int, float)):
                m[f"spill_10x.{k}"] = sp[k]
    sa = (detail.get("matrix") or {}).get("spill_assoc")
    if isinstance(sa, dict):
        # set-associative geometry point: the N-way hot hit rate on the
        # adversarial colliding stream (the number direct-mapped caps)
        # plus the fetch throughput — both higher-is-better, gate-held
        for k in ("assoc_hit_rate", "fetch_keys_per_s"):
            if isinstance(sa.get(k), (int, float)):
                m[f"spill_assoc.{k}"] = sa[k]
    bd = (detail.get("matrix") or {}).get("boundary_incremental")
    if isinstance(bd, dict):
        # pass-boundary point: the incremental+overlapped boundary wall
        # (lower-is-better off the _seconds suffix) and the speedup it
        # holds over the full-rebuild baseline on the same key stream
        for k in ("boundary_seconds", "speedup"):
            if isinstance(bd.get(k), (int, float)):
                m[f"boundary_incremental.{k}"] = bd[k]
    e2e = detail.get("e2e")
    if isinstance(e2e, dict) and "examples_per_sec_per_chip" in e2e:
        m["e2e_eps"] = e2e["examples_per_sec_per_chip"]
    host = detail.get("host")
    if isinstance(host, dict) and \
            isinstance(host.get("derived_max_feed_eps_per_chip"),
                       (int, float)):
        m["host.derived_max_feed_eps"] = \
            host["derived_max_feed_eps_per_chip"]
    return m


def apply_regression_gate(current: dict, best: dict | None,
                          device_kind: str) -> dict:
    """Compare `current` metrics against the recorded bests.

    Returns the gate record for the artifact: per-metric
    ``ok(+x%)`` / ``REGRESS(-x%)`` / ``REGRESS(-x%) waived: note`` lines,
    and ``ok`` False iff any metric regressed more than the threshold
    WITHOUT an explicit waiver note. Skips (ok) when no best file exists
    or it was recorded on different hardware — a CPU dryrun must not
    "regress" against chip numbers."""
    if not best:
        return {"ok": True, "skipped": "no BENCH_BEST.json recorded"}
    want_kind = best.get("device_kind")
    if want_kind is not None and want_kind != device_kind:
        return {"ok": True,
                "skipped": f"BENCH_BEST records {want_kind!r}, this run "
                           f"is on {device_kind!r} — not comparable"}
    thresh = float(best.get("threshold", GATE_THRESHOLD))
    waivers = best.get("waivers", {}) or {}
    lines: dict = {}
    ok = True
    regressed = []
    for name, best_v in (best.get("metrics") or {}).items():
        cur = current.get(name)
        if cur is None:
            lines[name] = "missing (not measured this run)"
            continue
        # latency-flavored metrics (…_ms/_seconds) are lower-is-better:
        # rel is the signed improvement fraction either way, so the
        # threshold/waiver/line machinery below is direction-blind.
        # Sub-floor latencies are timer noise — the swap pause is one
        # attribute rebind, sub-µs, where scheduler jitter alone is a
        # multi-x relative swing — so both sides clamp to the floor:
        # noise never trips the gate, real-scale regressions still do
        if name.endswith(("_ms", "_seconds")) or \
                (name.endswith("_s") and not name.endswith("_per_s")):
            floor = 1.0 if name.endswith("_ms") else 0.05
            rel = max(best_v, floor) / max(cur, floor) - 1.0
        else:
            rel = cur / best_v - 1.0
        if rel < -thresh:
            if name in waivers:
                lines[name] = (f"REGRESS({rel:+.0%}) waived: "
                               f"{waivers[name]}")
            else:
                lines[name] = f"REGRESS({rel:+.0%})"
                regressed.append(name)
                ok = False
        else:
            lines[name] = f"ok({rel:+.0%})"
    for name in current:
        if name not in lines:
            lines[name] = "new (no recorded best)"
    return {"ok": ok, "threshold": thresh, "lines": lines,
            "regressed": regressed,
            "note": "values compared against the best RECORDED value per "
                    "metric (BENCH_BEST.json); an unwaived regression "
                    "past the threshold fails audit_ok and the exit code"}


def _mark(msg, t0=[None]):
    if t0[0] is None:
        t0[0] = time.time()
    print(f"# bench [{time.time()-t0[0]:6.1f}s] {msg}", file=sys.stderr,
          flush=True)


def _sync_scalar(x) -> float:
    """Force materialization with a real 4-byte D2H (see module docstring)."""
    return float(np.asarray(x))


def _analytic_cost(batch, num_slots, emb_dim, dense_dim, hidden, emb_cfg,
                   n_pad_rows, max_len=1):
    """Matmul-dominant FLOPs and HBM traffic of one train step."""
    dims = [num_slots * emb_dim + dense_dim, *hidden, 1]
    fwd = 2.0 * batch * sum(a * b for a, b in zip(dims[:-1], dims[1:]))
    fwd += 2.0 * batch * num_slots * emb_dim * 4  # FM sum-square term
    flops = 3.0 * fwd                              # fwd + ~2x bwd
    toks = batch * num_slots * max_len
    w, pw, gw = emb_cfg.row_width, emb_cfg.pull_width, emb_cfg.grad_width
    hbm = 4.0 * (
        toks * w + toks * pw            # gather read rows, write pulled
        + toks * (gw + 3) * 2           # scatter payload write + add
        + n_pad_rows * (gw + 3) * 2     # accumulator init + read
        + n_pad_rows * w * 2            # merge-update table read+write
        + batch * 2 * sum(dims))        # activations fwd+bwd (rough)
    return flops, hbm


def device_step_bench(small: bool, mode: str = "allreduce",
                      storage: str | None = None,
                      n_steps: int | None = None, n_windows: int = 3,
                      batch_per_dev: int | None = None,
                      n_split: int | None = None,
                      emb_dim: int = 8, max_len: int = 1,
                      return_ctx: bool = False, tiny: bool = False,
                      table_layout: str | None = None,
                      exchange_wire: str | None = None):
    import jax
    from paddlebox_tpu.config import flags as config_flags
    from paddlebox_tpu.data import DataFeedSchema
    from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                         PassWorkingSet)
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.parallel import make_mesh, mesh as mesh_lib
    from paddlebox_tpu.train import Trainer, TrainerConfig

    # n_split=None keeps the STARTUP value (framework default or the
    # operator's PBTPU_BINNED_PUSH_SPLITS env override) — matrix points
    # that override it must not leak into later configs; same rule for
    # the sharded-exchange engine knobs
    config_flags.binned_push_splits = (_startup_splits() if n_split is None
                                       else n_split)
    config_flags.table_layout = (_startup_flag("table_layout")
                                 if table_layout is None else table_layout)
    config_flags.exchange_wire = (_startup_flag("exchange_wire")
                                  if exchange_wire is None
                                  else exchange_wire)
    devices = jax.devices()
    n_dev = len(devices)
    # tiny = --dryrun geometry: small enough that the full bench pipeline
    # (trainer, attribution, floor, gate) runs in seconds on one CPU —
    # the code paths are the product, the numbers are not
    num_slots, dense_dim, hidden = ((4, 3, (32,)) if tiny
                                    else (26, 13, (400, 400, 400)))
    if batch_per_dev is None:
        batch_per_dev = 64 if tiny else (256 if small else 8192)
    batch = batch_per_dev * n_dev
    schema = DataFeedSchema.ctr(num_sparse=num_slots, num_float=dense_dim,
                                batch_size=batch, max_len=max_len)
    # PBTPU_BENCH_STORAGE=int8|int16 overrides the headline storage mode
    if storage is None:
        storage = os.environ.get("PBTPU_BENCH_STORAGE", "f32")
    emb_cfg = EmbeddingConfig(dim=emb_dim, optimizer="adagrad",
                              learning_rate=0.05, storage=storage)
    store = HostEmbeddingStore(emb_cfg)
    mesh = make_mesh(n_dev)
    model = DeepFMModel(num_slots=num_slots, emb_dim=emb_dim,
                        dense_dim=dense_dim, hidden=hidden)
    tr = Trainer(model, store, schema, mesh,
                 TrainerConfig(global_batch_size=batch, auc_buckets=1 << 16,
                               dense_sync_mode=mode))
    rng = np.random.default_rng(0)
    n_keys = 1 << (9 if tiny else (14 if small else 19))
    keys = rng.choice(1 << 50, n_keys, replace=False).astype(np.uint64)
    _mark("keys ready")
    ws = PassWorkingSet.begin_pass(store, keys, mesh)
    _mark("begin_pass done")
    T = tr.layout.total_len
    sh = mesh_lib.batch_sharding(mesh)

    n_staged = 4
    host_batches = []
    # measured dedup: the pack-side plan emits the per-batch unique-lane
    # counters (trainer.plan_unique_tokens single-shard, exchange.
    # unique_lanes sharded) while these batches stage — their mean feeds
    # the push floor the measured lanes instead of the tokens upper
    # bound (ROADMAP PR-12 follow-up #3)
    from paddlebox_tpu import monitor as _mon
    _plan0 = _mon.STATS.snapshot()
    for _ in range(n_staged):
        raw = rng.choice(keys, size=(batch, T))
        if max_len > 1 and T == num_slots * max_len:
            # multi-hot: variable slot lengths with real pad masking
            # (the DLRM/DCN-v2 geometry — BASELINE.md)
            lens = rng.integers(1, max_len + 1, size=(batch, num_slots))
            mask = (np.arange(max_len)[None, None, :]
                    < lens[:, :, None]).reshape(batch, T)
        else:
            mask = np.ones((batch, T), dtype=bool)
        idx = ws.translate(raw, mask)
        dense = rng.normal(size=(batch, dense_dim)).astype(np.float32)
        labels = (rng.random(batch) < 0.25).astype(np.float32)
        # the host binned-push plan is part of the pack pipeline (overlaps
        # device compute in train_pass); staged here like the batch itself
        plan = tr._host_plan(ws, idx)
        host_batches.append((idx, mask, dense, labels, *plan))
    _plan1 = _mon.STATS.snapshot()
    _udelta = (_plan1.get("exchange.unique_lanes", 0.0)
               - _plan0.get("exchange.unique_lanes", 0.0)) \
        or (_plan1.get("trainer.plan_unique_tokens", 0.0)
            - _plan0.get("trainer.plan_unique_tokens", 0.0))
    # per-shard per-step mean (the floor models ONE chip's pass; the
    # counters sum the whole world's lanes per batch)
    measured_lanes = (int(round(_udelta / n_staged / n_dev))
                      if _udelta > 0 else None)
    staged = [tuple(jax.device_put(a, sh) for a in hb)
              for hb in host_batches]
    # superstep operands: the same batches stacked for k-per-dispatch
    # groups (what train_pass stages by default — steps_per_dispatch)
    ksd = tr.cfg.steps_per_dispatch if tr._superstep_fn is not None else 1
    staged_stacked = None
    if ksd > 1:
        assert n_staged % ksd == 0 or ksd % n_staged == 0
        reps = max(1, ksd // n_staged)
        seq = (host_batches * reps)[:ksd]
        staged_stacked = jax.device_put(
            tuple(np.stack(cols) for cols in zip(*seq)),
            mesh_lib.stacked_batch_sharding(mesh))
    _mark("staged batches on device")

    repl = mesh_lib.replicated_sharding(mesh)

    def run_steps(table, k):
        """k steps in the selected dense-sync mode, returning the final
        loss array (mode-faithful: kstep syncs every param_sync_step,
        async pulls/pushes the host dense table each step — the real
        cost profile of trainer_desc.proto:100-108's modes). Allreduce
        runs the trainer's default k-microbatch superstep (one dispatch
        per steps_per_dispatch batches, like train_pass)."""
        nonlocal params, opt, dstate
        from paddlebox_tpu import monitor
        monitor.counter_add("bench.device_steps", k)
        if mode == "allreduce" and staged_stacked is not None:
            assert k % ksd == 0, (k, ksd)
            for _ in range(k // ksd):
                out = tr._superstep_fn(table, *dstate, *staged_stacked)
                table, dstate, loss, _, _ = tr.split_step_out(out)
            return table, loss[-1:]
        for i in range(k):
            b = staged[i % n_staged]
            if mode == "async":
                p = jax.device_put(tr._unravel(tr.dense_table.pull()),
                                   repl)
                table, gp_flat, loss, preds, drop = tr._step_fn(
                    table, p, *b)
                tr.dense_table.push(np.asarray(gp_flat))
            elif mode == "kstep":
                table, params, opt, loss, preds, drop = tr._step_fn(
                    table, params, opt, *b)
                params, opt = tr._sync_fn(params, opt)
            elif tr.push_overlap:
                # deferred push pipeline (flags.push_overlap): loss-path
                # program + apply program back to back, train_pass's
                # dataflow — the headline measures the mode training runs
                out = tr._defer_step_fn(table, *dstate, *b)
                dstate, ops, loss, _, _ = tr.split_defer_out(out)
                table = tr._apply_fn(table, b[0], b[1], b[3],
                                     *b[4:9], *ops)
            else:
                out = tr._step_fn(table, *dstate, *b)
                table, dstate, loss, _, _ = tr.split_step_out(out)
        return table, loss

    params, opt = tr.params, tr.opt_state
    dstate = tr.pack_dense() if mode == "allreduce" else None
    if mode == "async":
        tr.dense_table.start()
    # compile + settle layouts (one superstep group when that's the path)
    table, loss = run_steps(ws.table, ksd if staged_stacked is not None
                            else 2)
    _sync_scalar(loss)
    _mark(f"warmup/compile done ({mode}/{storage})")

    if n_steps is None:
        n_steps = 5 if small else 200
    if staged_stacked is not None:
        n_steps = -(-n_steps // ksd) * ksd     # whole superstep groups
    windows = []
    for _ in range(1 if small else n_windows):
        t0 = time.perf_counter()
        table, loss = run_steps(table, n_steps)
        loss_v = _sync_scalar(loss)  # real D2H terminates the window
        windows.append(time.perf_counter() - t0)
    dt = min(windows)
    if mode == "async":
        tr.dense_table.flush()
    _mark(f"device-step windows done ({mode}/{storage})")

    eps_chip = n_steps * batch / dt / n_dev
    ws.table = table                       # post-donation rebind
    if mode == "allreduce":
        tr.params, tr.opt_state = tr.unpack_dense(dstate)
    elif mode == "kstep":
        tr.params, tr.opt_state = params, opt
    # stage attribution is NOT run here: _enrich is its single entry
    # point (under main's print-always guard, after this frame's staged
    # batches would otherwise be redundantly resident)
    flops, hbm = _analytic_cost(batch, num_slots, emb_dim, dense_dim,
                                hidden, emb_cfg, ws.padded_rows,
                                max_len=max_len)
    kind = devices[0].device_kind
    peaks = _peaks(kind)
    audit = {
        "flops_per_step": flops,
        "hbm_bytes_per_step": hbm,
        "step_seconds": dt / n_steps,
        "sync": "device_get(loss); block_until_ready returns early over "
                "the tunnel and is not trusted",
    }
    if peaks is not None:
        peak_f, peak_b = peaks
        audit["peak_flops"] = peak_f
        audit["peak_hbm_bytes"] = peak_b
        audit["implied_mfu"] = flops / (dt / n_steps) / peak_f
        audit["implied_hbm_frac"] = hbm / (dt / n_steps) / peak_b
        audit["ok"] = (audit["implied_mfu"] <= 0.6
                       and audit["implied_hbm_frac"] <= 1.0)
    else:
        audit["ok"] = True  # unknown hardware (CPU smoke): no peak table
    from paddlebox_tpu.ops import pallas_kernels as _pk
    from paddlebox_tpu.utils.step_probe import push_floor_analysis
    # sparse-push floor: analytic per-stage bounds for THIS point's
    # geometry; the closure statement is finalized once the attribution
    # measures the real push stage (_enrich) — regressions then alarm
    # against the push's own physics, not just the chip peaks. PER-SHARD
    # geometry: the kernel/engine dispatch keys on rows_per_shard and
    # each shard pushes its local tokens, so the floor must model the
    # pass one chip actually performs (global rows would overstate the
    # update bytes n_shards-fold and could even flip the engine)
    premerged = tr.push_premerged(ws)
    push_floor = push_floor_analysis(
        emb_cfg, ws.rows_per_shard, batch * T // n_dev,
        n_split=config_flags.binned_push_splits, peaks=peaks,
        premerged=premerged,
        # the RECORDED per-batch dedup counters, not the tokens upper
        # bound: on premerged engines the fused floor scales with the
        # rows the lanes actually touch (capped at tokens — a foreign
        # counter bump can only tighten toward truth, never past it)
        unique_lanes=(min(measured_lanes, batch * T // n_dev)
                      if premerged and measured_lanes else None),
        table_width=(int(ws.table.shape[1]) if storage == "f32"
                     else None))
    detail = {
        "device_kind": kind,
        "storage": storage,
        "dense_sync_mode": mode,
        # which merge engine the step compiled with — THE resolver's
        # verdict (resolve_push_engine), the same call the compiled
        # dispatch makes, so the record can never name an engine the
        # program does not contain. The engine dispatches per SHARD, so
        # the per-shard row count decides.
        "push_engine": tr.resolved_push_engine(ws),
        # measured per-batch unique lanes (per shard) from the recorded
        # dedup counters — what the floor above consumed (None = no
        # plan ran, floors fall back to the tokens bound)
        "unique_lanes_measured": measured_lanes,
        # which pull engine the step compiled with (trainer heuristic:
        # fused gather-pool for multi-hot/wide layouts — the mh4d32 and
        # d128 envelope points — unfused lookup+seqpool elsewhere)
        "pull_engine": tr.pull_engine,
        # which _bp_pack width-class path the push compiled with (None =
        # scatter engine, no pack; premerged points compile no reorder
        # at all) — the per-point record whose absence let the round-5
        # pack rewrite regress the headline unnoticed
        "pack_engine": _pk.pack_engine(
            emb_cfg, ws.rows_per_shard,
            premerged=tr._use_plan and tr._dedup_premerge(ws)),
        # deferred-push pipeline state (flags.push_overlap)
        "push_overlap": "on" if tr.push_overlap else "off",
        "steps_per_dispatch": ksd,
        # sharded-exchange identity: which table engine the point
        # compiled with, the push wire format its a2a rode, and the mesh
        # partition — recorded per point like pull/push/pack_engine
        "table_layout": tr.table_layout,
        "exchange_wire": tr.exchange_wire or "-",
        "table_shards": tr.n_shards,
        "devices": n_dev,
        "global_batch": batch,
        "steps": n_steps,
        "seconds": round(dt, 3),
        "window_seconds": [round(w, 3) for w in windows],
        "working_set_keys": n_keys,
        "loss_final": loss_v,
        "audit": audit,
        "push_floor": push_floor,
    }
    if return_ctx:
        # live handles for a later attribution pass (main runs it under
        # the print-always guard); the caller MUST drop these before the
        # matrix runs or the headline buffers stay resident
        return eps_chip, detail, {
            "tr": tr, "ws": ws, "staged0": staged[0],
            "step_seconds": dt / n_steps, "mode": mode, "n_dev": n_dev}
    return eps_chip, detail


def _attribute_with_retry(tr, ws, staged0, step_seconds, small,
                          tiny=False):
    """Stage attribution (log_for_profile's cal-split analogue,
    boxps_worker.cc:746-759) with ONE retry — BENCH_r03 was killed by a
    transient tunnel error here (VERDICT r3 missing #2). Transient and
    deterministic failures are indistinguishable up front, so the retry
    fires on any Exception; one wasted re-attempt on a deterministic bug
    is the accepted cost. The retry runs on the next loop iteration,
    OUTSIDE the except block, so the failed attempt's exception state
    (whose traceback pins the dead run's device buffers) is fully
    released before the second attempt."""
    from paddlebox_tpu.utils.step_probe import attribute_step
    errors = []
    for attempt in (0, 1):
        try:
            res = attribute_step(tr, ws, staged0, step_seconds,
                                 k=2 if tiny else (4 if small else 24),
                                 n_loop=3 if tiny else
                                 (10 if small else 100))
            _mark(f"stage attribution done (coverage "
                  f"{res['coverage']:.0%})")
            return res
        except Exception as e:
            errors.append(repr(e))
            del e
        if not attempt:
            _mark(f"stage attribution failed ({errors[0]}); retrying "
                  f"once")
    # the FIRST error is the root cause (a retry after a mid-execution
    # donation loss fails fast with a derivative 'Array deleted' error)
    return {"error": errors[0], "retry_error": errors[1]}


def _synth_pass(schema, n_ex, num_slots, dense_slots, slot_space, seed,
                prev=None, overlap=0.9):
    """Vectorized synthetic SlotRecordBatch (pre-parsed pass data).

    With `prev`, ~`overlap` of tokens resample prev's keys (consecutive
    CTR passes share most of their working set) and the rest draw from a
    disjoint key window — the day-over-day churn."""
    from paddlebox_tpu.data.slot_record import SlotRecordBatch
    rng = np.random.default_rng(seed)
    sparse_values, sparse_offsets = [], []
    offs = np.arange(n_ex + 1, dtype=np.int64)  # one token per slot
    for s in range(num_slots):
        if prev is None:
            ids = rng.integers(0, slot_space, size=n_ex).astype(np.int64)
            ids |= np.int64(s + 1) << np.int64(40)  # slot-salted sign space
        else:
            pool = np.unique(prev.sparse_values[s])
            old = pool[rng.integers(0, len(pool), size=n_ex)]
            fresh = rng.integers(slot_space, 2 * slot_space,
                                 size=n_ex).astype(np.int64)
            fresh |= np.int64(s + 1) << np.int64(40)
            ids = np.where(rng.random(n_ex) < overlap, old, fresh)
        sparse_values.append(ids)
        sparse_offsets.append(offs.copy())
    float_values = [(rng.random(n_ex) < 0.25).astype(np.float32)]  # label
    float_values += [rng.normal(size=n_ex).astype(np.float32)
                     for _ in range(len(dense_slots))]
    return SlotRecordBatch(
        schema=schema, num=n_ex,
        sparse_values=sparse_values, sparse_offsets=sparse_offsets,
        float_values=float_values,
        ins_id=np.zeros(n_ex, dtype=np.uint64),
        search_id=np.zeros(n_ex, dtype=np.uint64),
        rank=np.zeros(n_ex, dtype=np.int32),
        cmatch=np.zeros(n_ex, dtype=np.int32))


def e2e_bench(small: bool):
    """Two full train_pass calls from pre-built archives (parse excluded;
    translate + H2D + step + metrics + pass boundaries included)."""
    import tempfile

    from paddlebox_tpu.config import flags as config_flags
    # device_step_bench's matrix points mutate this trace-time flag (the
    # bf16-push point leaves it at 1); the e2e semantics must stay the
    # startup config regardless of run order
    config_flags.binned_push_splits = _startup_splits()

    import jax
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.data.archive import read_archive, write_archive
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig

    n_dev = len(jax.devices())
    num_slots, emb_dim, dense_dim = 26, 16, 13
    batch = (256 if small else 8192) * n_dev
    steps_per_pass = 4 if small else 56
    n_ex = steps_per_pass * batch
    slot_space = 4096 if small else 650_000     # → ~8.4M unique keys big
    schema = DataFeedSchema.ctr(num_sparse=num_slots, num_float=dense_dim,
                                batch_size=batch, max_len=1)
    dense_slots = [s for s in schema.float_slots if s.name != "label"]

    with tempfile.TemporaryDirectory(prefix="pbtpu_bench_") as tmp:
        paths = []
        rec = None
        for p in range(2):
            rec = _synth_pass(schema, n_ex, num_slots, dense_slots,
                              slot_space, seed=p, prev=rec)
            path = os.path.join(tmp, f"pass{p}.pbar")
            write_archive(path, rec)
            paths.append(path)
        _mark("e2e archives written")
        passes = [read_archive(p, schema) for p in paths]
    _mark("e2e archives loaded (pre-parsed, excluded from timing)")

    store = HostEmbeddingStore(EmbeddingConfig(dim=emb_dim,
                                               optimizer="adagrad",
                                               learning_rate=0.05))
    mesh = make_mesh(n_dev)
    tr = Trainer(DeepFMModel(num_slots=num_slots, emb_dim=emb_dim,
                             dense_dim=dense_dim, hidden=(400, 400, 400)),
                 store, schema, mesh,
                 TrainerConfig(global_batch_size=batch,
                               auc_buckets=1 << 16))
    pass_secs, stats = [], []
    all_ds = []
    for rec in passes:
        ds = SlotDataset(schema)
        ds.records = rec
        all_ds.append(ds)
    for p, ds in enumerate(all_ds):
        tr.timers.reset()
        t0 = time.perf_counter()
        # NOTE: train_pass(preload_keys=...) would overlap pass p+1's
        # working-set build with pass p's training (PreLoadIntoMemory +
        # BeginFeedPass) — measured COUNTERPRODUCTIVE here because the
        # tunnel serializes all host<->device traffic (~10MB/s), so the
        # preload H2D steals bandwidth from the training batches
        # (A/B: pass walls 191+179s with preload vs 158+111s without).
        # On a real PCIe/DMA host the overlap is the win it is designed
        # to be; the bench reports the un-overlapped, honest number.
        out = tr.train_pass(ds)
        wall = time.perf_counter() - t0
        pass_secs.append(wall)
        m = tr.feed_mgr
        # main-thread wall accounting: queue wait ("read", starvation =
        # host-bound), step dispatch ("train"), AUC, the post-loop drain
        # (where async-dispatched device time lands), and the boundary
        # (now terminated by a real D2H sync). "translate" runs on the
        # pack thread and OVERLAPS — reported but not in coverage.
        stage = {s: round(tr.timers.total[s], 3)
                 for s in ("read", "train", "auc", "drain", "translate")}
        from paddlebox_tpu.config import flags as _flags
        main_stages = ["read", "train", "auc", "drain"]
        if _flags.prefetch_batches <= 0:
            # synchronous pack: translate runs on the MAIN thread and is
            # part of the wall, not an overlapped background stage
            main_stages.append("translate")
        accounted = (sum(stage[s] for s in main_stages)
                     + m.last_boundary_seconds)
        bsec = m.last_boundary_seconds
        stats.append({
            "steps": out["steps"],
            "loss_mean": round(out["loss_mean"], 4),
            "working_set_keys": int(len(ds.unique_keys())),
            "boundary_h2d_bytes": m.last_h2d_bytes,
            "boundary_d2h_bytes": m.last_d2h_bytes,
            "fresh_rows": m.last_fresh_rows,
            "reused_rows": m.last_reused_rows,
            "boundary_seconds": round(bsec, 3),
            "boundary_split": {k: round(v, 3)
                               for k, v in m.last_boundary_split.items()},
            "boundary_h2d_mbps": round(
                m.last_h2d_bytes / bsec / 1e6, 1) if bsec > 0.01 else None,
            "stage_seconds": stage,
            "wall_coverage": round(accounted / wall, 3),
        })
        _mark(f"e2e pass {p} done in {pass_secs[-1]:.1f}s "
              f"({stats[-1]['working_set_keys']} keys, coverage "
              f"{stats[-1]['wall_coverage']:.0%})")
    # eval_pass rides the same background pack pipeline as train_pass
    # (VERDICT r3 weak #6); record its wall against the train pass so a
    # regression to a serialized host path is visible
    t0 = time.perf_counter()
    ev = tr.eval_pass(all_ds[-1])
    eval_wall = time.perf_counter() - t0
    _mark(f"e2e eval pass done in {eval_wall:.1f}s (auc {ev['auc']:.3f})")
    eps_chip = n_ex / min(pass_secs) / n_dev
    return eps_chip, {
        "eval_pass_seconds": round(eval_wall, 2),
        "eval_vs_train_wall": round(eval_wall / min(pass_secs), 3),
        "examples_per_pass": n_ex,
        "emb_dim": emb_dim,
        "pass_seconds": [round(s, 2) for s in pass_secs],
        "passes": stats,
        "note": "translate+H2D+step+metrics+boundaries; parse excluded "
                "(pre-built archive); translate+pack+plan+H2D overlap "
                "on a background thread (flags.prefetch_batches); "
                "host<->device rides the tunnel (~30MB/s H2D), not a "
                "local PCIe/DMA path — 'read' wait + 'drain' are where "
                "tunnel stalls surface",
    }


def host_bench(small: bool) -> dict:
    """Tunnel-immune host-path timings — no tunnel traffic in any timed
    window (run in a JAX_PLATFORMS=cpu subprocess; see _enrich).

    The reference treats parse as the pass bottleneck (dozens of parser
    threads, flags.cc:480-484) and times download/parse/shuffle per pass
    (box_wrapper.h:896-899). The recorded e2e here measures the axon
    tunnel, not the framework (VERDICT r4 weak #2) — so these are the
    environment-independent numbers: what each host stage costs on THIS
    host, and the feed ceiling they impose on a chip at the headline
    geometry."""
    import time as _t

    from paddlebox_tpu.data import DataFeedSchema
    from paddlebox_tpu.data.archive import read_archive, write_archive
    from paddlebox_tpu.data.parser import _parse_python
    from paddlebox_tpu.embedding import (EmbeddingConfig,
                                         HostEmbeddingStore,
                                         PassWorkingSet)
    from paddlebox_tpu.native import key_index
    from paddlebox_tpu.native import slot_parser_binding as native_parser
    from paddlebox_tpu.parallel import make_mesh

    rng = np.random.default_rng(0)
    num_slots, dense_dim = 26, 13
    batch = 256 if small else 8192
    n_keys = 1 << (14 if small else 19)
    schema = DataFeedSchema.ctr(num_sparse=num_slots, num_float=dense_dim,
                                batch_size=batch, max_len=1)
    out: dict = {
        "host_cores": os.cpu_count(),
        "note": "pure host timings; this machine has "
                f"{os.cpu_count()} core(s), so thread counts >1 "
                "measure oversubscription here — per-thread numbers "
                "extrapolate to the reference's many-core ingest hosts",
    }

    def best_of(fn, reps=3):
        w = []
        for _ in range(reps):
            t0 = _t.perf_counter()
            fn()
            w.append(_t.perf_counter() - t0)
        return min(w)

    # --- parse: MultiSlot text -> SlotRecordBatch (native vs python) ---
    n_lines = 200 if small else 20_000
    ids = rng.integers(1, 1 << 50, size=(n_lines, num_slots))
    dn = rng.random((n_lines, dense_dim))
    lab = (rng.random(n_lines) < 0.25).astype(int)
    lines = []
    for i in range(n_lines):
        parts = [f"1 {lab[i]}"]
        parts += [f"1 {v:.6f}" for v in dn[i]]
        parts += [f"1 {k}" for k in ids[i]]
        lines.append(" ".join(parts))
    buf = ("\n".join(lines) + "\n").encode()
    mb = len(buf) / 1e6
    parse = {"input_mb": round(mb, 2), "lines": n_lines}
    if native_parser.available():
        for nt in (1, 2):
            dt = best_of(lambda: native_parser.parse_buffer(
                buf, schema, n_threads=nt))
            parse[f"native_t{nt}_mb_per_s"] = round(mb / dt, 1)
            parse[f"native_t{nt}_ex_per_s"] = round(n_lines / dt)
    py_lines = lines[:max(1, n_lines // 10)]
    dt = best_of(lambda: _parse_python(py_lines, schema,
                                       with_ins_id=False), reps=2)
    parse["python_ex_per_s"] = round(len(py_lines) / dt)
    parse["python_mb_per_s"] = round(
        mb * len(py_lines) / n_lines / dt, 2)
    out["parse"] = parse

    # --- archive read (the pre-parsed fast path the e2e bench feeds on)
    import tempfile
    rec = _synth_pass(schema, n_lines, num_slots,
                      [s for s in schema.float_slots
                       if s.name != "label"],
                      n_keys, seed=0)
    with tempfile.TemporaryDirectory(prefix="pbtpu_host_") as tmp:
        pth = os.path.join(tmp, "p.pbar")
        write_archive(pth, rec)
        amb = os.path.getsize(pth) / 1e6
        dt = best_of(lambda: read_archive(pth, schema))
        out["archive_read"] = {"mb": round(amb, 2),
                               "mb_per_s": round(amb / dt, 1),
                               "ex_per_s": round(n_lines / dt)}

    # --- working-set build + translate + binned-push plan ---
    keys = rng.choice(1 << 50, n_keys, replace=False).astype(np.uint64)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, optimizer="adagrad",
                                               learning_rate=0.05))
    mesh = make_mesh(1)
    t0 = _t.perf_counter()
    ws = PassWorkingSet.begin_pass(store, keys, mesh)
    dt = _t.perf_counter() - t0
    out["ws_build"] = {
        "keys": n_keys, "keys_per_s": round(n_keys / dt),
        "note": "store fetch/init + sort + pad + CPU staging "
                "(device_put on the cpu backend = memcpy)"}

    T = num_slots
    raw = rng.choice(keys, size=(batch, T))
    mask = np.ones((batch, T), dtype=bool)
    dt = best_of(lambda: ws.translate(raw, mask), reps=5)
    tokens = batch * T
    out["translate"] = {
        "tokens": tokens, "seconds": round(dt, 5),
        "tokens_per_s": round(tokens / dt),
        "backend": "native" if ws._tindex.is_native else "searchsorted"}
    t_translate = dt

    idx = ws.translate(raw, mask)
    from paddlebox_tpu.ops import pallas_kernels
    geom = pallas_kernels.binned_push_geometry(store.cfg, ws.padded_rows)
    t_plan = 0.0
    if geom is not None:
        dt = best_of(lambda: key_index.block_plan(
            idx.reshape(-1), geom[0], geom[1]), reps=5)
        t_plan = dt
        out["block_plan"] = {
            "tokens": tokens, "seconds": round(dt, 5),
            "tokens_per_s": round(tokens / dt),
            "native": key_index.native_available()}

    # --- the derived line: what this host could FEED a chip at the
    # headline geometry (translate + plan per batch on one pack thread;
    # parse/archive are per-pass upstream stages with their own ceilings
    # above). flags.prefetch_batches pipelines pack against device
    # compute, so the ceiling scales ~linearly with pack threads on a
    # multicore host.
    per_batch = t_translate + t_plan
    out["derived_max_feed_eps_per_chip"] = round(batch / per_batch)
    out["derived_note"] = (
        f"one pack thread on this host sustains batch={batch} every "
        f"{per_batch*1e3:.1f}ms = {batch/per_batch:,.0f} ex/s of "
        "translate+plan; compare against THIS artifact's recorded "
        "headline eps (feed_margin_vs_headline) — no hardcoded "
        "device-step constants here")

    # --- superstep A/B (VERDICT r4 weak #4): steps_per_dispatch exists
    # for DISPATCH-BOUND hosts; the tunneled TPU measured it neutral
    # (async dispatch hides the launch floor). The CPU backend IS a
    # dispatch-bound host — record the win (or its absence) here, in
    # the regime the knob targets.
    try:
        from paddlebox_tpu.data import SlotDataset
        from paddlebox_tpu.models import DeepFMModel
        from paddlebox_tpu.train import Trainer, TrainerConfig
        ss_schema = DataFeedSchema.ctr(num_sparse=4, num_float=1,
                                       batch_size=64, max_len=1)
        n_ex = 64 * (8 if small else 64)
        rec = _synth_pass(ss_schema, n_ex, 4,
                          [s for s in ss_schema.float_slots
                           if s.name != "label"], 2000, seed=1)
        ab = {}
        for k in (1, 4):
            st = HostEmbeddingStore(EmbeddingConfig(
                dim=4, optimizer="adagrad", learning_rate=0.05))
            trk = Trainer(DeepFMModel(num_slots=4, emb_dim=4,
                                      dense_dim=1, hidden=(16,)),
                          st, ss_schema, make_mesh(1),
                          TrainerConfig(global_batch_size=64,
                                        steps_per_dispatch=k))
            ds = SlotDataset(ss_schema)
            ds.records = rec
            trk.train_pass(ds)             # warmup pass (compiles)
            t0 = _t.perf_counter()
            trk.train_pass(ds)
            ab[f"k{k}_pass_seconds"] = round(_t.perf_counter() - t0, 3)
        ab["speedup_k4"] = round(ab["k1_pass_seconds"]
                                 / ab["k4_pass_seconds"], 3)
        out["superstep_ab"] = ab
    except Exception as e:
        out["superstep_ab"] = {"error": repr(e)}
    return out


def elastic_drill(small: bool, tiny: bool = False) -> dict:
    """Elastic rank-loss recovery drill (ISSUE 6): measure what a world
    shrink actually costs. A 2-member elastic world trains one pass on
    its shard, "loses" rank 1, and runs the REAL recovery path — world
    re-formation (generation seal over a FileStore), coordinated resume
    election, restore, and the cursor-preserving re-route of the departed
    rank's records — timed as ``world_resize_seconds``; the continued
    pass then trains the whole working set at N−1 and its throughput is
    recorded as the ``elastic_degraded`` matrix point (gated by
    BENCH_BEST.json like every other point). The numbers answer the two
    operator questions: how long is the pass stalled by a rank loss, and
    how fast does the shrunk world train."""
    import tempfile as _tempfile
    import time as _t
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.distributed.resilience import (ElasticWorld,
                                                      coordinated_resume)
    from paddlebox_tpu.distributed.store import FileStore
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig

    bs = 64
    n_ex = bs * (4 if tiny else (16 if small else 128))
    schema = DataFeedSchema.ctr(num_sparse=4, num_float=1, batch_size=bs,
                                max_len=1)
    rec = _synth_pass(schema, n_ex, 4,
                      [s for s in schema.float_slots if s.name != "label"],
                      2000, seed=3)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, optimizer="adagrad",
                                               learning_rate=0.05))
    tr = Trainer(DeepFMModel(num_slots=4, emb_dim=8, dense_dim=1,
                             hidden=(16,)),
                 store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=bs))
    box = BoxPS(store)
    with _tempfile.TemporaryDirectory() as td:
        from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
        ckpt = PassCheckpointer(os.path.join(td, "snaps"), keep_last_n=2)
        world = ElasticWorld(
            FileStore(os.path.join(td, "store"), namespace="bench",
                      poll_s=0.005),
            0, [0, 1], heartbeat_interval_s=0.2, lost_after_s=600,
            stall_after_s=600, reform_timeout_s=0.25)
        ds = SlotDataset(schema)
        ds.records = rec
        shards = ds.member_shards(2)
        ds_mine = SlotDataset(schema)
        ds_mine.records = shards[0]
        box.begin_pass()
        tr.train_pass(ds_mine)
        box.end_pass(checkpointer=ckpt, trainer=tr, dataset=ds)
        # rank 1 "dies" at the pass boundary: re-form, re-elect, re-route
        t0 = _t.perf_counter()
        world2 = world.reform([1])
        cursor = coordinated_resume(ckpt, tr, world2.collectives, box=box)
        routed = ds.reroute_records(shards[1], world2.world)
        resize_s = _t.perf_counter() - t0
        # degraded continuation: the shrunk world carries the whole
        # working set (warm, like steady state after a shrink)
        ds_all = SlotDataset(schema)
        ds_all.records = rec
        box.begin_pass()
        tr.train_pass(ds_all)          # warmup (compiles at new shapes)
        box.end_pass(trainer=tr)
        box.begin_pass()
        t1 = _t.perf_counter()
        out = tr.train_pass(ds_all)
        seconds = _t.perf_counter() - t1
        box.end_pass(trainer=tr)
        world2.close()
    eps = out["steps"] * bs / max(seconds, 1e-9)
    return {"examples_per_sec_per_chip": round(eps, 1),
            "world_resize_seconds": round(resize_s, 4),
            "resumed_pass": None if cursor is None else cursor["pass_id"],
            "rerouted_records": sum(int(r.num) for r in routed
                                    if r is not None),
            "world": 1}


def serving_drill(small: bool, tiny: bool = False) -> dict:
    """Train→publish→serve drill (ISSUE 7): the online loop's three
    operator numbers, measured on the REAL path. A one-pass job publishes
    a base artifact (timed as ``publish_seconds`` — plane snapshot, int8
    cold-row quantization, CRC-chained manifest, donefile announce), a
    ServingServer tails + loads it, and a BatchingFrontend drives the
    predictor at concurrency while pass 2's delta publish hot-swaps
    underneath the traffic — ``swap_pause_ms`` (the atomic handle rebind
    requests actually see) and the served ``p50_ms``/``p99_ms`` land as
    gate-held matrix points (latency metrics compare lower-is-better off
    the ``_ms``/``_seconds`` suffix). Zero request failures across the
    swap is asserted — the drill fails loudly rather than record a tail
    latency from a broken loop."""
    import tempfile as _tempfile
    import threading as _threading
    import time as _t
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.serving import (BatchingFrontend, ServingPublisher,
                                       ServingServer)
    from paddlebox_tpu.train import Trainer, TrainerConfig

    bs = 64
    n_ex = bs * (2 if tiny else (8 if small else 64))
    schema = DataFeedSchema.ctr(num_sparse=4, num_float=1, batch_size=bs,
                                max_len=1)
    rec = _synth_pass(schema, n_ex, 4,
                      [s for s in schema.float_slots if s.name != "label"],
                      2000, seed=11)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, optimizer="adagrad",
                                               learning_rate=0.05))
    model = DeepFMModel(num_slots=4, emb_dim=8, dense_dim=1, hidden=(16,))
    tr = Trainer(model, store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=bs))
    box = BoxPS(store)
    ds = SlotDataset(schema)
    ds.records = rec
    with _tempfile.TemporaryDirectory() as td:
        root = os.path.join(td, "serve")
        pub = ServingPublisher(root, model, schema, publish_base_every=8,
                               quant="int8", hot_top_k=64)
        box.begin_pass()
        tr.train_pass(ds)
        info = box.end_pass(trainer=tr, publisher=pub)["publish"]
        srv = ServingServer(root, poll_s=0.01)
        if srv.poll_once() != 1:
            raise RuntimeError("server failed to load the published base")
        pb = next(iter(ds.batches(batch_size=bs)))
        lc, lw, _ = schema.float_split_cols("label")
        floats = np.concatenate(
            [pb.floats[:, :lc], pb.floats[:, lc + lw:]], axis=1)
        ids64 = pb.ids.astype(np.uint64)
        fe = BatchingFrontend(srv, max_batch=32, max_wait_s=0.002).start()
        try:
            # warmup OUTSIDE the window: the first batch compiles the
            # frontend's one fixed shape
            for f in [fe.submit(ids64[i], pb.mask[i], floats[i])
                      for i in range(32)]:
                f.result(timeout=300)
            # pass 2 trains + publishes its delta while the frontend is
            # live; the swap itself lands mid-traffic below
            box.begin_pass()
            tr.train_pass(ds)
            d_info = box.end_pass(trainer=tr, publisher=pub)["publish"]
            n_req = bs * (4 if tiny else (16 if small else 64))
            futs: list = []

            def _load():
                r = np.random.default_rng(5)
                while len(futs) < n_req:
                    i = int(r.integers(0, bs))
                    futs.append(fe.submit(ids64[i], pb.mask[i],
                                          floats[i]))

            t_load = _threading.Thread(target=_load, daemon=True)
            t0 = _t.perf_counter()
            t_load.start()
            _t.sleep(0.01)                   # traffic in flight
            if srv.poll_once() != 1:         # THE hot-swap, under load
                raise RuntimeError("delta hot-swap did not apply")
            t_load.join(timeout=600)
            done = [f.result(timeout=300) for f in list(futs)]
            serve_s = _t.perf_counter() - t0
            st = fe.stats()
        finally:
            fe.stop()
            srv.stop()
    if srv.active is None or srv.active.version != 2:
        raise RuntimeError("drill ended off the delta version")
    if st.get("failures"):
        raise RuntimeError(f"{st['failures']} requests failed across the "
                           f"hot-swap — the latency numbers are not "
                           f"trustable")
    return {"publish_seconds": round(info["seconds"], 4),
            "delta_publish_seconds": round(d_info["seconds"], 4),
            "publish_bytes": int(info["bytes"]),
            "swap_pause_ms": round(max(srv._last_swap_pause_ms, 1e-6), 6),
            "p50_ms": st["p50_ms"], "p99_ms": st["p99_ms"],
            "serve_eps": round(len(done) / max(serve_s, 1e-9), 1),
            "requests": len(done), "failures": int(st["failures"]),
            "swapped_to_version": srv.active.version}


def serving_split_drill(small: bool, tiny: bool = False) -> dict:
    """Version-split serving drill (ISSUE 19): shadow-mode scoring on the
    REAL two-version path. Pass 1 publishes the stable version, pass 2's
    publish is HELD as the candidate (``flags.serving_shadow``) while
    every request scores on both — the drill records the served tail
    latency under the doubled predictor work (``shadow_p99_ms``,
    gate-held lower-is-better), joins the pass's labels back to both
    versions' scores for the per-version AUC + candidate-vs-stable
    score-KL, commits a serving window record, schema-checks it, and
    runs the doctor's three serving rules over it — the whole
    capture→record→diagnose loop the chip run will lean on."""
    import tempfile as _tempfile
    import time as _t
    from paddlebox_tpu.config import flags as _flags
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.monitor import doctor as doctor_lib
    from paddlebox_tpu.monitor import flight as flight_lib
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.serving import ServingPublisher, ServingServer
    from paddlebox_tpu.train import Trainer, TrainerConfig

    bs = 64
    n_ex = bs * (2 if tiny else (8 if small else 32))
    schema = DataFeedSchema.ctr(num_sparse=4, num_float=1, batch_size=bs,
                                max_len=1)
    rec = _synth_pass(schema, n_ex, 4,
                      [s for s in schema.float_slots if s.name != "label"],
                      2000, seed=13)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, optimizer="adagrad",
                                               learning_rate=0.05))
    model = DeepFMModel(num_slots=4, emb_dim=8, dense_dim=1, hidden=(16,))
    tr = Trainer(model, store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=bs))
    box = BoxPS(store)
    ds = SlotDataset(schema)
    ds.records = rec
    prev_shadow = _flags.serving_shadow
    try:
        _flags.serving_shadow = True
        with _tempfile.TemporaryDirectory() as td:
            root = os.path.join(td, "serve")
            pub = ServingPublisher(root, model, schema,
                                   publish_base_every=8, quant="f32",
                                   hot_top_k=64)
            box.begin_pass()
            tr.train_pass(ds)
            box.end_pass(trainer=tr, publisher=pub)
            srv = ServingServer(root, poll_s=0.01)
            if srv.poll_once() != 1:
                raise RuntimeError(
                    "server failed to load the published base")
            # pass 2's publish lands as the HELD candidate
            box.begin_pass()
            tr.train_pass(ds)
            box.end_pass(trainer=tr, publisher=pub)
            if srv.poll_once() != 1 or srv.candidate is None:
                raise RuntimeError("candidate did not load under shadow")
            pb = next(iter(ds.batches(batch_size=bs)))
            lc, lw, _ = schema.float_split_cols("label")
            floats = np.concatenate(
                [pb.floats[:, :lc], pb.floats[:, lc + lw:]], axis=1)
            ids64 = pb.ids.astype(np.uint64)
            labels = pb.floats[:, lc:lc + lw].reshape(-1)
            # warmup OUTSIDE the measured window: first batch compiles
            srv.predict(ids64, pb.mask, floats)
            srv.observe_labels(labels)
            srv.commit_window(force=True)
            n_batches = 2 if tiny else (8 if small else 32)
            t0 = _t.perf_counter()
            for _ in range(n_batches):
                srv.predict(ids64, pb.mask, floats)
                srv.observe_labels(labels)
            serve_s = _t.perf_counter() - t0
            fields = srv.commit_window(force=True)
            srv.stop()
    finally:
        _flags.serving_shadow = prev_shadow
    full_rec = {"ts": _t.time(), "type": "serving_record",
                "name": "serving_window", "pass_id": None, "step": None,
                "phase": -1, "thread": "bench", "fields": fields}
    schema_errors = flight_lib.validate_serving_record(full_rec)
    rep = doctor_lib.diagnose(servings=[full_rec])
    rules = {r["rule"]: r["status"] for r in rep["rules"]
             if r["rule"] in ("version-regression", "p99-burn",
                              "swap-regression")}
    by_role = {e.get("role"): (vid, e)
               for vid, e in (fields.get("versions") or {}).items()}
    stable = by_role.get("stable", (None, {}))
    cand = by_role.get("candidate", (None, {}))
    return {"shadow": True,
            "stable_version": stable[0], "candidate_version": cand[0],
            "requests": int(fields["requests"]),
            "shadow_p50_ms": float(fields["p50_ms"]),
            "shadow_p99_ms": float(fields["p99_ms"]),
            "serve_eps": round(n_batches * bs / max(serve_s, 1e-9), 1),
            "stable_auc": stable[1].get("auc"),
            "candidate_auc": cand[1].get("auc"),
            "score_kl": cand[1].get("score_kl"),
            "record_schema_errors": schema_errors,
            "doctor_rules": rules}


def serving_fleet_drill(small: bool, tiny: bool = False) -> dict:
    """Fleet resilience drill (ISSUE 20): two in-process replicas behind
    the health-aware router with ONE injected slow — the routed tail
    under hedging is the gate (``p99_ms``: the hedge must cut the slow
    replica's latency out of the fleet tail), a version publish is timed
    to EVERY replica serving it (``swap_convergence_s``,
    lower-is-better), the promotion governor is fed a regressing
    candidate window and must HOLD, and the composed fleet window record
    is schema-checked and run through the doctor's fleet-degraded rule
    (which must fire on the recorded hold)."""
    import random as _random
    import tempfile as _tempfile
    import threading as _threading
    import time as _t
    from concurrent.futures import Future as _Future
    from paddlebox_tpu.config import flags as _flags
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.monitor import doctor as doctor_lib
    from paddlebox_tpu.monitor import flight as flight_lib
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.serving import ServingPublisher
    from paddlebox_tpu.serving.fleet import (FleetReplicaServer,
                                             LocalReplica,
                                             PromotionGovernor)
    from paddlebox_tpu.serving.frontend import BatchingFrontend
    from paddlebox_tpu.serving.router import Router
    from paddlebox_tpu.train import Trainer, TrainerConfig

    class _SlowReplica:
        """LocalReplica wrapper with a mutable injected service delay —
        the drill's 'one replica went slow' fault. The delayed future is
        marked running so a hedge-loser cancel fails and the router's
        discard accounting is the path exercised."""

        def __init__(self, inner):
            self._inner = inner
            self.name = inner.name
            self.delay_s = 0.0

        @property
        def quarantined(self):
            return self._inner.quarantined

        @property
        def inflight(self):
            return self._inner.inflight

        def health(self):
            return self._inner.health()

        def promote(self):
            return self._inner.promote()

        def submit(self, ids, mask, dense=None):
            inner_fut = self._inner.submit(ids, mask, dense)
            delay = float(self.delay_s)
            if delay <= 0:
                return inner_fut
            out = _Future()
            out.set_running_or_notify_cancel()

            def _later(f):
                def _fire():
                    try:
                        out.set_result(f.result())
                    except Exception as e:  # noqa: BLE001 — relay, not
                        # swallow: the inner failure must surface on the
                        # delayed future exactly as it would undelayed
                        out.set_exception(e)
                _threading.Timer(delay, _fire).start()
            inner_fut.add_done_callback(_later)
            return out

    bs = 64
    n_ex = bs * (2 if tiny else (8 if small else 32))
    schema = DataFeedSchema.ctr(num_sparse=4, num_float=1, batch_size=bs,
                                max_len=1)
    rec = _synth_pass(schema, n_ex, 4,
                      [s for s in schema.float_slots if s.name != "label"],
                      2000, seed=17)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, optimizer="adagrad",
                                               learning_rate=0.05))
    model = DeepFMModel(num_slots=4, emb_dim=8, dense_dim=1, hidden=(16,))
    tr = Trainer(model, store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=bs))
    box = BoxPS(store)
    ds = SlotDataset(schema)
    ds.records = rec
    prev_promote = _flags.serving_auto_promote
    slow_ms = 150.0
    try:
        _flags.serving_auto_promote = True
        with _tempfile.TemporaryDirectory() as td:
            root = os.path.join(td, "serve")
            pub = ServingPublisher(root, model, schema,
                                   publish_base_every=8, quant="f32",
                                   hot_top_k=64)
            box.begin_pass()
            tr.train_pass(ds)
            box.end_pass(trainer=tr, publisher=pub)
            servers = [FleetReplicaServer(root, poll_s=0.01)
                       for _ in range(2)]
            for s in servers:
                if s.poll_once() != 1:
                    raise RuntimeError(
                        "replica failed to load the published base")
            fes = [BatchingFrontend(s, max_batch=32,
                                    max_wait_s=0.002).start()
                   for s in servers]
            fast = LocalReplica("replica-0", servers[0], fes[0])
            slow = _SlowReplica(
                LocalReplica("replica-1", servers[1], fes[1]))
            router = Router([fast, slow], timeout_s=10.0,
                            health_ttl_s=0.2, hedge_factor=1.5,
                            hedge_min_count=8, window_s=60.0,
                            rng=_random.Random(7))
            pb = next(iter(ds.batches(batch_size=bs)))
            lc, lw, _ = schema.float_split_cols("label")
            floats = np.concatenate(
                [pb.floats[:, :lc], pb.floats[:, lc + lw:]], axis=1)
            ids64 = pb.ids.astype(np.uint64)
            # compile OUTSIDE the router: the first request per replica
            # pays the predict compile (seconds) — routed through, it
            # would land in the hedge-threshold window and a threshold
            # derived off a compile-scale p99 never hedges anything
            for fe in fes:
                fe.submit(ids64[0], pb.mask[0], floats[0]).result(
                    timeout=300)
            # warmup through the router: fill its latency window so the
            # hedge threshold derives from the healthy-fleet p99
            n_warm = 12 if tiny else (16 if small else 32)
            for i in range(n_warm):
                router.score(ids64[i % bs], pb.mask[i % bs],
                             floats[i % bs])
            # inject the slow replica, then the measured phase: hedging
            # must keep the routed tail well under the injected delay
            slow.delay_s = slow_ms / 1e3
            n_req = 16 if tiny else (32 if small else 96)
            t0 = _t.perf_counter()
            for i in range(n_req):
                router.score(ids64[i % bs], pb.mask[i % bs],
                             floats[i % bs])
            serve_s = _t.perf_counter() - t0
            slow.delay_s = 0.0
            # publish the next version and time fleet-wide convergence:
            # the wall from donefile append to BOTH replicas serving it
            box.begin_pass()
            tr.train_pass(ds)
            box.end_pass(trainer=tr, publisher=pub)
            t0 = _t.perf_counter()
            deadline = t0 + 60.0
            while _t.perf_counter() < deadline:
                for s in servers:
                    if s.active is None or s.active.version != 2:
                        s.poll_once()
                if all(s.active is not None and s.active.version == 2
                       for s in servers):
                    break
            swap_convergence_s = _t.perf_counter() - t0
            if any(s.active is None or s.active.version != 2
                   for s in servers):
                raise RuntimeError("fleet never converged on version 2")
            # the governor leg: a window where the candidate regresses
            # hard on AUC must HOLD promotion fleet-wide
            gov = PromotionGovernor([fast, slow], windows=2)
            decision = gov.observe({
                "ts": _t.time(), "requests": 2 * bs,
                "candidate_version": 3,
                "versions": {
                    "2": {"role": "stable", "auc": 0.74, "requests": bs},
                    "3": {"role": "candidate", "auc": 0.52,
                          "requests": bs, "score_kl": 0.7}}})
            rs = router.stats()
            healthy = sum(
                1 for s in servers
                if str(s.health().get("status", "")).startswith("ok"))
            for fe in fes:
                fe.stop()
            for s in servers:
                s.stop()
    finally:
        _flags.serving_auto_promote = prev_promote
    fields = {"window_s": round(serve_s, 3), "replicas": 2,
              "healthy": healthy, "quarantined": 0,
              "requests": int(rs["requests"]), "sheds": int(rs["sheds"]),
              "retries": int(rs["retries"]), "hedges": int(rs["hedges"]),
              "hedges_won": int(rs["hedges_won"]), "restarts": 0,
              "promote_holds": int(gov.promote_holds),
              "p50_ms": float(rs.get("p50_ms", 0.0)),
              "p99_ms": float(rs.get("p99_ms", 0.0))}
    full_rec = {"ts": _t.time(), "type": "fleet_record",
                "name": "fleet_window", "pass_id": None, "step": None,
                "phase": -1, "thread": "bench", "fields": fields}
    schema_errors = flight_lib.validate_fleet_record(full_rec)
    rep = doctor_lib.diagnose(fleets=[full_rec])
    rules = {r["rule"]: r["status"] for r in rep["rules"]
             if r["rule"] == "fleet-degraded"}
    return {"replicas": 2, "healthy": healthy,
            "requests": int(rs["requests"]),
            "p50_ms": float(rs.get("p50_ms", 0.0)),
            "p99_ms": float(rs.get("p99_ms", 0.0)),
            "slow_replica_ms": slow_ms,
            "hedges": int(rs["hedges"]),
            "hedges_won": int(rs["hedges_won"]),
            "retries": int(rs["retries"]), "sheds": int(rs["sheds"]),
            "failures": int(rs["failures"]),
            "serve_eps": round(n_req / max(serve_s, 1e-9), 1),
            "swap_convergence_s": round(swap_convergence_s, 4),
            "swapped_to_version": 2,
            "promote_decision": decision,
            "promote_holds": int(gov.promote_holds),
            "record_schema_errors": schema_errors,
            "doctor_rules": rules}


def spill_drill(small: bool, tiny: bool = False) -> dict:
    """Tiered-table drill (ISSUE 11): a working set >= 10x the RAM
    row-cache budget through the sharded+spill path — 2 hash-partitioned
    shards, each a SpillEmbeddingStore (memmap row file + capped RAM
    cache), the configuration ``flags.table_tiering=spill`` selects.

    Four passes of skewed traffic (a hot set re-read every pass under a
    rotating cold scan that floods every direct-mapped slot — the
    Parallax skew argument) run TWICE on identical key sequences: once
    under the show-count-weighted admission policy (``freq``, the
    product) and once under the legacy direct-mapped last-wins install
    (``direct``, the baseline bench_spill.py records). The drill records
    both hot-tier hit rates side by side — the acceptance bar is the
    policy's rate beating the baseline's on the same traffic — plus the
    admission/eviction counters, the dedup ratio of the simulated token
    stream, and the cold-tier fetch throughput (gate-held)."""
    import tempfile as _tf
    import time as _t
    from paddlebox_tpu.embedding import (EmbeddingConfig,
                                         ShardedEmbeddingStore)
    from paddlebox_tpu.embedding.tiering import (end_pass_rebalance,
                                                 shard_store_factory,
                                                 spill_stats)

    n_shards = 2
    cache_rows = 128 if tiny else (1 << 11 if small else 1 << 15)
    budget = n_shards * cache_rows          # total RAM hot-tier rows
    n_keys = budget * 10                    # the >=10x working set
    n_hot = budget // 2
    n_cold = budget * 2                     # per pass: floods every slot
    passes = 4
    cfg = EmbeddingConfig(dim=8, optimizer="adagrad", learning_rate=0.05)

    def key_window(lo, hi):
        return (np.arange(lo, hi, dtype=np.uint64)
                * np.uint64(2654435761) + np.uint64(1))

    hot = key_window(0, n_hot)
    results: dict = {}
    with _tf.TemporaryDirectory(prefix="pbtpu_spill_drill_") as td:
        for policy in ("freq", "direct"):
            ss = ShardedEmbeddingStore(
                cfg, n_shards,
                store_factory=shard_store_factory(
                    tiering="spill", cache_rows=cache_rows,
                    spill_dir=os.path.join(td, policy), policy=policy))
            # build: the whole key space lands on the spill tier first
            # (LoadSSD2Mem's table, bigger than the hot tier by 10x)
            chunk = 1 << 18
            for lo in range(0, n_keys, chunk):
                ss.lookup_or_init(key_window(lo, min(n_keys, lo + chunk)))
            hot_hits_last = 0
            fetch_s = 0.0
            for p in range(passes):
                cold_lo = n_hot + (p * n_cold) % (n_keys - n_hot - n_cold)
                cold = key_window(cold_lo, cold_lo + n_cold)
                h0 = sum(s.cache_hits for s in ss._shards)
                t0 = _t.perf_counter()
                rows = ss.lookup_or_init(hot)
                hot_hits_last = sum(s.cache_hits
                                    for s in ss._shards) - h0
                cr = ss.lookup_or_init(cold)
                fetch_s = _t.perf_counter() - t0
                # train-like write-back: hot rows accumulate real shows
                # (the admission weight), cold ones one impression each
                rows[:, 0] += 4.0
                ss.write_back(hot, rows)
                cr[:, 0] += 1.0
                ss.write_back(cold, cr)
                end_pass_rebalance(ss)      # the pass-boundary re-score
            st = spill_stats(ss)
            results[policy] = {
                "hot_hit_rate": round(hot_hits_last / n_hot, 4),
                "hit_rate": st["hit_rate"],
                "admitted": st["admitted"], "evicted": st["evicted"],
                "spill_bytes": st["spill_bytes"],
                "fetch_keys_per_s": round((n_hot + n_cold) / fetch_s),
            }
    f, d = results["freq"], results["direct"]
    # simulated token stream of the last pass: hot keys appear 4x (their
    # show increment), cold once — what the exchange would dedup
    tokens = 4 * n_hot + n_cold
    return {
        "table_tiering": "spill", "table_shards": n_shards,
        "tier_policy": "freq", "cache_rows": int(cache_rows),
        "cache_budget_rows": int(budget),
        "working_set_keys": int(n_keys),
        "ws_over_cache": round(n_keys / budget, 1),
        "passes": passes,
        "dedup_ratio": round((n_hot + n_cold) / tokens, 4),
        "hot_hit_rate": f["hot_hit_rate"],
        "direct_hot_hit_rate": d["hot_hit_rate"],
        "hit_rate": f["hit_rate"], "direct_hit_rate": d["hit_rate"],
        "admitted": f["admitted"], "evicted": f["evicted"],
        "direct_evicted": d["evicted"],
        "spill_bytes": f["spill_bytes"],
        "fetch_keys_per_s": f["fetch_keys_per_s"],
    }


def spill_assoc_drill(small: bool, tiny: bool = False) -> dict:
    """spill_assoc point: set-associative RAM-cache geometry
    (``flags.spill_cache_assoc``) vs the direct-mapped baseline on an
    ADVERSARIAL colliding stream — a hot set built so ``assoc`` rows
    land on every set index. Direct-mapped, those rows evict each other
    on every pass (conflict misses — the whole set is one slot); N-way,
    they coexist and the hot re-read holds. Both variants replay the
    IDENTICAL key/write sequence and the drill byte-compares the row
    files at the end: geometry is placement only, never a math change
    (the ``parity`` field the dryrun gate asserts)."""
    import tempfile as _tf
    import time as _t
    from paddlebox_tpu.embedding import EmbeddingConfig
    from paddlebox_tpu.embedding.spill_store import SpillEmbeddingStore

    cache_rows = 128 if tiny else (1 << 11 if small else 1 << 14)
    assoc = 4
    n_keys = cache_rows * 8
    passes = 3
    cfg = EmbeddingConfig(dim=8, optimizer="adagrad", learning_rate=0.05)

    def key_window(lo, hi):
        return (np.arange(lo, hi, dtype=np.uint64)
                * np.uint64(2654435761) + np.uint64(1))

    # row ids are assigned in first-lookup order, so building the whole
    # space with key_window(0, n_keys) pins id i to key i — the hot set
    # below then holds `assoc` ids per direct-mapped slot j (ids
    # j, j+C, j+2C, j+3C all map to slot j mod C) and exactly fills the
    # N-way set j under the set-major geometry
    hot_ids = np.concatenate(
        [np.arange(cache_rows // assoc) + i * cache_rows
         for i in range(assoc)])
    results: dict = {}
    with _tf.TemporaryDirectory(prefix="pbtpu_assoc_drill_") as td:
        for name, policy, ways in (("assoc", "freq", assoc),
                                   ("direct", "direct", 1)):
            st = SpillEmbeddingStore(
                cfg, spill_dir=os.path.join(td, name),
                cache_rows=cache_rows, initial_capacity=n_keys + 16,
                tier_policy=policy, cache_assoc=ways)
            chunk = 1 << 18
            for lo in range(0, n_keys, chunk):
                st.lookup_or_init(key_window(lo, min(n_keys, lo + chunk)))
            hot = key_window(0, n_keys)[hot_ids]
            hot_hits_last = 0
            fetch_s = 1e-9
            for p in range(passes):
                cold_lo = 4 * cache_rows + (p * cache_rows) % (
                    3 * cache_rows)
                cold = key_window(cold_lo, cold_lo + cache_rows)
                h0 = st.cache_hits
                t0 = _t.perf_counter()
                rows = st.lookup_or_init(hot)
                hot_hits_last = st.cache_hits - h0
                cr = st.lookup_or_init(cold)
                fetch_s = _t.perf_counter() - t0
                rows[:, 0] += 4.0
                st.write_back(hot, rows)
                cr[:, 0] += 1.0
                st.write_back(cold, cr)
                st.tier_end_pass()
            st._rows.flush()
            results[name] = {
                "hit_rate": round(hot_hits_last / len(hot_ids), 4),
                "conflicts": int(st.conflict_misses),
                "fetch_keys_per_s": round(
                    (len(hot_ids) + len(cold)) / fetch_s),
                "rows": np.array(st._rows[:st._n], np.float32),
            }
    a, d = results["assoc"], results["direct"]
    return {
        "cache_rows": int(cache_rows), "assoc": int(assoc),
        "working_set_keys": int(n_keys),
        "hot_set_rows": int(len(hot_ids)),
        "passes": passes,
        "assoc_hit_rate": a["hit_rate"],
        "direct_hit_rate": d["hit_rate"],
        "conflict_misses_assoc": a["conflicts"],
        "conflict_misses_direct": d["conflicts"],
        "parity": bool(np.array_equal(a.pop("rows"), d.pop("rows"))),
        "fetch_keys_per_s": a["fetch_keys_per_s"],
    }


def boundary_drill(small: bool, tiny: bool = False) -> dict:
    """boundary_incremental point (ISSUE 14): the same key stream through
    (a) the incremental + overlapped feed — resident reuse, background
    staging consumed at the boundary, stale-delta patching after a
    shrink, spill-tier madvise prefetch — and (b) the full-rebuild feed
    (``flags.incremental_feed=False``, no staging, the resident set
    dropped every boundary), with a pure-eviction ``shrink`` between
    passes so every boundary crosses a store mutation (the case that
    used to force the full rebuild even with reuse on). Records
    boundary_seconds + the build/h2d/spill_fault_in split for both
    variants and proves the two land bit-identical store bytes."""
    import tempfile as _tf
    import jax.numpy as jnp
    from paddlebox_tpu.config import flags as config_flags
    from paddlebox_tpu.embedding import EmbeddingConfig
    from paddlebox_tpu.embedding.feed_pass import FeedPassManager
    from paddlebox_tpu.embedding.spill_store import SpillEmbeddingStore

    # tiny keeps the SMALL working set: below ~20k rows the full-rebuild
    # baseline costs less than the combine's fixed jit dispatch on CPU
    # and the point would measure dispatch overhead, not the feed
    n_keys = 40_000 if (tiny or small) else 200_000
    churn = n_keys // 10                 # 90% overlap pass to pass
    passes = 5
    timed_from = 2        # pass-1 boundary compiles the combine/patch
    #                       jits once; steady-state boundaries gate
    cfg = EmbeddingConfig(dim=8, optimizer="adagrad", learning_rate=0.05)

    def key_window(lo, hi):
        return np.sort(np.arange(lo, hi, dtype=np.uint64)
                       * np.uint64(2654435761) + np.uint64(1))

    def run(incremental: bool, spill_dir: str) -> dict:
        config_flags.incremental_feed = incremental
        store = SpillEmbeddingStore(cfg, spill_dir=spill_dir,
                                    cache_rows=max(256, n_keys // 8))
        mgr = FeedPassManager(store)
        bsec, split = 0.0, {"build": 0.0, "h2d": 0.0,
                            "spill_fault_in": 0.0}
        stats = {"fresh_rows": 0, "reused_rows": 0, "patched_rows": 0,
                 "stale_rows": 0}
        for p in range(passes):
            keys = key_window(p * churn, p * churn + n_keys)
            ws = mgr.begin_pass(keys)
            if p >= timed_from:          # steady state (see timed_from)
                bsec += mgr.last_boundary_seconds
                for k in split:
                    split[k] += mgr.last_boundary_split.get(k, 0.0)
            if p:                        # pass-1 full build is identical
                stats["fresh_rows"] += mgr.last_fresh_rows
                stats["reused_rows"] += mgr.last_reused_rows
                stats["patched_rows"] += mgr.last_patched_rows
                stats["stale_rows"] += mgr.last_stale_rows
            # train: touch every key; the cold tail (keys absent from
            # the next pass) zeroes its show counter so the boundary
            # shrink evicts exactly it — a pure store-side mutation
            # every single boundary crosses
            idx = ws.translate(keys)
            t = np.asarray(ws.table).copy()
            staying = np.isin(keys, key_window((p + 1) * churn,
                                               (p + 1) * churn + n_keys),
                              assume_unique=True)
            t[idx[staying], 0] += 1.0
            t[idx[~staying], 0] = 0.0
            t[idx, 2] += 0.5
            mgr.end_pass(ws, jnp.asarray(t))
            if incremental:
                # overlap: stage the next pass BEFORE the shrink, so the
                # boundary exercises the staged-patch delta plane
                mgr.begin_feed_pass(key_window((p + 1) * churn,
                                               (p + 1) * churn + n_keys))
            # pure-eviction hygiene shrink (decay=1.0): flushes the
            # device tier, then evicts this pass's cold tail — a
            # mutation whose reach the stale log can prove
            store.shrink(min_show=0.5, decay=1.0)
        mgr.drop()
        all_keys = key_window(passes * churn, passes * churn + n_keys
                              - churn)
        rows = store.peek_rows(all_keys)
        return {"bsec": bsec, "split": split, "stats": stats,
                "rows": rows, "prefetched": int(store.prefetched_rows)}

    with _tf.TemporaryDirectory(prefix="pbtpu_boundary_drill_") as td:
        startup = config_flags.incremental_feed
        try:
            inc = run(True, os.path.join(td, "inc"))
            full = run(False, os.path.join(td, "full"))
        finally:
            config_flags.incremental_feed = startup
    parity = bool(np.array_equal(inc["rows"], full["rows"]))
    return {
        "working_set_keys": int(n_keys), "passes": passes,
        "overlap_frac": round(1 - churn / n_keys, 2),
        "boundary_seconds": round(inc["bsec"], 4),
        "full_rebuild_seconds": round(full["bsec"], 4),
        "speedup": round(full["bsec"] / inc["bsec"], 2)
        if inc["bsec"] > 0 else None,
        "boundary_split": {k: round(v, 4)
                           for k, v in inc["split"].items()},
        "full_boundary_split": {k: round(v, 4)
                                for k, v in full["split"].items()},
        # the incremental variant fetches almost nothing from disk, so
        # the readahead shows on the FULL-rebuild side (its every
        # boundary re-faults the working set through the spill tier)
        "prefetched_rows": inc["prefetched"],
        "full_prefetched_rows": full["prefetched"],
        "parity": parity,
        **{k: int(v) for k, v in inc["stats"].items()},
    }


def adaptive_wire_drill(small: bool, tiny: bool = False) -> dict:
    """Drifting-sparsity adaptive-wire drill (ISSUE 16): the REAL
    trainer on a 2-shard mesh with flags.exchange_adaptive on, fed a
    key stream whose duplication depth drifts across the wire regimes —
    duplication-heavy passes (tiny key pool: the merged f32 sum
    amortizes over many contributions) then unique-heavy passes (wide
    pool: the wire bytes dominate and the narrow wire wins). The
    controller must flip the wire within the hysteresis bound, and the
    pass-summed modeled wire cost of the ADAPTIVE run must be <= every
    fixed wire's cost on the same stream (``adaptive_best`` — the
    deterministic gate; real-chip wall-clock wire A/B stays queued for
    the consolidated chip round). Throughput rides along gate-held like
    the other sharded points."""
    import time as _t
    from paddlebox_tpu import monitor
    from paddlebox_tpu.config import flags as config_flags
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.embedding import (EmbeddingConfig,
                                         HostEmbeddingStore, exchange)
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig

    bs = 64
    steps = 2 if tiny else (4 if small else 8)
    num_slots = 4
    schema = DataFeedSchema.ctr(num_sparse=num_slots, num_float=1,
                                batch_size=bs, max_len=1)
    dense = [s for s in schema.float_slots if s.name != "label"]
    # The drift: duplication-heavy passes draw from a single hot key per
    # slot (merge depth ~32, deep in the f32 regime — the per-lane lane
    # cost amortizes over dozens of duplicates) and carry 6x the
    # traffic — the busy head of a stream, where the exact wide wire
    # wins outright; the tail's unique-heavy passes (pool 16x the
    # stream, depth ~1) are bytes-bound, where the narrow wire wins.
    # 4 heavy + 5 light passes: the hysteresis window (2 suboptimal
    # passes after the drift) must cost less than a pinned wire loses
    # across the other seven.
    phases = ["dup"] * 4 + ["uni"] * 5

    def pass_dataset(kind, seed):
        n_ex = bs * steps * (6 if kind == "dup" else 1)
        space = 1 if kind == "dup" else 16 * n_ex
        ds = SlotDataset(schema)
        ds.records = _synth_pass(schema, n_ex, num_slots, dense, space,
                                 seed=seed)
        return ds

    def build_trainer():
        store = HostEmbeddingStore(EmbeddingConfig(
            dim=8, optimizer="adagrad", learning_rate=0.05))
        return Trainer(DeepFMModel(num_slots=num_slots, emb_dim=8,
                                   dense_dim=1, hidden=(16,)),
                       store, schema, make_mesh(2),
                       TrainerConfig(global_batch_size=bs)), store

    saved = (config_flags.table_layout, config_flags.exchange_wire,
             config_flags.exchange_adaptive)
    try:
        config_flags.table_layout = "sharded"
        config_flags.exchange_wire = "f32"
        config_flags.exchange_adaptive = True
        tr, store = build_trainer()
        cfg = store.cfg
        per_pass = []
        total = {w: 0.0 for w in exchange.WIRES}
        adaptive_cost = 0.0
        examples = 0
        t0 = _t.perf_counter()
        for i, kind in enumerate(phases):
            active = tr.exchange_wire
            snap0 = monitor.STATS.snapshot()
            out = tr.train_pass(pass_dataset(kind, seed=100 + i))
            snap = monitor.STATS.snapshot()
            toks = int(snap.get("exchange.tokens", 0)
                       - snap0.get("exchange.tokens", 0))
            uniq = int(snap.get("exchange.unique_lanes", 0)
                       - snap0.get("exchange.unique_lanes", 0))
            examples += out["steps"] * bs
            adaptive_cost += exchange.wire_cost(cfg, toks, uniq, active)
            for w in exchange.WIRES:
                total[w] += exchange.wire_cost(cfg, toks, uniq, w)
            per_pass.append({"kind": kind, "wire": active,
                             "tokens": toks, "unique": uniq})
        seconds = _t.perf_counter() - t0
        switches = tr._wire_controller.switches
        hysteresis = tr._wire_controller.hysteresis
    finally:
        (config_flags.table_layout, config_flags.exchange_wire,
         config_flags.exchange_adaptive) = saved
    wire_path = [p["wire"] for p in per_pass]
    return {
        "examples_per_sec_per_chip": round(
            examples / max(seconds, 1e-9) / 2, 1),
        "passes": per_pass,
        "wire_path": wire_path,
        "switches": int(switches),
        "hysteresis": int(hysteresis),
        # the gate: summed modeled cost, adaptive vs each fixed wire
        "adaptive_cost": round(adaptive_cost, 1),
        "fixed_costs": {w: round(c, 1) for w, c in total.items()},
        "adaptive_best": bool(
            switches >= 1
            and all(adaptive_cost <= c + 1e-6 for c in total.values())),
        "table_shards": 2,
        "simulated": True,
    }


def self_healing_drill(small: bool, tiny: bool = False) -> dict:
    """Self-healing runtime drill (ISSUE 18): the doctor-driven
    remediation loop and the elastic shrink→grow round trip, end to end
    on the REAL paths. Part one trains a tiny job with resident reuse
    OFF and a seeded pass-boundary wall: the doctor's boundary-wall rule
    fires over the drill's own flight records, the RemediationController
    applies ``enable-incremental-feed`` under the parity guard, and the
    before/after counter deltas land in the (schema-validated) flight
    record — then the drill's telemetry stream is fed back through the
    doctor CLI, whose ``--fail-on warn`` must gate (exit 1) on the same
    finding CI would see. Part two forms a 2-member elastic world, loses
    rank 1, and a joiner thread re-enters via ``ElasticWorld.admit``
    while ``poll_grow`` consumes heartbeat-gap evidence: the round trip
    must converge back to a FULL world — degraded gauge cleared,
    ``world_grow`` event carrying ``joined=[1]``."""
    import contextlib
    import io
    import tempfile as _tempfile
    import threading as _threading
    import time as _t
    from paddlebox_tpu import monitor
    from paddlebox_tpu.config import flags as _flags, set_flags
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.distributed.resilience import ElasticWorld
    from paddlebox_tpu.distributed.store import FileStore
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.monitor import doctor as doctor_lib
    from paddlebox_tpu.monitor.flight import validate_flight_record
    from paddlebox_tpu.monitor.hub import STATS
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.runtime.remediation import RemediationController
    from paddlebox_tpu.train import Trainer, TrainerConfig

    out: dict = {}
    hub = monitor.hub()
    was_enabled = hub.enabled
    ms = monitor.MemorySink()
    hub.enable(ms)
    f0 = (_flags.incremental_feed, _flags.self_healing,
          _flags.self_healing_sustain)
    set_flags(incremental_feed=False, self_healing=True,
              self_healing_sustain=1)
    try:
        with _tempfile.TemporaryDirectory() as td:
            # -- part one: finding -> guarded apply -> flight record ------
            bs = 64
            n_ex = bs * (2 if tiny else (8 if small else 32))
            schema = DataFeedSchema.ctr(num_sparse=4, num_float=1,
                                        batch_size=bs, max_len=1)
            rec = _synth_pass(schema, n_ex, 4,
                              [s for s in schema.float_slots
                               if s.name != "label"], 2000, seed=11)
            store = HostEmbeddingStore(EmbeddingConfig(
                dim=8, optimizer="adagrad", learning_rate=0.05))
            tr = Trainer(DeepFMModel(num_slots=4, emb_dim=8, dense_dim=1,
                                     hidden=(16,)),
                         store, schema, make_mesh(1),
                         TrainerConfig(global_batch_size=bs))
            box = BoxPS(store)
            ctl = tr.enable_self_healing()
            ds = SlotDataset(schema)
            ds.records = rec
            # findings are fed from a diagnosis over the DRILL's own
            # flight records (feed_report, the world-view path): the
            # process-global flight ring may carry earlier bench passes
            # whose reuse counters would mask this run's symptom
            my_flights: list = []
            applied = after = None
            flight_errs: list = ["unvalidated"]
            for _ in range(4):
                box.begin_pass()
                tr.train_pass(ds)
                # the seeded wall: a boundary account dominating the
                # tiny pass is the rule's trigger — the seconds are
                # synthetic, the decision path is not
                monitor.hub().record_train(boundary_seconds=30.0)
                ctl.feed_report(doctor_lib.diagnose(flights=my_flights))
                res = box.end_pass(trainer=tr)
                my_flights.append(res["flight_record"])
                healed = res.get("remediation")
                if applied is None:
                    if healed and healed.get("status") == "applied":
                        applied = healed
                        flight_errs = validate_flight_record(
                            res["flight_record"])
                elif healed and "after" in healed:
                    after = healed
                    break
            out["applied"] = applied
            out["after_keys"] = sorted((after or {}).get("after") or {})
            out["flight_schema_errors"] = flight_errs
            out["flag_flipped"] = bool(_flags.incremental_feed)
            out["remediation_events"] = len(ms.find("remediation_applied"))
            # the CI gate sees what the runtime did to itself: the same
            # stream through the doctor CLI must trip --fail-on warn
            tele = os.path.join(td, "telemetry")
            os.makedirs(tele)
            with open(os.path.join(tele, "events.jsonl"), "w") as f:
                for r in ms.records:
                    f.write(json.dumps(r, default=str) + "\n")
            rep_out = io.StringIO()
            with contextlib.redirect_stdout(rep_out):
                out["doctor_fail_on_warn"] = doctor_lib.main(
                    [tele, "--json", "--fail-on", "warn"])
                out["doctor_fail_on_critical"] = doctor_lib.main(
                    [tele, "--json", "--fail-on", "critical"])
            rep = json.loads(rep_out.getvalue().splitlines()[0])
            out["doctor_found"] = sorted(f["rule"]
                                         for f in rep["findings"])
            # -- part two: shrink -> admit -> poll_grow round trip --------
            wkw = dict(heartbeat_interval_s=0.05, lost_after_s=30.0,
                       stall_after_s=60.0, reform_timeout_s=2.0,
                       initial_world=2)
            spath = os.path.join(td, "world")
            w0 = ElasticWorld(FileStore(spath, namespace="heal",
                                        poll_s=0.01), 0, [0, 1], **wkw)
            t0 = _t.perf_counter()
            w1 = w0.reform([1])           # rank 1 lost: degraded gen 1
            out["degraded_after_shrink"] = STATS.snapshot().get(
                "resilience.degraded")
            jres: dict = {}
            jerr: list = []

            def _joiner():
                try:
                    w = ElasticWorld.admit(
                        FileStore(spath, namespace="heal", poll_s=0.01),
                        1, timeout_s=60.0, **wkw)
                    jres["gen"], jres["members"] = w.gen, w.members
                    w.collectives.barrier("post_grow")
                    w.close()
                except BaseException as e:   # surfaced via joiner_errors
                    jerr.append(repr(e))

            jt = _threading.Thread(target=_joiner)
            jt.start()
            gctl = RemediationController()
            hbgap = {"rule": "heartbeat-gap", "severity": "critical",
                     "summary": "drill", "suggestion": "",
                     "evidence": {"degraded": True, "world_size": 1}}
            w2 = w1
            deadline = _t.monotonic() + 90.0
            while w2 is w1 and _t.monotonic() < deadline:
                w2, _cur = gctl.poll_grow(w1, findings=[hbgap])
            if w2 is not w1:
                w2.collectives.barrier("post_grow")
            round_trip = _t.perf_counter() - t0
            jt.join(timeout=60.0)
            out["degraded_after_grow"] = STATS.snapshot().get(
                "resilience.degraded")
            grows = ms.find("world_grow")
            out.update(
                round_trip_seconds=round(round_trip, 4),
                grow_gen=w2.gen, grow_members=list(w2.members),
                joiner_gen=jres.get("gen"),
                joiner_members=jres.get("members"),
                joiner_errors=jerr,
                world_grow_joined=(grows[-1]["fields"]["joined"]
                                   if grows else None))
            w2.close()
    finally:
        set_flags(incremental_feed=f0[0], self_healing=f0[1],
                  self_healing_sustain=f0[2])
        if was_enabled:
            # detach only the drill's sink; the caller's sinks stay
            with hub._lock:
                hub._sinks = tuple(s for s in hub._sinks if s is not ms)
        else:
            hub.disable()
    return out


def _run_sharded_probe(small: bool, tiny: bool = False) -> dict:
    """Run the sharded-exchange matrix points in a 2-virtual-device CPU
    subprocess (``--sharded-probe``): a single-device environment cannot
    host an in-process multi-shard mesh, and the backend's device count
    is fixed at init. The probe's numbers are simulated (CPU), but the
    FIELDS — table_layout, exchange_wire, table_shards, dedup ratio —
    are the product, and the eps values gate like-for-like because the
    probe environment is stable round over round."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2"
                        ).strip()
    env.pop("PBTPU_BENCH_SMALL", None)
    args = [sys.executable, os.path.abspath(__file__), "--sharded-probe"]
    if tiny:
        args.append("--tiny")
    elif small:
        args.append("--small")
    try:
        r = subprocess.run(args, capture_output=True, text=True, env=env,
                           timeout=1200)
        if r.returncode != 0:
            return {"error": r.stderr[-500:]}
        return json.loads(r.stdout.strip().splitlines()[-1])
    except Exception as e:
        return {"error": repr(e)}


def sharded_probe_main() -> int:
    """Subprocess entry for the sharded-exchange matrix points (see
    _run_sharded_probe). Prints ONE JSON line."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu import monitor
    tiny = "--tiny" in sys.argv
    small = "--small" in sys.argv or tiny
    out: dict = {"simulated": True, "devices": len(jax.devices()),
                 "points": {}}
    for mname, w in (("sharded_wire_f32", "f32"),
                     ("sharded_wire_bf16", "bf16"),
                     ("sharded_wire_int8", "int8")):
        snap0 = monitor.STATS.snapshot()
        try:
            eps, detail = device_step_bench(
                small, n_steps=2 if tiny else 3, n_windows=1, tiny=tiny,
                table_layout="sharded", exchange_wire=w)
            snap = monitor.STATS.snapshot()
            toks = snap.get("exchange.tokens", 0.0) - snap0.get(
                "exchange.tokens", 0.0)
            uniq = snap.get("exchange.unique_lanes", 0.0) - snap0.get(
                "exchange.unique_lanes", 0.0)
            out["points"][mname] = {
                "examples_per_sec_per_chip": round(eps, 1),
                "table_layout": detail["table_layout"],
                "exchange_wire": detail["exchange_wire"],
                "table_shards": detail["table_shards"],
                "pull_engine": detail["pull_engine"],
                "push_engine": detail["push_engine"],
                "dedup_ratio": (round(uniq / toks, 4) if toks else None),
                "simulated": True,
            }
        except Exception as e:
            out["points"][mname] = {"error": repr(e)}
    # the drifting-sparsity adaptive point: same 2-device mesh, but the
    # wire is the CONTROLLER's to pick — the point is the proof that
    # per-pass re-costing beats every pinned wire on a stream whose
    # dedup depth drifts (the fixed points above are its baselines)
    try:
        out["points"]["adaptive_wire"] = adaptive_wire_drill(
            small, tiny=tiny)
    except Exception as e:
        out["points"]["adaptive_wire"] = {"error": repr(e)}
    print(json.dumps(out), flush=True)
    return 0


def dryrun_main() -> int:
    """Fast CPU smoke of the bench's regression-gate, stage-attribution,
    and push-floor code paths (tier-1: exercised on every PR instead of
    only on-chip). Tiny geometry — the numbers are meaningless, the
    MACHINERY is the product: the attribution must produce a stage
    account, the floor must close (or abstain with a reason), and the
    gate must (a) skip bests recorded on foreign hardware, (b) TRIP on
    an injected synthetic >10% regression, (c) honor an explicit waiver
    note, (d) pass at parity. Prints ONE JSON line; exit 0 iff all four
    behaved."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu import monitor
    from paddlebox_tpu.utils.step_probe import finalize_push_floor

    # telemetry rides the dryrun too: the artifact must embed the hub
    # summary (counters + any flight records) — asserted as a check below
    # (the sink is kept: the world-trace embed merges its record ring)
    dryrun_sink = monitor.MemorySink()
    monitor.hub().enable(dryrun_sink)
    checks: dict = {}
    eps, detail, ctx = device_step_bench(True, n_steps=2, n_windows=1,
                                         tiny=True, return_ctx=True)
    attr = _attribute_with_retry(ctx["tr"], ctx["ws"], ctx["staged0"],
                                 ctx["step_seconds"], True, tiny=True)
    detail["stage_attribution"] = attr
    checks["attribution_ok"] = bool(attr.get("stages"))
    if "push_floor" in detail:
        finalize_push_floor(detail["push_floor"],
                            (attr.get("stages") or {}).get("sparse_push"))
    checks["floor_ok"] = "closed" in (detail.get("push_floor") or {})
    # the per-point push-engine record (ISSUE 13): every training point
    # must name the resolver's engine, and the floor must carry the
    # per-candidate-engine closure statements the doctor's push-floor
    # rule names concrete flags.push_engine forces from
    from paddlebox_tpu.ops import pallas_kernels as _pk_chk
    _pf = detail.get("push_floor") or {}
    checks["push_engine_recorded"] = (
        detail.get("push_engine") in _pk_chk.PUSH_ENGINES
        and isinstance(_pf.get("engines"), dict)
        and all(e in _pk_chk.PUSH_ENGINES for e in _pf["engines"])
        and all("closed" in v for v in _pf["engines"].values())
        and _pf.get("engine") == detail.get("push_engine"))
    ctx.clear()
    # elastic drill rides the dryrun too: the artifact schema must carry
    # world_resize_seconds and the degraded matrix point, and tier-1 must
    # catch drift in those fields before a chip run does
    try:
        drill = elastic_drill(True, tiny=True)
    except Exception as e:
        drill = {"error": repr(e)}
    detail.setdefault("matrix", {})["elastic_degraded"] = drill
    detail["world_resize_seconds"] = drill.get("world_resize_seconds")
    checks["elastic_fields"] = (
        isinstance(drill.get("world_resize_seconds"), float)
        and drill["world_resize_seconds"] > 0
        and isinstance(drill.get("examples_per_sec_per_chip"),
                       (int, float))
        and drill.get("resumed_pass") == 1
        and drill.get("rerouted_records", 0) > 0)
    # serving drill rides the dryrun too: the artifact schema must carry
    # publish/swap/latency points (and their lower-is-better gating must
    # hold) before a chip run records them
    try:
        sdrill = serving_drill(True, tiny=True)
    except Exception as e:
        sdrill = {"error": repr(e)}
    detail.setdefault("matrix", {})["serving"] = sdrill
    checks["serving_fields"] = (
        isinstance(sdrill.get("publish_seconds"), float)
        and sdrill["publish_seconds"] > 0
        and isinstance(sdrill.get("swap_pause_ms"), float)
        and sdrill["swap_pause_ms"] > 0
        and isinstance(sdrill.get("p99_ms"), (int, float))
        and sdrill.get("p99_ms", 0) > 0
        and sdrill.get("failures") == 0
        and sdrill.get("swapped_to_version") == 2)
    # version-split drill rides the dryrun too (ISSUE 19): the shadow
    # two-version loop must produce a schema-valid serving window record
    # with per-version AUC + score-KL attribution, and the doctor's
    # three serving rules must have evaluated it (version-regression off
    # real signal, not no-data) — before a chip round records the point
    try:
        ssd = serving_split_drill(True, tiny=True)
    except Exception as e:
        ssd = {"error": repr(e)}
    detail.setdefault("matrix", {})["serving_split"] = ssd
    _ssr = ssd.get("doctor_rules") or {}
    checks["serving_obs_fields"] = (
        ssd.get("record_schema_errors") == []
        and ssd.get("requests", 0) > 0
        and isinstance(ssd.get("shadow_p99_ms"), float)
        and ssd.get("shadow_p99_ms", 0) > 0
        and isinstance(ssd.get("stable_auc"), float)
        and isinstance(ssd.get("candidate_auc"), float)
        and isinstance(ssd.get("score_kl"), float)
        and ssd.get("score_kl", -1) >= 0
        and set(_ssr) == {"version-regression", "p99-burn",
                          "swap-regression"}
        and _ssr.get("version-regression") in ("quiet", "fired"))
    # fleet drill rides the dryrun too (ISSUE 20): two replicas behind
    # the router with one injected slow — hedging must keep the routed
    # tail under the injected delay with zero failed/shed requests, the
    # publish must converge fleet-wide, the governor must HOLD the
    # regressing candidate, and the composed fleet window record must be
    # schema-valid and fire the doctor's fleet-degraded rule (off the
    # recorded hold) — before a chip round records the point
    try:
        fsd = serving_fleet_drill(True, tiny=True)
    except Exception as e:
        fsd = {"error": repr(e)}
    detail.setdefault("matrix", {})["serving_fleet"] = fsd
    checks["fleet_fields"] = (
        fsd.get("record_schema_errors") == []
        and fsd.get("requests", 0) > 0
        and fsd.get("failures", -1) == 0
        and fsd.get("sheds", -1) == 0
        and isinstance(fsd.get("p99_ms"), float)
        and 0 < fsd.get("p99_ms", 0) < fsd.get("slow_replica_ms", 0)
        and fsd.get("hedges", 0) >= 1
        and fsd.get("hedges_won", 0) >= 1
        and isinstance(fsd.get("swap_convergence_s"), float)
        and fsd.get("swap_convergence_s", 0) > 0
        and fsd.get("swapped_to_version") == 2
        and fsd.get("promote_decision") == "hold"
        and fsd.get("promote_holds") == 1
        and (fsd.get("doctor_rules") or {}).get("fleet-degraded")
        == "fired")
    # tiered-table drill rides the dryrun too (ISSUE 11): the spill_10x
    # point must carry a working set >= 10x the RAM cache budget through
    # the sharded+spill path, with the tier identity + cache budget +
    # dedup ratio recorded, and the show-count-weighted admission policy
    # must beat the direct-mapped baseline's hot-tier hit rate on the
    # same traffic — before a chip round ever records the point
    try:
        spd = spill_drill(True, tiny=True)
    except Exception as e:
        spd = {"error": repr(e)}
    detail.setdefault("matrix", {})["spill_10x"] = spd
    checks["spill_fields"] = (
        spd.get("table_tiering") == "spill"
        and spd.get("table_shards") == 2
        and isinstance(spd.get("cache_rows"), int)
        and spd.get("working_set_keys", 0)
        >= 10 * spd.get("cache_budget_rows", 1 << 30)
        and isinstance(spd.get("dedup_ratio"), float)
        and 0 < spd["dedup_ratio"] <= 1
        and isinstance(spd.get("fetch_keys_per_s"), int)
        and spd.get("hot_hit_rate", 0.0)
        > spd.get("direct_hot_hit_rate", 1.0)
        and spd.get("evicted", 1 << 30) < spd.get("direct_evicted", 0))
    # set-associative geometry drill rides the dryrun too: on the
    # adversarial colliding stream the N-way cache must hold a hot hit
    # rate STRICTLY above direct-mapped at the same row budget, the
    # baseline must show the conflict misses that explain it, and the
    # two variants' row files must be byte-identical (geometry is
    # placement only) — before a chip round ever records the point
    try:
        sad = spill_assoc_drill(True, tiny=True)
    except Exception as e:
        sad = {"error": repr(e)}
    detail.setdefault("matrix", {})["spill_assoc"] = sad
    checks["assoc_fields"] = (
        sad.get("assoc") == 4
        and isinstance(sad.get("cache_rows"), int)
        and sad.get("parity") is True
        and sad.get("conflict_misses_direct", 0) > 0
        and isinstance(sad.get("assoc_hit_rate"), float)
        and isinstance(sad.get("direct_hit_rate"), float)
        and sad.get("assoc_hit_rate", 0.0)
        > sad.get("direct_hit_rate", 1.0)
        and isinstance(sad.get("fetch_keys_per_s"), int))
    # pass-boundary drill rides the dryrun too (ISSUE 14): the
    # incremental + overlapped feed must land bit-identical store bytes
    # AND a boundary wall strictly below the full-rebuild baseline on
    # the same key stream (with the 3-way split + the fresh/reused/
    # patched accounting recorded) — before a chip round ever records it
    try:
        bdrill = boundary_drill(True, tiny=True)
        if not (0 < bdrill.get("boundary_seconds", 0.0)
                < bdrill.get("full_rebuild_seconds", 0.0)):
            # the only wall-clock comparison in the dryrun: one
            # scheduler stall on a loaded runner can invert a ~1.5x
            # margin, so the timing race gets one retry — the
            # deterministic fields (parity, row accounting) never do
            bdrill = boundary_drill(True, tiny=True)
    except Exception as e:
        bdrill = {"error": repr(e)}
    detail.setdefault("matrix", {})["boundary_incremental"] = bdrill
    checks["boundary_fields"] = (
        bdrill.get("parity") is True
        and isinstance(bdrill.get("boundary_seconds"), float)
        and isinstance(bdrill.get("full_rebuild_seconds"), float)
        and 0 < bdrill["boundary_seconds"]
        < bdrill["full_rebuild_seconds"]
        and set(bdrill.get("boundary_split", {}))
        == {"build", "h2d", "spill_fault_in"}
        and bdrill.get("reused_rows", 0) > 0
        and bdrill.get("fresh_rows", 0) > 0
        and bdrill.get("patched_rows", 0) > 0
        # the readahead is advisory BY CONTRACT: require it only where
        # the platform has madvise at all (elsewhere the documented
        # fallback is the synchronous fault-in)
        and (bdrill.get("full_prefetched_rows", 0) > 0
             or not hasattr(__import__("mmap"), "MADV_WILLNEED")))
    # the self-healing runtime rides the dryrun too (ISSUE 18): the
    # remediation loop must CLOSE — a boundary-wall finding diagnosed
    # from the drill's own flight records auto-applies
    # enable-incremental-feed under the parity guard with the
    # before/after delta in a schema-valid flight record, the drill's
    # telemetry gates under doctor --fail-on warn, and the elastic
    # shrink->grow round trip converges back to a full world with the
    # degraded gauge cleared — before any chip run leans on it
    try:
        heal = self_healing_drill(True, tiny=True)
    except Exception as e:
        heal = {"error": repr(e)}
    detail.setdefault("matrix", {})["self_healing"] = heal
    _ap = heal.get("applied") or {}
    checks["self_healing_fields"] = (
        _ap.get("rule") == "boundary-wall"
        and _ap.get("action") == "enable-incremental-feed"
        and _ap.get("status") == "applied"
        and isinstance(_ap.get("before"), dict)
        and heal.get("after_keys") == ["feed_pass.fresh_rows",
                                       "feed_pass.reused_rows"]
        and heal.get("flight_schema_errors") == []
        and heal.get("flag_flipped") is True
        and heal.get("remediation_events", 0) >= 1
        and heal.get("doctor_fail_on_warn") == 1
        and heal.get("doctor_fail_on_critical") == 0
        and "boundary-wall" in (heal.get("doctor_found") or ())
        and heal.get("degraded_after_shrink") == 1.0
        and heal.get("degraded_after_grow") == 0.0
        and heal.get("grow_gen") == 2
        and heal.get("grow_members") == [0, 1]
        and heal.get("joiner_members") == [0, 1]
        and heal.get("joiner_errors") == []
        and heal.get("world_grow_joined") == [1])
    # sharded-exchange points ride the dryrun too (ISSUE 10): the 2-
    # virtual-device probe must produce the sharded matrix points with
    # table_layout / exchange_wire / table_shards recorded and a real
    # dedup ratio, before a multi-chip run ever records them
    probe = _run_sharded_probe(True, tiny=True)
    for pname, p in (probe.get("points") or {}).items():
        detail.setdefault("matrix", {})[pname] = p
    sp = probe.get("points") or {}
    f32p = sp.get("sharded_wire_f32") or {}
    bfp = sp.get("sharded_wire_bf16") or {}
    i8p = sp.get("sharded_wire_int8") or {}
    checks["sharded_fields"] = (
        f32p.get("table_layout") == "sharded"
        and f32p.get("exchange_wire") == "f32"
        and bfp.get("exchange_wire") == "bf16"
        and i8p.get("exchange_wire") == "int8"
        and f32p.get("push_engine") in _pk_chk.PUSH_ENGINES
        and f32p.get("table_shards") == 2
        and isinstance(f32p.get("examples_per_sec_per_chip"),
                       (int, float))
        and isinstance(bfp.get("examples_per_sec_per_chip"),
                       (int, float))
        and isinstance(i8p.get("examples_per_sec_per_chip"),
                       (int, float))
        and (f32p.get("dedup_ratio") or 0) > 0
        and "table_layout" in detail and "exchange_wire" in detail
        and "table_shards" in detail)
    # the adaptive point's CONTRACT (ISSUE 16): on the drifting-sparsity
    # stream the controller must actually flip (within its hysteresis
    # bound of the drift pass) and land a modeled wire cost no worse
    # than EVERY fixed wire — adaptive that loses to a pinned wire is a
    # regression, not a feature
    from paddlebox_tpu.embedding import exchange as _exch_chk
    adp = sp.get("adaptive_wire") or {}
    wpath = adp.get("wire_path") or []
    n_dup = sum(1 for k in (adp.get("passes") or [])
                if k.get("kind") == "dup")
    checks["adaptive_wire_fields"] = (
        adp.get("adaptive_best") is True
        and adp.get("switches", 0) >= 1
        and isinstance(adp.get("adaptive_cost"), (int, float))
        and set(adp.get("fixed_costs") or {}) == set(_exch_chk.WIRES)
        and len(wpath) == len(adp.get("passes") or ())
        # the flip lands within hysteresis passes of the dup->uni drift
        and 0 < n_dup < len(wpath)
        and wpath[:n_dup] == ["f32"] * n_dup
        and all(w == wpath[-1] for w in
                wpath[n_dup + adp.get("hysteresis", 2):])
        and wpath[-1] != "f32")
    g_lat = apply_regression_gate(
        {"serving.p99_ms": 10.0},
        {"device_kind": None, "metrics": {"serving.p99_ms": 5.0}}, "")
    checks["latency_gate_trips_lower_is_better"] = (
        not g_lat["ok"]
        and apply_regression_gate(
            {"serving.p99_ms": 4.0},
            {"device_kind": None,
             "metrics": {"serving.p99_ms": 5.0}}, "")["ok"])
    # bare _s is lower-is-better (the fleet's swap convergence) while
    # _per_s stays throughput — a slower convergence must trip, a faster
    # fetch rate must NOT read as a regression
    checks["convergence_gate_trips_lower_is_better"] = (
        not apply_regression_gate(
            {"serving_fleet.swap_convergence_s": 8.0},
            {"device_kind": None,
             "metrics": {"serving_fleet.swap_convergence_s": 2.0}},
            "")["ok"]
        and apply_regression_gate(
            {"spill_10x.fetch_keys_per_s": 9000.0},
            {"device_kind": None,
             "metrics": {"spill_10x.fetch_keys_per_s": 5000.0}},
            "")["ok"])
    # the world trace rides the dryrun too (ISSUE 15): a traced probe
    # pass whose publish flow pair must merge into a Chrome-trace summary
    # embedded in the artifact — asserted like doctor_embedded. The probe
    # runs the REAL machinery end to end (sampled begin_pass -> stamped
    # span -> flow points -> in-memory merge), not a synthetic dict.
    from paddlebox_tpu.config import flags as _flags
    from paddlebox_tpu.monitor import trace as trace_lib
    _prev_trace = _flags.trace
    try:
        _flags.trace = True
        hub = monitor.hub()
        hub.begin_pass(9001, owner="bench")
        with monitor.span("publish"):
            trace_lib.flow("publish", "v9001", role="src")
        trace_lib.flow("publish", "v9001", role="dst")
        hub.end_pass()
    finally:
        _flags.trace = _prev_trace
    _stream = trace_lib.records_to_stream(dryrun_sink.records)
    detail["world_trace"] = trace_lib.summarize(
        trace_lib.merge_streams([_stream], [0]))
    detail["telemetry"] = monitor.hub().summary()
    # the run-doctor verdict rides the dryrun too (ISSUE 12): the
    # artifact must embed a schema-valid report with the boundary-wall
    # rule evaluated and the dryrun's own push_floor fed to the
    # push-floor rule — asserted like telemetry_embedded
    from paddlebox_tpu.monitor import doctor as doctor_lib
    detail["doctor"] = doctor_lib.diagnose_hub(
        monitor.hub(), detail={"push_floor": detail.get("push_floor"),
                               "world_trace": detail["world_trace"]})
    monitor.hub().disable()
    checks["telemetry_embedded"] = (
        isinstance(detail["telemetry"], dict)
        and bool(detail["telemetry"].get("counters")))
    checks["doctor_embedded"] = (
        doctor_lib.validate_report(detail["doctor"]) == []
        and isinstance(detail["doctor"].get("verdict"), str)
        and any(r["rule"] == "boundary-wall"
                for r in detail["doctor"]["rules"])
        # the dryrun's push_floor must have reached the rule: its status
        # is fired/quiet/no-data depending on closure, but an evaluated
        # entry must exist
        and any(r["rule"] == "push-floor"
                for r in detail["doctor"]["rules"]))
    checks["trace_embedded"] = (
        detail["world_trace"].get("spans", 0) >= 1
        and any(e.get("kind") == "publish"
                for e in detail["world_trace"].get("flow_edges", []))
        and isinstance(detail["world_trace"].get("clock_offsets_s"),
                       dict)
        # the span-level data must have reached the doctor's cross-rank
        # rule (any status but an evaluated entry — like push-floor)
        and any(r["rule"] == "cross-rank-flow"
                for r in detail["doctor"]["rules"]))
    metrics = collect_gate_metrics(eps, detail)
    kind = detail.get("device_kind", "")
    committed = load_bench_best()
    g0 = apply_regression_gate(metrics, committed, kind)
    checks["gate_skips_foreign_hardware"] = (committed is None
                                            or bool(g0.get("skipped")))
    synth = {"device_kind": None,
             "metrics": {"headline_eps": eps * 2.0}}
    g1 = apply_regression_gate(metrics, synth, kind)
    checks["gate_trips_on_regression"] = not g1["ok"]
    g2 = apply_regression_gate(
        metrics, dict(synth, waivers={"headline_eps":
                                      "synthetic dryrun waiver"}), kind)
    checks["waiver_untrips"] = g2["ok"]
    g3 = apply_regression_gate(
        metrics, {"device_kind": None,
                  "metrics": {"headline_eps": eps}}, kind)
    checks["gate_ok_at_parity"] = g3["ok"]
    # the pblint gate must not be able to rot silently: the linter module
    # imports and carries its full rule set (the tier-1 lint-clean test
    # runs the CLI itself; this catches an import-time breakage even if
    # that test is ever skipped/filtered)
    try:
        from paddlebox_tpu.analysis import lint as lint_mod
        from paddlebox_tpu.analysis.rules import ALL_RULES
        checks["lint_importable"] = (callable(lint_mod.main)
                                     and len(ALL_RULES) >= 6)
    except Exception:
        checks["lint_importable"] = False
    ok = all(checks.values())
    print(json.dumps({
        "metric": "bench_dryrun", "ok": ok, "checks": checks,
        "value": round(eps, 1),
        "pack_engine": detail.get("pack_engine"),
        "push_engine": detail.get("push_engine"),
        "push_overlap": detail.get("push_overlap"),
        "push_floor_closed": (detail.get("push_floor") or {}
                              ).get("closed"),
        "doctor": detail["doctor"].get("verdict"),
        "world_resize_seconds": detail.get("world_resize_seconds"),
        "sharded": {k: f32p.get(k) for k in
                    ("table_layout", "exchange_wire", "table_shards",
                     "dedup_ratio", "error") if k in f32p},
        "serving": {k: sdrill.get(k) for k in
                    ("publish_seconds", "swap_pause_ms", "p99_ms",
                     "error") if k in sdrill},
        "serving_split": {k: ssd.get(k) for k in
                          ("shadow_p99_ms", "stable_auc",
                           "candidate_auc", "score_kl", "requests",
                           "doctor_rules", "error") if k in ssd},
        "serving_fleet": {k: fsd.get(k) for k in
                          ("p99_ms", "swap_convergence_s", "hedges",
                           "hedges_won", "promote_decision",
                           "doctor_rules", "error") if k in fsd},
        "spill": {k: spd.get(k) for k in
                  ("hot_hit_rate", "direct_hot_hit_rate",
                   "fetch_keys_per_s", "error") if k in spd},
        "spill_assoc": {k: sad.get(k) for k in
                        ("assoc", "assoc_hit_rate", "direct_hit_rate",
                         "conflict_misses_assoc",
                         "conflict_misses_direct", "parity", "error")
                        if k in sad},
        "boundary": {k: bdrill.get(k) for k in
                     ("boundary_seconds", "full_rebuild_seconds",
                      "speedup", "parity", "error") if k in bdrill},
        "self_healing": {k: heal.get(k) for k in
                         ("applied", "doctor_fail_on_warn",
                          "grow_gen", "round_trip_seconds", "error")
                         if k in heal},
        "overlap_ab": attr.get("overlap_ab"),
        "stages": attr.get("stages"),
        "gate_example_lines": g1.get("lines"),
    }), flush=True)
    return 0 if ok else 2


def main() -> None:
    import jax

    if "--dryrun" in sys.argv:
        raise SystemExit(dryrun_main())

    if "--sharded-probe" in sys.argv:
        raise SystemExit(sharded_probe_main())

    if "--host" in sys.argv:
        # host-section subprocess entry (see _enrich): CPU backend,
        # prints ONE JSON line with the host timings. config.update
        # beats the sitecustomize that force-registers the TPU plugin
        # and overwrites JAX_PLATFORMS (same dance as tests/conftest.py)
        # — without it this section would silently time the tunnel.
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(host_bench("--small" in sys.argv)), flush=True)
        return

    small = os.environ.get("PBTPU_BENCH_SMALL") == "1"  # CPU smoke mode
    if small:
        jax.config.update("jax_platforms", "cpu")

    # Headline windows: one retry (aimed at transient tunnel errors, but
    # fired on any Exception — the two are indistinguishable up front; a
    # deterministic bug just fails identically twice). If both attempts
    # die there is no honest number to report and the run fails.
    # The failed attempt's exception is dropped BEFORE retrying — its
    # traceback pins the dead run's device buffers (table + staged
    # batches), and holding them across the retry would double HBM
    # exactly when the chip is already unhappy.
    for attempt in (0, 1):
        try:
            eps_chip, detail, ctx = device_step_bench(
                small, return_ctx=True)
            break
        except Exception as e:
            if attempt:
                raise
            _mark(f"headline bench failed ({e!r}); retrying once")
            del e
    # From here on, NOTHING may prevent the one JSON line from printing
    # (VERDICT r3 weak #2: the artifact was hostage to its most fragile
    # stage). Attribution/matrix/e2e enrich `detail` in place; any
    # escape — including KeyboardInterrupt mid-attribution — is recorded
    # in detail and the line still prints. Non-Exception escapes (Ctrl-C,
    # SystemExit) re-raise after the print so the recorded rc still says
    # the run was interrupted.
    pending = None
    try:
        _enrich(small, detail, ctx, eps_chip)
    except BaseException as e:
        detail["bench_error"] = repr(e)
        if not isinstance(e, Exception):
            pending = e

    # telemetry summary rides every artifact (counters accumulated across
    # the run + flight records from the e2e section's real passes) — the
    # hub may be disabled; the cumulative registry still tells the story
    try:
        from paddlebox_tpu import monitor as _monitor
        detail["telemetry"] = _monitor.hub().summary()
    except Exception as e:
        detail["telemetry"] = {"error": repr(e)}

    # the merged world-trace summary rides every artifact (ISSUE 15):
    # the hub's in-memory flight records render as per-rank pass slices
    # (flow points live only in the JSONL streams — the offline
    # `python -m paddlebox_tpu.monitor.trace` merge reads those)
    try:
        from paddlebox_tpu.monitor import trace as _trace
        detail["world_trace"] = _trace.summarize(_trace.merge_streams(
            [_trace.records_to_stream(_monitor.hub().flight_records())],
            [0]))
    except Exception as e:
        detail["world_trace"] = {"error": repr(e)}

    # the run-doctor verdict rides every artifact (ISSUE 12): critical-
    # path attribution over the e2e passes' flight records + the rule
    # set, with this round's push_floor closing the push-floor rule
    try:
        from paddlebox_tpu.monitor import doctor as _doctor
        detail["doctor"] = _doctor.diagnose_hub(
            _monitor.hub(),
            detail={"push_floor": detail.get("push_floor"),
                    "world_trace": detail.get("world_trace")})
    except Exception as e:
        detail["doctor"] = {"error": repr(e)}

    # round-over-round regression gate: every recorded number vs the best
    # recorded value for this hardware (BENCH_BEST.json); an unwaived
    # >10% regression fails audit_ok — the alarm round 5 did not have.
    # Guarded like _enrich: a hand-edited BENCH_BEST.json with a zero /
    # quoted / malformed value must not hold the artifact hostage
    # (the one JSON line below prints NO MATTER WHAT).
    try:
        gate = apply_regression_gate(
            collect_gate_metrics(eps_chip, detail), load_bench_best(),
            detail.get("device_kind", ""))
    except Exception as e:
        gate = {"ok": False, "regressed": [],
                "error": f"gate failed on BENCH_BEST.json: {e!r}",
                "lines": {}}
    detail["regression_gate"] = gate
    detail["audit"]["ok"] = detail["audit"]["ok"] and gate["ok"]

    print(json.dumps({
        "metric": "deepfm_device_step_examples_per_sec_per_chip",
        "value": round(eps_chip, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(eps_chip / TARGET_PER_CHIP, 4),
        "detail": detail,
    }), flush=True)
    # compact self-contained summary, printed LAST: the driver records a
    # bounded TAIL of stdout, and BENCH_r04 lost its headline to exactly
    # that truncation (VERDICT r4 missing #4) — this line alone must
    # carry the verdict-grade numbers (<= ~500 chars)
    short = {"kstep_f32": "kstep", "async_f32": "async",
             "allreduce_int16": "i16", "allreduce_int8": "i8",
             "allreduce_f32_b16384": "b16k",
             "allreduce_f32_push_exact": "px3",
             "allreduce_f32_push_bf16": "px1",
             "allreduce_f32_dim64": "d64",
             "allreduce_f32_dim128": "d128",
             "allreduce_f32_multihot4_dim32": "mh4d32"}
    mshort = {short.get(k, k): int(v["examples_per_sec_per_chip"])
              for k, v in detail.get("matrix", {}).items()
              if isinstance(v, dict)
              and "examples_per_sec_per_chip" in v}
    # compact gate tail: one token per regressed metric (ok runs print
    # "ok"); the tail line alone must carry the verdict
    if gate.get("error"):
        gate_short = f"error({gate['error'][:80]})"
    elif gate.get("skipped"):
        gate_short = f"skipped({gate['skipped'][:60]})"
    elif gate["ok"]:
        gate_short = "ok"
    else:
        gate_short = "REGRESS:" + ",".join(
            f"{n}({gate['lines'][n].split('(')[1].rstrip(')')})"
            for n in gate.get("regressed", []))
    summary = {
        "metric": "deepfm_device_step_examples_per_sec_per_chip",
        "value": round(eps_chip, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(eps_chip / TARGET_PER_CHIP, 4),
        "step_ms": round(detail["audit"]["step_seconds"] * 1e3, 2),
        "audit_ok": detail["audit"]["ok"],
        "gate": gate_short,
        "push_engine": detail.get("push_engine"),
        "pull_engine": detail.get("pull_engine"),
        "pack_engine": detail.get("pack_engine"),
        "push_overlap": detail.get("push_overlap"),
        "matrix_eps": mshort,
        "e2e_eps": (detail.get("e2e", {}).get(
            "examples_per_sec_per_chip")
            if isinstance(detail.get("e2e"), dict) else None),
        "serving": ({k: detail["matrix"]["serving"].get(k) for k in
                     ("publish_seconds", "swap_pause_ms", "p99_ms",
                      "error")
                     if k in detail["matrix"]["serving"]}
                    if isinstance(detail.get("matrix", {}).get("serving"),
                                  dict) else None),
        "spill": ({k: detail["matrix"]["spill_10x"].get(k) for k in
                   ("hot_hit_rate", "direct_hot_hit_rate",
                    "fetch_keys_per_s", "ws_over_cache", "error")
                   if k in detail["matrix"]["spill_10x"]}
                  if isinstance(detail.get("matrix", {}).get("spill_10x"),
                                dict) else None),
        "host_feed_cap_eps": (detail.get("host", {}).get(
            "derived_max_feed_eps_per_chip")
            if isinstance(detail.get("host"), dict) else None),
        "bench_error": detail.get("bench_error"),
    }
    print(json.dumps(summary), flush=True)
    if pending is not None:
        raise pending
    if not gate["ok"]:
        print("REGRESSION GATE FAIL: " + (gate.get("error") or "; ".join(
            f"{n} {gate['lines'][n]}" for n in gate.get("regressed", []))),
            file=sys.stderr)
        raise SystemExit(2)
    if not detail["audit"]["ok"]:
        print("AUDIT FAIL: implied MFU/HBM exceeds hardware peaks — the "
              "measurement window is broken; do not trust the number",
              file=sys.stderr)
        raise SystemExit(2)


def _enrich(small: bool, detail: dict, ctx: dict,
            eps_chip: float | None = None) -> None:
    """Attribution + matrix + e2e datapoints, mutating `detail` in place
    so partial progress survives any failure (main prints whatever
    landed)."""
    from paddlebox_tpu.utils.step_probe import finalize_push_floor
    if ctx["mode"] == "allreduce" and ctx["n_dev"] == 1 \
            and os.environ.get("PBTPU_BENCH_ATTR", "1") != "0":
        detail["stage_attribution"] = _attribute_with_retry(
            ctx["tr"], ctx["ws"], ctx["staged0"], ctx["step_seconds"],
            small)
        if "push_floor" in detail:
            finalize_push_floor(
                detail["push_floor"],
                detail["stage_attribution"].get("stages", {})
                .get("sparse_push"))
    # release the headline run's device buffers before the matrix
    # re-allocates its own table + staged batches
    ctx.clear()
    if os.environ.get("PBTPU_BENCH_MATRIX", "1") != "0":
        # one device-step datapoint per dense-sync mode and per storage
        # mode (VERDICT r3 item #6): regressions in the non-headline
        # configs become visible round over round
        # stage-attributed points (the envelope's slowest — the audit
        # must name the stage behind each gap, VERDICT r4 weak #1; the
        # dim128 and multihot4 points are where the fused gather-pool
        # pull engages, so their splits name the fused stages);
        # override with PBTPU_BENCH_MATRIX_ATTR="name1,name2" or "" off
        attr_points = set(filter(None, os.environ.get(
            "PBTPU_BENCH_MATRIX_ATTR",
            "allreduce_f32_dim64,allreduce_f32_dim128,"
            "allreduce_f32_multihot4_dim32").split(",")))
        matrix = {}
        for mname, kw in (
                ("kstep_f32", dict(mode="kstep", storage="f32")),
                ("async_f32", dict(mode="async", storage="f32")),
                ("allreduce_int16", dict(storage="int16")),
                ("allreduce_int8", dict(storage="int8")),
                # batch scaling: the ~1.3ms/step dispatch floor amortizes
                ("allreduce_f32_b16384",
                 dict(storage="f32",
                      batch_per_dev=512 if small else 16384)),
                # push-precision endpoints around the 2-plane default:
                # 3-plane f32-exact and 1-plane bf16 (the reference's
                # quantized-push capacity/precision trade)
                ("allreduce_f32_push_exact",
                 dict(storage="f32", n_split=3)),
                ("allreduce_f32_push_bf16",
                 dict(storage="f32", n_split=1)),
                # wide-row envelope (VERDICT r3 missing #1): the binned
                # push must hold up where the reference dispatches big
                # embedx (box_wrapper.cc:444-461), not just at dim 8/16
                ("allreduce_f32_dim64",
                 dict(storage="f32", emb_dim=64)),
                ("allreduce_f32_dim128",
                 dict(storage="f32", emb_dim=128)),
                # DLRM-style multi-hot: variable lengths + pad masking
                # through seqpool and the wide-row push (BASELINE.md)
                ("allreduce_f32_multihot4_dim32",
                 dict(storage="f32", emb_dim=32, max_len=4))):
            try:
                want_attr = mname in attr_points
                res = device_step_bench(
                    small, n_steps=3 if small else 50, n_windows=2,
                    return_ctx=want_attr, **kw)
                m_eps, m_detail = res[0], res[1]
                m_audit = m_detail["audit"]
                matrix[mname] = {
                    "examples_per_sec_per_chip": round(m_eps, 1),
                    "step_seconds": m_audit["step_seconds"],
                    "push_engine": m_detail["push_engine"],
                    "pull_engine": m_detail["pull_engine"],
                    "pack_engine": m_detail["pack_engine"],
                    "push_overlap": m_detail["push_overlap"],
                    "table_layout": m_detail["table_layout"],
                    "exchange_wire": m_detail["exchange_wire"],
                    "table_shards": m_detail["table_shards"],
                    "push_floor": m_detail.get("push_floor"),
                    # per-point self-audit (VERDICT r4 weak #1): the
                    # headline's founding rule — a number without a
                    # FLOPs/bytes audit is not trusted — applied to
                    # every envelope point, slowest ones included
                    "audit": {
                        k: m_audit[k] for k in
                        ("flops_per_step", "hbm_bytes_per_step",
                         "implied_mfu", "implied_hbm_frac", "ok")
                        if k in m_audit},
                }
                if want_attr:
                    m_ctx = res[2]
                    # device-time stage split for the envelope's slow
                    # points: the dim64/multihot gaps need a named
                    # stage, not just a slower total
                    matrix[mname]["stage_attribution"] = \
                        _attribute_with_retry(
                            m_ctx["tr"], m_ctx["ws"], m_ctx["staged0"],
                            m_ctx["step_seconds"], small)
                    if matrix[mname].get("push_floor"):
                        finalize_push_floor(
                            matrix[mname]["push_floor"],
                            matrix[mname]["stage_attribution"]
                            .get("stages", {}).get("sparse_push"))
                    m_ctx.clear()
                if kw.get("mode") == "async":
                    # BoxPSAsynDenseTable pulls+pushes the full flat
                    # dense vector through the HOST each step; on this
                    # environment that traffic rides the ~10-30MB/s
                    # axon tunnel (~100-200ms/step), not a PCIe/DMA
                    # path — the number measures the tunnel, the mode's
                    # host machinery is exercised and correct
                    matrix[mname]["note"] = (
                        "per-step host dense pull/push rides the "
                        "tunnel; PCIe-class hosts are ~100x faster "
                        "on this path")
            except Exception as e:   # a matrix point must not kill the run
                matrix[mname] = {"error": repr(e)}
            _mark(f"matrix point {mname} done")
        if os.environ.get("PBTPU_BENCH_SHARDED", "1") != "0":
            # sharded-exchange points (ISSUE 10): the mesh-partitioned
            # table with the dedup-plan-keyed a2a, one point per push
            # wire format — gate-held like every other matrix point,
            # with table_layout/exchange_wire/table_shards recorded. On
            # a single-device environment the points run in a 2-virtual-
            # device CPU subprocess (marked simulated: like-for-like
            # round over round, since the probe environment is stable).
            if detail.get("devices", 1) >= 2:
                from paddlebox_tpu.config import flags as config_flags
                try:
                    for mname, w in (("sharded_wire_f32", "f32"),
                                     ("sharded_wire_bf16", "bf16"),
                                     ("sharded_wire_int8", "int8")):
                        try:
                            s_eps, s_detail = device_step_bench(
                                small, n_steps=3 if small else 50,
                                n_windows=2, table_layout="sharded",
                                exchange_wire=w)
                            matrix[mname] = {
                                "examples_per_sec_per_chip":
                                    round(s_eps, 1),
                                "step_seconds":
                                    s_detail["audit"]["step_seconds"],
                                "table_layout": s_detail["table_layout"],
                                "exchange_wire":
                                    s_detail["exchange_wire"],
                                "table_shards": s_detail["table_shards"],
                                "pull_engine": s_detail["pull_engine"],
                                "push_engine": s_detail["push_engine"],
                            }
                        except Exception as e:
                            matrix[mname] = {"error": repr(e)}
                        _mark(f"matrix point {mname} done")
                finally:
                    # the forced engine must not leak into the elastic /
                    # serving drills below — they build 1-device
                    # trainers, and a leaked 'sharded' would error both
                    # gate-held points
                    config_flags.table_layout = \
                        _startup_flag("table_layout")
                    config_flags.exchange_wire = \
                        _startup_flag("exchange_wire")
                # drifting-sparsity adaptive point: the controller picks
                # the wire per pass and must beat every fixed point
                # above on its modeled cost (the drill saves/restores
                # its own flags)
                try:
                    matrix["adaptive_wire"] = adaptive_wire_drill(small)
                except Exception as e:
                    matrix["adaptive_wire"] = {"error": repr(e)}
                _mark("matrix point adaptive_wire done")
            else:
                probe = _run_sharded_probe(small)
                for mname, p in (probe.get("points") or {}).items():
                    matrix[mname] = p
                if "error" in probe:
                    matrix["sharded_wire_f32"] = {"error": probe["error"]}
                _mark("matrix sharded probe done")
        if os.environ.get("PBTPU_BENCH_SPILL", "1") != "0":
            # tiered-table drill: the sharded+spill path under a working
            # set >= 10x the RAM cache budget, admission policy vs the
            # direct-mapped baseline — gate-held like every other point
            try:
                matrix["spill_10x"] = spill_drill(small)
            except Exception as e:
                matrix["spill_10x"] = {"error": repr(e)}
            _mark("matrix point spill_10x done")
            # set-associative geometry drill: N-way vs direct-mapped on
            # the adversarial colliding stream, bit-parity held — the
            # assoc_hit_rate/fetch points are gate-held like the rest
            try:
                matrix["spill_assoc"] = spill_assoc_drill(small)
            except Exception as e:
                matrix["spill_assoc"] = {"error": repr(e)}
            _mark("matrix point spill_assoc done")
            # pass-boundary drill: incremental + overlapped feeds vs the
            # full-rebuild baseline on one key stream — gate-held
            # (boundary_seconds is lower-is-better off the suffix)
            try:
                matrix["boundary_incremental"] = boundary_drill(small)
            except Exception as e:
                matrix["boundary_incremental"] = {"error": repr(e)}
            _mark("matrix point boundary_incremental done")
        if os.environ.get("PBTPU_BENCH_ELASTIC", "1") != "0":
            # elastic rank-loss drill: world_resize_seconds + the
            # degraded (N−1) throughput point, gate-held like the rest
            try:
                matrix["elastic_degraded"] = elastic_drill(small)
                detail["world_resize_seconds"] = \
                    matrix["elastic_degraded"]["world_resize_seconds"]
            except Exception as e:
                matrix["elastic_degraded"] = {"error": repr(e)}
            _mark("matrix point elastic_degraded done")
        if os.environ.get("PBTPU_BENCH_SERVING", "1") != "0":
            # train→publish→serve drill: publish_seconds, swap_pause_ms
            # and served p50/p99 — gate-held like every other point
            # (latency metrics compare lower-is-better)
            try:
                matrix["serving"] = serving_drill(small)
            except Exception as e:
                matrix["serving"] = {"error": repr(e)}
            _mark("matrix point serving done")
            # version-split drill: shadow-mode two-version scoring —
            # shadow_p99_ms is gate-held (lower-is-better), the AUC /
            # score-KL attribution and doctor verdicts ride the artifact
            try:
                matrix["serving_split"] = serving_split_drill(small)
            except Exception as e:
                matrix["serving_split"] = {"error": repr(e)}
            _mark("matrix point serving_split done")
            try:
                matrix["serving_fleet"] = serving_fleet_drill(small)
            except Exception as e:
                matrix["serving_fleet"] = {"error": repr(e)}
            _mark("matrix point serving_fleet done")
        detail["matrix"] = matrix
    if os.environ.get("PBTPU_BENCH_HOST", "1") != "0":
        # tunnel-immune host section, in a CPU subprocess: the parent
        # process already initialized the TPU backend, and the host
        # numbers must not share a process (or the tunnel) with it
        try:
            import subprocess
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env.pop("PBTPU_BENCH_SMALL", None)
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--host"]
                + (["--small"] if small else []),
                capture_output=True, text=True, env=env, timeout=1800)
            if r.returncode == 0:
                detail["host"] = json.loads(r.stdout.strip().
                                            splitlines()[-1])
                cap = detail["host"].get("derived_max_feed_eps_per_chip")
                if eps_chip and isinstance(cap, (int, float)):
                    # the margin cites THIS run's measured headline, not
                    # a hardcoded constant (reconciled: the r5 artifact
                    # said "~1.2M" while recording 645k)
                    detail["host"]["feed_margin_vs_headline"] = round(
                        cap / eps_chip, 2)
            else:
                detail["host"] = {"error": r.stderr[-500:]}
        except Exception as e:
            detail["host"] = {"error": repr(e)}
        _mark("host section done")
    if os.environ.get("PBTPU_BENCH_E2E", "1") != "0":
        try:
            e2e_eps, e2e_detail = e2e_bench(small)
            detail["e2e"] = e2e_detail
            detail["e2e"]["examples_per_sec_per_chip"] = round(e2e_eps, 1)
            detail["e2e"]["vs_baseline"] = round(e2e_eps / TARGET_PER_CHIP,
                                                 4)
        except Exception as e:  # e2e failure must not hide the step number
            detail["e2e"] = {"error": repr(e)}


if __name__ == "__main__":
    main()
