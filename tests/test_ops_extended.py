"""Extended sparse pull, seqpool conv variant, per-slot thresholds,
replica cache, input table, summary sync, AUC runner."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                     InputTable, ReplicaCache,
                                     pull_cache_value)
from paddlebox_tpu.ops import (fused_seqpool_cvm, fused_seqpool_cvm_with_conv,
                               pull_box_extended_sparse, summary_update,
                               init_summary, data_norm)
from paddlebox_tpu.parallel import make_mesh


def test_expand_dim_geometry_and_split():
    cfg = EmbeddingConfig(dim=8, expand_dim=4)
    assert cfg.pull_width == 3 + 12
    assert cfg.grad_width == 1 + 12
    pulled = jnp.arange(2 * 3 * cfg.pull_width, dtype=jnp.float32).reshape(
        2, 3, cfg.pull_width)
    base, expand = pull_box_extended_sparse(pulled, cfg)
    assert base.shape == (2, 3, 11)
    assert expand.shape == (2, 3, 4)
    np.testing.assert_array_equal(np.asarray(pulled[..., 11:]),
                                  np.asarray(expand))


def test_expand_dim_store_roundtrip():
    cfg = EmbeddingConfig(dim=4, expand_dim=2)
    store = HostEmbeddingStore(cfg)
    keys = np.array([11, 22], dtype=np.uint64)
    rows = store.lookup_or_init(keys)
    assert rows.shape == (2, cfg.row_width)
    # expand columns are initialized like embedx (nonzero)
    assert np.abs(rows[:, 3 + cfg.dim:3 + cfg.total_dim]).sum() > 0


def test_extended_requires_expand():
    with pytest.raises(ValueError):
        pull_box_extended_sparse(jnp.zeros((1, 1, 11)), EmbeddingConfig(dim=8))


def test_trainer_rejects_mismatched_expand_dim():
    """expand_dim>0 with a model sized only for dim must fail loudly at
    Trainer init, not with a shape error deep inside jit."""
    from paddlebox_tpu.data import DataFeedSchema
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.train import Trainer, TrainerConfig
    schema = DataFeedSchema.ctr(num_sparse=2, num_float=1, batch_size=8,
                                max_len=1)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, expand_dim=4))
    model = DNNCTRModel(num_slots=2, emb_dim=8, dense_dim=0, hidden=(8,))
    with pytest.raises(ValueError, match="expand_dim"):
        Trainer(model, store, schema, make_mesh(8),
                TrainerConfig(global_batch_size=8))


def test_conv_variant_filters_at_conv_offsets():
    """embed_threshold must read w at column 3 (conv layout), not show."""
    # token: show=100, clk=5, conv=1, w=1e-6, emb=7 → must be filtered
    pulled = jnp.asarray(np.array(
        [[[100.0, 5.0, 1.0, 1e-6, 7.0]]], dtype=np.float32))
    mask = jnp.ones((1, 1), bool)
    seg = np.zeros(1, np.int64)
    out = fused_seqpool_cvm_with_conv(pulled, mask, seg, 1, use_cvm=False,
                                      flatten=False, embed_threshold=0.5)
    np.testing.assert_allclose(np.asarray(out[0, 0]), [0.0, 0.0])
    # quant_ratio quantizes embedx only, counters/w untouched
    out2 = fused_seqpool_cvm_with_conv(pulled, mask, seg, 1, use_cvm=False,
                                       flatten=False, quant_ratio=2)
    np.testing.assert_allclose(np.asarray(out2[0, 0]), [1e-6, 7.0])


def test_seqpool_cvm_with_conv():
    # P = [show, clk, conv, w]: one slot, 2 tokens
    pulled = jnp.asarray(np.array([
        [[3.0, 2.0, 1.0, 0.5], [1.0, 0.0, 0.0, 0.25]],
    ], dtype=np.float32))
    mask = jnp.ones((1, 2), bool)
    seg = np.zeros(2, np.int64)
    out = fused_seqpool_cvm_with_conv(pulled, mask, seg, 1, use_cvm=True,
                                      flatten=False)
    show, clk, conv = 4.0, 2.0, 1.0
    np.testing.assert_allclose(np.asarray(out[0, 0]), [
        np.log(show + 1), np.log(clk + 1) - np.log(show + 1),
        np.log(conv + 1) - np.log(clk + 1), 0.75], rtol=1e-6)
    # update phase drops the three counters
    out2 = fused_seqpool_cvm_with_conv(pulled, mask, seg, 1, use_cvm=False,
                                       flatten=False)
    assert out2.shape == (1, 1, 1)


def test_seqpool_per_slot_threshold():
    # two slots; slot 0 threshold low (keeps), slot 1 high (filters)
    pulled = jnp.asarray(np.array([
        [[5.0, 5.0, 1.0, 2.0], [5.0, 5.0, 1.0, 4.0]],
    ], dtype=np.float32))
    mask = jnp.ones((1, 2), bool)
    seg = np.array([0, 1], np.int64)
    out = fused_seqpool_cvm(pulled, mask, seg, 2, use_cvm=False,
                            need_filter=True, show_coeff=0.2, clk_coeff=1.0,
                            threshold=np.array([1.0, 100.0], np.float32),
                            flatten=False)
    # slot 0 kept: w=1, emb=2; slot 1 filtered: zeros
    np.testing.assert_allclose(np.asarray(out[0, 0]), [1.0, 2.0])
    np.testing.assert_allclose(np.asarray(out[0, 1]), [0.0, 0.0])


def test_replica_cache_and_input_table():
    mesh = make_mesh(8)
    cache = ReplicaCache(dim=3)
    keys = np.array([100, 200], dtype=np.uint64)
    cache.add(keys, np.array([[1, 2, 3], [4, 5, 6]], np.float32))
    table = cache.to_hbm(mesh)
    idx = cache.translate(np.array([[200, 100], [999, 100]], dtype=np.uint64))
    out = np.asarray(pull_cache_value(table, jnp.asarray(idx)))
    np.testing.assert_allclose(out[0, 0], [4, 5, 6])
    np.testing.assert_allclose(out[0, 1], [1, 2, 3])
    np.testing.assert_allclose(out[1, 0], [0, 0, 0])  # miss → null row

    it = InputTable()
    a = it.lookup(["cat", "dog", "cat"])
    assert a[0] == a[2] != a[1]
    b = it.lookup(["bird"], insert=False)
    assert b[0] == 0  # miss without insert


def test_summary_update_psum():
    mesh = make_mesh(8)
    from jax.sharding import PartitionSpec as P

    summary = init_summary(2)
    x = np.random.default_rng(0).normal(size=(16, 2)).astype(np.float32)

    def body(s, xl):
        return summary_update(s, xl, axis_name="dp")

    out = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=(P(), P("dp")),
                                out_specs=P()))(summary, jnp.asarray(x))
    # psum'd batch contribution equals the full-batch single-host update
    ref = summary_update(summary, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)
    # and normalization with the synced summary is well-formed
    y = data_norm(jnp.asarray(x), out)
    assert np.isfinite(np.asarray(y)).all()


def test_auc_runner_ranks_informative_slot():
    from paddlebox_tpu.metrics import AucRunner
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.train import Trainer, TrainerConfig
    from tests.test_train_e2e import NUM_SLOTS, synth_dataset

    ds, schema = synth_dataset(1024, seed=11)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, learning_rate=0.15))
    mesh = make_mesh(8)
    model = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1,
                        hidden=(32,))
    tr = Trainer(model, store, schema, mesh,
                 TrainerConfig(global_batch_size=128, dense_lr=3e-3,
                               auc_buckets=1 << 12))
    for _ in range(3):
        tr.train_pass(ds)
    runner = AucRunner(tr, pool_size=5000, seed=0)
    res = runner.run(ds, slots=[schema.sparse_slots[0].name])
    s0 = schema.sparse_slots[0].name
    assert res["__baseline__"]["auc"] > 0.6
    # ablating an informative slot must cost AUC
    assert res[s0]["auc_drop"] > 0.01, res
