"""Self-healing runtime (ISSUE 18): the RemediationController loop.

Covers the tentpole contract end to end, in process:

- gating: ``flags.self_healing`` off is a hard no-op; a rule must fire
  ``flags.self_healing_sustain`` consecutive boundaries before its
  action applies; at most ONE action per pass (a settling action blocks
  new applies);
- the parity guard: an action whose rule promises bit-identity but whose
  apply changes the dense params is REVERTED and its rule quarantined
  for the rest of the run — and a bit-identity action with no
  fingerprintable params is skipped, never trusted;
- the honesty record: apply/revert land in the committed flight record
  (``extra["remediation"]``, schema-validated here with negatives) with
  before/after counter deltas bracketing the apply, plus registered
  ``remediation_applied``/``remediation_reverted`` events;
- the flow feed (ROADMAP exchange follow-up 3): the cross-rank-flow
  finding feeds ``Trainer.note_flow_attribution`` at every boundary and
  a quiet boundary CLEARS the veto;
- elastic grow: ``grow_evidence`` gates on the heartbeat-gap finding's
  ``degraded`` field, and ``poll_grow`` over real threaded ElasticWorlds
  admits a joiner registered via ``ElasticWorld.admit`` (the union
  all-gather) and queues the world-grow record for the next boundary;
- faultpoint multi-arm (the compound-failure harness the grow kill
  matrix runs on): comma/list arming, per-point counters and AFTER
  thresholds, selective disarm, env parsing.
"""

import threading

import numpy as np
import pytest

from paddlebox_tpu import monitor
from paddlebox_tpu.config import flags, set_flags
from paddlebox_tpu.distributed.resilience import ElasticWorld
from paddlebox_tpu.distributed.store import FileStore
from paddlebox_tpu.monitor import flight
from paddlebox_tpu.monitor.names import EVENT_NAMES
from paddlebox_tpu.monitor.registry import STATS
from paddlebox_tpu.runtime.remediation import (Action,
                                               RemediationController)
from paddlebox_tpu.utils import faultpoint


@pytest.fixture(autouse=True)
def _clean_hub():
    h = monitor.hub()
    h.disable()
    h.abort_pass(reason="test setup")
    faultpoint.disarm()
    yield
    h.abort_pass(reason="test teardown")
    h.disable()
    faultpoint.disarm()


@pytest.fixture
def healing():
    set_flags(self_healing=True, self_healing_sustain=1)
    yield
    set_flags(self_healing=False, self_healing_sustain=2)


def _finding(rule, severity="warn", evidence=None):
    return {"rule": rule, "severity": severity, "summary": rule,
            "evidence": dict(evidence or {}), "suggestion": "fix it"}


class _StubTrainer:
    """The minimum surface the controller touches: fingerprintable dense
    params (the parity witness) and the flow-attribution note."""

    def __init__(self):
        self.params = np.arange(8, dtype=np.float32)
        self.flow_notes = []

    def eval_params(self):
        return {"w": self.params}

    def note_flow_attribution(self, fa, wall=None):
        self.flow_notes.append((fa, wall))


def _noop_action(rule="test-rule", bit_identity=True, watch=(),
                 mutate=None, fail=False, log=None):
    log = log if log is not None else []

    def _apply():
        log.append("apply")
        if fail:
            raise RuntimeError("boom")
        if mutate is not None:
            mutate()

    def _revert():
        log.append("revert")

    return Action(rule, "test-action", bit_identity=bit_identity,
                  apply=_apply, revert=_revert, watch=watch,
                  detail={"flag": "none"}), log


# ---------------------------------------------------------------------------
# gating
# ---------------------------------------------------------------------------


def test_self_healing_off_is_a_noop():
    tr = _StubTrainer()
    act, log = _noop_action()
    ctl = RemediationController(trainer=tr,
                                actions={"test-rule": lambda t, f: act})
    assert ctl.boundary([_finding("test-rule")]) is None
    assert log == []


def test_sustain_threshold_blocks_the_first_firing(healing):
    set_flags(self_healing_sustain=2)
    tr = _StubTrainer()
    act, log = _noop_action()
    ctl = RemediationController(trainer=tr,
                                actions={"test-rule": lambda t, f: act})
    h = monitor.hub()
    h.begin_pass(1)
    try:
        assert ctl.boundary([_finding("test-rule")]) is None   # streak 1
        rec = ctl.boundary([_finding("test-rule")])            # streak 2
        assert rec is not None and rec["status"] == "applied"
        assert log == ["apply"]
    finally:
        h.abort_pass()


def test_streak_resets_on_a_quiet_boundary(healing):
    set_flags(self_healing_sustain=2)
    tr = _StubTrainer()
    act, log = _noop_action()
    ctl = RemediationController(trainer=tr,
                                actions={"test-rule": lambda t, f: act})
    h = monitor.hub()
    h.begin_pass(1)
    try:
        assert ctl.boundary([_finding("test-rule")]) is None
        assert ctl.boundary([]) is None                        # quiet: reset
        assert ctl.boundary([_finding("test-rule")]) is None   # streak 1 again
        assert log == []
    finally:
        h.abort_pass()


# ---------------------------------------------------------------------------
# parity guard
# ---------------------------------------------------------------------------


def test_parity_guard_reverts_and_quarantines(healing):
    tr = _StubTrainer()
    act, log = _noop_action(mutate=lambda: tr.params.__setitem__(0, 99.0))
    ctl = RemediationController(trainer=tr,
                                actions={"test-rule": lambda t, f: act})
    h = monitor.hub()
    ms = monitor.MemorySink()
    h.enable(ms)
    before = STATS.get("remediation.reverted")
    h.begin_pass(1)
    try:
        rec = ctl.boundary([_finding("test-rule")])
        assert rec["status"] == "reverted"
        assert rec["reason"] == "parity-guard"
        assert log == ["apply", "revert"]
        assert "test-rule" in ctl.quarantined
        assert STATS.get("remediation.reverted") == before + 1
        # quarantined for the rest of the run: the rule can never apply
        assert ctl.boundary([_finding("test-rule")]) is None
    finally:
        h.abort_pass()
        h.disable()
    ev = ms.find("remediation_reverted")
    assert ev and ev[0]["fields"]["reason"] == "parity-guard"


def test_apply_error_reverts_and_quarantines(healing):
    tr = _StubTrainer()
    act, log = _noop_action(fail=True)
    ctl = RemediationController(trainer=tr,
                                actions={"test-rule": lambda t, f: act})
    h = monitor.hub()
    h.begin_pass(1)
    try:
        rec = ctl.boundary([_finding("test-rule")])
        assert rec["status"] == "reverted"
        assert rec["reason"] == "apply-error"
        assert log == ["apply", "revert"]
        assert "test-rule" in ctl.quarantined
    finally:
        h.abort_pass()


def test_bit_identity_without_params_is_skipped_not_trusted(healing):
    act, log = _noop_action(bit_identity=True)
    ctl = RemediationController(trainer=None,
                                actions={"test-rule": lambda t, f: act})
    h = monitor.hub()
    h.begin_pass(1)
    try:
        assert ctl.boundary([_finding("test-rule")]) is None
        assert log == []                      # never applied blind
    finally:
        h.abort_pass()


# ---------------------------------------------------------------------------
# the honesty record: flight-record schema + before/after windows
# ---------------------------------------------------------------------------


def test_applied_record_rides_the_flight_record_with_after(healing):
    tr = _StubTrainer()
    act, log = _noop_action(watch=("healing.test_counter",))
    ctl = RemediationController(trainer=tr,
                                actions={"test-rule": lambda t, f: act})
    h = monitor.hub()
    ms = monitor.MemorySink()
    h.enable(ms)
    try:
        h.begin_pass(1)
        monitor.counter_add("healing.test_counter", 3)
        rec = ctl.boundary([_finding("test-rule")])
        assert rec["status"] == "applied" and "after" not in rec
        flight_1 = h.end_pass()
        assert flight.validate_flight_record(flight_1) == []
        assert flight_1["extra"]["remediation"]["status"] == "applied"

        h.begin_pass(2)
        monitor.counter_add("healing.test_counter", 5)
        # the settling boundary: the SAME action's after-window commits,
        # and a concurrently fired rule must NOT apply (one per pass)
        act2, log2 = _noop_action(rule="other-rule")
        ctl.actions["other-rule"] = lambda t, f: act2
        rec2 = ctl.boundary([_finding("other-rule")])
        assert rec2["status"] == "applied"
        assert rec2["after"] == {"healing.test_counter": 5.0}
        assert log2 == []                     # settling blocked it
        flight_2 = h.end_pass()
        assert flight.validate_flight_record(flight_2) == []
        assert flight_2["extra"]["remediation"]["after"] == \
            {"healing.test_counter": 5.0}
    finally:
        h.disable()
    ev = ms.find("remediation_applied")
    assert ev and ev[0]["fields"]["rule"] == "test-rule"


def test_remediation_schema_negatives():
    base = {"ts": 0.0, "type": "flight_record", "name": "pass",
            "pass_id": 1, "step": None, "phase": 1, "thread": "Main",
            "seconds": 1.0, "steps": 1, "examples": 8,
            "examples_per_sec": 8.0, "stage_seconds": {},
            "stats_delta": {}, "metrics": {}}

    def with_rem(rem):
        return dict(base, extra={"remediation": rem})

    good = {"rule": "boundary-wall", "action": "enable-incremental-feed",
            "status": "applied", "before": {"feed_pass.fresh_rows": 10.0},
            "after": {"feed_pass.fresh_rows": 0.0}}
    assert flight.validate_flight_record(with_rem(good)) == []
    reverted = dict(good, status="reverted", reason="parity-guard")
    assert flight.validate_flight_record(with_rem(reverted)) == []
    # negatives: the CI gate must reject a forged/torn record
    assert flight.validate_flight_record(
        with_rem(dict(good, status="maybe")))
    assert flight.validate_flight_record(
        with_rem(dict(good, before={"x": "NaN-ish"})))
    assert flight.validate_flight_record(
        with_rem(dict(good, rule=7)))
    assert flight.validate_flight_record(
        with_rem(dict(good, reason=1.5)))
    assert flight.validate_flight_record(with_rem("applied"))


def test_self_healing_events_are_registered():
    assert {"remediation_applied", "remediation_reverted",
            "world_grow"} <= set(EVENT_NAMES)


# ---------------------------------------------------------------------------
# the flow feed (ROADMAP exchange follow-up 3)
# ---------------------------------------------------------------------------


def test_cross_rank_flow_finding_feeds_the_wire_veto(healing):
    tr = _StubTrainer()
    ctl = RemediationController(trainer=tr, actions={})
    h = monitor.hub()
    h.begin_pass(1)
    try:
        ev = {"longest_edge": {"kind": "exchange", "latency_s": 0.4},
              "longest_share_of_wall": 0.5,
              "by_kind": {"exchange": 0.4}, "edges": 3,
              "negative_edges": 0}
        ctl.boundary([_finding("cross-rank-flow", evidence=ev)])
        fa, wall = tr.flow_notes[-1]
        assert fa["longest"]["kind"] == "exchange"
        assert wall == pytest.approx(0.8)     # latency_s / share
        # a quiet boundary clears the veto — stale flow evidence must
        # not pin a wire forever
        ctl.boundary([])
        assert tr.flow_notes[-1] == (None, None) or \
            tr.flow_notes[-1][0] is None
    finally:
        h.abort_pass()


def test_feed_report_findings_consumed_at_next_boundary(healing):
    tr = _StubTrainer()
    act, log = _noop_action()
    ctl = RemediationController(trainer=tr,
                                actions={"test-rule": lambda t, f: act})
    ctl.feed_report({"findings": [_finding("test-rule")]})
    h = monitor.hub()
    h.begin_pass(1)
    try:
        rec = ctl.boundary()                  # consumes the fed findings
        assert rec is not None and rec["status"] == "applied"
        assert log == ["apply"]
    finally:
        h.abort_pass()


def test_boundary_wall_builder_flips_incremental_feed(healing):
    """The flagship catalog entry: the boundary-wall finding's reuse_off
    arm flips flags.incremental_feed under the parity guard (the flag
    defaults on; the run being healed turned it off)."""
    set_flags(incremental_feed=False)
    tr = _StubTrainer()
    ctl = RemediationController(trainer=tr)
    h = monitor.hub()
    h.begin_pass(1)
    try:
        rec = ctl.boundary(
            [_finding("boundary-wall",
                      evidence={"share": 0.8, "reused_rows": 0})])
        assert rec["status"] == "applied"
        assert rec["action"] == "enable-incremental-feed"
        assert flags.incremental_feed
        # already on: the builder declines (no second apply ever)
        ctl2 = RemediationController(trainer=tr)
        assert ctl2.boundary(
            [_finding("boundary-wall", evidence={"share": 0.8})]) is None
    finally:
        set_flags(incremental_feed=True)
        h.abort_pass()


# ---------------------------------------------------------------------------
# elastic grow
# ---------------------------------------------------------------------------


def test_grow_evidence_gates_on_degraded():
    ctl = RemediationController()
    assert ctl.grow_evidence(
        [_finding("heartbeat-gap", evidence={"degraded": False})]) is None
    ev = ctl.grow_evidence(
        [_finding("heartbeat-gap",
                  evidence={"degraded": True, "world_size": 2})])
    assert ev and ev["world_size"] == 2
    assert ctl.grow_evidence([]) is None


def test_poll_grow_requires_evidence_and_flag(healing):
    ctl = RemediationController()
    assert ctl.poll_grow(None) == (None, None)

    class _W:
        gen = 0
        members = [0]

    w = _W()
    set_flags(self_healing=False)
    assert ctl.poll_grow(
        w, findings=[_finding("heartbeat-gap",
                              evidence={"degraded": True})]) == (w, None)
    set_flags(self_healing=True)
    # healthy world: no heartbeat-gap evidence -> unchanged, no gather
    assert ctl.poll_grow(w, findings=[]) == (w, None)
    ctl.quarantined.add("world-grow")
    assert ctl.poll_grow(
        w, findings=[_finding("heartbeat-gap",
                              evidence={"degraded": True})]) == (w, None)


def test_poll_grow_admits_joiner_over_threaded_world(tmp_path, healing):
    """The grow protocol end to end in threads: a degraded 2-member world
    (launched at 3) polls grow under heartbeat-gap evidence while a
    joiner thread runs ElasticWorld.admit — the union all-gather sees the
    registration, reform admits it, and the world-grow record is queued
    for the next boundary."""
    hbgap = _finding("heartbeat-gap",
                     evidence={"degraded": True, "world_size": 2})
    results, errs = {}, []
    h = monitor.hub()
    ms = monitor.MemorySink()
    h.enable(ms)

    def incumbent(r):
        try:
            w = ElasticWorld(FileStore(str(tmp_path), namespace="r",
                                       poll_s=0.01),
                             r, [0, 1], heartbeat_interval_s=0.05,
                             lost_after_s=30.0, stall_after_s=60.0,
                             reform_timeout_s=2.0, initial_world=3)
            ctl = RemediationController()
            deadline = 60
            nw, cursor = w, None
            for _ in range(deadline):
                nw, cursor = ctl.poll_grow(w, findings=[hbgap])
                if nw is not w:
                    break
            results[r] = (nw.gen, nw.members, ctl._notes)
            nw.collectives.barrier("post_grow")
            nw.close()
        except BaseException as e:   # pragma: no cover
            errs.append((r, e))

    def joiner():
        try:
            w = ElasticWorld.admit(
                FileStore(str(tmp_path), namespace="r", poll_s=0.01),
                2, timeout_s=30.0, heartbeat_interval_s=0.05,
                lost_after_s=30.0, stall_after_s=60.0,
                reform_timeout_s=2.0, initial_world=3)
            results["j"] = (w.gen, w.members)
            w.collectives.barrier("post_grow")
            w.close()
        except BaseException as e:   # pragma: no cover
            errs.append(("j", e))

    ts = ([threading.Thread(target=incumbent, args=(r,)) for r in (0, 1)]
          + [threading.Thread(target=joiner)])
    [t.start() for t in ts]
    [t.join(timeout=90) for t in ts]
    h.disable()
    assert not errs, errs
    assert results[0][:2] == (1, [0, 1, 2])
    assert results[1][:2] == (1, [0, 1, 2])
    assert results["j"] == (1, [0, 1, 2])
    # the queued world-grow record drains at the next boundary
    notes = results[0][2]
    assert notes and notes[0]["action"] == "world-grow"
    assert notes[0]["detail"]["joined"] == "2"
    assert notes[0]["detail"]["to_world"] == 3
    grow_events = ms.find("world_grow")
    assert grow_events and any(e["fields"]["joined"] == [2]
                               for e in grow_events)
    # consumed registration never re-triggers a grow
    store = FileStore(str(tmp_path), namespace="r", poll_s=0.01)
    assert store.keys("elastic.admit.") == []


# ---------------------------------------------------------------------------
# faultpoint multi-arm (the compound-failure harness)
# ---------------------------------------------------------------------------


def test_faultpoint_multi_arm_comma_and_list():
    faultpoint.arm("elastic.admit.pre_register,elastic.admit.post_ack",
                   action="ioerror")
    assert faultpoint.armed_points() == ("elastic.admit.post_ack",
                                         "elastic.admit.pre_register")
    with pytest.raises(faultpoint.FaultInjected):
        faultpoint.hit("elastic.admit.pre_register")
    with pytest.raises(faultpoint.FaultInjected):
        faultpoint.hit("elastic.admit.post_ack")
    # selective disarm leaves the other leg armed
    faultpoint.disarm("elastic.admit.pre_register")
    faultpoint.hit("elastic.admit.pre_register")      # now a no-op
    with pytest.raises(faultpoint.FaultInjected):
        faultpoint.hit("elastic.admit.post_ack")
    faultpoint.disarm()
    faultpoint.arm(["elastic.ownership.rebind.pre"], action="ioerror")
    with pytest.raises(faultpoint.FaultInjected):
        faultpoint.hit("elastic.ownership.rebind.pre")
    faultpoint.disarm()


def test_faultpoint_multi_arm_keeps_per_point_counters():
    faultpoint.arm(["elastic.admit.pre_register",
                    "elastic.ownership.rebind.pre"],
                   action="ioerror", after=1)
    faultpoint.hit("elastic.admit.pre_register")       # hit 1: below after
    with pytest.raises(faultpoint.FaultInjected):
        faultpoint.hit("elastic.admit.pre_register")   # hit 2: fires
    # the OTHER point's counter is untouched by the first point's hits
    faultpoint.hit("elastic.ownership.rebind.pre")
    with pytest.raises(faultpoint.FaultInjected):
        faultpoint.hit("elastic.ownership.rebind.pre")
    faultpoint.disarm()


def test_faultpoint_env_comma_parsing(monkeypatch):
    monkeypatch.setenv("PBTPU_FAULTPOINT",
                       "elastic.admit.pre_register,elastic.admit.post_ack")
    monkeypatch.setenv("PBTPU_FAULTPOINT_ACTION", "ioerror")
    monkeypatch.setenv("PBTPU_FAULTPOINT_AFTER", "0,2")
    faultpoint._arm_from_env()
    try:
        assert faultpoint.armed_points() == ("elastic.admit.post_ack",
                                             "elastic.admit.pre_register")
        with pytest.raises(faultpoint.FaultInjected):
            faultpoint.hit("elastic.admit.pre_register")   # after=0
        faultpoint.hit("elastic.admit.post_ack")           # after=2: 1st
        faultpoint.hit("elastic.admit.post_ack")           # 2nd
        with pytest.raises(faultpoint.FaultInjected):
            faultpoint.hit("elastic.admit.post_ack")       # 3rd fires
    finally:
        faultpoint.disarm()


def test_faultpoint_unknown_name_rejected_in_multi_arm():
    with pytest.raises(KeyError):
        faultpoint.arm("elastic.admit.pre_register,nope.not.registered")
    assert faultpoint.armed_points() == ()
