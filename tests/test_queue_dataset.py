"""QueueDataset: streaming batches with bounded memory; heter streaming."""

import numpy as np
import pytest

from paddlebox_tpu.data import DataFeedSchema, QueueDataset

from test_train_e2e import synth_dataset, NUM_SLOTS


def _write_files(tmp_path, n_files=4, lines_per=100, seed=0):
    ds, schema = synth_dataset(n_files * lines_per, seed=seed)
    # re-serialize the in-memory records back to MultiSlot text per file
    rng = np.random.default_rng(seed)
    paths = []
    r = ds.records
    per = r.num // n_files
    for f in range(n_files):
        lines = []
        for i in range(f * per, (f + 1) * per):
            parts = []
            for j, slot in enumerate(schema.float_slots):
                v = r.float_values[j][i * slot.max_len:(i + 1) * slot.max_len]
                parts.append(f"{slot.max_len} " +
                             " ".join(f"{x:.6f}" for x in v))
            for j in range(len(schema.sparse_slots)):
                o = r.sparse_offsets[j]
                vals = r.sparse_values[j][o[i]:o[i + 1]]
                parts.append(f"{len(vals)} " +
                             " ".join(str(int(v)) for v in vals))
            lines.append(" ".join(parts))
        p = tmp_path / f"part-{f:03d}.txt"
        p.write_text("\n".join(lines) + "\n")
        paths.append(str(p))
    return paths, schema, ds


def test_streaming_batches_cover_all_examples(tmp_path):
    paths, schema, ds = _write_files(tmp_path)
    q = QueueDataset(schema, num_threads=2, queue_capacity=2)
    q.set_filelist(paths)
    seen = 0
    for pb in q.batches(batch_size=64, drop_last=False):
        assert pb.ids.shape[1] == NUM_SLOTS * 2
        seen += pb.num
    assert seen == 400


def test_batch_stitching_across_files(tmp_path):
    paths, schema, ds = _write_files(tmp_path)
    # batch size 96 doesn't divide the 100-example files: batches must
    # stitch across file boundaries
    q = QueueDataset(schema, num_threads=1, queue_capacity=2)
    q.set_filelist(paths)
    batches = list(q.batches(batch_size=96, drop_last=True))
    assert len(batches) == 400 // 96
    assert all(b.num == 96 for b in batches)


def test_shard_batches_partition_files(tmp_path):
    paths, schema, ds = _write_files(tmp_path)
    q = QueueDataset(schema)
    q.set_filelist(paths)
    n0 = sum(pb.num for pb in q.shard_batches(0, 2, batch_size=50))
    n1 = sum(pb.num for pb in q.shard_batches(1, 2, batch_size=50))
    assert n0 == n1 == 200


def test_reader_error_propagates(tmp_path):
    schema = DataFeedSchema.ctr(num_sparse=2, num_float=0, batch_size=8)
    q = QueueDataset(schema)
    q.set_filelist([str(tmp_path / "missing.txt")])
    with pytest.raises(OSError):
        list(q.batches(batch_size=8))


def test_abandoned_iterator_reaps_reader_threads(tmp_path):
    import threading, gc
    paths, schema, _ = _write_files(tmp_path)
    q = QueueDataset(schema, num_threads=3, queue_capacity=1)
    q.set_filelist(paths * 4)
    before = threading.active_count()
    it = q.batches(batch_size=32)
    next(it)              # start workers, then abandon
    it.close()            # GeneratorExit → cancel + join
    gc.collect()
    assert threading.active_count() <= before


def test_heter_surfaces_reader_errors(tmp_path):
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.train import HeterTrainer, HeterConfig

    paths, schema, _ = _write_files(tmp_path, n_files=2, lines_per=64)
    q = QueueDataset(schema, num_threads=1)
    q.set_filelist(paths + [str(tmp_path / "missing.txt")])
    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    tr = HeterTrainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4,
                                  dense_dim=1, hidden=(8,)),
                      store, schema, HeterConfig(global_batch_size=32))
    with pytest.raises(OSError):
        tr.train_pass(q)


def test_queue_dataset_feeds_heter_trainer(tmp_path):
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.train import HeterTrainer, HeterConfig

    paths, schema, _ = _write_files(tmp_path, n_files=4, lines_per=128)
    q = QueueDataset(schema, num_threads=2)
    q.set_filelist(paths)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4, learning_rate=0.1))
    model = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                        hidden=(16,))
    tr = HeterTrainer(model, store, schema,
                      HeterConfig(global_batch_size=64, dense_lr=3e-3,
                                  auc_buckets=1 << 10))
    out = tr.train_pass(q)
    assert out["steps"] == 8
    assert len(store) > 0
