"""Shared mock remote filesystem for tests and crash workers.

A tiny argv-based CLI maps ``<scheme>://…`` URIs onto a sandbox directory
— the same contract a real ``hadoop fs``/``gsutil`` deployment fills in
production (InitAfsAPI, box_wrapper.h:577). ``register_mockfs`` builds the
CommandFS and registers it for a scheme; crash workers do the same from
environment variables (PBTPU_MOCKFS_ROOT/PBTPU_MOCKFS_SCHEME) so a
subprocess kill→resume matrix can exercise hdfs://-schemed checkpoint
roots end-to-end.
"""

import os
import sys
import textwrap

from paddlebox_tpu.utils import fs as fs_lib

MOCK_CLI = textwrap.dedent("""
    import os, shutil, sys
    ROOT = os.environ["MOCKFS_ROOT"]
    SCHEME = os.environ.get("MOCKFS_SCHEME", "mock")

    def local(p):
        pre = SCHEME + "://"
        assert p.startswith(pre), p
        return os.path.join(ROOT, p[len(pre):])

    op = sys.argv[1]
    if op == "cat":
        with open(local(sys.argv[2]), "rb") as f:
            sys.stdout.buffer.write(f.read())
    elif op == "ls":
        d = local(sys.argv[2])
        for n in sorted(os.listdir(d)):
            print(sys.argv[2].rstrip("/") + "/" + n)
    elif op == "put":
        # hadoop-faithful: put INTO an existing directory nests the source
        # under it (this is the semantics FleetUtil._save_dir must survive)
        src, dst = sys.argv[2], local(sys.argv[3])
        if os.environ.get("MOCKFS_FAIL_PUT_DIR") and os.path.isdir(src):
            # injected outage for directory uploads (checkpoint dirs) —
            # file puts (donefile lines) still succeed, so a broken
            # upload→donefile ordering would be caught red-handed
            sys.stderr.write("injected put outage (dir)\\n")
            sys.exit(7)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if os.path.isdir(dst):
            dst = os.path.join(dst, os.path.basename(src.rstrip("/")))
        if os.path.isdir(src):
            if os.path.exists(dst):
                shutil.rmtree(dst)
            shutil.copytree(src, dst)
        else:
            shutil.copy2(src, dst)
    elif op == "get":
        src, dst = local(sys.argv[2]), sys.argv[3]
        if os.path.isdir(src):
            shutil.copytree(src, dst)
        else:
            shutil.copy2(src, dst)
    elif op == "mkdir":
        os.makedirs(local(sys.argv[2]), exist_ok=True)
    elif op == "test":
        sys.exit(0 if os.path.exists(local(sys.argv[2])) else 1)
    elif op == "rm":
        p = local(sys.argv[2])
        if os.path.isdir(p):
            shutil.rmtree(p)
        elif os.path.exists(p):
            os.remove(p)
    else:
        sys.exit(2)
""")


def write_cli(dirpath: str) -> str:
    cli = os.path.join(dirpath, "mockfs_cli.py")
    with open(cli, "w") as f:
        f.write(MOCK_CLI)
    return cli


def register_mockfs(sandbox_root: str, cli_path: str | None = None,
                    scheme: str = "mock") -> fs_lib.CommandFS:
    """Register a CommandFS for ``scheme`` backed by the sandbox CLI."""
    os.makedirs(sandbox_root, exist_ok=True)
    if cli_path is None:
        cli_path = write_cli(sandbox_root)
    base = f"{sys.executable} {cli_path}"
    fs = fs_lib.CommandFS(
        cat=f"{base} cat {{path}}", ls=f"{base} ls {{path}}",
        put=f"{base} put {{src}} {{dst}}", get=f"{base} get {{src}} {{dst}}",
        mkdir=f"{base} mkdir {{path}}", test=f"{base} test {{path}}",
        rm=f"{base} rm {{path}}",
        env={"MOCKFS_ROOT": str(sandbox_root), "MOCKFS_SCHEME": scheme})
    fs_lib.register_fs(scheme, fs)
    return fs


def register_from_env() -> fs_lib.CommandFS | None:
    """Worker-side hook: register the mock fs from PBTPU_MOCKFS_ROOT /
    PBTPU_MOCKFS_SCHEME (set by the test driving the subprocess)."""
    root = os.environ.get("PBTPU_MOCKFS_ROOT")
    if not root:
        return None
    return register_mockfs(root,
                           scheme=os.environ.get("PBTPU_MOCKFS_SCHEME",
                                                 "hdfs"))
