"""ShareEmbedding and Variable/NNCross feature types (VERDICT missing #5).

Reference: the feature-type dispatch at box_wrapper.cc:406-461 selects
pull/push value structs per type; ShareEmbedding rows carry one embed
weight per sharing slot (box_wrapper.cu:543-674), Variable/NNCross rows
carry presence-gated embedx/expand planes that pull as zeros and take no
grads while absent (box_wrapper.cu:161-260).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.data import DataFeedSchema, SlotDataset
from paddlebox_tpu.data.parser import parse_multislot_lines
from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                     PassWorkingSet, sharded)
from paddlebox_tpu.models import DNNCTRModel
from paddlebox_tpu.ops import ShareEmbeddingModel, select_share_embedding
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig


# ---------------------------------------------------------------------------
# config geometry
# ---------------------------------------------------------------------------

def test_share_embedding_row_geometry():
    c = EmbeddingConfig(dim=4, embed_w_num=3)
    assert c.fixed_cols == 5
    assert c.pull_width == 5 + 4
    assert c.grad_width == 3 + 4
    assert c.row_width == 5 + 4 + 2          # adagrad: 2 state cols
    assert c.w_cols == slice(2, 5)
    assert c.embedx_cols == slice(5, 9)


def test_share_embedding_rejects_ftrl():
    with pytest.raises(ValueError, match="ftrl"):
        EmbeddingConfig(dim=4, embed_w_num=2, optimizer="ftrl")


def test_variable_thresholds_validate():
    with pytest.raises(ValueError, match="expand_create_threshold"):
        EmbeddingConfig(dim=4, expand_create_threshold=2.0)  # no expand_dim
    EmbeddingConfig(dim=4, expand_dim=2, expand_create_threshold=2.0)


# ---------------------------------------------------------------------------
# optimizer block math
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("opt", ["sgd", "adagrad", "adam"])
def test_w_block_reduces_to_scalar_w(opt):
    """embed_w_num=1 must produce the exact legacy scalar-w update, and an
    embed_w_num=2 row whose two w planes get the same grad must update both
    planes identically (equal to the scalar result for sgd/adagrad; adam's
    block path deliberately blends the per-element direction, so only the
    plane-symmetry and the shared state/embedx columns are invariant)."""
    from paddlebox_tpu.embedding.optim import apply_updates

    c1 = EmbeddingConfig(dim=4, optimizer=opt, learning_rate=0.05)
    c2 = EmbeddingConfig(dim=4, optimizer=opt, learning_rate=0.05,
                         embed_w_num=2)
    rng = np.random.default_rng(0)
    n = 16
    rows1 = rng.normal(size=(n, c1.row_width)).astype(np.float32)
    grads1 = rng.normal(size=(n, c1.grad_width)).astype(np.float32)
    si = rng.random(n).astype(np.float32)
    ci = rng.random(n).astype(np.float32)
    out1 = np.asarray(apply_updates(jnp.asarray(rows1), jnp.asarray(grads1),
                                    jnp.asarray(si), jnp.asarray(ci), c1))

    if opt == "adam":
        # nw=1 must match the LEGACY scalar formula exactly (checkpoint
        # continuation): new_w = w - lr * nw_m / (sqrt(nw_v) + eps)
        b1, b2 = c1.beta1, c1.beta2
        w, g_w = rows1[:, 2], grads1[:, 0]
        w_m, w_v = rows1[:, 7], rows1[:, 8]
        nw_m = b1 * w_m + (1 - b1) * g_w
        nw_v = b2 * w_v + (1 - b2) * g_w * g_w
        legacy_w = w - 0.05 * nw_m / (np.sqrt(nw_v) + 1e-8)
        np.testing.assert_allclose(out1[:, 2], legacy_w, rtol=1e-6)

    # widen to 2 identical w planes with identical grads
    rows2 = np.concatenate(
        [rows1[:, :2], rows1[:, 2:3], rows1[:, 2:3], rows1[:, 3:]], axis=1)
    grads2 = np.concatenate(
        [grads1[:, :1], grads1[:, :1], grads1[:, 1:]], axis=1)
    out2 = np.asarray(apply_updates(jnp.asarray(rows2), jnp.asarray(grads2),
                                    jnp.asarray(si), jnp.asarray(ci), c2))
    np.testing.assert_allclose(out2[:, 2], out2[:, 3], rtol=1e-6)
    if opt != "adam":
        np.testing.assert_allclose(out2[:, 2], out1[:, 2], rtol=1e-6)
    np.testing.assert_allclose(out2[:, 4:], out1[:, 3:], rtol=1e-6)


# ---------------------------------------------------------------------------
# select op
# ---------------------------------------------------------------------------

def test_select_share_embedding_forward_and_grad():
    cfg = EmbeddingConfig(dim=2, embed_w_num=3)
    B, T = 2, 4
    seg = np.array([0, 0, 1, 2], np.int32)       # 3 slots over 4 positions
    share = np.array([2, 0, 1], np.int32)        # slot -> w plane
    pulled = jnp.asarray(
        np.random.default_rng(0).normal(size=(B, T, cfg.pull_width))
        .astype(np.float32))
    out = select_share_embedding(pulled, seg, share, cfg)
    assert out.shape == (B, T, 3 + 2)
    # slot 0 tokens (pos 0, 1) read w plane 2 = column 2+2
    np.testing.assert_allclose(out[:, 0, 2], pulled[:, 0, 4])
    np.testing.assert_allclose(out[:, 2, 2], pulled[:, 2, 2])  # slot1→plane0
    np.testing.assert_allclose(out[:, 3, 2], pulled[:, 3, 3])  # slot2→plane1
    # show/clk/embedx pass through
    np.testing.assert_allclose(out[..., :2], pulled[..., :2])
    np.testing.assert_allclose(out[..., 3:], pulled[..., 5:])

    # grads route ONLY to the selected plane
    g = jax.grad(lambda p: select_share_embedding(p, seg, share, cfg)
                 [..., 2].sum())(pulled)
    g = np.asarray(g)
    assert g[:, 0, 4].min() == 1.0 and g[:, 0, 2:4].max() == 0.0
    assert g[:, 2, 2].min() == 1.0 and g[:, 2, 3:5].max() == 0.0


# ---------------------------------------------------------------------------
# variable feature: pull gating
# ---------------------------------------------------------------------------

def test_variable_pull_gates_planes_by_show():
    cfg = EmbeddingConfig(dim=2, expand_dim=2, mf_create_threshold=5.0,
                          expand_create_threshold=10.0)
    store = HostEmbeddingStore(cfg)
    keys = np.array([11, 22, 33], np.uint64)
    rows = store.lookup_or_init(keys)
    rows[:, 0] = [2.0, 7.0, 12.0]                # shows: none / mf / mf+expand
    rows[:, cfg.embedx_cols] = 1.0
    store.write_back(keys, rows)
    ws = PassWorkingSet.begin_pass(store, keys)
    idx = ws.translate(keys)
    pulled = np.asarray(sharded.lookup(ws.table, jnp.asarray(idx), cfg))
    fc = cfg.fixed_cols
    assert pulled[0, fc:].max() == 0.0           # below both thresholds
    assert pulled[1, fc:fc + 2].min() == 1.0     # embedx present
    assert pulled[1, fc + 2:].max() == 0.0       # expand absent
    assert pulled[2, fc:].min() == 1.0           # both present


def test_variable_gating_on_host_paths():
    """Every pull path gates identically: device lookup, PS table pull, and
    the serving table (train/serve skew otherwise — gating.py)."""
    from paddlebox_tpu.distributed.ps import _SparseTable
    from paddlebox_tpu.inference.serving_table import ServingTable

    cfg = EmbeddingConfig(dim=2, mf_create_threshold=5.0)
    store = HostEmbeddingStore(cfg)
    keys = np.array([11, 22], np.uint64)
    rows = store.lookup_or_init(keys)
    rows[:, 0] = [2.0, 7.0]
    rows[:, cfg.embedx_cols] = 1.0
    store.write_back(keys, rows)
    fc = cfg.fixed_cols

    tbl = _SparseTable(cfg)
    tbl.store = store
    ps_pull = tbl.pull(keys, init_missing=False)
    assert ps_pull[0, fc:].max() == 0.0 and ps_pull[1, fc:].min() == 1.0

    sv = ServingTable.from_store(store)
    sv_pull = sv.lookup(keys)
    assert sv_pull[0, fc:].max() == 0.0 and sv_pull[1, fc:].min() == 1.0
    # gate survives a save/load roundtrip
    import tempfile
    d = tempfile.mkdtemp()
    sv.save(d)
    sv2 = ServingTable.load(d)
    np.testing.assert_array_equal(sv2.lookup(keys), sv_pull)


def test_variable_push_gates_grads_by_show():
    from paddlebox_tpu.embedding.optim import apply_updates

    cfg = EmbeddingConfig(dim=2, optimizer="sgd", learning_rate=1.0,
                          mf_create_threshold=5.0)
    rows = np.zeros((2, cfg.row_width), np.float32)
    rows[0, 0] = 1.0                             # stays below threshold
    rows[1, 0] = 10.0                            # above
    grads = np.full((2, cfg.grad_width), 1.0, np.float32)
    out = np.asarray(apply_updates(
        jnp.asarray(rows), jnp.asarray(grads),
        jnp.zeros(2), jnp.zeros(2), cfg))
    assert out[0, cfg.embedx_cols].max() == 0.0  # embedx grad dropped
    assert out[1, cfg.embedx_cols].max() == -1.0
    assert out[0, 2] == -1.0                     # w always trains


def test_variable_threshold_crossing_mid_training():
    """A key crossing mf_create_threshold starts training embedx; the
    threshold tests the post-increment show (plane created at push)."""
    from paddlebox_tpu.embedding.optim import apply_updates

    cfg = EmbeddingConfig(dim=2, optimizer="sgd", learning_rate=1.0,
                          mf_create_threshold=3.0)
    rows = np.zeros((1, cfg.row_width), np.float32)
    rows[0, 0] = 2.5
    grads = np.full((1, cfg.grad_width), 1.0, np.float32)
    out = np.asarray(apply_updates(
        jnp.asarray(rows), jnp.asarray(grads),
        jnp.ones(1), jnp.zeros(1), cfg))        # show 2.5 -> 3.5 crosses
    assert out[0, cfg.embedx_cols].max() == -1.0


# ---------------------------------------------------------------------------
# end-to-end training
# ---------------------------------------------------------------------------

NUM_SLOTS = 3


def _shared_key_dataset(n=1024, seed=0):
    """All slots draw ids from ONE shared key space (no slot salting) —
    the data shape ShareEmbedding exists for."""
    rng = np.random.default_rng(seed)
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=1,
                                batch_size=64, max_len=2)
    # per-(slot, id) latent weight: the shared embedx can model the id
    # main effect, the per-slot w planes model the slot-specific offsets
    idw = np.random.default_rng(7).normal(size=(NUM_SLOTS, 60)) * 1.2
    lines = []
    for _ in range(n):
        logits, parts, per_slot = 0.0, [], []
        for s in range(NUM_SLOTS):
            ids = rng.integers(0, 60, size=rng.integers(1, 3))
            per_slot.append(ids)
            logits += idw[s, ids].sum()
        label = float(rng.random() < 1.0 / (1.0 + np.exp(-0.8 * logits)))
        parts.append(f"1 {label}")
        parts.append(f"1 {rng.normal():.4f}")
        for ids in per_slot:
            parts.append(f"{len(ids)} {' '.join(str(int(v) + 1) for v in ids)}")
        lines.append(" ".join(parts))
    ds = SlotDataset(schema)
    ds.records = parse_multislot_lines(lines, schema)
    return ds, schema


def test_share_embedding_end_to_end():
    ds, schema = _shared_key_dataset()
    cfg = EmbeddingConfig(dim=8, embed_w_num=NUM_SLOTS, learning_rate=0.15)
    store = HostEmbeddingStore(cfg)
    inner = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1,
                        hidden=(32, 16))
    model = ShareEmbeddingModel(inner, np.arange(NUM_SLOTS), cfg)
    tr = Trainer(model, store, schema, make_mesh(8),
                 TrainerConfig(global_batch_size=128, dense_lr=3e-3,
                               auc_buckets=1 << 12))
    results = [tr.train_pass(ds) for _ in range(3)]
    assert results[-1]["auc"] > 0.6, results
    # all three w planes actually trained (each slot feeds its own)
    tr.flush_sparse()
    rows = store.get_rows(ds.unique_keys())
    w_block = rows[:, cfg.w_cols]
    assert (np.abs(w_block).sum(axis=0) > 0).all(), w_block.sum(axis=0)


def test_variable_feature_end_to_end():
    """High mf threshold: embedx stays at deterministic init (pull-gated,
    grad-gated) while w/show train; same run with threshold 0 trains it."""
    ds, schema = _shared_key_dataset(256, seed=3)
    results = {}
    for thresh in (1e9, 0.0):
        cfg = EmbeddingConfig(dim=8, learning_rate=0.15,
                              mf_create_threshold=thresh)
        store = HostEmbeddingStore(cfg)
        model = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1,
                            hidden=(16,))
        tr = Trainer(model, store, schema, make_mesh(8),
                     TrainerConfig(global_batch_size=64, dense_lr=3e-3,
                                   auc_buckets=1 << 10))
        tr.train_pass(ds)
        tr.flush_sparse()
        keys = ds.unique_keys()
        emb = store.get_rows(keys)[:, cfg.embedx_cols]
        init = store._init_rows(keys)[:, cfg.embedx_cols]
        results[thresh] = np.abs(emb - init).max()
        assert np.abs(store.get_rows(keys)[:, 2]).max() > 0  # w trained
    assert results[1e9] == 0.0            # embedx untouched below threshold
    assert results[0.0] > 0.0             # and trains normally without one
