"""The any-language serving claim, proven (VERDICT r2 missing #2).

native/serving_score.c — a libc-only C program — mmaps an exported
serving directory (serving.npz key/value planes + dense.npz MLP params,
both STORED zip members), binary-searches keys, applies CVM + pooling,
runs the MLP, and must score identically to the Python Predictor. The
reference ships the same proof as Go/R clients (go/paddle/predictor.go).
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

import jax

from paddlebox_tpu.data import DataFeedSchema
from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.inference import Predictor, save_inference_model
from paddlebox_tpu.models import DNNCTRModel

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddlebox_tpu", "native")

NUM_SLOTS, EMB_DIM, DENSE_DIM, MAX_LEN = 3, 4, 2, 2


@pytest.fixture(scope="module")
def cbin(tmp_path_factory):
    cc = shutil.which("cc") or shutil.which("gcc")
    if cc is None:
        pytest.skip("no C compiler")
    out = str(tmp_path_factory.mktemp("cbin") / "serving_score")
    subprocess.run([cc, "-O2", "-std=c11", "-Wall",
                    os.path.join(NATIVE, "serving_score.c"),
                    "-o", out, "-lm"], check=True)
    return out


def test_c_client_scores_match_python(cbin, tmp_path):
    rng = np.random.default_rng(0)
    cfg = EmbeddingConfig(dim=EMB_DIM, learning_rate=0.1)
    store = HostEmbeddingStore(cfg)
    keys = rng.choice(1 << 40, 200, replace=False).astype(np.uint64)
    rows = store.lookup_or_init(keys)
    # give rows non-trivial show/clk so the CVM transform matters
    rows[:, 0] = rng.integers(1, 50, len(rows))
    rows[:, 1] = rng.integers(0, 10, len(rows))
    store.write_back(keys, rows)

    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=DENSE_DIM,
                                batch_size=8, max_len=MAX_LEN)
    model = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM,
                        dense_dim=DENSE_DIM, hidden=(16, 8))
    params = model.init(jax.random.PRNGKey(1))
    export = str(tmp_path / "export")
    save_inference_model(export, model, params, store, schema)

    T = NUM_SLOTS * MAX_LEN
    B = 8
    ids = rng.choice(keys, size=(B, T)).astype(np.uint64)
    ids[0, 0] = np.uint64(123456789)     # unknown key -> zero row
    mask = rng.random((B, T)) < 0.8
    dense = rng.normal(size=(B, DENSE_DIM)).astype(np.float32)

    pred = Predictor.load(export)
    want = pred.predict(ids, mask, dense)

    lines = []
    for b in range(B):
        parts = ([str(int(v)) for v in ids[b]]
                 + [str(int(v)) for v in mask[b]]
                 + [f"{v:.8f}" for v in dense[b]])
        lines.append(" ".join(parts))
    out = subprocess.run(
        [cbin, export, str(NUM_SLOTS), str(MAX_LEN), "1"],
        input="\n".join(lines) + "\n", capture_output=True, text=True,
        check=True)
    got = np.array([float(x) for x in out.stdout.split()])
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_export_members_are_stored_uncompressed(tmp_path):
    """The format contract the C client depends on: STORED zip members."""
    import zipfile
    cfg = EmbeddingConfig(dim=EMB_DIM)
    store = HostEmbeddingStore(cfg)
    store.lookup_or_init(np.arange(1, 20, dtype=np.uint64))
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=DENSE_DIM,
                                batch_size=8, max_len=MAX_LEN)
    model = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM,
                        dense_dim=DENSE_DIM, hidden=(8,))
    params = model.init(jax.random.PRNGKey(0))
    export = str(tmp_path / "export")
    save_inference_model(export, model, params, store, schema)
    for fname in ("serving.npz", "dense.npz"):
        with zipfile.ZipFile(os.path.join(export, fname)) as z:
            for info in z.infolist():
                assert info.compress_type == zipfile.ZIP_STORED, (
                    fname, info.filename)
