"""Deferred sparse-push pipeline (flags.push_overlap).

The jitted step returns the packed push operands instead of applying them
inline; the trainer dispatches the table apply for step N as its own
program while step N+1's pack/plan-H2D runs. The contract under test:

- **Bit-for-bit parity**: overlap-on (after the pass-boundary flush) must
  equal overlap-off on the persisted table rows, the dense params, and
  the whole loss trajectory — the apply is always data-sequenced before
  the next step consumes the table, so deferral is a program-boundary
  choice with no numeric consequence.
- **Loss path**: the deferred step program must not contain the table
  apply (no scatter in the lowered text, no table output) — the
  acceptance criterion verified via jaxpr/HLO inspection.
- **Flush ordering**: pass end, eval, and store save/export must all see
  the applied table (pending applies land first).
- **Bounded staleness**: at most ONE unapplied step, enforced by the
  operand stager; and no thread or staged-buffer leaks after a pass.
"""

import threading

import numpy as np
import pytest

import jax

from paddlebox_tpu.config import set_flags
from paddlebox_tpu.data import DataFeedSchema
from paddlebox_tpu.data.dataset import SlotDataset
from paddlebox_tpu.data.slot_record import SlotRecordBatch
from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.embedding.working_set import PushOperandStager
from paddlebox_tpu.models import DeepFMModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig

NUM_SLOTS, EMB_DIM, BATCH = 4, 4, 16


@pytest.fixture(autouse=True)
def _restore_flags():
    yield
    set_flags(push_overlap="auto", push_dedup_premerge="auto")


def _dataset(n_ex, seed=0):
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=1,
                                batch_size=BATCH, max_len=1)
    rng = np.random.default_rng(seed)
    offs = np.arange(n_ex + 1, dtype=np.int64)
    ds = SlotDataset(schema)
    ds.records = SlotRecordBatch(
        schema=schema, num=n_ex,
        sparse_values=[(rng.integers(1, 400, size=n_ex).astype(np.int64)
                        | (np.int64(s + 1) << np.int64(40)))
                       for s in range(NUM_SLOTS)],
        sparse_offsets=[offs.copy() for _ in range(NUM_SLOTS)],
        float_values=[(rng.random(n_ex) < 0.3).astype(np.float32),
                      rng.normal(size=n_ex).astype(np.float32)],
        ins_id=np.zeros(n_ex, dtype=np.uint64),
        search_id=np.zeros(n_ex, dtype=np.uint64),
        rank=np.zeros(n_ex, dtype=np.int32),
        cmatch=np.zeros(n_ex, dtype=np.int32))
    return ds, schema


def _build(overlap, n_dev=8, use_plan=False, n_batches=6):
    set_flags(push_overlap=overlap)
    ds, schema = _dataset(n_batches * BATCH)
    store = HostEmbeddingStore(EmbeddingConfig(dim=EMB_DIM,
                                               learning_rate=0.05))
    tr = Trainer(DeepFMModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM,
                             dense_dim=1, hidden=(8,)),
                 store, schema, make_mesh(n_dev),
                 TrainerConfig(global_batch_size=BATCH))
    if use_plan:
        # the host binned/dedup plan is TPU-gated in production; force it
        # so the CPU suite exercises the premerged deferred variant
        tr._use_plan = True
    return tr, ds, store


def _run(overlap, n_dev=8, use_plan=False):
    tr, ds, store = _build(overlap, n_dev, use_plan)
    out = tr.train_pass(ds)
    tr.flush_sparse()
    keys = np.sort(np.unique(np.concatenate(
        [np.asarray(v) for v in ds.records.sparse_values]))).astype(
        np.uint64)
    rows = store.peek_rows(keys)
    params = jax.tree.map(np.asarray, tr.params)
    return out, rows, params, tr


def _assert_bitwise(a, b):
    assert np.array_equal(a, b), (
        f"maxdiff {np.abs(np.asarray(a) - np.asarray(b)).max()}")


def test_overlap_parity_bitwise_mesh8():
    """Flushed overlap-on == overlap-off bit-for-bit: table rows, dense
    params, loss trajectory (the acceptance criterion)."""
    out_on, rows_on, p_on, tr_on = _run("on")
    out_off, rows_off, p_off, tr_off = _run("off")
    assert tr_on.push_overlap and not tr_off.push_overlap
    assert out_on["steps"] == out_off["steps"] == 6
    for k in ("loss_first", "loss_last", "loss_mean", "auc"):
        assert out_on[k] == out_off[k], k
    _assert_bitwise(rows_on, rows_off)
    for a, b in zip(jax.tree.leaves(p_on), jax.tree.leaves(p_off)):
        _assert_bitwise(a, b)
    # one apply dispatched per step, all drained at the boundary
    assert tr_on.push_applies == out_on["steps"]
    assert tr_on._push_stager.pending() == 0
    assert tr_off.push_applies == 0


def test_overlap_parity_premerged_plan_single_shard():
    """The dedup-plan variant: the step premerges grads/shows/clks onto
    unique lanes in-step and the apply replays only the engine — still
    bit-for-bit against the inline path with the same plan."""
    set_flags(push_dedup_premerge="on")
    out_on, rows_on, p_on, tr_on = _run("on", n_dev=1, use_plan=True)
    set_flags(push_dedup_premerge="on")
    out_off, rows_off, p_off, tr_off = _run("off", n_dev=1, use_plan=True)
    # prove the plan actually carried dedup bounds (the premerged path)
    ws = tr_on.feed_mgr._current
    plan = tr_on._host_plan(ws, ws.translate(
        np.asarray(ws.sorted_keys[:BATCH * NUM_SLOTS]).reshape(
            BATCH, NUM_SLOTS)))
    assert plan[3].shape[0] > 0, "dedup premerge plan did not engage"
    for k in ("loss_first", "loss_last", "loss_mean"):
        assert out_on[k] == out_off[k], k
    _assert_bitwise(rows_on, rows_off)
    for a, b in zip(jax.tree.leaves(p_on), jax.tree.leaves(p_off)):
        _assert_bitwise(a, b)


def test_step_program_excludes_table_apply():
    """jaxpr/HLO inspection (the acceptance criterion): with overlap on,
    the loss-producing step program contains no table scatter-update and
    returns no table; the inline program contains both."""
    tr, ds, store = _build("on", n_dev=1)
    ws = tr.feed_mgr.begin_pass(ds.unique_keys())
    pb = next(iter(ds.batches(BATCH)))
    staged = tr._put_batch(ws, pb)
    dstate = tr.pack_dense()

    defer_txt = tr._defer_step_fn.lower(
        ws.table, *dstate, *staged).as_text()
    inline_txt = tr._step_fn.lower(ws.table, *dstate, *staged).as_text()
    assert "scatter" in inline_txt, \
        "inline step lost its table apply — test premise broken"
    assert "scatter" not in defer_txt, \
        "deferred step still contains the table apply on the loss path"
    # the apply program is where the scatter moved. Both the inline step
    # and the apply DONATE the table, so each consumer gets its own copy
    from paddlebox_tpu.parallel import mesh as mesh_lib
    tbl_sh = mesh_lib.table_sharding(tr.mesh)
    table_np = np.asarray(ws.table)
    # both execs below donate their dense state and must see the SAME
    # pre-step state — snapshot it to host first
    dstate_np = tuple(np.asarray(a) for a in dstate)
    ops = tr._defer_step_fn(jax.device_put(table_np, tbl_sh), *dstate,
                            *staged)
    dst, push_ops, loss, preds, dropped = tr.split_defer_out(ops)
    apply_txt = tr._apply_fn.lower(
        jax.device_put(table_np, tbl_sh), staged[0], staged[1],
        staged[3], *staged[4:9], *push_ops).as_text()
    assert "scatter" in apply_txt
    # and the deferred step's output carries no table: applying the ops
    # through the apply program reproduces the inline step's table
    inline_out = tr._step_fn(
        jax.device_put(table_np, tbl_sh),
        *(jax.device_put(a) for a in dstate_np), *staged)
    inline_table = np.asarray(inline_out[0])
    applied = tr._apply_fn(jax.device_put(table_np, tbl_sh), staged[0],
                           staged[1], staged[3], *staged[4:9], *push_ops)
    _assert_bitwise(np.asarray(applied), inline_table)


def test_flush_on_eval_and_save_ordering():
    """eval_pass and store save (via flush hooks) must observe the fully
    applied table: predictions and persisted rows equal the overlap-off
    run's after identical training."""
    out_on, rows_on, p_on, tr_on = _run("on")
    tr2, ds2, store2 = _build("off")
    tr2.train_pass(ds2)
    ev_off = tr2.eval_pass(ds2)

    tr3, ds3, store3 = _build("on")
    tr3.train_pass(ds3)
    ev_on = tr3.eval_pass(ds3)     # flush_push runs at eval entry
    assert ev_on["auc"] == ev_off["auc"]
    # store-initiated flush (save path) reaches the trainer through the
    # feed manager's pre-flush hook; rows must be final
    assert tr3._push_stager.pending() == 0
    assert tr3._push_stager.live() == 0


def test_staleness_bound_enforced():
    st = PushOperandStager()
    st.put("step0")
    with pytest.raises(RuntimeError, match="staleness"):
        st.put("step1")
    assert st.take() == "step0"
    assert st.live() == 1          # retired slot pins the in-flight refs
    st.put("step1")
    st.take()
    st.clear()
    assert st.live() == 0


def test_no_thread_or_slot_leaks():
    """The deferred pipeline is async-dispatch only: no helper threads,
    and the stager holds no buffers between passes (conftest's autouse
    thread-leak fixture double-checks the thread half)."""
    before = threading.active_count()
    out, rows, params, tr = _run("on")
    assert tr._push_stager.live() == 0
    assert tr._push_stager.pending() == 0
    assert threading.active_count() <= before + 1  # pack thread may lag


def test_auto_selection_rules():
    """auto = on for allreduce single-step; off for kstep/async and the
    superstep; 'on' raises where the pipeline cannot hold its bound."""
    ds, schema = _dataset(2 * BATCH)
    mesh = make_mesh(8)

    def make(**kw):
        return Trainer(DeepFMModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM,
                                   dense_dim=1, hidden=(8,)),
                       HostEmbeddingStore(EmbeddingConfig(dim=EMB_DIM)),
                       schema, mesh,
                       TrainerConfig(global_batch_size=BATCH, **kw))

    set_flags(push_overlap="auto")
    assert make().push_overlap
    assert not make(dense_sync_mode="kstep").push_overlap
    assert not make(dense_sync_mode="async").push_overlap
    assert not make(steps_per_dispatch=4).push_overlap
    set_flags(push_overlap="on")
    with pytest.raises(ValueError, match="push_overlap"):
        make(dense_sync_mode="kstep")
    with pytest.raises(ValueError, match="push_overlap"):
        make(steps_per_dispatch=4)
    set_flags(push_overlap="off")
    assert not make().push_overlap
