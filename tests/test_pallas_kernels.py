"""Pallas merge-update kernel vs the XLA reference path (interpret mode on
CPU; the same kernel compiles with Mosaic on TPU)."""

import numpy as np
import jax.numpy as jnp
import pytest

from paddlebox_tpu.embedding import sharded
from paddlebox_tpu.embedding.config import EmbeddingConfig
from paddlebox_tpu.embedding.optim import apply_updates
from paddlebox_tpu.ops import pallas_kernels


@pytest.mark.parametrize("opt", ["sgd", "adagrad", "adam", "ftrl"])
@pytest.mark.parametrize("n", [64, 100])   # 100: ragged edge block
def test_merge_update_matches_xla_path(opt, n):
    cfg = EmbeddingConfig(dim=4, optimizer=opt, learning_rate=0.1)
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(n, cfg.row_width)).astype(np.float32))
    acc = np.zeros((n, cfg.grad_width + 3), np.float32)
    touched = rng.choice(n, size=n // 3, replace=False)
    acc[touched, :cfg.grad_width] = rng.normal(
        size=(len(touched), cfg.grad_width))
    acc[touched, cfg.grad_width] = 1.0      # show
    acc[touched, cfg.grad_width + 1] = 0.5  # clk
    acc[touched, cfg.grad_width + 2] = 1.0  # touch count
    acc = jnp.asarray(acc)

    got = pallas_kernels.merge_update(table, acc, cfg, block_rows=32,
                                      interpret=True)
    ref_rows = apply_updates(table, acc[:, :cfg.grad_width],
                             acc[:, cfg.grad_width],
                             acc[:, cfg.grad_width + 1], cfg)
    want = jnp.where((acc[:, cfg.grad_width + 2] > 0)[:, None],
                     ref_rows, table)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    # untouched rows bit-identical
    untouched = np.setdiff1d(np.arange(n), touched)
    np.testing.assert_array_equal(np.asarray(got)[untouched],
                                  np.asarray(table)[untouched])


def test_vma_plumbing_api_canary():
    """merge_update's shard_map handshake is jax.typeof(x).vma →
    ShapeDtypeStruct(vma=...). It can only EXECUTE on real TPU (the Pallas
    interpreter rejects any kernel under a check_vma shard_map — even a
    pure copy trips its while_loop carry typing in JAX 0.9.0), so pin the
    two API halves here: a JAX upgrade that drops either breaks this test
    in CI instead of erroring first on a TPU pod."""
    import jax
    from jax.sharding import PartitionSpec as P
    from paddlebox_tpu import jax_compat
    from paddlebox_tpu.parallel import make_mesh

    if jax_compat.LEGACY_SHARD_MAP:
        pytest.skip("pre-vma jax (0.4.x shim): no vma to plumb — every "
                    "vma consumer getattr-defaults to frozenset()")

    mesh = make_mesh(8)
    axes = tuple(mesh.axis_names)
    seen = []

    def body(x):
        vma = getattr(jax.typeof(x), "vma", None)
        seen.append(vma)
        return x

    x = jnp.zeros((64, 4), jnp.float32)
    jax.jit(jax.shard_map(body, mesh=mesh, in_specs=(P(axes),),
                          out_specs=P(axes)))(x)
    assert seen and seen[0], "jax.typeof(...).vma no longer set in shard_map"
    s = jax.ShapeDtypeStruct((4, 4), jnp.float32, vma=seen[0])
    assert s.shape == (4, 4)


def test_routed_push_with_flag_on_cpu_mesh(monkeypatch):
    """routed_push under shard_map with PBTPU_PALLAS=1 on the CPU mesh:
    exercises the interpret+vma fallback inside merge_update (the kernel
    itself runs only on real TPU; its math is identical by construction
    and covered on-chip)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from paddlebox_tpu.parallel import make_mesh

    monkeypatch.setenv("PBTPU_PALLAS", "1")
    cfg = EmbeddingConfig(dim=4, optimizer="adagrad", learning_rate=0.1)
    rng = np.random.default_rng(2)
    mesh = make_mesh(8)
    axes = tuple(mesh.axis_names)
    n, tokens = 64 * 8, 128           # 64 rows per shard
    table = jnp.asarray(rng.normal(size=(n, cfg.row_width))
                        .astype(np.float32))
    idx = jnp.asarray(rng.integers(1, n, size=tokens * 8)
                      .astype(np.int32))
    grads = jnp.asarray(rng.normal(size=(tokens * 8, cfg.grad_width))
                        .astype(np.float32))
    ones = jnp.ones((tokens * 8,), jnp.float32)

    def body(tshard, idx_l, g_l, s_l, c_l):
        return sharded.routed_push(tshard, idx_l, g_l, s_l, c_l, cfg, axes)

    fused = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axes), P(axes), P(axes), P(axes), P(axes)),
        out_specs=P(axes)))(table, idx, grads, ones, ones)
    monkeypatch.setenv("PBTPU_PALLAS", "0")
    base = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axes), P(axes), P(axes), P(axes), P(axes)),
        out_specs=P(axes)))(table, idx, grads, ones, ones)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(base),
                               rtol=1e-5, atol=1e-5)


def test_push_flag_gated(monkeypatch):
    """PBTPU_PALLAS=1 routes push through the kernel with equal results."""
    cfg = EmbeddingConfig(dim=4, optimizer="adagrad", learning_rate=0.1)
    rng = np.random.default_rng(1)
    n, tokens = 64, 40
    table = jnp.asarray(rng.normal(size=(n, cfg.row_width)).astype(np.float32))
    idx = jnp.asarray(rng.integers(1, n, size=tokens).astype(np.int32))
    grads = jnp.asarray(rng.normal(size=(tokens, cfg.grad_width))
                        .astype(np.float32))
    ones = jnp.ones((tokens,), jnp.float32)

    monkeypatch.delenv("PBTPU_PALLAS", raising=False)
    base = sharded.push(table, idx, grads, ones, ones, cfg)
    monkeypatch.setenv("PBTPU_PALLAS", "1")
    fused = sharded.push(table, idx, grads, ones, ones, cfg)
    np.testing.assert_allclose(np.asarray(base), np.asarray(fused),
                               rtol=1e-6, atol=1e-6)
