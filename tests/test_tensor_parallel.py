"""Tensor parallelism: col/row sharded MLP == unsharded reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.parallel import tensor as tp


@pytest.mark.parametrize("dims", [(16, 32, 16), (8, 64, 32, 1),
                                  (16, 32), (8, 12, 5)])  # incl. rep modes
def test_tp_mlp_matches_reference(dims):
    n_tp = 8
    mesh = tp.make_tp_mesh(n_tp)
    params = tp.init_tp_mlp(jax.random.PRNGKey(0), dims)
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(16, dims[0])).astype(np.float32))
    want = tp.mlp_reference(params, x)
    sharded = tp.shard_tp_params(mesh, params)
    fn = tp.make_tp_mlp(mesh, dims)
    got = fn(sharded, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_tp_with_dp_axis():
    mesh = tp.make_tp_mesh(n_tp=4, n_dp=2)
    dims = (8, 16, 4)
    params = tp.init_tp_mlp(jax.random.PRNGKey(1), dims)
    x = jnp.asarray(np.random.default_rng(1).normal(
        size=(32, 8)).astype(np.float32))
    want = tp.mlp_reference(params, x)
    fn = tp.make_tp_mlp(mesh, dims, dp_axis="dp")
    got = fn(tp.shard_tp_params(mesh, params), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_tp_gradients_match():
    mesh = tp.make_tp_mesh(8)
    dims = (8, 16, 8)
    params = tp.init_tp_mlp(jax.random.PRNGKey(2), dims)
    x = jnp.asarray(np.random.default_rng(2).normal(
        size=(8, 8)).astype(np.float32))
    fn = tp.make_tp_mlp(mesh, dims)
    sharded = tp.shard_tp_params(mesh, params)

    g_ref = jax.grad(lambda p: jnp.sum(tp.mlp_reference(p, x) ** 2))(params)
    g_tp = jax.grad(lambda p: jnp.sum(fn(p, x) ** 2))(sharded)
    for a, b in zip(g_ref, g_tp):
        np.testing.assert_allclose(np.asarray(b["w"]), np.asarray(a["w"]),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(b["b"]), np.asarray(a["b"]),
                                   rtol=2e-4, atol=2e-5)
