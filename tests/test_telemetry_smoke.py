"""Tier-1 observability smoke: the example workflow's --short path with
the telemetry hub enabled must emit a schema-clean JSONL event stream,
one flight record per pass, and a chrome trace that reads in pass units
(pass-boundary + checkpoint-commit instant markers) — and the run
doctor CLI over the produced telemetry dir must exit 0 with a
schema-valid report carrying per-pass critical-path attribution
(ISSUE 12 acceptance)."""

import json
import os
import subprocess
import sys

from paddlebox_tpu.monitor import doctor, flight

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_short_example_emits_valid_telemetry(tmp_path):
    tele = str(tmp_path / "telemetry")
    env = dict(os.environ,
               PYTHONPATH=REPO,
               JAX_PLATFORMS="cpu",
               PBTPU_TELEMETRY_DIR=tele,
               # live doctor rides the smoke: findings (if any) land in
               # the stream as doctor.finding events and the stream must
               # stay schema-clean with them
               PBTPU_DOCTOR_LIVE="1",
               # same child-process hygiene as test_example.py: pin the
               # child's XLA host pools so two JAX processes don't
               # oversubscribe a small host
               XLA_FLAGS="--xla_force_host_platform_device_count=8 "
                         "--xla_cpu_multi_thread_eigen=false",
               OMP_NUM_THREADS="1",
               OPENBLAS_NUM_THREADS="1")
    last = None
    for attempt in range(2):
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "examples", "train_ctr.py"), "--short"],
            env=env, capture_output=True, text=True, timeout=420)
        last = out
        if out.returncode == 0:
            break
        print(f"attempt {attempt} rc={out.returncode} stderr head:\n"
              + out.stderr[:2000], file=sys.stderr)
    assert last.returncode == 0, last.stdout + last.stderr[:4000]
    assert "telemetry:" in last.stdout
    # log_for_profile-parity pass lines on stdout
    assert "[pbtpu] pass=1 " in last.stdout
    assert "[pbtpu] pass=2 " in last.stdout

    # ---- JSONL stream: schema-clean, per-pass flight records ----
    res = flight.validate_events_file(os.path.join(tele, "events.jsonl"))
    assert res["errors"] == [], res["errors"][:10]
    flights = res["flight_records"]
    assert [f["pass_id"] for f in flights] == [1, 2]
    for fr in flights:
        assert fr["steps"] > 0 and fr["examples_per_sec"] > 0
        assert {"read", "translate", "train", "auc",
                "drain"} <= set(fr["stage_seconds"])
        assert fr["stats_delta"].get("trainer.tokens", 0) > 0
        assert "auc" in fr["metrics"]
        # the crash-safe checkpoint commit is accounted inside its pass
        assert fr["stats_delta"].get("ckpt.saves") == 1
        assert fr["stats_delta"].get("ckpt.bytes", 0) > 0
    # background threads emitted tagged events (pack producer at minimum)
    assert any(t != "MainThread" for t in res["threads"]), res["threads"]

    # ---- chrome trace reads in pass units ----
    with open(os.path.join(tele, "trace.json")) as f:
        evs = json.load(f)["traceEvents"]
    instants = [e for e in evs if e["ph"] == "i"]
    names = [e["name"] for e in instants]
    assert names.count("pass_begin") == 2
    assert names.count("pass_end") == 2
    assert names.count("checkpoint_commit") == 2
    spans = [e for e in evs if e["ph"] == "X"
             and e.get("args", {}).get("pass_id") is not None]
    assert spans, "trace spans must carry pass/step args"

    # ---- Prometheus exposition written and well-formed ----
    with open(os.path.join(tele, "metrics.prom")) as f:
        lines = f.read().splitlines()
    assert any(line.startswith("# TYPE pbtpu_") for line in lines)
    for line in lines:
        if line and not line.startswith("#"):
            float(line.rsplit(" ", 1)[1])
    # the doctor's alert series are present even when untouched
    assert any("pbtpu_exchange_overflow_retries" in line
               for line in lines)
    assert any("pbtpu_tiering_hot_hit_rate" in line for line in lines)

    # ---- run doctor CLI over the real run (acceptance) ----
    assert "doctor:" in last.stdout      # the example printed a verdict
    out = subprocess.run(
        [sys.executable, "-m", "paddlebox_tpu.monitor.doctor",
         tele, "--json"],
        env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr[:2000]
    rep = json.loads(out.stdout)
    assert doctor.validate_report(rep) == []
    cp = rep["critical_path"]["passes"]
    assert [p["pass_id"] for p in cp] == [1, 2]
    for p in cp:
        # per-pass attribution names a limiter and carries the boundary
        # account with its split
        assert p["limiter"] in p["stages"]
        assert "boundary" in p["stages"]
        assert set(p["boundary_split"]) == {"build", "h2d",
                                            "spill_fault_in"}
    # the boundary-wall rule was evaluated against real data (fired or
    # quiet — never no-data on a run that carries boundary extras)
    status = {r["rule"]: r["status"] for r in rep["rules"]}
    assert status["boundary-wall"] in ("fired", "quiet")
    # human rendering runs too
    out2 = subprocess.run(
        [sys.executable, "-m", "paddlebox_tpu.monitor.doctor", tele],
        env=env, capture_output=True, text=True, timeout=120)
    assert out2.returncode == 0
    assert "run doctor — verdict:" in out2.stdout
