"""Join/update phase training: two programs, one table (FlipPhase).

Reference: box_wrapper.h:625 (FlipPhase), fused_seqpool_cvm_op.cu:166-228
(use_cvm on/off selects different effective inputs), box_wrapper.h:630
(metrics accumulated per phase).
"""

import numpy as np
import pytest

from paddlebox_tpu.data import DataFeedSchema, SlotDataset
from paddlebox_tpu.data.parser import parse_multislot_lines
from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.fleet import BoxPS
from paddlebox_tpu.fleet.boxps import JOIN_PHASE, UPDATE_PHASE
from paddlebox_tpu.models import DNNCTRModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import PhasedTrainer, TrainerConfig

NUM_SLOTS = 4


def _ds(n, seed=0):
    rng = np.random.default_rng(seed)
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=1,
                                batch_size=64, max_len=2)
    w = np.random.default_rng(13).normal(size=(NUM_SLOTS, 4000)) * 1.5
    lines = []
    for _ in range(n):
        logits, parts, sl = 0.0, [], []
        for s in range(NUM_SLOTS):
            ids = rng.integers(0, 4000, size=2)
            sl.append(ids)
            logits += w[s, ids].sum()
        p = 1 / (1 + np.exp(-logits * 0.6))
        parts.append(f"1 {float(rng.random() < p)}")
        parts.append(f"1 {rng.normal():.3f}")
        for s, ids in enumerate(sl):
            parts.append(
                f"2 {' '.join(str(int(i) + s * 1000003) for i in ids)}")
        lines.append(" ".join(parts))
    ds = SlotDataset(schema)
    ds.records = parse_multislot_lines(lines, schema)
    return ds, schema


def _models():
    join = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                       hidden=(16,), use_cvm=True)
    upd = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                      hidden=(16,), use_cvm=False)
    return join, upd


def test_phase_models_validated():
    join, upd = _models()
    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    _, schema = _ds(8)
    mesh = make_mesh(8)
    with pytest.raises(ValueError, match="use_cvm=True"):
        PhasedTrainer(upd, upd, store, schema, mesh)
    with pytest.raises(ValueError, match="use_cvm=False"):
        PhasedTrainer(join, join, store, schema, mesh)


def test_join_update_alternation_shares_table():
    ds, schema = _ds(512)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4, learning_rate=0.15))
    box = BoxPS(store)
    box.init_metric("auc", method="plain")
    mesh = make_mesh(8)
    join, upd = _models()
    pt = PhasedTrainer(join, upd, store, schema, mesh,
                       TrainerConfig(global_batch_size=64, dense_lr=5e-3,
                                     auc_buckets=1 << 10),
                       TrainerConfig(global_batch_size=64, dense_lr=5e-3,
                                     auc_buckets=1 << 10))
    # join widths differ: in_dim carries the 2 extra CVM columns per slot
    assert join.in_dim == upd.in_dim + 2 * NUM_SLOTS

    results = []
    # join, update, join alternation driven by the BoxPS phase bit
    assert box.phase == JOIN_PHASE
    import jax
    # snapshot to host: the step donates params, deleting old buffers
    upd_params_before = jax.tree.map(np.asarray, pt.update.params)
    for flip in range(3):
        box.begin_pass()
        out = pt.train_pass(ds, box=box)
        box.end_pass()
        results.append(out)
        box.flip_phase()
    assert [r["phase"] for r in results] == [JOIN_PHASE, UPDATE_PHASE,
                                             JOIN_PHASE]
    for r in results:
        assert np.isfinite(r["loss_mean"])
    # the second join pass continues learning on a table the update pass
    # trained in between — shared state, improving ranking. Measured on
    # AUC, not loss_mean: the join tower is exactly the one consuming the
    # CVM show/clk counters, which jump from all-zero to populated after
    # pass 1 — the second join pass sits at the peak of that covariate
    # shift's miscalibration (loss 0.692→0.888 while AUC leaps
    # 0.537→0.855; the third join pass drops to 0.681/0.992). See ROADMAP
    # "pass-2 loss signature" root cause.
    assert results[2]["auc"] > results[0]["auc"] + 0.1
    # the update pass really trained ITS program (params moved)...
    moved = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(upd_params_before),
                        jax.tree.leaves(pt.update.params)))
    assert moved
    # ...and both phases hit the same working set: every pass pushed show
    # increments into the SAME store rows (3 passes x 512 ex x 2 tokens
    # per slot x 4 slots, minus drop_last tails)
    keys = ds.unique_keys()
    shows = store.get_rows(keys)[:, 0]
    assert shows.sum() >= 3 * 0.9 * (2 * NUM_SLOTS * 512)

    # per-phase AUC: the registry only accumulated while its phase matched
    msg = box.get_metric_msg("auc")
    assert msg["size"] > 0


def test_phase_flip_reuses_resident_working_set():
    """A phase flip must NOT rebuild the working set from the host — the
    update trainer shares the join trainer's feed manager."""
    ds, schema = _ds(256)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    mesh = make_mesh(8)
    join, upd = _models()
    pt = PhasedTrainer(join, upd, store, schema, mesh,
                       TrainerConfig(global_batch_size=64),
                       TrainerConfig(global_batch_size=64))
    assert pt.update.feed_mgr is pt.join.feed_mgr
    pt.train_pass(ds, phase=JOIN_PHASE)
    pt.train_pass(ds, phase=UPDATE_PHASE)
    m = pt.join.feed_mgr
    assert m.last_fresh_rows == 0            # all rows were resident
    assert m.last_reused_rows == len(ds.unique_keys())
