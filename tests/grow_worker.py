"""Worker process for the elastic GROW kill matrix (ISSUE 18), protocol
level: ownership rebind under fire.

Launched (3 processes, ``fail_stop=False``) by tests/test_elastic.py.
Launcher ranks 0/1 are INCUMBENTS of a 2-member world that believes it
was launched at 3 (degraded); launcher rank 2 is a JOINER running
``ElasticWorld.admit``. The incumbents' RemediationController polls grow
under (synthetic, rank-consistent) heartbeat-gap evidence; the union
all-gather admits the joiner; ownership — a real 8-shard
:class:`ShardOwnership`, rebound through the REAL
``Trainer.set_shard_ownership`` (the ``elastic.ownership.rebind.pre``
crash window) — re-deals across the grown world.

``PBTPU_GROW_MODE`` selects the leg:

  clean                  no kill: all three converge on gen 1 [0, 1, 2];
                         the newcomer's ownership diff ``gained`` equals
                         its ``owned`` exactly (it rebuilds its shards'
                         boundary set and nothing else)
  kill_joiner_rebind     the NEWCOMER dies mid-shard-rebuild bind: the
                         incumbents detect it at the post-grow barrier
                         and shrink back to gen 2 [0, 1]
  kill_incumbent_rebind  incumbent 1 dies INSIDE poll_grow's ownership
                         rebind: the surviving incumbent + the newcomer
                         re-form gen 2 [0, 2]

Every leg ends with a live all_reduce on the surviving generation — the
"still trainable" witness — and writes info_{rank}.json.
"""

import json
import os
import sys
import time
import traceback

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from paddlebox_tpu import monitor  # noqa: E402
from paddlebox_tpu.config import set_flags  # noqa: E402
from paddlebox_tpu.distributed import RoleMaker  # noqa: E402
from paddlebox_tpu.distributed.ownership import ShardOwnership  # noqa: E402
from paddlebox_tpu.distributed.resilience import (ElasticWorld,  # noqa: E402
                                                  PeerFailureError)
from paddlebox_tpu.runtime.remediation import (  # noqa: E402
    RemediationController)
from paddlebox_tpu.train.trainer import Trainer  # noqa: E402
from paddlebox_tpu.utils import faultpoint  # noqa: E402

N_SHARDS = 8
INCUMBENTS = 2

HBGAP = {"rule": "heartbeat-gap", "severity": "critical",
         "summary": "synthetic grow evidence",
         "evidence": {"degraded": True, "world_size": INCUMBENTS},
         "suggestion": ""}


class _FeedMgr:
    def __init__(self, ownership):
        self.ownership = ownership

    def set_ownership(self, ownership):
        self.ownership = ownership


class _StubTrainer:
    """Just enough trainer for the rebind path — the ownership bind goes
    through the REAL Trainer.set_shard_ownership so the registered crash
    window is on the executed path."""

    set_shard_ownership = Trainer.set_shard_ownership

    def __init__(self, ownership):
        self.feed_mgr = _FeedMgr(ownership)
        self.peer_check = None


def run(log) -> None:
    rm = RoleMaker.from_env()
    mode = os.environ.get("PBTPU_GROW_MODE", "clean")
    work = os.environ["PBTPU_TEST_WORKDIR"]
    me = rm.rank
    monitor.hub().enable(monitor.JsonlSink(
        os.path.join(work, f"events_{me}.jsonl")))
    set_flags(self_healing=True, self_healing_sustain=1)
    store = rm.base_store(60.0)
    kw = dict(heartbeat_interval_s=0.1, lost_after_s=1.5,
              stall_after_s=60.0, reform_timeout_s=3.0,
              initial_world=INCUMBENTS + 1)
    info = {"rank": me, "mode": mode, "rebind": None, "owned": None}

    if me < INCUMBENTS:
        if mode == "kill_incumbent_rebind" and me == 1:
            faultpoint.arm("elastic.ownership.rebind.pre", "kill")
        own0 = ShardOwnership(N_SHARDS, INCUMBENTS, me)
        tr = _StubTrainer(own0)
        world = ElasticWorld(store, me, list(range(INCUMBENTS)), **kw)
        ctl = RemediationController(trainer=tr)
        deadline = time.monotonic() + 60.0
        while True:
            nw, _ = ctl.poll_grow(world, findings=[HBGAP])
            if nw is not world:
                world = nw
                break
            if time.monotonic() > deadline:
                raise TimeoutError("incumbent never grew the world")
            time.sleep(0.05)
        log(f"grew to gen {world.gen} members {world.members}")
        info["rebind"] = tr.feed_mgr.ownership.diff(own0)
        info["owned"] = tr.feed_mgr.ownership.owned.tolist()
    else:
        if mode == "kill_joiner_rebind":
            faultpoint.arm("elastic.ownership.rebind.pre", "kill")
        world = ElasticWorld.admit(store, me, timeout_s=60.0, **kw)
        log(f"admitted at gen {world.gen} members {world.members}")
        tr = _StubTrainer(None)
        own_new = ShardOwnership(N_SHARDS, world.world, world.rank)
        # the newcomer's shard-rebuild bind — the mid-rebuild crash window
        tr.set_shard_ownership(own_new)
        info["rebind"] = own_new.diff(None)
        info["owned"] = own_new.owned.tolist()

    # post-grow convergence: a rank dead mid-rebind must shrink back out
    try:
        world.collectives.barrier("post_grow")
    except PeerFailureError as e:
        log(f"peer died mid-grow: {e}")
        world = world.reform(sorted(e.ranks))
        world.collectives.barrier("post_reform")

    # the surviving generation is operational: a live collective completes
    total = world.collectives.all_reduce(
        np.asarray([world.rank + 1.0], dtype=np.float64))
    info.update(gen=world.gen, members=world.members,
                allreduce=float(np.asarray(total)[0]))
    with open(os.path.join(work, f"info_{me}.json"), "w") as f:
        json.dump(info, f)
    world.close()
    monitor.hub().disable()
    log("done")


def main() -> None:
    work = os.environ["PBTPU_TEST_WORKDIR"]
    os.makedirs(work, exist_ok=True)
    rank = os.environ.get("PBTPU_TRAINER_ID", "?")

    def log(msg):
        print(f"grow rank {rank}: {msg}", flush=True)

    try:
        run(log)
    except BaseException as e:
        with open(os.path.join(work, f"err_{rank}.txt"), "w") as f:
            f.write(f"{type(e).__name__}: {e}\n")
            f.write(traceback.format_exc())
        monitor.hub().disable()
        raise


if __name__ == "__main__":
    main()
