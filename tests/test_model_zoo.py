"""Model zoo: every family trains on the synthetic CTR task and lifts AUC."""

import jax
import numpy as np
import pytest

from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.models import (MODEL_REGISTRY, DCNv2Model, DLRMModel,
                                  MMoEModel, WideDeepModel)
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig
from tests.test_train_e2e import NUM_SLOTS, synth_dataset


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


def test_registry_complete():
    assert set(MODEL_REGISTRY) == {"dnn_ctr", "deepfm", "wide_deep",
                                   "dcn_v2", "dlrm", "mmoe", "pv_rank"}


@pytest.mark.parametrize("model_cls,kw", [
    (WideDeepModel, dict(hidden=(32, 16))),
    (DCNv2Model, dict(hidden=(32, 16), num_cross_layers=2)),
    (DLRMModel, dict(bottom_hidden=(16,), top_hidden=(32,))),
    (MMoEModel, dict(num_experts=3, num_tasks=2, expert_hidden=(32,),
                     expert_out=16, tower_hidden=(16,))),
])
def test_model_trains(mesh8, model_cls, kw):
    ds, schema = synth_dataset(2048)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, learning_rate=0.15))
    model = model_cls(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1, **kw)
    tr = Trainer(model, store, schema, mesh8,
                 TrainerConfig(global_batch_size=128, dense_lr=3e-3,
                               auc_buckets=1 << 12))
    results = [tr.train_pass(ds) for _ in range(3)]
    assert results[-1]["auc"] > 0.60, (model_cls.name, results)
    assert np.isfinite(results[-1]["loss_mean"])


def test_mmoe_multitask_heads(mesh8):
    ds, schema = synth_dataset(256, seed=4)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    model = MMoEModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                      num_experts=2, num_tasks=3, expert_hidden=(8,),
                      expert_out=8, tower_hidden=(8,))
    tr = Trainer(model, store, schema, mesh8,
                 TrainerConfig(global_batch_size=64, auc_buckets=1 << 10))
    from paddlebox_tpu.embedding import PassWorkingSet
    ws = PassWorkingSet.begin_pass(store, ds.unique_keys(), mesh8)
    pb = next(ds.batches(64))
    idx = ws.translate(pb.ids, pb.mask)
    labels, dense = tr.split_floats(pb.floats)
    params = model.init(jax.random.PRNGKey(0))
    from paddlebox_tpu.embedding import sharded
    pulled = sharded.lookup(ws.table, np.asarray(idx).reshape(-1), store.cfg)
    pulled = pulled.reshape(64, tr.layout.total_len, store.cfg.pull_width)
    out = model.apply_tasks(params, pulled, pb.mask,
                            dense.astype(np.float32),
                            tr.layout.segment_ids)
    assert out.shape == (64, 3)
    assert np.isfinite(np.asarray(out)).all()
