"""Subprocess driver for the publish/swap kill matrix (ISSUE 7).

Trains a small deterministic pass loop with crash-safe checkpointing AND
per-pass serving publishes (``BoxPS.end_pass(publisher=…)``), resuming
from the snapshot root when one exists and catching serving up after a
resume (``publish_if_behind`` — a kill between the pass snapshot and the
donefile append must not orphan that pass's model). Fault points arm
through the environment (PBTPU_FAULTPOINT / _ACTION / _AFTER), so one
invocation serves as the golden run, the killed run, and the resuming
re-run — the same contract as tests/crash_worker.py.

On completion dumps the scores a predictor on the FINAL trained state
assigns to the first deterministic batch — the parent compares them with
what a ServingServer tailing the (killed + resumed) donefile serves:
train→publish→serve parity through arbitrary publish-window kills.

Usage: python tests/serving_worker.py ROOT OUT_NPZ [--passes N]
       ROOT holds snaps/ (checkpointer) and serve/ (publish root);
       PBTPU_SERVE_REMOTE=<uri> publishes to a mock-hdfs URI instead.
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
TESTS = os.path.join(REPO, "tests")
if TESTS not in sys.path:
    sys.path.insert(0, TESTS)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mockfs  # noqa: E402
from crash_worker import NUM_SLOTS, synth  # noqa: E402
from paddlebox_tpu.embedding import (EmbeddingConfig,  # noqa: E402
                                     HostEmbeddingStore)
from paddlebox_tpu.fleet import BoxPS  # noqa: E402
from paddlebox_tpu.inference import Predictor, ServingTable  # noqa: E402
from paddlebox_tpu.models import DNNCTRModel  # noqa: E402
from paddlebox_tpu.parallel import make_mesh  # noqa: E402
from paddlebox_tpu.serving import ServingPublisher  # noqa: E402
from paddlebox_tpu.train import Trainer, TrainerConfig  # noqa: E402
from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("root")
    ap.add_argument("out")
    ap.add_argument("--passes", type=int, default=3)
    args = ap.parse_args()

    mockfs.register_from_env()
    serve_root = os.environ.get("PBTPU_SERVE_REMOTE",
                                os.path.join(args.root, "serve"))

    ds, schema = synth()
    store = HostEmbeddingStore(EmbeddingConfig(dim=4, learning_rate=0.05))
    model = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                        hidden=(8,))
    tr = Trainer(model, store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=64, dense_lr=2e-3,
                               auc_buckets=1 << 8), seed=7)
    box = BoxPS(store)
    box.set_date(20260803)
    ckpt = PassCheckpointer(os.path.join(args.root, "snaps"),
                            keep_last_n=4, base_every=2)
    # quant="f32": the parity assertion in the parent is EXACT — the
    # served scores must bit-match a predictor on the final state (the
    # int8 cold-row error bound gets its own in-process test)
    pub = ServingPublisher(serve_root, model, schema,
                           publish_base_every=2, quant="f32",
                           hot_top_k=4)

    cursor = tr.resume(ckpt, box=box)
    start = (int(cursor["pass_id"]) if cursor is not None else 0) + 1
    print(f"worker: resume cursor="
          f"{None if cursor is None else cursor['pass_id']} "
          f"-> starting at pass {start}", flush=True)
    if cursor is not None:
        info = pub.publish_if_behind(store, tr.eval_params(),
                                     pass_id=int(cursor["pass_id"]))
        if info is not None:
            print(f"worker: serving catch-up republished pass "
                  f"{cursor['pass_id']} as v{info['version']}",
                  flush=True)
    for _p in range(start, args.passes + 1):
        box.begin_pass()
        tr.train_pass(ds)
        out = box.end_pass(checkpointer=ckpt, trainer=tr, publisher=pub)
        pinfo = out.get("publish", {})
        print(f"worker: pass {box.pass_id} published "
              f"v{pinfo.get('version')} kind={pinfo.get('kind')}",
              flush=True)

    # final-state scores: what serving MUST reproduce once it tails the
    # donefile to the end
    tr.flush_sparse()
    pred = Predictor(model, tr.eval_params(),
                     ServingTable.from_store(store), schema)
    pb = next(iter(ds.batches(batch_size=64)))
    probs = pred.predict_batch(pb)
    np.savez(args.out, probs=np.asarray(probs),
             pass_id=np.int64(box.pass_id))
    print("worker: done", flush=True)


if __name__ == "__main__":
    main()
