"""Crash-safe pass lifecycle: atomic snapshots, manifest verification,
resume-from-pass, and the fault-injection kill→resume matrix.

The acceptance bar (ISSUE 3): for every registered fault point, killing a
training subprocess at that instruction and resuming must reproduce
bit-identical dense params and sparse table rows versus the uninterrupted
run; a deliberately truncated newest snapshot must be detected by checksum
and resume must fall back to the previous good one.

The subprocess matrix mirrors the reference's preemption model (SIGKILL via
``os._exit`` — no atexit, no finally, buffers lost; SURVEY.md §5 pass-
granularity restart). One point runs as a fast tier-1 smoke (the
``bench.py --dryrun`` pattern); the full matrix is ``slow``.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.utils import checkpoint as ckpt_lib
from paddlebox_tpu.utils import faultpoint
from paddlebox_tpu.utils.checkpoint import CheckpointCorruptError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "crash_worker.py")

# AFTER (skip count) per point, tuned so the kill lands in/after pass 2 —
# proving fallback to a real snapshot, not just a fresh start.
POINT_AFTER = {
    "ckpt.dense.pre_replace": 1,        # pass-2 snapshot's dense write
    "store.save_base.pre_replace": 1,   # pass-3 chain rotation base
    "store.save_delta.pre_replace": 0,  # pass-2 delta (pass 1 is a base)
    "store.save_delta.pre_manifest": 0,
    "feed_pass.flush.pre": 1,           # pass-2 save's D2H flush
    # ISSUE 14: the incremental delta feed's fetch window fires at every
    # reuse boundary (pass >= 2's begin_pass) — AFTER=1 kills the
    # pass-3 boundary, after the pass-2 snapshot committed
    "feed_pass.delta_stage.pre": 1,
    "trainer.push_apply.pre": 6,        # mid pass-2 deferred apply
    "pass_ckpt.pre_manifest": 1,        # pass-2 snapshot uncommitted
    "pass_ckpt.post_manifest": 1,       # pass-2 snapshot committed
    # ISSUE 5 points (the mid-pass/remote env of MIDPASS_REMOTE_ENV):
    "trainer.midpass.post_save": 2,     # mid pass-2 snapshot committed —
                                        # resume must skip from the cursor
    "remote_ckpt.upload.pre": 3,        # pass-2's first mirror upload
    # fires on the RESUME path (download with a wiped staging root) — the
    # dedicated test_kill_during_remote_download_resume flow, not the
    # generic kill→resume roundtrip
    "remote_ckpt.download.pre": 0,
    # ISSUE 6 step-loop windows (3 passes x 4 steps: AFTER=5 fires mid
    # pass 2 — the pack one on the producer thread, the step one right
    # before the dispatch)
    "trainer.pack.pre": 5,
    "trainer.step.pre": 5,
    # ISSUE 11 tiered-table windows (2 spill shards → 2 hits per save /
    # per boundary rebalance; AFTER=2 lands both in pass 2): the
    # streaming memmap save's pre-flush, and the pass-boundary RAM-tier
    # demotion — the cache is never authoritative, so both must resume
    # bit-exact
    "tiering.save.pre_flush": 2,
    "tiering.evict.pre": 2,
}

# points that only sit on the mid-pass / remote-mirror code paths run the
# worker with that configuration — which provably does not change the
# final planes (test_midpass_remote_run_matches_plain_golden)
MIDPASS_REMOTE_POINTS = {"trainer.midpass.post_save",
                         "remote_ckpt.upload.pre",
                         "remote_ckpt.download.pre"}

# points that only sit on the spill-tier code paths run the worker with
# a 2-shard ShardedEmbeddingStore over spill sub-stores (PBTPU_TABLE_
# TIERING=spill) — which provably does not change the final planes
# (test_spill_sharded_run_matches_plain_golden)
SPILL_POINTS = {"tiering.save.pre_flush", "tiering.evict.pre"}


def _midpass_remote_env(tmp_path):
    return {"PBTPU_MOCKFS_ROOT": str(tmp_path / "mock_root"),
            "PBTPU_MOCKFS_SCHEME": "hdfs",
            "PBTPU_CRASH_MIDPASS": "2",
            "PBTPU_CRASH_REMOTE": "hdfs://ck"}


def _spill_env(tmp_path):
    # RAM cache far below the ~120-key table: every pass faults through
    # the disk tier, so the kill windows sit on exercised code
    return {"PBTPU_TABLE_TIERING": "spill",
            "PBTPU_SPILL_CACHE_ROWS": "16",
            "PBTPU_SPILL_DIR": str(tmp_path / "spill"),
            "PBTPU_CRASH_SHARDS": "2"}


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faultpoint.disarm()


def _run_worker(root, out, env_extra=None, check=True):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PBTPU_FAULTPOINT", None)
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, WORKER, str(root), str(out)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    if check and proc.returncode != 0:
        raise AssertionError(
            f"worker failed ({proc.returncode}):\n{proc.stdout}\n"
            f"{proc.stderr}")
    return proc


@pytest.fixture(scope="module")
def golden(tmp_path_factory):
    """Uninterrupted reference run → final-state npz."""
    d = tmp_path_factory.mktemp("golden")
    out = d / "out.npz"
    _run_worker(d / "root", out)
    with np.load(out) as z:
        return {k: z[k] for k in z.files}


def _assert_bitwise_equal(golden, out):
    with np.load(out) as z:
        assert sorted(z.files) == sorted(golden)
        for k in golden:
            np.testing.assert_array_equal(
                golden[k], z[k], err_msg=f"plane {k!r} diverged after "
                                         f"kill -> resume")


def _kill_resume_roundtrip(point, tmp_path, golden):
    root, out = tmp_path / "root", tmp_path / "out.npz"
    if point in MIDPASS_REMOTE_POINTS:
        env = _midpass_remote_env(tmp_path)
    elif point in SPILL_POINTS:
        env = _spill_env(tmp_path)
    else:
        env = {}
    killed = _run_worker(
        root, out, check=False,
        env_extra=dict(env, PBTPU_FAULTPOINT=point,
                       PBTPU_FAULTPOINT_AFTER=str(POINT_AFTER[point])))
    assert killed.returncode == 137, (
        f"expected the armed kill, got rc={killed.returncode}:\n"
        f"{killed.stdout}\n{killed.stderr}")
    assert f"FAULTPOINT KILL {point}" in killed.stderr
    assert not out.exists()
    resumed = _run_worker(root, out, env_extra=env)
    assert "resume cursor=" in resumed.stdout
    _assert_bitwise_equal(golden, out)
    return resumed


def test_kill_resume_smoke(tmp_path, golden):
    """Tier-1 fast path: one kill point end-to-end (the delta-file/manifest
    commit window), mirroring the bench --dryrun smoke pattern."""
    _kill_resume_roundtrip("store.save_delta.pre_manifest", tmp_path, golden)


@pytest.mark.slow
@pytest.mark.parametrize("point",
                         [p for p in faultpoint.POINTS
                          if p not in ("store.save_delta.pre_manifest",
                                       "remote_ckpt.download.pre")
                          and p not in faultpoint.ELASTIC_POINTS
                          and p not in faultpoint.ADMIT_POINTS
                          and p not in faultpoint.SERVING_POINTS
                          and p not in faultpoint.EXCHANGE_POINTS
                          and p not in faultpoint.MONITOR_POINTS
                          and p not in faultpoint.FLEET_POINTS])
def test_kill_resume_matrix(point, tmp_path, golden):
    """Every registered fault point: kill there, resume, prove bit-identical
    dense params + table rows + metric state vs the uninterrupted run. The
    mid-pass point's resume must come back through the shuffle cursor
    (skip_steps), not a pass replay."""
    resumed = _kill_resume_roundtrip(point, tmp_path, golden)
    if point == "trainer.midpass.post_save":
        assert "(skip 2)" in resumed.stdout, resumed.stdout


@pytest.mark.slow
def test_kill_during_remote_download_resume(tmp_path, golden):
    """remote_ckpt.download.pre fires on the RESUME path: train + mirror,
    wipe the local staging root (replacement host), kill the resume mid
    download, then a THIRD run re-downloads from the donefile and lands
    bit-identical."""
    env = _midpass_remote_env(tmp_path)
    root, out = tmp_path / "root", tmp_path / "out.npz"
    _run_worker(root, tmp_path / "full.npz", env_extra=env)  # mirror built
    killed = _run_worker(
        root, out, check=False,
        env_extra=dict(env, PBTPU_CRASH_WIPE_LOCAL="1",
                       PBTPU_FAULTPOINT="remote_ckpt.download.pre",
                       PBTPU_FAULTPOINT_AFTER="0"))
    assert killed.returncode == 137, (killed.stdout, killed.stderr)
    assert "FAULTPOINT KILL remote_ckpt.download.pre" in killed.stderr
    resumed = _run_worker(root, out,
                          env_extra=dict(env, PBTPU_CRASH_WIPE_LOCAL="1"))
    assert "resume cursor=" in resumed.stdout
    _assert_bitwise_equal(golden, out)


def test_midpass_remote_run_matches_plain_golden(tmp_path, golden):
    """Mid-pass snapshots + the remote mirror are read-only side effects:
    a full run with both on lands the SAME final planes as the plain
    golden (the matrix's license to flip them per point), and the remote
    root ends up holding a donefile + uploaded snapshots."""
    env = _midpass_remote_env(tmp_path)
    out = tmp_path / "out.npz"
    _run_worker(tmp_path / "root", out, env_extra=env)
    _assert_bitwise_equal(golden, out)
    mock_root = tmp_path / "mock_root" / "ck"
    assert (mock_root / "snapshots.donefile").exists()
    assert any(n.startswith("pass-") for n in os.listdir(mock_root))
    assert any(".mid" in n for n in os.listdir(mock_root))


def test_spill_sharded_run_matches_plain_golden(tmp_path, golden):
    """The tier is a storage choice, not a math change: a full run on a
    2-shard ShardedEmbeddingStore with SPILL sub-stores (memmap row
    files, 16-row RAM caches) lands the SAME final planes as the plain
    in-RAM golden — the license for the kill matrix to flip the tiering
    points on that configuration. Also proves the spill-backed shards
    actually ran disk-backed (per-shard row files exist)."""
    env = _spill_env(tmp_path)
    out = tmp_path / "out.npz"
    _run_worker(tmp_path / "root", out, env_extra=env)
    _assert_bitwise_equal(golden, out)
    spill_root = tmp_path / "spill"
    for s in ("shard-00", "shard-01"):
        assert (spill_root / s / "rows.dat").exists()
        assert (spill_root / s / "rows.dat").stat().st_size > 0


def test_tiering_save_ioerror_rolls_back(tmp_path):
    """tiering.save.pre_flush: an IO fault before the spill store's
    memmap flush + streamed payload leaves the chain at the previous
    committed save (the save_delta seq-commit discipline holds for the
    streaming writer too), and the store keeps training afterwards."""
    from paddlebox_tpu.embedding import SpillEmbeddingStore
    cfg = EmbeddingConfig(dim=2)
    st = SpillEmbeddingStore(cfg, spill_dir=str(tmp_path / "sp"),
                             cache_rows=8)
    keys = np.arange(1, 41, dtype=np.uint64)
    rows = st.lookup_or_init(keys)
    rows[:, 0] = 5.0
    st.write_back(keys, rows)
    path = str(tmp_path / "chain")
    st.save_base(path)
    rows = st.get_rows(keys)
    rows[:, 2] = 1.0
    st.write_back(keys, rows)
    st.save_delta(path)                     # committed: seq 1, col2 = 1.0
    rows[:, 2] = 2.0
    st.write_back(keys, rows)
    faultpoint.arm("tiering.save.pre_flush", action="ioerror")
    with pytest.raises(faultpoint.FaultInjected):
        st.save_delta(path)                 # dies before flush + stream
    faultpoint.disarm()
    loaded = HostEmbeddingStore.load(path)
    assert loaded.save_seq == 1
    np.testing.assert_allclose(loaded.get_rows(keys)[:, 2], 1.0)
    # the interrupted save burned no seq: the re-run commits seq 2
    st.save_delta(path)
    loaded2 = HostEmbeddingStore.load(path)
    assert loaded2.save_seq == 2
    np.testing.assert_allclose(loaded2.get_rows(keys)[:, 2], 2.0)


def test_every_point_has_a_matrix_entry():
    """A new crash window cannot be registered without extending the
    kill→resume matrix. The elastic re-formation points fire only inside
    a world shrink — no reform happens in this single-host worker — so
    they are covered by the elastic kill matrix (tests/test_elastic.py)
    instead; the serving publish points fire only in the publish path
    and are covered by the publish/swap kill matrix
    (tests/test_serving.py); the sharded-exchange points fire only in
    the ShardedEmbeddingStore save / eval-overflow-retry paths and are
    covered by tests/test_exchange.py; the telemetry-plane points fire
    only on the JSONL writer thread — telemetry must never perturb
    training state — and are covered by tests/test_doctor.py; the elastic
    ADMIT (world-grow) points fire only in ElasticWorld.admit / the
    post-grow ownership rebind and are covered by the grow kill matrix
    (tests/test_elastic.py + tests/grow_worker.py); the serving-fleet
    points fire only inside the replica-fleet lease/build/dispatch paths
    and are covered by the fleet kill matrix (tests/test_fleet.py). All
    carry the same closed-registry guard."""
    assert (set(POINT_AFTER) | set(faultpoint.ELASTIC_POINTS)
            | set(faultpoint.ADMIT_POINTS)
            | set(faultpoint.SERVING_POINTS)
            | set(faultpoint.EXCHANGE_POINTS)
            | set(faultpoint.MONITOR_POINTS)
            | set(faultpoint.FLEET_POINTS) == set(faultpoint.POINTS))
    assert not set(POINT_AFTER) & (set(faultpoint.ELASTIC_POINTS)
                                   | set(faultpoint.ADMIT_POINTS)
                                   | set(faultpoint.SERVING_POINTS)
                                   | set(faultpoint.EXCHANGE_POINTS)
                                   | set(faultpoint.MONITOR_POINTS)
                                   | set(faultpoint.FLEET_POINTS))


# ---------------------------------------------------------------------------
# in-process: atomic writes + corrupt-chain diagnosis
# ---------------------------------------------------------------------------

def test_atomic_save_pytree_never_tears(tmp_path):
    """An IO fault between the durable tmp write and the rename leaves the
    previous complete file under the final name."""
    f = str(tmp_path / "dense.npz")
    ckpt_lib.save_pytree({"w": np.arange(4.0, dtype=np.float32)}, f)
    faultpoint.arm("ckpt.dense.pre_replace", action="ioerror")
    with pytest.raises(faultpoint.FaultInjected):
        ckpt_lib.save_pytree({"w": np.zeros(4, np.float32)}, f)
    faultpoint.disarm()
    got = ckpt_lib.load_pytree({"w": np.zeros(4, np.float32)}, f)
    np.testing.assert_array_equal(np.asarray(got["w"]),
                                  np.arange(4.0, dtype=np.float32))
    # the failed writer cleaned its temp file up
    assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]


def test_load_pytree_corrupt_names_file(tmp_path):
    f = str(tmp_path / "dense.npz")
    ckpt_lib.save_pytree({"w": np.arange(64.0, dtype=np.float32)}, f)
    raw = open(f, "rb").read()
    with open(f, "wb") as fh:
        fh.write(raw[:len(raw) // 2])      # truncate
    with pytest.raises(CheckpointCorruptError, match="dense.npz"):
        ckpt_lib.load_pytree({"w": np.zeros(64, np.float32)}, f)
    with open(f, "wb") as fh:              # not a zip at all
        fh.write(b"garbage" * 10)
    with pytest.raises(CheckpointCorruptError, match="dense.npz"):
        ckpt_lib.load_pytree({"w": np.zeros(64, np.float32)}, f)


def _trained_store(tmp_path, n=40):
    cfg = EmbeddingConfig(dim=2)
    store = HostEmbeddingStore(cfg)
    keys = np.arange(1, n + 1, dtype=np.uint64)
    rows = store.lookup_or_init(keys)
    rows[:, 0] = 5.0
    store.write_back(keys, rows)
    return store, keys


def test_corrupt_mid_chain_delta_fails_loudly(tmp_path):
    """A truncated mid-chain delta must raise with the manifest diagnosis
    (file name + chain position), never half-replay."""
    store, keys = _trained_store(tmp_path)
    path = str(tmp_path / "sp")
    store.save_base(path)
    for v in (1.0, 2.0):
        rows = store.get_rows(keys)
        rows[:, 2] = v
        store.write_back(keys, rows)
        store.save_delta(path)
    d1 = os.path.join(path, "delta-00001.npz")
    raw = open(d1, "rb").read()
    with open(d1, "wb") as f:
        f.write(raw[:-20])
    with pytest.raises(CheckpointCorruptError) as ei:
        HostEmbeddingStore.load(path)
    msg = str(ei.value)
    assert "delta-00001.npz" in msg and "position" in msg
    # same-size bit-rot must be caught by the CRC, not just the size check
    flipped = bytearray(raw)
    flipped[len(raw) // 2] ^= 0xFF
    with open(d1, "wb") as f:
        f.write(bytes(flipped))
    with pytest.raises(CheckpointCorruptError, match="crc32"):
        HostEmbeddingStore.load(path)
    # a missing mid-chain member is equally loud
    with open(d1, "wb") as f:
        f.write(raw)                       # restore bytes…
    os.remove(os.path.join(path, "delta-00002.npz"))
    with pytest.raises(CheckpointCorruptError, match="delta-00002"):
        HostEmbeddingStore.load(path)


def test_tombstones_survive_chain_fallback(tmp_path):
    """Falling back to an earlier save_seq must not resurrect keys whose
    tombstone rode a delta inside the replayed prefix."""
    store, keys = _trained_store(tmp_path)
    path = str(tmp_path / "sp")
    store.save_base(path)
    store.shrink(min_show=10.0)            # evicts everything (show=5)
    assert len(store) == 0
    live = store.lookup_or_init(keys[:3])  # re-create 3 keys
    live[:, 2] = 7.0
    store.write_back(keys[:3], live)
    store.save_delta(path)                 # delta-1: tombstones + 3 rows
    rows = store.get_rows(keys[:3])
    rows[:, 2] = 9.0
    store.write_back(keys[:3], rows)
    store.save_delta(path)                 # delta-2
    # fallback horizon = seq 1 (as a snapshot committed at seq 1 records)
    loaded = HostEmbeddingStore.load(path, upto_seq=1)
    assert len(loaded) == 3                # evicted keys stayed dead
    np.testing.assert_allclose(loaded.get_rows(keys[:3])[:, 2], 7.0)
    # full replay sees delta-2's values
    loaded2 = HostEmbeddingStore.load(path)
    np.testing.assert_allclose(loaded2.get_rows(keys[:3])[:, 2], 9.0)


def test_chain_manifest_records_parents(tmp_path):
    store, keys = _trained_store(tmp_path)
    path = str(tmp_path / "sp")
    store.save_base(path, pass_id=1)
    rows = store.get_rows(keys)
    rows[:, 2] = 1.0
    store.write_back(keys, rows)
    store.save_delta(path, pass_id=2)
    m = ckpt_lib.read_manifest(path)
    assert m["chain"] == ["base.npz", "delta-00001.npz"]
    assert m["files"]["base.npz"]["parent"] is None
    assert m["files"]["delta-00001.npz"]["parent"] == "base.npz"
    assert m["pass_id"] == 2 and m["save_seq"] == 1
    for name in ("base.npz", "delta-00001.npz", "meta.json"):
        assert m["files"][name]["bytes"] == os.path.getsize(
            os.path.join(path, name))


# ---------------------------------------------------------------------------
# in-process: PassCheckpointer snapshot fallback + retention
# ---------------------------------------------------------------------------

def _tiny_trainer(seed=7):
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig
    from tests.crash_worker import NUM_SLOTS, synth
    ds, schema = synth(n=128)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4, learning_rate=0.05))
    tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                             hidden=(8,)),
                 store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=64, auc_buckets=1 << 8),
                 seed=seed)
    return ds, tr, store


def test_truncated_newest_snapshot_falls_back(tmp_path):
    """Acceptance: a deliberately truncated newest snapshot is detected by
    checksum and resume restores the previous good one."""
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds, tr, store = _tiny_trainer()
    box = BoxPS(store)
    ckpt = PassCheckpointer(str(tmp_path / "ck"), keep_last_n=2,
                            base_every=4)
    import jax
    state_after = {}
    for p in (1, 2):
        box.begin_pass()
        tr.train_pass(ds)
        box.end_pass(checkpointer=ckpt, trainer=tr)
        tr.flush_sparse()
        keys = np.sort(np.asarray(ds.unique_keys(), np.uint64))
        state_after[p] = (keys, store.get_rows(keys),
                          jax.tree.map(np.asarray, tr.params),
                          tr.global_step)
    # truncate pass-2's dense plane, keeping its manifest intact: only the
    # recorded size/CRC can catch this
    dense2 = os.path.join(ckpt.snap_dir(2), "dense.npz")
    raw = open(dense2, "rb").read()
    with open(dense2, "wb") as f:
        f.write(raw[:-32])
    with pytest.warns(UserWarning, match="failed verification"):
        found = ckpt.latest_valid()
    assert found is not None and found[0] == 1

    ds2, tr2, store2 = _tiny_trainer(seed=99)  # different init: must be
    box2 = BoxPS(store2)                       # overwritten by the restore
    ck2 = PassCheckpointer(str(tmp_path / "ck"), keep_last_n=2,
                           base_every=4)
    with pytest.warns(UserWarning, match="failed verification"):
        cursor = tr2.resume(ck2, box=box2)
    assert cursor["pass_id"] == 1 and box2.pass_id == 1
    assert tr2.global_step == state_after[1][3]
    keys, rows, params, _ = state_after[1]
    np.testing.assert_array_equal(store2.get_rows(keys), rows)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        tr2.params, params)


def test_retention_keeps_last_n_and_referenced_chains(tmp_path):
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds, tr, store = _tiny_trainer()
    box = BoxPS(store)
    root = str(tmp_path / "ck")
    ckpt = PassCheckpointer(root, keep_last_n=2, base_every=2)
    for _ in range(5):
        box.begin_pass()
        tr.train_pass(ds)
        box.end_pass(checkpointer=ckpt, trainer=tr)
    snaps = sorted(n for n in os.listdir(root) if n.startswith("pass-"))
    assert snaps == ["pass-00004", "pass-00005"]
    chains = sorted(n for n in os.listdir(root) if n.startswith("chain-"))
    referenced = {ckpt_lib.read_manifest(os.path.join(root, s))["chain_dir"]
                  for s in snaps}
    assert set(chains) == referenced
    # every survivor still verifies end-to-end
    assert ckpt.latest_valid()[0] == 5


def test_resume_with_no_snapshots_returns_none(tmp_path):
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds, tr, store = _tiny_trainer()
    ck = PassCheckpointer(str(tmp_path / "empty"))
    assert tr.resume(ck, box=BoxPS(store)) is None


def test_faultpoint_registry_guards():
    with pytest.raises(KeyError):
        faultpoint.arm("not.a.point")
    with pytest.raises(ValueError):
        faultpoint.arm("ckpt.dense.pre_replace", action="explode")
    faultpoint.arm("ckpt.dense.pre_replace", action="ioerror", after=1)
    faultpoint.hit("ckpt.dense.pre_replace")   # skipped (after=1)
    with pytest.raises(faultpoint.FaultInjected):
        faultpoint.hit("ckpt.dense.pre_replace")


def test_delta_crash_before_manifest_resumes_previous_save(tmp_path):
    """The chain MANIFEST is the commit record: a save_delta that dies
    after writing the delta file + meta but BEFORE the manifest commit
    must leave a directory that load() resumes at the PREVIOUS save —
    not one that fails verification (the no-PassCheckpointer
    end_pass(need_save_delta) flow has nothing else to fall back to)."""
    store, keys = _trained_store(tmp_path)
    path = str(tmp_path / "sp")
    store.save_base(path)
    rows = store.get_rows(keys)
    rows[:, 2] = 1.0
    store.write_back(keys, rows)
    store.save_delta(path)                 # committed: seq 1, rows at 1.0
    rows[:, 2] = 2.0
    store.write_back(keys, rows)
    faultpoint.arm("store.save_delta.pre_manifest", action="ioerror")
    with pytest.raises(faultpoint.FaultInjected):
        store.save_delta(path)             # delta-2 + meta land, no commit
    faultpoint.disarm()
    loaded = HostEmbeddingStore.load(path)
    assert loaded.save_seq == 1            # manifest horizon, not meta's 2
    np.testing.assert_allclose(loaded.get_rows(keys)[:, 2], 1.0)


def test_foreign_save_between_snapshots_forces_base_rotation(tmp_path):
    """A FleetUtil-style save_delta on the shared store between two
    checkpointer saves consumes the dirty mask — the next snapshot must
    rotate to a full base (a delta into the open chain would silently
    miss those rows) and resume must still restore the exact state."""
    import jax
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds, tr, store = _tiny_trainer()
    box = BoxPS(store)
    ckpt = PassCheckpointer(str(tmp_path / "ck"), keep_last_n=3,
                            base_every=8)
    box.begin_pass(); tr.train_pass(ds)
    box.end_pass(checkpointer=ckpt, trainer=tr)       # base (chain-0001)
    box.begin_pass(); tr.train_pass(ds)
    # foreign writer: a fleet-style delta into its own dir, mid-lifecycle
    store.save_delta(str(tmp_path / "fleet_delta"))
    box.end_pass(checkpointer=ckpt, trainer=tr)       # must rotate
    m = ckpt_lib.read_manifest(ckpt.snap_dir(2))
    assert m["chain_dir"] == "chain-0002"             # fresh base, seq 0
    assert m["save_seq"] == 0
    tr.flush_sparse()
    keys = np.sort(np.asarray(ds.unique_keys(), np.uint64))
    want = store.get_rows(keys)
    want_params = jax.tree.map(np.asarray, tr.params)

    ds2, tr2, store2 = _tiny_trainer(seed=42)
    cursor = tr2.resume(PassCheckpointer(str(tmp_path / "ck")),
                        box=BoxPS(store2))
    assert cursor["pass_id"] == 2
    np.testing.assert_array_equal(store2.get_rows(keys), want)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        tr2.params, want_params)


def test_foreign_save_base_with_eviction_forces_rotation(tmp_path):
    """A foreign save_base resets store.save_seq to 0 — aliasing with
    'nothing happened' right after our own base. The monotonic save_count
    guard must still rotate, or the next snapshot's delta silently drops
    the eviction the foreign base consumed (confirmed divergence repro
    from review)."""
    import jax
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds, tr, store = _tiny_trainer()
    box = BoxPS(store)
    ckpt = PassCheckpointer(str(tmp_path / "ck"), keep_last_n=3,
                            base_every=8)
    box.begin_pass(); tr.train_pass(ds)
    box.end_pass(checkpointer=ckpt, trainer=tr)       # base, seq 0
    box.begin_pass(); tr.train_pass(ds)
    store.shrink(min_show=1e9)                        # evict everything
    store.save_base(str(tmp_path / "fleet_base"))     # foreign: seq -> 0
    box.end_pass(checkpointer=ckpt, trainer=tr)
    m = ckpt_lib.read_manifest(ckpt.snap_dir(2))
    assert m["chain_dir"] == "chain-0002"             # rotated, not delta
    keys = np.sort(np.asarray(ds.unique_keys(), np.uint64))

    ds2, tr2, store2 = _tiny_trainer(seed=42)
    cursor = tr2.resume(PassCheckpointer(str(tmp_path / "ck")),
                        box=BoxPS(store2))
    assert cursor["pass_id"] == 2
    assert len(store2) == len(store)                  # evictions honored
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        tr2.params, jax.tree.map(np.asarray, tr.params))


def test_failed_save_leaves_checkpointer_consistent(tmp_path):
    """A transient IO failure inside a snapshot save must not corrupt the
    checkpointer's chain state (a half-open baseless chain) or burn a
    delta sequence number (a permanent mid-chain gap): the NEXT save must
    succeed and produce a fully restorable snapshot."""
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds, tr, store = _tiny_trainer()
    box = BoxPS(store)
    ckpt = PassCheckpointer(str(tmp_path / "ck"), keep_last_n=2,
                            base_every=8)
    box.begin_pass(); tr.train_pass(ds)
    box.end_pass(checkpointer=ckpt, trainer=tr)           # base ok
    box.begin_pass(); tr.train_pass(ds)
    faultpoint.arm("store.save_delta.pre_replace", action="ioerror")
    with pytest.raises(faultpoint.FaultInjected):
        box.end_pass(checkpointer=ckpt, trainer=tr)       # delta fails
    faultpoint.disarm()
    # failed rotation case too: force a rotation failure on a fresh chain
    ck2 = PassCheckpointer(str(tmp_path / "ck2"), keep_last_n=2)
    faultpoint.arm("store.save_base.pre_replace", action="ioerror")
    with pytest.raises(faultpoint.FaultInjected):
        ck2.save(tr, pass_id=1)
    faultpoint.disarm()
    # both checkpointers recover on the next save, end to end
    snap = ckpt.save(tr, box=box, metrics=box.metrics, pass_id=2)
    assert ckpt_lib.read_manifest(snap) is not None
    snap2 = ck2.save(tr, pass_id=1)
    assert ckpt_lib.read_manifest(snap2) is not None
    ds2, tr2, store2 = _tiny_trainer(seed=42)
    cursor = tr2.resume(PassCheckpointer(str(tmp_path / "ck")),
                        box=BoxPS(store2))
    assert cursor["pass_id"] == 2
    keys = np.sort(np.asarray(ds.unique_keys(), np.uint64))
    tr.flush_sparse()
    np.testing.assert_array_equal(store2.get_rows(keys),
                                  store.get_rows(keys))


def test_prune_tolerates_corrupt_old_snapshot_manifest(tmp_path):
    """Bit rot in a RETAINED (non-newest) snapshot's manifest must not
    make later saves raise — resume already skips it; prune must too."""
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds, tr, store = _tiny_trainer()
    box = BoxPS(store)
    ckpt = PassCheckpointer(str(tmp_path / "ck"), keep_last_n=3,
                            base_every=8)
    for _ in range(2):
        box.begin_pass(); tr.train_pass(ds)
        box.end_pass(checkpointer=ckpt, trainer=tr)
    with open(os.path.join(ckpt.snap_dir(1), "MANIFEST.json"), "w") as f:
        f.write("{ not json")
    box.begin_pass(); tr.train_pass(ds)
    out = box.end_pass(checkpointer=ckpt, trainer=tr)     # must not raise
    assert ckpt_lib.read_manifest(out["snapshot"]) is not None
    assert ckpt.latest_valid()[0] == 3
