"""Golden numeric parity: the framework's full train step vs the
pure-NumPy reference in golden_deepfm.py (VERDICT r2 missing #1).

Every other correctness test validates the framework against itself; this
one trains the SAME DeepFM+adagrad+CVM+adam configuration for 60 steps in
both implementations and asserts the per-step loss trajectory and the
final sparse-table / dense-param state agree to floating-point tolerance
— a systematic numeric error anywhere in the jitted step (scaling,
column wiring, optimizer slots) diverges the trajectories. The OpTest
pattern (op_test.py) applied to the whole step, on f32 AND int16 device
storage.
"""

import numpy as np
import pytest

import jax

from paddlebox_tpu.data import DataFeedSchema
from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                     PassWorkingSet)
from paddlebox_tpu.models import DeepFMModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig

from tests.golden_deepfm import GoldenDeepFM, splitmix_init_rows

NUM_SLOTS, EMB_DIM, DENSE_DIM = 4, 4, 3
HIDDEN = (16, 16)
BATCH, STEPS, N_KEYS = 32, 60, 300


def _run_pair(storage, golden_lr_mult=1.0):
    cfg = EmbeddingConfig(dim=EMB_DIM, optimizer="adagrad",
                          learning_rate=0.05, storage=storage)
    store = HostEmbeddingStore(cfg)
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=DENSE_DIM,
                                batch_size=BATCH, max_len=1)
    mesh = make_mesh(1)
    tr = Trainer(DeepFMModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM,
                             dense_dim=DENSE_DIM, hidden=HIDDEN),
                 store, schema, mesh, TrainerConfig(global_batch_size=BATCH))
    rng = np.random.default_rng(7)
    keys = np.unique(rng.choice(1 << 40, N_KEYS).astype(np.uint64))
    ws = PassWorkingSet.begin_pass(store, keys, mesh)

    # independent init cross-check: the golden recomputes the
    # deterministic splitmix row init from the documented formula
    gold_rows = splitmix_init_rows(ws.sorted_keys, cfg.row_width,
                                   3, 3 + EMB_DIM, cfg.initial_range)
    n_pad = ws.padded_rows
    gold_table = np.zeros((n_pad, cfg.row_width), np.float32)
    gold_table[1:1 + len(keys)] = gold_rows
    if storage == "f32":
        np.testing.assert_array_equal(np.asarray(ws.table), gold_table)

    init_params = jax.tree.map(np.asarray, tr.params)
    gold = GoldenDeepFM(gold_table, init_params, NUM_SLOTS, EMB_DIM,
                        DENSE_DIM, HIDDEN,
                        lr_sparse=cfg.learning_rate * golden_lr_mult,
                        initial_g2sum=cfg.initial_g2sum,
                        dense_lr=tr.cfg.dense_lr, storage=storage)

    table, dstate = ws.table, tr.pack_dense()
    fw_losses, gold_losses = [], []
    for step in range(STEPS):
        raw = rng.choice(keys, size=(BATCH, NUM_SLOTS))
        mask = rng.random((BATCH, NUM_SLOTS)) < 0.9   # some padding
        idx = ws.translate(raw, mask)
        # independent translate cross-check: sorted-keys searchsorted + 1
        pos = np.searchsorted(ws.sorted_keys, raw.astype(np.uint64))
        gold_idx = np.where(mask, pos + 1, 0).astype(np.int32)
        np.testing.assert_array_equal(idx, gold_idx)
        dense = rng.normal(size=(BATCH, DENSE_DIM)).astype(np.float32)
        labels = (rng.random(BATCH) < 0.3).astype(np.float32)
        out = tr._step_fn(table, *dstate, idx, mask, dense, labels,
                          tr.NO_PLAN, tr.NO_PLAN, tr.NO_PLAN)
        table, dstate, loss, _, _ = tr.split_step_out(out)
        fw_losses.append(float(loss))
        gold_losses.append(gold.step(idx, mask, dense, labels))
    params = tr.unpack_dense(dstate)[0]
    return np.array(fw_losses), np.array(gold_losses), table, params, gold


@pytest.mark.parametrize("storage", ["f32", "int16"])
def test_trajectory_parity(storage):
    fw, gold, table, params, g = _run_pair(storage)
    # per-step loss trajectory: fp reassociation differs (XLA fuses),
    # systematic errors (a factor on sparse grads, a column off-by-one)
    # blow past this within a few steps
    np.testing.assert_allclose(fw, gold, rtol=2e-4, atol=2e-5)
    # final state parity
    from paddlebox_tpu.embedding import quant
    if quant.is_quant(table):
        fw_table = quant.decode_rows_np(
            np.asarray(table.fp), np.asarray(table.qx),
            EmbeddingConfig(dim=EMB_DIM, optimizer="adagrad",
                            learning_rate=0.05, storage=storage))
    else:
        fw_table = np.asarray(table)[:, :g.table.shape[1]]
    np.testing.assert_allclose(fw_table, g.table, rtol=1e-3, atol=2e-5)
    fw_params = jax.tree.map(np.asarray, params)
    for got, want in ((fw_params["bias"], g.params["bias"]),
                      (fw_params.get("wide_dense"),
                       g.params.get("wide_dense"))):
        if want is not None:
            np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-5)
    for i, layer in enumerate(fw_params["mlp"]):
        np.testing.assert_allclose(layer["w"], g.params["mlp"][i]["w"],
                                   rtol=2e-3, atol=2e-5)
        np.testing.assert_allclose(layer["b"], g.params["mlp"][i]["b"],
                                   rtol=2e-3, atol=2e-5)


def test_detects_systematic_error():
    """Teeth check: a real systematic deviation must blow the parity
    tolerance. A 2x factor on the sparse learning rate (equivalent to a
    2x sparse-grad bug) is injected into the GOLDEN side only; the
    trajectories must diverge beyond what test_trajectory_parity
    accepts — otherwise the harness could never catch the class of bug
    it exists for."""
    fw, gold, *_ = _run_pair("f32", golden_lr_mult=2.0)
    with pytest.raises(AssertionError):
        np.testing.assert_allclose(fw, gold, rtol=2e-4, atol=2e-5)
