"""Golden numeric parity: the framework's full train step vs the
pure-NumPy reference in golden_deepfm.py (VERDICT r2 missing #1).

Every other correctness test validates the framework against itself; this
one trains the SAME DeepFM+adagrad+CVM+adam configuration for 60 steps in
both implementations and asserts the per-step loss trajectory and the
final sparse-table / dense-param state agree to floating-point tolerance
— a systematic numeric error anywhere in the jitted step (scaling,
column wiring, optimizer slots) diverges the trajectories. The OpTest
pattern (op_test.py) applied to the whole step, on f32 AND int16 device
storage.
"""

import numpy as np
import pytest

import jax

from paddlebox_tpu.data import DataFeedSchema
from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                     PassWorkingSet)
from paddlebox_tpu.models import DeepFMModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig

from tests.golden_deepfm import GoldenDeepFM, splitmix_init_rows

NUM_SLOTS, EMB_DIM, DENSE_DIM = 4, 4, 3
HIDDEN = (16, 16)
BATCH, STEPS, N_KEYS = 32, 60, 300


def _run_pair(storage, mode="allreduce", n_dev=1, golden_lr_mult=1.0,
              sync_step=7, emb_dim=EMB_DIM, max_len=1):
    """Train STEPS batches through the real Trainer step in the given
    dense-sync mode / shard count AND through the NumPy twin; return the
    loss trajectories + final states.

    - allreduce: the bench headline config (flat dense transport).
    - kstep: per-step local dense updates, _sync_fn every `sync_step`
      steps plus at the end (trainer Finalize) — on one device the sync
      is a numeric identity, so the golden adam trajectory must be
      reproduced THROUGH the kstep plumbing (stacked params, sync calls).
    - async: the host AsyncDenseTable (pull -> device step -> push grads)
      with a flush() after every push so exactly one grad applies per
      step — the deterministic projection of the reference's
      ThreadUpdate merge loop (boxps_worker.cc:173-225); the golden
      applies the same no-bias-correction 0.99/0.9999 rule.
    - n_dev=8: the routed mesh path (all_to_all sparse lookup/push, dp
      grad pmean) against the SAME single-table golden — routing must be
      semantics-preserving.
    """
    from paddlebox_tpu.parallel import mesh as mesh_lib

    cfg = EmbeddingConfig(dim=emb_dim, optimizer="adagrad",
                          learning_rate=0.05, storage=storage)
    store = HostEmbeddingStore(cfg)
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=DENSE_DIM,
                                batch_size=BATCH, max_len=max_len)
    mesh = make_mesh(n_dev)
    tr = Trainer(DeepFMModel(num_slots=NUM_SLOTS, emb_dim=emb_dim,
                             dense_dim=DENSE_DIM, hidden=HIDDEN),
                 store, schema, mesh,
                 TrainerConfig(global_batch_size=BATCH,
                               dense_sync_mode=mode,
                               param_sync_step=sync_step,
                               # mesh8: uniform keys over 8 shards at
                               # batch 32 can exceed the default 2.0
                               # slack; any drop would desync the golden
                               capacity_factor=8.0 if n_dev > 1 else 2.0))
    rng = np.random.default_rng(7)
    keys = np.unique(rng.choice(1 << 40, N_KEYS).astype(np.uint64))
    ws = PassWorkingSet.begin_pass(store, keys, mesh)

    # independent init cross-check: the golden recomputes the
    # deterministic splitmix row init from the documented formula
    gold_rows = splitmix_init_rows(ws.sorted_keys, cfg.row_width,
                                   3, 3 + emb_dim, cfg.initial_range)
    n_pad = ws.padded_rows
    gold_table = np.zeros((n_pad, cfg.row_width), np.float32)
    gold_table[1:1 + len(keys)] = gold_rows
    if storage == "f32" and n_dev == 1:
        np.testing.assert_array_equal(np.asarray(ws.table), gold_table)

    init_params = jax.tree.map(np.asarray, tr.params)
    if mode == "kstep":
        # kstep keeps per-shard dense copies (stack_for_shards leading
        # axis); the golden models one logical copy
        init_params = jax.tree.map(lambda a: a[0], init_params)
    gold = GoldenDeepFM(gold_table, init_params, NUM_SLOTS, emb_dim,
                        DENSE_DIM, HIDDEN, max_len=max_len,
                        lr_sparse=cfg.learning_rate * golden_lr_mult,
                        initial_g2sum=cfg.initial_g2sum,
                        dense_lr=tr.cfg.dense_lr, storage=storage,
                        dense_opt=("async_merge" if mode == "async"
                                   else "adam"))

    sh = mesh_lib.batch_sharding(mesh)
    repl = mesh_lib.replicated_sharding(mesh)
    table = ws.table
    dstate = tr.pack_dense() if mode == "allreduce" else None
    params, opt = tr.params, tr.opt_state
    if mode == "async":
        tr.dense_table.start()
    fw_losses, gold_losses = [], []
    for step in range(STEPS):
        T = NUM_SLOTS * max_len
        raw = rng.choice(keys, size=(BATCH, T))
        mask = rng.random((BATCH, T)) < 0.9       # some padding
        idx = ws.translate(raw, mask)
        if n_dev == 1:
            # independent translate cross-check: searchsorted + 1
            pos = np.searchsorted(ws.sorted_keys, raw.astype(np.uint64))
            gold_idx = np.where(mask, pos + 1, 0).astype(np.int32)
            np.testing.assert_array_equal(idx, gold_idx)
        dense = rng.normal(size=(BATCH, DENSE_DIM)).astype(np.float32)
        labels = (rng.random(BATCH) < 0.3).astype(np.float32)
        batch = tuple(jax.device_put(a, sh) for a in
                      (idx, mask, dense, labels)) + \
            (tr.NO_PLAN,) * 5
        if mode == "async":
            p = jax.device_put(tr._unravel(tr.dense_table.pull()), repl)
            table, gp_flat, loss, _, dropped = tr._step_fn(
                table, p, *batch)
            tr.dense_table.push(np.asarray(gp_flat))
            tr.dense_table.flush()      # deterministic: 1 grad per apply
        elif mode == "kstep":
            table, params, opt, loss, _, dropped = tr._step_fn(
                table, params, opt, *batch)
            if (step + 1) % sync_step == 0:
                params, opt = tr._sync_fn(params, opt)
        else:
            out = tr._step_fn(table, *dstate, *batch)
            table, dstate, loss, _, dropped = tr.split_step_out(out)
        if n_dev > 1:
            assert int(np.asarray(dropped).sum()) == 0, \
                "routed capacity drop would desync the golden trajectory"
        fw_losses.append(float(loss))
        gold_losses.append(gold.step(idx, mask, dense, labels))
    if mode == "async":
        fin = jax.tree.map(np.asarray,
                           tr._unravel(tr.dense_table.pull()))
        tr.dense_table.stop()
        params = fin
    elif mode == "kstep":
        params, opt = tr._sync_fn(params, opt)   # trainer Finalize
        params = jax.tree.map(lambda a: np.asarray(a)[0], params)
    else:
        params = tr.unpack_dense(dstate)[0]
    return np.array(fw_losses), np.array(gold_losses), table, params, gold


@pytest.mark.parametrize("mode", ["allreduce", "kstep", "async"])
@pytest.mark.parametrize("storage", ["f32", "int16", "int8"])
def test_trajectory_parity(storage, mode):
    fw, gold, table, params, g = _run_pair(storage, mode=mode)
    # per-step loss trajectory: fp reassociation differs (XLA fuses),
    # systematic errors (a factor on sparse grads, a column off-by-one)
    # blow past this within a few steps
    np.testing.assert_allclose(fw, gold, rtol=2e-4, atol=2e-5)
    # final state parity
    from paddlebox_tpu.embedding import quant
    if quant.is_quant(table):
        fw_table = quant.decode_rows_np(
            np.asarray(table.fp), np.asarray(table.qx),
            EmbeddingConfig(dim=EMB_DIM, optimizer="adagrad",
                            learning_rate=0.05, storage=storage))
    else:
        fw_table = np.asarray(table)[:, :g.table.shape[1]]
    np.testing.assert_allclose(fw_table, g.table, rtol=1e-3, atol=2e-5)
    fw_params = jax.tree.map(np.asarray, params)
    for got, want in ((fw_params["bias"], g.params["bias"]),
                      (fw_params.get("wide_dense"),
                       g.params.get("wide_dense"))):
        if want is not None:
            np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-5)
    for i, layer in enumerate(fw_params["mlp"]):
        np.testing.assert_allclose(layer["w"], g.params["mlp"][i]["w"],
                                   rtol=2e-3, atol=2e-5)
        np.testing.assert_allclose(layer["b"], g.params["mlp"][i]["b"],
                                   rtol=2e-3, atol=2e-5)


def test_trajectory_parity_mesh8_routed():
    """The 8-shard routed path (all_to_all sparse lookup/push, dp-mean
    dense grads) against the SAME single-table NumPy golden: sharding
    must be a pure layout choice with no numeric consequence beyond fp
    reassociation (the reference's multi-GPU PullSparse/PushSparse
    contract, box_wrapper_impl.h:44-81)."""
    fw, gold, table, params, g = _run_pair("f32", n_dev=8)
    np.testing.assert_allclose(fw, gold, rtol=5e-4, atol=5e-5)
    fw_table = np.asarray(table)[:, :g.table.shape[1]]
    np.testing.assert_allclose(fw_table, g.table, rtol=2e-3, atol=5e-5)


def test_trajectory_parity_multihot4():
    """Multi-hot golden (VERDICT r4 weak #5): max_len=4 through the
    seqpool sum + pad masking — the pooling forward AND its broadcast
    backward (every token receives the slot grad) against the NumPy
    twin. The single-hot golden never touches this path."""
    fw, gold, table, params, g = _run_pair("f32", max_len=4)
    np.testing.assert_allclose(fw, gold, rtol=3e-4, atol=3e-5)
    fw_table = np.asarray(table)[:, :g.table.shape[1]]
    np.testing.assert_allclose(fw_table, g.table, rtol=2e-3, atol=3e-5)


def test_trajectory_parity_dim64_scatter():
    """Wide-dim golden (VERDICT r4 weak #5): dim 64 runs the
    scatter-engine push (G=1 — no binned kernel) and, on TPU, the
    merge_update consumer; the dim-4 golden never exercises the wide
    row layout or that dispatch."""
    fw, gold, table, params, g = _run_pair("f32", emb_dim=64)
    np.testing.assert_allclose(fw, gold, rtol=3e-4, atol=3e-5)
    fw_table = np.asarray(table)[:, :g.table.shape[1]]
    np.testing.assert_allclose(fw_table, g.table, rtol=2e-3, atol=3e-5)


def test_detects_systematic_error():
    """Teeth check: a real systematic deviation must blow the parity
    tolerance. A 2x factor on the sparse learning rate (equivalent to a
    2x sparse-grad bug) is injected into the GOLDEN side only; the
    trajectories must diverge beyond what test_trajectory_parity
    accepts — otherwise the harness could never catch the class of bug
    it exists for."""
    fw, gold, *_ = _run_pair("f32", golden_lr_mult=2.0)
    with pytest.raises(AssertionError):
        np.testing.assert_allclose(fw, gold, rtol=2e-4, atol=2e-5)
