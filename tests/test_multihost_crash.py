"""Multi-host kill→resume matrix + stalled-peer watchdog (ISSUE 5).

Two real worker processes (tests/multihost_crash_worker.py via the
launcher) train per-rank shards in lockstep over the FileStore control
plane, snapshotting per pass (and mid-pass). The acceptance bar:

- hard-kill one rank at every registered fault point (including a
  MID-pass kill and the remote upload/download points on an
  hdfs://-schemed root), restart the world, and prove the coordinated
  election lands every rank on the SAME cursor and the resumed world's
  final dense+sparse+metric planes are bit-identical to an uninterrupted
  2-worker run (per rank);
- a stalled (hung, not dead) peer surfaces a named-rank
  PeerStalledError + a ``peer_stalled`` telemetry event on the observing
  rank — never an opaque barrier timeout.

One election smoke runs in tier-1 (the CI satellite); the full matrix and
the hang scenario are ``slow``.
"""

import json
import os
import sys

import numpy as np
import pytest

from paddlebox_tpu.distributed.launch import launch
from paddlebox_tpu.utils import faultpoint

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(TESTS_DIR, "multihost_crash_worker.py")
WORLD = 2

# mirrors test_crash_safety.POINT_AFTER for the multi-host worker's
# cadence (3 passes x 4 steps, mid-pass snapshots every 2 steps, remote
# mirror on): the counts just need the armed kill to FIRE mid-run; the
# election + parity assertions carry the correctness burden.
POINT_AFTER = {
    "ckpt.dense.pre_replace": 2,
    "store.save_base.pre_replace": 1,
    "store.save_delta.pre_replace": 1,
    "store.save_delta.pre_manifest": 1,
    "feed_pass.flush.pre": 3,
    # ISSUE 14: the incremental delta feed fires at every reuse
    # boundary (pass >= 2 begin_pass); AFTER=1 kills rank 1 at its
    # pass-3 boundary, after a pass-2 snapshot exists
    "feed_pass.delta_stage.pre": 1,
    "trainer.push_apply.pre": 6,
    "pass_ckpt.pre_manifest": 3,
    "pass_ckpt.post_manifest": 3,
    "trainer.midpass.post_save": 2,     # pass-2's first mid-pass snapshot
    "remote_ckpt.upload.pre": 4,
    "trainer.pack.pre": 5,              # pass-2 pack (producer thread)
    "trainer.step.pre": 5,              # pass-2 step dispatch
}


def _env(tmp_path, extra=None, remote=True, midpass=2):
    env = {
        "PBTPU_TEST_WORKDIR": str(tmp_path / "work"),
        "PBTPU_CRASH_ROOT": str(tmp_path / "snaps"),
    }
    if midpass:
        env["PBTPU_CRASH_MIDPASS"] = str(midpass)
    if remote:
        env["PBTPU_MOCKFS_ROOT"] = str(tmp_path / "mock_root")
        env["PBTPU_MOCKFS_SCHEME"] = "hdfs"
        env["PBTPU_CRASH_REMOTE_BASE"] = "hdfs://snaps"
    env.update(extra or {})
    os.makedirs(env["PBTPU_TEST_WORKDIR"], exist_ok=True)
    return env


def _launch(tmp_path, env):
    return launch(WORLD, [sys.executable, WORKER],
                  store_dir=str(tmp_path / "store"), base_env=env)


def _load_outs(tmp_path):
    outs = []
    for r in range(WORLD):
        p = tmp_path / "work" / f"out_{r}.npz"
        assert p.exists(), f"rank {r} produced no final dump"
        with np.load(p) as z:
            outs.append({k: z[k] for k in z.files})
    return outs


def _resume_info(tmp_path):
    infos = []
    for r in range(WORLD):
        with open(tmp_path / "work" / f"resume_{r}.json") as f:
            infos.append(json.load(f))
    return infos


def _events(tmp_path, rank):
    p = tmp_path / "work" / f"events_{rank}.jsonl"
    if not p.exists():
        return []
    return [json.loads(ln) for ln in p.read_text().splitlines() if ln]


@pytest.fixture(scope="module")
def golden(tmp_path_factory):
    """Uninterrupted 2-worker run (plain local roots, no mid-pass /
    remote — those are proven state-neutral in test_crash_safety) →
    per-rank final npz."""
    d = tmp_path_factory.mktemp("mh_golden")
    env = _env(d, remote=False, midpass=0)
    code = _launch(d, env)
    assert code == 0, f"golden multihost run failed ({code})"
    return _load_outs(d)


def _assert_world_parity(golden, tmp_path):
    outs = _load_outs(tmp_path)
    for r in range(WORLD):
        assert sorted(outs[r]) == sorted(golden[r])
        for k in golden[r]:
            np.testing.assert_array_equal(
                golden[r][k], outs[r][k],
                err_msg=f"rank {r} plane {k!r} diverged after the "
                        f"multi-host kill -> elected resume")


def _kill_resume_world(tmp_path, golden, point, extra_env=None):
    """Kill rank 1 at `point` (whole world fail-stops), relaunch against
    the same roots, assert both ranks elected the SAME cursor and final
    state parity."""
    kill_env = _env(tmp_path, extra={
        "PBTPU_FAULTPOINT": point,
        "PBTPU_FAULTPOINT_AFTER": str(POINT_AFTER[point]),
        "PBTPU_FAULTPOINT_ONLY_RANK": "1", **(extra_env or {})})
    code = _launch(tmp_path, kill_env)
    assert code == 137, f"expected the armed kill on rank 1, got {code}"
    resume_env = _env(tmp_path, extra=extra_env)
    code = _launch(tmp_path, resume_env)
    assert code == 0, (
        f"resume world failed ({code}); worker errors: "
        + "; ".join(
            (tmp_path / "work" / f"err_{r}.txt").read_text()[:400]
            for r in range(WORLD)
            if (tmp_path / "work" / f"err_{r}.txt").exists()))
    infos = _resume_info(tmp_path)
    assert infos[0]["elected"] is not None, infos
    assert infos[0]["elected"] == infos[1]["elected"], (
        f"world diverged at election: {infos}")
    assert infos[0]["mid_steps"] == infos[1]["mid_steps"]
    _assert_world_parity(golden, tmp_path)
    return infos


def test_two_host_election_smoke(tmp_path, golden):
    """Tier-1 (CI satellite): kill rank 1 with its pass-2 snapshot
    UNCOMMITTED (pre_manifest) while rank 0 may well have committed its
    own — the election must roll BOTH ranks back to pass 1 (never let
    rank 0 resume ahead), and the resumed world must be bit-identical.
    Local FileStore only — no remote/mid-pass riders, keeps tier-1 lean."""
    kill_env = _env(tmp_path, remote=False, midpass=0, extra={
        "PBTPU_FAULTPOINT": "pass_ckpt.pre_manifest",
        "PBTPU_FAULTPOINT_AFTER": "1",       # pass-2's snapshot commit
        "PBTPU_FAULTPOINT_ONLY_RANK": "1"})
    code = _launch(tmp_path, kill_env)
    assert code == 137, f"expected the armed kill on rank 1, got {code}"
    resume_env = _env(tmp_path, remote=False, midpass=0)
    code = _launch(tmp_path, resume_env)
    assert code == 0
    infos = _resume_info(tmp_path)
    # rank 1's pass-2 snapshot never committed -> the world elects pass 1
    assert infos[0]["elected"] == infos[1]["elected"] == [1, 0], infos
    assert [i["start"] for i in infos] == [2, 2]
    _assert_world_parity(golden, tmp_path)
    # both ranks' event streams carry the election record
    for r in range(WORLD):
        names = [e.get("name") for e in _events(tmp_path, r)]
        assert "resume_election" in names


@pytest.mark.slow
@pytest.mark.parametrize("point",
                         [p for p in faultpoint.POINTS
                          if p not in ("pass_ckpt.pre_manifest",
                                       "remote_ckpt.download.pre")
                          and p not in faultpoint.ELASTIC_POINTS
                          # the fixed 2-rank crash worker never calls
                          # ElasticWorld.admit or rebinds ownership —
                          # the admit/grow windows are covered by the
                          # grow kill matrix (test_elastic.py +
                          # grow_worker.py); a leg here would KeyError
                          # on POINT_AFTER and could never fire anyway
                          and p not in faultpoint.ADMIT_POINTS
                          and p not in faultpoint.SERVING_POINTS
                          and p not in faultpoint.MONITOR_POINTS
                          and p not in faultpoint.FLEET_POINTS
                          # the multi-host worker trains a plain
                          # 1-shard in-RAM store: the sharded-save and
                          # spill-tier windows never execute here —
                          # they are covered (incl. kill→resume) by
                          # test_exchange.py and the single-host matrix
                          # under PBTPU_TABLE_TIERING=spill
                          and p not in faultpoint.EXCHANGE_POINTS
                          and p not in ("tiering.save.pre_flush",
                                        "tiering.evict.pre")])
def test_multihost_kill_resume_matrix(point, tmp_path, golden):
    """Every registered fault point, multi-host: kill rank 1 there
    (mid-pass snapshots + hdfs:// remote mirror ON so every point is on
    the executed path), restart the world, elected resume, per-rank
    bitwise parity."""
    infos = _kill_resume_world(tmp_path, golden, point)
    if point == "trainer.midpass.post_save":
        # the kill landed right after rank 1's mid-pass-2 commit: the
        # world must resume FROM THE SHUFFLE CURSOR (skip the trained
        # steps), not replay the pass
        assert infos[0]["elected"] == [1, 2], infos
        assert infos[0]["mid_steps"] == 2


@pytest.mark.slow
def test_multihost_kill_during_remote_download(tmp_path, golden):
    """Replacement-host flow: after a mirrored run, rank 1 loses its local
    staging root and is killed mid-download on the restart; the THIRD
    launch re-downloads from the donefile, elects, and lands parity."""
    env = _env(tmp_path)
    code = _launch(tmp_path, env)
    assert code == 0
    kill_env = _env(tmp_path, extra={
        "PBTPU_CRASH_WIPE_LOCAL_RANK": "1",
        "PBTPU_FAULTPOINT": "remote_ckpt.download.pre",
        "PBTPU_FAULTPOINT_AFTER": "0",
        "PBTPU_FAULTPOINT_ONLY_RANK": "1"})
    code = _launch(tmp_path, kill_env)
    assert code == 137, f"expected the download kill, got {code}"
    resume_env = _env(tmp_path, extra={"PBTPU_CRASH_WIPE_LOCAL_RANK": "1"})
    code = _launch(tmp_path, resume_env)
    assert code == 0
    infos = _resume_info(tmp_path)
    assert infos[0]["elected"] == infos[1]["elected"] is not None
    _assert_world_parity(golden, tmp_path)


def test_elastic_two_to_one_shrink_smoke(tmp_path):
    """Tier-1 (ISSUE 6 satellite): a 2-rank elastic world loses rank 1
    mid pass 2 and CONTINUES — rank 0 re-forms the world at size 1,
    re-elects its resume cursor, trains the remaining schedule (pass 3
    carries the whole dataset) and exits cleanly, all without operator
    action. The full 3-rank phase matrix incl. kills inside re-formation
    is ``-m slow`` in tests/test_elastic.py."""
    worker = os.path.join(TESTS_DIR, "elastic_worker.py")
    env = {
        "PBTPU_TEST_WORKDIR": str(tmp_path / "work"),
        "PBTPU_ELASTIC_ROOT": str(tmp_path / "snaps"),
        "PBTPU_ELASTIC_PASSES": "3",
        "PBTPU_ELASTIC_N": "256",            # 4 steps/rank at world 2
        "PBTPU_FAULTPOINT": "trainer.step.pre",
        "PBTPU_FAULTPOINT_AFTER": "5",       # pass-2 step 2 on rank 1
        "PBTPU_FAULTPOINT_ONLY_RANK": "1",
    }
    os.makedirs(env["PBTPU_TEST_WORKDIR"], exist_ok=True)
    codes = launch(2, [sys.executable, worker],
                   store_dir=str(tmp_path / "store"), base_env=env,
                   fail_stop=False, timeout_s=300)
    assert codes[1] == 137, codes            # the armed kill fired
    assert codes[0] == 0, (
        codes,
        (tmp_path / "work" / "err_0.txt").read_text()[:800]
        if (tmp_path / "work" / "err_0.txt").exists() else "")
    with open(tmp_path / "work" / "info_0.json") as f:
        info = json.load(f)
    assert info["gen"] >= 1 and info["members"] == [0], info
    assert info["elected"] is not None
    p = tmp_path / "work" / "out_0.npz"
    assert p.exists()
    with np.load(p) as z:
        assert int(z["pass_id"]) == 3        # the full schedule finished
        assert int(z["global_step"]) > 0
    events = [json.loads(ln) for ln in
              (tmp_path / "work" / "events_0.jsonl").read_text()
              .splitlines() if ln]
    resize = [e for e in events if e.get("name") == "world_resize"]
    assert resize and resize[-1]["fields"]["departed"] == [1], \
        [e.get("name") for e in events][-20:]


@pytest.mark.slow
def test_stalled_peer_names_rank_and_emits_event(tmp_path):
    """Hang (not death): rank 1 sleeps mid pass 2 with its heartbeat still
    beating. Rank 0's watchdog must fail the run with a PeerStalledError
    NAMING rank 1 — not a bare 300 s barrier timeout — and emit the
    peer_stalled telemetry event."""
    env = _env(tmp_path, remote=False, midpass=0, extra={
        "PBTPU_TEST_STALL_RANK": "1",
        "PBTPU_TEST_STALL_S": "90",
        "PBTPU_TEST_STALL_AFTER_S": "10"})
    code = _launch(tmp_path, env)
    assert code not in (0, 137), f"expected a watchdog failure, got {code}"
    err = (tmp_path / "work" / "err_0.txt")
    assert err.exists(), "rank 0 exited without a recorded error"
    text = err.read_text()
    assert "PeerStalledError" in text and "[1]" in text, text[:800]
    assert "stalled" in text
    events = _events(tmp_path, 0)
    stalled = [e for e in events if e.get("name") == "peer_stalled"]
    assert stalled and stalled[0]["fields"]["rank"] == 1, events[-10:]
