"""Embedding engine: config, optimizers, host store, working set, sharded ops."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                     PassWorkingSet, sharded)
from paddlebox_tpu.embedding.optim import apply_updates
from paddlebox_tpu.parallel import make_mesh, mesh


def cfg_small(**kw):
    kw.setdefault("dim", 4)
    kw.setdefault("optimizer", "adagrad")
    kw.setdefault("learning_rate", 0.1)
    return EmbeddingConfig(**kw)


# ---------------- config ----------------

def test_row_geometry():
    c = cfg_small()
    assert c.pull_width == 7      # show, clk, w, 4x embedx
    assert c.grad_width == 5
    assert c.row_width == 9       # + w_g2sum, x_g2sum


def test_bad_optimizer_rejected():
    with pytest.raises(ValueError):
        EmbeddingConfig(optimizer="adamw")


# ---------------- optimizers ----------------

def np_adagrad_reference(row, g, show_inc, clk_inc, c):
    d = c.dim
    out = row.copy()
    out[0] += show_inc
    out[1] += clk_inc
    wg2 = row[3 + d] + g[0] ** 2
    gx = g[1:]
    xg2 = row[4 + d] + np.mean(gx ** 2)
    out[2] = row[2] - c.learning_rate * np.sqrt(
        c.initial_g2sum / (c.initial_g2sum + wg2)) * g[0]
    out[3:3 + d] = row[3:3 + d] - c.learning_rate * np.sqrt(
        c.initial_g2sum / (c.initial_g2sum + xg2)) * gx
    out[3 + d], out[4 + d] = wg2, xg2
    return out


def test_adagrad_matches_numpy():
    c = cfg_small()
    rng = np.random.default_rng(0)
    rows = rng.normal(size=(6, c.row_width)).astype(np.float32)
    rows[:, 3 + c.dim:] = np.abs(rows[:, 3 + c.dim:])  # g2sum >= 0
    grads = rng.normal(size=(6, c.grad_width)).astype(np.float32)
    shows = rng.integers(0, 3, 6).astype(np.float32)
    clks = rng.integers(0, 2, 6).astype(np.float32)
    got = np.asarray(apply_updates(jnp.asarray(rows), jnp.asarray(grads),
                                   jnp.asarray(shows), jnp.asarray(clks), c))
    want = np.stack([np_adagrad_reference(rows[i], grads[i], shows[i],
                                          clks[i], c) for i in range(6)])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("opt", ["sgd", "adagrad", "adam", "ftrl"])
def test_all_optimizers_zero_grad_preserves_fresh_rows(opt):
    # Zero grad on a *fresh* row (zero counters/optimizer state) must be a
    # no-op — this is what keeps null/padding rows at zero forever. (With
    # arbitrary state adam/ftrl legitimately move: momentum decay, proximal
    # w-from-z.)
    c = cfg_small(optimizer=opt)
    rng = np.random.default_rng(1)
    rows = np.zeros((4, c.row_width), dtype=np.float32)
    rows[:, c.embedx_cols] = rng.normal(size=(4, c.dim))
    if opt in ("adam", "adagrad", "sgd"):
        rows[:, 2] = rng.normal(size=4)  # ftrl's w is derived from z state
    zeros_g = jnp.zeros((4, c.grad_width))
    z = jnp.zeros((4,))
    out = np.asarray(apply_updates(jnp.asarray(rows), zeros_g, z, z, c))
    np.testing.assert_allclose(out[:, :3 + c.dim], rows[:, :3 + c.dim],
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("opt", ["sgd", "adagrad", "adam", "ftrl"])
def test_all_optimizers_reduce_loss_direction(opt):
    # One update with grad g must move <params, g> down (descent direction).
    c = cfg_small(optimizer=opt, learning_rate=0.1)
    rng = np.random.default_rng(2)
    rows = np.zeros((8, c.row_width), dtype=np.float32)
    rows[:, 2] = rng.normal(size=8)
    rows[:, c.embedx_cols] = rng.normal(size=(8, c.dim))
    g = rng.normal(size=(8, c.grad_width)).astype(np.float32)
    out = np.asarray(apply_updates(jnp.asarray(rows), jnp.asarray(g),
                                   jnp.zeros(8), jnp.zeros(8), c))
    delta = out[:, 2:3 + c.dim] - rows[:, 2:3 + c.dim]
    if opt == "ftrl":
        delta = delta[:, 1:]  # w jumps to the proximal point on first step
        g = g[:, 1:]
    assert float(np.sum(delta * g)) < 0.0


def test_sgd_direction():
    c = cfg_small(optimizer="sgd", learning_rate=1.0)
    rows = jnp.zeros((1, c.row_width))
    grads = jnp.ones((1, c.grad_width))
    out = apply_updates(rows, grads, jnp.zeros(1), jnp.zeros(1), c)
    np.testing.assert_allclose(out[0, 2:3 + c.dim], -1.0)


# ---------------- host store ----------------

def test_store_init_deterministic():
    c = cfg_small()
    s1, s2 = HostEmbeddingStore(c), HostEmbeddingStore(c)
    keys = np.array([5, 9, 12345678901234], dtype=np.uint64)
    r1, r2 = s1.lookup_or_init(keys), s2.lookup_or_init(keys)
    np.testing.assert_array_equal(r1, r2)
    assert np.all(np.abs(r1[:, c.embedx_cols]) <= c.initial_range)
    assert np.any(r1[:, c.embedx_cols] != 0)
    # counters and optimizer state start at zero
    np.testing.assert_array_equal(r1[:, :3], 0)


def test_store_write_back_and_growth():
    c = cfg_small()
    s = HostEmbeddingStore(c, initial_capacity=2)
    keys = np.arange(100, dtype=np.uint64)
    rows = s.lookup_or_init(keys)
    assert len(s) == 100
    rows[:, 2] = 7.0
    s.write_back(keys, rows)
    np.testing.assert_allclose(s.get_rows(keys)[:, 2], 7.0)
    # same keys again: no new rows
    s.lookup_or_init(keys[:10])
    assert len(s) == 100


def test_store_save_load_roundtrip(tmp_path):
    c = cfg_small()
    s = HostEmbeddingStore(c)
    keys = np.array([3, 1, 4, 1, 5], dtype=np.uint64)
    s.lookup_or_init(keys)
    s.save_base(str(tmp_path))
    # mutate two keys, save delta
    rows = s.get_rows(np.array([3, 4], dtype=np.uint64))
    rows[:, 2] = 42.0
    s.write_back(np.array([3, 4], dtype=np.uint64), rows)
    s.save_delta(str(tmp_path))
    s2 = HostEmbeddingStore.load(str(tmp_path))
    assert len(s2) == len(s)
    np.testing.assert_allclose(
        s2.get_rows(np.array([3, 4], dtype=np.uint64))[:, 2], 42.0)
    np.testing.assert_array_equal(
        s2.get_rows(np.array([5], dtype=np.uint64)),
        s.get_rows(np.array([5], dtype=np.uint64)))


def test_store_shrink():
    c = cfg_small()
    s = HostEmbeddingStore(c)
    keys = np.arange(10, dtype=np.uint64)
    rows = s.lookup_or_init(keys)
    rows[:5, 0] = 10.0   # hot
    s.write_back(keys, rows)
    evicted = s.shrink(min_show=1.0)
    assert evicted == 5
    assert len(s) == 5
    np.testing.assert_allclose(s.get_rows(keys[:5])[:, 0], 10.0)


# ---------------- working set ----------------

def test_working_set_translate_and_roundtrip():
    c = cfg_small()
    store = HostEmbeddingStore(c)
    keys = np.array([100, 7, 555, 31], dtype=np.uint64)
    ws = PassWorkingSet.begin_pass(store, keys)
    # translate known, unknown, masked
    ids = np.array([[7, 555], [999, 100]], dtype=np.uint64)
    mask = np.array([[True, True], [True, False]])
    idx = ws.translate(ids, mask)
    assert idx.dtype == np.int32
    assert idx[0, 0] > 0 and idx[0, 1] > 0
    assert idx[1, 0] == 0   # unknown key -> null
    assert idx[1, 1] == 0   # masked -> null
    # device table row for key 7 equals store row (the device table may
    # carry zero pad columns past row_width — working_set.device_width)
    np.testing.assert_allclose(
        np.asarray(ws.table)[idx[0, 0], :c.row_width],
        store.get_rows([7])[0], rtol=1e-6)
    # mutate device table; default end_pass ships only the pass delta —
    # the rows translate() recorded (keys 7 and 555), not untouched ones
    t = ws.table.at[:, 2].set(3.5)
    ws.end_pass(store, t)
    np.testing.assert_allclose(store.get_rows([7, 555])[:, 2], 3.5)
    np.testing.assert_allclose(store.get_rows([100, 31])[:, 2], 0.0)
    # explicit full write-back persists every working-set row
    ws.end_pass(store, t, only_touched=False)
    np.testing.assert_allclose(store.get_rows(keys)[:, 2], 3.5)


def test_working_set_null_row_zero():
    c = cfg_small()
    store = HostEmbeddingStore(c)
    ws = PassWorkingSet.begin_pass(store, np.array([9], dtype=np.uint64))
    np.testing.assert_array_equal(np.asarray(ws.table)[0], 0)


# ---------------- sharded lookup/push (single shard) ----------------

def test_lookup_null_and_values():
    c = cfg_small()
    store = HostEmbeddingStore(c)
    keys = np.array([11, 22, 33], dtype=np.uint64)
    ws = PassWorkingSet.begin_pass(store, keys)
    idx = ws.translate(np.array([11, 22, 33, 0], dtype=np.uint64),
                       np.array([True, True, True, False]))
    out = np.asarray(sharded.lookup(ws.table, jnp.asarray(idx), c))
    assert out.shape == (4, c.pull_width)
    np.testing.assert_array_equal(out[3], 0)  # null -> zeros
    np.testing.assert_allclose(out[0], store.get_rows([11])[0, :c.pull_width])


def test_push_merges_duplicates():
    c = cfg_small(optimizer="sgd", learning_rate=1.0)
    store = HostEmbeddingStore(c)
    keys = np.array([5, 6], dtype=np.uint64)
    ws = PassWorkingSet.begin_pass(store, keys)
    i5 = int(ws.translate(np.array([5], dtype=np.uint64))[0])
    i6 = int(ws.translate(np.array([6], dtype=np.uint64))[0])
    idx = jnp.asarray([i5, i5, i6, 0], dtype=jnp.int32)
    grads = jnp.asarray([[1.0] * c.grad_width, [2.0] * c.grad_width,
                         [4.0] * c.grad_width, [0.0] * c.grad_width])
    shows = jnp.asarray([1.0, 1.0, 1.0, 0.0])
    clks = jnp.asarray([1.0, 0.0, 0.0, 0.0])
    before = np.asarray(ws.table).copy()
    after = np.asarray(sharded.push(ws.table, idx, grads, shows, clks, c))
    # key 5: merged grad 3.0 -> w -= 3; show += 2; clk += 1
    np.testing.assert_allclose(after[i5, 2], before[i5, 2] - 3.0, rtol=1e-6)
    np.testing.assert_allclose(after[i5, 0], 2.0)
    np.testing.assert_allclose(after[i5, 1], 1.0)
    np.testing.assert_allclose(after[i6, 2], before[i6, 2] - 4.0, rtol=1e-6)
    np.testing.assert_array_equal(after[0], 0)  # null row untouched


def test_dedup_tokens():
    idx = jnp.asarray([7, 3, 7, 0, 3, 3], dtype=jnp.int32)
    uniq, inv = sharded.dedup_tokens(idx)
    out = np.asarray(uniq)[np.asarray(inv)]
    np.testing.assert_array_equal(out, np.asarray(idx))


# ---------------- routed (multi-shard) path ----------------

@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


def _build_ws(c, n_keys, mesh_):
    store = HostEmbeddingStore(c)
    keys = np.random.default_rng(7).choice(1 << 40, size=n_keys,
                                           replace=False).astype(np.uint64)
    ws = PassWorkingSet.begin_pass(store, keys, mesh_)
    return store, ws


def test_routed_lookup_matches_local(mesh8):
    c = cfg_small()
    store, ws = _build_ws(c, 100, mesh8)
    rng = np.random.default_rng(3)
    # 8 devices x 16 tokens each, with duplicates and nulls
    idx_global = rng.integers(0, ws.num_keys + 1, size=(8, 16)).astype(np.int32)
    flat = jnp.asarray(idx_global.reshape(-1))

    def body(table_shard, idx_local):
        # capacity_factor = n_shards guarantees losslessness (cap == n_local)
        return sharded.routed_lookup(table_shard, idx_local, c, mesh.DP_AXIS,
                                     capacity_factor=8.0)

    out = jax.jit(jax.shard_map(
        body, mesh=mesh8,
        in_specs=(P(mesh.DP_AXIS), P(mesh.DP_AXIS)),
        out_specs=P(mesh.DP_AXIS)))(ws.table, flat)
    want = np.asarray(sharded.lookup(ws.table, flat, c))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-6)


def test_routed_push_matches_local(mesh8):
    c = cfg_small(optimizer="adagrad")
    store, ws = _build_ws(c, 60, mesh8)
    rng = np.random.default_rng(4)
    n_tok = 8 * 12
    idx = rng.integers(0, ws.num_keys + 1, size=n_tok).astype(np.int32)
    grads = rng.normal(size=(n_tok, c.grad_width)).astype(np.float32)
    shows = (idx > 0).astype(np.float32)
    clks = rng.integers(0, 2, n_tok).astype(np.float32) * shows
    # null tokens must carry zero grads
    grads[idx == 0] = 0.0
    jidx, jg = jnp.asarray(idx), jnp.asarray(grads)
    js, jc = jnp.asarray(shows), jnp.asarray(clks)

    def body(table_shard, i, g, s, k):
        return sharded.routed_push(table_shard, i, g, s, k, c, mesh.DP_AXIS,
                                   capacity_factor=8.0)

    out = jax.jit(jax.shard_map(
        body, mesh=mesh8,
        in_specs=(P(mesh.DP_AXIS), P(mesh.DP_AXIS), P(mesh.DP_AXIS),
                  P(mesh.DP_AXIS), P(mesh.DP_AXIS)),
        out_specs=P(mesh.DP_AXIS)))(ws.table, jidx, jg, js, jc)
    want = np.asarray(sharded.push(ws.table, jidx, jg, js, jc, c))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-5)


def test_routed_push_adam_empty_lanes_no_corruption(mesh8):
    # Regression: empty all-to-all lanes must not touch shard-local row 0
    # (adam applies momentum decay even on zero grads).
    c = cfg_small(optimizer="adam")
    store, ws = _build_ws(c, 40, mesh8)
    # tokens that never reference rows k*rows_per_shard
    rps = ws.rows_per_shard
    idx = np.array([i for i in range(1, rps * 8) if i % rps != 0][:32],
                   dtype=np.int32)
    assert len(idx) == 32
    grads = np.zeros((32, c.grad_width), np.float32)
    grads[:, 0] = 0.01
    shows = np.ones(32, np.float32)
    clks = np.zeros(32, np.float32)

    def body(t, i, g, s, k):
        return sharded.routed_push(t, i, g, s, k, c, mesh.DP_AXIS, 8.0)

    out = jax.jit(jax.shard_map(
        body, mesh=mesh8,
        in_specs=(P(mesh.DP_AXIS),) * 5,
        out_specs=P(mesh.DP_AXIS)))(
            ws.table, jnp.asarray(idx), jnp.asarray(grads),
            jnp.asarray(shows), jnp.asarray(clks))
    want = np.asarray(sharded.push(ws.table, jnp.asarray(idx),
                                   jnp.asarray(grads), jnp.asarray(shows),
                                   jnp.asarray(clks), c))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-6)


def test_store_shrink_survives_delta_checkpoint(tmp_path):
    # Regression: evictions + decay must reach load(base + deltas).
    c = cfg_small()
    s = HostEmbeddingStore(c)
    keys = np.arange(1, 11, dtype=np.uint64)
    rows = s.lookup_or_init(keys)
    rows[:, 0] = 10.0
    rows[5:, 0] = 0.5
    s.write_back(keys, rows)
    s.save_base(str(tmp_path))
    s.shrink(min_show=1.0, decay=0.5)   # evicts the 5 cold keys, decays hot
    s.save_delta(str(tmp_path))
    s2 = HostEmbeddingStore.load(str(tmp_path))
    assert len(s2) == len(s) == 5
    np.testing.assert_allclose(s2.get_rows(keys[:5])[:, 0], 5.0)


def test_recreated_tombstoned_key_reaches_delta(tmp_path):
    """shrink-evicted key re-created by lookup_or_init: the next delta must
    carry its fresh row, or load(base+deltas) resurrects the stale one."""
    cfg = EmbeddingConfig(dim=2, optimizer="sgd")
    s = HostEmbeddingStore(cfg)
    keys = np.array([11, 22], np.uint64)
    rows = s.lookup_or_init(keys)
    rows[:, 0] = 5.0           # show counters keep both alive
    rows[:, 2] = 7.0           # distinctive trained w
    s.write_back(keys, rows)
    s.save_base(str(tmp_path))
    s.get_rows(keys)
    # evict key 11 (low show), then re-create it fresh
    r = s.get_rows(keys); r[0, 0] = 0.0; s.write_back(keys, r)
    s.save_delta(str(tmp_path))
    assert s.shrink(min_show=1.0) == 1
    s.lookup_or_init(np.array([11], np.uint64))     # re-created, fresh row
    s.save_delta(str(tmp_path))
    s2 = HostEmbeddingStore.load(str(tmp_path), cfg)
    live = s.get_rows(np.array([11], np.uint64))
    restored = s2.get_rows(np.array([11], np.uint64))
    np.testing.assert_array_equal(live, restored)
    assert restored[0, 2] != 7.0    # NOT the stale pre-eviction row


def test_reingested_tombstoned_key_reaches_delta(tmp_path):
    """Same resurrection hole on the apply_delta_file path: a shrink-evicted
    key re-added by delta replay must be dirtied so the NEXT delta carries
    its new row."""
    cfg = EmbeddingConfig(dim=2, optimizer="sgd")
    s = HostEmbeddingStore(cfg)
    keys = np.array([11, 22], np.uint64)
    rows = s.lookup_or_init(keys)
    rows[:, 0] = 5.0
    rows[:, 2] = 7.0
    s.write_back(keys, rows)
    s.save_base(str(tmp_path))
    # an external delta carrying a new value for key 11
    ext = tmp_path / "ext-delta.npz"
    new_row = rows[0:1].copy(); new_row[0, 2] = 42.0
    np.savez(ext, keys=np.array([11], np.uint64), rows=new_row,
             removed=np.zeros(0, np.uint64))
    # evict 11, then replay the external delta (re-adds it, w=42)
    r = s.get_rows(keys); r[0, 0] = 0.0; s.write_back(keys, r)
    s.save_delta(str(tmp_path))
    assert s.shrink(min_show=1.0) == 1
    s.apply_delta_file(str(ext))
    s.save_delta(str(tmp_path))
    s2 = HostEmbeddingStore.load(str(tmp_path), cfg)
    np.testing.assert_array_equal(
        s.get_rows(np.array([11], np.uint64)),
        s2.get_rows(np.array([11], np.uint64)))
    assert s2.get_rows(np.array([11], np.uint64))[0, 2] == 42.0


def test_translate_empty_working_set():
    c = cfg_small()
    store = HostEmbeddingStore(c)
    ws = PassWorkingSet.begin_pass(store, np.array([], dtype=np.uint64))
    idx = ws.translate(np.array([5, 6], dtype=np.uint64))
    np.testing.assert_array_equal(idx, 0)


def test_routed_dropped_counts():
    idx = jnp.asarray([1, 2, 3, 4, 8, 9], dtype=jnp.int32)
    # 2 shards of 8 rows, capacity factor 1.0 -> cap=3 per dest; 4 real
    # tokens to shard 0 -> 1 dropped
    n = sharded.routed_dropped(idx, rows_per_shard=8, n_shards=2,
                               capacity_factor=1.0)
    assert int(n) == 1
    # null/padding tokens are not routed and never count against capacity
    idx2 = jnp.asarray([0, 0, 0, 0, 8, 9], dtype=jnp.int32)
    n2 = sharded.routed_dropped(idx2, rows_per_shard=8, n_shards=2,
                                capacity_factor=1.0)
    assert int(n2) == 0


def test_transfer_compress_embedx_roundtrip(mesh8):
    """Flags.transfer_compress_embedx: pass boundaries ship embedx as bf16;
    counters/w/opt state stay exact, embedx within bf16 tolerance, and a
    training pass still works."""
    from paddlebox_tpu.config import flags as cfg_flags
    from paddlebox_tpu.embedding.working_set import PassWorkingSet

    old = cfg_flags.transfer_compress_embedx
    cfg_flags.transfer_compress_embedx = True
    try:
        cfg = EmbeddingConfig(dim=8, optimizer="adagrad")
        s = HostEmbeddingStore(cfg)
        rng = np.random.default_rng(0)
        keys = rng.choice(1 << 40, 200, replace=False).astype(np.uint64)
        rows = s.lookup_or_init(keys)
        rows[:, 0] = rng.integers(0, 100_000, 200)   # large counters
        rows[:, 1] = rng.integers(0, 50_000, 200)
        rows[:, 2] = rng.normal(size=200)
        rows[:, cfg.embedx_cols] = rng.normal(size=(200, cfg.total_dim))
        s.write_back(keys, rows)
        before = s.get_rows(keys)

        ws = PassWorkingSet.begin_pass(s, keys, mesh8)
        ws.end_pass(s)
        after = s.get_rows(keys)
        # counters/w/opt exact — including counters far beyond bf16's 2^8
        np.testing.assert_array_equal(after[:, :3], before[:, :3])
        # embedx within bf16 rounding
        np.testing.assert_allclose(after[:, cfg.embedx_cols],
                                   before[:, cfg.embedx_cols],
                                   rtol=1 / 128)
        assert np.abs(after[:, cfg.embedx_cols]
                      - before[:, cfg.embedx_cols]).max() > 0  # really bf16

        # training under the flag matches the uncompressed baseline
        from test_train_e2e import synth_dataset, NUM_SLOTS
        from paddlebox_tpu.models import DNNCTRModel
        from paddlebox_tpu.train import Trainer, TrainerConfig

        def run():
            ds, schema = synth_dataset(512, seed=5)
            store2 = HostEmbeddingStore(EmbeddingConfig(dim=8,
                                                        learning_rate=0.15))
            tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=8,
                                     dense_dim=1, hidden=(16,)),
                         store2, schema, mesh8,
                         TrainerConfig(global_batch_size=128,
                                       dense_lr=3e-3))
            return [tr.train_pass(ds) for _ in range(2)][-1]

        r_on = run()
        cfg_flags.transfer_compress_embedx = False
        r_off = run()
        assert abs(r_on["auc"] - r_off["auc"]) < 0.02, (r_on, r_off)
        assert abs(r_on["loss_mean"] - r_off["loss_mean"]) < 0.01
    finally:
        cfg_flags.transfer_compress_embedx = old
