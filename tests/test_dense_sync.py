"""Dense sync modes: async host table, K-step parameter averaging.

Mirrors the reference's three BoxPSWorker dense modes
(boxps_worker.cc:481-521, BoxPSAsynDenseTable cc:37-296)."""

import numpy as np
import pytest

from paddlebox_tpu.data import DataFeedSchema
from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.models import DNNCTRModel
from paddlebox_tpu.parallel import AsyncDenseTable, make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig


# ---------------------------------------------------------------------------
# AsyncDenseTable unit tests
# ---------------------------------------------------------------------------

def _ref_update(params, mom1, mom2, g, lr, betas, eps=1e-8):
    b1, b2 = betas
    mom1 = b1 * mom1 + (1 - b1) * g
    mom2 = b2 * mom2 + (1 - b2) * g * g
    params = params - lr * mom1 / (np.sqrt(mom2) + eps)
    return params, mom1, mom2


def test_async_table_update_math():
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=64).astype(np.float32)
    tbl = AsyncDenseTable(p0, lr=0.1, betas=(0.9, 0.99))
    g1 = rng.normal(size=64).astype(np.float32)
    g2 = rng.normal(size=64).astype(np.float32)
    tbl.start()
    tbl.push(g1)
    tbl.flush()
    tbl.push(g2)
    tbl.flush()
    tbl.stop()
    want, m1, m2 = _ref_update(p0, 0, 0, g1, 0.1, (0.9, 0.99))
    want, m1, m2 = _ref_update(want, m1, m2, g2, 0.1, (0.9, 0.99))
    np.testing.assert_allclose(tbl.pull(), want, rtol=1e-5)
    assert tbl.steps_applied == 2
    assert tbl.grads_merged == 2


def test_async_table_merges_queued_grads():
    p0 = np.zeros(8, np.float32)
    tbl = AsyncDenseTable(p0, lr=0.1, merge_limit=4)
    for _ in range(4):  # queued before the thread starts -> one merged apply
        tbl.push(np.ones(8, np.float32))
    tbl.start()
    tbl.flush()
    tbl.stop()
    assert tbl.steps_applied == 1
    assert tbl.grads_merged == 4
    # merged grad = mean of the 4 (all ones) -> same as single push of ones
    ref = AsyncDenseTable(p0, lr=0.1)
    ref.start(); ref.push(np.ones(8, np.float32)); ref.flush(); ref.stop()
    np.testing.assert_allclose(tbl.pull(), ref.pull(), rtol=1e-6)


def test_async_table_lr_map():
    p0 = np.zeros(4, np.float32)
    tbl = AsyncDenseTable(p0, lr=1.0, betas=(0.0, 0.0),
                          lr_map=[(slice(2, 4), 0.5)])
    tbl.start(); tbl.push(np.ones(4, np.float32)); tbl.flush(); tbl.stop()
    got = tbl.pull()
    assert abs(got[0] / got[2] - 2.0) < 1e-5


# ---------------------------------------------------------------------------
# Trainer-mode integration (8-dev CPU mesh via conftest)
# ---------------------------------------------------------------------------

def _make(mode, seed=0, **cfg_kw):
    schema = DataFeedSchema.ctr(num_sparse=4, num_float=2, batch_size=32,
                                max_len=2)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    mesh = make_mesh(8)
    model = DNNCTRModel(num_slots=4, emb_dim=4, dense_dim=2, hidden=(16, 8))
    tr = Trainer(model, store, schema, mesh,
                 TrainerConfig(global_batch_size=32, auc_buckets=1 << 8,
                               dense_optimizer="sgd", dense_lr=0.1,
                               dense_sync_mode=mode, **cfg_kw), seed=seed)
    return tr


def _run_steps(tr, n_steps=6, seed=3):
    import jax
    from paddlebox_tpu.embedding import PassWorkingSet
    rng = np.random.default_rng(seed)
    keys = rng.choice(1 << 40, 300, replace=False).astype(np.uint64)
    ws = PassWorkingSet.begin_pass(tr.store, keys, tr.mesh)

    class FakeDataset:
        def unique_keys(self):
            return keys

        def batches(self, bs, drop_last=False):
            r = np.random.default_rng(seed + 1)
            from paddlebox_tpu.data.slot_record import PackedBatch
            T = tr.layout.total_len
            for _ in range(n_steps):
                ids = r.choice(keys, size=(bs, T))
                mask = r.random((bs, T)) < 0.8
                floats = np.concatenate(
                    [(r.random((bs, 1)) < 0.4).astype(np.float32),
                     r.normal(size=(bs, 2)).astype(np.float32)], axis=1)
                yield PackedBatch(schema=tr.schema, num=bs, ids=ids,
                                  mask=mask, floats=floats.astype(np.float32),
                                  rank=np.zeros(bs, np.int32),
                                  cmatch=np.zeros(bs, np.int32))

    return tr.train_pass(FakeDataset())


def test_kstep_k1_sgd_matches_allreduce():
    import jax
    tr_a = _make("allreduce", seed=7)
    tr_k = _make("kstep", seed=7, param_sync_step=1)
    m_a = _run_steps(tr_a)
    m_k = _run_steps(tr_k)
    # SGD + param averaging every step == grad averaging (linear)
    pa = tr_a.eval_params()
    pk = tr_k.eval_params()
    for a, b in zip(jax.tree.leaves(pa), jax.tree.leaves(pk)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
    assert abs(m_a["loss_mean"] - m_k["loss_mean"]) < 1e-3


def test_kstep_k3_trains_and_ends_synced():
    import jax
    tr = _make("kstep", param_sync_step=3)
    m = _run_steps(tr, n_steps=7)
    assert np.isfinite(m["loss_mean"])
    # end-of-pass sync: every shard's dense copy identical
    for leaf in jax.tree.leaves(tr.params):
        a = np.asarray(leaf)
        np.testing.assert_allclose(a, np.broadcast_to(a[:1], a.shape),
                                   rtol=1e-6)


def test_async_mode_trains():
    import jax
    tr = _make("async")
    p0 = [np.asarray(x).copy() for x in jax.tree.leaves(tr.params)]
    m = _run_steps(tr, n_steps=8)
    assert np.isfinite(m["loss_mean"])
    # every pushed grad is applied by end of pass (train_pass flushes), and
    # the pulled-back params actually moved off the init
    assert tr.dense_table.grads_merged == 8
    assert tr.dense_table.steps_applied > 0
    moved = max(np.abs(np.asarray(a) - b).max()
                for a, b in zip(jax.tree.leaves(tr.params), p0))
    assert moved > 0
    tr.dense_table.stop()


def test_async_table_stop_mid_merge_then_flush():
    # stop sentinel consumed mid-merge must not corrupt the queue's
    # unfinished count (flush would deadlock)
    tbl = AsyncDenseTable(np.zeros(4, np.float32), lr=0.1, merge_limit=4)
    tbl.push(np.ones(4, np.float32))
    tbl.push(np.ones(4, np.float32))
    tbl._queue.put(None)  # sentinel queued behind the grads, merged together
    tbl._run()
    tbl.flush()  # must return immediately
    assert tbl.grads_merged == 2


def test_async_checkpoint_roundtrip():
    import jax
    tr = _make("async")
    _run_steps(tr, n_steps=4)
    saved_params = jax.tree.map(np.asarray, tr.params)
    saved_opt = {k: np.asarray(v) for k, v in tr.opt_state.items()}
    assert saved_opt["steps"][0] > 0  # real table state, not a dummy
    tr.dense_table.stop()

    tr2 = _make("async")
    tr2.restore_dense(saved_params, saved_opt)
    np.testing.assert_allclose(tr2.dense_table.pull(),
                               tr.dense_table.pull())
    np.testing.assert_allclose(tr2.dense_table._mom1, tr.dense_table._mom1)
    _run_steps(tr2, n_steps=1)  # and training continues
    tr2.dense_table.stop()


def test_kstep_restore_from_collapsed():
    import jax
    tr = _make("kstep", param_sync_step=2)
    _run_steps(tr, n_steps=4)
    collapsed = jax.tree.map(np.asarray, tr.eval_params())
    tr2 = _make("kstep", param_sync_step=2)
    tr2.restore_dense(collapsed)
    for a, b in zip(jax.tree.leaves(tr2.eval_params()),
                    jax.tree.leaves(collapsed)):
        np.testing.assert_allclose(np.asarray(a), b)
    _run_steps(tr2, n_steps=1)


def test_param_sync_step_validated():
    with pytest.raises(ValueError, match="param_sync_step"):
        _make("kstep", param_sync_step=0)
