"""SpillEmbeddingStore: disk-backed row tier + RAM hot cache.

Reference role: the SSD + host tiers behind libbox_ps (LoadSSD2Mem,
box_wrapper.h:487-494) — table capacity bounded by disk, not DRAM.
"""

import os

import numpy as np
import pytest

from paddlebox_tpu.data import DataFeedSchema
from paddlebox_tpu.data.parser import parse_multislot_lines
from paddlebox_tpu.data.dataset import SlotDataset
from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                     SpillEmbeddingStore)
from paddlebox_tpu.models import DNNCTRModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig


def cfg_small(**kw):
    kw.setdefault("dim", 4)
    kw.setdefault("optimizer", "adagrad")
    kw.setdefault("learning_rate", 0.1)
    return EmbeddingConfig(**kw)


def _keys(lo, hi):
    return np.arange(lo, hi, dtype=np.uint64) * np.uint64(2654435761) + 1


def test_rows_live_on_disk(tmp_path):
    c = cfg_small()
    store = SpillEmbeddingStore(c, spill_dir=str(tmp_path / "spill"),
                                cache_rows=64)
    keys = _keys(0, 5000)
    rows = store.lookup_or_init(keys)
    assert store.spill_file_bytes >= 5000 * c.row_width * 4
    # deterministic init matches the RAM store's
    ram = HostEmbeddingStore(c)
    np.testing.assert_array_equal(rows, ram.lookup_or_init(keys))


def test_parity_with_ram_store_under_mixed_ops(tmp_path):
    """Same op sequence on both stores → bit-identical state, even with a
    cache FAR smaller than the key count (cold reads fault in from disk)."""
    c = cfg_small()
    rng = np.random.default_rng(0)
    ram = HostEmbeddingStore(c)
    spill = SpillEmbeddingStore(c, spill_dir=str(tmp_path / "s"),
                                cache_rows=37)   # pathologically tiny
    all_keys = _keys(0, 3000)
    seen = set()
    for step in range(6):
        ks = rng.choice(all_keys, size=500, replace=False)
        seen.update(int(k) for k in ks)
        r1 = ram.lookup_or_init(ks)
        r2 = spill.lookup_or_init(ks)
        np.testing.assert_array_equal(r1, r2)
        upd = r1 + rng.normal(size=r1.shape).astype(np.float32)
        upd[:, 0] += 1.0                         # show counters
        ram.write_back(ks, upd)
        spill.write_back(ks, upd)
    check = np.array(sorted(seen), dtype=np.uint64)[:500]
    np.testing.assert_array_equal(ram.get_rows(check),
                                  spill.get_rows(check))
    assert spill.cache_misses > 0 and spill.cache_hits > 0


def test_shrink_decay_without_eviction_invalidates_cache(tmp_path):
    """Regression: shrink's show decay writes self._rows in place (bypassing
    _write_rows). With nothing evicted, no compaction runs — cached rows
    must still see the decayed counters, matching the RAM store exactly."""
    c = cfg_small()
    ram = HostEmbeddingStore(c)
    spill = SpillEmbeddingStore(c, spill_dir=str(tmp_path / "s"),
                                cache_rows=1024)
    keys = _keys(0, 100)
    for st in (ram, spill):
        rows = st.lookup_or_init(keys)
        rows[:, 0] = 10.0
        st.write_back(keys, rows)
        st.get_rows(keys)                 # warm the spill store's cache
        assert st.shrink(min_show=1.0, decay=0.5) == 0
    np.testing.assert_array_equal(ram.get_rows(keys), spill.get_rows(keys))
    assert spill.get_rows(keys)[:, 0].max() == 5.0


def test_shrink_and_checkpoint_roundtrip(tmp_path):
    c = cfg_small()
    spill = SpillEmbeddingStore(c, spill_dir=str(tmp_path / "s"),
                                cache_rows=50)
    keys = _keys(0, 400)
    rows = spill.lookup_or_init(keys)
    rows[:200, 0] = 5.0                          # half get shows
    spill.write_back(keys, rows)
    evicted = spill.shrink(min_show=1.0)
    assert evicted == 200
    assert len(spill) == 200
    # post-compaction reads are correct (cache was invalidated)
    np.testing.assert_allclose(spill.get_rows(keys[:200])[:, 0], 5.0)
    base = spill.save_base(str(tmp_path / "ckpt"))
    assert os.path.exists(base)
    loaded = HostEmbeddingStore.load(str(tmp_path / "ckpt"))
    np.testing.assert_array_equal(loaded.get_rows(keys[:200]),
                                  spill.get_rows(keys[:200]))


NUM_SLOTS = 4


def _ds(n, seed=0):
    rng = np.random.default_rng(seed)
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=1,
                                batch_size=64, max_len=2)
    w = np.random.default_rng(7).normal(size=(NUM_SLOTS, 4000)) * 1.5
    lines = []
    for _ in range(n):
        logits, parts, sl = 0.0, [], []
        for s in range(NUM_SLOTS):
            ids = rng.integers(0, 4000, size=2)
            sl.append(ids)
            logits += w[s, ids].sum()
        p = 1 / (1 + np.exp(-logits * 0.6))
        parts.append(f"1 {float(rng.random() < p)}")
        parts.append(f"1 {rng.normal():.3f}")
        for s, ids in enumerate(sl):
            parts.append(
                f"2 {' '.join(str(int(i) + s * 1000003) for i in ids)}")
        lines.append(" ".join(parts))
    ds = SlotDataset(schema)
    ds.records = parse_multislot_lines(lines, schema)
    return ds, schema


def test_training_with_cache_under_half_of_keys(tmp_path):
    """VERDICT r1 #3 'done' bar: train correctly with the RAM tier capped
    below 50% of the table's keys; trajectory must match the RAM store
    exactly (the spill tier is a storage choice, not a math change)."""
    ds, schema = _ds(512)
    n_keys = len(ds.unique_keys())
    results = {}
    mesh = make_mesh(8)
    for name in ("ram", "spill"):
        if name == "ram":
            store = HostEmbeddingStore(cfg_small())
        else:
            store = SpillEmbeddingStore(
                cfg_small(), spill_dir=str(tmp_path / "sp"),
                cache_rows=max(1, n_keys // 3))   # < 50% of keys in RAM
        tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4,
                                 dense_dim=1, hidden=(16,)),
                     store, schema, mesh,
                     TrainerConfig(global_batch_size=64, dense_lr=5e-3,
                                   auc_buckets=1 << 10))
        out1 = tr.train_pass(ds)
        out2 = tr.train_pass(ds)
        results[name] = (out1, out2, store)
    spill_store = results["spill"][2]
    assert spill_store._cache_slots < 0.5 * n_keys
    for i in range(2):
        assert results["ram"][i]["loss_mean"] == \
            pytest.approx(results["spill"][i]["loss_mean"], abs=1e-7)
        assert results["ram"][i]["auc"] == \
            pytest.approx(results["spill"][i]["auc"], abs=1e-7)
    # second pass learned (sanity that the comparison is not vacuous).
    # Measured on AUC, not loss_mean: pass-2 log-loss transiently RISES
    # here by construction — the CVM show/clk counter features (clk
    # accumulates the label itself, and these keys are near-singletons)
    # jump from all-zero to populated between pass 1 and 2, and the dense
    # tower is miscalibrated under that covariate shift exactly while
    # ranking improves sharply (loss_mean 0.71→0.79 while AUC 0.48→0.69;
    # by pass 3 loss drops decisively). Which side of a pass2<pass1 loss
    # assert lands is jax-version numeric luck — see ROADMAP "pass-2 loss
    # signature" root cause.
    assert results["spill"][1]["auc"] > results["spill"][0]["auc"] + 0.1
