"""Dense optimizer registry tests (reference operators/optimizers/*)."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from paddlebox_tpu.train import optimizers


def _run(tx, steps=50, lr_target=None):
    # minimize ||w - t||^2 on a small vector
    w = {"w": jnp.ones((4,), jnp.float32) * 2.0}
    t = jnp.asarray([1.0, -0.5, 0.0, 3.0], jnp.float32)
    state = tx.init(w)
    for _ in range(steps):
        g = {"w": 2.0 * (w["w"] - t)}
        upd, state = tx.update(g, state, w)
        w = optax.apply_updates(w, upd)
    return np.asarray(w["w"]), np.asarray(t)


@pytest.mark.parametrize("name", ["adam", "sgd", "momentum", "adagrad",
                                  "rmsprop", "ftrl"])
def test_all_optimizers_descend(name):
    lr = {"sgd": 0.1, "momentum": 0.05, "adam": 0.1, "adagrad": 0.5,
          "rmsprop": 0.05, "ftrl": 0.5}[name]
    w, t = _run(optimizers.make(name, lr), steps=200)
    assert np.abs(w - t).max() < 0.15, (name, w, t)


def test_ftrl_l1_sparsifies():
    # strong l1 drives small-gradient coordinates exactly to zero
    tx = optimizers.ftrl(learning_rate=0.5, l1=5.0)
    w = {"w": jnp.zeros((2,), jnp.float32)}
    state = tx.init(w)
    for _ in range(20):
        g = {"w": jnp.asarray([0.01, -4.0], jnp.float32)}
        upd, state = tx.update(g, state, w)
        w = optax.apply_updates(w, upd)
    arr = np.asarray(w["w"])
    assert arr[0] == 0.0          # tiny gradient → clipped by l1
    assert arr[1] > 0.0           # large gradient survives shrinkage


def test_ftrl_step_pinned_numerics():
    # Pin the shared FTRL-proximal rule (ops/ftrl.py) to hand-computed
    # values: sigma divides by lr (the standard alpha denominator, as in the
    # reference's ftrl_op.h) — NOT by beta.
    from paddlebox_tpu.ops.ftrl import ftrl_step
    g, z, n, w = 2.0, 0.5, 4.0, 1.0
    lr, l1, l2, beta = 0.5, 0.1, 0.2, 1.0
    new_n = n + g * g                                   # 8
    sigma = (np.sqrt(new_n) - np.sqrt(n)) / lr          # (2.828..-2)/0.5
    new_z = z + g - sigma * w
    shrink = max(abs(new_z) - l1, 0.0)
    new_w = -np.sign(new_z) * shrink / ((beta + np.sqrt(new_n)) / lr + l2)
    got = ftrl_step(jnp.float32(g), jnp.float32(z), jnp.float32(n),
                    jnp.float32(w), lr, l1, l2, beta)
    np.testing.assert_allclose(np.asarray(got[0]), new_w, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got[1]), new_z, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got[2]), new_n, rtol=1e-6)


def test_ftrl_tuple_container_pytree():
    # param trees with tuple containers must round-trip leaf-wise
    tx = optimizers.ftrl(learning_rate=0.5)
    w = {"layer": (jnp.ones((2,), jnp.float32), jnp.zeros((3,), jnp.float32))}
    state = tx.init(w)
    g = {"layer": (jnp.ones((2,), jnp.float32) * 0.1,
                   jnp.ones((3,), jnp.float32) * 0.1)}
    upd, state = tx.update(g, state, w)
    assert upd["layer"][0].shape == (2,)
    assert upd["layer"][1].shape == (3,)


def test_unknown_name_raises():
    with pytest.raises(ValueError):
        optimizers.make("lamb", 0.1)


def test_trainer_accepts_ftrl():
    from paddlebox_tpu.train.trainer import TrainerConfig, _dense_tx
    tx = _dense_tx(TrainerConfig(dense_optimizer="ftrl", dense_lr=0.1))
    w = {"w": jnp.ones((3,), jnp.float32)}
    st = tx.init(w)
    upd, _ = tx.update({"w": jnp.ones((3,), jnp.float32)}, st, w)
    assert upd["w"].shape == (3,)
