"""Observability: RecordEvent spans, chrome trace, stats, nan guard, dumps."""

import json
import os
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.utils import profiler as prof


def test_record_event_spans_and_chrome_trace(tmp_path):
    prof.enable_profiler()
    try:
        with prof.RecordEvent("outer"):
            with prof.RecordEvent("inner"):
                pass
        @prof.RecordEvent("decorated")
        def f(x):
            return x + 1
        assert f(1) == 2
    finally:
        prof.disable_profiler()
    evs = prof.profiler_events()
    names = [e["name"] for e in evs]
    assert names == ["inner", "outer", "decorated"]  # inner closes first
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in evs)
    # nesting: outer must contain inner
    by = {e["name"]: e for e in evs}
    assert by["outer"]["ts"] <= by["inner"]["ts"]
    assert (by["outer"]["ts"] + by["outer"]["dur"]
            >= by["inner"]["ts"] + by["inner"]["dur"])

    path = str(tmp_path / "trace.json")
    n = prof.export_chrome_trace(path)
    assert n == 3
    with open(path) as f:
        doc = json.load(f)
    assert len(doc["traceEvents"]) == 3


def test_record_event_disabled_is_free():
    prof.disable_profiler()
    before = len(prof.profiler_events())
    with prof.RecordEvent("ignored"):
        pass
    assert len(prof.profiler_events()) == before


def test_stat_registry_threaded():
    reg = prof.StatRegistry()
    def add_many():
        for _ in range(1000):
            reg.add("n")
    ts = [threading.Thread(target=add_many) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert reg.get("n") == 4000
    reg.set("x", 2.5)
    assert "n=4000" in reg.report() and "x=2.5" in reg.report()
    reg.reset()
    assert reg.get("n") == 0


def test_find_nonfinite_and_dump(tmp_path):
    good = {"a": jnp.ones(3), "b": {"c": np.zeros(2, np.float32)}}
    assert prof.find_nonfinite(good) == []
    bad = {"a": jnp.ones(3), "b": {"c": np.array([1.0, np.nan])},
           "ints": np.arange(3)}  # int leaves are skipped
    paths = prof.find_nonfinite(bad)
    assert len(paths) == 1 and "c" in paths[0]

    out = prof.dump_tree(str(tmp_path / "scope"), bad)
    loaded = np.load(out)
    assert any("c" in k for k in loaded.files)


def test_dump_stream(tmp_path):
    path = str(tmp_path / "dump" / "fields.txt")
    with prof.DumpStream(path) as ds:
        ds.write("hello")
        ds.write_fields(7, [0.25, 0.75], [0.0, 1.0], extra={"rank": [1, 2]})
    with open(path) as f:
        lines = f.read().splitlines()
    assert lines[0] == "hello"
    assert lines[1].startswith("7 0 0.250000 0") and "rank:1" in lines[1]
    assert lines[2].startswith("7 1 0.750000 1") and "rank:2" in lines[2]


def test_trainer_dump_and_nan_guard(tmp_path):
    # integration: dump_fields writes one line per example; nan trip dumps
    # the scope
    import jax
    from paddlebox_tpu.data import DataFeedSchema
    from paddlebox_tpu.data.dataset import SlotDataset
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig

    schema = DataFeedSchema.ctr(num_sparse=3, num_float=1, batch_size=8,
                                max_len=2)
    rng = np.random.default_rng(0)
    ds = SlotDataset(schema)
    lines = []
    for i in range(16):
        # schema order: label, dense_0, slot_0..2
        parts = [f"1 {int(rng.random() < 0.4)}", f"1 {rng.random():.3f}"]
        for s in range(3):
            parts.append(f"2 {rng.integers(1, 1000)} {rng.integers(1, 1000)}")
        lines.append(" ".join(parts))
    f = tmp_path / "part-0"
    f.write_text("\n".join(lines) + "\n")
    ds.set_filelist([str(f)])
    ds.load_into_memory(global_shuffle=False)

    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    mesh = make_mesh(1)
    model = DNNCTRModel(num_slots=3, emb_dim=4, dense_dim=1, hidden=(8,))
    dump_path = str(tmp_path / "fields.txt")
    tr = Trainer(model, store, schema, mesh,
                 TrainerConfig(global_batch_size=8,
                               auc_buckets=1 << 8,
                               dump_fields_path=dump_path))
    out = tr.train_pass(ds)
    assert out["steps"] == 2
    with open(dump_path) as fh:
        dumped = fh.read().splitlines()
    assert len(dumped) == 16  # one line per trained example


def test_dump_field_param_parity(tmp_path):
    """Configurable DumpField columns (ins_id + slots) and DumpParam
    (trainer_desc.proto:39-45)."""
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig

    schema = DataFeedSchema.ctr(num_sparse=3, num_float=1, batch_size=8,
                                max_len=2)
    rng = np.random.default_rng(0)
    ds = SlotDataset(schema)
    ds.with_ins_id = True
    lines = []
    for i in range(16):
        parts = [f"1 {int(rng.random() < 0.4)}", f"1 {rng.random():.3f}"]
        for s in range(3):
            parts.append(f"2 {rng.integers(1, 1000)} {rng.integers(1, 1000)}")
        lines.append(f"ins_{i:04d}\t" + " ".join(parts))
    f = tmp_path / "part-0"
    f.write_text("\n".join(lines) + "\n")
    ds.set_filelist([str(f)])
    ds.load_into_memory(global_shuffle=False)
    assert ds.records.ins_id.any()

    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    mesh = make_mesh(1)
    model = DNNCTRModel(num_slots=3, emb_dim=4, dense_dim=1, hidden=(8,))
    dump_path = str(tmp_path / "fields.txt")
    tr = Trainer(model, store, schema, mesh,
                 TrainerConfig(global_batch_size=8, auc_buckets=1 << 8,
                               dump_fields_path=dump_path,
                               dump_fields=("ins_id", "dense_0", "slot_1"),
                               dump_param=("mlp",)))
    tr.train_pass(ds)
    with open(dump_path) as fh:
        dumped = fh.read().splitlines()
    inst = [l for l in dumped if l.startswith("param") is False]
    params = [l for l in dumped if l.startswith("param")]
    assert len(inst) == 16
    # every instance line carries the configured columns
    for l in inst:
        assert "ins_id:" in l and "dense_0:" in l and "slot_1:" in l
    # slot_1 column carries comma-joined raw feature signs
    assert any("," in l.split("slot_1:")[1] for l in inst)
    # param dump matched the mlp tree
    assert params and all(l.startswith("param mlp") for l in params)
