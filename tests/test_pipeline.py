"""Pipeline parallelism: GPipe schedule == sequential stage composition.

Mirrors what the reference cannot test in CI (PipelineTrainer needs real
GPUs): here the pp axis runs on virtual CPU devices and the schedule is
checked numerically, forward and backward, against running the stages
back-to-back on one device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from paddlebox_tpu.parallel import pipeline as pp


def _stage_params(rng, n_stages, layers_per_stage, width):
    per_stage = []
    for _ in range(n_stages):
        per_stage.append({
            "w": jnp.asarray(rng.normal(
                size=(layers_per_stage, width, width)).astype(np.float32)
                / np.sqrt(width)),
            "b": jnp.asarray(rng.normal(
                size=(layers_per_stage, width)).astype(np.float32) * 0.01),
        })
    return per_stage


def _sequential(stage_fn, per_stage, x):
    h = x
    for p in per_stage:
        h = stage_fn(p, h)
    return h


@pytest.mark.parametrize("n_stages,n_micro", [(4, 8), (2, 2), (8, 16)])
def test_gpipe_matches_sequential(n_stages, n_micro):
    rng = np.random.default_rng(0)
    width, batch = 16, 32
    mesh = Mesh(np.array(jax.devices()[:n_stages]), (pp.PP_AXIS,))
    stage_fn = pp.mlp_stage_fn()
    per_stage = _stage_params(rng, n_stages, 2, width)
    stacked = pp.stack_stage_params(per_stage)
    x = jnp.asarray(rng.normal(size=(batch, width)).astype(np.float32))

    fn = pp.make_pipeline(mesh, stage_fn, num_microbatches=n_micro)
    got = fn(stacked, x)
    want = _sequential(stage_fn, per_stage, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_gpipe_backward_matches_sequential():
    rng = np.random.default_rng(1)
    width, batch, n_stages, n_micro = 8, 16, 4, 4
    mesh = Mesh(np.array(jax.devices()[:n_stages]), (pp.PP_AXIS,))
    stage_fn = pp.mlp_stage_fn(activation=jnp.tanh)
    per_stage = _stage_params(rng, n_stages, 1, width)
    stacked = pp.stack_stage_params(per_stage)
    x = jnp.asarray(rng.normal(size=(batch, width)).astype(np.float32))
    tgt = jnp.asarray(rng.normal(size=(batch, width)).astype(np.float32))

    fn = pp.make_pipeline(mesh, stage_fn, num_microbatches=n_micro)

    def loss_pp(stacked):
        return jnp.mean((fn(stacked, x) - tgt) ** 2)

    def loss_seq(stacked):
        per = [jax.tree.map(lambda a, i=i: a[i], stacked)
               for i in range(n_stages)]
        return jnp.mean((_sequential(stage_fn, per, x) - tgt) ** 2)

    g_pp = jax.grad(loss_pp)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g_pp, g_seq)


def test_gpipe_composes_with_data_parallel():
    rng = np.random.default_rng(2)
    width, batch = 8, 32
    n_pp, n_dp, n_micro = 4, 2, 4
    devs = np.array(jax.devices()[:n_pp * n_dp]).reshape(n_dp, n_pp)
    mesh = Mesh(devs, ("dp", pp.PP_AXIS))
    stage_fn = pp.mlp_stage_fn()
    per_stage = _stage_params(rng, n_pp, 1, width)
    stacked = pp.stack_stage_params(per_stage)
    x = jnp.asarray(rng.normal(size=(batch, width)).astype(np.float32))

    fn = pp.make_pipeline(mesh, stage_fn, num_microbatches=n_micro,
                          dp_axis="dp")
    got = fn(stacked, x)
    want = _sequential(stage_fn, per_stage, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_split_stages_cut_list():
    layers = list(range(10))
    assert pp.split_stages(layers, num_stages=2) == [list(range(5)),
                                                     list(range(5, 10))]
    got = pp.split_stages(layers, cut_list=[3, 7])
    assert got == [[0, 1, 2], [3, 4, 5, 6], [7, 8, 9]]
    with pytest.raises(ValueError):
        pp.split_stages(layers, cut_list=[7, 3])
