"""World trace (ISSUE 15): cross-rank distributed tracing with a
clock-corrected merged timeline.

The acceptance bar: a merged Perfetto trace from a 2-rank run shows
ALIGNED timelines (injected skew recovered within tolerance) with
causal flow edges across the exchange and from the end_pass publish to
the serving swap — proven here — and tracing disabled costs one
enabled-check per scope (micro-test, same contract as the hub's
disabled event path). Every record the write side emits passes
``flight.validate_event``.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

from paddlebox_tpu import monitor
from paddlebox_tpu.config import flags
from paddlebox_tpu.monitor import critical_path as cp_lib
from paddlebox_tpu.monitor import flight, names
from paddlebox_tpu.monitor import trace as trace_lib
from paddlebox_tpu.monitor.aggregate import EVIDENCE_EVENTS

TRACE_FLAGS = ("trace", "trace_sample_passes", "trace_run_id",
               "trace_device", "trace_device_dir")


@pytest.fixture(autouse=True)
def _clean(tmp_path):
    saved = {k: flags.get(k) for k in TRACE_FLAGS}
    h = monitor.hub()
    h.disable()
    h.abort_pass(reason="test setup")
    trace_lib.on_end_pass()
    trace_lib._SAW_PASS = False     # each test is its own "process"
    yield
    trace_lib.on_end_pass()
    trace_lib._SAW_PASS = False
    h.abort_pass(reason="test teardown")
    h.disable()
    for k, v in saved.items():
        flags.set(k, v)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _emit_rank_stream(dirpath, pass_id=1, steps=2):
    """One traced pass emitted through the REAL pipeline: JsonlSink +
    begin_pass + spans + exchange flow points + end_pass."""
    flags.set("trace", True)
    h = monitor.hub()
    h.enable(monitor.JsonlSink(os.path.join(dirpath, "events.jsonl")))
    h.begin_pass(pass_id, phase=1)
    assert trace_lib.active()
    for s in range(steps):
        monitor.context.set_step(s)
        with monitor.span("pack_batch"):
            pass
        trace_lib.flow("exchange", f"p{pass_id}.s{s}",
                       wire="f32", tokens=64, bytes_bound=4096)
        with monitor.span("train_step"):
            time.sleep(0.001)
    h.record_train(stage_seconds={"read": 0.01}, steps=steps,
                   examples=steps * 64, seconds=0.01)
    h.end_pass()
    h.disable()
    return os.path.join(dirpath, "events.jsonl")


def _shift_stream(src_file, dst_dir, shift_s):
    """A second 'rank' = the first stream with every wall clock shifted
    (the injected skew): same records, skewed host."""
    os.makedirs(dst_dir, exist_ok=True)
    out = os.path.join(dst_dir, "events.jsonl")
    with open(src_file) as f, open(out, "w") as g:
        for line in f:
            rec = json.loads(line)
            if isinstance(rec.get("ts"), (int, float)):
                rec["ts"] = rec["ts"] + shift_s
            g.write(json.dumps(rec) + "\n")
    return out


def _append_probe(path, observer, peer, offset_s, rtt_s=0.01):
    rec = {"ts": time.time(), "type": "event",
           "name": "trace.clock_probe", "pass_id": None, "step": None,
           "phase": None, "thread": "hb",
           "fields": {"observer": observer, "peer": peer,
                      "offset_s": offset_s, "rtt_s": rtt_s}}
    assert flight.validate_event(rec) == []
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")


def _pass_slices(trace, pid):
    return [e for e in trace["traceEvents"]
            if e.get("ph") == "X" and e.get("pid") == pid
            and str(e.get("name", "")).startswith("pass ")]


# ---------------------------------------------------------------------------
# the acceptance tests
# ---------------------------------------------------------------------------

def test_two_rank_merge_recovers_injected_skew(tmp_path):
    """2-rank merge: rank1 is rank0's stream with +5s of injected wall
    skew; a clock probe recovers the offset and the merged timelines
    ALIGN within tolerance (they are ~5s apart uncorrected)."""
    d0 = str(tmp_path / "rank0")
    os.makedirs(d0)
    f0 = _emit_rank_stream(d0)
    skew = 5.0
    _shift_stream(f0, str(tmp_path / "rank1"), skew)
    _append_probe(f0, observer=0, peer=1, offset_s=skew)

    merged = trace_lib.merge_roots([d0, str(tmp_path / "rank1")])
    summary = trace_lib.summarize(merged)
    assert summary["ranks"] == [0, 1]
    # the injected skew is recovered ~exactly (a single exact probe)
    assert abs(summary["clock_offsets_s"]["1"] - skew) < 1e-6
    assert summary["clock_corrected_ranks"] == [0, 1]
    p0, p1 = _pass_slices(merged, 0), _pass_slices(merged, 1)
    assert p0 and p1
    assert abs(p0[0]["ts"] - p1[0]["ts"]) < 0.05 * 1e6   # aligned

    # exchange flow edges present, cross-rank, ~zero latency corrected
    ex = [e for e in summary["flow_edges"] if e["kind"] == "exchange"]
    assert len(ex) == 2                      # one per step
    for e in ex:
        assert {e["src_rank"], e["dst_rank"]} == {0, 1}
        assert abs(e["latency_s"]) < 0.05
    # the chrome flow events pair s/f on shared ids
    s_ids = {e["id"] for e in merged["traceEvents"] if e.get("ph") == "s"}
    f_ids = {e["id"] for e in merged["traceEvents"] if e.get("ph") == "f"}
    assert s_ids and s_ids == f_ids

    # WITHOUT the probe, the same merge is ~5s misaligned — the
    # correction is real, not an artifact of the fixture
    raw = trace_lib.read_trace_records(d0)
    raw["clock_probes"] = []
    other = trace_lib.read_trace_records(str(tmp_path / "rank1"))
    uncorrected = trace_lib.merge_streams([raw, other], [0, 1])
    q0, q1 = _pass_slices(uncorrected, 0), _pass_slices(uncorrected, 1)
    assert abs(q0[0]["ts"] - q1[0]["ts"]) > 4.0 * 1e6


def test_every_emitted_record_passes_validate_event(tmp_path):
    d0 = str(tmp_path / "rank0")
    os.makedirs(d0)
    f0 = _emit_rank_stream(d0)
    out = flight.validate_events_file(f0)
    assert out["errors"] == []
    assert out["events"] > 0 and out["flight_records"]


def test_trace_ids_and_parent_links(tmp_path):
    """Span records carry their own span_id with a parent chain rooted
    at the pass; event records point at their enclosing span."""
    flags.set("trace", True)
    ms = monitor.MemorySink()
    h = monitor.hub()
    h.enable(ms)
    h.begin_pass(3)
    with monitor.span("pack_batch"):
        with monitor.span("train_step"):
            monitor.event("nan_guard", n_bad=0)
    h.end_pass()
    by_name = {r["name"]: r for r in ms.records}
    outer, inner = by_name["pack_batch"], by_name["train_step"]
    ev, fr = by_name["nan_guard"], by_name["pass"]
    tid = outer["trace_id"]
    assert tid and tid.endswith(":3")
    assert all(r.get("trace_id") == tid for r in (inner, ev, fr))
    assert inner["parent_span_id"] == outer["span_id"]
    assert ev["parent_span_id"] == inner["span_id"]
    assert fr["span_id"] == outer["parent_span_id"]  # the pass root
    assert fr["parent_span_id"] is None
    for r in ms.records:
        assert flight.validate_event(r) == []


def test_sampling_gates_whole_passes(tmp_path):
    flags.set("trace", True)
    flags.set("trace_sample_passes", 2)
    h = monitor.hub()
    ms = monitor.MemorySink()
    h.enable(ms)
    h.begin_pass(1)                  # 1 % 2 != 0 -> unsampled
    assert not trace_lib.active()
    with monitor.span("pack_batch"):
        pass
    h.end_pass()
    h.begin_pass(2)                  # sampled
    assert trace_lib.active()
    with monitor.span("pack_batch"):
        pass
    h.end_pass()
    spans = [r for r in ms.records if r["name"] == "pack_batch"]
    assert len(spans) == 2
    assert "trace_id" not in spans[0]      # unsampled: no trace plane
    assert spans[1]["trace_id"].endswith(":2")


def test_disabled_cost_is_one_check():
    """Tracing off: flow() and the hub-record stamp cost one module-flag
    check — the same micro-contract as the hub's disabled event path."""
    assert not trace_lib.active()
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        trace_lib.flow("exchange", "p0.s0", wire="f32")
    cost = (time.perf_counter() - t0) / n
    assert cost < 5e-6, f"disabled flow() costs {cost:.2e}s"


# ---------------------------------------------------------------------------
# heartbeat clock probes (the real round trip, skew injected)
# ---------------------------------------------------------------------------

def test_heartbeat_roundtrip_emits_clock_probe_with_skew(tmp_path):
    from paddlebox_tpu.distributed.resilience import HeartbeatMonitor
    from paddlebox_tpu.distributed.store import FileStore
    st = FileStore(str(tmp_path), timeout_s=1.0)
    h = monitor.hub()
    ms = monitor.MemorySink()
    h.enable(ms)
    hb0 = HeartbeatMonitor(st, 0, 2, run_id="r", watch=False, start=False)
    hb1 = HeartbeatMonitor(st, 1, 2, run_id="r", watch=False, start=False)
    skew = 5.0
    hb1._wall = lambda: time.time() + skew     # rank1's host runs fast
    try:
        hb0.publish()                  # t0 leaves rank0
        hb1.scan()                     # rank1 observes it (t1, skewed)
        hb1.publish()                  # echo + t2 leave rank1
        hb0.scan()                     # rank0 closes the loop (t3)
    finally:
        hb0.close()
        hb1.close()
    probes = ms.find("trace.clock_probe")
    mine = [p for p in probes if (p["fields"] or {}).get("observer") == 0]
    assert mine, f"no probe from rank0 in {probes}"
    f = mine[-1]["fields"]
    assert f["peer"] == 1
    # the estimate recovers the injected skew within the store rtt
    assert abs(f["offset_s"] - skew) < 0.5
    assert f["rtt_s"] >= 0
    assert flight.validate_event(mine[-1]) == []


# ---------------------------------------------------------------------------
# publish -> serving swap (cross-process propagation through the donefile)
# ---------------------------------------------------------------------------

def test_publish_to_swap_flow_edge(tmp_path):
    """The full loop: a traced end_pass publishes (trace ids stamped
    into the donefile entry + a publish/src flow point), a serving
    process swaps it in (publish/dst flow point carrying the parent
    link), and the merged world trace shows the causal edge."""
    from test_train_e2e import synth_dataset, NUM_SLOTS
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.fleet import BoxPS, FleetUtil
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.serving import (DONEFILE, ServingPublisher,
                                       ServingServer)
    from paddlebox_tpu.train import Trainer, TrainerConfig

    flags.set("trace", True)
    ds, schema = synth_dataset(128)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, learning_rate=0.15))
    model = DeepFMModel(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1,
                        hidden=(16,))
    tr = Trainer(model, store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=64, dense_lr=3e-3))
    box = BoxPS(store)
    root = str(tmp_path / "serve")
    pub = ServingPublisher(root, model, schema, quant="f32", hot_top_k=8)

    d_train = str(tmp_path / "rank0")
    h = monitor.hub()
    h.enable(monitor.JsonlSink(os.path.join(d_train, "events.jsonl")))
    box.begin_pass()
    tr.train_pass(ds)
    out = box.end_pass(trainer=tr, publisher=pub)
    assert out["publish"]["announced"]
    h.disable()

    # the donefile entry carries the publish span's trace context
    entry = FleetUtil(root).latest(DONEFILE)
    assert isinstance(entry.get("trace"), dict)
    assert entry["trace"]["trace_id"] and entry["trace"]["span_id"]

    # serving side: its own telemetry stream (a second "rank")
    d_serve = str(tmp_path / "rank1")
    h.enable(monitor.JsonlSink(os.path.join(d_serve, "events.jsonl")))
    srv = ServingServer(root, poll_s=0.05)
    assert srv.poll_once() == 1
    h.disable()

    merged = trace_lib.merge_roots([d_train, d_serve])
    summary = trace_lib.summarize(merged)
    pub_edges = [e for e in summary["flow_edges"]
                 if e["kind"] == "publish"]
    assert pub_edges, f"no publish edge in {summary['flow_edges']}"
    e = pub_edges[0]
    assert e["key"] == "v1"
    assert e["src_rank"] == 0 and e["dst_rank"] == 1
    assert e["latency_s"] >= 0
    # the swap-side point carries the explicit parent link back to the
    # publish span that produced the version
    assert e["fields"]["parent_span_id"] == entry["trace"]["span_id"]
    assert e["fields"]["parent_trace_id"] == entry["trace"]["trace_id"]
    # both streams stay schema-clean end to end
    for d in (d_train, d_serve):
        out = flight.validate_events_file(os.path.join(d, "events.jsonl"))
        assert out["errors"] == []


def test_request_spans_parent_linked_across_processes(tmp_path):
    """ISSUE 19 acceptance: a REAL serving process (subprocess, own hub
    + JsonlSink + standing serving scope) serves a version this process
    published under a traced pass; the merged world trace contains
    request-level ``serve/score`` spans parent-linked — through the
    donefile-carried publish ids — to the publish span, across the
    process boundary. One timeline: train pass -> publish -> swap ->
    requests."""
    import subprocess
    import sys

    from test_train_e2e import synth_dataset, NUM_SLOTS
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.fleet import BoxPS, FleetUtil
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.serving import DONEFILE, ServingPublisher
    from paddlebox_tpu.train import Trainer, TrainerConfig

    flags.set("trace", True)
    ds, schema = synth_dataset(128)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, learning_rate=0.15))
    model = DeepFMModel(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1,
                        hidden=(16,))
    tr = Trainer(model, store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=64, dense_lr=3e-3))
    box = BoxPS(store)
    root = str(tmp_path / "serve")
    pub = ServingPublisher(root, model, schema, quant="f32", hot_top_k=8)

    d_train = str(tmp_path / "rank0")
    h = monitor.hub()
    h.enable(monitor.JsonlSink(os.path.join(d_train, "events.jsonl")))
    box.begin_pass()
    tr.train_pass(ds)
    assert box.end_pass(trainer=tr, publisher=pub)["publish"]["announced"]
    h.disable()
    entry = FleetUtil(root).latest(DONEFILE)
    assert isinstance(entry.get("trace"), dict)

    # the serving process: fresh interpreter, request tracing sampled at
    # every batch, serving telemetry to its own "rank" directory
    d_serve = str(tmp_path / "rank1")
    os.makedirs(d_serve)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PBTPU_TRACE="1",
               PBTPU_SERVING_TRACE_SAMPLE="1")
    env.pop("PBTPU_FAULTPOINT", None)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(repo, "tests", "serving_obs_worker.py"),
         root, d_serve, "--requests", "16"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    out = json.loads(proc.stdout.splitlines()[-1])
    assert out["version"] == 1 and out["served"] >= 16

    # the serving stream carries sampled request spans whose payload
    # parent ids are EXACTLY the donefile-carried publish ids
    records = [json.loads(ln) for ln in
               open(os.path.join(d_serve, "events.jsonl"))]
    score_spans = [r for r in records if r.get("name") == "serve/score"]
    assert score_spans, "no sampled serve/score span in the stream"
    for r in score_spans:
        assert r["fields"]["parent_span_id"] == entry["trace"]["span_id"]
        assert r["fields"]["parent_trace_id"] == entry["trace"]["trace_id"]
    assert any(r.get("name") == "serve/wait" for r in records)
    assert any(r.get("type") == "serving_record" for r in records)

    # the merged world trace draws the cross-process parent link: the
    # publish span lives in the TRAINER's stream, the request spans in
    # the serving process's — linked via the propagated ids
    merged = trace_lib.merge_roots([d_train, d_serve])
    summary = trace_lib.summarize(merged)
    assert summary["linked_spans"] >= 1
    assert summary["linked_edges"] >= 1
    pub_edges = [e for e in summary["flow_edges"]
                 if e["kind"] == "publish"]
    assert pub_edges and pub_edges[0]["dst_rank"] == 1
    # both streams schema-clean end to end (the serving record included)
    for d in (d_train, d_serve):
        res = flight.validate_events_file(os.path.join(d, "events.jsonl"))
        assert res["errors"] == []


# ---------------------------------------------------------------------------
# CLI + doctor integration
# ---------------------------------------------------------------------------

def test_trace_cli_writes_perfetto_json(tmp_path, capsys):
    d0 = str(tmp_path / "rank0")
    os.makedirs(d0)
    f0 = _emit_rank_stream(d0)
    _shift_stream(f0, str(tmp_path / "rank1"), 2.0)
    _append_probe(f0, observer=0, peer=1, offset_s=2.0)
    out = str(tmp_path / "world_trace.json")
    rc = trace_lib.main([d0, str(tmp_path / "rank1"), "-o", out,
                         "--json"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["out"] == out
    assert abs(summary["clock_offsets_s"]["1"] - 2.0) < 1e-6
    with open(out) as f:
        trace = json.load(f)
    phs = {e.get("ph") for e in trace["traceEvents"]}
    assert {"X", "M", "s", "f"} <= phs


def test_trace_cli_refuses_empty_inputs(tmp_path, capsys):
    d = tmp_path / "empty"
    d.mkdir()
    (d / "events.jsonl").write_text("")
    assert trace_lib.main([str(d)]) == 2
    assert trace_lib.main([]) == 2


def _write_doctor_world(tmp_path, latency_s):
    """Two synthetic rank streams whose publish flow edge takes
    ``latency_s`` against a 10s pass wall."""
    t = time.time()
    fr = {"ts": t, "type": "flight_record", "name": "pass", "pass_id": 1,
          "step": None, "phase": 1, "thread": "Main", "seconds": 10.0,
          "train_seconds": 6.0, "steps": 8, "examples": 1024,
          "examples_per_sec": 102.4,
          "stage_seconds": {"train": 6.0}, "stats_delta": {},
          "metrics": {}, "owner": "box"}
    assert flight.validate_flight_record(fr) == []

    def flow_rec(ts, role):
        return {"ts": ts, "type": "flow", "name": "trace.flow",
                "pass_id": 1, "step": None, "phase": None, "thread": "M",
                "fields": {"kind": "publish", "key": "v9", "role": role}}
    d0, d1 = tmp_path / "rank0", tmp_path / "rank1"
    d0.mkdir(), d1.mkdir()
    (d0 / "events.jsonl").write_text(
        json.dumps(fr) + "\n" + json.dumps(flow_rec(t, "src")) + "\n")
    (d1 / "events.jsonl").write_text(
        json.dumps(flow_rec(t + latency_s, "dst")) + "\n")
    return str(d0), str(d1)


def test_doctor_cli_reports_cross_rank_flow(tmp_path, capsys):
    from paddlebox_tpu.monitor import doctor
    d0, d1 = _write_doctor_world(tmp_path, latency_s=4.0)  # 40% of wall
    assert doctor.main([d0, d1, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["world_trace"]["flow_edges"]
    status = {r["rule"]: r["status"] for r in rep["rules"]}
    assert status["cross-rank-flow"] == "fired"
    f = next(f for f in rep["findings"] if f["rule"] == "cross-rank-flow")
    assert f["evidence"]["longest_edge"]["kind"] == "publish"
    assert f["evidence"]["longest_edge"]["latency_s"] == pytest.approx(
        4.0, abs=0.01)
    # --fail-on: the CI gate exits 1 on a warn-or-worse finding
    assert doctor.main([d0, d1, "--json", "--fail-on", "warn"]) == 1
    capsys.readouterr()
    assert doctor.main([d0, d1, "--json", "--fail-on", "critical"]) == 0
    capsys.readouterr()
    assert doctor.main(["--fail-on", "bogus", d0]) == 2


def test_doctor_cli_quiet_without_trace_records(tmp_path, capsys):
    """A stream with no trace plane: the rule is no-data, never an
    error, and the report has no world_trace key."""
    from paddlebox_tpu.monitor import doctor
    d0, _ = _write_doctor_world(tmp_path, latency_s=0.0)
    # strip the flow records: keep only the flight record
    p = os.path.join(d0, "events.jsonl")
    lines = [ln for ln in open(p) if "trace.flow" not in ln]
    open(p, "w").writelines(lines)
    assert doctor.main([d0, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert "world_trace" not in rep
    status = {r["rule"]: r["status"] for r in rep["rules"]}
    assert status["cross-rank-flow"] == "no-data"


# ---------------------------------------------------------------------------
# attribution + satellites
# ---------------------------------------------------------------------------

def test_attribute_flow_edges_names_longest():
    edges = [
        {"kind": "exchange", "key": "p1.s0", "src_rank": 0,
         "dst_rank": 1, "latency_s": 0.2},
        {"kind": "publish", "key": "v3", "src_rank": 0, "dst_rank": 2,
         "latency_s": 3.0},
        {"kind": "exchange", "key": "p1.s1", "src_rank": 1,
         "dst_rank": 0, "latency_s": -0.01},
    ]
    fa = cp_lib.attribute_flow_edges(edges, wall_seconds_mean=10.0)
    assert fa["edges"] == 3
    assert fa["longest"]["kind"] == "publish"
    assert fa["longest"]["dst_rank"] == 2
    assert fa["longest_share_of_wall"] == pytest.approx(0.3)
    assert fa["by_kind"]["exchange"]["count"] == 2
    assert fa["negative_edges"] == 1
    assert cp_lib.attribute_flow_edges([]) == {
        "edges": 0, "longest": None, "by_kind": {}}


def test_exchange_flow_fields_shape():
    from paddlebox_tpu.embedding import EmbeddingConfig
    from paddlebox_tpu.embedding import exchange
    f = exchange.flow_fields(EmbeddingConfig(dim=8), "bf16", 128)
    assert f["wire"] == "bf16" and f["tokens"] == 128
    assert isinstance(f["bytes_bound"], int) and f["bytes_bound"] > 0


def test_prometheus_exports_sink_health_gauges():
    h = monitor.hub()
    # zero-filled even with no sinks: an alert on the series is defined
    text = h.prometheus_text()
    assert "pbtpu_monitor_sinks_attached 0" in text
    assert "# TYPE pbtpu_monitor_sinks_unhealthy gauge" in text
    ms = monitor.MemorySink()
    h.enable(ms)
    ms.dropped = 7
    text = h.prometheus_text()
    assert "pbtpu_monitor_sinks_attached 1" in text
    assert "pbtpu_monitor_sink_dropped_events 7" in text
    assert "pbtpu_monitor_sinks_unhealthy 1" in text


def test_event_name_registry_is_closed_and_consistent():
    assert len(set(names.EVENT_NAMES)) == len(names.EVENT_NAMES)
    assert len(set(names.SPAN_NAMES)) == len(names.SPAN_NAMES)
    # every evidence event the aggregator retains is a registered name
    assert set(EVIDENCE_EVENTS) <= set(names.EVENT_NAMES)
    for n in ("trace.flow", "trace.clock_probe", "trace.device_capture",
              "serving_swap", "pass_begin"):
        assert names.is_registered(n)
    for n in ("pack_batch", "train_step", "publish"):
        assert n in names.SPAN_NAMES
    assert not names.is_registered("totally_made_up")


def test_ensure_service_never_clobbers_a_training_process():
    """Co-located publisher+server: once a process has opened ANY pass
    scope, the pass lifecycle owns the trace window — a serving poll
    must not re-activate tracing inside an unsampled pass or between
    passes (the review-found sampling-clobber hazard)."""
    flags.set("trace", True)
    flags.set("trace_sample_passes", 2)
    h = monitor.hub()
    h.enable(monitor.MemorySink())
    h.begin_pass(1)                       # unsampled (1 % 2 != 0)
    assert not trace_lib.active()
    assert trace_lib.ensure_service("serving") is False
    assert not trace_lib.active()         # sampling decision intact
    h.end_pass()
    assert trace_lib.ensure_service("serving") is False
    assert not trace_lib.active()         # between passes too
    # a fresh pass-less process (fixture resets the latch) activates
    trace_lib._SAW_PASS = False
    assert trace_lib.ensure_service("serving") is True
    assert trace_lib.active()


def test_flow_propagated_pairs_under_producer_run(tmp_path):
    """A serving host with DEFAULT flags (no local trace scope, no
    matching trace_run_id) still lands the publish->swap edge: the
    donefile-carried parent ids activate the dst point and the merger
    pairs it under the PRODUCER's run prefix."""
    h = monitor.hub()
    ms = monitor.MemorySink()
    h.enable(ms)
    # producer side: traced pass under run id "jobA"
    flags.set("trace", True)
    flags.set("trace_run_id", "jobA")
    h.begin_pass(5)
    trace_lib.flow("publish", "v7", role="src")
    h.end_pass()
    # consumer side: tracing OFF locally, only the propagated parent
    flags.set("trace", False)
    trace_lib._SAW_PASS = False
    assert not trace_lib.active()
    trace_lib.flow_propagated("publish", "v7", "dst",
                              {"trace_id": "jobA:5", "span_id": "s-9"},
                              swap_pause_ms=0.1)
    # no parent + no local scope -> no-op (an untraced run stays silent)
    trace_lib.flow_propagated("publish", "v8", "dst", None)
    h.disable()
    flows = [r for r in ms.records if r.get("name") == "trace.flow"]
    assert len(flows) == 2                 # v8 never emitted
    stream = trace_lib.records_to_stream(ms.records)
    summary = trace_lib.summarize(trace_lib.merge_streams([stream], [0]))
    edges = [e for e in summary["flow_edges"] if e["kind"] == "publish"]
    assert len(edges) == 1 and edges[0]["key"] == "v7"
    assert edges[0]["fields"]["parent_span_id"] == "s-9"


def test_ntp_offset_math():
    # observer clock = 0-based; peer clock = observer + 3; delay 0.1 each way
    t0 = 100.0
    t1 = (t0 + 0.1) + 3.0        # peer reads after 0.1s, peer clock
    t2 = t1 + 0.05               # peer publishes echo 0.05s later
    t3 = (t2 - 3.0) + 0.1        # observer reads 0.1s after, its clock
    off, rtt = trace_lib.ntp_offset(t0, t1, t2, t3)
    assert off == pytest.approx(3.0, abs=1e-9)
    assert rtt == pytest.approx(0.2, abs=1e-9)
