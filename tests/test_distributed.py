"""Control plane: FileStore, HostCollectives, launcher, data generator.

Multi-host behavior is tested the reference's way (test_collective_base.py:
spawn real worker subprocesses on localhost and run actual exchanges)."""

import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

from paddlebox_tpu.distributed import FileStore, HostCollectives
from paddlebox_tpu.distributed.launch import launch


def test_filestore_set_get_wait(tmp_path):
    st = FileStore(str(tmp_path), timeout_s=2)
    assert st.get("k") is None
    st.set("k", b"v1")
    assert st.get("k") == b"v1"
    st.set("k", b"v2")  # overwrite
    assert st.wait("k") == b"v2"
    with pytest.raises(TimeoutError):
        st.wait("missing", timeout_s=0.1)


def _threaded_ranks(tmp_path, world, fn, **col_kwargs):
    store = FileStore(str(tmp_path), timeout_s=20)
    results = [None] * world
    errs = []

    def run(r):
        try:
            results[r] = fn(HostCollectives(store, r, world, **col_kwargs), r)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=run, args=(r,)) for r in range(world)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs, errs
    return results


def test_collectives_allreduce_gather_bcast(tmp_path):
    world = 3

    def body(col, r):
        col.barrier()
        s = col.all_reduce(np.full(4, r + 1.0), op="sum")
        g = col.all_gather(f"host{r}")
        b = col.broadcast({"day": 20260729} if r == 0 else None)
        m = col.all_reduce(np.asarray([float(r)]), op="max")
        return s, g, b, m

    for s, g, b, m in _threaded_ranks(tmp_path, world, body):
        np.testing.assert_allclose(s, np.full(4, 6.0))
        assert g == ["host0", "host1", "host2"]
        assert b == {"day": 20260729}
        assert m[0] == 2.0


def test_collectives_repeat_rounds(tmp_path):
    # sequence numbers isolate successive rounds on the same store
    def body(col, r):
        out = []
        for i in range(3):
            out.append(float(col.all_reduce(np.asarray([r + i + 0.0]))[0]))
        return out

    for got in _threaded_ranks(tmp_path, 2, body):
        assert got == [1.0, 3.0, 5.0]


WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np
    sys.path.insert(0, %(repo)r)
    from paddlebox_tpu.distributed import RoleMaker
    from paddlebox_tpu.data import DataFeedSchema
    from paddlebox_tpu.data.parser import _parse_python
    from paddlebox_tpu.data.shuffle import TcpShuffleService, route_records

    rm = RoleMaker.from_env()
    assert rm.world_size == 2, rm
    col = rm.collectives(timeout_s=60)

    # host collective: global histogram sum (the global-AUC path)
    local = np.full(8, rm.rank + 1.0)
    tot = col.all_reduce(local, op="sum")
    assert tot[0] == 3.0, tot

    # inter-host record shuffle over the DCN transport
    schema = DataFeedSchema.ctr(num_sparse=2, num_float=1, max_len=2)
    lines = []
    rng = np.random.default_rng(rm.rank)
    for i in range(40):
        sid = rng.integers(0, 1000)
        lines.append(f"1 1 1 0.5 1 {sid} 2 {sid} {sid+1}")
    batch = _parse_python(lines, schema, with_ins_id=False)
    batch.search_id = rng.integers(0, 1000, size=batch.num).astype(np.uint64)
    svc = TcpShuffleService(rm.rank, rm.endpoints)
    col.barrier()  # both servers listening before anyone connects
    routed = route_records(batch, rm.world_size, "search_id")
    got = svc.exchange(routed, schema)
    svc.close()
    n_local = sum(b.num for b in got)
    # every received record's search_id must route here
    for b in got:
        assert ((b.search_id %% 2) == rm.rank).all()
    # conservation: totals across hosts == totals sent
    n_tot = col.all_reduce(np.asarray([float(n_local)]))
    assert n_tot[0] == 80.0, n_tot
    print(f"rank {rm.rank} ok: {n_local} records after shuffle")
""")


def test_launcher_two_host_shuffle_and_collectives(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))})
    code = launch(2, [sys.executable, str(script)],
                  store_dir=str(tmp_path / "store"))
    assert code == 0


def test_launcher_propagates_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)")
    code = launch(2, [sys.executable, str(script)],
                  store_dir=str(tmp_path / "store"))
    assert code == 3


def test_data_generator_pipe(tmp_path):
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.data.data_generator import MultiSlotDataGenerator

    schema = DataFeedSchema.ctr(num_sparse=2, num_float=0, max_len=2)

    class Gen(MultiSlotDataGenerator):
        def generate_sample(self, line):
            a, b = line.split(",")
            yield [("label", [int(a) % 2]), ("slot_0", [int(a)]),
                   ("slot_1", [int(b), int(b) + 1])]

    raw = tmp_path / "raw.csv"
    raw.write_text("3,10\n4,20\n")
    out = tmp_path / "out.txt"
    with open(raw) as fin, open(out, "w") as fout:
        n = Gen(schema).process(fin, out=fout)
    assert n == 2
    ds = SlotDataset(schema)
    ds.set_filelist([str(out)])
    ds.load_into_memory(global_shuffle=False)
    assert ds.num_examples == 2
    np.testing.assert_array_equal(ds.records.sparse_values[0], [3, 4])
    np.testing.assert_array_equal(ds.records.sparse_values[1],
                                  [10, 11, 20, 21])


def test_global_auc_across_ranks(tmp_path):
    # two accumulators with disjoint batches: global compute must equal a
    # single accumulator fed both (exactness of the histogram reduction)
    import jax
    from paddlebox_tpu.metrics.auc import AucAccumulator, auc_update

    rng = np.random.default_rng(0)
    preds = rng.random(400).astype(np.float32)
    labels = (rng.random(400) < preds).astype(np.float32)
    fn = jax.jit(auc_update)

    ref = AucAccumulator(1 << 10)
    ref.update(fn, preds, labels)
    want = ref.compute()

    halves = [(preds[:200], labels[:200]), (preds[200:], labels[200:])]

    def body(col, r):
        acc = AucAccumulator(1 << 10)
        acc.update(fn, *halves[r])
        return acc.compute_global(col)

    for got in _threaded_ranks(tmp_path, 2, body):
        assert got["auc"] == pytest.approx(want["auc"], abs=1e-12)
        assert got["size"] == want["size"]
        # fp32 on-device accumulation order differs between one full batch
        # and two halves; the cross-rank reduction itself is exact
        assert got["mae"] == pytest.approx(want["mae"], rel=1e-6)


def test_collectives_store_cleanup(tmp_path):
    # files from old rounds are unlinked cleanup_lag rounds later
    def body(col, r):
        for i in range(12):
            col.all_reduce(np.asarray([1.0]))
        return None

    _threaded_ranks(tmp_path, 2, body, cleanup_lag=3)
    files = os.listdir(str(tmp_path))
    # 12 rounds x 3 files each would be 36; cleanup keeps only ~last lag
    assert len(files) <= 3 * 4, sorted(files)
