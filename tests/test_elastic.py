"""Elastic rank-loss recovery: shrink-to-N−1 continuation (ISSUE 6).

Two layers:

- tier-1 in-process tests of the re-formation protocol itself: the
  generation-sealed membership (exclusive-create seal, ack phase,
  escalation past a second failure), fencing, ghost-key sweeping, and
  the closed-registry guard tying every elastic fault point to this
  file's kill matrix;

- the ``slow`` elastic kill matrix: a REAL 3-process world
  (local-FileStore control plane, ``fail_stop=False`` launcher) loses
  rank 1 in each phase of the hot loop — pack, step dispatch, deferred
  push apply, end_pass — and, with a second armed victim, at each kill
  point INSIDE the re-formation window. The acceptance bar per phase:

  * the survivors converge on ONE generation with the same membership
    and the same elected cursor (never a mixed world);
  * the departed rank's unconsumed records (past the elected cursor) are
    consumed exactly once across the survivors (per-record audit from
    the workers' consumed logs);
  * the survivors' final dense+sparse+metric planes — and the global
    AUC — are bit-identical to an UNINTERRUPTED N−1 run that trains the
    same record schedule (the simulated-shrink golden, launched from the
    observed elected cursor).
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu.distributed.launch import launch
from paddlebox_tpu.distributed.resilience import (ElasticWorld,
                                                  WorldFencedError,
                                                  WorldTooSmallError)
from paddlebox_tpu.distributed.store import FileStore
from paddlebox_tpu.utils import faultpoint

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(TESTS_DIR, "elastic_worker.py")
WORLD = 3
PASSES = 3
BS = 32
N_EX = 768                       # 8 steps per rank per pass at world 3

# the elastic kill matrix: phase name -> (victim point, AFTER count,
# extra env). Counts assume 3 passes x 8 steps, mid-pass cadence 2.
PHASES = {
    # host pack pipeline (producer thread), mid pass 2
    "pack": ("trainer.pack.pre", 9, {}),
    # step dispatch, with slowed steps so the survivors detect MID-pass
    # and the elected cursor carries mid_steps > 0 — the re-route path
    "step_dispatch": ("trainer.step.pre", 9,
                      {"PBTPU_ELASTIC_STEP_SLEEP": "0.25",
                       "PBTPU_ELASTIC_LOST_S": "1.2"}),
    # deferred push apply (flags.push_overlap auto-on for allreduce)
    "push_apply": ("trainer.push_apply.pre", 12, {}),
    # the end-of-pass snapshot commit window
    "end_pass": ("pass_ckpt.pre_manifest", 9, {}),
}


def _env(tmp_path, extra=None):
    env = {
        "PBTPU_TEST_WORKDIR": str(tmp_path / "work"),
        "PBTPU_ELASTIC_ROOT": str(tmp_path / "snaps"),
        "PBTPU_ELASTIC_PASSES": str(PASSES),
        "PBTPU_ELASTIC_N": str(N_EX),
    }
    env.update(extra or {})
    os.makedirs(env["PBTPU_TEST_WORKDIR"], exist_ok=True)
    return env


def _launch(tmp_path, env, nprocs=WORLD):
    return launch(nprocs, [sys.executable, WORKER],
                  store_dir=str(tmp_path / f"store_{nprocs}"),
                  base_env=env, fail_stop=False, timeout_s=420)


def _info(tmp_path, rank):
    with open(tmp_path / "work" / f"info_{rank}.json") as f:
        return json.load(f)


def _consumed(tmp_path, rank):
    with open(tmp_path / "work" / f"consumed_{rank}.json") as f:
        return {int(k): set(v) for k, v in json.load(f).items()}


def _out(tmp_path, rank):
    p = tmp_path / "work" / f"out_{rank}.npz"
    assert p.exists(), f"rank {rank} produced no final dump"
    with np.load(p) as z:
        return {k: z[k] for k in z.files}


def _events(tmp_path, rank):
    p = tmp_path / "work" / f"events_{rank}.jsonl"
    if not p.exists():
        return []
    return [json.loads(ln) for ln in p.read_text().splitlines() if ln]


def _worker_errors(tmp_path, n=WORLD):
    return "; ".join(
        (tmp_path / "work" / f"err_{r}.txt").read_text()[:500]
        for r in range(n)
        if (tmp_path / "work" / f"err_{r}.txt").exists())


def _run_sim_golden(tmp_path, survivors, dead, elected):
    """The uninterrupted N−1 comparator: same record schedule, no kill."""
    d = tmp_path / "sim"
    env = _env(d, extra={"PBTPU_ELASTIC_SIM": json.dumps(
        {"orig_members": list(range(WORLD)), "dead": sorted(dead),
         "elected": list(elected)})})
    codes = _launch(d, env, nprocs=len(survivors))
    assert codes == [0] * len(survivors), (codes, _worker_errors(d))
    return d


def _audit_exactly_once(tmp_path, survivors, elected):
    """Per-record audit: across survivors, no record consumed twice in
    any pass of the surviving timeline; the departed ranks' unconsumed
    tails are covered (up to drop_last batch remainders); adopted shares
    are disjoint and cover the rerouted tail exactly."""
    q, m = elected
    consumed = {r: _consumed(tmp_path, r) for r in survivors}
    for p in range(1, PASSES + 1):
        seen: set = set()
        for r in survivors:
            ids = consumed[r].get(p, set())
            dup = seen & ids
            assert not dup, (f"pass {p}: records consumed twice "
                             f"{sorted(dup)[:8]}")
            seen |= ids
    if m > 0:
        infos = [_info(tmp_path, r) for r in survivors]
        rr = [i["reroute"] for i in infos]
        assert all(x is not None for x in rr), infos
        # every survivor derived the SAME dead tail from the cursor
        tails = [set(x["dead_tail_ids"]) for x in rr]
        assert all(t == tails[0] for t in tails)
        adopted_all: set = set()
        for x in rr:
            a = set(x["adopted_ids"])
            assert not adopted_all & a, "adopted shares overlap"
            adopted_all |= a
        assert adopted_all == tails[0], (
            "re-route did not cover the departed tail exactly once")
        # consumption of the kill pass covers the dead tail up to
        # drop_last remainders (< one batch per survivor)
        kill_seen = set()
        for r in survivors:
            kill_seen |= consumed[r].get(q + 1, set())
        uncovered = tails[0] - kill_seen
        assert len(uncovered) < BS * len(survivors), (
            f"{len(uncovered)} departed-tail records never consumed")


def _assert_parity(live_dir, sim_dir, survivors):
    for r in survivors:
        live, gold = _out(live_dir, r), _out(sim_dir, r)
        assert sorted(live) == sorted(gold)
        for k in gold:
            np.testing.assert_array_equal(
                gold[k], live[k],
                err_msg=f"rank {r} plane {k!r} diverged between the "
                        f"killed+recovered run and the uninterrupted "
                        f"N−1 run")
    live_auc = [_info(live_dir, r)["global_auc"] for r in survivors]
    sim_auc = [_info(sim_dir, r)["global_auc"] for r in survivors]
    assert all(a == live_auc[0] for a in live_auc)
    assert live_auc[0] == pytest.approx(sim_auc[0], abs=1e-12), (
        f"final AUC diverged: recovered {live_auc[0]} vs "
        f"uninterrupted N−1 {sim_auc[0]}")


def _run_phase(tmp_path, point, after, extra, second=None):
    extra = dict(extra)
    extra.update({"PBTPU_FAULTPOINT": point,
                  "PBTPU_FAULTPOINT_AFTER": str(after),
                  "PBTPU_FAULTPOINT_ONLY_RANK": "1"})
    victims = {1}
    if second is not None:
        extra.update({"PBTPU_FAULTPOINT2": second,
                      "PBTPU_FAULTPOINT2_RANK": "2",
                      "PBTPU_FAULTPOINT2_AFTER": "0"})
        victims.add(2)
    env = _env(tmp_path, extra=extra)
    codes = _launch(tmp_path, env)
    survivors = sorted(set(range(WORLD)) - victims)
    for v in victims:
        assert codes[v] == 137, (codes, _worker_errors(tmp_path))
    for s in survivors:
        assert codes[s] == 0, (codes, _worker_errors(tmp_path))
    infos = [_info(tmp_path, r) for r in survivors]
    # one generation, one membership, one elected cursor — never mixed
    assert all(i["gen"] == infos[0]["gen"] and i["gen"] >= 1
               for i in infos), infos
    assert all(i["members"] == survivors for i in infos), infos
    assert all(i["elected"] == infos[0]["elected"] for i in infos), infos
    assert infos[0]["elected"] is not None, infos
    elected = tuple(infos[0]["elected"])
    _audit_exactly_once(tmp_path, survivors, elected)
    sim_dir = _run_sim_golden(tmp_path, survivors, victims, elected)
    _assert_parity(tmp_path, sim_dir, survivors)
    # telemetry: the world_resize events name every departed rank — one
    # event per generation transition, so a victim that dies AFTER
    # acking a formed generation departs in a LATER event than one that
    # died before it (the union covers the whole victim set)
    for s in survivors:
        resize = [e for e in _events(tmp_path, s)
                  if e.get("name") == "world_resize"]
        assert resize, f"rank {s} emitted no world_resize event"
        departed = set()
        for e in resize:
            departed |= set(e["fields"]["departed"])
        assert departed == victims, (departed, victims)
    return infos


@pytest.mark.slow
@pytest.mark.parametrize("phase", sorted(PHASES))
def test_elastic_kill_matrix(phase, tmp_path):
    """Kill rank 1 of a 3-rank world in each hot-loop phase: survivors
    re-form at N−1, re-elect, re-route, and finish — state bit-identical
    to the uninterrupted 2-rank run of the same schedule."""
    point, after, extra = PHASES[phase]
    infos = _run_phase(tmp_path, point, after, extra)
    if phase == "step_dispatch":
        # slowed steps force MID-pass detection: the elected cursor must
        # carry mid_steps and the re-route path must actually run
        assert infos[0]["mid_steps"] > 0, infos
        assert infos[0]["reroute"] is not None


@pytest.mark.slow
@pytest.mark.parametrize("reform_point", sorted(faultpoint.ELASTIC_POINTS))
def test_elastic_kill_inside_reformation(reform_point, tmp_path):
    """The re-formation window is itself a crash window: rank 1 dies in
    the step loop, then rank 2 dies INSIDE the resulting re-formation
    (before arriving / after the seal / after its ack). The survivor
    must escalate to a single consistent generation of one, finish the
    schedule, and match the uninterrupted 1-rank run — never a mixed
    world."""
    point, after, extra = PHASES["step_dispatch"]
    infos = _run_phase(tmp_path, point, after, extra,
                       second=reform_point)
    assert infos[0]["members"] == [0]


def test_elastic_points_are_registered_and_scoped():
    """Closed-registry guard (mirrors test_crash_safety): the in-reform
    kill matrix above parametrizes over faultpoint.ELASTIC_POINTS, so a
    new elastic crash window cannot be registered without a matrix
    entry; and only genuinely reform-scoped points may hide from the
    plain kill→resume matrices."""
    assert set(faultpoint.ELASTIC_POINTS) <= set(faultpoint.POINTS)
    assert all(p.startswith("elastic.")
               for p in faultpoint.ELASTIC_POINTS)


# ---------------------------------------------------------------------------
# tier-1 in-process protocol tests (threads as ranks, no subprocesses)
# ---------------------------------------------------------------------------


def _world(tmp_path, rank, members, **kw):
    kw.setdefault("heartbeat_interval_s", 0.05)
    kw.setdefault("lost_after_s", 30.0)
    kw.setdefault("stall_after_s", 60.0)
    kw.setdefault("reform_timeout_s", 2.0)
    return ElasticWorld(FileStore(str(tmp_path), namespace="r",
                                  poll_s=0.01),
                        rank, members, **kw)


def test_reform_converges_on_one_generation(tmp_path):
    """3 ranks, rank 1 dead: both survivors form gen 1 with members
    [0, 2], renumbered densely, and the new generation's collectives
    work."""
    results, errs = [None] * 3, []

    def rank(r):
        try:
            w = _world(tmp_path, r, [0, 1, 2])
            if r == 1:
                w.close()
                return
            nw = w.reform([1])
            results[r] = (nw.gen, nw.members, nw.rank, nw.world)
            nw.collectives.barrier("post_reform")
            nw.close()
        except BaseException as e:    # pragma: no cover
            errs.append((r, e))

    ts = [threading.Thread(target=rank, args=(r,)) for r in range(3)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs, errs
    assert results[0] == (1, [0, 2], 0, 2)
    assert results[2] == (1, [0, 2], 1, 2)


def test_reform_seal_is_exclusive_and_fences_stragglers(tmp_path):
    """A survivor whose peers never arrive seals the generation alone
    after its patience expires; a straggler arriving later reads the
    sealed membership, finds itself excluded, and is FENCED (clean
    exit), never split into a second world."""
    w0 = _world(tmp_path, 0, [0, 1, 2], reform_timeout_s=0.5)
    nw = w0.reform([1])
    assert (nw.gen, nw.members) == (1, [0])
    nw.close()
    w2 = _world(tmp_path, 2, [0, 1, 2], reform_timeout_s=0.5)
    with pytest.raises(WorldFencedError):
        w2.reform([1])


def test_reform_escalates_past_arrived_but_unacked_rank(tmp_path):
    """A rank that arrives at the proposed generation but dies before
    acking (the post_seal crash window): the survivor times out the ack
    phase and escalates to the NEXT generation without it — generations
    seal at most once each, so no membership mixes."""
    store = FileStore(str(tmp_path), namespace="r", poll_s=0.01)
    # fake rank 2: arrived at g1 (but will never ack)
    store.set("elastic.reform.g1.arrive.2",
              json.dumps({"rank": 2}).encode())
    w0 = _world(tmp_path, 0, [0, 1, 2], reform_timeout_s=0.6)
    nw = w0.reform([1])
    assert (nw.gen, nw.members) == (2, [0])
    # g1 sealed with both, g2 sealed with the survivor alone
    g1 = json.loads(store.get("elastic.world.g1"))
    g2 = json.loads(store.get("elastic.world.g2"))
    assert g1["members"] == [0, 2] and g2["members"] == [0]
    nw.close()


def test_reform_respects_min_world_floor(tmp_path):
    from paddlebox_tpu.config import flags, set_flags
    old = flags.elastic_min_world
    set_flags(elastic_min_world=2)
    try:
        w0 = _world(tmp_path, 0, [0, 1], reform_timeout_s=0.3)
        with pytest.raises(WorldTooSmallError):
            w0.reform([1])
    finally:
        set_flags(elastic_min_world=old)


def test_reform_sweeps_departed_rank_keys(tmp_path):
    """After re-formation the departed rank's heartbeat and barrier
    arrivals are gone from the live namespace — the new generation's
    wait_count can never count ghosts — while other ranks' keys and the
    sealed world records survive."""
    store = FileStore(str(tmp_path), namespace="r", poll_s=0.01)
    store.set("hb.1", b"ghost")
    store.add("end_pass.7", 1)
    store.add("end_pass.7", 0)
    store.set("gather.3.v1", b"npyghost")
    # a NEW-generation key owned by gen-local rank 1 — which is a
    # SURVIVOR under the generation's dense renumbering — must never be
    # rank-swept (the race that once ate a live election value)
    store.scoped("g1").set("resume_candidates.1.v1", b"live")
    w0 = _world(tmp_path, 0, [0, 1], reform_timeout_s=0.3)
    nw = w0.reform([1])
    assert store.get("hb.1") is None
    assert store.get("gather.3.v1") is None
    assert store.missing_ranks("end_pass.7", 2) == [1]
    assert store.get("end_pass.7.0") is not None     # rank 0's arrival
    assert store.get("elastic.world.g1") is not None  # sealed record
    assert store.scoped("g1").get("resume_candidates.1.v1") == b"live"
    nw.close()


def test_gen_collectives_isolated_from_old_generation(tmp_path):
    """A fenced straggler still writing under the OLD generation can
    never satisfy the new generation's waits: gen keys are
    store-namespace scoped."""
    base = FileStore(str(tmp_path), namespace="r", poll_s=0.01)
    stale = base.scoped("g0_fake")
    stale.set("barrier.1.0", b"1")
    g1 = base.scoped("g1")
    assert g1.get("barrier.1.0") is None
    g1.set("x", b"1")
    assert base.get("x") is None


def test_admit_joins_next_generation(tmp_path):
    """Elastic GROW, protocol level: a world shrunk to one survivor
    admits a NEW rank — the joiner registers via ElasticWorld.admit, the
    incumbent sees it in pending_admissions and re-forms WITH it, both
    converge on the same grown generation, and the consumed admit
    registration is deleted (it can never re-trigger)."""
    results, errs = {}, []

    def incumbent():
        try:
            w0 = _world(tmp_path, 0, [0, 1], reform_timeout_s=0.5,
                        initial_world=2)
            g1 = w0.reform([1])                    # degraded: [0]
            deadline = time.monotonic() + 20.0
            while not g1.pending_admissions():
                assert time.monotonic() < deadline, "joiner never registered"
                time.sleep(0.02)
            assert g1.pending_admissions() == [1]
            g2 = g1.reform([], admit_orig_ranks=g1.pending_admissions())
            results["inc"] = (g2.gen, g2.members, g2.rank)
            g2.collectives.barrier("post_grow")
            g2.close()
        except BaseException as e:    # pragma: no cover
            errs.append(("inc", e))

    def joiner():
        try:
            store = FileStore(str(tmp_path), namespace="r", poll_s=0.01)
            w = ElasticWorld.admit(store, 1, timeout_s=20.0,
                                   heartbeat_interval_s=0.05,
                                   lost_after_s=30.0, stall_after_s=60.0,
                                   reform_timeout_s=2.0, initial_world=2)
            results["join"] = (w.gen, w.members, w.rank)
            w.collectives.barrier("post_grow")
            w.close()
        except BaseException as e:    # pragma: no cover
            errs.append(("join", e))

    ts = [threading.Thread(target=incumbent), threading.Thread(target=joiner)]
    [t.start() for t in ts]
    [t.join(timeout=60) for t in ts]
    assert not errs, errs
    assert results["inc"] == (2, [0, 1], 0)
    assert results["join"] == (2, [0, 1], 1)
    store = FileStore(str(tmp_path), namespace="r", poll_s=0.01)
    assert store.keys("elastic.admit.") == []


def test_pending_admissions_scoped_to_generation_and_members(tmp_path):
    """A member rank's registration is invisible (it already belongs), a
    stale registration against an OLD generation is invisible, and only
    a live non-member registration against THIS generation shows."""
    store = FileStore(str(tmp_path), namespace="r", poll_s=0.01)
    w0 = _world(tmp_path, 0, [0, 1])
    store.set("elastic.admit.g0.1", b"{}")       # member: already in
    store.set("elastic.admit.g7.5", b"{}")       # wrong generation
    store.set("elastic.admit.g0.5", b"{}")       # live non-member
    assert w0.pending_admissions() == [5]
    w0.close()


def test_admit_reregisters_when_sealed_without_it(tmp_path):
    """The shrink-races-admit window: a generation seals WITHOUT the
    joiner (the incumbent never scanned). The joiner must re-register
    against the newly sealed generation and join the NEXT one — never
    block forever on a train that left the station."""
    results, errs = {}, []

    def incumbent():
        try:
            w0 = _world(tmp_path, 0, [0, 1, 2], reform_timeout_s=0.5,
                        initial_world=3)
            g1 = w0.reform([1])                  # seals g1 = [0, 2]...
            deadline = time.monotonic() + 20.0
            while not g1.pending_admissions():
                assert time.monotonic() < deadline
                time.sleep(0.02)
            g2 = g1.reform([], admit_orig_ranks=g1.pending_admissions())
            results["inc0"] = (g2.gen, sorted(g2.members))
            g2.collectives.barrier("post_grow")
            g2.close()
        except BaseException as e:    # pragma: no cover
            errs.append(("inc0", e))

    def survivor2():
        try:
            w = _world(tmp_path, 2, [0, 1, 2], reform_timeout_s=0.5,
                       initial_world=3)
            g1 = w.reform([1])
            deadline = time.monotonic() + 20.0
            while not g1.pending_admissions():
                assert time.monotonic() < deadline
                time.sleep(0.02)
            g2 = g1.reform([], admit_orig_ranks=g1.pending_admissions())
            results["inc2"] = (g2.gen, sorted(g2.members))
            g2.collectives.barrier("post_grow")
            g2.close()
        except BaseException as e:    # pragma: no cover
            errs.append(("inc2", e))

    def joiner():
        try:
            # registers against gen 0 BEFORE the shrink; g1 then seals
            # without it (the ...raced window) and the re-registration
            # against g1 is what the incumbents' scans pick up
            store = FileStore(str(tmp_path), namespace="r", poll_s=0.01)
            w = ElasticWorld.admit(store, 3, timeout_s=30.0,
                                   heartbeat_interval_s=0.05,
                                   lost_after_s=30.0, stall_after_s=60.0,
                                   reform_timeout_s=2.0, initial_world=3)
            results["join"] = (w.gen, sorted(w.members))
            w.collectives.barrier("post_grow")
            w.close()
        except BaseException as e:    # pragma: no cover
            errs.append(("join", e))

    tj = threading.Thread(target=joiner)
    tj.start()
    time.sleep(0.3)                  # let the g0 registration land first
    ts = [threading.Thread(target=incumbent),
          threading.Thread(target=survivor2)]
    [t.start() for t in ts]
    [t.join(timeout=60) for t in ts]
    tj.join(timeout=60)
    assert not errs, errs
    assert results["inc0"] == (2, [0, 2, 3])
    assert results["inc2"] == (2, [0, 2, 3])
    assert results["join"] == (2, [0, 2, 3])


def test_admit_times_out_when_nobody_grows(tmp_path):
    """No incumbent ever polls: the admit deadline raises instead of
    hanging the replacement process forever."""
    store = FileStore(str(tmp_path), namespace="r", poll_s=0.01)
    with pytest.raises(TimeoutError, match="admit of rank 7"):
        ElasticWorld.admit(store, 7, timeout_s=0.4, reform_timeout_s=0.2,
                           heartbeat_interval_s=0.05)


def test_admit_points_are_registered_and_scoped():
    """Closed-registry guard for the grow kill matrix: the admit-window
    points — 'elastic.admit.pre_register', 'elastic.admit.post_ack',
    'elastic.ownership.rebind.pre' — are registered, elastic-scoped, and
    parametrized over by the grow kill matrix below, so a new grow crash
    window cannot ship without a matrix entry."""
    assert set(faultpoint.ADMIT_POINTS) <= set(faultpoint.POINTS)
    assert all(p.startswith("elastic.") for p in faultpoint.ADMIT_POINTS)
    assert set(faultpoint.ADMIT_POINTS) == {
        "elastic.admit.pre_register", "elastic.admit.post_ack",
        "elastic.ownership.rebind.pre"}
    assert not set(faultpoint.ADMIT_POINTS) & set(faultpoint.ELASTIC_POINTS)


# -- grow kill matrix (tests/grow_worker.py) ------------------------------
#
# Protocol-level harness: 2 incumbents + 1 joiner across real processes,
# real ShardOwnership rebound through the REAL Trainer.set_shard_ownership
# (so the 'elastic.ownership.rebind.pre' crash window is on the executed
# path), RemediationController.poll_grow driving the admission.

GROW_WORKER = os.path.join(TESTS_DIR, "grow_worker.py")


def _run_grow(tmp_path, mode):
    env = {"PBTPU_TEST_WORKDIR": str(tmp_path / "work"),
           "PBTPU_GROW_MODE": mode}
    os.makedirs(env["PBTPU_TEST_WORKDIR"], exist_ok=True)
    return launch(3, [sys.executable, GROW_WORKER],
                  store_dir=str(tmp_path / "store"),
                  base_env=env, fail_stop=False, timeout_s=240)


def _grow_errors(tmp_path):
    return "; ".join(
        (tmp_path / "work" / f"err_{r}.txt").read_text()[:500]
        for r in range(3)
        if (tmp_path / "work" / f"err_{r}.txt").exists())


def test_grow_rebinds_ownership_to_exactly_the_owned_shards(tmp_path):
    """Clean grow: poll_grow admits the joiner into gen 1 [0, 1, 2]; the
    newcomer's ownership diff ``gained`` equals its ``owned`` EXACTLY (it
    rebuilds its shards' boundary set and nothing else); incumbents shed
    precisely the shards the 3-way re-deal takes from them; the grown
    generation completes a live all_reduce."""
    codes = _run_grow(tmp_path, "clean")
    assert codes == [0, 0, 0], (codes, _grow_errors(tmp_path))
    infos = [_info(tmp_path, r) for r in range(3)]
    assert all(i["gen"] == 1 and i["members"] == [0, 1, 2] for i in infos)
    # sum over gen-local ranks of (rank + 1) on the 3-member world
    assert all(i["allreduce"] == 6.0 for i in infos)
    joiner = infos[2]
    assert joiner["owned"] == sorted(range(2, 8, 3))
    assert joiner["rebind"] == {"gained": joiner["owned"],
                                "lost": [], "kept": []}
    for i in infos[:2]:
        r = i["rank"]
        before, after = set(range(r, 8, 2)), set(range(r, 8, 3))
        assert i["owned"] == sorted(after)
        assert i["rebind"] == {"gained": sorted(after - before),
                               "lost": sorted(before - after),
                               "kept": sorted(before & after)}
    # the grow is visible in every incumbent's event stream
    for r in range(2):
        grows = [e for e in _events(tmp_path, r) if e["name"] == "world_grow"]
        assert grows and grows[0]["fields"]["joined"] == [2], grows


@pytest.mark.slow
def test_grow_kill_joiner_mid_shard_rebuild(tmp_path):
    """The NEWCOMER dies inside its shard-rebuild bind (after acking the
    grown generation): the incumbents detect the silence at the post-grow
    barrier and shrink back to a trainable gen 2 [0, 1]."""
    codes = _run_grow(tmp_path, "kill_joiner_rebind")
    assert codes[2] == 137, (codes, _grow_errors(tmp_path))
    assert codes[0] == 0 and codes[1] == 0, (codes, _grow_errors(tmp_path))
    infos = [_info(tmp_path, r) for r in range(2)]
    assert all(i["gen"] == 2 and i["members"] == [0, 1] for i in infos)
    assert all(i["allreduce"] == 3.0 for i in infos)


@pytest.mark.slow
def test_grow_kill_incumbent_mid_ownership_rebind(tmp_path):
    """An INCUMBENT dies inside poll_grow's ownership rebind, after the
    grown generation sealed: the surviving incumbent and the newcomer
    both detect it and re-form a trainable gen 2 [0, 2] — the joiner's
    admission survives the very failure mode it was healing."""
    codes = _run_grow(tmp_path, "kill_incumbent_rebind")
    assert codes[1] == 137, (codes, _grow_errors(tmp_path))
    assert codes[0] == 0 and codes[2] == 0, (codes, _grow_errors(tmp_path))
    infos = [_info(tmp_path, r) for r in (0, 2)]
    assert all(i["gen"] == 2 and i["members"] == [0, 2] for i in infos)
    assert all(i["allreduce"] == 3.0 for i in infos)


# -- self-healing grow e2e (elastic_worker.py grow mode) ------------------

def _grow_env(tmp_path, extra=None):
    e = {"PBTPU_ELASTIC_GROW": "1",
         "PBTPU_ELASTIC_TRAIN_WORLD": str(WORLD),
         "PBTPU_ELASTIC_JOINER_AS": "1",
         "PBTPU_ELASTIC_MIDPASS": "0",
         "PBTPU_FAULTPOINT": "trainer.step.pre",
         "PBTPU_FAULTPOINT_AFTER": "9",
         "PBTPU_FAULTPOINT_ONLY_RANK": "1"}
    e.update(extra or {})
    return _env(tmp_path, extra=e)


@pytest.mark.slow
def test_self_healing_grow_is_bit_identical_to_never_failed(tmp_path):
    """The acceptance drill: a 3-rank run loses rank 1 mid-pass, shrinks,
    and the controller admits a replacement (assuming the dead rank's
    identity) at the next pass boundary. The grown world elects the last
    snapshot intact EVERYWHERE — the dead rank's last completed pass —
    rolls back, and retrains at full world. Every final plane on every
    rank must be bit-identical to a 3-rank run that never failed."""
    live = tmp_path / "live"
    env = _grow_env(live)
    codes = _launch(live, env, nprocs=WORLD + 1)
    assert codes[1] == 137, (codes, _worker_errors(live, WORLD + 1))
    assert [codes[0], codes[2], codes[3]] == [0, 0, 0], (
        codes, _worker_errors(live, WORLD + 1))
    infos = {r: _info(live, r) for r in range(WORLD)}
    # one grown generation, full membership, one elected cursor
    assert all(i["members"] == [0, 1, 2] for i in infos.values()), infos
    assert len({i["gen"] for i in infos.values()}) == 1, infos
    assert infos[0]["gen"] >= 2, infos
    assert infos[0].get("grew") and infos[2].get("grew"), infos
    assert infos[1].get("admitted"), infos
    # the election landed on the victim's last completed pass boundary
    assert all(i["elected"] == [1, 0] for i in infos.values()), infos
    # the grow is visible in the survivors' event streams
    for r in (0, 2):
        grows = [e for e in _events(live, r)
                 if e.get("name") == "world_grow"]
        assert grows and grows[-1]["fields"]["joined"] == [1], grows
    # golden: the same world size, never failed
    gold = tmp_path / "gold"
    genv = _env(gold, extra={"PBTPU_ELASTIC_MIDPASS": "0"})
    gcodes = _launch(gold, genv)
    assert gcodes == [0] * WORLD, (gcodes, _worker_errors(gold))
    _assert_parity(live, gold, [0, 1, 2])
    # the surviving timeline consumed exactly the golden schedule: full
    # history on the incumbents; from the rejoin pass on the newcomer
    for r in (0, 2):
        assert _consumed(live, r) == _consumed(gold, r)
    jc, gc = _consumed(live, 1), _consumed(gold, 1)
    assert set(jc) == {pp for pp in gc if pp >= 2}, sorted(jc)
    for pp in jc:
        assert jc[pp] == gc[pp], f"pass {pp} schedule diverged"


@pytest.mark.slow
def test_grow_joiner_killed_before_registering(tmp_path):
    """The replacement dies before its admit registration ever lands:
    the survivors' grow polls drain on the same all-gather round, and
    the degraded world simply finishes training — bit-consistent between
    the survivors."""
    env = _grow_env(tmp_path, extra={
        "PBTPU_FAULTPOINT2": "elastic.admit.pre_register",
        "PBTPU_FAULTPOINT2_RANK": "joiner",
        "PBTPU_ELASTIC_GROW_POLLS": "30"})
    codes = _launch(tmp_path, env, nprocs=WORLD + 1)
    assert codes[1] == 137 and codes[3] == 137, (
        codes, _worker_errors(tmp_path, WORLD + 1))
    assert codes[0] == 0 and codes[2] == 0, (
        codes, _worker_errors(tmp_path, WORLD + 1))
    infos = [_info(tmp_path, r) for r in (0, 2)]
    assert all(i["gen"] == 1 and i["members"] == [0, 2] for i in infos)
    assert all(not i.get("grew") for i in infos), infos
    aucs = [i["global_auc"] for i in infos]
    assert aucs[0] == aucs[1] and not np.isnan(aucs[0]), aucs


@pytest.mark.slow
def test_grow_joiner_killed_after_ack_escalates_cleanly(tmp_path):
    """The replacement acks the grown generation then dies: the
    incumbents' resume election hits the silence, recovery re-forms past
    the sealed-with-it generation, and the survivors escalate to a
    trainable two-rank world — the grow path's own failure mode heals
    through the same machinery it extends."""
    env = _grow_env(tmp_path, extra={
        "PBTPU_FAULTPOINT2": "elastic.admit.post_ack",
        "PBTPU_FAULTPOINT2_RANK": "joiner",
        "PBTPU_ELASTIC_GROW_POLLS": "30"})
    codes = _launch(tmp_path, env, nprocs=WORLD + 1)
    assert codes[1] == 137 and codes[3] == 137, (
        codes, _worker_errors(tmp_path, WORLD + 1))
    assert codes[0] == 0 and codes[2] == 0, (
        codes, _worker_errors(tmp_path, WORLD + 1))
    infos = [_info(tmp_path, r) for r in (0, 2)]
    assert all(i["members"] == [0, 2] for i in infos), infos
    assert infos[0]["gen"] == infos[1]["gen"] >= 2, infos
    aucs = [i["global_auc"] for i in infos]
    assert aucs[0] == aucs[1] and not np.isnan(aucs[0]), aucs


def test_heartbeat_names_original_ranks(tmp_path):
    """In a shrunk generation the watchdog errors name ORIGINAL launcher
    ranks, not gen-local indices — drivers keep one rank language."""
    from paddlebox_tpu.distributed.resilience import (HeartbeatMonitor,
                                                      PeerLostError)
    store = FileStore(str(tmp_path), poll_s=0.01)
    # gen-local world of 2 mapping to original ranks [0, 5]
    h0 = HeartbeatMonitor(store, 0, 2, rank_names=[0, 5],
                          interval_s=0.05, lost_after_s=0.3,
                          stall_after_s=30, watch=False)
    try:
        deadline = time.monotonic() + 5.0
        with pytest.raises(PeerLostError, match=r"\[5\]") as ei:
            while time.monotonic() < deadline:
                h0.check()
                time.sleep(0.05)
        assert ei.value.ranks == [5]
    finally:
        h0.close()
