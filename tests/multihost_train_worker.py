"""Worker process for the cross-process multi-host train test.

Launched (2 processes) by tests/test_multihost_train.py via
paddlebox_tpu.distributed.launch. Each worker:

1. joins the global JAX process group on the CPU backend (2 virtual local
   devices each -> one 4-device global mesh across 2 processes),
2. loads its rank-local file shard and runs the inter-host TCP global
   shuffle routed by ins_id,
3. reassembles the identical canonical global dataset on every rank
   (archive write + barrier + read-all, sorted by ins_id),
4. runs the real sharded train_pass recipe over the global mesh,
5. rank 0 writes the metrics JSON the pytest side compares against a
   single-process run of the same recipe.

Mirrors the reference's subprocess trainer harness
(test_collective_base.py:141 _run_cluster: real NCCL over loopback).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from paddlebox_tpu.distributed import RoleMaker  # noqa: E402

rm = RoleMaker.from_env()
rm.init_distributed(sim_cpu_devices=2)  # before any other JAX use

import jax  # noqa: E402
import numpy as np  # noqa: E402

import multihost_train_common as common  # noqa: E402
from paddlebox_tpu.data import SlotDataset  # noqa: E402
from paddlebox_tpu.data.archive import read_archive, write_archive  # noqa: E402
from paddlebox_tpu.data.shuffle import TcpShuffleService  # noqa: E402
from paddlebox_tpu.data.slot_record import SlotRecordBatch  # noqa: E402
from paddlebox_tpu.parallel import make_mesh  # noqa: E402

assert rm.world_size == common.WORLD, rm
assert len(jax.devices()) == 2 * common.WORLD, jax.devices()
assert len(jax.local_devices()) == 2

work_dir = os.environ["PBTPU_TEST_WORKDIR"]
col = rm.collectives(timeout_s=180)
schema = common.make_schema()

# -- rank-local ingest + inter-host global shuffle (DCN transport) ---------
shard_file = os.path.join(work_dir, f"input_{rm.rank}.txt")
with open(shard_file, "w") as f:
    f.write("\n".join(common.make_lines(rm.rank)) + "\n")
svc = TcpShuffleService(rm.rank, rm.endpoints)
ds = SlotDataset(schema, shuffle_service=svc)
ds.with_ins_id = True
ds.set_filelist([shard_file])
col.barrier()                        # both shuffle servers listening
ds.load_into_memory(global_shuffle=True, routing="ins_id")
svc.close()

# every record must have routed to the rank its ins_id hashes to
from paddlebox_tpu.data.shuffle import hash64_array  # noqa: E402
assert (hash64_array(ds.records.ins_id) % np.uint64(common.WORLD)
        == rm.rank).all()
n_tot = col.all_reduce(np.asarray([float(ds.records.num)]))
assert n_tot[0] == common.WORLD * common.EXAMPLES_PER_RANK, n_tot

# -- canonical global dataset on every rank (SPMD needs identical feeds) ---
write_archive(os.path.join(work_dir, f"shard_{rm.rank}.pbar"), ds.records)
col.barrier()
parts = [read_archive(os.path.join(work_dir, f"shard_{r}.pbar"), schema)
         for r in range(rm.world_size)]
records = common.sort_by_ins_id(SlotRecordBatch.concat(parts))
assert records.num == common.WORLD * common.EXAMPLES_PER_RANK

# -- the real sharded training recipe over the 2-process global mesh -------
mesh = make_mesh(num_nodes=common.WORLD)   # (2 node, 2 dp) across processes
assert mesh.devices.shape == (common.WORLD, 2)
out = common.run_training(mesh, records, schema)

if rm.rank == 0:
    with open(os.path.join(work_dir, "result.json"), "w") as f:
        json.dump(out, f)
print(f"rank {rm.rank} done: {out}", flush=True)
