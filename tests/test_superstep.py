"""k-microbatch superstep (TrainerConfig.steps_per_dispatch): one
lax.scan dispatch per k packed batches must be NUMERICALLY IDENTICAL to
k sequential single-step dispatches — same math, same order; only the
program-launch count changes. Tail groups (dataset length not a multiple
of k) fall back to the single-step program mid-pass.
"""

import numpy as np

import jax

from paddlebox_tpu.data import DataFeedSchema
from paddlebox_tpu.data.dataset import SlotDataset
from paddlebox_tpu.data.slot_record import SlotRecordBatch
from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.models import DeepFMModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig

NUM_SLOTS, EMB_DIM, BATCH = 4, 4, 16


def _dataset(n_ex, seed=0):
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=1,
                                batch_size=BATCH, max_len=1)
    rng = np.random.default_rng(seed)
    offs = np.arange(n_ex + 1, dtype=np.int64)
    ds = SlotDataset(schema)
    ds.records = SlotRecordBatch(
        schema=schema, num=n_ex,
        sparse_values=[(rng.integers(1, 400, size=n_ex).astype(np.int64)
                        | (np.int64(s + 1) << np.int64(40)))
                       for s in range(NUM_SLOTS)],
        sparse_offsets=[offs.copy() for _ in range(NUM_SLOTS)],
        float_values=[(rng.random(n_ex) < 0.3).astype(np.float32),
                      rng.normal(size=n_ex).astype(np.float32)],
        ins_id=np.zeros(n_ex, dtype=np.uint64),
        search_id=np.zeros(n_ex, dtype=np.uint64),
        rank=np.zeros(n_ex, dtype=np.int32),
        cmatch=np.zeros(n_ex, dtype=np.int32))
    return ds, schema


def _train(n_dev, steps_per_dispatch, n_batches=6):
    ds, schema = _dataset(n_batches * BATCH)
    store = HostEmbeddingStore(EmbeddingConfig(dim=EMB_DIM,
                                               learning_rate=0.05))
    mesh = make_mesh(n_dev)
    tr = Trainer(DeepFMModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM,
                             dense_dim=1, hidden=(8,)),
                 store, schema, mesh,
                 TrainerConfig(global_batch_size=BATCH,
                               steps_per_dispatch=steps_per_dispatch))
    out = tr.train_pass(ds)
    table = np.asarray(tr.feed_mgr.current_ws.table) \
        if hasattr(tr.feed_mgr, "current_ws") else None
    params = jax.tree.map(np.asarray, tr.params)
    return out, params, tr, store


def test_superstep_matches_single_step_trajectory():
    """6 batches at k=4 -> one stacked superstep + 2 tail singles; the
    loss list, final dense params, and persisted store rows must match
    the k=1 run (scan of the same body in the same order)."""
    out1, params1, tr1, store1 = _train(8, 1)
    out4, params4, tr4, store4 = _train(8, 4)
    assert tr4._superstep_fn is not None
    assert tr1._superstep_fn is None
    assert out1["steps"] == out4["steps"] == 6
    np.testing.assert_allclose(out1["loss_mean"], out4["loss_mean"],
                               rtol=1e-6)
    np.testing.assert_allclose(out1["loss_first"], out4["loss_first"],
                               rtol=1e-6)
    np.testing.assert_allclose(out1["loss_last"], out4["loss_last"],
                               rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                         atol=1e-7),
                 params1, params4)
    # persisted sparse rows identical
    keys = np.sort(np.unique(np.concatenate(
        [np.asarray(v) for v in
         _dataset(6 * BATCH)[0].records.sparse_values]))).astype(np.uint64)
    r1 = store1.peek_rows(keys)
    r4 = store4.peek_rows(keys)
    np.testing.assert_allclose(r1, r4, rtol=1e-5, atol=1e-7)


def test_superstep_single_chip():
    out1, *_ = _train(1, 1, n_batches=4)
    out4, *_ = _train(1, 4, n_batches=4)
    np.testing.assert_allclose(out1["loss_mean"], out4["loss_mean"],
                               rtol=1e-6)
    assert out1["auc"] == out4["auc"]


def test_superstep_disabled_for_other_modes():
    ds, schema = _dataset(2 * BATCH)
    store = HostEmbeddingStore(EmbeddingConfig(dim=EMB_DIM))
    tr = Trainer(DeepFMModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM,
                             dense_dim=1, hidden=(8,)),
                 store, schema, make_mesh(8),
                 TrainerConfig(global_batch_size=BATCH,
                               dense_sync_mode="kstep",
                               steps_per_dispatch=4))
    assert tr._superstep_fn is None
    out = tr.train_pass(ds)
    assert np.isfinite(out["loss_mean"])
