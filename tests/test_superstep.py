"""k-microbatch superstep (TrainerConfig.steps_per_dispatch): one
lax.scan dispatch per k packed batches must be NUMERICALLY IDENTICAL to
k sequential single-step dispatches — same math, same order; only the
program-launch count changes. Tail groups (dataset length not a multiple
of k) fall back to the single-step program mid-pass.
"""

import numpy as np

import jax

from paddlebox_tpu.data import DataFeedSchema
from paddlebox_tpu.data.dataset import SlotDataset
from paddlebox_tpu.data.slot_record import SlotRecordBatch
from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.models import DeepFMModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig

NUM_SLOTS, EMB_DIM, BATCH = 4, 4, 16


def _dataset(n_ex, seed=0):
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=1,
                                batch_size=BATCH, max_len=1)
    rng = np.random.default_rng(seed)
    offs = np.arange(n_ex + 1, dtype=np.int64)
    ds = SlotDataset(schema)
    ds.records = SlotRecordBatch(
        schema=schema, num=n_ex,
        sparse_values=[(rng.integers(1, 400, size=n_ex).astype(np.int64)
                        | (np.int64(s + 1) << np.int64(40)))
                       for s in range(NUM_SLOTS)],
        sparse_offsets=[offs.copy() for _ in range(NUM_SLOTS)],
        float_values=[(rng.random(n_ex) < 0.3).astype(np.float32),
                      rng.normal(size=n_ex).astype(np.float32)],
        ins_id=np.zeros(n_ex, dtype=np.uint64),
        search_id=np.zeros(n_ex, dtype=np.uint64),
        rank=np.zeros(n_ex, dtype=np.int32),
        cmatch=np.zeros(n_ex, dtype=np.int32))
    return ds, schema


def _train(n_dev, steps_per_dispatch, n_batches=6):
    ds, schema = _dataset(n_batches * BATCH)
    store = HostEmbeddingStore(EmbeddingConfig(dim=EMB_DIM,
                                               learning_rate=0.05))
    mesh = make_mesh(n_dev)
    tr = Trainer(DeepFMModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM,
                             dense_dim=1, hidden=(8,)),
                 store, schema, mesh,
                 TrainerConfig(global_batch_size=BATCH,
                               steps_per_dispatch=steps_per_dispatch))
    out = tr.train_pass(ds)
    table = np.asarray(tr.feed_mgr.current_ws.table) \
        if hasattr(tr.feed_mgr, "current_ws") else None
    params = jax.tree.map(np.asarray, tr.params)
    return out, params, tr, store


def test_superstep_matches_single_step_trajectory():
    """6 batches at k=4 -> one stacked superstep + 2 tail singles; the
    loss list, final dense params, and persisted store rows must match
    the k=1 run (scan of the same body in the same order)."""
    out1, params1, tr1, store1 = _train(8, 1)
    out4, params4, tr4, store4 = _train(8, 4)
    assert tr4._superstep_fn is not None
    assert tr1._superstep_fn is None
    assert out1["steps"] == out4["steps"] == 6
    np.testing.assert_allclose(out1["loss_mean"], out4["loss_mean"],
                               rtol=1e-6)
    np.testing.assert_allclose(out1["loss_first"], out4["loss_first"],
                               rtol=1e-6)
    np.testing.assert_allclose(out1["loss_last"], out4["loss_last"],
                               rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                         atol=1e-7),
                 params1, params4)
    # persisted sparse rows identical
    keys = np.sort(np.unique(np.concatenate(
        [np.asarray(v) for v in
         _dataset(6 * BATCH)[0].records.sparse_values]))).astype(np.uint64)
    r1 = store1.peek_rows(keys)
    r4 = store4.peek_rows(keys)
    np.testing.assert_allclose(r1, r4, rtol=1e-5, atol=1e-7)


def test_superstep_single_chip():
    out1, *_ = _train(1, 1, n_batches=4)
    out4, *_ = _train(1, 4, n_batches=4)
    np.testing.assert_allclose(out1["loss_mean"], out4["loss_mean"],
                               rtol=1e-6)
    assert out1["auc"] == out4["auc"]


def test_superstep_disabled_for_other_modes():
    ds, schema = _dataset(2 * BATCH)
    store = HostEmbeddingStore(EmbeddingConfig(dim=EMB_DIM))
    tr = Trainer(DeepFMModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM,
                             dense_dim=1, hidden=(8,)),
                 store, schema, make_mesh(8),
                 TrainerConfig(global_batch_size=BATCH,
                               dense_sync_mode="kstep",
                               steps_per_dispatch=4))
    assert tr._superstep_fn is None
    out = tr.train_pass(ds)
    assert np.isfinite(out["loss_mean"])


# ---------------------------------------------------------------------------
# mid-pass snapshots at steps_per_dispatch > 1 (ISSUE 7 satellite): the
# cursor exists only BETWEEN dispatches, so the cadence must land on the
# dispatch boundary — and a resume from such a cursor is bit-exact.
# ---------------------------------------------------------------------------

def _job(tmp_path, tag, k, n_batches=6, midpass_every=0, seed_data=3):
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds, schema = _dataset(n_batches * BATCH, seed=seed_data)
    store = HostEmbeddingStore(EmbeddingConfig(dim=EMB_DIM,
                                               learning_rate=0.05))
    tr = Trainer(DeepFMModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM,
                             dense_dim=1, hidden=(8,)),
                 store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=BATCH,
                               steps_per_dispatch=k))
    box = BoxPS(store)
    ck = PassCheckpointer(str(tmp_path / tag), keep_last_n=6, base_every=4)
    if midpass_every:
        tr.enable_midpass_snapshots(ck, midpass_every, box)
    return ds, store, tr, box, ck


def test_superstep_midpass_dispatch_boundary_resume_bit_exact(tmp_path):
    """k=2 superstep job snapshots mid-pass every 2 steps (one snapshot
    per dispatched group); a fresh job restored at (pass 1, mid 2)
    finishes pass 2 with skip_steps=2 and lands bit-identical dense +
    sparse planes and global_step."""
    import jax
    ds, store, tr, box, ck = _job(tmp_path, "ss_mid", k=2,
                                  midpass_every=2)
    assert tr._superstep_fn is not None
    for _ in range(2):
        box.begin_pass()
        tr.train_pass(ds)
        box.end_pass(checkpointer=ck, trainer=tr)
    tr.flush_sparse()
    keys = np.sort(np.asarray(ds.unique_keys(), np.uint64))
    want_rows = store.get_rows(keys)
    want_params = jax.tree.map(np.asarray, tr.params)
    assert (1, 2) in ck.intact_cursors()     # a dispatch-boundary cursor

    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds2, store2, tr2, box2, _ = _job(tmp_path, "ss_mid_unused", k=2)
    ck2 = PassCheckpointer(str(tmp_path / "ss_mid"), keep_last_n=6,
                           base_every=4)
    cursor = ck2.resume(tr2, box=box2, at=(1, 2))
    assert cursor["pass_id"] == 1 and cursor["mid_steps"] == 2
    box2.begin_pass()
    tr2.train_pass(ds2, skip_steps=cursor["mid_steps"])
    box2.end_pass(checkpointer=ck2, trainer=tr2)
    tr2.flush_sparse()
    np.testing.assert_array_equal(want_rows, store2.get_rows(keys))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        want_params, tr2.params)
    assert tr2.global_step == tr.global_step


def test_superstep_midpass_off_boundary_refused(tmp_path):
    """Cadences and resume cursors OFF the dispatch boundary keep a clear
    refusal — the k-microbatch program commits k steps atomically."""
    import pytest
    ds, store, tr, box, ck = _job(tmp_path, "ss_ref", k=2)
    with pytest.raises(NotImplementedError, match="dispatch boundary"):
        tr.enable_midpass_snapshots(ck, 3, box)
    tr.enable_midpass_snapshots(ck, 4, box)      # multiple of k: accepted
    assert tr._midpass is not None
    tr.enable_midpass_snapshots(ck, 0, box)      # off again
    with pytest.raises(NotImplementedError, match="dispatch boundary"):
        tr.train_pass(ds, skip_steps=3)
