"""Padded device-table width (flags.table_pad_width).

TPU random-row gathers run ~2x faster from 64/128-column sources than
from narrow odd widths, so the f32 device table pads its rows to
``working_set.device_width`` — semantics must be identical to the
logical-width table and no pad byte may ever cross host<->device.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import flags
from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                     PassWorkingSet, sharded)
from paddlebox_tpu.embedding.feed_pass import FeedPassManager
from paddlebox_tpu.embedding.working_set import device_width, fetch_rows
from paddlebox_tpu.parallel import make_mesh


@pytest.fixture
def pad_on():
    old = flags.table_pad_width
    flags.table_pad_width = "auto"
    yield
    flags.table_pad_width = old


def _mk(dim=32, n_keys=100):  # rw 38: inside the auto pad zone [16, 64)
    cfg = EmbeddingConfig(dim=dim, optimizer="adagrad", learning_rate=0.1)
    store = HostEmbeddingStore(cfg)
    rng = np.random.default_rng(0)
    keys = rng.choice(1 << 40, n_keys, replace=False).astype(np.uint64)
    return cfg, store, keys, rng


def test_device_width_rules():
    old = flags.table_pad_width
    try:
        flags.table_pad_width = "auto"
        # width-aware: only the pathological 14..63-lane gather zone
        # pads (round-5 v5e sweep — the slowdown starts at 14, ADVICE
        # r5); <=13-lane and >=64-lane sources are already fast and
        # keep their logical width
        assert device_width(EmbeddingConfig(dim=8)) == \
            EmbeddingConfig(dim=8).row_width                  # rw 13
        assert device_width(EmbeddingConfig(dim=9)) == 64     # rw 14
        assert device_width(EmbeddingConfig(dim=10)) == 64    # rw 15
        assert device_width(EmbeddingConfig(dim=32)) == 64    # rw 38
        assert device_width(EmbeddingConfig(dim=50)) == 64    # rw 55
        assert device_width(EmbeddingConfig(dim=100)) == \
            EmbeddingConfig(dim=100).row_width                # rw 105
        wide = EmbeddingConfig(dim=160)                       # rw > 128
        assert device_width(wide) == wide.row_width
        assert device_width(EmbeddingConfig(dim=8, storage="int8")) == \
            EmbeddingConfig(dim=8, storage="int8").row_width
        flags.table_pad_width = 0
        assert device_width(EmbeddingConfig(dim=8)) == \
            EmbeddingConfig(dim=8).row_width
        flags.table_pad_width = 96
        assert device_width(EmbeddingConfig(dim=8)) == 96
    finally:
        flags.table_pad_width = old


def test_padded_table_lookup_push_parity(pad_on):
    cfg, store, keys, rng = _mk()
    ws = PassWorkingSet.begin_pass(store, keys)
    assert ws.table.shape[1] == 64
    idx = ws.translate(rng.choice(keys, size=(32, 4)),
                       np.ones((32, 4), bool))
    flat = jnp.asarray(idx.reshape(-1))
    pulled_pad = np.asarray(sharded.lookup(ws.table, flat, cfg))

    # same store contents, unpadded table
    old = flags.table_pad_width
    flags.table_pad_width = 0
    try:
        store2 = HostEmbeddingStore(cfg)
        store2.lookup_or_init(keys)  # same zero-init rows
        ws2 = PassWorkingSet.begin_pass(store2, keys)
        assert ws2.table.shape[1] == cfg.row_width
        pulled_ref = np.asarray(sharded.lookup(ws2.table, flat, cfg))
    finally:
        flags.table_pad_width = old
    np.testing.assert_array_equal(pulled_pad, pulled_ref)

    # push parity: padded vs unpadded, same grads
    grads = rng.normal(size=(flat.shape[0], cfg.grad_width)
                       ).astype(np.float32)
    shows = np.ones(flat.shape[0], np.float32)
    clks = (rng.random(flat.shape[0]) < 0.3).astype(np.float32)
    args = (flat, jnp.asarray(grads), jnp.asarray(shows), jnp.asarray(clks))
    new_pad = np.asarray(sharded.push(ws.table, *args, cfg))
    new_ref = np.asarray(sharded.push(ws2.table, *args, cfg))
    np.testing.assert_allclose(new_pad[:, :cfg.row_width], new_ref,
                               rtol=0, atol=0)
    # pad columns stay exactly zero through the update
    assert (new_pad[:, cfg.row_width:] == 0).all()


def test_end_pass_and_fetch_rows_ship_logical_width(pad_on):
    cfg, store, keys, rng = _mk(n_keys=50)
    ws = PassWorkingSet.begin_pass(store, keys)
    idx = ws.translate(keys[:20].reshape(1, -1), np.ones((1, 20), bool))
    rows, nbytes = fetch_rows(ws.table, np.arange(1, 21), cfg)
    assert rows.shape == (20, cfg.row_width)
    nbytes_moved = ws.end_pass(store)
    # accounting is logical-width bytes (no pad bytes cross D2H)
    assert nbytes_moved <= ws.padded_rows * cfg.row_width * 4
    got = store.get_rows(keys[:5])
    assert got.shape == (5, cfg.row_width)


def test_feed_pass_incremental_keeps_padding(pad_on):
    cfg, store, keys, rng = _mk(n_keys=200)
    mgr = FeedPassManager(store)
    ws1 = mgr.begin_pass(keys[:150])
    assert ws1.table.shape[1] == 64
    # train-ish mutation so rows differ from zero init
    idx = ws1.translate(keys[:150].reshape(1, -1), np.ones((1, 150), bool))
    flat = jnp.asarray(idx.reshape(-1))
    g = jnp.asarray(rng.normal(size=(150, cfg.grad_width)
                               ).astype(np.float32))
    ws1.table = sharded.push(ws1.table, flat, g,
                             jnp.ones(150), jnp.zeros(150), cfg)
    mgr.end_pass(ws1, ws1.table)
    # second pass: 100 resident + 50 fresh keys — combine pads fresh rows
    ws2 = mgr.begin_pass(keys[50:])
    assert ws2.table.shape[1] == 64
    assert mgr.last_reused_rows > 0 and mgr.last_fresh_rows > 0
    # resident rows carried their trained values
    idx2 = ws2.translate(keys[50:150].reshape(1, -1),
                         np.ones((1, 100), bool))
    pulled = np.asarray(sharded.lookup(ws2.table,
                                       jnp.asarray(idx2.reshape(-1)), cfg))
    assert np.abs(pulled[:, 2]).sum() > 0   # trained w values survived
    mgr.flush()
    assert store.get_rows(keys[:5]).shape == (5, cfg.row_width)


def test_quant_tables_unpadded(pad_on):
    cfg = EmbeddingConfig(dim=8, storage="int16")
    store = HostEmbeddingStore(cfg)
    keys = np.arange(1, 40, dtype=np.uint64)
    ws = PassWorkingSet.begin_pass(store, keys)
    from paddlebox_tpu.embedding import quant
    assert quant.is_quant(ws.table)   # planes keep their own layout
