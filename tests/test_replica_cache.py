"""TrainerReplicaCache: the HBM replica hot tier on the TRAINING pull
path (flags.use_replica_cache).

Reference role: GpuReplicaCache (box_wrapper.h:140-248) above the
SSD+RAM hierarchy — the hottest rows mirrored to every device, the
staging short-circuiting the RAM/SSD fault path for them. The contract
under test is bit-consistency: a replica-served run must be
byte-identical to the no-replica baseline through a mutation-heavy
stream (write-backs, shrinks), on the single-store and sharded+spill
paths alike.
"""

import numpy as np

import jax.numpy as jnp

from paddlebox_tpu import monitor
from paddlebox_tpu.embedding import (EmbeddingConfig,
                                     ShardedEmbeddingStore,
                                     SpillEmbeddingStore, tiering)
from paddlebox_tpu.embedding.feed_pass import FeedPassManager
from paddlebox_tpu.embedding.replica_cache import TrainerReplicaCache
from paddlebox_tpu.monitor.flight import validate_flight_record
from paddlebox_tpu.parallel import make_mesh


def cfg_small(**kw):
    kw.setdefault("dim", 4)
    kw.setdefault("optimizer", "adagrad")
    kw.setdefault("learning_rate", 0.1)
    return EmbeddingConfig(**kw)


def _keys(lo, hi):
    return np.arange(lo, hi, dtype=np.uint64) * np.uint64(2654435761) + 1


# ---------------------------------------------------------------------------
# unit surface: refresh / serve / invalidation
# ---------------------------------------------------------------------------

def test_refresh_serves_tier_ranked_rows_bit_exact(tmp_path):
    st = SpillEmbeddingStore(cfg_small(), spill_dir=str(tmp_path / "s"),
                             cache_rows=256)
    keys = _keys(0, 128)
    rows = st.lookup_or_init(keys)
    rows[:, 0] = 5.0
    st.write_back(keys, rows)
    tiering.end_pass_rebalance(st)
    rc = TrainerReplicaCache(st, mesh=None, capacity_rows=1 << 10)
    assert rc.refresh() == 128
    out = rc.serve(keys)
    assert out is not None and out.n == 128 and out.hit.all()
    # replica bytes ARE store bytes (harvested from the memmap)
    np.testing.assert_array_equal(out.rows, st.get_rows(keys))
    np.testing.assert_array_equal(np.asarray(out.plane)[out.src],
                                  out.rows)


def test_note_written_and_stale_log_invalidate_served_keys(tmp_path):
    st = SpillEmbeddingStore(cfg_small(), spill_dir=str(tmp_path / "s"),
                             cache_rows=256)
    keys = _keys(0, 64)
    rows = st.lookup_or_init(keys)
    rows[:, 0] = 5.0
    st.write_back(keys, rows)
    tiering.end_pass_rebalance(st)
    rc = TrainerReplicaCache(st, mesh=None)
    rc.refresh()
    # write-back invalidation: the one mutation class outside the log
    rc.note_written(keys[:16])
    out = rc.serve(keys)
    assert out is not None and out.n == 48
    assert not out.hit[:16].any() and out.hit[16:].all()
    # a shrink that evicts rows enters the stale-key log — the next
    # serve folds it in before answering
    rows2 = st.get_rows(keys)
    rows2[16:32, 0] = 0.0
    st.write_back(keys, rows2)          # doomed rows lose their shows
    rc.refresh()                        # clean replica of current bytes
    assert st.shrink(min_show=1.0) == 16
    out2 = rc.serve(keys)
    assert out2 is not None
    assert not out2.hit[16:32].any()    # evicted keys never served
    assert out2.hit[32:].all()


def test_stale_log_overflow_drops_whole_replica(tmp_path):
    st = SpillEmbeddingStore(cfg_small(), spill_dir=str(tmp_path / "s"),
                             cache_rows=64)
    keys = _keys(0, 32)
    st.write_back(keys, st.lookup_or_init(keys))
    tiering.end_pass_rebalance(st)
    rc = TrainerReplicaCache(st, mesh=None)
    rc.refresh()
    assert len(rc) == 32
    # unprovable staleness (log overflow → None): everything drops
    st.stale_keys_since = lambda marker: None
    assert rc.serve(keys) is None
    assert len(rc) == 0
    assert rc.serve(keys) is None       # stays dropped until a refresh


# ---------------------------------------------------------------------------
# the acceptance bar: replica run bit-identical to the no-replica
# baseline through a mutation-heavy feed stream, hits on the record
# ---------------------------------------------------------------------------

A = _keys(0, 256)
B = _keys(1000, 1256)
C = _keys(2000, 2064)      # doomed: zero shows, evicted mid-stream


def _run_stream(store, mesh=None, use_replica=False):
    """Three passes (A∪B∪C → A → A∪B) through the incremental feed with
    write-backs every pass and a REAL eviction between the pass-2
    replica refresh and pass 3 — so pass 3's serve must fold the
    stale-key log (C gone, row ids compacted) out of the replica while
    still hitting every fresh B key. Returns (final store bytes,
    replica, flight records)."""
    mgr = FeedPassManager(store, mesh) if mesh is not None \
        else FeedPassManager(store)
    rc = None
    if use_replica:
        rc = TrainerReplicaCache(store, mesh=mesh, capacity_rows=1 << 10)
        mgr.set_replica(rc)
    h = monitor.hub()
    h.disable()
    ms = monitor.MemorySink()
    h.enable(ms)
    recs = []
    try:
        for p, ks in enumerate((np.concatenate([A, B, C]), A,
                                np.concatenate([A, B]))):
            h.begin_pass(p + 1)
            ws = mgr.begin_pass(ks)
            idx = ws.translate(ks)
            t = np.array(ws.table)
            t[idx, 2] += float(p + 1)
            t[idx, 0] += 4.0            # shows — the tier ranking signal
            if p == 0:
                t[ws.translate(C), 0] = 0.0   # C never earns its slot
            mgr.end_pass(ws, jnp.asarray(t))
            # the trainer's boundary order: rebalance → replica refresh
            # → flight-record commit (the hit delta lands in THIS pass)
            tiering.end_pass_rebalance(store)
            if rc is not None:
                rc.refresh()
            recs.append(h.end_pass())
            # out-of-cycle mutation AFTER the pass-2 refresh captured
            # its marker: evicting C enters the stale-key log and
            # compacts row ids under the replica — pass 3's serve must
            # prove B's bytes are still current before answering
            if p == 1:
                assert store.shrink(min_show=0.5) == len(C)
    finally:
        h.disable()
    mgr.flush()
    return store.get_rows(np.concatenate([A, B])), rc, recs


def test_replica_run_bit_identical_with_hits_in_flight_record(tmp_path):
    rows = {}
    for name, use in (("base", False), ("repl", True)):
        st = SpillEmbeddingStore(cfg_small(),
                                 spill_dir=str(tmp_path / name),
                                 cache_rows=1024)
        rows[name], rc, recs = _run_stream(st, use_replica=use)
    # pass 3's fresh keys (B re-entering) were served from the replica…
    assert rc.replica_hits == len(B)
    d3 = recs[2]["stats_delta"]
    assert d3.get("tiering.replica_hits") == len(B)
    # the replica_rows gauge moved inside pass 3 (C's eviction shrank
    # the harvest), so its delta is on the record; the post-stream
    # flush's note_written then rightly empties the replica
    assert d3.get("tiering.replica_rows", 0.0) != 0.0
    assert rc.refreshes == 3 and len(rc) == 0
    assert all(validate_flight_record(r) == [] for r in recs)
    # …and the training stream is bit-identical to the baseline's
    np.testing.assert_array_equal(rows["repl"], rows["base"])


def test_replica_parity_on_sharded_spill_mesh(tmp_path):
    mesh = make_mesh(8)
    rows = {}
    for name, use in (("base", False), ("repl", True)):
        ss = ShardedEmbeddingStore(
            cfg_small(), 2, store_factory=tiering.shard_store_factory(
                tiering="spill", cache_rows=1024,
                spill_dir=str(tmp_path / name)))
        rows[name], rc, recs = _run_stream(ss, mesh=mesh, use_replica=use)
    assert rc.replica_hits == len(B)
    assert recs[2]["stats_delta"].get("tiering.replica_hits") == len(B)
    np.testing.assert_array_equal(rows["repl"], rows["base"])
