"""Crash-safe train→publish→serve loop (ISSUE 7).

The acceptance bar: **a torn publish must never serve.** Tier-1 proves
the loop end-to-end on CPU (one pass → publish → serve → scores match a
Predictor on the same params) plus one publish kill-point; the ``slow``
matrix kills a real training+publishing subprocess at EVERY
``serving.publish.*`` fault point and proves every ANNOUNCED version
verifies, the server never loads a torn one, and the resumed run catches
serving up to score parity — plus hot-swap under concurrent load with
zero dropped requests and stale-version fallback.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddlebox_tpu.data import DataFeedSchema
from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.fleet import BoxPS, FleetUtil
from paddlebox_tpu.inference import Predictor, ServingTable
from paddlebox_tpu.models import DeepFMModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.serving import (DONEFILE, BatchingFrontend,
                                   ServingPublisher, ServingServer,
                                   ServingUnavailableError, read_artifact)
from paddlebox_tpu.train import Trainer, TrainerConfig
from paddlebox_tpu.utils import faultpoint

from test_train_e2e import synth_dataset, NUM_SLOTS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "serving_worker.py")


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faultpoint.disarm()


@pytest.fixture()
def job(tmp_path):
    """One trained pass + a publisher + an untouched serving root."""
    ds, schema = synth_dataset(256)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, learning_rate=0.15))
    model = DeepFMModel(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1,
                        hidden=(16,))
    tr = Trainer(model, store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=64, dense_lr=3e-3))
    box = BoxPS(store)
    pub = ServingPublisher(str(tmp_path / "serve"), model, schema,
                           publish_base_every=2, quant="f32",
                           hot_top_k=16)
    box.begin_pass()
    tr.train_pass(ds)
    return ds, schema, store, model, tr, box, pub, str(tmp_path / "serve")


def _live_predictor(tr, store, model, schema):
    return Predictor(model, tr.eval_params(),
                     ServingTable.from_store(store), schema)


# ---------------------------------------------------------------- tier-1


def test_publish_serve_scores_match_predictor(job):
    """The tier-1 loop: end_pass publishes, the server tails + swaps, and
    the served scores bit-match a Predictor on the same params."""
    ds, schema, store, model, tr, box, pub, root = job
    info = box.end_pass(trainer=tr, publisher=pub)["publish"]
    assert info["kind"] == "base" and info["announced"]
    srv = ServingServer(root, poll_s=0.05)
    assert srv.poll_once() == 1
    h = srv.health()
    assert h["status"] == "ok" and h["active_version"] == 1
    assert h["active_pass"] == 1 and h["pass_lag"] == 0
    pb = next(iter(ds.batches(batch_size=64)))
    got = srv.predict_batch(pb)
    want = _live_predictor(tr, store, model, schema).predict_batch(pb)
    np.testing.assert_allclose(want, got, rtol=1e-6, atol=1e-7)
    # hot keys landed in the replica cache at full precision
    m = srv.active
    assert m.replica_cache is not None and len(m.replica_cache) == 17
    np.testing.assert_array_equal(
        m.replica_cache.translate(m.hot_keys) > 0,
        np.ones(len(m.hot_keys), bool))


def test_delta_publish_and_hot_swap(job):
    """Pass 2 publishes a key-delta; the server swaps to it and serves
    the updated model; in-flight handles on v1 stay intact."""
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)
    srv = ServingServer(root)
    srv.poll_once()
    v1 = srv.active
    box.begin_pass()
    tr.train_pass(ds)
    info = box.end_pass(trainer=tr, publisher=pub)["publish"]
    assert info["kind"] == "delta"
    assert srv.poll_once() == 1
    assert srv.active.version == 2 and srv.active.kind == "delta"
    pb = next(iter(ds.batches(batch_size=64)))
    want = _live_predictor(tr, store, model, schema).predict_batch(pb)
    np.testing.assert_allclose(want, srv.predict_batch(pb),
                               rtol=1e-6, atol=1e-7)
    # the v1 handle still serves its own (older) table — swap did not
    # mutate it (copy-on-write)
    assert v1.version == 1
    old = v1.predictor.predict_batch(pb)
    assert not np.allclose(old, want)


def test_publish_killpoint_never_announces_torn(job):
    """Tier-1 kill-point (ioerror flavor): a publish failing at
    pre_donefile — artifact fully written and verified, announce lost —
    leaves the donefile unchanged, the server on its last good version,
    and the NEXT publish lands cleanly."""
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)
    srv = ServingServer(root)
    srv.poll_once()
    faultpoint.arm("serving.publish.pre_donefile", action="ioerror")
    box.begin_pass()
    tr.train_pass(ds)
    with pytest.warns(UserWarning, match="publish failed"):
        out = box.end_pass(trainer=tr, publisher=pub)
    assert "error" in out["publish"]
    faultpoint.disarm()
    assert srv.poll_once() == 0            # nothing new announced
    assert srv.active.version == 1
    # every announced version still verifies (the invariant)
    for e in FleetUtil(root)._entries(DONEFILE):
        read_artifact(e["path"], verify=True)
    # recovery: the next publish re-lands the state
    box.begin_pass()
    tr.train_pass(ds)
    info = box.end_pass(trainer=tr, publisher=pub)["publish"]
    assert info["announced"]
    assert srv.poll_once() == 1
    pb = next(iter(ds.batches(batch_size=64)))
    want = _live_predictor(tr, store, model, schema).predict_batch(pb)
    np.testing.assert_allclose(want, srv.predict_batch(pb),
                               rtol=1e-6, atol=1e-7)


def test_quantized_publish_bounded_error(tmp_path, job):
    """int8 cold rows: served scores track the f32 predictor within the
    quantization error bound; hot rows stay exact in the replica cache."""
    ds, schema, store, model, tr, box, pub, _ = job
    root8 = str(tmp_path / "serve8")
    pub8 = ServingPublisher(root8, model, schema, publish_base_every=4,
                            quant="int8", hot_top_k=8)
    box.end_pass(trainer=tr, publisher=pub8)
    srv = ServingServer(root8)
    srv.poll_once()
    pb = next(iter(ds.batches(batch_size=64)))
    want = _live_predictor(tr, store, model, schema).predict_batch(pb)
    got = srv.predict_batch(pb)
    np.testing.assert_allclose(want, got, atol=0.02)
    assert not np.array_equal(want, got)    # quantization really applied
    m = srv.active
    rows = store.get_rows(m.hot_keys)[:, :m.table.pull_width]
    pos, hit = m.table._probe(m.hot_keys)
    np.testing.assert_array_equal(rows[hit], m.table.vals[pos[hit]])


def test_server_empty_root_unavailable(tmp_path):
    srv = ServingServer(str(tmp_path / "nothing"))
    assert srv.poll_once() == 0
    assert srv.health()["status"] == "empty"
    with pytest.raises(ServingUnavailableError):
        srv.predict(np.zeros((1, 2), np.uint64), np.ones((1, 2), bool))


def test_frontend_batches_and_scores(job):
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)
    srv = ServingServer(root)
    srv.poll_once()
    fe = BatchingFrontend(srv, max_batch=32, max_wait_s=0.01).start()
    pb = next(iter(ds.batches(batch_size=64)))
    lc, lw, _ = pb.schema.float_split_cols("label")
    floats = np.concatenate([pb.floats[:, :lc], pb.floats[:, lc + lw:]],
                            axis=1)
    try:
        futs = [fe.submit(pb.ids[i].astype(np.uint64), pb.mask[i],
                          floats[i]) for i in range(48)]
        got = np.asarray([f.result(timeout=60) for f in futs])
    finally:
        fe.stop()
    want = srv.predict(pb.ids.astype(np.uint64), pb.mask, floats)[:48]
    np.testing.assert_allclose(want, got, rtol=1e-6, atol=1e-7)
    st = fe.stats()
    assert st["count"] == 48 and st["failures"] == 0
    assert st["p99_ms"] >= st["p50_ms"] > 0


def test_health_endpoint_http(job):
    """The runbook surface: /healthz serves the health JSON (503 before a
    model loads, 200 after), /metrics the Prometheus exposition."""
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)
    srv = ServingServer(root, health_port=0)
    try:
        url = f"http://127.0.0.1:{srv.health_port}/healthz"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=10)
        assert ei.value.code == 503
        srv.poll_once()
        with urllib.request.urlopen(url, timeout=10) as r:
            h = json.loads(r.read())
        assert h["status"] == "ok" and h["active_version"] == 1
        # per-version fields ride the same payload (ISSUE 19)
        assert h["candidate_version"] is None
        assert h["split_fraction"] == 0.0 and h["shadow"] is False
        assert h["versions"]["1"]["role"] == "stable"
        metrics_url = f"http://127.0.0.1:{srv.health_port}/metrics"
        with urllib.request.urlopen(metrics_url, timeout=10) as r:
            assert b"pbtpu" in r.read()
    finally:
        srv.stop()


def test_staleness_reported_when_publishes_stop(job):
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)
    # donefile ts has 1-second resolution: the age right after a publish
    # is < 1s, so a 1.5s threshold is deterministic on both sides
    srv = ServingServer(root, stale_after_s=1.5, stale_pass_lag=99)
    srv.poll_once()
    assert srv.health()["status"] == "ok"
    time.sleep(2.0)
    h = srv.health()
    assert h["status"] == "stale" and h["age_seconds"] >= 1.5
    # a fresh publish clears it
    box.begin_pass()
    tr.train_pass(ds)
    box.end_pass(trainer=tr, publisher=pub)
    srv.poll_once()
    assert srv.health()["status"] == "ok"


def test_health_tolerates_foreign_tail_entry(job):
    """A valid-JSON donefile tail line with no 'version' (foreign writer,
    hand edit) must degrade the report, not 500 /healthz or break every
    subsequent poll."""
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)
    srv = ServingServer(root)
    srv.poll_once()
    FleetUtil(root).append_donefile(DONEFILE, {"day": 20260801,
                                               "note": "foreign"})
    with pytest.warns(UserWarning, match="unusable donefile entry"):
        srv.poll_once()                 # must not raise
    h = srv.health()                    # must not raise either
    assert h["active_version"] == 1 and h["announced_version"] is None
    # versionless, so _skipped can't remember it: the dedup set must —
    # the tailer hits this line once per poll_s forever otherwise
    import warnings as _warnings
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        srv.poll_once()


def test_cold_start_seeks_newest_base(job):
    """A fresh server starts from the newest loadable base + trailing
    deltas instead of replaying the donefile's whole history; a rotted
    newest base falls back to the previous base chain."""
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)            # v1 base
    base3_path = None
    for _ in range(3):                                 # v2 delta, v3 base,
        box.begin_pass()                               # v4 delta
        tr.train_pass(ds)
        info = box.end_pass(trainer=tr, publisher=pub)["publish"]
        if info["version"] == 3:
            assert info["kind"] == "base"
            base3_path = info["path"]
    srv = ServingServer(root)
    assert srv.poll_once() == 2                        # v3 + v4 only
    assert srv.active.version == 4 and srv._swaps == 2
    pb = next(iter(ds.batches(batch_size=64)))
    want = _live_predictor(tr, store, model, schema).predict_batch(pb)
    np.testing.assert_allclose(want, srv.predict_batch(pb),
                               rtol=1e-6, atol=1e-7)
    # rot the newest base: the next fresh server must fall back to the
    # v1 base + v2 delta chain (v4 parents the rotted v3 — dead)
    with open(os.path.join(base3_path, "sparse.npz"), "r+b") as f:
        f.seek(16)
        f.write(b"\xde\xad\xbe\xef")
    srv2 = ServingServer(root)
    with pytest.warns(UserWarning):
        applied = srv2.poll_once()
    assert applied == 2 and srv2.active.version == 2
    assert srv2.health()["status"] in ("degraded", "stale")


def test_build_rejects_version_mismatch(job):
    """CRCs only prove an artifact matches ITS manifest — an entry whose
    path holds a different version's artifact (stale staging, foreign
    line) must be skipped with a diagnostic, never served as the
    announced version."""
    ds, schema, store, model, tr, box, pub, root = job
    info = box.end_pass(trainer=tr, publisher=pub)["publish"]
    with open(os.path.join(root, DONEFILE), "a") as f:
        f.write(json.dumps({"version": 2, "pass": 2, "kind": "base",
                            "parent": None, "path": info["path"],
                            "ts": int(time.time())}) + "\n")
    srv = ServingServer(root)
    with pytest.warns(UserWarning, match="claims"):
        srv.poll_once()
    assert srv.active.version == 1
    assert 2 in srv.health()["skipped_versions"]


def test_frontend_splits_mixed_dense_batch(job):
    """Dense presence changes the predict signature: requests carrying
    dense features must score WITH them even when coalesced behind a
    dense-less request (which previously keyed the whole batch)."""
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)
    srv = ServingServer(root)
    srv.poll_once()
    pb = next(iter(ds.batches(batch_size=64)))
    lc, lw, _ = pb.schema.float_split_cols("label")
    floats = np.concatenate([pb.floats[:, :lc], pb.floats[:, lc + lw:]],
                            axis=1)
    fe = BatchingFrontend(srv, max_batch=32, max_wait_s=0.05).start()
    try:
        f_nd = fe.submit(pb.ids[0].astype(np.uint64), pb.mask[0])
        futs = [fe.submit(pb.ids[i].astype(np.uint64), pb.mask[i],
                          floats[i]) for i in range(1, 5)]
        got = np.asarray([f.result(timeout=60) for f in futs])
        try:
            f_nd.result(timeout=60)     # may legitimately error (model
        except Exception:               # requires dense) — must not
            pass                        # poison the dense group
    finally:
        fe.stop()
    want = srv.predict(pb.ids[1:5].astype(np.uint64), pb.mask[1:5],
                       floats[1:5])
    np.testing.assert_allclose(want, got, rtol=1e-6, atol=1e-7)


# ------------------------------------- version split / shadow (ISSUE 19)


@pytest.fixture()
def _split_flags():
    from paddlebox_tpu.config import flags, set_flags
    keys = ("serving_split_fraction", "serving_shadow",
            "serving_window_s", "serving_trace_sample")
    saved = {k: flags.get(k) for k in keys}
    yield set_flags
    for k, v in saved.items():
        flags.set(k, v)


def _req_batch(ds):
    pb = next(iter(ds.batches(batch_size=64)))
    lc, lw, _ = pb.schema.float_split_cols("label")
    floats = np.concatenate([pb.floats[:, :lc], pb.floats[:, lc + lw:]],
                            axis=1)
    return pb.ids.astype(np.uint64), pb.mask, floats


class _WorsePredictor:
    """The injected-worse candidate: anti-correlated scores."""

    def __init__(self, inner):
        self._inner = inner

    def predict(self, ids, mask, dense=None):
        return 1.0 - self._inner.predict(ids, mask, dense)


def test_shadow_two_versions_records_and_doctor_verdicts(job, _split_flags):
    """ISSUE 19 acceptance: a two-version shadow run produces
    schema-valid serving window records the doctor reads end to end —
    version-regression FIRES on an injected-worse candidate and stays
    quiet when the versions score identically."""
    from paddlebox_tpu import monitor
    from paddlebox_tpu.monitor import doctor, flight
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)            # v1 (stable)
    _split_flags(serving_shadow=True)
    ms = monitor.MemorySink()
    monitor.hub().enable(ms)
    try:
        srv = ServingServer(root)
        srv.poll_once()
        # v2 publishes the SAME params (no training in between): a
        # byte-identical candidate — the deterministic quiet case
        pub.publish(store, tr.eval_params(), pass_id=1)
        assert srv.poll_once() == 1
        assert srv.active.version == 1 and srv.candidate.version == 2
        ids, mask, floats = _req_batch(ds)
        served = srv.predict(ids, mask, floats)
        # delayed labels arrive, perfectly separating the stable scores:
        # both versions scored the batch, both join, identical AUC
        labels = (np.asarray(served) >
                  np.median(served)).astype(np.float64).reshape(-1)
        joined = srv.observe_labels(labels)
        assert set(joined) == {1, 2}
        assert srv.commit_window(force=True) is not None
        rec = ms.find("serving_window")[-1]
        assert flight.validate_serving_record(rec) == []
        v = rec["fields"]["versions"]
        assert v["1"]["role"] == "stable"
        assert v["2"]["role"] == "candidate"
        assert v["2"]["auc"] == pytest.approx(v["1"]["auc"])
        assert v["2"]["score_kl"] == pytest.approx(0.0, abs=1e-9)
        assert rec["fields"]["requests"] == 64      # shadow not counted
        rep = doctor.diagnose(servings=[rec])
        status = {r["rule"]: r["status"] for r in rep["rules"]}
        assert status["version-regression"] == "quiet"

        # inject the worse candidate: the next window's record must fire
        srv._candidate.predictor = _WorsePredictor(
            srv._candidate.predictor)
        served = srv.predict(ids, mask, floats)
        labels = (np.asarray(served) >
                  np.median(served)).astype(np.float64).reshape(-1)
        srv.observe_labels(labels)
        srv.commit_window(force=True)
        rec2 = ms.find("serving_window")[-1]
        assert flight.validate_serving_record(rec2) == []
        v2 = rec2["fields"]["versions"]
        assert v2["1"]["auc"] - v2["2"]["auc"] > 0.2
        rep2 = doctor.diagnose(servings=[rec2])
        status2 = {r["rule"]: r["status"] for r in rep2["rules"]}
        assert status2["version-regression"] == "fired"
        f = next(f for f in rep2["findings"]
                 if f["rule"] == "version-regression")
        assert f["severity"] == "critical"
        assert f["evidence"]["candidate_version"] == "2"
        assert "do not promote" in f["suggestion"]
    finally:
        monitor.hub().disable()


def test_live_split_routes_and_health_reports_versions(job, _split_flags):
    """flags.serving_split_fraction live-splits request batches between
    stable and candidate (deterministic accumulator), /healthz reports
    the per-version fields, and dropping the split promotes."""
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)
    _split_flags(serving_split_fraction=0.5)
    srv = ServingServer(root)
    srv.poll_once()
    pub.publish(store, tr.eval_params(), pass_id=1)       # identical v2
    assert srv.poll_once() == 1
    assert srv.active.version == 1 and srv.candidate.version == 2
    ids, mask, floats = _req_batch(ds)
    for _ in range(4):
        srv.predict(ids, mask, floats)
    h = srv.health()
    assert h["status"] == "ok"
    assert h["active_version"] == 1 and h["candidate_version"] == 2
    assert h["split_fraction"] == 0.5 and h["shadow"] is False
    assert h["versions"]["1"]["role"] == "stable"
    assert h["versions"]["2"]["role"] == "candidate"
    assert h["versions"]["2"]["age_seconds"] >= 0
    # 4 batches at fraction 0.5: exactly 2 routed to each version
    fields = srv.commit_window(force=True)
    assert fields["versions"]["1"]["requests"] == 128
    assert fields["versions"]["2"]["requests"] == 128
    assert fields["requests"] == 256                 # all batches served
    assert fields["active_version"] == 1
    assert fields["candidate_version"] == 2
    # split off -> the next poll promotes the held candidate
    _split_flags(serving_split_fraction=0.0)
    assert srv.poll_once() == 0
    assert srv.active.version == 2 and srv.candidate is None
    hh = srv.health()
    assert hh["candidate_version"] is None
    assert hh["versions"]["2"]["role"] == "stable"


def test_frontend_latency_window_ages_out(job, _split_flags):
    """The satellite fix: the frontend's reservoir is time-windowed —
    after an idle spell the percentiles report NO stale traffic instead
    of blending hours of history (count stays cumulative)."""
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)
    srv = ServingServer(root)
    srv.poll_once()
    ids, mask, floats = _req_batch(ds)
    fe = BatchingFrontend(srv, max_batch=8, max_wait_s=0.01,
                          window_s=0.4).start()
    try:
        futs = [fe.submit(ids[i], mask[i], floats[i]) for i in range(8)]
        [f.result(timeout=60) for f in futs]
        st = fe.stats()
        assert st["count"] == 8 and st["window_count"] == 8
        assert st["p99_ms"] >= st["p50_ms"] > 0
        time.sleep(0.6)                   # the window empties
        assert fe.stats() == {"count": 0, "failures": 0}
    finally:
        fe.stop()


# -------------------------------------------------- donefile satellites


def test_staged_fetch_removed_after_swap(job, tmp_path):
    """A remote-tailing server stages each download before verify; once
    the build consumed it the copy must go — a forever-running host
    accumulating one artifact per publish would fill the staging disk
    and degrade permanently."""
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)
    stage = str(tmp_path / "stage")
    srv = ServingServer(root, staging_dir=stage)
    srv._remote = True                  # force the staging path (LocalFS.get)
    assert srv.poll_once() == 1 and srv.active.version == 1
    assert os.listdir(stage) == []
    # a version that fails verify must not leave its partial behind either
    box.begin_pass()
    tr.train_pass(ds)
    info = pub.publish(store, tr.eval_params(), pass_id=2)
    with open(os.path.join(info["path"], "sparse.npz"), "r+b") as f:
        f.seek(20)
        f.write(b"\xde\xad\xbe\xef")
    with pytest.warns(UserWarning, match="v2"):
        srv.poll_once()
    assert srv.active.version == 1 and os.listdir(stage) == []


def test_frontend_submit_during_stop_never_leaves_pending_future():
    """submit() racing stop()'s drain: a request put after the queue was
    drained must still resolve (with an error), never hang the caller's
    future forever."""
    fe = BatchingFrontend(server=None, max_batch=4)
    # emulate the interleaving: submit passed the liveness check, then
    # stop() set _stopping and drained the queue before the put landed
    fe._thread = threading.Thread(target=lambda: None)
    fe._stopping = True
    f = fe.submit(np.zeros(4, np.uint64), np.zeros(4, bool))
    with pytest.raises(RuntimeError, match="stopped before dispatch"):
        f.result(timeout=1)


def test_fleet_donefile_skips_malformed_lines(tmp_path):
    """A half-written/foreign donefile line must not brick model
    discovery: _entries/latest skip it with a named warning."""
    fleet = FleetUtil(str(tmp_path))
    fleet.append_donefile("x.donefile", {"day": 1, "pass": 1, "path": "a"})
    with open(tmp_path / "x.donefile", "a") as f:
        f.write('{"day": 2, "pass": 2, "pa')     # torn mid-write
        f.write("\n[1, 2, 3]\n")                 # valid JSON, not an object
    # the append's internal latest() does the first parse — that's where
    # the torn lines are diagnosed, once
    with pytest.warns(UserWarning, match="malformed line 2"):
        fleet.append_donefile("x.donefile", {"day": 3, "pass": 3,
                                             "path": "c"})
    # a tailer re-reads every poll: the same torn line still skips but
    # must not re-warn forever (it would drown the alert signal)
    import warnings as _warnings
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        entries = fleet._entries("x.donefile")
        assert [e["day"] for e in entries] == [1, 3]
        assert fleet.latest("x.donefile")["day"] == 3
    # a fresh instance (new process) diagnoses it again
    with pytest.warns(UserWarning, match="malformed line 2"):
        FleetUtil(str(tmp_path))._entries("x.donefile")


def test_append_donefile_idempotent_on_replay(tmp_path):
    fleet = FleetUtil(str(tmp_path))
    e = {"version": 1, "pass": 1, "path": "p1"}
    assert fleet.append_donefile("s.donefile", e, dedup=("version", "path"))
    assert not fleet.append_donefile("s.donefile", dict(e, ts=9),
                                     dedup=("version", "path"))
    assert len(fleet._entries("s.donefile")) == 1


def test_serving_table_duplicate_key_error_names_keys():
    keys = np.asarray([7, 7, 9, 9, 3], np.uint64)
    with pytest.raises(ValueError, match=r"2 key\(s\)") as ei:
        ServingTable(keys, np.zeros((5, 2), np.float32))
    assert "7" in str(ei.value) and "9" in str(ei.value)


# ------------------------------------------------------- slow matrices


# ------------------------------------------------- donefile compaction


def _publish_passes(job, n):
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)        # pass 1 (already trained)
    for _ in range(n - 1):
        box.begin_pass()
        tr.train_pass(ds)
        box.end_pass(trainer=tr, publisher=pub)
    return os.path.join(root, DONEFILE)


def test_donefile_compaction_keeps_serving_loadable(job):
    """Delta-chain compaction: the donefile keeps the newest keep_bases
    bases + everything after the oldest kept base; a cold-starting
    server still loads the newest version off the compacted file, pruned
    version dirs are reclaimed, and the version sequence continues."""
    ds, schema, store, model, tr, box, pub, root = job
    df = _publish_passes(job, 7)                   # base_every=2 → 4 bases
    n_before = len(open(df).read().splitlines())
    dropped = pub.compact_donefile(keep_bases=2)
    lines = [json.loads(ln) for ln in open(df).read().splitlines()]
    assert dropped > 0 and len(lines) == n_before - dropped
    assert not os.path.exists(df + ".compact")     # two-phase completed
    assert sum(1 for e in lines if e["kind"] == "base") == 2
    # pruned artifacts gone, kept ones intact
    kept_versions = {e["version"] for e in lines}
    dirs = {n for n in os.listdir(root) if n.startswith("v-")}
    assert dirs == {f"v-{v:06d}" for v in kept_versions}
    srv = ServingServer(root, poll_s=0.05)
    assert srv.poll_once() >= 1
    assert srv.active is not None and srv.active.version == 7
    srv.stop()
    # the sequence continues across the rewrite
    box.begin_pass()
    tr.train_pass(ds)
    info = box.end_pass(trainer=tr, publisher=pub)["publish"]
    assert info["version"] == 8


def test_donefile_compaction_auto_threshold(job):
    """publish() auto-compacts once the donefile passes compact_after."""
    ds, schema, store, model, tr, box, pub, root = job
    pub.compact_after = 4
    pub.keep_bases = 1
    df = _publish_passes(job, 6)
    lines = open(df).read().splitlines()
    # bounded: at most compact_after + the entries after the kept base
    assert len(lines) <= pub.compact_after
    assert json.loads(lines[0])["kind"] == "base"


def test_interrupted_compaction_append_repairs_first(job):
    """A kill between the compaction's rm and rewrite leaves only the
    .compact staging copy: reads fall back to it, and the NEXT append
    restores the full history before extending — the main file is never
    recreated with a single line (the PR-6 hazard, regression-tested on
    the serving root)."""
    ds, schema, store, model, tr, box, pub, root = job
    df = _publish_passes(job, 3)
    content = open(df).read()
    # simulate the torn window: staging copy present, main file gone
    with open(df + ".compact", "w") as f:
        f.write(content)
    os.remove(df)
    # reads fall back to the staging copy
    assert pub.latest_announced()["version"] == 3
    srv = ServingServer(root, poll_s=0.05)
    assert srv.poll_once() >= 1 and srv.active.version == 3
    srv.stop()
    # the next publish's append repairs the main file FIRST
    box.begin_pass()
    tr.train_pass(ds)
    info = box.end_pass(trainer=tr, publisher=pub)["publish"]
    assert info["version"] == 4
    final = open(df).read().splitlines()
    assert len(final) == len(content.splitlines()) + 1
    assert not os.path.exists(df + ".compact")
    assert json.loads(final[-1])["version"] == 4


def _run_worker(root, out, env_extra=None, check=True):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PBTPU_FAULTPOINT", None)
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, WORKER, str(root), str(out)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    if check and proc.returncode != 0:
        raise AssertionError(
            f"worker failed ({proc.returncode}):\n{proc.stdout}\n"
            f"{proc.stderr}")
    return proc


def _assert_announced_all_verify(serve_root):
    """THE invariant: every donefile-announced version verifies clean."""
    entries = FleetUtil(serve_root)._entries(DONEFILE)
    for e in entries:
        read_artifact(e["path"], verify=True)
    return entries


def _serve_batch(schema=None):
    from crash_worker import synth
    ds, schema = synth()
    return next(iter(ds.batches(batch_size=64)))


@pytest.fixture(scope="module")
def serving_golden(tmp_path_factory):
    """Uninterrupted train+publish run → final predictor scores."""
    d = tmp_path_factory.mktemp("serve_golden")
    out = d / "out.npz"
    _run_worker(d / "root", out)
    with np.load(out) as z:
        return {k: z[k] for k in z.files}


# AFTER=0 kills the first (base) publish window, AFTER=1 the second
# (delta) — both artifact kinds cross every window.
@pytest.mark.slow
@pytest.mark.parametrize("after", [0, 1])
@pytest.mark.parametrize("point", sorted(faultpoint.SERVING_POINTS))
def test_publish_kill_matrix(point, after, tmp_path, serving_golden):
    """Kill a real training+publishing subprocess at every publish
    window: no announced version may ever be torn, a tailing server ends
    on a verified version, and the resumed run (incl. the catch-up
    republish) reaches score parity with the uninterrupted golden."""
    root, out = tmp_path / "root", tmp_path / "out.npz"
    killed = _run_worker(
        root, out, check=False,
        env_extra={"PBTPU_FAULTPOINT": point,
                   "PBTPU_FAULTPOINT_AFTER": str(after)})
    assert killed.returncode == 137, (killed.stdout, killed.stderr)
    assert f"FAULTPOINT KILL {point}" in killed.stderr
    serve_root = str(root / "serve")
    # invariant after the kill: announced ⊆ verified
    entries = _assert_announced_all_verify(serve_root)
    assert len(entries) == after, \
        f"the killed publish must not be announced: {entries}"
    srv = ServingServer(serve_root)
    srv.poll_once()
    assert (srv.active.version if srv.active else 0) == after
    # resume: training continues, serving catches up, scores match golden
    resumed = _run_worker(root, out)
    assert "resume cursor=" in resumed.stdout
    entries = _assert_announced_all_verify(serve_root)
    assert int(entries[-1]["pass"]) == 3
    srv.poll_once()
    assert srv.active.pass_id == 3
    got = srv.predict_batch(_serve_batch())
    np.testing.assert_allclose(serving_golden["probs"], got,
                               rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_hot_swap_under_concurrent_load(job):
    """Requests hammer the server from 8 threads while three new
    versions publish and swap in: ZERO failed requests, every result is
    a valid probability vector from one of the published versions, and
    the recorded swap pause stays bounded (ms-scale, not seconds)."""
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)
    srv = ServingServer(root, poll_s=0.01).start()
    deadline = time.time() + 10
    while srv.active is None and time.time() < deadline:
        time.sleep(0.01)
    assert srv.active is not None
    pb = next(iter(ds.batches(batch_size=64)))
    ids, mask = pb.ids.astype(np.uint64), pb.mask
    lc, lw, _ = pb.schema.float_split_cols("label")
    floats = np.concatenate([pb.floats[:, :lc], pb.floats[:, lc + lw:]],
                            axis=1)
    # warm the compile before load starts (the swap itself must not
    # compile — Predictor.with_model shares the jitted fwd)
    srv.predict(ids, mask, floats)
    stop = threading.Event()
    errors, results = [], []
    lock = threading.Lock()

    def hammer():
        while not stop.is_set():
            try:
                p = srv.predict(ids, mask, floats)
                with lock:
                    results.append((srv.active.version, np.asarray(p)))
            except Exception as e:   # noqa: BLE001 — the assertion target
                with lock:
                    errors.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    [t.start() for t in threads]
    try:
        versions = {}
        versions[1] = _live_predictor(tr, store, model,
                                      schema).predict_batch(pb)
        for v in (2, 3, 4):
            box.begin_pass()
            tr.train_pass(ds)
            box.end_pass(trainer=tr, publisher=pub)
            versions[v] = _live_predictor(tr, store, model,
                                          schema).predict_batch(pb)
            time.sleep(0.15)           # let the tailer swap under load
    finally:
        stop.set()
        [t.join(timeout=30) for t in threads]
        srv.stop()
    assert not errors, errors[:3]
    assert srv.active.version == 4 and srv._swaps == 4
    assert srv.health()["request_failures"] == 0
    assert len(results) > 50
    # every served result matches EXACTLY one published version (no torn
    # tables, no half-swapped states)
    for _v_seen, p in results[:: max(1, len(results) // 64)]:
        assert any(np.allclose(p, versions[v], rtol=1e-5, atol=1e-6)
                   for v in versions), "served scores match no version"
    assert srv._last_swap_pause_ms < 100.0


@pytest.mark.slow
def test_stale_version_fallback_and_recovery(job, tmp_path):
    """An ANNOUNCED version corrupted after the fact (storage rot — the
    publisher's verify passed) must be diagnosed and skipped: the server
    keeps serving the last good version, reports degraded, and recovers
    on the next clean base."""
    ds, schema, store, model, tr, box, _pub, _root = job
    # base every THREE publishes: v1 base, v2/v3 deltas, v4 base — the
    # exact shape the parent-gap scenario needs
    root = str(tmp_path / "serve3")
    pub = ServingPublisher(root, model, schema, publish_base_every=3,
                           quant="f32", hot_top_k=16)
    box.end_pass(trainer=tr, publisher=pub)
    srv = ServingServer(root, fetch_attempts=2, fetch_backoff_s=0.01)
    srv.poll_once()
    pb = next(iter(ds.batches(batch_size=64)))
    want_v1 = srv.predict_batch(pb)
    # v2 publishes clean, then rots on disk
    box.begin_pass()
    tr.train_pass(ds)
    info = box.end_pass(trainer=tr, publisher=pub)["publish"]
    sp = os.path.join(info["path"], "sparse.npz")
    with open(sp, "r+b") as f:
        f.seek(max(0, os.path.getsize(sp) // 2))
        f.write(b"\xde\xad\xbe\xef")
    with pytest.warns(UserWarning, match="continuing on the last good"):
        assert srv.poll_once() == 0
    assert srv.active.version == 1
    h = srv.health()
    assert h["status"] == "degraded" and h["skipped_versions"] == [2]
    assert h["last_error"] and "v2" in h["last_error"]
    np.testing.assert_array_equal(want_v1, srv.predict_batch(pb))
    # v3 (delta) parents the rotted v2 → must also be skipped, with the
    # reason naming the parent gap
    box.begin_pass()
    tr.train_pass(ds)
    assert box.end_pass(trainer=tr, publisher=pub)["publish"][
        "kind"] == "delta"
    with pytest.warns(UserWarning, match="waiting for the next base"):
        assert srv.poll_once() == 0
    assert srv.active.version == 1
    # v4 is a base (publish_base_every=2) → full recovery
    box.begin_pass()
    tr.train_pass(ds)
    info = box.end_pass(trainer=tr, publisher=pub)["publish"]
    assert info["kind"] == "base"
    assert srv.poll_once() == 1
    assert srv.active.version == 4
    assert srv.health()["status"] == "ok"
    want = _live_predictor(tr, store, model, schema).predict_batch(pb)
    np.testing.assert_allclose(want, srv.predict_batch(pb),
                               rtol=1e-6, atol=1e-7)


@pytest.mark.slow
def test_publisher_kill_during_swap_under_load(tmp_path, serving_golden):
    """The combined drill: a server serves under load while the PUBLISHER
    process is killed inside the announce window; the server never fails
    a request, stays on the last good version, and the restarted
    publisher's catch-up brings it to parity."""
    root, out = tmp_path / "root", tmp_path / "out.npz"
    serve_root = str(root / "serve")
    pb = _serve_batch()
    srv = ServingServer(serve_root, poll_s=0.02).start()
    errors, served = [], [0]
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            if srv.active is None:
                time.sleep(0.005)
                continue
            try:
                srv.predict_batch(pb)
                served[0] += 1
            except Exception as e:   # noqa: BLE001
                errors.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    [t.start() for t in threads]
    try:
        killed = _run_worker(
            root, out, check=False,
            env_extra={"PBTPU_FAULTPOINT": "serving.publish.pre_donefile",
                       "PBTPU_FAULTPOINT_AFTER": "1"})
        assert killed.returncode == 137
        time.sleep(0.2)
        _assert_announced_all_verify(serve_root)
        resumed = _run_worker(root, out)
        assert "catch-up republished" in resumed.stdout, resumed.stdout
        deadline = time.time() + 20
        while time.time() < deadline and (
                srv.active is None or srv.active.pass_id < 3):
            time.sleep(0.05)
    finally:
        stop.set()
        [t.join(timeout=30) for t in threads]
        srv.stop()
    assert not errors, errors[:3]
    assert served[0] > 0
    assert srv.active is not None and srv.active.pass_id == 3
    np.testing.assert_allclose(serving_golden["probs"],
                               srv.predict_batch(pb),
                               rtol=1e-6, atol=1e-7)


def test_serving_points_closed_registry():
    """The publish kill matrix above parametrizes over
    faultpoint.SERVING_POINTS — a new publish window cannot be
    registered without the matrix covering it (the same guard
    test_crash_safety/test_elastic carry for their point sets)."""
    assert set(faultpoint.SERVING_POINTS) <= set(faultpoint.POINTS)
    assert all(p.startswith("serving.publish.")
               for p in faultpoint.SERVING_POINTS)
