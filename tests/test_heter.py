"""Heterogeneous trainer: host-resident table + device dense stage."""

import numpy as np

from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.models import DeepFMModel
from paddlebox_tpu.train import HeterTrainer, HeterConfig

from test_train_e2e import synth_dataset, NUM_SLOTS


def test_heter_training_lifts_auc():
    ds, schema = synth_dataset(2048)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, learning_rate=0.15))
    model = DeepFMModel(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1,
                        hidden=(32, 16))
    tr = HeterTrainer(model, store, schema,
                      HeterConfig(global_batch_size=128, dense_lr=3e-3,
                                  auc_buckets=1 << 12))
    results = [tr.train_pass(ds) for _ in range(3)]
    assert results[0]["steps"] == 16
    assert results[-1]["auc"] > 0.62, results
    assert results[-1]["loss_mean"] < results[0]["loss_first"]
    # table trained host-side: counters and weights moved, no HBM table
    keys = ds.unique_keys()
    rows = store.get_rows(keys[:10])
    assert rows[:, 0].sum() > 0          # show counters
    assert np.abs(rows[:, 2]).sum() > 0  # w moved


def test_heter_matches_homogeneous_semantics():
    """Same data, same seeds: heter and standard trainers should reach a
    comparable loss (they share optimizer math; scheduling differs)."""
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig

    ds, schema = synth_dataset(1024, seed=7)
    mk = lambda: HostEmbeddingStore(
        EmbeddingConfig(dim=8, learning_rate=0.15))
    model_kw = dict(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1,
                    hidden=(32, 16))

    s1 = mk()
    t1 = HeterTrainer(DeepFMModel(**model_kw), s1, schema,
                      HeterConfig(global_batch_size=128, dense_lr=3e-3))
    r1 = [t1.train_pass(ds) for _ in range(2)][-1]

    s2 = mk()
    t2 = Trainer(DeepFMModel(**model_kw), s2, schema, make_mesh(8),
                 TrainerConfig(global_batch_size=128, dense_lr=3e-3))
    r2 = [t2.train_pass(ds) for _ in range(2)][-1]

    assert abs(r1["loss_mean"] - r2["loss_mean"]) < 0.08, (r1, r2)
    assert r1["auc"] > 0.6 and r2["auc"] > 0.6
