"""Multi-host crash resilience: store namespacing, heartbeat watchdog,
coordinated resume election, and mid-pass cursors (ISSUE 5).

Cross-process behavior (real kills, real launcher) lives in
tests/test_multihost_crash.py; this file proves the building blocks
in-process: the FileStore satellites, the named-rank watchdog errors, the
pure election, and the PassCheckpointer election/mid-pass API the
multi-host protocol rides."""

import json
import os
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu.distributed import FileStore, HostCollectives
from paddlebox_tpu.distributed.resilience import (HeartbeatMonitor,
                                                  PeerLostError,
                                                  PeerStalledError,
                                                  coordinated_resume,
                                                  elect_resume_cursor)
from paddlebox_tpu.monitor import context as mon_ctx


# ---------------------------------------------------------------------------
# FileStore satellites
# ---------------------------------------------------------------------------

def test_filestore_namespace_isolates_runs(tmp_path):
    """A previous launch's keys must not satisfy a new launch's waits or
    barriers — the run-id namespace is the correctness barrier."""
    old = FileStore(str(tmp_path), timeout_s=0.3, namespace="run_old")
    old.set("day", b"20260801")
    old.add("barrier.1", 0)
    old.add("barrier.1", 1)
    new = FileStore(str(tmp_path), timeout_s=0.3, namespace="run_new")
    assert new.get("day") is None
    with pytest.raises(TimeoutError):
        new.wait_count("barrier.1", 2, timeout_s=0.2)
    # same store dir, both runs live side by side
    assert old.get("day") == b"20260801"


def test_filestore_wait_count_names_missing_ranks(tmp_path):
    st = FileStore(str(tmp_path), timeout_s=0.3)
    st.add("b", 0)
    st.add("b", 2)
    with pytest.raises(TimeoutError, match=r"missing ranks \[1, 3\]"):
        st.wait_count("b", 4, timeout_s=0.2)
    assert st.missing_ranks("b", 4) == [1, 3]
    assert st.count("b", 4) == 2


def test_filestore_tmp_suffix_collision_safe(tmp_path):
    """Two writers sharing a pid (two hosts on one mount) must not share a
    tmp file: the suffix carries hostname + pid + a fresh uuid, and
    concurrent sets leave no .tmp. litter behind."""
    st = FileStore(str(tmp_path))
    errs = []

    def writer(i):
        try:
            for k in range(50):
                st.set("hot", f"{i}.{k}".encode())
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs
    assert st.get("hot") is not None
    assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]


def test_filestore_sweep_stale(tmp_path):
    dead = FileStore(str(tmp_path), namespace="run_dead")
    dead.set("k", b"1")
    live = FileStore(str(tmp_path), namespace="run_live")
    live.set("aged_arrival", b"2")
    past = time.time() - 7200
    os.utime(dead._path("k"), (past, past))
    # the live run's own key ages too (a barrier arrival waiting out a
    # straggler) — it must survive any threshold
    os.utime(live._path("aged_arrival"), (past, past))
    assert live.sweep_stale(3600) == 1
    assert dead.get("k") is None
    assert live.get("aged_arrival") == b"2"
    # an un-namespaced store cannot tell its keys from a dead run's
    with pytest.raises(ValueError, match="namespaced"):
        FileStore(str(tmp_path)).sweep_stale(3600)


def test_filestore_wait_check_callback_preempts_timeout(tmp_path):
    st = FileStore(str(tmp_path), timeout_s=30)

    def boom():
        raise PeerLostError("rank [1] lost", [1])

    t0 = time.monotonic()
    with pytest.raises(PeerLostError):
        st.wait("never", check=boom)
    with pytest.raises(PeerLostError):
        st.wait_count("neverb", 2, check=boom)
    assert time.monotonic() - t0 < 5.0   # no 30s timeout paid


# ---------------------------------------------------------------------------
# heartbeat watchdog
# ---------------------------------------------------------------------------

def _monitor(st, rank, world, **kw):
    kw.setdefault("interval_s", 0.05)
    kw.setdefault("watch", False)        # deterministic: scan via check()
    return HeartbeatMonitor(st, rank, world, run_id="r", **kw)


def test_watchdog_detects_lost_peer_with_named_rank(tmp_path):
    st = FileStore(str(tmp_path))
    h0 = _monitor(st, 0, 2, lost_after_s=0.4, stall_after_s=30)
    h1 = _monitor(st, 1, 2, lost_after_s=0.4, stall_after_s=30)
    try:
        time.sleep(0.15)
        h0.check()                       # both alive
        h1.close()                       # rank 1 "dies" (publisher stops)
        deadline = time.monotonic() + 5.0
        with pytest.raises(PeerLostError, match=r"\[1\]") as ei:
            while time.monotonic() < deadline:
                h0.check()
                time.sleep(0.1)
        assert ei.value.ranks == [1]
        # latched: the next check re-raises immediately
        with pytest.raises(PeerLostError):
            h0.check()
    finally:
        h0.close()
        h1.close()


def test_watchdog_detects_stalled_peer(tmp_path):
    """A peer whose process is alive (heartbeat beating) but whose
    pass/step progress froze must surface as peer_stalled — the hung-rank
    signature a plain liveness check cannot see."""
    st = FileStore(str(tmp_path))
    handle = mon_ctx.enter_pass(3)       # both monitors read this context
    mon_ctx.set_step(7)
    h0 = _monitor(st, 0, 2, lost_after_s=30, stall_after_s=0.4)
    h1 = _monitor(st, 1, 2, lost_after_s=30, stall_after_s=0.4)
    try:
        time.sleep(0.15)
        h0.check()
        # progress frozen from here on (h1 keeps beating via its thread)
        deadline = time.monotonic() + 5.0
        with pytest.raises(PeerStalledError, match=r"\[1\]"):
            while time.monotonic() < deadline:
                h0.check()
                time.sleep(0.1)
    finally:
        h0.close()
        h1.close()
        mon_ctx.exit_pass(handle)


def test_collectives_barrier_raises_named_rank_not_timeout(tmp_path):
    """The acceptance shape: a barrier against a dead peer fails with the
    watchdog's named-rank error, not the opaque store timeout."""
    st = FileStore(str(tmp_path), timeout_s=60)
    h0 = _monitor(st, 0, 2, lost_after_s=0.3, stall_after_s=30)
    col = HostCollectives(st, 0, 2, run_id="r", watchdog=h0)
    try:
        time.sleep(0.1)   # a beat or two… then rank 1 simply never exists
        t0 = time.monotonic()
        with pytest.raises(PeerLostError, match=r"ranks? \[1\]"):
            col.barrier("never_arrives")
        assert time.monotonic() - t0 < 10.0
    finally:
        h0.close()


# ---------------------------------------------------------------------------
# election
# ---------------------------------------------------------------------------

def test_elect_resume_cursor_pure_cases():
    # unanimous newest
    assert elect_resume_cursor([], [[[1, 0], [2, 0]],
                                    [[1, 0], [2, 0]]]) == (2, 0)
    # one rank's newest tore: the world rolls back together
    assert elect_resume_cursor([], [[[1, 0], [2, 0]], [[1, 0]]]) == (1, 0)
    # mid-pass cursors order between pass boundaries
    assert elect_resume_cursor([], [[[1, 0], [1, 2], [2, 0]],
                                    [[1, 0], [1, 2]]]) == (1, 2)
    # a rank with nothing intact forces a whole-world fresh start
    assert elect_resume_cursor([], [[[1, 0]], []]) is None
    assert elect_resume_cursor([], [[], []]) is None


def _tiny_job(tmp_path, tag, seed=7):
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    from tests.crash_worker import NUM_SLOTS, synth
    ds, schema = synth(n=128, seed=11)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4, learning_rate=0.05))
    tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                             hidden=(8,)),
                 store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=64, auc_buckets=1 << 8),
                 seed=seed)
    box = BoxPS(store)
    ck = PassCheckpointer(str(tmp_path / tag), keep_last_n=6, base_every=4)
    return ds, tr, store, box, ck


def test_coordinated_resume_rolls_world_back_to_common_cursor(tmp_path):
    """Two 'ranks' (threads, separate trainers/roots): rank 0 holds intact
    passes {1,2}, rank 1's pass-2 snapshot is torn. The election must land
    BOTH on pass 1, and rank 0's abandoned pass-2 snapshot must be
    discarded so it can never win a later newest-first walk."""
    jobs = [_tiny_job(tmp_path, f"rank{r}") for r in range(2)]
    for r, (ds, tr, store, box, ck) in enumerate(jobs):
        for _ in range(2):
            box.begin_pass()
            tr.train_pass(ds)
            box.end_pass(checkpointer=ck, trainer=tr, dataset=ds)
    # tear rank 1's newest snapshot (truncate its dense plane)
    ck1 = jobs[1][4]
    dense2 = os.path.join(ck1.snap_dir(2), "dense.npz")
    raw = open(dense2, "rb").read()
    with open(dense2, "wb") as f:
        f.write(raw[:-32])
    assert jobs[0][4].intact_cursors() == [(1, 0), (2, 0)]
    assert ck1.intact_cursors() == [(1, 0)]

    st = FileStore(str(tmp_path / "store"), timeout_s=30)
    fresh = [_tiny_job(tmp_path, f"rank{r}", seed=50 + r)
             for r in range(2)]
    results, errs = [None, None], []

    def resume_rank(r):
        try:
            ds, tr, store, box, ck = fresh[r]
            col = HostCollectives(st, r, 2, run_id="x")
            results[r] = coordinated_resume(ck, tr, col, box=box)
        except BaseException as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=resume_rank, args=(r,)) for r in range(2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs, errs
    for r in range(2):
        assert results[r]["pass_id"] == 1
        assert results[r]["elected"] == [1, 0]
        assert fresh[r][3].pass_id == 1
    # rank 0's pass-2 snapshot (abandoned timeline) is gone
    assert fresh[0][4].intact_cursors() == [(1, 0)]
    assert not os.path.exists(fresh[0][4].snap_dir(2))


def test_coordinated_resume_fresh_start_when_any_rank_empty(tmp_path):
    jobs = [_tiny_job(tmp_path, f"er{r}") for r in range(2)]
    ds, tr, store, box, ck = jobs[0]
    box.begin_pass(); tr.train_pass(ds)
    box.end_pass(checkpointer=ck, trainer=tr)
    st = FileStore(str(tmp_path / "store2"), timeout_s=30)
    results, errs = [0, 0], []

    def resume_rank(r):
        try:
            dsr, trr, _, boxr, ckr = jobs[r]
            col = HostCollectives(st, r, 2, run_id="y")
            results[r] = coordinated_resume(ckr, trr, col, box=boxr)
        except BaseException as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=resume_rank, args=(r,)) for r in range(2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs, errs
    assert results == [None, None]       # whole-world fresh start
    # rank 0's pass-1 snapshot belonged to a timeline the world just
    # abandoned — left intact, a future election could match it against a
    # freshly retrained pass-1 on rank 1 (silent divergence). It must be
    # discarded with the fresh start.
    assert jobs[0][4].intact_cursors() == []


def test_prune_keeps_fulls_and_mids_in_separate_pools(tmp_path):
    """Ranks mid-pass-snapshot on their own step cadence; mids must never
    evict pass-boundary snapshots (the cursors ranks hold in COMMON), or
    intra-pass skew > keep_last_n*every_steps would collapse the next
    election to a fresh start."""
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds, tr, store, box, _ = _tiny_job(tmp_path, "pp_unused")
    ck = PassCheckpointer(str(tmp_path / "pp"), keep_last_n=2,
                          base_every=8)
    tr.enable_midpass_snapshots(ck, 1, box)
    for _ in range(3):                   # 2 steps/pass -> 2 mids + 1 full
        box.begin_pass()
        tr.train_pass(ds)
        box.end_pass(checkpointer=ck, trainer=tr)
    cursors = ck.intact_cursors()
    assert [c for c in cursors if c[1] == 0] == [(2, 0), (3, 0)]
    assert [c for c in cursors if c[1] > 0] == [(2, 1), (2, 2)]


# ---------------------------------------------------------------------------
# mid-pass snapshots + cursor resume (in-process bit-parity)
# ---------------------------------------------------------------------------

def test_midpass_snapshot_skip_resume_bit_identical(tmp_path):
    """Kill-free core of the mid-pass tentpole: snapshot at step 2 of
    pass 2, restore it into a FRESH job, replay the pass order from the
    shuffle cursor with skip_steps=2, and land bit-identical dense +
    sparse + metric planes and the same global_step."""
    import jax
    ds, tr, store, box, ck = _tiny_job(tmp_path, "mid")
    box.init_metric("m", n_buckets=64)
    tr.enable_midpass_snapshots(ck, 2, box, metrics=box.metrics)
    base = ds.records
    for _ in range(2):
        tr.midpass_cursor_extra = {"shuffle_state": ds.shuffle_state()}
        ds.records = base
        ds.local_shuffle()
        box.begin_pass()
        tr.train_pass(ds, metrics=box.metrics)
        box.end_pass(checkpointer=ck, trainer=tr, dataset=ds)
    tr.flush_sparse()
    keys = np.sort(np.asarray(ds.unique_keys(), np.uint64))
    want_rows = store.get_rows(keys)
    want_params = jax.tree.map(np.asarray, tr.params)
    want_met = box.metrics.get_state("m")
    assert (1, 2) in ck.intact_cursors()

    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds2, tr2, store2, box2, _ = _tiny_job(tmp_path, "mid_unused", seed=99)
    box2.init_metric("m", n_buckets=64)
    ck2 = PassCheckpointer(str(tmp_path / "mid"), keep_last_n=6,
                           base_every=4)
    cursor = ck2.resume(tr2, box=box2, metrics=box2.metrics, at=(1, 2))
    assert cursor["pass_id"] == 1 and cursor["mid_steps"] == 2
    assert cursor["shuffle_state"] is not None
    ds2.set_shuffle_state(cursor["shuffle_state"])
    base2 = ds2.records
    ds2.records = base2
    ds2.local_shuffle()                  # replays pass-2's permutation
    box2.begin_pass()
    tr2.train_pass(ds2, metrics=box2.metrics,
                   skip_steps=cursor["mid_steps"])
    box2.end_pass(trainer=tr2, checkpointer=ck2, dataset=ds2)
    tr2.flush_sparse()
    np.testing.assert_array_equal(want_rows, store2.get_rows(keys))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        want_params, tr2.params)
    np.testing.assert_array_equal(np.asarray(want_met["pos"]),
                                  np.asarray(box2.metrics.get_state("m")["pos"]))
    assert tr2.global_step == tr.global_step


def test_midpass_snapshot_cadence_and_naming(tmp_path):
    ds, tr, store, box, ck = _tiny_job(tmp_path, "cad")
    tr.enable_midpass_snapshots(ck, 1, box)      # every step
    box.begin_pass()
    tr.train_pass(ds)
    box.end_pass(checkpointer=ck, trainer=tr)
    names = sorted(n for n in os.listdir(ck.root) if n.startswith("pass-"))
    # 128 examples / batch 64 = 2 steps: mids at 1 and 2, then the full
    assert names == ["pass-00000.mid00001", "pass-00000.mid00002",
                     "pass-00001"]
    # cursor ordering: full pass-1 outranks its own mid snapshots
    assert ck.intact_cursors() == [(0, 1), (0, 2), (1, 0)]


def test_midpass_kstep_needs_sync_boundary_cadence(tmp_path):
    """kstep mid-pass snapshots are allowed ONLY at the K-step sync
    boundary (ISSUE 6 satellite): a cadence that is not a multiple of
    param_sync_step refuses with a clear error; a multiple is accepted."""
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig
    from tests.crash_worker import NUM_SLOTS, synth
    ds, schema = synth(n=64)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                             hidden=(8,)),
                 store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=64,
                               dense_sync_mode="kstep",
                               param_sync_step=2), seed=1)
    with pytest.raises(NotImplementedError, match="sync boundary"):
        tr.enable_midpass_snapshots(object(), 3, BoxPS(store))
    tr.enable_midpass_snapshots(object(), 4, BoxPS(store))   # multiple: ok
    assert tr._midpass is not None


def _tiny_job_mode(tmp_path, tag, mode, seed=7, n=256, **cfg_kw):
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    from tests.crash_worker import NUM_SLOTS, synth
    ds, schema = synth(n=n, seed=11)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4, learning_rate=0.05))
    tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                             hidden=(8,)),
                 store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=64, auc_buckets=1 << 8,
                               dense_sync_mode=mode, **cfg_kw),
                 seed=seed)
    box = BoxPS(store)
    ck = PassCheckpointer(str(tmp_path / tag), keep_last_n=6, base_every=4)
    return ds, tr, store, box, ck


def test_midpass_kstep_skip_resume_bit_identical(tmp_path):
    """ISSUE 6 satellite: mid-pass snapshots in the K-step dense-sync
    mode — the snapshot lands on the sync boundary (every_steps a
    multiple of K) and stores the STACKED per-shard planes, so a resumed
    run replays the remaining steps (syncs included) bit-identically."""
    import jax
    ds, tr, store, box, ck = _tiny_job_mode(tmp_path, "km", "kstep",
                                            param_sync_step=2)
    tr.enable_midpass_snapshots(ck, 2, box)
    for _ in range(2):                        # 256 ex / 64 = 4 steps
        tr.midpass_cursor_extra = {"shuffle_state": ds.shuffle_state()}
        box.begin_pass()
        tr.train_pass(ds)
        box.end_pass(checkpointer=ck, trainer=tr, dataset=ds)
    tr.flush_sparse()
    keys = np.sort(np.asarray(ds.unique_keys(), np.uint64))
    want_rows = store.get_rows(keys)
    want_params = jax.tree.map(np.asarray, tr.eval_params())
    assert (1, 2) in ck.intact_cursors()

    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds2, tr2, store2, box2, _ = _tiny_job_mode(tmp_path, "km_u", "kstep",
                                               seed=99,
                                               param_sync_step=2)
    ck2 = PassCheckpointer(str(tmp_path / "km"), keep_last_n=6,
                           base_every=4)
    cursor = ck2.resume(tr2, box=box2, at=(1, 2))
    assert cursor["pass_id"] == 1 and cursor["mid_steps"] == 2
    box2.begin_pass()
    tr2.train_pass(ds2, skip_steps=2)
    box2.end_pass(trainer=tr2, checkpointer=ck2, dataset=ds2)
    tr2.flush_sparse()
    np.testing.assert_array_equal(want_rows, store2.get_rows(keys))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        want_params, jax.tree.map(np.asarray, tr2.eval_params()))
    assert tr2.global_step == tr.global_step


def test_midpass_async_quiesces_and_resumes(tmp_path):
    """Async dense sync: the mid-pass snapshot quiesces the host dense
    table (flush) and stores its exact state dict; a resumed run
    restores it and continues (the continued grad-merge timing stays
    async-nondeterministic by design, so the assertion is exact state at
    the cursor + a working continuation, not bitwise end parity)."""
    import numpy as _np
    ds, tr, store, box, ck = _tiny_job_mode(tmp_path, "am", "async")
    tr.enable_midpass_snapshots(ck, 2, box)
    tr.midpass_cursor_extra = {"shuffle_state": ds.shuffle_state()}
    box.begin_pass()
    tr.train_pass(ds)
    box.end_pass(checkpointer=ck, trainer=tr, dataset=ds)
    assert (0, 2) in ck.intact_cursors()

    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds2, tr2, store2, box2, _ = _tiny_job_mode(tmp_path, "am_u", "async",
                                               seed=99)
    ck2 = PassCheckpointer(str(tmp_path / "am"), keep_last_n=6,
                           base_every=4)
    cursor = ck2.resume(tr2, box=box2, at=(0, 2))
    assert cursor["mid_steps"] == 2
    # the table state (params + Adam moments + applied-step count) is
    # exactly what the snapshot quiesced
    st = tr2.dense_table.state_dict()
    assert int(_np.asarray(st["steps"]).reshape(-1)[0]) > 0
    box2.begin_pass()
    out = tr2.train_pass(ds2, skip_steps=2)
    box2.end_pass(trainer=tr2)
    assert out["steps"] == 2                  # the remaining tail only
    tr.dense_table.stop()
    tr2.dense_table.stop()


def test_drain_snapshot_commits_abort_cursor(tmp_path):
    """The elastic drain point: a peer failure aborts the step loop at a
    step boundary; drain_and_snapshot commits a mid-pass snapshot at the
    abort step (resumable like any mid cursor) and abort_pass closes the
    box without the world barrier."""
    from paddlebox_tpu.distributed.resilience import PeerLostError
    ds, tr, store, box, ck = _tiny_job(tmp_path, "drain")
    calls = [0]

    def check():
        calls[0] += 1
        if calls[0] == 2:                     # before step 2 dispatches
            raise PeerLostError("rank [1] lost", [1])

    tr.peer_check = check
    tr.midpass_cursor_extra = {"shuffle_state": ds.shuffle_state()}
    box.begin_pass()
    with pytest.raises(PeerLostError):
        tr.train_pass(ds)
    assert box.in_pass and tr.last_pass_steps == 1
    snap = tr.drain_and_snapshot(ck, box)
    assert snap is not None
    assert ck.intact_cursors() == [(0, 1)]
    box.abort_pass(reason="peer lost")
    assert not box.in_pass
    # a fresh job resumes exactly at the abort cursor
    ds2, tr2, store2, box2, _ = _tiny_job(tmp_path, "drain_u", seed=99)
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ck2 = PassCheckpointer(str(tmp_path / "drain"), keep_last_n=6,
                           base_every=4)
    cursor = ck2.resume(tr2, box=box2, at=(0, 1))
    assert cursor["mid_steps"] == 1
    assert cursor["shuffle_state"] is not None
    assert tr2.global_step == tr.global_step


# ---------------------------------------------------------------------------
# remote snapshot roots (in-process, mock CommandFS)
# ---------------------------------------------------------------------------

@pytest.fixture
def hdfs_mock(tmp_path):
    from paddlebox_tpu.utils import fs as fs_lib
    from tests.mockfs import register_mockfs
    root = tmp_path / "hdfs_root"
    fs = register_mockfs(str(root), scheme="hdfsmock")
    yield fs, root
    fs_lib._REGISTRY.pop("hdfsmock", None)


def test_remote_root_upload_donefile_and_replacement_host_resume(
        tmp_path, hdfs_mock):
    """PassCheckpointer over a remote root: local atomic commit → upload →
    donefile; a REPLACEMENT host (empty staging dir) resumes purely from
    the donefile, bit-identical."""
    import jax
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    fs, mock_root = hdfs_mock
    ds, tr, store, box, _ = _tiny_job(tmp_path, "unused_local")
    ck = PassCheckpointer("hdfsmock://snaps", keep_last_n=4, base_every=2,
                          staging_dir=str(tmp_path / "stage_a"))
    for _ in range(2):
        box.begin_pass()
        tr.train_pass(ds)
        box.end_pass(checkpointer=ck, trainer=tr, dataset=ds)
    tr.flush_sparse()
    keys = np.sort(np.asarray(ds.unique_keys(), np.uint64))
    want_rows = store.get_rows(keys)
    want_params = jax.tree.map(np.asarray, tr.params)
    done = mock_root / "snaps" / "snapshots.donefile"
    assert done.exists()
    entries = [json.loads(ln) for ln in done.read_text().splitlines()]
    assert [(e["pass"], e["mid"]) for e in entries] == [(1, 0), (2, 0)]
    assert (mock_root / "snaps" / "pass-00002" / "MANIFEST.json").exists()

    ds2, tr2, store2, box2, _ = _tiny_job(tmp_path, "unused2", seed=42)
    ck2 = PassCheckpointer("hdfsmock://snaps", keep_last_n=4, base_every=2,
                           staging_dir=str(tmp_path / "stage_b"))
    # syncs up to keep_last_n donefile entries, not just the newest — a
    # replacement host must join the election with every cursor the
    # donefile can deliver, or a surviving rank one pass behind would
    # collapse the intersection to a fresh start
    assert ck2.intact_cursors() == [(1, 0), (2, 0)]
    cursor = tr2.resume(ck2, box=box2)
    assert cursor["pass_id"] == 2
    np.testing.assert_array_equal(want_rows, store2.get_rows(keys))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        want_params, tr2.params)


def test_remote_retention_compacts_donefile_and_prunes_dirs(
        tmp_path, hdfs_mock):
    """ISSUE 6 satellite: the mirror no longer grows unboundedly — the
    donefile is rewritten to the retained entries (per pool) and remote
    snapshot/chain dirs no kept entry references are removed; a
    replacement host still resumes from the compacted donefile."""
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    fs, mock_root = hdfs_mock
    ds, tr, store, box, _ = _tiny_job(tmp_path, "unused_rr")
    ck = PassCheckpointer("hdfsmock://rr", keep_last_n=2, base_every=2,
                          staging_dir=str(tmp_path / "stage_rr"))
    for _ in range(5):
        box.begin_pass()
        tr.train_pass(ds)
        box.end_pass(checkpointer=ck, trainer=tr, dataset=ds)
    done = mock_root / "rr" / "snapshots.donefile"
    entries = [json.loads(ln) for ln in done.read_text().splitlines()]
    assert [(e["pass"], e["mid"]) for e in entries] == [(4, 0), (5, 0)]
    names = sorted(os.listdir(mock_root / "rr"))
    kept_snaps = {e["snapshot"] for e in entries}
    kept_chains = {e["chain"] for e in entries} | {ck._chain_dir}
    for n in names:
        if n.startswith("pass-"):
            assert n in kept_snaps, f"pruned snapshot {n} still mirrored"
        if n.startswith("chain-"):
            assert n in kept_chains, f"unreferenced chain {n} survived"
    # replacement host resumes from the compacted donefile
    tr.flush_sparse()
    keys = np.sort(np.asarray(ds.unique_keys(), np.uint64))
    want_rows = store.get_rows(keys)
    ds2, tr2, store2, box2, _ = _tiny_job(tmp_path, "unused_rr2",
                                          seed=42)
    ck2 = PassCheckpointer("hdfsmock://rr", keep_last_n=2, base_every=2,
                           staging_dir=str(tmp_path / "stage_rr2"))
    cursor = tr2.resume(ck2, box=box2)
    assert cursor["pass_id"] == 5
    np.testing.assert_array_equal(want_rows, store2.get_rows(keys))


def test_donefile_compaction_drops_masked_lines(tmp_path, hdfs_mock):
    """An elected rollback appends a ``reset_after`` line; the next
    save's compaction materializes the mask away — the rewritten
    donefile carries only live entries, no masks, no shadowed lines."""
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    fs, mock_root = hdfs_mock
    ds, tr, store, box, _ = _tiny_job(tmp_path, "unused_mask")
    ck = PassCheckpointer("hdfsmock://mm", keep_last_n=3, base_every=2,
                          staging_dir=str(tmp_path / "stage_mm"))
    for _ in range(3):
        box.begin_pass()
        tr.train_pass(ds)
        box.end_pass(checkpointer=ck, trainer=tr, dataset=ds)
    # elected rollback to pass 1 masks passes 2-3 with a reset line
    cursor = ck.resume(tr, box=box, at=(1, 0))
    assert cursor["pass_id"] == 1
    done = mock_root / "mm" / "snapshots.donefile"
    raw = [json.loads(ln) for ln in done.read_text().splitlines()]
    assert any("reset_after" in e for e in raw)
    # retrain pass 2: its save compacts masked + shadowed lines away
    box.begin_pass()
    tr.train_pass(ds)
    box.end_pass(checkpointer=ck, trainer=tr, dataset=ds)
    raw = [json.loads(ln) for ln in done.read_text().splitlines()]
    assert not any("reset_after" in e for e in raw), raw
    assert [(e["pass"], e["mid"]) for e in raw] == [(1, 0), (2, 0)]


def test_donefile_append_repairs_interrupted_compaction(tmp_path,
                                                        hdfs_mock):
    """A kill between the compaction's rm(donefile) and put(donefile)
    leaves only the ``.compact`` staging copy. The NEXT save must
    restore the main file from it before appending — an append into a
    recreated empty donefile would shadow the whole history with one
    line, and the following prune would reclaim every 'unreferenced'
    mirror dir."""
    import shutil as _sh
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    fs, mock_root = hdfs_mock
    ds, tr, store, box, _ = _tiny_job(tmp_path, "unused_rep")
    ck = PassCheckpointer("hdfsmock://rep", keep_last_n=4, base_every=2,
                          staging_dir=str(tmp_path / "stage_rep"))
    for _ in range(2):
        box.begin_pass()
        tr.train_pass(ds)
        box.end_pass(checkpointer=ck, trainer=tr, dataset=ds)
    done = mock_root / "rep" / "snapshots.donefile"
    before = done.read_text().splitlines()
    assert len(before) == 2
    # the crash window: compacted content staged, main file removed
    _sh.copy(done, str(done) + ".compact")
    done.unlink()
    box.begin_pass()
    tr.train_pass(ds)
    box.end_pass(checkpointer=ck, trainer=tr, dataset=ds)
    raw = [json.loads(ln) for ln in done.read_text().splitlines()]
    assert [(e["pass"], e["mid"]) for e in raw] == [(1, 0), (2, 0),
                                                    (3, 0)], raw
    assert not (mock_root / "rep" / "snapshots.donefile.compact").exists()
    # every surviving entry's mirror dirs are still referenced/alive
    names = set(os.listdir(mock_root / "rep"))
    for e in raw:
        assert e["snapshot"] in names
        assert e["chain"] in names


def test_remote_resume_falls_back_past_torn_remote_snapshot(
        tmp_path, hdfs_mock):
    """A torn REMOTE newest snapshot (upload raced the kill but the
    donefile line landed — or bit rot on the remote store) is diagnosed
    and the restore falls back to the previous donefile entry."""
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    fs, mock_root = hdfs_mock
    ds, tr, store, box, _ = _tiny_job(tmp_path, "unused_t")
    ck = PassCheckpointer("hdfsmock://t", keep_last_n=4, base_every=2,
                          staging_dir=str(tmp_path / "stage_t"))
    for _ in range(2):
        box.begin_pass()
        tr.train_pass(ds)
        box.end_pass(checkpointer=ck, trainer=tr, dataset=ds)
    # corrupt the remote pass-2 dense plane (size intact CRC broken)
    f = mock_root / "t" / "pass-00002" / "dense.npz"
    raw = bytearray(f.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    f.write_bytes(bytes(raw))

    ds2, tr2, store2, box2, _ = _tiny_job(tmp_path, "unused_t2", seed=42)
    ck2 = PassCheckpointer("hdfsmock://t", keep_last_n=4, base_every=2,
                           staging_dir=str(tmp_path / "stage_t2"))
    with pytest.warns(UserWarning, match="falling back"):
        cursor = tr2.resume(ck2, box=box2)
    assert cursor is not None and cursor["pass_id"] == 1
