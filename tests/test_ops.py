"""CTR ops: seqpool_cvm, cvm, rank_attention, batch_fc, cross_norm, concat."""

import numpy as np
import pytest

import jax.numpy as jnp

from paddlebox_tpu.ops import (batch_fc, build_rank_offset, cross_norm_hadamard,
                               cvm, cvm_inverse, data_norm, fused_concat,
                               fused_seqpool_cvm, init_summary, rank_attention)
from paddlebox_tpu.ops.cross_norm import cross_norm_raw, summary_update


def test_seqpool_cvm_manual():
    # 2 examples, 2 slots (T = 1 + 2), pull width 5 (show, clk, w, 2x embedx)
    seg = np.array([0, 1, 1], dtype=np.int32)
    pulled = np.zeros((2, 3, 5), np.float32)
    pulled[0, 0] = [1, 0, 0.5, 1.0, 2.0]       # ex0 slot0
    pulled[0, 1] = [2, 1, 0.25, 3.0, 4.0]      # ex0 slot1 tok0
    pulled[0, 2] = [1, 1, 0.25, 1.0, 1.0]      # ex0 slot1 tok1
    mask = np.array([[True, True, True], [False, False, False]])
    out = np.asarray(fused_seqpool_cvm(jnp.asarray(pulled), jnp.asarray(mask),
                                       seg, num_slots=2, flatten=False))
    assert out.shape == (2, 2, 5)
    # slot0 ex0: show=1, clk=0 -> [log2, log1-log2, .5, 1, 2]
    np.testing.assert_allclose(
        out[0, 0], [np.log(2), np.log(1) - np.log(2), 0.5, 1.0, 2.0],
        rtol=1e-6)
    # slot1 ex0 pooled: show=3, clk=2, w=.5, x=[4,5]
    np.testing.assert_allclose(
        out[0, 1], [np.log(4), np.log(3) - np.log(4), 0.5, 4.0, 5.0],
        rtol=1e-6)
    # ex1 fully masked -> log(1)=0 everywhere
    np.testing.assert_allclose(out[1], 0.0, atol=1e-7)


def test_seqpool_cvm_update_phase_drops_cvm():
    seg = np.array([0], dtype=np.int32)
    pulled = np.ones((1, 1, 5), np.float32)
    mask = np.ones((1, 1), bool)
    out = fused_seqpool_cvm(jnp.asarray(pulled), jnp.asarray(mask), seg, 1,
                            use_cvm=False, flatten=False)
    assert out.shape == (1, 1, 3)  # dropped show/clk


def test_seqpool_cvm_need_filter():
    # (show-clk)*0.2 + clk*1.0 < 0.96 filters out low-signal ids
    seg = np.array([0], dtype=np.int32)
    pulled = np.zeros((1, 1, 5), np.float32)
    pulled[0, 0] = [1, 0, 9.0, 9.0, 9.0]   # score 0.2 < 0.96 -> filtered
    mask = np.ones((1, 1), bool)
    out = np.asarray(fused_seqpool_cvm(jnp.asarray(pulled), jnp.asarray(mask),
                                       seg, 1, need_filter=True, flatten=False))
    np.testing.assert_allclose(out[0, 0, 2:], 0.0)


def test_cvm_roundtrip():
    x = np.abs(np.random.default_rng(0).normal(size=(4, 6))).astype(np.float32)
    y = cvm(jnp.asarray(x))
    back = np.asarray(cvm_inverse(y))
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)
    assert cvm(jnp.asarray(x), use_cvm=False).shape == (4, 4)


def test_rank_attention_bruteforce():
    rng = np.random.default_rng(1)
    B, in_dim, out_dim, K = 6, 3, 4, 3
    x = rng.normal(size=(B, in_dim)).astype(np.float32)
    ranks = np.array([1, 2, 3, 1, 2, 0])      # ex5 invalid
    groups = np.array([0, 0, 0, 1, 1, 2])
    ro = build_rank_offset(ranks, groups, K)
    param = rng.normal(size=(K * K * in_dim, out_dim)).astype(np.float32)
    got = np.asarray(rank_attention(jnp.asarray(x), jnp.asarray(ro),
                                    jnp.asarray(param), K))
    # brute force (mirrors expand_input/expand_param kernels)
    P = param.reshape(K * K, in_dim, out_dim)
    want = np.zeros((B, out_dim), np.float32)
    for i in range(B):
        if ranks[i] <= 0:
            continue
        for j in range(B):
            if groups[j] == groups[i] and 1 <= ranks[j] <= K:
                blk = (ranks[i] - 1) * K + (ranks[j] - 1)
                want[i] += x[j] @ P[blk]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_rank_attention_invalid_rank_zero_output():
    ro = np.zeros((2, 7), dtype=np.int32)  # all invalid
    x = jnp.ones((2, 3))
    param = jnp.ones((9 * 3, 4))
    out = np.asarray(rank_attention(x, jnp.asarray(ro), param, 3))
    np.testing.assert_allclose(out, 0.0)


def test_batch_fc():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(2, 5, 3)).astype(np.float32)
    w = rng.normal(size=(2, 3, 4)).astype(np.float32)
    b = rng.normal(size=(2, 4)).astype(np.float32)
    got = np.asarray(batch_fc(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
                              activation="relu"))
    want = np.maximum(np.einsum("gni,gio->gno", x, w) + b[:, None], 0.0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_data_norm_normalizes():
    rng = np.random.default_rng(3)
    x = (rng.normal(size=(1000, 4)) * 5 + 3).astype(np.float32)
    s = init_summary(4)
    s = summary_update(s, jnp.asarray(x), decay=1.0)
    y = np.asarray(data_norm(jnp.asarray(x), s))
    # mean ~0; scale = sqrt(count/sq_sum) — the reference's normalization is
    # by RMS, not std, so just check mean-centering and finite scale
    np.testing.assert_allclose(y.mean(axis=0), 0.0, atol=1e-2)


def test_cross_norm_hadamard_shapes_and_values():
    rng = np.random.default_rng(4)
    n, d, B = 2, 3, 8
    x = rng.normal(size=(B, 2 * d * n)).astype(np.float32)
    cols = n * (3 * d + 1)
    s = init_summary(cols)
    raw = np.asarray(cross_norm_raw(jnp.asarray(x), n, d))
    assert raw.shape == (B, cols)
    # block structure: [a, b, a*b, dot]
    a = x[:, 0:d]
    b = x[:, d:2 * d]
    np.testing.assert_allclose(raw[:, 0:d], a, rtol=1e-6)
    np.testing.assert_allclose(raw[:, 2 * d:3 * d], a * b, rtol=1e-5)
    np.testing.assert_allclose(raw[:, 3 * d], np.sum(a * b, -1), rtol=1e-5)
    out = np.asarray(cross_norm_hadamard(jnp.asarray(x), s, n, d))
    assert out.shape == (B, cols)


def test_fused_concat():
    a = jnp.ones((2, 4))
    b = jnp.zeros((2, 4))
    out = fused_concat([a, b], offset=1, length=2)
    assert out.shape == (2, 4)


def test_seqpool_cvm_with_pcoc_manual():
    """Hand-computed PCOC transform (fused_seqpool_cvm_with_pcoc_op.cu):
    layout [show, clk, show2, clk2, pclk, embedx...], P=1 pclk."""
    from paddlebox_tpu.ops import fused_seqpool_cvm_with_pcoc
    B, S, L, E = 1, 1, 2, 9      # 7 leading + 2 embedx
    seg = np.zeros(S * L, np.int32)
    tok = np.array([
        [2.0, 1.0, 4.0, 2.0, 3.0, 0.0, 0.0, 0.5, 0.25],
        [1.0, 0.0, 2.0, 1.0, 1.0, 0.0, 0.0, 0.5, 0.75],
    ], np.float32)[None]         # (1, 2, 9)
    mask = np.ones((B, S * L), bool)
    # cvm_offset=5 (show,clk,show2,clk2 + 1 pclk); max_cvm_offset=7
    out = fused_seqpool_cvm_with_pcoc(
        jnp.asarray(tok), jnp.asarray(mask), seg, S,
        cvm_offset=5, max_cvm_offset=7, flatten=False)
    pooled = tok[0].sum(0)       # [3, 1, 6, 3, 4, 0, 0, 1.0, 1.0]
    lg = lambda v: np.log(v + 1.0)
    want = [lg(3), lg(1) - lg(3),
            lg(4) - lg(6),       # pclk vs show2
            lg(4) - lg(3),       # pclk vs clk2
            1.0, 1.0]            # embedx passthrough
    np.testing.assert_allclose(np.asarray(out)[0, 0], want, rtol=1e-6)
    # update phase: embedx only
    out_u = fused_seqpool_cvm_with_pcoc(
        jnp.asarray(tok), jnp.asarray(mask), seg, S, use_cvm=False,
        cvm_offset=5, max_cvm_offset=7, flatten=False)
    np.testing.assert_allclose(np.asarray(out_u)[0, 0], [1.0, 1.0],
                               rtol=1e-6)
