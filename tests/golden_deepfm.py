"""Pure-NumPy golden reference for the DeepFM training step.

Independent ground truth for the whole train step — forward, backward,
sparse adagrad push with CVM counters, dense adam — written against the
framework's DOCUMENTED semantics (embedding/config.py row layout,
embedding/optim.py update rules, ops/seqpool_cvm.py CVM transform,
models/deepfm.py architecture) with NO jax and NO framework imports, so a
systematic numeric error anywhere in the jitted path (a constant factor
on sparse grads, a CVM column off-by-one, a mis-wired optimizer slot)
shows up as trajectory divergence instead of passing a self-referential
test. This is the OpTest pattern of the reference
(python/paddle/fluid/tests/unittests/op_test.py) applied to the full
step.

Only the benchmark configuration is modeled: embed_w_num=1, no
expand/gating thresholds, uniform max_len slot layout (sum-pool over L
tokens per slot; L=1 is the single-hot identity), adagrad sparse
optimizer, adam dense optimizer, f32 or int16/int8 device storage.
"""

from __future__ import annotations

import numpy as np


def splitmix_init_rows(keys, row_width, embedx_lo, embedx_hi,
                       initial_range, seed=0):
    """Deterministic per-key row init (store._init_rows)."""
    n = len(keys)
    rows = np.zeros((n, row_width), dtype=np.float32)
    d = embedx_hi - embedx_lo
    if d:
        k = keys.astype(np.uint64)[:, None]
        j = np.arange(d, dtype=np.uint64)[None, :]
        with np.errstate(over="ignore"):
            z = (k * np.uint64(0x9E3779B97F4A7C15)
                 + (j + np.uint64(seed)) * np.uint64(0xBF58476D1CE4E5B9))
            z ^= z >> np.uint64(30)
            z *= np.uint64(0x94D049BB133111EB)
            z ^= z >> np.uint64(27)
        u = (z >> np.uint64(11)).astype(np.float64) / float(1 << 53)
        rows[:, embedx_lo:embedx_hi] = ((2.0 * u - 1.0)
                                        * initial_range).astype(np.float32)
    return rows


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _bce_mean(logits, y):
    # optax.sigmoid_binary_cross_entropy: max(l,0) - l*y + log1p(exp(-|l|))
    return float(np.mean(np.maximum(logits, 0.0) - logits * y
                         + np.log1p(np.exp(-np.abs(logits)))))


class GoldenDeepFM:
    """Numpy twin of Trainer(DeepFMModel, adagrad store, adam dense).

    init_params: {"mlp": [{"w","b"}...], "bias", "wide_dense"} as numpy
    arrays (extracted once from the framework's init — parameter
    INITIALIZATION is jax PRNG territory; everything after step 0 is
    recomputed independently here).
    table: (N, row_width) f32 — row 0 is the null row.
    """

    def __init__(self, table, init_params, num_slots, emb_dim, dense_dim,
                 hidden, lr_sparse=0.05, initial_g2sum=3.0,
                 dense_lr=1e-3, storage="f32", dense_opt="adam",
                 max_len=1):
        self.S, self.E, self.D = num_slots, emb_dim, dense_dim
        self.L = max_len                        # tokens per slot (seqpool)
        self.row_width = table.shape[1]
        self.pull_width = 3 + emb_dim           # show, clk, w, embedx
        self.gw = 1 + emb_dim                   # d_w, d_embedx
        self.lr, self.ig2 = lr_sparse, initial_g2sum
        self.dense_lr = dense_lr
        self.storage = storage
        self.qmax = {"f32": None, "int16": 32767.0, "int8": 127.0}[storage]
        self.table = table.astype(np.float32).copy()
        if self.qmax is not None:
            self._requant(np.ones(len(table), bool))
        self.params = {
            "mlp": [{"w": p["w"].astype(np.float32).copy(),
                     "b": p["b"].astype(np.float32).copy()}
                    for p in init_params["mlp"]],
            "bias": init_params["bias"].astype(np.float32).copy(),
        }
        if dense_dim:
            self.params["wide_dense"] = \
                init_params["wide_dense"].astype(np.float32).copy()
        self.m = {k: _tree_zeros(v) for k, v in self.params.items()}
        self.v = {k: _tree_zeros(v) for k, v in self.params.items()}
        self.t = 0
        # "adam" = optax.adam (allreduce/kstep modes); "async_merge" =
        # the host dense table's hand-rolled Adam-like rule (reference
        # ThreadUpdate, boxps_worker.cc:173-225: betas 0.99/0.9999, NO
        # bias correction — parallel/dense_sync.AsyncDenseTable._apply)
        self.dense_opt = dense_opt

    # -- quantized storage round trip (quant.py split/assemble) ---------
    def _requant(self, rows_mask):
        """Emulate int8/16 device storage: embedx lives quantized with a
        per-row scale; each push dequantizes, updates, requantizes."""
        lo, hi = 3, 3 + self.E
        x = self.table[rows_mask, lo:hi]
        scale = np.maximum(np.abs(x).max(axis=1) / self.qmax, 1e-12
                           ).astype(np.float32)
        q = _round_half_even(x / scale[:, None])
        self.table[rows_mask, lo:hi] = (q * scale[:, None]
                                        ).astype(np.float32)

    # -- one train step --------------------------------------------------
    def step(self, idx, mask, dense, labels):
        """idx (B, S*L) int32 working-set rows; mask (B, S*L) bool; dense
        (B, D) f32; labels (B,) f32. Returns the step loss; mutates
        table/params in place exactly once, like Trainer._step_fn."""
        B, S, E, L = idx.shape[0], self.S, self.E, self.L
        maskf = mask.astype(np.float32)
        pulled = self.table[idx.reshape(-1), :self.pull_width].reshape(
            B, S * L, self.pull_width)
        tok = pulled * maskf[..., None]         # masked tokens contribute 0
        # sum-pool L tokens per slot (ops/seqpool_cvm._pool reshape-sum;
        # identity at L=1), then the CVM join transform on the POOLED
        # show/clk
        x = tok.reshape(B, S, L, self.pull_width).sum(axis=2)
        show, clk = x[..., 0], x[..., 1]
        log_show = np.log(show + 1.0)
        log_ctr = np.log(clk + 1.0) - log_show
        w = x[..., 2]
        v = x[..., 3:]
        feats = np.concatenate(
            [log_show[..., None], log_ctr[..., None], w[..., None], v],
            axis=-1).astype(np.float32)
        wide = w.sum(axis=1)
        if self.D:
            wide = wide + dense @ self.params["wide_dense"]
        sum_v = v.sum(axis=1)
        fm = 0.5 * ((sum_v * sum_v).sum(axis=1)
                    - (v * v).sum(axis=(1, 2)))
        xd = feats.reshape(B, -1)
        if self.D:
            xd = np.concatenate([xd, dense], axis=1)
        # MLP forward, keeping pre-relu activations for backward
        hs, zs = [xd], []
        h = xd
        layers = self.params["mlp"]
        for i, p in enumerate(layers):
            z = h @ p["w"] + p["b"]
            zs.append(z)
            h = np.maximum(z, 0.0) if i < len(layers) - 1 else z
            hs.append(h)
        deep = h[:, 0]
        logits = (wide + fm + deep + self.params["bias"][0]
                  ).astype(np.float32)
        loss = _bce_mean(logits, labels)

        # ---- backward ----
        g = ((_sigmoid(logits) - labels) / B).astype(np.float32)
        grads = {"bias": np.array([g.sum()], np.float32), "mlp": []}
        if self.D:
            grads["wide_dense"] = dense.T @ g
        dh = np.zeros_like(hs[-1])
        dh[:, 0] = g
        mlp_grads = [None] * len(layers)
        for i in reversed(range(len(layers))):
            dz = dh if i == len(layers) - 1 else dh * (zs[i] > 0)
            mlp_grads[i] = {"w": hs[i].T @ dz, "b": dz.sum(axis=0)}
            dh = dz @ layers[i]["w"].T
        grads["mlp"] = mlp_grads
        dxd = dh                                 # grad wrt MLP input
        d_feats = dxd[:, :S * (3 + E)].reshape(B, S, 3 + E).copy()
        d_feats[..., 2] += g[:, None]            # wide path
        d_v = d_feats[..., 3:] + g[:, None, None] * (sum_v[:, None, :] - v)
        d_w = d_feats[..., 2]
        # show/clk grads are DROPPED by the push (CVM counters train
        # nothing) — only (w, embedx) columns leave the model. Sum-pool
        # backward: every token of a slot receives the slot's grad
        # (masked tokens zero).
        sgrad = np.concatenate([d_w[..., None], d_v], axis=-1)  # (B,S,gw)
        sgrad = np.repeat(sgrad[:, :, None, :], L, axis=2).reshape(
            B, S * L, self.gw)
        sgrad = (sgrad * maskf[..., None]).reshape(B * S * L, self.gw)

        # ---- sparse push: scatter-merge + in-table adagrad ----
        show_inc = maskf.reshape(-1)
        clk_inc = (maskf * labels[:, None]).reshape(-1)
        payload = np.concatenate(
            [sgrad, show_inc[:, None], clk_inc[:, None],
             np.ones((B * S * L, 1), np.float32)], axis=1)
        acc = np.zeros((len(self.table), self.gw + 3), np.float32)
        np.add.at(acc, idx.reshape(-1), payload)
        gw = self.gw
        touched = acc[:, gw + 2] > 0
        tbl = self.table
        if self.qmax is not None:
            pass          # table already stores dequantized values
        new_show = tbl[:, 0] + acc[:, gw]
        new_clk = tbl[:, 1] + acc[:, gw + 1]
        g_w, g_x = acc[:, 0], acc[:, 1:gw]
        w_g2, x_g2 = tbl[:, 3 + E], tbl[:, 4 + E]
        new_wg2 = w_g2 + g_w * g_w
        new_xg2 = x_g2 + (g_x * g_x).mean(axis=1)
        scale_w = self.lr * np.sqrt(self.ig2 / (self.ig2 + new_wg2))
        scale_x = self.lr * np.sqrt(self.ig2 / (self.ig2 + new_xg2))
        new = np.concatenate(
            [new_show[:, None], new_clk[:, None],
             (tbl[:, 2] - scale_w * g_w)[:, None],
             tbl[:, 3:3 + E] - scale_x[:, None] * g_x,
             new_wg2[:, None], new_xg2[:, None]], axis=1)
        self.table = np.where(touched[:, None], new, tbl).astype(np.float32)
        if self.qmax is not None:
            self._requant(touched)

        # ---- dense update ----
        self.t += 1
        if self.dense_opt == "async_merge":
            b1, b2, eps = 0.99, 0.9999, 1e-8   # no bias correction

            def upd(path, p, gr):
                m = self.m[path[0]]
                vv = self.v[path[0]]
                for k in path[1:]:
                    m, vv = m[k], vv[k]
                m *= b1
                m += (1 - b1) * gr
                vv *= b2
                vv += (1 - b2) * gr * gr
                p -= self.dense_lr * m / (np.sqrt(vv) + eps)
        else:
            b1, b2, eps = 0.9, 0.999, 1e-8
            bc1 = 1.0 - b1 ** self.t
            bc2 = 1.0 - b2 ** self.t

            def upd(path, p, gr):
                m = self.m[path[0]]
                vv = self.v[path[0]]
                for k in path[1:]:
                    m, vv = m[k], vv[k]
                m *= b1
                m += (1 - b1) * gr
                vv *= b2
                vv += (1 - b2) * gr * gr
                p -= self.dense_lr * (m / bc1) / (np.sqrt(vv / bc2) + eps)

        upd(("bias",), self.params["bias"], grads["bias"])
        if self.D:
            upd(("wide_dense",), self.params["wide_dense"],
                grads["wide_dense"])
        for i in range(len(layers)):
            for k in ("w", "b"):
                upd(("mlp", i, k), self.params["mlp"][i][k],
                    grads["mlp"][i][k])
        return loss


def _tree_zeros(x):
    if isinstance(x, dict):
        return {k: _tree_zeros(v) for k, v in x.items()}
    if isinstance(x, list):
        return [_tree_zeros(v) for v in x]
    return np.zeros_like(np.asarray(x), dtype=np.float32)


def _round_half_even(x):
    return np.round(x)       # numpy rounds half to even, like jnp.round
