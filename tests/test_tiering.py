"""Tiered table subsystem (embedding/tiering.py): show-count-weighted
RAM-tier admission/eviction over the spill store, flag-driven store
construction, pass-boundary re-scoring, and the streamed checkpoint
payloads.

Reference role: BoxPS's SSD + host-DRAM + HBM hierarchy (LoadSSD2Mem,
box_wrapper.h:487-494) with Parallax-style frequency-driven placement
(arXiv:1808.02621) — a small hot tier must absorb most traffic, and a
cold scan must not thrash it (the failure mode of the old direct-mapped
"last wins" install).
"""

import io
import os
import zipfile

import numpy as np
import pytest

from paddlebox_tpu import monitor
from paddlebox_tpu.config import flags, set_flags
from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                     ShardedEmbeddingStore,
                                     SpillEmbeddingStore, tiering)
from paddlebox_tpu.embedding.spill_store import _write_rows_npz
from paddlebox_tpu.embedding.tiering import TierManager
from paddlebox_tpu.utils import faultpoint


def _cfg(**kw):
    kw.setdefault("dim", 4)
    kw.setdefault("optimizer", "adagrad")
    kw.setdefault("learning_rate", 0.1)
    return EmbeddingConfig(**kw)


def _keys(lo, hi):
    return np.arange(lo, hi, dtype=np.uint64) * np.uint64(2654435761) + 1


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faultpoint.disarm()


# ---------------------------------------------------------------------------
# TierManager policy
# ---------------------------------------------------------------------------

def test_admit_prefers_hotter_rows_and_ties_go_to_newcomer():
    tm = TierManager(16)
    hot = np.array([1, 2], dtype=np.int64)
    cold = np.array([3, 4], dtype=np.int64)
    for _ in range(5):
        tm.note_access(hot)
    tm.note_access(cold)
    # cold candidates lose to hot occupants...
    assert not tm.admit(cold, hot).any()
    # ...hot candidates win over cold occupants...
    assert tm.admit(hot, cold).all()
    # ...empty slots always admit, and equal scores admit (recency wins)
    assert tm.admit(cold, np.array([-1, -1])).all()
    assert tm.admit(cold, cold[::-1].copy()).all()


def test_show_weight_breaks_frequency_ties():
    tm = TierManager(8, show_weight=0.5)
    a = np.array([1], dtype=np.int64)
    b = np.array([2], dtype=np.int64)
    tm.note_written(a, np.array([10.0], np.float32))  # 10 shows
    tm.note_written(b, np.array([0.0], np.float32))
    assert tm.admit(a, b).all()          # same freq, more shows -> wins
    assert not tm.admit(b, a).any()


def test_end_pass_decays_and_reports_deltas():
    tm = TierManager(8, decay=0.5)
    idx = np.array([1, 1, 2], dtype=np.int64)
    tm.note_access(idx)
    tm.count_install(3, 1)
    out = tm.end_pass()
    assert out == {"admitted": 3, "evicted": 1}
    assert tm.end_pass() == {"admitted": 0, "evicted": 0}   # flushed
    np.testing.assert_allclose(tm.score(np.array([1, 2, 3])),
                               [1.0, 0.5, 0.0])             # decayed EMA
    assert tm.total_admitted == 3 and tm.total_evicted == 1


def test_show_pin_decays_and_boundary_demotion_fires(tmp_path):
    """Review regression: the show weight must DECAY across idle passes —
    an absolute counter would pin a formerly-popular row's slot forever
    and keep its score above evict_below for good (boundary demotion was
    dead code for any written row). After a few idle passes the row
    demotes and a newly-hot row wins its slot."""
    tm = TierManager(8, decay=0.5, show_weight=0.25)
    old = np.array([1], dtype=np.int64)
    new = np.array([2], dtype=np.int64)
    tm.note_written(old, np.array([16.0], np.float32))  # once-popular
    for _ in range(6):
        tm.end_pass()                                   # goes idle
    assert tm.score(old)[0] < tm.evict_below            # demotable now
    tm.note_access(new)
    assert tm.admit(new, old).all()                     # newcomer wins
    # end to end: the cached occupant is demoted at the boundary
    st = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "s"),
                             cache_rows=8)
    keys = _keys(0, 4)
    rows = st.lookup_or_init(keys)
    rows[:, 0] = 16.0
    st.write_back(keys, rows)
    assert (st._ctags >= 0).sum() == 4
    for _ in range(6):
        st.tier_end_pass()
    assert (st._ctags >= 0).sum() == 0                  # all demoted


def test_install_counts_slot_collisions_once(tmp_path):
    """Review regression: N admitted candidates colliding on one slot in
    a single batch must count ONE admission (and at most one eviction) —
    only the last candidate actually resides."""
    st = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "s"),
                             cache_rows=1)       # every row -> slot 0
    st.lookup_or_init(_keys(0, 10))              # 10 candidates, 1 slot
    assert st.tier.total_admitted == 1
    assert st.tier.total_evicted == 0            # slot was empty
    st.get_rows(_keys(0, 6))                     # re-read: 6 -> 1 slot
    assert st.tier.total_admitted == 2           # one more install...
    assert st.tier.total_evicted == 1            # ...over ONE occupant


def test_direct_policy_skips_signal_accumulation(tmp_path):
    """Review regression: the direct-mapped baseline reads no signals,
    so its hot path must not pay the per-row accumulation (which would
    also skew the freq-vs-direct A/B)."""
    st = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "s"),
                             cache_rows=8, tier_policy="direct")
    keys = _keys(0, 20)
    rows = st.lookup_or_init(keys)
    st.write_back(keys, rows)
    assert not st.tier._freq.any() and not st.tier._show.any()


def test_bad_policy_and_bad_mode_raise():
    with pytest.raises(ValueError, match="policy"):
        TierManager(4, policy="lru")
    with pytest.raises(ValueError, match="table_tiering"):
        tiering.shard_store_factory(tiering="nvme")(_cfg(), 16, 0)


# ---------------------------------------------------------------------------
# the anti-thrash property the ISSUE names: a cold scan cannot evict the
# hot set under the freq policy, and does under the direct baseline
# ---------------------------------------------------------------------------

def _scan_workload(store, n_hot=16, n_cold_per_pass=64, passes=3, seed=0):
    """Hot keys re-read+written every pass; a rotating cold scan floods
    every direct-mapped slot in between. Returns the last pass's hot-read
    hit count."""
    hot = _keys(0, n_hot)
    rows = store.lookup_or_init(hot)
    rows[:, 0] = 50.0                      # hot rows carry real shows
    store.write_back(hot, rows)
    last_hot_hits = 0
    for p in range(passes):
        h0 = store.cache_hits
        r = store.lookup_or_init(hot)
        last_hot_hits = store.cache_hits - h0
        r[:, 0] += 1.0
        store.write_back(hot, r)
        cold = _keys(1000 + p * n_cold_per_pass,
                     1000 + (p + 1) * n_cold_per_pass)
        cr = store.lookup_or_init(cold)
        store.write_back(cold, cr)
        store.tier_end_pass()
    return last_hot_hits


def test_freq_policy_keeps_hot_set_where_direct_mapped_thrashes(tmp_path):
    n_hot = 16
    freq = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "f"),
                               cache_rows=n_hot, tier_policy="freq")
    direct = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "d"),
                                 cache_rows=n_hot, tier_policy="direct")
    hits_freq = _scan_workload(freq, n_hot=n_hot)
    hits_direct = _scan_workload(direct, n_hot=n_hot)
    # frequency-aware victim selection holds the whole hot set resident;
    # last-wins lost it to the cold scan every pass
    assert hits_freq == n_hot
    assert hits_direct < hits_freq
    assert freq.tier.total_evicted < direct.tier.total_evicted


def test_write_install_satellite_written_row_hits_on_next_read(tmp_path):
    """Regression (ISSUE 11 satellite): write-through used to refresh
    cache HITS only, so a just-written row faulted back in from disk on
    its next read. Written rows now install into their slots."""
    st = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "s"),
                             cache_rows=64)
    keys = _keys(0, 32)
    rows = st.lookup_or_init(keys)         # read-installs the rows
    st._ctags[:] = -1                      # empty the cache: only the
    st.tier.invalidate()                   # write path can re-install
    rows[:, 0] += 1.0
    st.write_back(keys, rows)
    h0, m0 = st.cache_hits, st.cache_misses
    got = st.get_rows(keys)
    assert st.cache_hits - h0 == len(keys)     # pure hits, no disk fault
    assert st.cache_misses == m0
    np.testing.assert_array_equal(got, rows)


def test_cache_stat_counters_batch_to_pass_boundary(tmp_path):
    """Satellite: spill.cache_* counter deltas accumulate in-store and
    land in the STATS registry once per tier_end_pass, together — the
    hub import is module-level, off the read hot path."""
    st = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "s"),
                             cache_rows=8)
    snap0 = monitor.STATS.snapshot()
    st.lookup_or_init(_keys(0, 40))
    snap1 = monitor.STATS.snapshot()
    assert snap1.get("spill.cache_misses", 0.0) == \
        snap0.get("spill.cache_misses", 0.0)       # batched, not yet live
    st.tier_end_pass()
    snap2 = monitor.STATS.snapshot()
    assert (snap2.get("spill.cache_misses", 0.0)
            - snap0.get("spill.cache_misses", 0.0)) == st.cache_misses
    assert st._stat_hits == 0 and st._stat_misses == 0


# ---------------------------------------------------------------------------
# set-associative geometry (flags.spill_cache_assoc): adversarial slot
# collisions stop capping the hit rate, and the geometry is placement
# only — never a math change
# ---------------------------------------------------------------------------

def test_set_assoc_holds_colliding_hot_set_where_direct_thrashes(tmp_path):
    """The adversarial stream the assoc geometry exists for: `assoc` hot
    rows per direct-mapped slot. 1-way, they evict EACH OTHER every pass
    (conflict misses — no budget increase fixes that); 4-way, the whole
    colliding set coexists and the hot re-read holds at 100%. Identical
    op sequences must leave byte-identical row files either way."""
    C, assoc = 64, 4
    # build the whole space first so row id i is pinned to key i — the
    # hot ids j, j+C, j+2C, j+3C then land 4-deep on direct slot j and
    # exactly fill the 4-way set j
    hot_ids = np.concatenate(
        [np.arange(C // assoc) + i * C for i in range(assoc)])
    results = {}
    for name, pol, ways in (("assoc", "freq", assoc),
                            ("direct", "direct", 1)):
        st = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / name),
                                 cache_rows=C, tier_policy=pol,
                                 cache_assoc=ways)
        space = _keys(0, 8 * C)
        st.lookup_or_init(space)
        hot = space[hot_ids]
        rows = st.lookup_or_init(hot)
        rows[:, 0] = 8.0                     # hot rows carry real shows
        st.write_back(hot, rows)
        st.tier_end_pass()
        last_hits = 0
        for p in range(2):
            h0 = st.cache_hits
            r = st.lookup_or_init(hot)
            last_hits = st.cache_hits - h0
            r[:, 0] += 1.0
            st.write_back(hot, r)
            cold = _keys(4 * C + p * C, 4 * C + (p + 1) * C)
            st.write_back(cold, st.lookup_or_init(cold))
            st.tier_end_pass()
        results[name] = (st, last_hits)
    sa, hits_a = results["assoc"]
    sd, hits_d = results["direct"]
    assert sa._n_sets * sa._assoc == C and sa._assoc == assoc
    assert hits_a == len(hot_ids)            # whole colliding set resident
    assert hits_d < hits_a                   # direct-mapped thrashed it
    assert sd.conflict_misses > 0            # ...and says why
    np.testing.assert_array_equal(np.array(sa._rows[:sa._n]),
                                  np.array(sd._rows[:sd._n]))


def test_cache_assoc_flag_default_and_direct_forces_one(tmp_path):
    st = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "a"),
                             cache_rows=16)
    assert st._assoc == flags.spill_cache_assoc == 4
    sd = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "d"),
                             cache_rows=16, tier_policy="direct")
    assert sd._assoc == 1            # direct IS the 1-way geometry
    set_flags(spill_cache_assoc=2)
    try:
        st2 = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "b"),
                                  cache_rows=16)
        assert st2._assoc == 2
    finally:
        set_flags(spill_cache_assoc=4)


def test_resize_cache_assoc_roundtrip(tmp_path):
    """The autotune's resize keeps the current associativity (and the
    budget a whole number of sets); an explicit ``assoc`` re-shapes the
    geometry. Either way contents re-fault from the authoritative spill
    file — every row still reads back exactly."""
    st = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "s"),
                             cache_rows=64, cache_assoc=4)
    keys = _keys(0, 200)
    rows = st.lookup_or_init(keys).copy()
    assert (st._n_sets, st._assoc, st._cache_slots) == (16, 4, 64)
    st.resize_cache(32)                          # assoc sticks
    assert (st._n_sets, st._assoc, st._cache_slots) == (8, 4, 32)
    np.testing.assert_array_equal(st.get_rows(keys), rows)
    st.resize_cache(48, assoc=3)                 # reshape
    assert (st._n_sets, st._assoc, st._cache_slots) == (16, 3, 48)
    np.testing.assert_array_equal(st.get_rows(keys), rows)
    st.resize_cache(40, assoc=1)                 # legacy direct-mapped
    assert (st._n_sets, st._assoc, st._cache_slots) == (40, 1, 40)
    np.testing.assert_array_equal(st.get_rows(keys), rows)
    # a ragged budget rounds down to whole sets, never below one set
    st.resize_cache(13, assoc=4)
    assert (st._n_sets, st._assoc, st._cache_slots) == (3, 4, 12)
    np.testing.assert_array_equal(st.get_rows(keys), rows)


def test_autotune_keeps_budget_set_aligned(tmp_path):
    """The grow/shrink targets align to the current associativity so the
    recorded slot count never drifts from the decision's arithmetic."""
    st = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "s"),
                             cache_rows=tiering.CACHE_MIN_ROWS,
                             cache_assoc=4)
    st.lookup_or_init(_keys(0, 8 * tiering.CACHE_MIN_ROWS))
    stats = st.tier_end_pass()
    target = tiering.autotune_cache_rows(st, stats)
    if target is not None:                       # thrash path fired
        assert target % st._assoc == 0
        assert st._cache_slots == target


def test_conflict_counters_batch_to_pass_boundary(tmp_path):
    """tiering.conflict_misses rides the same batch-to-boundary
    discipline as the hit/miss counters: accumulated in-store, flushed
    once per tier_end_pass (with the per-pass window in the returned
    stats), so the delta lands in the pass's flight record."""
    st = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "s"),
                             cache_rows=8, cache_assoc=2)
    snap0 = monitor.STATS.snapshot()
    st.lookup_or_init(_keys(0, 64))        # cold fill: sets still empty
    assert st.conflict_misses == 0         # compulsory, not conflict
    st.get_rows(_keys(0, 32))              # sets live now → conflicts
    assert st.conflict_misses > 0
    snap1 = monitor.STATS.snapshot()
    assert snap1.get("tiering.conflict_misses", 0.0) == \
        snap0.get("tiering.conflict_misses", 0.0)   # batched, not live
    stats = st.tier_end_pass()
    assert stats["pass_conflicts"] == st.conflict_misses
    snap2 = monitor.STATS.snapshot()
    assert (snap2.get("tiering.conflict_misses", 0.0)
            - snap0.get("tiering.conflict_misses", 0.0)) \
        == st.conflict_misses
    assert st._stat_conflicts == 0


# ---------------------------------------------------------------------------
# flag-driven construction
# ---------------------------------------------------------------------------

def test_store_from_flags_selects_tier_and_partition(tmp_path):
    assert isinstance(tiering.store_from_flags(_cfg()),
                      HostEmbeddingStore)
    set_flags(table_tiering="spill", spill_cache_rows=32,
              spill_dir=str(tmp_path / "root"))
    try:
        st = tiering.store_from_flags(_cfg())
        assert isinstance(st, SpillEmbeddingStore)
        assert st._cache_slots == 32
        ss = tiering.store_from_flags(_cfg(), n_shards=2)
        assert isinstance(ss, ShardedEmbeddingStore)
        assert all(isinstance(s, SpillEmbeddingStore)
                   for s in ss._shards)
        # per-shard row files under the flagged root, self-contained
        ss.lookup_or_init(_keys(0, 64))
        assert os.path.exists(tmp_path / "root" / "shard-00" / "rows.dat")
        assert os.path.exists(tmp_path / "root" / "shard-01" / "rows.dat")
        assert tiering.describe(ss) == "sharded+spill"
        assert tiering.describe(st) == "spill"
        assert tiering.describe(HostEmbeddingStore(_cfg())) is None
        stats = tiering.spill_stats(ss)
        assert stats["cache_rows"] == 64 and stats["spill_bytes"] > 0
        assert tiering.spill_stats(HostEmbeddingStore(_cfg())) is None
    finally:
        set_flags(table_tiering="off", spill_cache_rows=1 << 16,
                  spill_dir="")


# ---------------------------------------------------------------------------
# pass-boundary rebalance: telemetry + the evict crash window
# ---------------------------------------------------------------------------

def test_rebalance_emits_counters_into_flight_record(tmp_path):
    from paddlebox_tpu.monitor.flight import validate_flight_record
    ss = ShardedEmbeddingStore(
        _cfg(), 2, store_factory=tiering.shard_store_factory(
            tiering="spill", cache_rows=8,
            spill_dir=str(tmp_path / "sp")))
    h = monitor.hub()
    h.disable()
    ms = monitor.MemorySink()
    h.enable(ms)
    try:
        h.begin_pass(1)
        ss.lookup_or_init(_keys(0, 100))       # misses -> admissions
        out = tiering.end_pass_rebalance(ss)
        rec = h.end_pass()
    finally:
        h.disable()
    assert out["admitted"] > 0
    assert out["hot_rows"] > 0 and out["spill_bytes"] > 0
    assert rec["stats_delta"].get("tiering.admitted") == out["admitted"]
    assert rec["stats_delta"].get("spill.cache_misses", 0) > 0
    assert validate_flight_record(rec) == []
    # untiered stores are a no-op
    assert tiering.end_pass_rebalance(HostEmbeddingStore(_cfg())) is None


def test_flight_validator_rejects_bad_tiering_fields():
    from paddlebox_tpu.monitor.flight import validate_flight_record
    base = {"ts": 1.0, "type": "flight_record", "name": "pass",
            "pass_id": 1, "step": None, "phase": None, "thread": "t",
            "seconds": 1.0, "steps": 1, "examples": 1,
            "examples_per_sec": 1.0, "stage_seconds": {},
            "stats_delta": {}, "metrics": {}}
    bad_counter = dict(base, stats_delta={"tiering.admitted": -3})
    assert any("monotone" in e for e in
               validate_flight_record(bad_counter))
    # the set-assoc / replica counters are monotone too — a negative
    # per-pass delta means a consumer double-counted the flush
    bad_conflicts = dict(base,
                         stats_delta={"tiering.conflict_misses": -1})
    assert any("monotone" in e for e in
               validate_flight_record(bad_conflicts))
    bad_replica = dict(base, stats_delta={"tiering.replica_hits": -2})
    assert any("monotone" in e for e in
               validate_flight_record(bad_replica))
    bad_extra = dict(base, extra={"table_tiering": 7})
    assert any("table_tiering" in e for e in
               validate_flight_record(bad_extra))
    ok = dict(base, stats_delta={"tiering.admitted": 3,
                                 "tiering.evicted": 0,
                                 "tiering.conflict_misses": 5,
                                 "tiering.replica_hits": 12,
                                 "tiering.replica_rows": -64},
              extra={"table_tiering": "sharded+spill"})
    assert validate_flight_record(ok) == []


def test_evict_faultpoint_is_harmless_to_authoritative_state(tmp_path):
    """tiering.evict.pre: an IO fault inside the boundary rebalance
    leaves the authoritative tier untouched — every row still reads back
    exactly, and the next rebalance completes."""
    st = SpillEmbeddingStore(_cfg(), spill_dir=str(tmp_path / "s"),
                             cache_rows=8)
    keys = _keys(0, 50)
    rows = st.lookup_or_init(keys)
    rows[:, 2] = 3.25
    st.write_back(keys, rows)
    faultpoint.arm("tiering.evict.pre", action="ioerror")
    with pytest.raises(faultpoint.FaultInjected):
        st.tier_end_pass()
    faultpoint.disarm()
    np.testing.assert_array_equal(st.get_rows(keys), rows)
    st.tier_end_pass()
    np.testing.assert_array_equal(st.get_rows(keys), rows)


# ---------------------------------------------------------------------------
# streamed checkpoint payloads
# ---------------------------------------------------------------------------

def test_streamed_npz_matches_savez_semantics(tmp_path):
    """_write_rows_npz produces an archive np.load reads exactly like
    np.savez_compressed's — keys/rows/removed members, same values —
    while streaming the row plane in bounded chunks (chunking is
    exercised by a gather index longer than one chunk via monkeypatched
    chunk size)."""
    import paddlebox_tpu.embedding.spill_store as sp
    rng = np.random.default_rng(3)
    rows = rng.normal(size=(500, 6)).astype(np.float32)
    keys = rng.integers(1, 1 << 50, size=500).astype(np.uint64)
    idx = rng.permutation(500)[:333].astype(np.int64)
    removed = np.array([7, 9], dtype=np.uint64)
    old = sp._STREAM_CHUNK_ROWS
    sp._STREAM_CHUNK_ROWS = 100            # force multi-chunk streaming
    try:
        buf = io.BytesIO()
        _write_rows_npz(buf, keys[idx], rows, idx, len(idx),
                        removed=removed)
    finally:
        sp._STREAM_CHUNK_ROWS = old
    buf.seek(0)
    with np.load(buf) as z:
        np.testing.assert_array_equal(z["keys"], keys[idx])
        np.testing.assert_array_equal(z["rows"], rows[idx])
        np.testing.assert_array_equal(z["removed"], removed)
    # and the zip really is deflated (the savez_compressed trade)
    buf.seek(0)
    with zipfile.ZipFile(buf) as zf:
        assert zf.getinfo("rows.npy").compress_type == \
            zipfile.ZIP_DEFLATED


def test_spill_chain_loads_in_plain_host_store(tmp_path):
    """Storage-tier symmetry: a chain written by the STREAMING spill
    writer loads bit-identically into the in-RAM store, and vice versa
    (restore replays through _write_rows either way)."""
    cfg = _cfg()
    keys = _keys(0, 200)
    spill = SpillEmbeddingStore(cfg, spill_dir=str(tmp_path / "s"),
                                cache_rows=16)
    rows = spill.lookup_or_init(keys)
    rows[:, 0] = 2.0
    spill.write_back(keys, rows)
    spill.save_base(str(tmp_path / "ck"))
    rows[:, 2] = 4.5
    spill.write_back(keys[:77], rows[:77])
    spill.save_delta(str(tmp_path / "ck"))
    ram = HostEmbeddingStore.load(str(tmp_path / "ck"))
    np.testing.assert_array_equal(ram.get_rows(keys), spill.get_rows(keys))
    # round-trip the other way: RAM chain -> spill store
    ram.save_base(str(tmp_path / "ck2"))
    spill2 = SpillEmbeddingStore(cfg, spill_dir=str(tmp_path / "s2"),
                                 cache_rows=16)
    spill2.restore(str(tmp_path / "ck2"))
    np.testing.assert_array_equal(spill2.get_rows(keys),
                                  spill.get_rows(keys))


def test_remote_sharded_chain_uploads_incrementally(tmp_path):
    """Review regression: a sharded chain's delta save must upload only
    what it touched (per-shard delta + manifests + shards.json), not the
    whole accumulated chain — for the terabyte-class tables this tier
    exists for, whole-chain re-upload per pass is O(chain) exactly where
    incremental matters most. Proof: a file deleted from the remote
    BASE after rotation stays deleted across later delta saves (a
    whole-dir re-upload would resurrect it), while the deltas land; a
    replacement host then resumes bit-exact once the base is restored."""
    import json
    import shutil
    import jax
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig
    from paddlebox_tpu.utils import fs as fs_lib
    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    from tests.crash_worker import NUM_SLOTS, synth
    from tests.mockfs import register_mockfs

    mock_root = tmp_path / "hdfs_root"
    register_mockfs(str(mock_root), scheme="tiermock")
    try:
        def mk(sub, seed):
            store = ShardedEmbeddingStore(
                EmbeddingConfig(dim=4, learning_rate=0.05), 2,
                store_factory=tiering.shard_store_factory(
                    tiering="spill", cache_rows=16,
                    spill_dir=str(tmp_path / sub)))
            ds, schema = synth(n=128)
            tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4,
                                     dense_dim=1, hidden=(8,)),
                         store, schema, make_mesh(1),
                         TrainerConfig(global_batch_size=64,
                                       auc_buckets=1 << 8), seed=seed)
            return ds, tr, store

        ds, tr, store = mk("a", seed=7)
        box = BoxPS(store)
        ck = PassCheckpointer("tiermock://ck", keep_last_n=4,
                              base_every=8,
                              staging_dir=str(tmp_path / "stage_a"))
        box.begin_pass(); tr.train_pass(ds)
        box.end_pass(checkpointer=ck, trainer=tr)   # pass 1: base upload
        chain = mock_root / "ck" / "chain-0001"
        canary = chain / "shard-00" / "base.npz"
        assert canary.exists()
        canary_bytes = canary.read_bytes()
        canary.unlink()                             # the re-upload canary
        for _ in (2, 3):                            # delta saves
            box.begin_pass(); tr.train_pass(ds)
            box.end_pass(checkpointer=ck, trainer=tr)
        assert not canary.exists(), \
            "delta save re-uploaded the whole chain dir"
        for s in ("shard-00", "shard-01"):
            for n in ("delta-00001.npz", "delta-00002.npz", "meta.json",
                      "MANIFEST.json"):
                assert (chain / s / n).exists(), (s, n)
        assert (chain / "shards.json").exists()
        entries = [json.loads(ln) for ln in
                   (mock_root / "ck" / "snapshots.donefile"
                    ).read_text().splitlines()]
        assert [e["pass"] for e in entries] == [1, 2, 3]

        tr.flush_sparse()
        keys = np.sort(np.asarray(ds.unique_keys(), np.uint64))
        want_rows = store.get_rows(keys)
        want_params = jax.tree.map(np.asarray, tr.params)
        canary.write_bytes(canary_bytes)            # storage repaired
        ds2, tr2, store2 = mk("b", seed=99)
        ck2 = PassCheckpointer("tiermock://ck", keep_last_n=4,
                               base_every=8,
                               staging_dir=str(tmp_path / "stage_b"))
        cursor = tr2.resume(ck2, box=BoxPS(store2))
        assert cursor["pass_id"] == 3
        np.testing.assert_array_equal(store2.get_rows(keys), want_rows)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            tr2.params, want_params)
    finally:
        shutil.rmtree(tmp_path / "hdfs_root", ignore_errors=True)
        fs_lib._REGISTRY.pop("tiermock", None)


def test_sharded_spill_through_pass_checkpointer(tmp_path):
    """The tentpole wiring: spill-backed shards checkpoint through
    PassCheckpointer's rotating per-shard chain dirs and resume
    bit-exact into a FRESH spill-backed store (a different spill root —
    the row files are scratch, the chain is authoritative)."""
    import jax
    from paddlebox_tpu.data import DataFeedSchema
    from paddlebox_tpu.fleet import BoxPS
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig
    from paddlebox_tpu.utils import checkpoint as ckpt_lib
    from tests.crash_worker import NUM_SLOTS, synth

    def mk(sub, seed):
        store = ShardedEmbeddingStore(
            EmbeddingConfig(dim=4, learning_rate=0.05), 2,
            store_factory=tiering.shard_store_factory(
                tiering="spill", cache_rows=16,
                spill_dir=str(tmp_path / sub)))
        ds, schema = synth(n=128)
        tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4,
                                 dense_dim=1, hidden=(8,)),
                     store, schema, make_mesh(1),
                     TrainerConfig(global_batch_size=64,
                                   auc_buckets=1 << 8), seed=seed)
        return ds, tr, store

    from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer
    ds, tr, store = mk("a", seed=7)
    box = BoxPS(store)
    ckpt = PassCheckpointer(str(tmp_path / "ck"), keep_last_n=2,
                            base_every=2)
    for _ in range(3):                      # base, delta, rotated base
        box.begin_pass()
        tr.train_pass(ds)
        box.end_pass(checkpointer=ckpt, trainer=tr)
    tr.flush_sparse()
    keys = np.sort(np.asarray(ds.unique_keys(), np.uint64))
    want_rows = store.get_rows(keys)
    want_params = jax.tree.map(np.asarray, tr.params)
    # the snapshot recorded shard-prefixed chain members and verifies
    m = ckpt_lib.read_manifest(ckpt.snap_dir(3))
    assert any(n.startswith("shard-00/") for n in m["chain_files"])
    assert ckpt.latest_valid()[0] == 3

    ds2, tr2, store2 = mk("b", seed=99)     # different init + spill root
    cursor = tr2.resume(PassCheckpointer(str(tmp_path / "ck")),
                        box=BoxPS(store2))
    assert cursor["pass_id"] == 3
    np.testing.assert_array_equal(store2.get_rows(keys), want_rows)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        tr2.params, want_params)
    # a corrupt shard member in the newest snapshot's chain is diagnosed
    # at its shard-prefixed CHAIN POSITION (review regression: a bare
    # basename lookup reported '#-1') and the walk falls back past it
    newest_chain = ckpt_lib.read_manifest(ckpt.snap_dir(3))["chain_dir"]
    victim = os.path.join(str(tmp_path / "ck"), newest_chain,
                          "shard-01", "base.npz")
    raw = open(victim, "rb").read()
    with open(victim, "wb") as f:
        f.write(raw[:-8])
    with pytest.warns(UserWarning,
                      match=r"chain member #1 of the 2 recorded"):
        found = PassCheckpointer(str(tmp_path / "ck")).latest_valid()
    assert found is not None and found[0] == 2


def test_sharded_chain_corruption_names_shard_member(tmp_path):
    """Bit-rot in one shard's delta is diagnosed with the shard-prefixed
    member name + chain position (store-level _verify_chain over the
    shard-aware chain_members), never half-replayed."""
    from paddlebox_tpu.utils.checkpoint import CheckpointCorruptError
    ss = ShardedEmbeddingStore(
        _cfg(), 2, store_factory=tiering.shard_store_factory(
            tiering="spill", cache_rows=8,
            spill_dir=str(tmp_path / "sp")))
    keys = _keys(0, 80)
    ss.lookup_or_init(keys)
    ss.save_base(str(tmp_path / "ck"))
    rows = ss.get_rows(keys)
    rows[:, 2] = 1.5
    ss.write_back(keys, rows)
    ss.save_delta(str(tmp_path / "ck"))
    victim = tmp_path / "ck" / "shard-01" / "delta-00001.npz"
    blob = bytearray(victim.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    victim.write_bytes(bytes(blob))
    with pytest.raises(CheckpointCorruptError, match="delta-00001"):
        ShardedEmbeddingStore.load(str(tmp_path / "ck"))
