"""Per-width-class _bp_pack engines (flags.pack_engine).

The pack's expensive op is the token reorder, and the v5e row-gather
sweep is sharply non-monotone in source width — so the pack dispatches
per payload width class (narrow <14 / gather_zone 14..63 / wide >=64).
The contract: all three engines produce the IDENTICAL packed operand
(only the gather's source width differs), the auto selection follows the
sweep's zone boundaries, and the choice is recordable per bench point
(pack_engine()) — the discipline whose absence let the round-5 _bp_pack
rewrite halve headline throughput unnoticed.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import set_flags
from paddlebox_tpu.embedding import EmbeddingConfig
from paddlebox_tpu.ops import pallas_kernels as pk


@pytest.fixture(autouse=True)
def _restore_flags():
    yield
    set_flags(pack_engine="auto", push_engine="auto")


def test_width_class_boundaries():
    assert pk.pack_width_class(8) == "narrow"
    assert pk.pack_width_class(13) == "narrow"
    assert pk.pack_width_class(14) == "gather_zone"
    assert pk.pack_width_class(40) == "gather_zone"
    assert pk.pack_width_class(63) == "gather_zone"
    assert pk.pack_width_class(64) == "wide"
    assert pk.pack_width_class(290) == "wide"


def _operands(cfg, n_rows, tok, seed=0):
    rng = np.random.default_rng(seed)
    idx = jnp.asarray(rng.integers(0, n_rows, size=tok).astype(np.int32))
    grads = jnp.asarray(
        rng.normal(size=(tok, cfg.grad_width)).astype(np.float32))
    shows = jnp.asarray(np.ones(tok, np.float32))
    clks = jnp.asarray((rng.random(tok) < 0.3).astype(np.float32))
    return idx, grads, shows, clks


@pytest.mark.parametrize("dim", [4, 16, 64])
def test_engines_produce_identical_packed_operand(dim):
    """Forcing any engine is always legal and bit-identical: the packed
    array, rstart, and end must not depend on the gather layout."""
    cfg = EmbeddingConfig(dim=dim, optimizer="adagrad")
    n_rows = 4096
    geom = pk._bp_geometry(cfg, n_rows)
    assert geom is not None
    TILE = pk._bp_tile(geom[3], geom[2])
    idx, grads, shows, clks = _operands(cfg, n_rows, 1000)

    outs = {}
    for eng in pk.PACK_ENGINES:
        set_flags(pack_engine=eng)
        packed, rstart, end = jax.jit(
            lambda i, g, s, c: pk._bp_pack(i, g, s, c, geom, TILE,
                                           n_rows))(idx, grads, shows,
                                                    clks)
        outs[eng] = (np.asarray(packed), np.asarray(rstart),
                     np.asarray(end))
    ref = outs["narrow"]
    for eng in ("gather_zone", "wide"):
        for a, b in zip(ref, outs[eng]):
            assert np.array_equal(a, b), f"{eng} diverges from narrow"


def test_engines_identical_with_host_plan():
    """Same invariant when the grouping arrives as a host plan (the
    production pack-pipeline path)."""
    from paddlebox_tpu.native.key_index import block_plan
    cfg = EmbeddingConfig(dim=8, optimizer="adagrad")
    n_rows = 4096
    geom = pk._bp_geometry(cfg, n_rows)
    SB = geom[3]
    TILE = pk._bp_tile(SB, geom[2])
    idx, grads, shows, clks = _operands(cfg, n_rows, 512)
    o, r, e = block_plan(np.asarray(idx), SB, n_rows // SB)
    plan = (jnp.asarray(o), jnp.asarray(r), jnp.asarray(e))
    outs = {}
    for eng in pk.PACK_ENGINES:
        set_flags(pack_engine=eng)
        packed, rstart, end = jax.jit(
            lambda i, g, s, c, p: pk._bp_pack(i, g, s, c, geom, TILE,
                                              n_rows, plan=p))(
            idx, grads, shows, clks, plan)
        outs[eng] = np.asarray(packed)
    assert np.array_equal(outs["narrow"], outs["gather_zone"])
    assert np.array_equal(outs["narrow"], outs["wide"])


def test_auto_selection_per_width():
    """pack_engine(cfg, rows) follows the width class where the kernel
    engages, honors the override, and is None on scatter-engine widths
    (no pack to choose)."""
    rows = 1 << 16
    # dim 8 -> P = 12 -> narrow
    assert pk.pack_engine(EmbeddingConfig(dim=8), rows) == "narrow"
    # dim 16 -> P = 20 -> gather_zone
    assert pk.pack_engine(EmbeddingConfig(dim=16), rows) == "gather_zone"
    # dim 64 -> G == 1 -> scatter engine keeps the push: no pack engine
    assert pk.pack_engine(EmbeddingConfig(dim=64), rows) is None
    # ...unless the kernel is forced, where the wide pack serves it
    set_flags(push_engine="kernel")
    assert pk.pack_engine(EmbeddingConfig(dim=64), rows) == "wide"
    set_flags(push_engine="auto")
    # override is reported verbatim where a pack exists
    set_flags(pack_engine="wide")
    assert pk.pack_engine(EmbeddingConfig(dim=8), rows) == "wide"
    set_flags(pack_engine="auto")
    # premerged lanes arrive sorted — no reorder compiles, and the
    # record must say so instead of naming the width class
    assert pk.pack_engine(EmbeddingConfig(dim=16), rows,
                          premerged=True) == "premerged_no_reorder"


def test_forced_engine_typo_raises():
    """A misspelled forced engine must fail loudly at trace time, not
    silently measure auto (the A/B-trust property)."""
    cfg = EmbeddingConfig(dim=8, optimizer="adagrad")
    n_rows = 4096
    geom = pk._bp_geometry(cfg, n_rows)
    TILE = pk._bp_tile(geom[3], geom[2])
    idx, grads, shows, clks = _operands(cfg, n_rows, 64)
    set_flags(pack_engine="gatherzone")       # typo for gather_zone
    with pytest.raises(ValueError, match="pack_engine"):
        pk._bp_pack(idx, grads, shows, clks, geom, TILE, n_rows)
    with pytest.raises(ValueError, match="pack_engine"):
        pk.pack_engine(cfg, n_rows)


def test_binned_push_parity_across_engines():
    """End to end through the merge accumulator (interpret-mode kernel):
    the engine choice must not change the accumulated rows."""
    cfg = EmbeddingConfig(dim=8, optimizer="adagrad")
    n_rows = 4096
    idx, grads, shows, clks = _operands(cfg, n_rows, 600)
    accs = {}
    for eng in pk.PACK_ENGINES:
        set_flags(pack_engine=eng)
        accs[eng] = np.asarray(pk.binned_merge_acc(
            idx, grads, shows, clks, cfg, n_rows, n_split=3,
            interpret=True))
    assert np.array_equal(accs["narrow"], accs["gather_zone"])
    assert np.array_equal(accs["narrow"], accs["wide"])
