"""Native C++ parser vs the Python reference implementation.

The two must be byte-identical on every field (the reference keeps one
parser in C++; we keep two and pin them together here)."""

import numpy as np
import pytest

from paddlebox_tpu.data.parser import _parse_python
from paddlebox_tpu.data.schema import DataFeedSchema, Slot, SlotType
from paddlebox_tpu.native import slot_parser_binding as native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native lib not built")


def make_schema():
    return DataFeedSchema([
        Slot("label", SlotType.FLOAT, max_len=1),
        Slot("dense", SlotType.FLOAT, max_len=3),
        Slot("skip_me", SlotType.UINT64, max_len=5, is_used=False),
        Slot("s0", SlotType.UINT64, max_len=4),
        Slot("s1", SlotType.UINT64, max_len=2),
    ], batch_size=8)


def make_lines(n, seed=0, with_ins_id=False):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        parts = []
        if with_ins_id:
            parts.append(f"ins_{i}\t1")
        else:
            parts.append("1")
        parts.append(str(int(rng.integers(0, 2))))
        ln = int(rng.integers(0, 5))  # dense: pad/truncate vs width 3
        parts.append(str(ln))
        parts.extend(f"{rng.random():.6f}" for _ in range(ln))
        for _slot in range(3):  # skip_me, s0, s1
            ln = int(rng.integers(0, 6))
            parts.append(str(ln))
            parts.extend(str(int(k)) for k in
                         rng.integers(0, 1 << 63, ln, dtype=np.int64))
        lines.append(" ".join(parts))
    return lines


def assert_batches_equal(a, b):
    assert a.num == b.num
    for x, y in zip(a.sparse_values, b.sparse_values):
        np.testing.assert_array_equal(x, y)
    for x, y in zip(a.sparse_offsets, b.sparse_offsets):
        np.testing.assert_array_equal(x, y)
    for x, y in zip(a.float_values, b.float_values):
        np.testing.assert_array_equal(x, y)
    np.testing.assert_array_equal(a.ins_id, b.ins_id)


def test_matches_python_parser():
    schema = make_schema()
    lines = make_lines(200, seed=1)
    got = native.parse_lines(lines, schema)
    want = _parse_python(lines, schema, with_ins_id=False)
    assert_batches_equal(got, want)


def test_matches_python_parser_with_ins_id():
    schema = make_schema()
    lines = make_lines(50, seed=2, with_ins_id=True)
    got = native.parse_lines(lines, schema, with_ins_id=True)
    want = _parse_python(lines, schema, with_ins_id=True)
    assert_batches_equal(got, want)
    assert got.ins_id.any()  # FNV hashes actually computed


def test_blank_lines_and_crlf():
    schema = make_schema()
    lines = make_lines(10, seed=3)
    buf = ("\n\n" + "\r\n".join(lines) + "\n\n").encode()
    got = native.parse_buffer(buf, schema)
    want = _parse_python(lines, schema, with_ins_id=False)
    assert_batches_equal(got, want)


def test_multithreaded_matches_single():
    schema = make_schema()
    buf = "\n".join(make_lines(500, seed=4)).encode()
    got1 = native.parse_buffer(buf, schema, n_threads=1)
    got4 = native.parse_buffer(buf, schema, n_threads=4)
    assert_batches_equal(got1, got4)


def test_malformed_line_raises():
    schema = make_schema()
    with pytest.raises(ValueError, match="malformed"):
        native.parse_buffer(b"1 0 2 5\n", schema)
    with pytest.raises(ValueError, match="malformed"):
        native.parse_buffer(b"not a number\n", schema)


def test_hash_matches_python():
    from paddlebox_tpu.utils.hashing import hash64
    for s in ["", "a", "ins_123", "ünicode-☃"]:
        assert native.hash64_native(s) == hash64(s)


def test_uint64_range_roundtrip():
    # feasigns up to 2^63-1 survive exactly (int64 storage)
    schema = DataFeedSchema([Slot("s", SlotType.UINT64, max_len=2)])
    big = (1 << 63) - 1
    got = native.parse_buffer(f"2 {big} 7".encode(), schema)
    np.testing.assert_array_equal(got.sparse_values[0], [big, 7])


def test_generator_input_not_consumed_on_fallback(monkeypatch):
    # parse_multislot_lines must not hand an exhausted iterator to the
    # Python fallback when the native lib is unavailable
    from paddlebox_tpu.data import parser as parser_mod
    monkeypatch.setattr(parser_mod, "_native_cache", [None])
    schema = DataFeedSchema([Slot("s", SlotType.UINT64, max_len=2)])
    got = parser_mod.parse_multislot_lines(
        (l for l in ["1 5", "1 6"]), schema)
    assert got.num == 2


def test_u64_above_2_63_parity():
    schema = DataFeedSchema([Slot("s", SlotType.UINT64, max_len=2)])
    line = "2 9223372036854775813 18446744073709551615"
    a = _parse_python([line], schema, False).sparse_values[0]
    b = native.parse_buffer(line.encode(), schema).sparse_values[0]
    np.testing.assert_array_equal(a, b)


def test_error_reports_global_line_number():
    schema = make_schema()
    good = "\n".join(make_lines(300, seed=7))
    with pytest.raises(ValueError, match=r"line 301"):
        native.parse_buffer((good + "\nbogus\n").encode(), schema,
                            n_threads=4)
