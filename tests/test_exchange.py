"""Sharded embedding exchange (embedding/exchange.py + the trainer's
table_layout=sharded engine + ShardedEmbeddingStore).

Bitwise-parity discipline: gathers move exact bits, so PULL parity is
asserted bit-for-bit on arbitrary rows. PUSH parity is asserted
bit-for-bit under EXACT arithmetic — lattice grads (multiples of 2^-10,
bounded) and a power-of-two SGD learning rate keep every sum and update
exactly representable, so ANY merge order yields identical bits and the
comparison pins routing/dedup/premerge/wire delivery exactly: a
misrouted, duplicated, or dropped lane shows as a large error, not a
rounding one. With adagrad the optimizer's sqrt/divide compiles to
different fusions under shard_map vs plain jit (1-ulp variance, present
in the LEGACY routed path too — verified while building this suite), so
the adagrad companion bounds at allclose.
"""

import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddlebox_tpu import monitor
from paddlebox_tpu.config import flags, set_flags
from paddlebox_tpu.data import DataFeedSchema, SlotDataset
from paddlebox_tpu.data.slot_record import SlotRecordBatch
from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                     PassWorkingSet, ShardedEmbeddingStore,
                                     exchange, sharded)
from paddlebox_tpu.embedding.feed_pass import FeedPassManager
from paddlebox_tpu.models import DeepFMModel
from paddlebox_tpu.native.key_index import dedup_plan
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig
from paddlebox_tpu.utils import faultpoint


@pytest.fixture(scope="module")
def mesh2():
    return make_mesh(2)


@pytest.fixture(scope="module")
def mesh4():
    return make_mesh(4)


def _cfg(**kw):
    kw.setdefault("dim", 4)
    kw.setdefault("optimizer", "sgd")
    kw.setdefault("learning_rate", 0.0625)   # power of two: exact step
    return EmbeddingConfig(**kw)


def _ws(cfg, n_keys, mesh):
    store = HostEmbeddingStore(cfg)
    keys = np.random.default_rng(7).choice(
        1 << 40, size=n_keys, replace=False).astype(np.uint64)
    return store, PassWorkingSet.begin_pass(store, keys, mesh)


def _device_plans(idx_flat: np.ndarray, n_rows: int, n_dev: int):
    """Per-device dedup plans concatenated along dim 0 — exactly what
    Trainer._host_plan stages for the sharded engine (shard_map splits
    every plan array into contiguous per-device slices)."""
    parts = [dedup_plan(a, n_rows, n_rows, 1)
             for a in idx_flat.reshape(n_dev, -1)]
    Z = jnp.zeros(0, jnp.int32)
    return (jnp.asarray(np.concatenate([p[0] for p in parts])), Z, Z,
            jnp.asarray(np.concatenate([p[1] for p in parts])),
            jnp.asarray(np.concatenate([p[2] for p in parts])))


def _lattice_grads(rng, n, width):
    """Exact-arithmetic payloads: multiples of 2^-10 bounded by 0.5 —
    every sum of a few hundred stays exactly representable in f32, so
    summation order cannot change bits."""
    return (rng.integers(-512, 512, size=(n, width)) / 1024.0
            ).astype(np.float32)


# ---------------------------------------------------------------------------
# op-level parity (the acceptance bar: 2-shard routed exchange
# bit-identical to the single-shard path on identical data)
# ---------------------------------------------------------------------------

def test_pull_bit_identical_2shard(mesh2):
    c = _cfg()
    store, ws = _ws(c, 100, mesh2)
    rng = np.random.default_rng(3)
    idx = rng.integers(0, ws.num_keys + 1, size=64).astype(np.int32)
    plan = _device_plans(idx, ws.padded_rows, 2)

    def body(tshard, i, *p):
        return exchange.routed_pull(tshard, i, c, ("dp",), 2.0, plan=p,
                                    return_dropped=True)

    out, dropped = jax.jit(jax.shard_map(
        body, mesh=mesh2, in_specs=(P("dp"),) * 7,
        out_specs=(P("dp"), P())))(ws.table, jnp.asarray(idx), *plan)
    want = np.asarray(sharded.lookup(ws.table, jnp.asarray(idx), c))
    assert int(dropped) == 0
    np.testing.assert_array_equal(np.asarray(out), want)


def test_plan_dedup_indices_reconstructs():
    idx = np.array([5, 3, 5, 0, 9, 3, 3, 12], np.int32)
    o, u, s, _r, _e = dedup_plan(idx, 16, 16, 1)
    Z = jnp.zeros(0, jnp.int32)
    dplan = tuple(jnp.asarray(a) for a in (o, np.zeros(0, np.int32),
                                           np.zeros(0, np.int32), u, s))
    uniq, inverse = exchange.plan_dedup_indices(
        (dplan[0], dplan[1], dplan[2], dplan[3], dplan[4]))
    np.testing.assert_array_equal(
        np.asarray(uniq)[np.asarray(inverse)], idx)


def test_pull_pooled_bit_identical_2shard(mesh2):
    """The fused gather-pool pull per shard after routing: the pooled
    sums over the received lanes match the single-shard fused path
    bit-for-bit (same gathered values summed in the same slot order)."""
    c = _cfg()
    store, ws = _ws(c, 80, mesh2)
    rng = np.random.default_rng(5)
    B, S, L = 8, 4, 2
    idx = rng.integers(0, ws.num_keys + 1, size=(B, S * L)).astype(np.int32)
    idx[rng.random(idx.shape) < 0.3] = 0        # mask-nulled padding
    plan = _device_plans(idx.reshape(-1), ws.padded_rows, 2)

    def body(tshard, i, *p):
        return exchange.routed_pull_pooled(tshard, i, c, ("dp",), S, L,
                                           2.0, plan=p,
                                           return_dropped=True)

    pooled, dropped = jax.jit(jax.shard_map(
        body, mesh=mesh2, in_specs=(P("dp"),) * 7,
        out_specs=(P("dp"), P())))(ws.table, jnp.asarray(idx), *plan)
    want = np.asarray(sharded.fused_pull_pool(ws.table, jnp.asarray(idx),
                                              c, S, L))
    assert int(dropped) == 0
    np.testing.assert_array_equal(np.asarray(pooled), want)


def _push_operands(c, ws, n_tok=64, seed=4):
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, ws.num_keys + 1, size=n_tok).astype(np.int32)
    grads = _lattice_grads(rng, n_tok, c.grad_width)
    shows = (idx > 0).astype(np.float32)
    clks = (rng.integers(0, 2, n_tok) * shows).astype(np.float32)
    grads[idx == 0] = 0.0                       # null rows carry zeros
    return idx, grads, shows, clks


def test_push_bit_identical_2shard_exact(mesh2):
    """Plan-keyed, premerged-before-route push over the f32 wire equals
    the single-shard push bit-for-bit under exact arithmetic."""
    c = _cfg()
    store, ws = _ws(c, 60, mesh2)
    idx, grads, shows, clks = _push_operands(c, ws)
    plan = _device_plans(idx, ws.padded_rows, 2)
    args = tuple(map(jnp.asarray, (idx, grads, shows, clks)))

    def body(tshard, i, g, sh, ck, *p):
        return exchange.routed_push(tshard, i, g, sh, ck, c, ("dp",),
                                    2.0, wire="f32", plan=p)

    out = jax.jit(jax.shard_map(
        body, mesh=mesh2, in_specs=(P("dp"),) * 10,
        out_specs=P("dp")))(ws.table, *args, *plan)
    want = np.asarray(sharded.push(ws.table, *args, c))
    np.testing.assert_array_equal(np.asarray(out), want)


def test_push_premerged_deferred_bit_identical(mesh2):
    """The deferred-apply form: the step premerges onto unique lanes
    (deferred_push_operands) and the apply routes the premerged lanes —
    bit-identical to the inline exchange under exact arithmetic."""
    c = _cfg()
    store, ws = _ws(c, 60, mesh2)
    idx, grads, shows, clks = _push_operands(c, ws, seed=9)
    plan = _device_plans(idx, ws.padded_rows, 2)
    args = tuple(map(jnp.asarray, (idx, grads, shows, clks)))

    def inline(tshard, i, g, sh, ck, *p):
        return exchange.routed_push(tshard, i, g, sh, ck, c, ("dp",),
                                    2.0, wire="f32", plan=p)

    def deferred(tshard, i, g, sh, ck, *p):
        mg, ms, mc = sharded.deferred_push_operands(i, g, sh, ck, p)
        return exchange.routed_push(tshard, p[3], mg, ms, mc, c, ("dp",),
                                    2.0, wire="f32", premerged=True)

    a = jax.jit(jax.shard_map(inline, mesh=mesh2,
                              in_specs=(P("dp"),) * 10,
                              out_specs=P("dp")))(ws.table, *args, *plan)
    b = jax.jit(jax.shard_map(deferred, mesh=mesh2,
                              in_specs=(P("dp"),) * 10,
                              out_specs=P("dp")))(ws.table, *args, *plan)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_push_adagrad_close(mesh2):
    """Adagrad companion: the optimizer's sqrt/divide fuses differently
    under shard_map vs plain jit (1-ulp program variance, present in the
    legacy routed path too) — the exchange stays within float noise."""
    c = _cfg(optimizer="adagrad", learning_rate=0.05)
    store, ws = _ws(c, 60, mesh2)
    idx, grads, shows, clks = _push_operands(c, ws, seed=11)
    plan = _device_plans(idx, ws.padded_rows, 2)
    args = tuple(map(jnp.asarray, (idx, grads, shows, clks)))
    out = jax.jit(jax.shard_map(
        lambda t, i, g, sh, ck, *p: exchange.routed_push(
            t, i, g, sh, ck, c, ("dp",), 2.0, wire="f32", plan=p),
        mesh=mesh2, in_specs=(P("dp"),) * 10,
        out_specs=P("dp")))(ws.table, *args, *plan)
    want = np.asarray(sharded.push(ws.table, *args, c))
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5,
                               atol=1e-6)


@pytest.mark.parametrize("wire,rtol", [("bf16", 2e-2), ("int8", 2e-2)])
def test_push_wire_compression_bounded(mesh2, wire, rtol):
    """Compressed wires: grads round (bf16 mantissa / int8 per-lane
    scale) but show/clk counter increments stay EXACT — counters must
    never round."""
    c = _cfg()
    store, ws = _ws(c, 60, mesh2)
    idx, grads, shows, clks = _push_operands(c, ws, seed=13)
    plan = _device_plans(idx, ws.padded_rows, 2)
    args = tuple(map(jnp.asarray, (idx, grads, shows, clks)))
    out = np.asarray(jax.jit(jax.shard_map(
        lambda t, i, g, sh, ck, *p: exchange.routed_push(
            t, i, g, sh, ck, c, ("dp",), 2.0, wire=wire, plan=p),
        mesh=mesh2, in_specs=(P("dp"),) * 10,
        out_specs=P("dp")))(ws.table, *args, *plan))
    want = np.asarray(sharded.push(ws.table, *args, c))
    np.testing.assert_allclose(out, want, rtol=rtol, atol=rtol)
    # counters crossed the f32 side plane: bit-exact show/clk columns
    np.testing.assert_array_equal(out[:, :2], want[:, :2])


def test_select_wire_and_bytes():
    c = _cfg()
    old = flags.exchange_wire
    try:
        flags.exchange_wire = "auto"
        assert exchange.select_wire(c) == "bf16"
        assert exchange.select_wire(_cfg(storage="int8")) == "int8"
        flags.exchange_wire = "f32"
        assert exchange.select_wire(c) == "f32"
        flags.exchange_wire = "nope"
        with pytest.raises(ValueError, match="exchange_wire"):
            exchange.select_wire(c)
    finally:
        flags.exchange_wire = old
    # wire accounting: bf16 halves the grad plane, int8 quarters it
    f32b = exchange.push_wire_bytes(c, 100, "f32")
    bfb = exchange.push_wire_bytes(c, 100, "bf16")
    i8b = exchange.push_wire_bytes(c, 100, "int8")
    gw = c.grad_width
    assert f32b - bfb == 100 * 2 * gw
    assert f32b - i8b == 100 * (3 * gw - 4)     # minus the scale column
    assert exchange.pull_wire_bytes(c, 100) == 100 * (4 + 4 * c.pull_width)


# ---------------------------------------------------------------------------
# trainer engine
# ---------------------------------------------------------------------------

def _dataset(n_ex, num_slots=4, batch=32, seed=0, key_space=400,
             skew=False):
    schema = DataFeedSchema.ctr(num_sparse=num_slots, num_float=1,
                                batch_size=batch, max_len=1)
    rng = np.random.default_rng(seed)
    offs = np.arange(n_ex + 1, dtype=np.int64)
    if skew:
        # DISTINCT contiguous keys per batch: lands on 1-2 shards and
        # dedup cannot shrink it — the capacity worst case
        e = np.arange(n_ex, dtype=np.int64)
        sv = [(e // batch) * 100_000 + (e % batch) * num_slots + s
              for s in range(num_slots)]
    else:
        sv = [(rng.integers(0, key_space, size=n_ex)
               | (np.int64(s + 1) << 40)).astype(np.int64)
              for s in range(num_slots)]
    ds = SlotDataset(schema)
    ds.records = SlotRecordBatch(
        schema=schema, num=n_ex, sparse_values=sv,
        sparse_offsets=[offs.copy() for _ in range(num_slots)],
        float_values=[(rng.random(n_ex) < 0.3).astype(np.float32),
                      rng.normal(size=n_ex).astype(np.float32)],
        ins_id=np.zeros(n_ex, np.uint64),
        search_id=np.zeros(n_ex, np.uint64),
        rank=np.zeros(n_ex, np.int32), cmatch=np.zeros(n_ex, np.int32))
    return ds, schema


def _trainer(schema, mesh, **cfg_kw):
    store = HostEmbeddingStore(EmbeddingConfig(dim=4, learning_rate=0.05))
    cfg_kw.setdefault("global_batch_size", 32)
    return Trainer(DeepFMModel(num_slots=4, emb_dim=4, dense_dim=1,
                               hidden=(8,)),
                   store, schema, mesh, TrainerConfig(**cfg_kw))


@pytest.fixture
def sharded_flags():
    set_flags(table_layout="sharded", exchange_wire="f32")
    try:
        yield
    finally:
        set_flags(table_layout="auto", exchange_wire="auto")


def test_trainer_sharded_engine_end_to_end(mesh2, sharded_flags):
    """The sharded engine trains and evals on a 2-shard mesh: plan-keyed
    exchange engaged, traffic counters populated (dedup ratio < 1),
    flight record carrying the engine identity, zero drops."""
    ds, schema = _dataset(4 * 32)
    tr = _trainer(schema, mesh2)
    assert tr.table_layout == "sharded"
    assert tr.exchange_wire == "f32"
    assert tr._use_plan                      # plan-keyed a2a engaged
    h = monitor.hub()
    h.disable()
    ms = monitor.MemorySink()
    h.enable(ms)
    try:
        snap0 = monitor.STATS.snapshot()
        out = tr.train_pass(ds)
        flights = [r for r in ms.records
                   if r.get("type") == "flight_record"]
    finally:
        h.disable()
    assert out["routed_dropped"] == 0
    assert out["steps"] == 4
    snap = monitor.STATS.snapshot()
    toks = snap["exchange.tokens"] - snap0.get("exchange.tokens", 0)
    uniq = snap["exchange.unique_lanes"] - snap0.get(
        "exchange.unique_lanes", 0)
    assert toks == 4 * 32 * 4
    assert 0 < uniq <= toks
    assert snap["exchange.pull_bytes"] > snap0.get(
        "exchange.pull_bytes", 0)
    assert snap["exchange.push_bytes"] > snap0.get(
        "exchange.push_bytes", 0)
    # the engine identity + the exchange counters ride the flight record
    assert flights
    assert flights[-1]["extra"]["table_layout"] == "sharded"
    assert flights[-1]["extra"]["exchange_wire"] == "f32"
    assert flights[-1]["stats_delta"].get("exchange.tokens") == toks
    ev = tr.eval_pass(ds)
    assert ev["routed_dropped"] == 0
    assert np.isfinite(ev["auc"])


def test_trainer_sharded_emits_exchange_flow_points(mesh2, sharded_flags):
    """World trace (ISSUE 15): a traced pass on the sharded engine
    stamps one deterministic exchange flow point per step — the
    cross-rank edge anchor — with the wire identity riding along."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.monitor import flight
    ds, schema = _dataset(4 * 32)
    tr = _trainer(schema, mesh2)
    h = monitor.hub()
    h.disable()
    ms = monitor.MemorySink()
    prev = flags.trace
    flags.trace = True
    h.enable(ms)
    try:
        out = tr.train_pass(ds)
    finally:
        h.disable()
        flags.trace = prev
    flows = [r for r in ms.records if r.get("name") == "trace.flow"]
    ex = [r for r in flows
          if (r.get("fields") or {}).get("kind") == "exchange"]
    assert len(ex) == out["steps"]
    keys = [(r["fields"]["key"]) for r in ex]
    assert len(set(keys)) == len(keys)        # one per step, distinct
    assert all(k.startswith("p") and ".s" in k for k in keys)
    for r in ex:
        assert r["fields"]["wire"] == "f32"
        assert r["fields"]["tokens"] == 32 * 4
        assert r["fields"]["bytes_bound"] > 0
        assert r["trace_id"]                  # stamped, mergeable
        assert flight.validate_event(r) == []


def test_trainer_sharded_matches_single_shard_loss(mesh2, sharded_flags):
    """Same data through the 2-shard exchange engine and a single-shard
    trainer: losses agree to float tolerance (dense pmean over 2 devices
    reassociates the batch mean, so bitwise equality is not defined at
    trainer level — the op-level tests above carry the bitwise bar)."""
    ds, schema = _dataset(4 * 32, seed=2)
    tr2 = _trainer(schema, mesh2)
    out2 = tr2.train_pass(ds)
    set_flags(table_layout="auto")
    tr1 = _trainer(schema, make_mesh(1))
    assert tr1.table_layout == "single"
    out1 = tr1.train_pass(ds)
    assert out2["routed_dropped"] == 0
    np.testing.assert_allclose(out2["loss_mean"], out1["loss_mean"],
                               rtol=1e-4)
    np.testing.assert_allclose(out2["auc"], out1["auc"], atol=5e-3)


def test_overflow_never_silent_and_retry(mesh4, sharded_flags):
    """Capacity overflow accounting end to end: with the preplan off and
    a skewed pass, drops are counted (exchange.overflow_dropped), the
    capacity factor doubles, and the NEXT pass trains losslessly (the
    trainer-level retry at a larger factor). The eval pass retries IN
    PLACE: its returned numbers are from the lossless re-run."""
    old = flags.routed_capacity_preplan
    flags.routed_capacity_preplan = False
    try:
        ds, schema = _dataset(4 * 32, skew=True)
        tr = _trainer(schema, mesh4)
        snap0 = monitor.STATS.snapshot()
        with pytest.warns(UserWarning, match="exceeded all_to_all"):
            out = tr.train_pass(ds)
        assert out["routed_dropped"] > 0
        snap = monitor.STATS.snapshot()
        assert (snap.get("exchange.overflow_dropped", 0)
                - snap0.get("exchange.overflow_dropped", 0)) \
            == out["routed_dropped"]
        assert tr.cfg.capacity_factor == 4.0     # doubled
        with warnings.catch_warnings():
            warnings.simplefilter("error")       # retry pass: no drops
            out2 = tr.train_pass(ds)
        assert out2["routed_dropped"] == 0
        # eval overflow: fresh trainer at the small factor; the eval
        # pass must re-run itself and return LOSSLESS numbers
        tr_e = _trainer(schema, mesh4)
        r0 = monitor.STATS.snapshot().get("exchange.overflow_retries", 0)
        with pytest.warns(UserWarning, match="exceeded all_to_all"):
            ev = tr_e.eval_pass(ds)
        assert ev["routed_dropped"] == 0         # the RETURNED run is clean
        assert monitor.STATS.snapshot()["exchange.overflow_retries"] > r0
        # the retry window is a registered fault point
        tr_f = _trainer(schema, mesh4)
        faultpoint.arm("exchange.eval.pre_retry", "ioerror")
        try:
            with pytest.raises(faultpoint.FaultInjected):
                with pytest.warns(UserWarning):
                    tr_f.eval_pass(ds)
        finally:
            faultpoint.disarm()
    finally:
        flags.routed_capacity_preplan = old


def test_sharded_layout_forced_on_single_shard_raises():
    ds, schema = _dataset(32)
    set_flags(table_layout="sharded")
    try:
        with pytest.raises(ValueError, match="multi-device"):
            _trainer(schema, make_mesh(1))
    finally:
        set_flags(table_layout="auto")


# ---------------------------------------------------------------------------
# ShardedEmbeddingStore (the host plane of the partitioned table)
# ---------------------------------------------------------------------------

def test_sharded_store_protocol_parity():
    c = _cfg()
    ss = ShardedEmbeddingStore(c, 4)
    href = HostEmbeddingStore(c)
    keys = np.random.default_rng(1).choice(
        1 << 60, size=200, replace=False).astype(np.uint64)
    # deterministic per-key init: identical rows regardless of partition
    np.testing.assert_array_equal(ss.lookup_or_init(keys),
                                  href.lookup_or_init(keys))
    assert len(ss) == len(href) == 200
    owner = ss.shard_of(keys)
    assert owner.min() >= 0 and owner.max() < 4
    assert len(set(owner.tolist())) > 1          # really partitioned
    rows = ss.get_rows(keys)
    rows[:, 2] = 7.5
    ss.write_back(keys, rows)
    np.testing.assert_array_equal(ss.get_rows(keys)[:, 2], 7.5)
    # peek never grows
    ss.peek_rows(np.array([123456789], np.uint64))
    assert len(ss) == 200


def test_sharded_store_save_load_roundtrip(tmp_path):
    c = _cfg()
    ss = ShardedEmbeddingStore(c, 3)
    keys = np.arange(1, 101, dtype=np.uint64) * 0x1234567890ab
    ss.lookup_or_init(keys)
    ss.save_base(str(tmp_path))
    rows = ss.get_rows(keys)
    rows[:, 2] = 42.0
    ss.write_back(keys[:50], rows[:50])
    ss.save_delta(str(tmp_path))
    assert ss.save_seq == 1
    s2 = ShardedEmbeddingStore.load(str(tmp_path))
    assert len(s2) == 100 and s2.n_shards == 3
    np.testing.assert_array_equal(s2.get_rows(keys), ss.get_rows(keys))
    assert sorted(n for n in os.listdir(tmp_path)
                  if n.startswith("shard-")) == \
        ["shard-00", "shard-01", "shard-02"]


def test_sharded_store_crash_rolls_whole_save_back(tmp_path):
    """A kill before the top-level manifest commit (or mid shard loop)
    must leave the restore on the LAST COMMITTED save — orphaned newer
    shard files are invisible (the save_delta seq-commit discipline,
    lifted to the shard fan-out)."""
    c = _cfg()
    ss = ShardedEmbeddingStore(c, 2)
    keys = np.arange(1, 41, dtype=np.uint64) * 0x9876543210
    ss.lookup_or_init(keys)
    ss.save_base(str(tmp_path))
    base_rows = ss.get_rows(keys)
    rows = base_rows.copy()
    rows[:, 2] = 9.0
    ss.write_back(keys, rows)
    faultpoint.arm("exchange.store.pre_manifest", "ioerror")
    try:
        with pytest.raises(faultpoint.FaultInjected):
            ss.save_delta(str(tmp_path))
    finally:
        faultpoint.disarm()
    s2 = ShardedEmbeddingStore.load(str(tmp_path))
    np.testing.assert_array_equal(s2.get_rows(keys), base_rows)
    # mid-shard-loop kill: first shard's delta landed, second didn't
    ss2 = ShardedEmbeddingStore(c, 2)
    ss2.lookup_or_init(keys)
    ss2.save_base(str(tmp_path / "b"))
    r2 = ss2.get_rows(keys)
    r2[:, 2] = 11.0
    ss2.write_back(keys, r2)
    faultpoint.arm("exchange.store.pre_shard_save", "ioerror", after=1)
    try:
        with pytest.raises(faultpoint.FaultInjected):
            ss2.save_delta(str(tmp_path / "b"))
    finally:
        faultpoint.disarm()
    s3 = ShardedEmbeddingStore.load(str(tmp_path / "b"))
    # base state == the deterministic init rows the save captured
    np.testing.assert_array_equal(
        s3.get_rows(keys), ShardedEmbeddingStore(c, 2).lookup_or_init(keys))
    # a re-run of the interrupted save commits cleanly over the orphans
    ss2.write_back(keys, r2)
    ss2.save_delta(str(tmp_path / "b"))
    s4 = ShardedEmbeddingStore.load(str(tmp_path / "b"))
    np.testing.assert_array_equal(s4.get_rows(keys)[:, 2], 11.0)


def test_sharded_store_base_resave_crash_detected_loudly(tmp_path):
    """The documented caveat (HostEmbeddingStore.save_base, restated on
    the sharded wrapper): a BASE re-save into a directory already
    holding a chain, killed before the top manifest commit, resets the
    shard chains under a stale top manifest — load must fail LOUDLY
    (CheckpointCorruptError), never silently resurrect mixed state.
    Writers needing fall-back semantics rotate directories per base."""
    from paddlebox_tpu.utils.checkpoint import CheckpointCorruptError
    c = _cfg()
    ss = ShardedEmbeddingStore(c, 2)
    keys = np.arange(1, 31, dtype=np.uint64) * 0xabcdef
    ss.lookup_or_init(keys)
    ss.save_base(str(tmp_path))
    r = ss.get_rows(keys)
    r[:, 2] = 3.0
    ss.write_back(keys, r)
    ss.save_delta(str(tmp_path))
    faultpoint.arm("exchange.store.pre_manifest", "ioerror")
    try:
        with pytest.raises(faultpoint.FaultInjected):
            ss.save_base(str(tmp_path))      # re-save into the SAME dir
    finally:
        faultpoint.disarm()
    with pytest.raises(CheckpointCorruptError):
        ShardedEmbeddingStore.load(str(tmp_path))


def test_sharded_store_wrong_shard_count_rejected(tmp_path):
    c = _cfg()
    ss = ShardedEmbeddingStore(c, 2)
    ss.lookup_or_init(np.array([5, 6], np.uint64))
    ss.save_base(str(tmp_path))
    from paddlebox_tpu.utils.checkpoint import CheckpointCorruptError
    with pytest.raises(CheckpointCorruptError, match="shards"):
        ShardedEmbeddingStore(c, 4).restore(str(tmp_path))


def test_sharded_spill_substores_train_bit_identical(mesh2, sharded_flags,
                                                     tmp_path):
    """ISSUE 11 acceptance: a 2-shard ShardedEmbeddingStore whose
    sub-stores are SPILL-backed (memmap row file + pathologically tiny
    frequency-aware RAM cache) trains bit-identical to host-backed
    sub-stores through the sharded exchange engine — the tier is a
    storage choice, not a math change. Same compiled step, so the bar is
    exact bits on losses AND on every final store row, which pins the
    whole read/install/write-through/fault-in cycle."""
    from paddlebox_tpu.embedding.tiering import shard_store_factory
    ds, schema = _dataset(4 * 32, seed=5)
    results = {}
    for name in ("host", "spill"):
        factory = (None if name == "host" else shard_store_factory(
            tiering="spill", cache_rows=37,
            spill_dir=str(tmp_path / "spill")))
        store = ShardedEmbeddingStore(
            EmbeddingConfig(dim=4, learning_rate=0.05), 2,
            store_factory=factory)
        tr = Trainer(DeepFMModel(num_slots=4, emb_dim=4, dense_dim=1,
                                 hidden=(8,)),
                     store, schema, mesh2,
                     TrainerConfig(global_batch_size=32))
        assert tr.table_layout == "sharded"
        outs = [tr.train_pass(ds) for _ in range(2)]
        tr.flush_sparse()
        keys = np.sort(np.asarray(ds.unique_keys(), np.uint64))
        results[name] = (outs, store.get_rows(keys), tr)
    for p in range(2):
        np.testing.assert_array_equal(
            results["host"][0][p]["loss_mean"],
            results["spill"][0][p]["loss_mean"])
    np.testing.assert_array_equal(results["host"][1], results["spill"][1])
    # the spill tier really engaged: disk traffic + the tier identity
    spill_tr = results["spill"][2]
    assert spill_tr.table_tiering == "sharded+spill"
    subs = results["spill"][2].store._shards
    assert all(s.cache_misses > 0 for s in subs)
    assert all(s.spill_file_bytes > 0 for s in subs)


def test_sharded_store_spill_factory_checkpoint_roundtrip(tmp_path):
    """Spill-backed shards save/load through the per-shard chain dirs
    with the STREAMED payloads, and the loaded store reads back
    bit-identical through a fresh spill factory."""
    from paddlebox_tpu.embedding.tiering import shard_store_factory
    c = _cfg()
    mk = lambda sub: shard_store_factory(      # noqa: E731
        tiering="spill", cache_rows=13, spill_dir=str(tmp_path / sub))
    ss = ShardedEmbeddingStore(c, 2, store_factory=mk("a"))
    keys = np.arange(1, 301, dtype=np.uint64) * np.uint64(0x9E3779B9)
    ss.lookup_or_init(keys)
    ss.save_base(str(tmp_path / "ck"))
    rows = ss.get_rows(keys)
    rows[:, 2] = 6.5
    ss.write_back(keys[:150], rows[:150])
    ss.save_delta(str(tmp_path / "ck"))
    s2 = ShardedEmbeddingStore.load(str(tmp_path / "ck"),
                                    store_factory=mk("b"))
    assert s2.n_shards == 2
    np.testing.assert_array_equal(s2.get_rows(keys), ss.get_rows(keys))
    # really spill-backed on both sides
    assert all(s.spill_file_bytes > 0 for s in s2._shards)


def test_sharded_store_drives_working_set(mesh2):
    """Drop-in for the trainer stack: a pass working set builds from the
    sharded host store, trains nothing, and writes back through it."""
    c = _cfg()
    ss = ShardedEmbeddingStore(c, 2)
    mgr = FeedPassManager(ss, mesh2)
    keys = np.random.default_rng(2).choice(
        1 << 50, size=64, replace=False).astype(np.uint64)
    ws = mgr.begin_pass(keys)
    assert ws.num_keys == 64 and len(ss) == 64
    idx = ws.translate(keys)
    assert (idx > 0).all()
    mgr.end_pass(ws, ws.table)
    mgr.flush()
    np.testing.assert_array_equal(
        ss.get_rows(keys),
        np.asarray(ws.table)[idx.reshape(-1)][:, :c.row_width])
    mgr.close()


# ---------------------------------------------------------------------------
# self-adapting exchange (ISSUE 16): the D-way merge of the routed tail,
# the hierarchical topology, and the per-pass wire controller
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mesh4h():
    """2 hosts x 2 devices — the (node, dp) mesh the hier topology keys
    off (conftest forces 8 virtual CPU devices, so 4 are available)."""
    return make_mesh(4, num_nodes=2)


def test_merge_sorted_runs_matches_argsort_dedup():
    """The D-way merge is bit-equivalent to the stable-argsort dedup on
    row-wise ascending runs — including overflow-capped runs (ascending
    valid prefix + out-of-range pad tail, exactly what a capacity-capped
    receive buffer holds)."""
    rng = np.random.default_rng(17)
    for trial in range(10):
        D = int(rng.integers(2, 6))
        L = int(rng.integers(3, 40))
        runs = np.sort(rng.integers(0, 50, size=(D, L)), axis=1)
        if trial % 2:
            for r in range(D):          # capped run: pad tail stays sorted
                k = int(rng.integers(0, L + 1))
                runs[r, k:] = 64        # out-of-range, >= any valid row
        runs = jnp.asarray(np.ascontiguousarray(runs).astype(np.int32))
        u_m, inv_m = sharded.merge_sorted_runs(runs)
        u_a, inv_a = sharded.dedup_tokens(runs.reshape(-1))
        np.testing.assert_array_equal(np.asarray(u_m), np.asarray(u_a))
        np.testing.assert_array_equal(np.asarray(inv_m), np.asarray(inv_a))


def test_select_topology_resolution_and_errors():
    old = flags.exchange_topology
    try:
        flags.exchange_topology = "auto"
        assert exchange.select_topology((2,)) == "flat"
        assert exchange.select_topology((2, 2)) == "hier"
        assert exchange.select_topology((1, 4)) == "flat"   # degenerate axis
        flags.exchange_topology = "flat"
        assert exchange.select_topology((2, 2)) == "flat"
        flags.exchange_topology = "hier"
        assert exchange.select_topology((2, 2)) == "hier"
        with pytest.raises(ValueError, match="hier"):
            exchange.select_topology((4,))
        flags.exchange_topology = "ring"
        with pytest.raises(ValueError, match="exchange_topology"):
            exchange.select_topology((2, 2))
    finally:
        flags.exchange_topology = old


def test_hier_push_bit_identical_to_flat_and_single_shard(mesh4h):
    """The two-stage (intra-host shuffle, host-merge, inter-host) push
    over the f32 wire lands the exact bits of both the flat 4-way a2a
    and the single-shard scatter path — for the plan-keyed AND the
    planless (token-order) input."""
    c = _cfg()
    store, ws = _ws(c, 120, mesh4h)
    idx, grads, shows, clks = _push_operands(c, ws, n_tok=128, seed=21)
    plan = _device_plans(idx, ws.padded_rows, 4)
    args = tuple(map(jnp.asarray, (idx, grads, shows, clks)))
    axes = tuple(mesh4h.axis_names)

    def run(topology, use_plan):
        def body(tshard, i, g, sh, ck, *p):
            return exchange.routed_push(
                tshard, i, g, sh, ck, c, axes, 2.0, wire="f32",
                plan=p if use_plan else None, topology=topology)
        return np.asarray(jax.jit(jax.shard_map(
            body, mesh=mesh4h, in_specs=(P(axes),) * 10,
            out_specs=P(axes)))(ws.table, *args, *plan))

    want = np.asarray(sharded.push(ws.table, *args, c))
    for use_plan in (True, False):
        np.testing.assert_array_equal(run("flat", use_plan), want)
        np.testing.assert_array_equal(run("hier", use_plan), want)


@pytest.mark.parametrize("wire,rtol", [("bf16", 2e-2), ("int8", 2e-2)])
def test_hier_push_wire_compression_bounded(mesh4h, wire, rtol):
    """Compressed wires through the hier topology: grads round within
    the wire's tolerance, but the parity guard holds — show/clk counter
    columns cross the f32 side plane on BOTH legs and stay bit-exact."""
    c = _cfg()
    store, ws = _ws(c, 120, mesh4h)
    idx, grads, shows, clks = _push_operands(c, ws, n_tok=128, seed=23)
    plan = _device_plans(idx, ws.padded_rows, 4)
    args = tuple(map(jnp.asarray, (idx, grads, shows, clks)))
    axes = tuple(mesh4h.axis_names)
    out = np.asarray(jax.jit(jax.shard_map(
        lambda t, i, g, sh, ck, *p: exchange.routed_push(
            t, i, g, sh, ck, c, axes, 2.0, wire=wire, plan=p,
            topology="hier"),
        mesh=mesh4h, in_specs=(P(axes),) * 10,
        out_specs=P(axes)))(ws.table, *args, *plan))
    want = np.asarray(sharded.push(ws.table, *args, c))
    np.testing.assert_allclose(out, want, rtol=rtol, atol=rtol)
    np.testing.assert_array_equal(out[:, :2], want[:, :2])


def test_compress_push_side_plane_exact_on_every_wire():
    """The structural parity guard: whatever the wire does to the grad
    plane, the show/clk side plane survives compress->decompress
    bit-for-bit (int8 additionally rides its scale column there)."""
    rng = np.random.default_rng(29)
    gw = 5
    pay = jnp.asarray(rng.normal(size=(2, 16, gw + 2)).astype(np.float32))
    for wire in exchange.WIRES:
        planes = exchange._compress_push(pay, gw, wire)
        back = exchange._decompress_push(planes, wire)
        np.testing.assert_array_equal(np.asarray(back[..., gw:gw + 2]),
                                      np.asarray(pay[..., gw:gw + 2]))
        if wire != "f32":               # the grad plane really compressed
            assert planes[0].dtype != jnp.float32


def test_wire_cost_regimes_and_errors():
    c = _cfg()                          # grad_width 5
    # unique-heavy (depth ~1): bytes-bound, the narrow wire wins
    assert (exchange.wire_cost(c, 100, 100, "bf16")
            < exchange.wire_cost(c, 100, 100, "f32"))
    # duplication-heavy (depth 32): exposure-bound, the exact wire wins
    assert (exchange.wire_cost(c, 3200, 100, "f32")
            < exchange.wire_cost(c, 3200, 100, "bf16"))
    with pytest.raises(ValueError, match="wire"):
        exchange.wire_cost(c, 1, 1, "fp8")


def test_wire_controller_flips_within_hysteresis_no_flap():
    c = _cfg()
    ctl = exchange.WireController(c, "f32", hysteresis=2)
    for _ in range(3):                  # deep-dup regime: f32 optimal
        d = ctl.observe(3200, 100)
        assert d["wire"] == "f32" and d["reason"] == "optimal"
    # a single unique-heavy spike: challenger appears, hysteresis holds
    d = ctl.observe(100, 100)
    assert (d["candidate"] == "bf16" and not d["switched"]
            and d["wire"] == "f32" and d["streak"] == 1)
    # regime snaps back: the streak resets — no flap
    d = ctl.observe(3200, 100)
    assert d["reason"] == "optimal" and ctl.switches == 0
    # sustained drift: the flip lands on EXACTLY the hysteresis'th
    # consecutive challenger win, not earlier
    assert not ctl.observe(100, 100)["switched"]
    d = ctl.observe(100, 100)
    assert d["switched"] and d["wire"] == "bf16" and d["prev_wire"] == "f32"
    assert ctl.switches == 1 and ctl.wire == "bf16"


def test_wire_controller_holds_on_overflow_flow_and_silence():
    c = _cfg()
    ctl = exchange.WireController(c, "f32", hysteresis=1)
    assert ctl.observe(0, 0)["reason"] == "no-traffic"
    d = ctl.observe(100, 100, overflow_retries=1)
    assert not d["switched"] and d["reason"] == "overflow-hold"
    # flow attribution says the exchange edge is not the limiter: hold
    quiet = {"edges": 4, "by_kind": {"exchange": {"max_latency_s": 0.01}}}
    d = ctl.observe(100, 100, flow=quiet, wall_seconds=10.0)
    assert not d["switched"] and d["reason"] == "not-limiter"
    # no exchange edge at all in the attribution: same hold
    d = ctl.observe(100, 100, flow={"edges": 4, "by_kind": {}},
                    wall_seconds=10.0)
    assert d["reason"] == "not-limiter"
    # the limiter signal present: the switch proceeds (hysteresis=1)
    hot = {"edges": 4, "by_kind": {"exchange": {"max_latency_s": 5.0}}}
    d = ctl.observe(100, 100, flow=hot, wall_seconds=10.0)
    assert d["switched"] and d["wire"] == "bf16"


def test_trainer_adaptive_wire_end_to_end(mesh2):
    """flags.exchange_adaptive on a drifting stream: duplication-heavy
    passes hold f32, then unique-heavy passes flip the wire to bf16 on
    exactly the hysteresis'th pass after the drift; the switch emits the
    registered exchange_wire_adapted event, bumps the switch counter,
    and every pass's flight record carries the exchange_wire /
    exchange_topology / exchange_wire_next extras through the schema."""
    from paddlebox_tpu.monitor import flight
    set_flags(table_layout="sharded", exchange_wire="f32",
              exchange_adaptive=True)
    try:
        dup, schema = _dataset(4 * 32, key_space=1, seed=3)
        uni, _ = _dataset(4 * 32, key_space=1 << 30, seed=4)
        tr = _trainer(schema, mesh2)
        assert tr._wire_controller is not None
        assert tr.exchange_topology == "flat"    # 1-axis mesh
        h = monitor.hub()
        h.disable()
        ms = monitor.MemorySink()
        h.enable(ms)
        try:
            sw0 = monitor.STATS.snapshot().get("exchange.wire_switches", 0)
            for _ in range(2):                   # dup regime: f32 holds
                tr.train_pass(dup)
                assert tr.exchange_wire == "f32"
            wires = []
            for _ in range(3):                   # the drift
                tr.train_pass(uni)
                wires.append(tr.exchange_wire)
        finally:
            h.disable()
        # hysteresis=2: pass 1 after the drift challenges, pass 2 flips
        assert wires == ["f32", "bf16", "bf16"]
        ev = [r for r in ms.records
              if r.get("name") == "exchange_wire_adapted"]
        assert len(ev) == 1
        f = ev[0]["fields"]
        assert f["prev"] == "f32" and f["wire"] == "bf16"
        assert f["streak"] == 2 and set(f["costs"]) == set(exchange.WIRES)
        assert flight.validate_event(ev[0]) == []
        assert (monitor.STATS.snapshot()["exchange.wire_switches"]
                - sw0) == 1
        flights = [r for r in ms.records
                   if r.get("type") == "flight_record"]
        assert len(flights) == 5
        for r in flights:
            assert flight.validate_flight_record(r) == []
            assert r["extra"]["exchange_topology"] == "flat"
        # the record carries the pass's ACTIVE wire and the controller's
        # verdict for the next one — the flip pass shows the handover
        assert [r["extra"]["exchange_wire"] for r in flights] \
            == ["f32"] * 4 + ["bf16"]
        assert flights[3]["extra"]["exchange_wire_next"] == "bf16"
        assert flights[-1]["extra"]["exchange_wire_next"] == "bf16"
    finally:
        set_flags(table_layout="auto", exchange_wire="auto",
                  exchange_adaptive=False)


def test_adaptive_wire_via_boxps_end_pass(mesh2):
    """Fleet-driven scopes adapt at BoxPS.end_pass(trainer=...) — the
    boundary mirror of the tier re-evaluation — and surface the next
    wire in the end_pass dict."""
    from paddlebox_tpu.fleet.boxps import BoxPS
    set_flags(table_layout="sharded", exchange_wire="f32",
              exchange_adaptive=True)
    try:
        uni, schema = _dataset(2 * 32, key_space=1 << 30, seed=6)
        tr = _trainer(schema, mesh2)
        tr._wire_controller.hysteresis = 1       # flip on first evidence
        box = BoxPS(tr.store)
        box.begin_pass()
        tr.train_pass(uni, metrics=box.metrics)
        out = box.end_pass(trainer=tr)
        assert out["exchange_wire_next"] == "bf16"
        assert tr.exchange_wire == "bf16"
    finally:
        set_flags(table_layout="auto", exchange_wire="auto",
                  exchange_adaptive=False)
