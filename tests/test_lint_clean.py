"""Tier-1 lint gate: the shipped tree must be pblint-clean.

Runs the real CLI (``python -m paddlebox_tpu.analysis.lint``) over the
package exactly as CI/a reviewer would, and proves the linter needs no
jax (so the gate runs on a bare CPU box and cannot be taken down by an
accelerator-stack breakage). A violation landed by a future PR fails
HERE with the offending ``file:line rule`` on stdout — fix it or waive
it with a reason; reasonless waivers fail too (bad-waiver).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "paddlebox_tpu")


def _run_cli(*argv: str, env: dict | None = None):
    # PBTPU_NO_JAX: the gate is pure-host — paying a jax import per CLI
    # run would burn the tier-1 budget for nothing
    return subprocess.run(
        [sys.executable, "-m", "paddlebox_tpu.analysis.lint", *argv],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "PBTPU_NO_JAX": "1", **(env or {})})


def test_tree_is_lint_clean():
    """Zero unwaived findings over paddlebox_tpu/ — THE gate. No
    baseline is passed: the shipped tree must be clean outright."""
    proc = _run_cli("paddlebox_tpu")
    assert proc.returncode == 0, (
        "pblint found unwaived findings:\n" + proc.stdout + proc.stderr)
    assert "0 finding(s)" in proc.stdout


def test_lint_runs_without_jax():
    """The gate must not need the accelerator stack: block every jax/
    jaxlib import via a meta-path hook and run the full lint in-process.
    (This is why paddlebox_tpu/__init__ forgives ONLY a missing jax.)"""
    code = r"""
import sys


class _BlockJax:
    def find_spec(self, name, path=None, target=None):
        root = name.partition(".")[0]
        if root in ("jax", "jaxlib"):
            raise ModuleNotFoundError(f"{name} blocked by test",
                                      name=name)
        return None


sys.meta_path.insert(0, _BlockJax())
for mod in list(sys.modules):
    assert mod.partition(".")[0] not in ("jax", "jaxlib")

from paddlebox_tpu.analysis.lint import main

rc = main(["paddlebox_tpu"])
assert not any(m.partition(".")[0] in ("jax", "jaxlib")
               for m in sys.modules), "lint imported jax"
sys.exit(rc)
"""
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_shipped_baseline_is_empty_and_valid():
    """The incremental-adoption baseline ships empty: the tree is clean,
    and a future rule that is not yet clean records its debt here."""
    path = os.path.join(PKG, "analysis", "baseline.json")
    with open(path) as f:
        doc = json.load(f)
    assert doc["version"] == 1
    assert doc["findings"] == []
    assert len(doc["rules"]) >= 6


def test_every_shipped_rule_is_exercised_on_the_tree():
    """Each rule either fires-and-is-waived somewhere in the real tree or
    is provably active (the fixture suite covers firing; this covers the
    waiver inventory staying honest — every waiver names a live rule and
    a reason, enforced by bad-waiver inside the run itself)."""
    proc = _run_cli("paddlebox_tpu", "--show-waived", "--json")
    doc = json.loads(proc.stdout)
    assert doc["clean"] is True
    waived_rules = {w["rule"] for w in doc["waived"]}
    # the waiver inventory of this tree (see docs/INVARIANTS.md):
    # donefile mirror writes, legitimate silent-excepts, reserved flags,
    # the compaction staging write
    assert {"donefile-discipline", "silent-except", "flag-audit",
            "durable-write"} <= waived_rules
    assert all(w.get("reason") for w in doc["waived"])
