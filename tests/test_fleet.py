"""Serving fleet resilience (ISSUE 20): shared staging, health-aware
router, replica supervision, verdict-guarded auto-promotion — and the
fleet kill matrix.

The acceptance bar, each leg on REAL processes where a process boundary
is the claim:

- a replica hard-killed mid-swap (``serving.fleet.replica.pre_build``)
  drops out of rotation with ZERO failed requests; the supervisor
  restarts it and the fleet converges on the new version;
- a lease-holder hard-killed mid-download
  (``serving.fleet.lease.pre_verify``) leaves an expirable lease; a peer
  retakes it and the host ends with exactly ONE verified staging copy;
- a worse candidate publish is HELD fleet-wide by the doctor's
  version-regression verdict — auto-promotion never promotes it.

Router edge cases (satellite): shed is a named counted refusal (never a
hang); the one retry never lands on the replica that just timed out; a
hedge loser's result is discarded even when it completes after cancel;
an all-stale fleet degrades to the freshest replica with a
``fleet.serving_stale`` event.
"""

import json
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from paddlebox_tpu import monitor
from paddlebox_tpu.config import flags, set_flags
from paddlebox_tpu.monitor import flight
from paddlebox_tpu.serving.fleet import (FleetReplicaServer, LocalReplica,
                                         PromotionGovernor, ReplicaFleet,
                                         SharedStagingCache)
from paddlebox_tpu.serving.router import (Router, RouterShedError,
                                          RouterTimeoutError)
from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.fleet import BoxPS
from paddlebox_tpu.models import DeepFMModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.serving import ServingPublisher
from paddlebox_tpu.train import Trainer, TrainerConfig
from paddlebox_tpu.utils import checkpoint as ckpt_lib
from paddlebox_tpu.utils import faultpoint
from paddlebox_tpu.utils.checkpoint import CheckpointCorruptError

from test_serving import _WorsePredictor, _req_batch, job   # noqa: F401
from test_train_e2e import NUM_SLOTS, synth_dataset


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faultpoint.disarm()


@pytest.fixture()
def events():
    ms = monitor.MemorySink()
    monitor.hub().enable(ms)
    yield ms
    monitor.hub().disable()


@pytest.fixture()
def _fleet_flags():
    keys = ("serving_shadow", "serving_split_fraction", "serving_window_s",
            "serving_auto_promote", "serving_promote_windows",
            "serving_hedge_factor", "serving_fleet_replicas")
    saved = {k: flags.get(k) for k in keys}
    yield set_flags
    for k, v in saved.items():
        flags.set(k, v)


# ------------------------------------------------------------ registry


def test_fleet_points_closed_registry():
    """The fleet's crash windows are a closed, prefixed registry: a new
    point cannot appear without this matrix covering it."""
    assert set(faultpoint.FLEET_POINTS) <= set(faultpoint.POINTS)
    assert all(p.startswith("serving.fleet.")
               for p in faultpoint.FLEET_POINTS)
    assert set(faultpoint.FLEET_POINTS) == {
        "serving.fleet.lease.pre_verify",
        "serving.fleet.replica.pre_build",
        "serving.fleet.router.pre_dispatch"}
    assert not set(faultpoint.FLEET_POINTS) & (
        set(faultpoint.ELASTIC_POINTS) | set(faultpoint.ADMIT_POINTS)
        | set(faultpoint.SERVING_POINTS)
        | set(faultpoint.EXCHANGE_POINTS)
        | set(faultpoint.MONITOR_POINTS))


# ------------------------------------------------------------ staging


def _make_artifact(dirpath: str, payload: bytes = b"model-bytes") -> str:
    """A minimal manifest-committed artifact dir (the staging cache only
    cares about verify_manifest, not the member shapes)."""
    os.makedirs(dirpath, exist_ok=True)
    member = os.path.join(dirpath, "payload.bin")
    with ckpt_lib.atomic_file(member) as tmp:
        with open(tmp, "wb") as f:
            f.write(payload)
    ckpt_lib.write_manifest(
        dirpath, {"payload.bin": ckpt_lib.file_entry(member)})
    return dirpath


def _staged_versions(cache: SharedStagingCache) -> list[str]:
    return sorted(os.listdir(cache.versions_dir))


def test_staging_one_download_per_host(tmp_path):
    """N replicas (their own cache instances, one shared root) racing for
    the same version produce exactly ONE copy + verify."""
    src = _make_artifact(str(tmp_path / "pub" / "v-000001"))
    root = str(tmp_path / "staging")
    caches = [SharedStagingCache(root) for _ in range(4)]
    outs: list[str] = []

    def _go(c):
        outs.append(c.materialize(src))

    threads = [threading.Thread(target=_go, args=(c,)) for c in caches]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(set(outs)) == 1 and os.path.isdir(outs[0])
    ckpt_lib.verify_manifest(outs[0])
    assert sum(c.downloads for c in caches) == 1
    assert _staged_versions(caches[0]) == ["v-000001"]   # no tmp orphans
    # a later ask is a pure cache hit — no lease traffic
    before = caches[1].cache_hits
    assert caches[1].materialize(src) == outs[0]
    assert caches[1].cache_hits == before + 1


def test_staging_refuses_corrupt_artifact_and_releases_lease(tmp_path):
    src = _make_artifact(str(tmp_path / "pub" / "v-000002"))
    with open(os.path.join(src, "payload.bin"), "ab") as f:
        f.write(b"rot")                    # CRC mismatch vs manifest
    cache = SharedStagingCache(str(tmp_path / "staging"))
    with pytest.raises(CheckpointCorruptError):
        cache.materialize(src)
    assert _staged_versions(cache) == []   # nothing under the final name
    assert os.listdir(cache.leases_dir) == []   # lease released


def test_staging_stale_lease_expires_and_is_retaken(tmp_path, events):
    """A lease whose holder died (mtime frozen) is retaken after the
    TTL; the retaker materializes and the event names the takeover."""
    src = _make_artifact(str(tmp_path / "pub" / "v-000003"))
    cache = SharedStagingCache(str(tmp_path / "staging"),
                               lease_ttl_s=0.2)
    lease = cache._lease_path("v-000003")
    with open(lease, "w") as f:
        f.write("{}")                      # a dead holder's lease
    old = time.time() - 10
    os.utime(lease, (old, old))
    out = cache.materialize(src)
    ckpt_lib.verify_manifest(out)
    assert cache.lease_retakes == 1 and cache.downloads == 1
    retaken = events.find("fleet_lease_retaken")
    assert retaken and retaken[-1]["fields"]["version"] == "v-000003"


def test_lease_holder_killed_mid_download_is_retaken(tmp_path, events):
    """Kill-matrix leg: a REAL stager process dies at
    ``serving.fleet.lease.pre_verify`` (bytes staged, verify+rename not
    run). Its lease goes stale, a peer retakes it, and the host ends
    with exactly one verified copy and no torn bytes under the final
    name."""
    src = _make_artifact(str(tmp_path / "pub" / "v-000004"))
    staging = str(tmp_path / "staging")
    env = dict(os.environ)
    env.update({"PBTPU_FAULTPOINT": "serving.fleet.lease.pre_verify",
                "PBTPU_FAULTPOINT_ACTION": "kill",
                "JAX_PLATFORMS": "cpu"})
    proc = subprocess.run(
        [sys.executable, "-m", "paddlebox_tpu.serving.fleet",
         "unused-root", "--stage", src, "--staging-root", staging],
        env=env, capture_output=True, timeout=120)
    assert proc.returncode == 137, proc.stderr.decode()[-400:]
    assert b"FAULTPOINT KILL serving.fleet.lease.pre_verify" \
        in proc.stderr
    cache = SharedStagingCache(staging, lease_ttl_s=0.3)
    # the dead holder left its lease and its partial tmp behind
    assert os.path.exists(cache._lease_path("v-000004"))
    assert any(e.startswith(".tmp.v-000004.")
               for e in os.listdir(cache.versions_dir))
    time.sleep(0.35)                       # age the lease past the TTL
    out = cache.materialize(src)
    ckpt_lib.verify_manifest(out)
    assert cache.lease_retakes >= 1
    # exactly ONE verified copy; the orphaned tmp was swept
    assert _staged_versions(cache) == ["v-000004"]
    assert os.listdir(cache.leases_dir) == []
    assert events.find("fleet_lease_retaken")


# ------------------------------------------------------------ router


class _FakeReplica:
    """A scriptable replica handle: health + latency + result/failure."""

    def __init__(self, name, *, status="ok", building=False,
                 active_version=1, age_seconds=1.0, latency_s=0.0,
                 result=1.0, fail=None, hang=False, inflight=0):
        self.name = name
        self.quarantined = False
        self.status = status
        self.building = building
        self.active_version = active_version
        self.age_seconds = age_seconds
        self.latency_s = latency_s
        self.result = result
        self.fail = fail
        self.hang = hang
        self.inflight = inflight
        self.calls = 0

    def health(self):
        if self.status == "unreachable":
            raise ConnectionError(f"{self.name} is down")
        return {"status": self.status, "building": self.building,
                "active_version": self.active_version,
                "age_seconds": self.age_seconds}

    def submit(self, ids, mask, dense=None) -> Future:
        self.calls += 1
        fut: Future = Future()
        if self.hang:
            return fut                     # never resolves (cancellable)

        def _resolve():
            if self.fail is not None:
                fut.set_exception(self.fail)
            else:
                fut.set_result(self.result)
        if self.latency_s > 0:
            # the request is already in flight: cancel() must fail, so
            # the router's discard contract (late loser counted, never
            # surfaced) is what gets exercised
            fut.set_running_or_notify_cancel()
            threading.Timer(self.latency_s, _resolve).start()
        else:
            _resolve()
        return fut


def test_router_shed_is_named_counted_and_never_hangs(tmp_path):
    reps = [_FakeReplica("a", status="empty"),
            _FakeReplica("b", status="unreachable")]
    r = Router(reps, timeout_s=1.0, health_ttl_s=0.0)
    t0 = time.monotonic()
    with pytest.raises(RouterShedError, match="no serviceable replica"):
        r.score([1], [True])
    assert time.monotonic() - t0 < 2.0     # refusal, not a hang
    s = r.stats()
    assert s["sheds"] == 1 and s["requests"] == 1
    assert reps[0].calls == reps[1].calls == 0


def test_router_retry_never_lands_on_the_timed_out_replica():
    slow = _FakeReplica("slow", hang=True, inflight=0)
    fast = _FakeReplica("fast", result=7.0, inflight=5)
    r = Router([slow, fast], timeout_s=0.2, health_ttl_s=10.0)
    out = r.score([1], [True])             # least-loaded picks `slow`
    assert out == 7.0
    assert slow.calls == 1 and fast.calls == 1
    s = r.stats()
    assert s["timeouts"] == 1 and s["retries"] == 1
    assert s["failures"] == 0


def test_router_drains_a_building_replica():
    building = _FakeReplica("building", building=True, result=0.0)
    serving = _FakeReplica("serving", result=3.0)
    r = Router([building, serving], health_ttl_s=10.0)
    for _ in range(10):
        assert r.score([1], [True]) == 3.0
    assert building.calls == 0 and serving.calls == 10


def test_router_all_stale_degrades_to_freshest_with_event(events):
    older = _FakeReplica("older", status="stale", age_seconds=100.0,
                         result=1.0)
    fresher = _FakeReplica("fresher", status="stale", age_seconds=5.0,
                           result=2.0)
    r = Router([older, fresher], health_ttl_s=10.0)
    assert r.score([1], [True]) == 2.0     # freshest stale replica
    assert fresher.calls == 1 and older.calls == 0
    assert r.stats()["degraded_dispatches"] == 1
    ev = events.find("fleet.serving_stale")
    assert ev and ev[-1]["fields"]["chosen"] == "fresher"


def test_router_all_building_falls_back_instead_of_shedding():
    """Draining is a preference: when EVERY replica is mid-build, the
    freshest one (its active version still serves; swap is atomic) takes
    the request — a shed here would fail traffic the fleet can answer."""
    b1 = _FakeReplica("b1", building=True, result=1.0, age_seconds=2.0)
    b2 = _FakeReplica("b2", building=True, result=2.0, age_seconds=9.0)
    r = Router([b1, b2], health_ttl_s=10.0)
    assert r.score([1], [True]) == 1.0
    s = r.stats()
    assert s["degraded_dispatches"] == 1 and s["sheds"] == 0


def test_router_hedge_first_wins_loser_cancelled_and_discarded():
    slow = _FakeReplica("slow", latency_s=0.5, result=1.0, inflight=0)
    fast = _FakeReplica("fast", latency_s=0.01, result=2.0, inflight=3)
    r = Router([slow, fast], timeout_s=5.0, health_ttl_s=10.0,
               hedge_factor=1.0, hedge_min_count=5)
    for _ in range(10):                    # seed the p99 the threshold
        r._lat_svc.add(10.0)               # derives from (~10ms)
    out = r.score([1], [True])
    # primary went to `slow` (least loaded); the hedge fired past the
    # threshold, landed on `fast`, and its answer won
    assert out == 2.0
    assert slow.calls == 1 and fast.calls == 1
    s = r.stats()
    assert s["hedges"] == 1 and s["hedges_won"] == 1
    assert s["retries"] == 0 and s["failures"] == 0
    # the loser completes AFTER cancel — its late result is discarded
    # (counted), never surfaced to any caller
    deadline = time.monotonic() + 2.0
    while (r.stats()["hedge_discards"] < 1
           and time.monotonic() < deadline):
        time.sleep(0.02)
    assert r.stats()["hedge_discards"] == 1


def test_router_pre_dispatch_ioerror_retries_elsewhere():
    """The ioerror leg of serving.fleet.router.pre_dispatch: a faulted
    primary dispatch is retried on a DIFFERENT replica, the caller sees
    only the answer."""
    a = _FakeReplica("a", result=1.0)
    b = _FakeReplica("b", result=1.0)
    r = Router([a, b], health_ttl_s=10.0)
    faultpoint.arm("serving.fleet.router.pre_dispatch", "ioerror")
    try:
        assert r.score([1], [True]) == 1.0
    finally:
        faultpoint.disarm()
    # the fault fired BEFORE the submit — the primary target got no
    # request; the retry landed on the other replica
    assert a.calls + b.calls == 1
    s = r.stats()
    assert s["retries"] == 1 and s["failures"] == 0


# --------------------------------------------------- fleet flight record


def _fleet_fields(**over):
    fields = {"window_s": 10.0, "replicas": 2, "healthy": 2,
              "quarantined": 0, "requests": 100, "sheds": 0,
              "retries": 1, "hedges": 3, "hedges_won": 2, "restarts": 0,
              "promote_holds": 0, "p50_ms": 2.0, "p99_ms": 9.0}
    fields.update(over)
    return fields


def test_fleet_record_schema_negatives(events):
    monitor.event("fleet_window", type="fleet_record", **_fleet_fields())
    rec = events.find("fleet_window")[-1]
    assert flight.validate_fleet_record(rec) == []
    bad = dict(rec)
    bad["fields"] = {k: v for k, v in rec["fields"].items()
                     if k != "healthy"}
    assert any("healthy" in e for e in flight.validate_fleet_record(bad))
    bad = dict(rec, fields=dict(rec["fields"], retries="three"))
    assert any("retries" in e for e in flight.validate_fleet_record(bad))
    bad = dict(rec, fields=dict(rec["fields"], sheds=True))
    assert any("sheds" in e for e in flight.validate_fleet_record(bad))
    # cross-field: more healthy replicas than replicas is nonsense
    bad = dict(rec, fields=dict(rec["fields"], healthy=5))
    assert any("healthy" in e for e in flight.validate_fleet_record(bad))


def test_fleet_record_rides_events_file_validation(tmp_path):
    envelope = {"ts": 1.0, "name": "fleet_window", "type": "fleet_record",
                "pass_id": None, "step": None, "phase": None,
                "thread": "MainThread"}
    good = dict(envelope, fields=_fleet_fields())
    bad = dict(envelope,
               fields={k: v for k, v in _fleet_fields().items()
                       if k != "p99_ms"})
    p = tmp_path / "events.jsonl"
    p.write_text(json.dumps(good) + "\n" + json.dumps(bad) + "\n")
    out = flight.validate_events_file(str(p))
    assert out["events"] == 2
    assert len(out["errors"]) == 1 and "p99_ms" in out["errors"][0]


# ------------------------------------------- verdict-guarded promotion


def _window_with_labels(srv, ids, mask, dense):
    """Serve a batch, join delayed labels that perfectly separate the
    STABLE scores, and commit the window (the test_serving shadow
    pattern: identical candidate → identical AUC; worse candidate →
    anti-correlated scores → AUC gap)."""
    served = srv.predict(ids, mask, dense)
    labels = (np.asarray(served) >
              np.median(served)).astype(np.float64).reshape(-1)
    srv.observe_labels(labels)
    return srv.commit_window(force=True)


def test_governor_disabled_and_no_candidate(_fleet_flags):
    gov = PromotionGovernor([])
    assert gov.observe({"candidate_version": 2}) == "disabled"
    _fleet_flags(serving_auto_promote=True)
    assert gov.observe({}) == "no-candidate"


def test_governor_holds_worse_candidate_fleet_wide(job, _fleet_flags,
                                                   events):
    """Kill-matrix leg: an injected-WORSE candidate's window fires the
    doctor's version-regression verdict critical — the governor HOLDS it
    fleet-wide and quarantines the version; no replica ever promotes."""
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)            # v1 (stable)
    _fleet_flags(serving_shadow=True, serving_auto_promote=True,
                 serving_promote_windows=2)
    servers = [FleetReplicaServer(root) for _ in range(2)]
    for s in servers:
        s.poll_once()
    pub.publish(store, tr.eval_params(), pass_id=1)    # v2 candidate
    for s in servers:
        assert s.poll_once() == 1 and s.candidate.version == 2
    gov = PromotionGovernor(
        [LocalReplica(f"r{i}", s, None) for i, s in enumerate(servers)])
    lead = servers[0]
    lead._candidate.predictor = _WorsePredictor(lead._candidate.predictor)
    ids, mask, dense = _req_batch(ds)
    fields = _window_with_labels(lead, ids, mask, dense)
    assert gov.observe(fields) == "hold"
    assert gov.held_versions == {2} and gov.promote_holds == 1
    # a later clean-looking window cannot resurrect a quarantined
    # version — the hold is checked before the rule ever runs again
    assert gov.observe(fields) == "held"
    for s in servers:
        assert s.active.version == 1 and s.candidate is not None
    hold = events.find("fleet_promote_hold")
    assert hold and hold[-1]["fields"]["version"] == 2
    assert hold[-1]["fields"]["rule"] == "version-regression"
    quar = events.find("fleet_version_quarantined")
    assert quar and quar[-1]["fields"]["version"] == 2
    assert not events.find("fleet_promoted")


def test_governor_promotes_after_k_clean_windows(job, _fleet_flags,
                                                 events):
    """The positive path: a byte-identical candidate scores K = 2
    consecutive clean windows and the governor promotes it on EVERY
    replica (one clean window must not suffice)."""
    ds, schema, store, model, tr, box, pub, root = job
    box.end_pass(trainer=tr, publisher=pub)            # v1
    _fleet_flags(serving_shadow=True, serving_auto_promote=True,
                 serving_promote_windows=2)
    servers = [FleetReplicaServer(root) for _ in range(2)]
    for s in servers:
        s.poll_once()
    pub.publish(store, tr.eval_params(), pass_id=1)    # identical v2
    for s in servers:
        assert s.poll_once() == 1
    gov = PromotionGovernor(
        [LocalReplica(f"r{i}", s, None) for i, s in enumerate(servers)])
    lead = servers[0]
    ids, mask, dense = _req_batch(ds)
    assert gov.observe(_window_with_labels(lead, ids, mask, dense)) \
        == "clean"
    for s in servers:                  # one clean window: nothing moves
        assert s.active.version == 1
    assert gov.observe(_window_with_labels(lead, ids, mask, dense)) \
        == "promoted"
    for s in servers:
        assert s.active.version == 2 and s.candidate is None
    promoted = events.find("fleet_promoted")
    assert promoted and promoted[-1]["fields"]["version"] == 2
    assert promoted[-1]["fields"]["replicas_promoted"] == 2
    assert gov.promote_holds == 0 and not events.find("fleet_promote_hold")


# --------------------------------------- the fleet kill matrix (leg 1)


def _rh(rep) -> dict:
    try:
        return rep.health()
    except Exception:   # noqa: BLE001 — "unreachable" during the wait
        return {}


def _wait(cond, timeout_s: float, what: str):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out after {timeout_s}s waiting for {what}")


@pytest.fixture()
def fleet_job(tmp_path):
    """One trained pass publishing EVERY version as a base, so a
    restarted replica cold-starts straight onto the newest version (one
    pre_build window) instead of replaying a delta chain through the
    very window that killed it."""
    ds, schema = synth_dataset(256)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, learning_rate=0.15))
    model = DeepFMModel(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1,
                        hidden=(16,))
    tr = Trainer(model, store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=64, dense_lr=3e-3))
    box = BoxPS(store)
    root = str(tmp_path / "serve")
    pub = ServingPublisher(root, model, schema, publish_base_every=1,
                           quant="f32", hot_top_k=16)
    box.begin_pass()
    tr.train_pass(ds)
    return ds, tr, box, pub, root


@pytest.mark.slow
def test_replica_killed_mid_swap_routes_around(fleet_job, tmp_path,
                                               events):
    """Kill-matrix leg: replica 0 (a REAL subprocess) is hard-killed at
    ``serving.fleet.replica.pre_build`` when v2 arrives. The router
    routes around it — ZERO failed requests under continuous load — the
    supervisor restarts it, and the fleet converges on v2 with exactly
    one verified staging copy per version on the host."""
    ds, tr, box, pub, root = fleet_job
    box.end_pass(trainer=tr, publisher=pub)            # v1 (base)
    kill_env = {"PBTPU_FAULTPOINT": "serving.fleet.replica.pre_build",
                "PBTPU_FAULTPOINT_AFTER": "1",     # hit #1 = the v1
                "JAX_PLATFORMS": "cpu"}            # build; #2 = v2 kills
    fleet = ReplicaFleet(
        root, replicas=2, workdir=str(tmp_path / "fw"),
        staging_root=str(tmp_path / "fw" / "staging"),
        poll_s=0.1, backoff0_s=0.2, supervise_tick_s=0.05, window_s=0,
        replica_env=lambda i: (kill_env if i == 0
                               else {"JAX_PLATFORMS": "cpu"}))
    router = Router(fleet.replicas, timeout_s=120.0, health_ttl_s=0.5)
    fleet.attach_router(router)
    fleet.start()
    errors: list = []
    stop = threading.Event()
    try:
        _wait(lambda: all(_rh(r).get("active_version") == 1
                          for r in fleet.replicas),
              120, "both replicas serving v1")
        ids, mask, dense = _req_batch(ds)
        router.score(ids, mask, dense)     # warm both ends' compile

        def hammer():
            while not stop.is_set():
                try:
                    router.score(ids, mask, dense)
                except Exception as e:   # noqa: BLE001 — the assertion
                    errors.append(e)     # target: must stay empty

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        [t.start() for t in threads]
        try:
            box.begin_pass()
            tr.train_pass(ds)
            box.end_pass(trainer=tr, publisher=pub)    # v2: replica 0
            _wait(lambda: 137 in fleet.replicas[0].exits,   # dies here
                  120, "replica-0 faultpoint kill")
            _wait(lambda: all(r.alive()
                              and _rh(r).get("active_version") == 2
                              and _rh(r).get("status") == "ok"
                              for r in fleet.replicas),
                  180, "fleet convergence on v2")
            router.score(ids, mask, dense)
        finally:
            stop.set()
            [t.join(timeout=60) for t in threads]
        assert not errors, errors[:3]
        assert fleet.restarts >= 1
        assert not fleet.replicas[0].quarantined
        assert events.find("fleet_replica_restart")
        # one verified staging copy per version, no tmp orphans
        staged = sorted(os.listdir(
            os.path.join(fleet.staging_root, "versions")))
        assert staged == ["v-000001", "v-000002"]
        rs = router.stats()
        assert rs["requests"] > 0
        assert rs["failures"] == 0 and rs["sheds"] == 0
        fields = fleet.commit_window(force=True)
        assert fields["healthy"] == 2 and fields["restarts"] >= 1
        rec = events.find("fleet_window")[-1]
        assert flight.validate_fleet_record(rec) == []
    finally:
        stop.set()
        fleet.stop()
