"""Quantized embedx storage (EmbeddingConfig.storage = int8/int16).

Reference: Quant/ShowClk feature-type pull variants dequantize embedx at
the pull (box_wrapper.cu:35-432); here the device working set stores the
embedx plane quantized with a per-row scale and computes in f32
(embedding/quant.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.data import DataFeedSchema
from paddlebox_tpu.data.parser import parse_multislot_lines
from paddlebox_tpu.data.dataset import SlotDataset
from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                     PassWorkingSet, quant, sharded)
from paddlebox_tpu.models import DNNCTRModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig


def _rows(cfg, n, seed=0):
    rng = np.random.default_rng(seed)
    rows = rng.normal(size=(n, cfg.row_width)).astype(np.float32) * 0.05
    rows[:, 0] = rng.integers(0, 50, n)       # shows
    rows[:, 1] = rng.integers(0, 5, n)        # clks
    return rows


@pytest.mark.parametrize("storage", ["int8", "int16"])
def test_encode_decode_roundtrip(storage):
    cfg = EmbeddingConfig(dim=8, storage=storage)
    rows = _rows(cfg, 64)
    fp, qx = quant.encode_rows_np(rows, cfg)
    assert qx.dtype == np.dtype(storage)
    back = quant.decode_rows_np(fp, qx, cfg)
    # counters/w/opt state exact; embedx within one quantization step
    np.testing.assert_array_equal(back[:, :3], rows[:, :3])
    np.testing.assert_array_equal(back[:, cfg.opt_cols], rows[:, cfg.opt_cols])
    scale = fp[:, -1]
    err = np.abs(back[:, cfg.embedx_cols] - rows[:, cfg.embedx_cols])
    assert (err <= 0.5 * scale[:, None] + 1e-9).all()


def test_lookup_dequantizes(storage="int16"):
    cfg = EmbeddingConfig(dim=8, storage=storage)
    rows = _rows(cfg, 128)
    table = quant.device_table(rows, cfg, None)
    idx = jnp.asarray(np.arange(128, dtype=np.int32))
    pulled = np.asarray(sharded.lookup(table, idx, cfg))
    np.testing.assert_allclose(pulled[:, :3], rows[:, :3], rtol=1e-6)
    np.testing.assert_allclose(pulled[:, 3:], rows[:, cfg.embedx_cols],
                               atol=np.abs(rows[:, cfg.embedx_cols]
                                           ).max() / 30000)


def test_push_parity_with_f32():
    """Several update steps on int16 storage track the f32 table closely
    (exact f32 optimizer math between dequant/requant)."""
    f32 = EmbeddingConfig(dim=8, learning_rate=0.1)
    q16 = EmbeddingConfig(dim=8, learning_rate=0.1, storage="int16")
    rows = _rows(f32, 256, seed=3)
    t_f = jnp.asarray(rows)
    t_q = quant.device_table(rows, q16, None)
    rng = np.random.default_rng(0)
    push_f = jax.jit(lambda t, i, g, s, c: sharded.push(t, i, g, s, c, f32))
    push_q = jax.jit(lambda t, i, g, s, c: sharded.push(t, i, g, s, c, q16))
    for step in range(5):
        idx = jnp.asarray(rng.integers(1, 256, 64).astype(np.int32))
        g = jnp.asarray(0.1 * rng.normal(size=(64, f32.grad_width))
                        .astype(np.float32))
        s = jnp.ones(64, jnp.float32)
        c = jnp.zeros(64, jnp.float32)
        t_f = push_f(t_f, idx, g, s, c)
        t_q = push_q(t_q, idx, g, s, c)
    final_q = quant.decode_rows_np(np.asarray(t_q.fp), np.asarray(t_q.qx),
                                   q16)
    final_f = np.asarray(t_f)
    np.testing.assert_array_equal(final_q[:, :3], final_f[:, :3])
    np.testing.assert_allclose(final_q[:, q16.opt_cols],
                               final_f[:, f32.opt_cols], atol=1e-5)
    emb_err = np.abs(final_q[:, q16.embedx_cols]
                     - final_f[:, f32.embedx_cols])
    assert emb_err.max() < 5e-4, emb_err.max()


def test_untouched_rows_keep_exact_bits():
    """Rows no batch referenced must not be re-rounded by the pass."""
    cfg = EmbeddingConfig(dim=4, storage="int8", learning_rate=0.1)
    rows = _rows(cfg, 64, seed=9)
    t = quant.device_table(rows, cfg, None)
    qx0 = np.asarray(t.qx).copy()
    fp0 = np.asarray(t.fp).copy()
    idx = jnp.asarray(np.array([5, 9], np.int32))
    g = jnp.asarray(0.5 * np.ones((2, cfg.grad_width), np.float32))
    t = sharded.push(t, idx, g, jnp.ones(2), jnp.zeros(2), cfg)
    untouched = np.setdiff1d(np.arange(64), [5, 9])
    np.testing.assert_array_equal(np.asarray(t.qx)[untouched],
                                  qx0[untouched])
    np.testing.assert_array_equal(np.asarray(t.fp)[untouched],
                                  fp0[untouched])
    assert not np.array_equal(np.asarray(t.fp)[[5, 9]], fp0[[5, 9]])


NUM_SLOTS = 4


def _ds(n, seed=0):
    rng = np.random.default_rng(seed)
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=1,
                                batch_size=64, max_len=2)
    w = np.random.default_rng(21).normal(size=(NUM_SLOTS, 4000)) * 1.5
    lines = []
    for _ in range(n):
        logits, parts, sl = 0.0, [], []
        for s in range(NUM_SLOTS):
            ids = rng.integers(0, 4000, size=2)
            sl.append(ids)
            logits += w[s, ids].sum()
        p = 1 / (1 + np.exp(-logits * 0.6))
        parts.append(f"1 {float(rng.random() < p)}")
        parts.append(f"1 {rng.normal():.3f}")
        for s, ids in enumerate(sl):
            parts.append(
                f"2 {' '.join(str(int(i) + s * 1000003) for i in ids)}")
        lines.append(" ".join(parts))
    ds = SlotDataset(schema)
    ds.records = parse_multislot_lines(lines, schema)
    return ds, schema


def test_trainer_e2e_quant_storage_close_to_f32():
    """Full sharded training with int16 storage matches f32 AUC/loss
    within tolerance; boundary transfers shrink accordingly."""
    ds, schema = _ds(512)
    mesh = make_mesh(8)
    out = {}
    for storage in ("f32", "int16"):
        store = HostEmbeddingStore(
            EmbeddingConfig(dim=4, learning_rate=0.15, storage=storage))
        tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4,
                                 dense_dim=1, hidden=(16,)),
                     store, schema, mesh,
                     TrainerConfig(global_batch_size=64, dense_lr=5e-3,
                                   auc_buckets=1 << 10))
        r1 = tr.train_pass(ds)
        r2 = tr.train_pass(ds)
        out[storage] = (r1, r2, tr.feed_mgr.last_h2d_bytes)
    for i in range(2):
        assert out["int16"][i]["loss_mean"] == pytest.approx(
            out["f32"][i]["loss_mean"], abs=5e-3)
        assert out["int16"][i]["auc"] == pytest.approx(
            out["f32"][i]["auc"], abs=0.02)
    # learning sanity on AUC, not loss_mean: the pass-1→2 CVM counter
    # jump (all-zero → populated; clk carries the label for these
    # near-singleton keys) transiently raises log-loss while ranking
    # improves — see ROADMAP "pass-2 loss signature" root cause.
    assert out["int16"][1]["auc"] > out["int16"][0]["auc"] + 0.1
    # pass-2 boundary H2D for int16 is smaller than f32's
    assert out["int16"][2] < out["f32"][2]


def test_quant_checkpoint_roundtrip_keeps_f32_host():
    """The host store stays f32 regardless of device storage: save/load
    reproduces trained values (within quant tolerance of the device)."""
    ds, schema = _ds(128)
    mesh = make_mesh(4)
    store = HostEmbeddingStore(
        EmbeddingConfig(dim=4, learning_rate=0.15, storage="int16"))
    tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                             hidden=(16,)),
                 store, schema, mesh,
                 TrainerConfig(global_batch_size=64, auc_buckets=1 << 8))
    tr.train_pass(ds)
    keys = ds.unique_keys()
    rows = store.get_rows(keys)              # flush hook fires
    assert rows[:, 0].sum() > 0              # shows accumulated
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        store.save_base(os.path.join(d, "b"))
        loaded = HostEmbeddingStore.load(os.path.join(d, "b"))
        np.testing.assert_array_equal(loaded.get_rows(keys), rows)
