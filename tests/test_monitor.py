"""Telemetry hub: context propagation, per-pass deltas, sink isolation,
Prometheus exposition, flight-record schema against a real 2-pass run,
and the disabled-path cost contract."""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pytest

from paddlebox_tpu import monitor
from paddlebox_tpu.monitor import context as mon_ctx
from paddlebox_tpu.monitor import flight
from paddlebox_tpu.monitor.registry import STATS


@pytest.fixture(autouse=True)
def _clean_hub():
    """Every test starts with a disabled hub and no open pass, and leaves
    it that way (the hub is a process singleton — leaks poison the suite
    exactly like leaked threads)."""
    h = monitor.hub()
    h.disable()
    h.abort_pass(reason="test setup")
    yield
    h.abort_pass(reason="test teardown")
    h.disable()


# ---------------------------------------------------------------------------
# context propagation
# ---------------------------------------------------------------------------

def test_context_propagates_into_spawned_threads():
    h = monitor.hub()
    ms = monitor.MemorySink()
    h.enable(ms)
    try:
        h.begin_pass(11, phase=1)
        mon_ctx.set_step(3)

        def worker():
            monitor.event("from_worker", x=1)

        t = mon_ctx.spawn(worker, name="ctx-worker")
        t.start(); t.join()
        # a plainly-created thread resolves the pass too (global fallback)
        t2 = threading.Thread(target=worker)
        t2.start(); t2.join()
        # step advanced AFTER the threads were created must be visible to
        # a thread spawned earlier (the context object is shared, mutable)
        seen = []
        start = threading.Event()
        go = threading.Event()

        def late_reader():
            start.set()
            go.wait(5)
            seen.append(mon_ctx.current().tags())

        t3 = mon_ctx.spawn(late_reader, name="late-reader")
        t3.start(); start.wait(5)
        mon_ctx.set_step(99)
        go.set(); t3.join()
        h.end_pass()
    finally:
        h.disable()
    evs = ms.find("from_worker")
    assert len(evs) == 2
    for e in evs:
        assert e["pass_id"] == 11 and e["step"] == 3 and e["phase"] == 1
        assert e["thread"] != "MainThread"
    assert seen == [{"pass_id": 11, "step": 99, "phase": 1}]
    # scope closed: events outside a pass carry nulls
    ms2 = monitor.MemorySink()
    h.enable(ms2)
    monitor.event("outside")
    h.disable()
    assert ms2.records[-1]["pass_id"] is None


def test_nested_scope_restores_outer():
    h = monitor.hub()
    h.begin_pass(1)
    handle = mon_ctx.enter_pass(2)
    assert mon_ctx.current().pass_id == 2
    mon_ctx.exit_pass(handle)
    assert mon_ctx.current().pass_id == 1
    h.end_pass()
    assert mon_ctx.current().pass_id is None


# ---------------------------------------------------------------------------
# per-pass counter deltas vs cumulative STATS
# ---------------------------------------------------------------------------

def test_flight_record_stats_delta_vs_cumulative():
    h = monitor.hub()
    monitor.counter_add("t.mon.delta", 10)       # before the pass
    h.begin_pass(21)
    monitor.counter_add("t.mon.delta", 3)
    monitor.counter_add("t.mon.fresh", 2)
    rec = h.end_pass()
    assert rec["pass_id"] == 21
    # delta since pass start, NOT the cumulative value
    assert rec["stats_delta"]["t.mon.delta"] == 3
    assert rec["stats_delta"]["t.mon.fresh"] == 2
    assert STATS.get("t.mon.delta") == 13        # cumulative untouched
    # untouched counters don't clutter the record
    assert "t.mon.delta" in rec["stats_delta"]
    h.begin_pass(22)
    rec2 = h.end_pass()
    assert "t.mon.delta" not in rec2["stats_delta"]


def test_record_train_accumulates_across_trainers():
    """Phased programs run several train_passes inside one box pass; the
    flight record must carry the sum."""
    h = monitor.hub()
    h.begin_pass(31)
    h.record_train(stage_seconds={"train": 1.0}, steps=4, examples=64,
                   seconds=2.0)
    h.record_train(stage_seconds={"train": 0.5, "auc": 0.25}, steps=2,
                   examples=32, seconds=1.0)
    rec = h.end_pass()
    assert rec["steps"] == 6 and rec["examples"] == 96
    assert rec["stage_seconds"]["train"] == pytest.approx(1.5)
    assert rec["stage_seconds"]["auc"] == pytest.approx(0.25)
    assert rec["train_seconds"] == pytest.approx(3.0)


# ---------------------------------------------------------------------------
# sink error isolation
# ---------------------------------------------------------------------------

class _BoomSink(monitor.Sink):
    def __init__(self):
        self.calls = 0

    def emit(self, rec):
        self.calls += 1
        raise RuntimeError("sink boom")


def test_failing_sink_never_kills_training_and_is_detached():
    h = monitor.hub()
    boom = _BoomSink()
    ms = monitor.MemorySink()
    h.enable(boom, ms)
    try:
        for i in range(10):
            monitor.event("tick", i=i)   # must never raise
    finally:
        # read health BEFORE disable so both live and detached states
        # are visible (disable moves live sinks to "closed")
        health = h.summary()["sinks"]
        h.disable()
    assert boom.calls == 3               # detached after 3 failures
    assert len(ms.find("tick")) == 10    # healthy sink got everything
    assert h.sink_errors >= 3
    # satellite: the detached sink is VISIBLE in the summary with its
    # strike count — not a mysteriously short stream
    detached = [s for s in health if s["state"] == "detached"]
    assert detached and detached[0]["type"] == "_BoomSink"
    assert detached[0]["strikes"] == 3
    assert any(s["state"] == "attached" and s["type"] == "MemorySink"
               for s in health)


def test_jsonl_sink_bad_path_never_blocks(tmp_path):
    """A JSONL sink whose file cannot open must swallow events (recording
    the error) without blocking or raising into the emitting thread."""
    bad = tmp_path / "iam_a_dir"
    bad.mkdir()
    sink = monitor.JsonlSink(str(bad), queue_size=32)  # open() will fail
    h = monitor.hub()
    h.enable(sink)
    try:
        t0 = time.perf_counter()
        for i in range(5000):
            monitor.event("flood", i=i)
        elapsed = time.perf_counter() - t0
    finally:
        h.disable()                      # joins the writer thread
    assert elapsed < 5.0                 # never blocked on the dead writer
    assert sink.error is not None


def test_jsonl_sink_roundtrip(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    sink = monitor.JsonlSink(path)
    h = monitor.hub()
    h.enable(sink)
    h.begin_pass(5)
    monitor.event("alpha", k=1)
    h.end_pass()
    h.disable()
    res = flight.validate_events_file(path)
    assert res["errors"] == []
    assert res["events"] >= 3            # pass_begin, alpha, flight record
    assert len(res["flight_records"]) == 1
    assert sink.error is None and sink.written >= 3


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------

def test_prometheus_exposition_format():
    h = monitor.hub()
    monitor.counter_add("t.prom/count:er", 7)
    monitor.gauge_set("t.prom.gauge", 2.5)
    text = h.prometheus_text()
    lines = text.splitlines()
    # sanitized names, one TYPE line per metric, counter vs gauge kinds
    assert "# TYPE pbtpu_t_prom_count:er counter" in lines
    assert "pbtpu_t_prom_count:er 7" in lines
    assert "# TYPE pbtpu_t_prom_gauge gauge" in lines
    assert "pbtpu_t_prom_gauge 2.5" in lines
    # the doctor's alert series are ALWAYS exported (zero-filled when
    # untouched) so training/serving /metrics never gain or lose series
    assert "# TYPE pbtpu_exchange_overflow_retries counter" in lines
    assert "# TYPE pbtpu_tiering_hot_rows gauge" in lines
    assert "# TYPE pbtpu_tiering_hot_hit_rate gauge" in lines
    for line in lines:
        if not line or line.startswith("#"):
            continue
        name, val = line.rsplit(" ", 1)
        float(val)                       # every sample parses
        assert " " not in name


def test_training_metrics_endpoint_scrapes_alert_series():
    """The training-side /metrics twin of the serving endpoint: the
    doctor's alert series are scrapeable from a bare training process."""
    import urllib.request

    srv = monitor.start_metrics_endpoint(port=0)
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            body = r.read().decode()
        assert "pbtpu_exchange_overflow_retries" in body
        assert "pbtpu_tiering_hot_hit_rate" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10)
    finally:
        srv.shutdown()
        srv.server_close()
        srv._pbtpu_thread.join(timeout=10)


# ---------------------------------------------------------------------------
# disabled-path cost (acceptance: no-op fast path)
# ---------------------------------------------------------------------------

def test_disabled_path_call_cost():
    h = monitor.hub()
    assert not h.enabled
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        monitor.event("noop", x=1)
    event_cost = (time.perf_counter() - t0) / n
    t0 = time.perf_counter()
    for _ in range(n):
        with monitor.span("noop"):
            pass
    span_cost = (time.perf_counter() - t0) / n
    # generous bounds (CI noise): the disabled event is one flag check,
    # the disabled span two — micro-seconds, not tens of them
    assert event_cost < 5e-6, f"disabled event() costs {event_cost:.2e}s"
    assert span_cost < 10e-6, f"disabled span() costs {span_cost:.2e}s"


# ---------------------------------------------------------------------------
# profiler ring buffer (satellite: bounded span store)
# ---------------------------------------------------------------------------

def test_profiler_ring_buffer_caps_and_counts_drops():
    from paddlebox_tpu.config import flags, set_flags
    from paddlebox_tpu.utils import profiler as prof

    old = flags.profiler_max_events
    set_flags(profiler_max_events=16)
    try:
        prof.enable_profiler()
        for i in range(50):
            with prof.RecordEvent(f"s{i}"):
                pass
        evs = prof.profiler_events()
        assert len(evs) == 16
        assert prof.dropped_spans() == 34
        # oldest dropped, newest kept
        assert evs[-1]["name"] == "s49" and evs[0]["name"] == "s34"
    finally:
        prof.disable_profiler()
        set_flags(profiler_max_events=old)


def test_chrome_trace_has_pass_markers_and_tagged_spans(tmp_path):
    from paddlebox_tpu.utils import profiler as prof

    h = monitor.hub()
    prof.enable_profiler()
    try:
        h.begin_pass(77)
        mon_ctx.set_step(5)
        with monitor.span("tagged_work"):
            pass
        h.end_pass()
    finally:
        prof.disable_profiler()
    path = str(tmp_path / "trace.json")
    prof.export_chrome_trace(path)
    with open(path) as f:
        evs = json.load(f)["traceEvents"]
    instants = {e["name"] for e in evs if e["ph"] == "i"}
    assert {"pass_begin", "pass_end"} <= instants
    span = next(e for e in evs if e["name"] == "tagged_work")
    assert span["args"] == {"pass_id": 77, "step": 5}


# ---------------------------------------------------------------------------
# fs / faultpoint routing (satellite)
# ---------------------------------------------------------------------------

def test_commandfs_failure_routes_through_hub_counters():
    from paddlebox_tpu.utils.fs import CommandFS

    h = monitor.hub()
    ms = monitor.MemorySink()
    h.enable(ms)
    before_ex = STATS.get("fs.rm.exhausted")
    before_rt = STATS.get("fs.rm.retries")
    fs = CommandFS(rm="false {path}", retries=3, retry_backoff=0.0)
    try:
        with pytest.raises(RuntimeError, match="after 3 attempts"):
            fs.rm("/nonexistent/x")
    finally:
        h.disable()
    assert STATS.get("fs.rm.exhausted") == before_ex + 1
    assert STATS.get("fs.rm.retries") == before_rt + 2
    ev = ms.find("fs_exhausted")
    assert ev and ev[0]["fields"]["op"] == "rm"
    assert ev[0]["fields"]["attempts"] == 3


def test_faultpoint_trip_routes_through_hub():
    from paddlebox_tpu.utils import faultpoint

    h = monitor.hub()
    ms = monitor.MemorySink()
    h.enable(ms)
    before = STATS.get("faultpoint.trips")
    try:
        faultpoint.arm("pass_ckpt.pre_manifest", action="ioerror")
        with pytest.raises(faultpoint.FaultInjected):
            faultpoint.hit("pass_ckpt.pre_manifest")
    finally:
        faultpoint.disarm()
        h.disable()
    assert STATS.get("faultpoint.trips") == before + 1
    ev = ms.find("faultpoint_trip")
    assert ev and ev[0]["fields"]["point"] == "pass_ckpt.pre_manifest"
    assert ms.find("faultpoint_armed")


# ---------------------------------------------------------------------------
# nan guard (satellite: flags.check_nan_inf wiring)
# ---------------------------------------------------------------------------

def _tiny_trainer(tmp_path, nan_dump_dir=None, inject_inf=False):
    from paddlebox_tpu.data import DataFeedSchema
    from paddlebox_tpu.data.dataset import SlotDataset
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig

    schema = DataFeedSchema.ctr(num_sparse=3, num_float=1, batch_size=8,
                                max_len=2)
    rng = np.random.default_rng(0)
    ds = SlotDataset(schema)
    lines = []
    for i in range(16):
        dense = "inf" if (inject_inf and i == 9) else f"{rng.random():.3f}"
        parts = [f"1 {int(rng.random() < 0.4)}", f"1 {dense}"]
        for s in range(3):
            parts.append(
                f"2 {rng.integers(1, 1000)} {rng.integers(1, 1000)}")
        lines.append(" ".join(parts))
    f = tmp_path / "part-0"
    f.write_text("\n".join(lines) + "\n")
    ds.set_filelist([str(f)])
    ds.load_into_memory(global_shuffle=False)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    model = DNNCTRModel(num_slots=3, emb_dim=4, dense_dim=1, hidden=(8,))
    tr = Trainer(model, store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=8, auc_buckets=1 << 8,
                               nan_dump_dir=nan_dump_dir))
    return tr, ds


def test_flags_check_nan_inf_trips_with_telemetry(tmp_path):
    from paddlebox_tpu.config import set_flags

    tr, ds = _tiny_trainer(tmp_path, nan_dump_dir=str(tmp_path / "dump"),
                           inject_inf=True)
    h = monitor.hub()
    ms = monitor.MemorySink()
    h.enable(ms)
    set_flags(check_nan_inf=True)
    try:
        with pytest.raises(FloatingPointError, match="non-finite leaves"):
            tr.train_pass(ds)
    finally:
        set_flags(check_nan_inf=False)
        h.disable()
    ev = ms.find("nan_guard")
    assert ev, "nan trip must emit a telemetry event"
    assert ev[0]["fields"]["n_bad"] >= 1
    assert any("loss" in p or "dense" in p or "labels" in p
               for p in ev[0]["fields"]["paths"])
    # the aborted pass closed its scope (no leak into the next pass)
    assert mon_ctx.current().pass_id is None
    # scope dump landed next to the error
    dumps = os.listdir(tmp_path / "dump")
    assert any(d.startswith("nan_step") for d in dumps)
    assert STATS.get("trainer.nan_trips") >= 1


# ---------------------------------------------------------------------------
# flight-record schema against a REAL 2-pass train on CPU (acceptance)
# ---------------------------------------------------------------------------

def test_two_pass_train_flight_records_and_schema(tmp_path):
    from paddlebox_tpu.fleet import BoxPS

    tr, ds = _tiny_trainer(tmp_path)
    box = BoxPS(tr.store)
    box.init_metric("auc", method="plain")
    h = monitor.hub()
    ms = monitor.MemorySink()
    jl = monitor.JsonlSink(str(tmp_path / "events.jsonl"))
    h.enable(ms, jl)
    try:
        for _ in range(2):
            box.begin_pass()
            out = tr.train_pass(ds, metrics=box.metrics)
            info = box.end_pass()
            assert info["flight_record"] is not None
    finally:
        h.disable()

    res = flight.validate_events_file(str(tmp_path / "events.jsonl"))
    assert res["errors"] == [], res["errors"][:10]
    flights = res["flight_records"]
    assert [f["pass_id"] for f in flights] == [1, 2]
    for fr in flights:
        assert fr["steps"] == 2 and fr["examples"] == 16
        assert fr["examples_per_sec"] > 0
        # stage split covers the trainer's stages
        assert {"read", "translate", "train", "auc",
                "drain"} <= set(fr["stage_seconds"])
        # per-pass sparse telemetry deltas
        assert fr["stats_delta"].get("trainer.tokens") == 2 * 8 * 6
        assert fr["stats_delta"].get("trainer.pull_bytes", 0) > 0
        # metric snapshot came from the registry
        assert "auc" in fr["metrics"] and "auc" in fr["metrics"]["auc"]
        assert fr["extra"]["loss_mean"] == pytest.approx(
            out["loss_mean"], abs=1.0)   # same field, last pass exact
        # pass-boundary account (ISSUE 12): wall + component split, the
        # critical-path attributor's input
        assert fr["extra"]["boundary_seconds"] >= 0
        split = fr["extra"]["boundary_split"]
        assert set(split) == {"build", "h2d", "spill_fault_in"}
        assert all(v >= 0 for v in split.values())
        assert split["build"] + split["h2d"] > 0
    # every event in the stream carries the tag keys; events emitted
    # while a pass was open carry its id
    with open(tmp_path / "events.jsonl") as f:
        recs = [json.loads(line) for line in f]
    in_pass = [r for r in recs if r.get("type") in ("span", "event")
               and r["name"] not in ("eval_pass",)]
    assert in_pass
    assert all(r["pass_id"] in (1, 2) for r in in_pass), (
        sorted({r["name"] for r in in_pass if r["pass_id"] is None}))
    # background threads contributed tagged events (the pack producer at
    # minimum — prefetch is on by default)
    assert any(t != "MainThread" for t in res["threads"]), res["threads"]


def test_flight_validator_rejects_bad_exchange_extras():
    """ISSUE 16: the adaptive-exchange identity extras are closed
    vocabularies — an off-vocabulary wire or topology is a schema error,
    not a silent dashboard mystery."""
    base = {"ts": 1.0, "type": "flight_record", "name": "pass",
            "pass_id": 1, "step": None, "phase": None, "thread": "t",
            "seconds": 1.0, "steps": 1, "examples": 1,
            "examples_per_sec": 1.0, "stage_seconds": {},
            "stats_delta": {}, "metrics": {}}
    for k, bad in (("exchange_wire", "fp64"),
                   ("exchange_wire_next", 8),
                   ("exchange_topology", "ring")):
        errs = flight.validate_flight_record(dict(base, extra={k: bad}))
        assert any(k in e for e in errs), (k, errs)
    ok = dict(base, extra={"exchange_wire": "f32",
                           "exchange_wire_next": "bf16",
                           "exchange_topology": "hier"})
    assert flight.validate_flight_record(ok) == []
