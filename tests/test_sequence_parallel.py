"""Ring + Ulysses attention vs full-attention ground truth (8-dev mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.parallel.sequence import (attention_reference,
                                             make_sequence_parallel_attention)


def _qkv(B=2, S=64, H=8, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.normal(size=(B, S, H, D)).astype(np.float32) * 0.5
    return mk(), mk(), mk()


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(mode, causal):
    mesh = make_mesh(8)
    q, k, v = _qkv()
    want = attention_reference(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), causal=causal)
    fn = make_sequence_parallel_attention(mesh, "dp", mode=mode,
                                          causal=causal)
    got = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_gradients_match(mode):
    mesh = make_mesh(8)
    q, k, v = _qkv(B=1, S=32, H=8, D=8, seed=3)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    fn = make_sequence_parallel_attention(mesh, "dp", mode=mode, causal=True)

    def loss_sp(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b in zip(g_ref, g_sp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=5e-4)


def test_ring_long_sequence_memory_shape():
    # S_local^2 scores only: S=512 over 8 devices -> 64x64 blocks
    mesh = make_mesh(8)
    q, k, v = _qkv(B=1, S=512, H=2, D=8, seed=1)
    fn = make_sequence_parallel_attention(mesh, "dp", mode="ring")
    got = fn(q, k, v)
    want = attention_reference(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_rejects_bad_heads():
    mesh = make_mesh(8)
    q, k, v = _qkv(H=4)  # 4 heads, 8 devices
    fn = make_sequence_parallel_attention(mesh, "dp", mode="ulysses")
    with pytest.raises(ValueError, match="not divisible"):
        fn(q, k, v)


def test_ring_bf16_accumulates_in_f32():
    # the (o, m, l) online-softmax state stays f32 even for bf16 inputs, so
    # ring results track the f32 reference to bf16 resolution regardless of
    # how many hops the ring has
    mesh = make_mesh(8)
    q, k, v = _qkv(S=128)
    want = attention_reference(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    fn = make_sequence_parallel_attention(mesh, "dp", mode="ring")
    got = fn(jnp.asarray(q, jnp.bfloat16), jnp.asarray(k, jnp.bfloat16),
             jnp.asarray(v, jnp.bfloat16))
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want), rtol=0.06, atol=0.06)
