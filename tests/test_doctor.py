"""Run doctor (ISSUE 12): every rule has a fire + quiet fixture, the
2-rank aggregation golden (skew + straggler naming), critical-path
attribution, JSONL rotation (schema-clean segments picked up in order),
the telemetry.rotate.pre fault window, live mode, and the CLI."""

from __future__ import annotations

import json
import os
import time

import pytest

from paddlebox_tpu import monitor
from paddlebox_tpu.monitor import aggregate as agg_lib
from paddlebox_tpu.monitor import critical_path as cp_lib
from paddlebox_tpu.monitor import doctor, flight
from paddlebox_tpu.monitor.registry import STATS
from paddlebox_tpu.utils import faultpoint


@pytest.fixture(autouse=True)
def _clean_hub():
    h = monitor.hub()
    h.disable()
    h.abort_pass(reason="test setup")
    yield
    h.abort_pass(reason="test teardown")
    h.disable()


# ---------------------------------------------------------------------------
# synthetic flight records
# ---------------------------------------------------------------------------

def make_flight(pass_id, seconds=10.0, train=6.0, read=0.5, auc=0.2,
                drain=0.1, boundary=0.5, split=None, stats=None,
                **extra):
    rec = {
        "ts": time.time(), "type": "flight_record", "name": "pass",
        "pass_id": pass_id, "step": None, "phase": 1, "thread": "Main",
        "seconds": seconds, "train_seconds": train, "steps": 8,
        "examples": 1024, "examples_per_sec": 1024 / seconds,
        "stage_seconds": {"read": read, "train": train, "auc": auc,
                          "drain": drain, "translate": 0.3},
        "stats_delta": dict(stats or {}),
        "metrics": {}, "owner": "box",
        "extra": dict({"boundary_seconds": boundary,
                       "boundary_split": split or
                       {"build": boundary * 0.6, "h2d": boundary * 0.4,
                        "spill_fault_in": 0.0}}, **extra),
    }
    assert flight.validate_flight_record(rec) == []
    return rec


def make_serving_window(ts, requests=100, failures=0, swaps=0,
                        version_lag=0, slo_ms=50.0, p50_ms=3.0,
                        p99_ms=8.0, versions=None, **extra):
    """One schema-valid serving window record (ISSUE 19) — the serving
    plane's make_flight. The doctor flattens ``fields``; fixtures pass
    full records so every synthetic window also exercises the schema."""
    rec = {
        "ts": float(ts), "type": "serving_record",
        "name": "serving_window", "pass_id": None, "step": None,
        "phase": -1, "thread": "serving",
        "fields": dict({"window_s": 30.0, "requests": requests,
                        "failures": failures, "swaps": swaps,
                        "version_lag": version_lag, "slo_ms": slo_ms,
                        "p50_ms": p50_ms, "p99_ms": p99_ms,
                        "versions": versions or {}}, **extra),
    }
    assert flight.validate_serving_record(rec) == []
    return rec


def make_fleet_window(ts, replicas=2, healthy=2, quarantined=0,
                      requests=500, sheds=0, retries=0, hedges=0,
                      hedges_won=0, restarts=0, promote_holds=0,
                      p50_ms=2.0, p99_ms=8.0, **extra):
    """One schema-valid fleet window record (ISSUE 20) — the replica
    fleet's make_serving_window."""
    rec = {
        "ts": float(ts), "type": "fleet_record", "name": "fleet_window",
        "pass_id": None, "step": None, "phase": -1, "thread": "fleet",
        "fields": dict({"window_s": 10.0, "replicas": replicas,
                        "healthy": healthy, "quarantined": quarantined,
                        "requests": requests, "sheds": sheds,
                        "retries": retries, "hedges": hedges,
                        "hedges_won": hedges_won, "restarts": restarts,
                        "promote_holds": promote_holds, "p50_ms": p50_ms,
                        "p99_ms": p99_ms}, **extra),
    }
    assert flight.validate_fleet_record(rec) == []
    return rec


# Per-rule (fire_kwargs, quiet_kwargs) for doctor.diagnose — the
# closed-registry discipline: a new rule cannot ship without BOTH a
# firing and a quiet synthetic fixture registered here (the coverage
# test below parametrizes over doctor.ALL_RULES).
RULE_FIXTURES: dict = {
    "boundary-wall": (
        dict(flights=[make_flight(1, seconds=10.0, train=4.0,
                                  boundary=4.0)]),
        dict(flights=[make_flight(1, seconds=10.0, train=8.0,
                                  boundary=0.5)]),
    ),
    "exchange-overflow": (
        dict(flights=[
            make_flight(1, stats={"exchange.tokens": 1000,
                                  "exchange.overflow_retries": 2}),
            make_flight(2, stats={"exchange.tokens": 1000,
                                  "exchange.overflow_retries": 3,
                                  "exchange.overflow_dropped": 40})]),
        dict(flights=[make_flight(1, stats={"exchange.tokens": 1000}),
                      make_flight(2, stats={"exchange.tokens": 1000})]),
    ),
    "spill-thrash": (
        dict(flights=[
            make_flight(1, stats={"spill.cache_hits": 900,
                                  "spill.cache_misses": 100}),
            make_flight(2, stats={"spill.cache_hits": 200,
                                  "spill.cache_misses": 800,
                                  "tiering.admitted": 500,
                                  "tiering.evicted": 490})]),
        dict(flights=[
            make_flight(1, stats={"spill.cache_hits": 900,
                                  "spill.cache_misses": 100}),
            make_flight(2, stats={"spill.cache_hits": 880,
                                  "spill.cache_misses": 120,
                                  "tiering.admitted": 50,
                                  "tiering.evicted": 5})]),
    ),
    "dedup-drift": (
        dict(flights=[
            make_flight(1, stats={"exchange.tokens": 1000,
                                  "exchange.unique_lanes": 800}),
            make_flight(2, stats={"exchange.tokens": 1000,
                                  "exchange.unique_lanes": 400})]),
        dict(flights=[
            make_flight(1, stats={"exchange.tokens": 1000,
                                  "exchange.unique_lanes": 800}),
            make_flight(2, stats={"exchange.tokens": 1000,
                                  "exchange.unique_lanes": 780})]),
    ),
    "push-floor": (
        dict(detail={"push_engine": "binned_kernel",
                     "push_floor": {
                         "engine": "binned_kernel",
                         "floor_seconds": 0.001,
                         "measured_push_seconds": 0.02,
                         "closed": "measured 20.00ms > 3x floor 1.00ms",
                         "engines": {
                             "binned_kernel": {"floor_seconds": 0.001,
                                               "closed": "measured ..."},
                             "scatter_accumulate": {
                                 "floor_seconds": 0.0004,
                                 "closed": "measured ...",
                                 "note": "requires premerged unique "
                                         "lanes"}},
                         "best_engine": "scatter_accumulate"}}),
        dict(detail={"push_engine": "binned_kernel",
                     "push_floor": {
                         "engine": "binned_kernel",
                         "floor_seconds": 0.001,
                         "measured_push_seconds": 0.002,
                         "closed": True}}),
    ),
    "nan-guard": (
        dict(flights=[make_flight(1, stats={"trainer.nan_trips": 1})],
             evidence={"nan_guard": [{
                 "name": "nan_guard", "pass_id": 1, "step": 7,
                 "fields": {"n_bad": 2, "paths": ["loss"]}}]}),
        dict(flights=[make_flight(1)]),
    ),
    "serving-staleness": (
        dict(flights=[make_flight(
            1, stats={"serving.publishes": 1,
                      "serving.publish_failures": 1})]),
        dict(flights=[make_flight(
            1, stats={"serving.publishes": 1, "serving.pass_lag": 0})]),
    ),
    "heartbeat-gap": (
        dict(counters={"resilience.peer_lost": 1},
             evidence={"peer_lost": [{
                 "name": "peer_lost",
                 "fields": {"rank": 3, "observer": 0,
                            "after_s": 30.0}}]}),
        dict(counters={"resilience.peer_lost": 0}),
    ),
    "sink-health": (
        dict(sink_health=[{"type": "JsonlSink", "state": "detached",
                           "strikes": 3, "dropped": 120,
                           "error": "OSError(28, 'No space left')"}]),
        dict(sink_health=[{"type": "JsonlSink", "state": "attached",
                           "strikes": 0, "dropped": 0, "written": 99}]),
    ),
    "cross-rank-flow": (
        # longest edge 4s against a 10s mean pass wall = 40% — fired;
        # quiet: the same edge at 0.1s (1%)
        dict(flights=[make_flight(1, seconds=10.0)],
             detail={"world_trace": {
                 "flow_edges": [
                     {"kind": "exchange", "key": "p1.s3",
                      "src_rank": 0, "dst_rank": 1, "latency_s": 4.0,
                      "fields": {"wire": "bf16"}},
                     {"kind": "publish", "key": "v7", "src_rank": 0,
                      "dst_rank": 2, "latency_s": 0.5, "fields": {}}],
                 "clock_offsets_s": {"0": 0.0, "1": 1.25}}}),
        dict(flights=[make_flight(1, seconds=10.0)],
             detail={"world_trace": {
                 "flow_edges": [
                     {"kind": "exchange", "key": "p1.s3",
                      "src_rank": 0, "dst_rank": 1, "latency_s": 0.1,
                      "fields": {}}],
                 "clock_offsets_s": {"0": 0.0, "1": 0.0}}}),
    ),
    "version-regression": (
        # candidate AUC 0.58 against stable 0.74 — far past the 0.005
        # margin; quiet: identical versions score identically
        dict(servings=[make_serving_window(
            100.0,
            versions={"1": {"role": "stable", "requests": 80,
                            "auc": 0.74, "score_mean": 0.21},
                      "2": {"role": "candidate", "requests": 80,
                            "auc": 0.58, "score_mean": 0.34,
                            "score_kl": 0.8}})]),
        dict(servings=[make_serving_window(
            100.0,
            versions={"1": {"role": "stable", "requests": 80,
                            "auc": 0.74, "score_mean": 0.21},
                      "2": {"role": "candidate", "requests": 80,
                            "auc": 0.74, "score_mean": 0.21,
                            "score_kl": 0.01}})]),
    ),
    "p99-burn": (
        # 3 of 4 recent windows (incl. the latest) breach the 50ms SLO;
        # quiet: same traffic, p99 comfortably under
        dict(servings=[
            make_serving_window(100.0, p99_ms=12.0),
            make_serving_window(130.0, p99_ms=72.0),
            make_serving_window(160.0, p99_ms=65.0),
            make_serving_window(190.0, p99_ms=80.0)]),
        dict(servings=[
            make_serving_window(100.0, p99_ms=12.0),
            make_serving_window(130.0, p99_ms=72.0),
            make_serving_window(160.0, p99_ms=11.0),
            make_serving_window(190.0, p99_ms=13.0)]),
    ),
    "swap-regression": (
        # the swap window's p99 steps 6ms -> 40ms (> 1.5x and > +1ms);
        # quiet: a swap whose window holds the pre-swap latency
        dict(servings=[
            make_serving_window(100.0, p99_ms=6.0),
            make_serving_window(130.0, p99_ms=40.0, swaps=1,
                                active_version=7)]),
        dict(servings=[
            make_serving_window(100.0, p99_ms=6.0),
            make_serving_window(130.0, p99_ms=6.5, swaps=1,
                                active_version=7)]),
    ),
    "fleet-degraded": (
        # one replica out of rotation after a crash-loop quarantine;
        # quiet: full fleet, no sheds, no promotion holds
        dict(fleets=[make_fleet_window(
            100.0, healthy=1, quarantined=1, restarts=4, retries=3)]),
        dict(fleets=[make_fleet_window(100.0)]),
    ),
}


@pytest.mark.parametrize("rule_cls", doctor.ALL_RULES,
                         ids=[r.id for r in doctor.ALL_RULES])
def test_every_rule_fires_and_stays_quiet(rule_cls):
    assert rule_cls.id in RULE_FIXTURES, (
        f"rule {rule_cls.id!r} shipped without fire+quiet fixtures — "
        "register them in RULE_FIXTURES")
    assert rule_cls.incident, "every rule must cite its prior incident"
    fire_kw, quiet_kw = RULE_FIXTURES[rule_cls.id]

    rep = doctor.diagnose(**fire_kw)
    assert doctor.validate_report(rep) == []
    status = {r["rule"]: r["status"] for r in rep["rules"]}
    assert status[rule_cls.id] == "fired", (rule_cls.id, status)
    finding = next(f for f in rep["findings"]
                   if f["rule"] == rule_cls.id)
    # a finding is NAMED and carries evidence + a suggestion — never a
    # bare boolean
    assert finding["severity"] in ("critical", "warn", "info")
    assert finding["summary"] and finding["suggestion"]
    assert isinstance(finding["evidence"], dict) and finding["evidence"]

    rep_q = doctor.diagnose(**quiet_kw)
    status_q = {r["rule"]: r["status"] for r in rep_q["rules"]}
    assert status_q[rule_cls.id] == "quiet", (rule_cls.id, status_q)
    assert all(f["rule"] != rule_cls.id for f in rep_q["findings"])


def test_quarantined_rule_downgrades_to_info_and_is_surfaced():
    """ISSUE 20 satellite (remediation-history feedback): a rule whose
    applied remediation the parity guard reverted still REPORTS its
    symptom, but as info with the discredited suggestion suppressed —
    and the report names the quarantined rule ids."""
    fire_kw, _ = RULE_FIXTURES["fleet-degraded"]
    rep = doctor.diagnose(**fire_kw,
                          quarantined_rules=["fleet-degraded"])
    assert doctor.validate_report(rep) == []
    assert rep["quarantined_rules"] == ["fleet-degraded"]
    f = next(f for f in rep["findings"] if f["rule"] == "fleet-degraded")
    assert f["severity"] == "info"              # symptom stays visible,
    assert "suggestion suppressed" in f["suggestion"]   # advice doesn't
    assert "original:" in f["suggestion"]       # ...but stays auditable
    # the SAME evidence un-quarantined is actionable (warn)
    rep2 = doctor.diagnose(**fire_kw)
    f2 = next(f for f in rep2["findings"]
              if f["rule"] == "fleet-degraded")
    assert f2["severity"] == "warn"
    assert "quarantined_rules" not in rep2


def test_push_floor_suggestion_names_concrete_engine():
    """ISSUE 13: the push-floor finding consumes the per-point engine
    record + the per-candidate-engine closure statements and names the
    CONCRETE flags.push_engine to force — never a bare 'A/B the knobs'."""
    rep = doctor.diagnose(**RULE_FIXTURES["push-floor"][0])
    f = next(f for f in rep["findings"] if f["rule"] == "push-floor")
    assert "flags.push_engine='scatter_accumulate'" in f["suggestion"]
    assert "premerged" in f["suggestion"]       # the note rides along
    assert f["evidence"]["engine"] == "binned_kernel"
    assert f["evidence"]["engine_floors"]["scatter_accumulate"] == 0.0004
    # the resolver already on the best engine: no force to suggest —
    # the suggestion pivots to the companion knobs instead
    fire = dict(detail={"push_engine": "scatter_accumulate",
                        "push_floor": {
                            "engine": "scatter_accumulate",
                            "floor_seconds": 0.001,
                            "measured_push_seconds": 0.02,
                            "closed": "measured 20.00ms > 3x floor "
                                      "1.00ms",
                            "engines": {"scatter_accumulate":
                                        {"floor_seconds": 0.001,
                                         "closed": "measured ..."}},
                            "best_engine": "scatter_accumulate"}})
    rep2 = doctor.diagnose(**fire)
    f2 = next(f for f in rep2["findings"] if f["rule"] == "push-floor")
    assert "lowest-floor engine" in f2["suggestion"]


def test_doctor_report_verdict_and_severity_order():
    rep = doctor.diagnose(**RULE_FIXTURES["nan-guard"][0])
    assert rep["verdict"] == "findings:1"
    # critical findings sort first when several fire
    fire = dict(RULE_FIXTURES["boundary-wall"][0])
    fire["evidence"] = RULE_FIXTURES["heartbeat-gap"][0]["evidence"]
    fire["counters"] = RULE_FIXTURES["heartbeat-gap"][0]["counters"]
    rep2 = doctor.diagnose(**fire)
    assert [f["severity"] for f in rep2["findings"]] == \
        sorted([f["severity"] for f in rep2["findings"]],
               key=lambda s: {"critical": 0, "warn": 1}.get(s, 9))
    assert rep2["findings"][0]["rule"] == "heartbeat-gap"


def test_serving_staleness_does_not_double_count_failures():
    """The CLI hands diagnose() counters that ARE the summed per-pass
    deltas — seeding from the counter and adding the deltas again would
    report every failure twice (review finding)."""
    flights = [make_flight(
        1, stats={"serving.publishes": 1, "serving.publish_failures": 1})]
    rep = doctor.diagnose(
        flights=flights,
        counters={"serving.publishes": 1, "serving.publish_failures": 1})
    f = next(f for f in rep["findings"]
             if f["rule"] == "serving-staleness")
    assert f["evidence"]["publish_failures"] == 1
    assert "1 failed publish(es)" in f["summary"]


def test_serving_staleness_fires_on_gradual_gauge_growth():
    """pass_lag grows by 1 every pass: the per-pass DELTAS are all 1.0,
    but the absolute gauge after 4 passes is 4 — the rule must
    reconstruct the running value, not max the deltas (review
    finding: gradual staleness could never fire)."""
    flights = [make_flight(p, stats={"serving.publishes": 1,
                                     "serving.pass_lag": 1.0})
               for p in range(1, 5)]
    rep = doctor.diagnose(flights=flights)
    f = next(f for f in rep["findings"]
             if f["rule"] == "serving-staleness")
    assert f["evidence"]["pass_lag"] == 4.0


def test_record_train_accumulates_boundary_across_phases():
    """Phased programs run several train_passes per pass: the boundary
    account must SUM like stage_seconds (review finding: last-write-wins
    extras kept only the cheap second-phase rebuild)."""
    h = monitor.hub()
    h.begin_pass(41)
    h.record_train(steps=1, examples=8, seconds=1.0,
                   boundary_seconds=40.0,
                   boundary_split={"build": 30.0, "h2d": 10.0,
                                   "spill_fault_in": 0.0})
    h.record_train(steps=1, examples=8, seconds=1.0,
                   boundary_seconds=0.2,
                   boundary_split={"build": 0.1, "h2d": 0.1,
                                   "spill_fault_in": 0.0})
    rec = h.end_pass()
    assert rec["extra"]["boundary_seconds"] == pytest.approx(40.2)
    assert rec["extra"]["boundary_split"]["build"] == pytest.approx(30.1)
    assert flight.validate_flight_record(rec) == []


def test_world_view_reads_push_bytes_counter(tmp_path):
    """The exchange push-traffic counter is exchange.push_bytes —
    the world view must surface its imbalance (review finding: a
    mis-spelled key silently dropped the distribution)."""
    r0 = make_flight(1, stats={"exchange.tokens": 100,
                               "exchange.push_bytes": 1000})
    r1 = make_flight(1, seconds=12.0,
                     stats={"exchange.tokens": 100,
                            "exchange.push_bytes": 9000})
    _write_stream(str(tmp_path / "rank0"), [r0])
    _write_stream(str(tmp_path / "rank1"), [r1])
    world = agg_lib.aggregate([str(tmp_path / "rank0"),
                               str(tmp_path / "rank1")])
    dist = world["passes"][0]["exchange"]["push_bytes"]
    assert dist["max_rank"] == 1 and dist["max"] == 9000.0


def test_rule_verdicts_are_rank_order_independent():
    """pass_deltas sums across merged ranks' records per pass — a
    last-wins collapse made spill-thrash/dedup-drift depend on the
    order the rank roots were listed in (review finding)."""
    healthy = [make_flight(1, stats={"spill.cache_hits": 900,
                                     "spill.cache_misses": 100}),
               make_flight(2, stats={"spill.cache_hits": 900,
                                     "spill.cache_misses": 100})]
    collapsed = [make_flight(1, stats={"spill.cache_hits": 900,
                                       "spill.cache_misses": 100}),
                 make_flight(2, stats={"spill.cache_hits": 100,
                                       "spill.cache_misses": 900,
                                       "tiering.admitted": 500,
                                       "tiering.evicted": 490})]
    verdicts = set()
    for order in (healthy + collapsed, collapsed + healthy):
        rep = doctor.diagnose(flights=order)
        verdicts.add({r["rule"]: r["status"]
                      for r in rep["rules"]}["spill-thrash"])
    assert len(verdicts) == 1, verdicts


def test_heartbeat_rule_no_data_without_resilience_plane():
    """A single-host run with no heartbeat plane must read no-data, not
    'heartbeats checked, all healthy' (the no-data contract)."""
    rep = doctor.diagnose(flights=[make_flight(1)])
    status = {r["rule"]: r["status"] for r in rep["rules"]}
    assert status["heartbeat-gap"] == "no-data"


def test_sink_health_does_not_latch_on_cumulative_counter():
    """A recovered transient emit error leaves the process-cumulative
    monitor.sink_errors nonzero forever; the rule must stay quiet when
    this session's sinks are healthy (review finding)."""
    healthy = [{"type": "JsonlSink", "state": "attached", "strikes": 0,
                "dropped": 0, "written": 10}]
    rep = doctor.diagnose(counters={"monitor.sink_errors": 3},
                          sink_health=healthy)
    status = {r["rule"]: r["status"] for r in rep["rules"]}
    assert status["sink-health"] == "quiet"


# ---------------------------------------------------------------------------
# critical-path attribution
# ---------------------------------------------------------------------------

def test_attribution_limiter_trend_and_headroom():
    flights = [
        make_flight(1, seconds=10.0, train=6.0, boundary=2.0),
        make_flight(2, seconds=10.0, train=4.0, boundary=5.0),
    ]
    out = cp_lib.attribute_records(flights)
    p1, p2 = out["passes"]
    assert p1["limiter"] == "train" and p2["limiter"] == "boundary"
    assert p1["stages"]["boundary"] == 2.0
    assert p2["boundary_share"] == 0.5
    # headroom: the boundary can hide under train, bounded by both
    assert p1["overlap_headroom_seconds"] == 2.0
    assert p2["overlap_headroom_seconds"] == 4.0
    assert p1["boundary_split"]["build"] == pytest.approx(1.2)
    # translate is overlapped, never charged to the wall
    assert "translate" not in p1["stages"]
    assert p1["overlapped"]["translate"] == pytest.approx(0.3)
    s = out["summary"]
    assert s["limiter"] in ("train", "boundary")
    assert s["boundary_share_trend"] == "rising"
    assert s["boundary_share_per_pass"] == [0.2, 0.5]
    # coverage accounts the attributable stages against the wall
    assert 0.8 <= p1["coverage"] <= 1.0


def test_attribution_over_merged_ranks_is_order_independent():
    """Several ranks' records for one pass: the STRAGGLER's record is
    attributed regardless of listing order (review finding — last-wins
    made the report depend on CLI argument order)."""
    fast = make_flight(1, seconds=8.0, train=5.0)
    slow = make_flight(1, seconds=14.0, train=10.0)
    for order in ([fast, slow], [slow, fast]):
        out = cp_lib.attribute_records(order)
        assert out["passes"][0]["wall_seconds"] == 14.0
        assert out["passes"][0]["stages"]["train"] == 10.0


# ---------------------------------------------------------------------------
# 2-rank aggregation golden: skew + straggler naming
# ---------------------------------------------------------------------------

def _write_stream(dirpath, records):
    os.makedirs(dirpath, exist_ok=True)
    with open(os.path.join(dirpath, "events.jsonl"), "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def _golden_world(tmp_path, names=("rank0", "rank1")):
    r0 = make_flight(1, seconds=8.0, train=5.0, boundary=1.0,
                     stats={"exchange.tokens": 1000,
                            "exchange.unique_lanes": 700,
                            "exchange.pull_bytes": 4000})
    r0b = make_flight(2, seconds=8.2, train=5.1, boundary=1.1,
                      stats={"exchange.tokens": 1000,
                             "exchange.unique_lanes": 690,
                             "exchange.pull_bytes": 4100})
    # rank 1 is the straggler: 2x train time, more exchange traffic
    r1 = make_flight(1, seconds=14.0, train=10.0, boundary=1.2,
                     stats={"exchange.tokens": 1000,
                            "exchange.unique_lanes": 710,
                            "exchange.pull_bytes": 9000})
    _write_stream(str(tmp_path / names[0]), [r0, r0b])
    _write_stream(str(tmp_path / names[1]), [r1])   # pass 2 missing
    return [str(tmp_path / names[0]), str(tmp_path / names[1])]


def test_two_rank_aggregation_golden(tmp_path):
    roots = _golden_world(tmp_path)
    world = agg_lib.aggregate(roots)
    assert world["world_size"] == 2
    assert [r["rank"] for r in world["ranks"]] == [0, 1]
    p1, p2 = world["passes"]
    assert p1["pass_id"] == 1 and p1["ranks_reporting"] == 2
    assert p1["missing_ranks"] == []
    # straggler NAMED: rank 1 set the pass wall
    assert p1["straggler"] == 1
    assert p1["seconds"]["max_rank"] == 1
    assert p1["seconds"]["max"] == 14.0 and p1["seconds"]["min"] == 8.0
    assert p1["stage_skew"]["train"]["max_rank"] == 1
    assert p1["stage_skew"]["train"]["skew"] == pytest.approx(
        10.0 / 7.5, rel=1e-3)
    # exchange imbalance across shards is visible per pass
    assert p1["exchange"]["pull_bytes"]["max_rank"] == 1
    assert 0 < p1["exchange"]["dedup_ratio"]["mean"] < 1
    # a rank that never committed pass 2 is named missing — the
    # aggregation-level straggler/lost-rank signal
    assert p2["pass_id"] == 2 and p2["missing_ranks"] == [1]
    # cumulative counter view sums the deltas
    assert world["counters"]["exchange.pull_bytes"] == 4000 + 4100 + 9000


def test_aggregation_rank_names_follow_heartbeat_naming(tmp_path):
    """rank_names maps dense position -> ORIGINAL launcher rank, the
    HeartbeatMonitor convention — the straggler carries the original
    id."""
    roots = _golden_world(tmp_path, names=("a", "b"))
    world = agg_lib.aggregate(roots, rank_names=[4, 7])
    assert [r["rank"] for r in world["ranks"]] == [4, 7]
    assert world["passes"][0]["straggler"] == 7
    assert world["passes"][1]["missing_ranks"] == [7]
    # without rank_names, rankN dir basenames name the rank
    world2 = agg_lib.aggregate(_golden_world(tmp_path))
    assert world2["passes"][0]["straggler"] == 1


def test_doctor_over_world_names_straggler(tmp_path):
    roots = _golden_world(tmp_path)
    world = agg_lib.aggregate(roots)
    rep = doctor.diagnose(flights=world["flight_records"],
                          counters=world["counters"],
                          evidence=world["evidence"], world=world)
    assert doctor.validate_report(rep) == []
    assert rep["world"]["world_size"] == 2


def test_aggregation_reads_remote_roots(tmp_path):
    """hdfs://-schemed telemetry roots (the PR-5 remote layout) read
    through the registered CommandFS, segments and all."""
    from mockfs import register_mockfs

    root = tmp_path / "mock_root"
    (root / "rank0").mkdir(parents=True)
    _write_stream(str(root / "rank0"), [make_flight(1)])
    register_mockfs(str(root), scheme="mockdoc")
    st = agg_lib.read_stream("mockdoc://rank0")
    assert len(st["flight_records"]) == 1
    world = agg_lib.aggregate(["mockdoc://rank0"])
    assert world["passes"][0]["pass_id"] == 1


# ---------------------------------------------------------------------------
# JSONL rotation (satellite 1)
# ---------------------------------------------------------------------------

def test_jsonl_rotation_segments_schema_clean_and_ordered(tmp_path):
    path = str(tmp_path / "events.jsonl")
    sink = monitor.JsonlSink(path, rotate_mb=0.01)     # ~10KB segments
    h = monitor.hub()
    h.enable(sink)
    try:
        h.begin_pass(1)
        for i in range(120):
            monitor.event("tick", i=i, pad="x" * 200)
        h.end_pass()
    finally:
        h.disable()
    assert sink.error is None
    assert sink.rotations >= 2 and len(sink.segments) >= 3
    # every segment independently schema-clean, whole lines only
    total = 0
    for seg in sink.segments:
        res = flight.validate_events_file(seg)
        assert res["errors"] == [], (seg, res["errors"][:5])
        total += res["events"]
    assert total >= 120
    # the old segment's tail names its successor
    with open(sink.segments[0]) as f:
        last = json.loads(f.read().splitlines()[-1])
    assert last["name"] == "sink_rotated"
    assert last["fields"]["next"] == os.path.basename(sink.segments[1])
    # aggregate discovers the segments in write order and sees every
    # event exactly once (incl. the flight record)
    files = agg_lib.discover_stream_files(str(tmp_path))
    assert files == sink.segments
    st = agg_lib.read_stream(str(tmp_path))
    assert st["events"] >= 120
    assert len(st["flight_records"]) == 1
    # ordering survives a shuffled listing
    assert agg_lib.order_segments(list(reversed(files))) == files


def test_rotation_fault_latches_error_not_training(tmp_path):
    """telemetry.rotate.pre: a failed rotation latches the sink error;
    the emitting thread never sees an exception and every
    already-written segment stays parseable."""
    path = str(tmp_path / "events.jsonl")
    sink = monitor.JsonlSink(path, rotate_mb=0.01)
    h = monitor.hub()
    h.enable(sink)
    faultpoint.arm("telemetry.rotate.pre", action="ioerror")
    try:
        for i in range(200):
            monitor.event("tick", i=i, pad="y" * 200)   # must never raise
    finally:
        # join the writer FIRST: disarming before the drain reaches the
        # rotation point would un-inject the fault under it
        h.disable()
        faultpoint.disarm()
    assert isinstance(sink.error, faultpoint.FaultInjected)
    assert len(sink.segments) == 1          # the rotation never landed
    res = flight.validate_events_file(path)
    assert res["errors"] == []
    # the latched error is visible through sink health (satellite 2)
    health = [s for s in h.summary()["sinks"]
              if s["type"] == "JsonlSink"]
    assert health and "FaultInjected" in health[0]["error"]
    # ...and the doctor's sink-health rule fires on exactly this
    rep = doctor.diagnose(sink_health=health)
    assert {r["rule"]: r["status"] for r in rep["rules"]}[
        "sink-health"] == "fired"


# ---------------------------------------------------------------------------
# live mode (flags.doctor_live)
# ---------------------------------------------------------------------------

def test_doctor_live_emits_findings_at_end_pass():
    from paddlebox_tpu.config import set_flags

    h = monitor.hub()
    ms = monitor.MemorySink()
    h.enable(ms)
    before = STATS.get("doctor.findings")
    set_flags(doctor_live=True)
    try:
        h.begin_pass(31)
        # a boundary far above the (tiny) pass wall -> boundary-wall
        h.record_train(steps=1, examples=8, seconds=0.01,
                       boundary_seconds=5.0,
                       boundary_split={"build": 3.0, "h2d": 2.0,
                                       "spill_fault_in": 0.0})
        h.end_pass()
        findings = h.last_doctor_findings
    finally:
        set_flags(doctor_live=False)
        h.disable()
    # live mode reads the CUMULATIVE registry, so rules fed by earlier
    # tests' counters may fire too — the boundary-wall finding must be
    # among them (assert membership, not position)
    assert findings
    assert any(f["rule"] == "boundary-wall" for f in findings)
    evs = ms.find("doctor.finding")
    assert evs, "live mode must emit doctor.finding events"
    bw = next(e for e in evs if e["fields"]["rule"] == "boundary-wall")
    # emitted inside the pass scope: the finding carries the pass tag
    assert bw["pass_id"] == 31
    assert bw["fields"]["suggestion"]
    assert STATS.get("doctor.findings") > before


def test_boxps_end_pass_returns_doctor_findings(tmp_path):
    from paddlebox_tpu.config import set_flags
    from paddlebox_tpu.fleet import BoxPS
    from test_monitor import _tiny_trainer

    tr, ds = _tiny_trainer(tmp_path)
    box = BoxPS(tr.store)
    h = monitor.hub()
    set_flags(doctor_live=True)
    try:
        box.begin_pass()
        tr.train_pass(ds)
        info = box.end_pass()
    finally:
        set_flags(doctor_live=False)
        h.disable()
    # live doctor ran; a tiny CPU pass is boundary-heavy, so findings
    # (if any) surface through the end_pass dict — both shapes are
    # legal, but the hub must have recorded the evaluation
    assert h.last_doctor_findings is not None or "doctor" not in info \
        or info["doctor"]


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_json_over_synthetic_stream(tmp_path, capsys):
    _write_stream(str(tmp_path / "rank0"),
                  [make_flight(1, seconds=10.0, train=4.0, boundary=4.0),
                   make_flight(2, seconds=10.0, train=4.0, boundary=4.5)])
    rc = doctor.main([str(tmp_path / "rank0"), "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    rep = json.loads(out)
    assert doctor.validate_report(rep) == []
    assert rep["verdict"].startswith("findings")
    assert [p["pass_id"] for p in rep["critical_path"]["passes"]] == [1, 2]
    assert any(f["rule"] == "boundary-wall" for f in rep["findings"])
    # human rendering carries the same facts
    rc2 = doctor.main([str(tmp_path / "rank0")])
    text = capsys.readouterr().out
    assert rc2 == 0
    assert "boundary-wall" in text and "suggestion:" in text


def test_cli_two_rank_world(tmp_path, capsys):
    roots = _golden_world(tmp_path)
    rc = doctor.main(roots + ["--json", "--rank-names", "4,7"])
    out = capsys.readouterr().out
    assert rc == 0
    rep = json.loads(out)
    assert rep["world"]["ranks"] == [4, 7]
    assert rep["world"]["passes"][0]["straggler"] == 7


def test_cli_fail_on_gates_serving_rules_from_stream(tmp_path, capsys):
    """ISSUE 19 CI gate: serving window records in a telemetry stream
    reach the serving rules through the CLI — --fail-on warn exits 1 on
    a version regression read off disk, 0 when the split looks clean."""
    bad = [make_flight(1),
           make_serving_window(
               100.0,
               versions={"1": {"role": "stable", "auc": 0.74},
                         "2": {"role": "candidate", "auc": 0.58}})]
    _write_stream(str(tmp_path / "bad"), bad)
    rc = doctor.main([str(tmp_path / "bad"), "--json",
                      "--fail-on", "warn"])
    out = capsys.readouterr().out
    assert rc == 1
    rep = json.loads(out)
    status = {r["rule"]: r["status"] for r in rep["rules"]}
    assert status["version-regression"] == "fired"

    good = [make_flight(1),
            make_serving_window(
                100.0,
                versions={"1": {"role": "stable", "auc": 0.74},
                          "2": {"role": "candidate", "auc": 0.74,
                                "score_kl": 0.02}})]
    _write_stream(str(tmp_path / "good"), good)
    assert doctor.main([str(tmp_path / "good"), "--json",
                        "--fail-on", "warn"]) == 0
    capsys.readouterr()


def test_cli_refuses_empty_inputs(tmp_path, capsys):
    assert doctor.main([]) == 2
    empty = tmp_path / "nothing"
    empty.mkdir()
    assert doctor.main([str(empty)]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# registry guards
# ---------------------------------------------------------------------------

def test_monitor_faultpoints_registered():
    """telemetry.rotate.pre lives in the closed registry and in the
    MONITOR_POINTS category the kill matrices exclude (same shape as
    ELASTIC/SERVING/EXCHANGE_POINTS)."""
    assert set(faultpoint.MONITOR_POINTS) <= set(faultpoint.POINTS)
    assert "telemetry.rotate.pre" in faultpoint.MONITOR_POINTS


def test_exchange_rules_name_adaptive_exchange_knobs():
    """ISSUE 16: the exchange rules' suggestions name the CONCRETE
    adaptive-exchange knobs — overflow points at the hierarchical
    topology, dedup drift at the per-pass wire controller, and the
    cross-rank exchange edge at both — never a bare 'tune the wire'."""
    rep = doctor.diagnose(**RULE_FIXTURES["exchange-overflow"][0])
    f = next(f for f in rep["findings"] if f["rule"] == "exchange-overflow")
    assert "flags.exchange_topology='hier'" in f["suggestion"]

    rep = doctor.diagnose(**RULE_FIXTURES["dedup-drift"][0])
    f = next(f for f in rep["findings"] if f["rule"] == "dedup-drift")
    assert "flags.exchange_adaptive" in f["suggestion"]

    rep = doctor.diagnose(**RULE_FIXTURES["cross-rank-flow"][0])
    f = next(f for f in rep["findings"] if f["rule"] == "cross-rank-flow")
    assert f["evidence"]["longest_edge"]["kind"] == "exchange"
    assert "flags.exchange_adaptive" in f["suggestion"]
    assert "flags.exchange_topology='hier'" in f["suggestion"]
    assert "note_flow_attribution" in f["suggestion"]
