"""Proactive all_to_all capacity sizing (Trainer._preplan_capacity).

The reference never drops tokens — it sizes its transfer buffers from the
actual batch (box_wrapper_impl.h:44-81). Under static shapes the analogue
is: histogram the pass's real token destinations BEFORE the first step
compiles and pick the capacity factor from the measured max, so a skewed
pass trains losslessly from batch 0 instead of training one lossy pass
while the adaptive doubling catches up (VERDICT r3 weak #4).
"""

import warnings

import numpy as np
import pytest

from paddlebox_tpu.config import flags
from paddlebox_tpu.data import DataFeedSchema
from paddlebox_tpu.data.slot_record import SlotRecordBatch
from paddlebox_tpu.data.dataset import SlotDataset
from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.models import DeepFMModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig

NUM_SLOTS, EMB_DIM, BATCH = 4, 4, 32


def _dataset(n_ex, key_fn, seed=0):
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=1,
                                batch_size=BATCH, max_len=1)
    rng = np.random.default_rng(seed)
    offs = np.arange(n_ex + 1, dtype=np.int64)
    sparse_values = [key_fn(rng, n_ex, s).astype(np.int64)
                     for s in range(NUM_SLOTS)]
    ds = SlotDataset(schema)
    ds.records = SlotRecordBatch(
        schema=schema, num=n_ex,
        sparse_values=sparse_values,
        sparse_offsets=[offs.copy() for _ in range(NUM_SLOTS)],
        float_values=[(rng.random(n_ex) < 0.3).astype(np.float32),
                      rng.normal(size=n_ex).astype(np.float32)],
        ins_id=np.zeros(n_ex, dtype=np.uint64),
        search_id=np.zeros(n_ex, dtype=np.uint64),
        rank=np.zeros(n_ex, dtype=np.int32),
        cmatch=np.zeros(n_ex, dtype=np.int32))
    return ds, schema


def _trainer(schema, mesh):
    store = HostEmbeddingStore(EmbeddingConfig(dim=EMB_DIM,
                                               learning_rate=0.05))
    return Trainer(DeepFMModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM,
                               dense_dim=1, hidden=(8,)),
                   store, schema, mesh,
                   TrainerConfig(global_batch_size=BATCH))


def _contiguous_skew_keys(rng, n, s):
    """DISTINCT keys, each batch occupying a contiguous key range: the
    whole batch lands on 1-2 table shards and dedup cannot shrink it —
    the worst case for fixed-capacity routing."""
    e = np.arange(n, dtype=np.int64)
    return (e // BATCH) * 100_000 + (e % BATCH) * NUM_SLOTS + s


def test_skewed_pass_trains_losslessly():
    """Each batch floods one shard with distinct keys. At the default
    capacity_factor=2.0 this drops most tokens; the preplan must raise
    capacity first so NOTHING drops and no capacity warning fires."""
    mesh = make_mesh(8)
    ds, schema = _dataset(4 * BATCH, _contiguous_skew_keys)
    tr = _trainer(schema, mesh)
    assert tr.cfg.capacity_factor == 2.0
    with warnings.catch_warnings():
        warnings.simplefilter("error")            # any drop warn = fail
        out = tr.train_pass(ds)
    assert out["routed_dropped"] == 0
    assert tr.cfg.capacity_factor == 8.0          # capped at n_shards


def test_spread_pass_grows_minimally():
    """A well-spread pass must size near the statistical max (small
    batches fluctuate past 2.0), never the n_shards blowup — and train
    losslessly."""
    mesh = make_mesh(8)

    def keys(rng, n, s):
        return rng.integers(0, 4096, size=n) | (np.int64(s + 1) << 40)

    ds, schema = _dataset(4 * BATCH, keys, seed=1)
    tr = _trainer(schema, mesh)
    out = tr.train_pass(ds)
    assert out["routed_dropped"] == 0
    assert tr.cfg.capacity_factor <= 4.0


def test_eval_tail_skew_rescans_and_stays_isolated():
    """A train-pass memo (tail dropped) must NOT satisfy an eval pass
    that scores the padded tail: 4 spread full batches + a half-batch
    tail flooding one shard. Eval must rescan (drop_last key), size its
    OWN capacity, drop nothing — and leave the train factor alone."""
    mesh = make_mesh(8)
    n_full = 4 * BATCH

    def keys(rng, n, s):
        ks = (rng.integers(0, 4096, size=n)
              | (np.int64(s + 1) << 40)).astype(np.int64)
        # tail examples: contiguous distinct keys -> one shard
        tail = np.arange(n - n_full, dtype=np.int64) * NUM_SLOTS \
            + s + 10_000_000
        ks[n_full:] = tail
        return ks

    ds, schema = _dataset(n_full + BATCH // 2, keys, seed=2)
    tr = _trainer(schema, mesh)
    out = tr.train_pass(ds)           # tail dropped by drop_last
    assert out["routed_dropped"] == 0
    train_capf = tr.cfg.capacity_factor
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        ev = tr.eval_pass(ds)         # scores the padded tail
    assert ev["routed_dropped"] == 0
    assert tr.cfg.capacity_factor == train_capf   # train step untouched
    assert tr._eval_capacity >= train_capf


def test_preplan_off_falls_back_to_adaptive():
    """With the flag off, the old behavior (lossy first pass + warn +
    doubling) remains — the backstop path stays exercised."""
    mesh = make_mesh(8)
    ds, schema = _dataset(4 * BATCH, _contiguous_skew_keys)
    old = flags.routed_capacity_preplan
    flags.routed_capacity_preplan = False
    try:
        tr = _trainer(schema, mesh)
        with pytest.warns(UserWarning, match="exceeded all_to_all"):
            out = tr.train_pass(ds)
        assert out["routed_dropped"] > 0
        assert tr.cfg.capacity_factor > 2.0       # adaptive kicked in
    finally:
        flags.routed_capacity_preplan = old
