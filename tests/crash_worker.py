"""Subprocess driver for the kill→resume fault-injection matrix.

Runs a small deterministic pass-loop training job with crash-safe
checkpointing (PassCheckpointer), resuming from the snapshot root if one
exists, and dumps the final dense/sparse/metric state to an npz for
bitwise comparison. Fault points are armed purely through the environment
(PBTPU_FAULTPOINT / _ACTION / _AFTER — see utils/faultpoint.py), so the
same invocation serves as the golden run, the killed run, and the
resuming re-run.

ISSUE 5 extensions (all env-driven so the golden run's MATH never
changes — snapshot cadence and mirroring are read-only side effects):

- every pass reshuffles the dataset through the persistent shuffle RNG
  (base order rebound each pass, so each pass's order depends only on the
  RNG state at its start — the checkpointable dataset cursor);
- ``PBTPU_CRASH_MIDPASS=<k>`` commits a MID-pass snapshot every k steps
  (Trainer.enable_midpass_snapshots) and a resumed run honors the
  cursor's ``mid_steps``/``shuffle_state`` via train_pass(skip_steps=…);
- ``PBTPU_CRASH_REMOTE=<uri>`` points the checkpointer at a remote
  (mock-hdfs CommandFS) root: local atomic commit → upload → donefile;
  ``PBTPU_CRASH_WIPE_LOCAL=1`` additionally empties the local staging
  root at startup (simulating resume on a REPLACEMENT host, which must
  download from the donefile).

Usage: python tests/crash_worker.py ROOT OUT_NPZ [--passes N]
"""

import argparse
import os
import shutil
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
TESTS = os.path.join(REPO, "tests")
if TESTS not in sys.path:
    sys.path.insert(0, TESTS)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mockfs  # noqa: E402
from paddlebox_tpu.data import DataFeedSchema, SlotDataset  # noqa: E402
from paddlebox_tpu.data.parser import parse_multislot_lines  # noqa: E402
from paddlebox_tpu.embedding import EmbeddingConfig, tiering  # noqa: E402
from paddlebox_tpu.fleet import BoxPS  # noqa: E402
from paddlebox_tpu.models import DNNCTRModel  # noqa: E402
from paddlebox_tpu.parallel import make_mesh  # noqa: E402
from paddlebox_tpu.train import Trainer, TrainerConfig  # noqa: E402
from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer  # noqa: E402

NUM_SLOTS = 3
VOCAB = 40


def synth(n=256, seed=11):
    rng = np.random.default_rng(seed)
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=1,
                                batch_size=64, max_len=2)
    w = np.random.default_rng(5).normal(size=(NUM_SLOTS, VOCAB))
    lines = []
    for _ in range(n):
        logits, parts, sl = 0.0, [], []
        for s in range(NUM_SLOTS):
            ids = rng.integers(0, VOCAB, size=2)
            sl.append(ids)
            logits += w[s, ids].sum()
        p = 1 / (1 + np.exp(-logits))
        parts.append(f"1 {float(rng.random() < p)}")
        parts.append(f"1 {rng.normal():.3f}")
        for s, ids in enumerate(sl):
            parts.append(
                f"2 {' '.join(str(int(i) + s * 1000003) for i in ids)}")
        lines.append(" ".join(parts))
    ds = SlotDataset(schema)
    ds.records = parse_multislot_lines(lines, schema)
    return ds, schema


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("root")
    ap.add_argument("out")
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--seed", type=int, default=11,
                    help="dataset seed (multi-host workers shard by rank)")
    args = ap.parse_args()

    mockfs.register_from_env()         # hdfs:// roots in the kill matrix
    remote = os.environ.get("PBTPU_CRASH_REMOTE", "")
    midpass = int(os.environ.get("PBTPU_CRASH_MIDPASS", "0"))
    if os.environ.get("PBTPU_CRASH_WIPE_LOCAL", "") == "1":
        # replacement-host model: the local staging root is gone; only
        # the remote donefile can deliver the resume
        shutil.rmtree(args.root, ignore_errors=True)

    ds, schema = synth(seed=args.seed)
    base = ds.records                  # pristine order; reshuffled per pass
    # flag-driven tier/partition (PBTPU_TABLE_TIERING / PBTPU_SPILL_* /
    # PBTPU_CRASH_SHARDS): the default stays the plain in-RAM store, and
    # the tier is a storage choice, not a math change — the spill-backed
    # sharded configuration must land the SAME golden planes
    store = tiering.store_from_flags(
        EmbeddingConfig(dim=4, learning_rate=0.05),
        n_shards=int(os.environ.get("PBTPU_CRASH_SHARDS", "1")))
    mesh = make_mesh(1)
    tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                             hidden=(8,)),
                 store, schema, mesh,
                 TrainerConfig(global_batch_size=64, dense_lr=2e-3,
                               auc_buckets=1 << 8),
                 seed=7)
    box = BoxPS(store)
    box.set_date(20260801)
    box.init_metric("job_auc", n_buckets=128)
    if remote:
        ckpt = PassCheckpointer(remote, keep_last_n=4, base_every=2,
                                staging_dir=args.root)
    else:
        ckpt = PassCheckpointer(args.root, keep_last_n=4, base_every=2)
    if midpass > 0:
        tr.enable_midpass_snapshots(ckpt, midpass, box,
                                    metrics=box.metrics)

    cursor = tr.resume(ckpt, box=box)
    skip = 0
    if cursor is not None:
        if cursor.get("shuffle_state"):
            ds.set_shuffle_state(cursor["shuffle_state"])
        skip = int(cursor.get("mid_steps") or 0)
    start = (int(cursor["pass_id"]) if cursor is not None else 0) + 1
    print(f"worker: resume cursor={None if cursor is None else {k: cursor[k] for k in ('pass_id', 'global_step', 'mid_steps')}} "
          f"-> starting at pass {start} (skip {skip})", flush=True)
    for p in range(start, args.passes + 1):
        # each pass's order = one permutation of the pristine base, drawn
        # from the persistent RNG — so the state BEFORE the draw (stashed
        # in the mid-pass cursor) fully determines the pass order
        tr.midpass_cursor_extra = {"shuffle_state": ds.shuffle_state()}
        ds.records = base
        ds.local_shuffle()
        box.begin_pass()
        tr.train_pass(ds, metrics=box.metrics,
                      skip_steps=(skip if p == start else 0))
        box.end_pass(checkpointer=ckpt, trainer=tr, dataset=ds)

    # final-state dump for bitwise comparison
    tr.flush_sparse()
    keys = np.sort(np.asarray(ds.unique_keys(), dtype=np.uint64))
    rows = store.get_rows(keys)
    dense = {f"p{i}": np.asarray(leaf) for i, leaf in
             enumerate(jax.tree_util.tree_leaves(
                 {"params": tr.params, "opt": tr.opt_state}))}
    met = box.metrics.get_state("job_auc")
    np.savez(args.out, keys=keys, rows=rows,
             global_step=np.int64(tr.global_step),
             pass_id=np.int64(box.pass_id),
             met_pos=np.asarray(met["pos"]),
             met_neg=np.asarray(met["neg"]), **dense)
    print("worker: done", flush=True)


if __name__ == "__main__":
    main()
