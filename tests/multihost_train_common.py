"""Shared data + training recipe for the cross-process multi-host train test.

Both the 2-process workers (tests/multihost_train_worker.py) and the
single-process reference run (tests/test_multihost_train.py) import this, so
parity is checked on literally the same code path — the only variable is
whether the 4-device (2 node x 2 dp) global mesh spans one process or two.
"""

from __future__ import annotations

import numpy as np

NUM_SLOTS = 4
VOCAB = 50
EXAMPLES_PER_RANK = 512
WORLD = 2
BATCH = 64
PASSES = 2


def make_schema():
    from paddlebox_tpu.data import DataFeedSchema
    return DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=1,
                              batch_size=BATCH, max_len=2)


def make_lines(rank: int) -> list[str]:
    """Rank-local shard of a learnable synthetic CTR set, with ins_id.

    Labels follow latent per-id weights so training has real signal; the
    ins_id prefix gives every example a globally unique, deterministic
    identity — the sort key that makes the post-shuffle global order
    process-count-invariant.
    """
    rng = np.random.default_rng(100 + rank)
    id_weight = np.random.default_rng(99).normal(
        size=(NUM_SLOTS, VOCAB)) * 1.5
    lines = []
    for i in range(EXAMPLES_PER_RANK):
        logits = 0.0
        parts = []
        ids_per_slot = []
        for s in range(NUM_SLOTS):
            k = rng.integers(1, 3)
            ids = rng.integers(0, VOCAB, size=k)
            ids_per_slot.append(ids)
            logits += id_weight[s, ids].sum()
        dense_val = rng.normal()
        p = 1.0 / (1.0 + np.exp(-(logits * 0.8)))
        label = float(rng.random() < p)
        parts.append(f"1 {label}")
        parts.append(f"1 {dense_val:.4f}")
        for s, ids in enumerate(ids_per_slot):
            signs = [str(int(v) + s * 1000003) for v in ids]
            parts.append(f"{len(signs)} {' '.join(signs)}")
        lines.append(f"ins_{rank}_{i}\t" + " ".join(parts))
    return lines


def sort_by_ins_id(records):
    """Canonical global order: ascending ins_id hash (unique per example)."""
    order = np.argsort(records.ins_id, kind="stable")
    return records.select(order)


def run_training(mesh, records, schema) -> dict:
    """The recipe under test: sharded table + jitted SPMD step, 2 passes."""
    from paddlebox_tpu.data import SlotDataset
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.train import Trainer, TrainerConfig

    ds = SlotDataset(schema)
    ds.records = records
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, learning_rate=0.15))
    model = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1,
                        hidden=(16, 8))
    tr = Trainer(model, store, schema, mesh,
                 TrainerConfig(global_batch_size=BATCH, dense_lr=3e-3,
                               auc_buckets=1 << 12), seed=0)
    out = {}
    for p in range(PASSES):
        res = tr.train_pass(ds)
        out[f"pass{p}_loss_first"] = res["loss_first"]
        out[f"pass{p}_loss_mean"] = res["loss_mean"]
        out[f"pass{p}_auc"] = res["auc"]
        out[f"pass{p}_steps"] = res["steps"]
    tr.flush_sparse()                       # D2H of dirty rows (cross-proc)
    keys = np.sort(records.unique_keys())
    rows = store.get_rows(keys)
    out["store_keys"] = int(len(store))
    out["store_w_sum"] = float(np.abs(rows[:, 2]).sum())
    out["store_show_sum"] = float(rows[:, 0].sum())
    return out
