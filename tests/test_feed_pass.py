"""FeedPassManager: incremental + overlapped pass-boundary transfer.

Covers the BoxPS FeedPass model (box_wrapper.h:994-1072: background
BeginFeedPass/WaitFeedPassDone; box_wrapper.h:423: EndPass moves only the
pass delta): resident-row reuse, dirty-row-only D2H, background staging,
and invalidation when the store mutates (shrink).
"""

import numpy as np
import pytest

import jax

from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.embedding.feed_pass import FeedPassManager
from paddlebox_tpu.embedding.working_set import bucket_size
from paddlebox_tpu.parallel import make_mesh


def cfg_small(**kw):
    kw.setdefault("dim", 4)
    kw.setdefault("optimizer", "adagrad")
    kw.setdefault("learning_rate", 0.1)
    return EmbeddingConfig(**kw)


def _keys(lo, hi):
    return np.arange(lo, hi, dtype=np.uint64) * np.uint64(2654435761) + 1


def test_bucket_size_monotonic_bounded():
    prev = 0
    for x in [1, 3, 16, 17, 100, 1000, 12345, 1 << 20]:
        b = bucket_size(x)
        assert b >= x
        assert b <= max(16, x + (x // 4) + 4)   # ≤ ~25% waste
        assert b >= prev or x < prev
        prev = b
    # buckets collapse many sizes onto few shapes
    assert len({bucket_size(x) for x in range(1000, 1100)}) <= 2


def test_reuse_moves_only_delta_bytes():
    """VERDICT round-1 'done' bar: two consecutive passes with 90% key
    overlap must move <20% of the table's bytes across the boundary."""
    c = cfg_small()
    store = HostEmbeddingStore(c)
    mgr = FeedPassManager(store)
    base = _keys(0, 1000)
    ws1 = mgr.begin_pass(base)
    full_bytes = mgr.last_h2d_bytes
    assert full_bytes > 0
    # train pass 1: touch every key, bump w column
    idx = ws1.translate(base)
    t = np.array(ws1.table)
    t[idx, 2] += 1.0
    assert mgr.end_pass(ws1, jax.numpy.asarray(t)) == 0   # lazy: no D2H
    # pass 2: 90% overlap (drop 100 keys, add 100 new)
    nxt = np.concatenate([base[100:], _keys(5000, 5100)])
    ws2 = mgr.begin_pass(nxt)
    assert mgr.last_fresh_rows == 100
    assert mgr.last_reused_rows == 900
    # boundary traffic = fresh H2D + retiring-row D2H, both O(churn)
    moved = mgr.last_h2d_bytes + mgr.last_d2h_bytes
    table_bytes = ws2.padded_rows * c.row_width * 4
    assert moved < 0.2 * (2 * table_bytes), (moved, table_bytes)
    # the 100 retired keys' trained values reached the store
    np.testing.assert_allclose(store.get_rows(base[:100])[:, 2], 1.0)
    # reused rows carry the POST-pass-1 values (w == 1), not store inits
    idx2 = ws2.translate(base[100:200])
    np.testing.assert_allclose(np.asarray(ws2.table)[idx2, 2], 1.0)
    # a flush materializes the rest for checkpoint/serving consumers
    mgr.flush()
    np.testing.assert_allclose(store.get_rows(base[100:])[:, 2], 1.0)


def test_dirty_row_writeback_only_touched():
    c = cfg_small()
    store = HostEmbeddingStore(c)
    mgr = FeedPassManager(store)
    keys = _keys(0, 50)
    ws = mgr.begin_pass(keys)
    touched_keys = keys[:10]
    idx = ws.translate(touched_keys)
    t = np.array(ws.table)
    t[:, 2] = 9.0                        # mutate EVERY row on device
    mgr.end_pass(ws, jax.numpy.asarray(t))
    mgr.flush()
    np.testing.assert_allclose(store.get_rows(touched_keys)[:, 2], 9.0)
    # untouched rows kept their host values (delta-only EndPass)
    assert not np.any(store.get_rows(keys[10:])[:, 2] == 9.0)
    # and the flush hook fires automatically on save_delta: dirty mask
    # covers exactly the touched rows
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        f = store.save_delta(os.path.join(d, "delta"))
        z = np.load(f)
        assert set(z["keys"].tolist()) <= set(keys.tolist())


def test_background_feed_pass_overlap():
    c = cfg_small()
    store = HostEmbeddingStore(c)
    mgr = FeedPassManager(store)
    p1 = _keys(0, 400)
    ws1 = mgr.begin_pass(p1)
    ws1.translate(p1)
    # stage pass 2 while "training" pass 1
    p2 = np.unique(np.concatenate([p1[50:], _keys(9000, 9050)]))
    mgr.begin_feed_pass(p2)
    mgr.wait_feed_pass_done()
    mgr.end_pass(ws1, ws1.table)
    ws2 = mgr.begin_pass(p2)
    assert mgr.last_fresh_rows == 50     # staged feed was consumed
    assert set(ws2.sorted_keys.tolist()) == set(p2.tolist())
    # staged fresh rows match deterministic store init
    fresh = _keys(9000, 9050)
    idxf = ws2.translate(fresh)
    np.testing.assert_allclose(
        np.asarray(ws2.table)[idxf, :c.row_width],
        store.get_rows(fresh), rtol=1e-6)


def test_stale_staging_discarded_on_key_mismatch():
    c = cfg_small()
    store = HostEmbeddingStore(c)
    mgr = FeedPassManager(store)
    p1 = _keys(0, 100)
    ws1 = mgr.begin_pass(p1)
    ws1.translate(p1)
    mgr.end_pass(ws1, ws1.table)
    mgr.begin_feed_pass(_keys(100, 200))       # staged for the wrong keys
    actual = _keys(200, 300)
    ws2 = mgr.begin_pass(actual)               # different keys arrive
    assert set(ws2.sorted_keys.tolist()) == set(actual.tolist())
    idx = ws2.translate(actual)
    np.testing.assert_allclose(
        np.asarray(ws2.table)[idx, :c.row_width],
        store.get_rows(actual), rtol=1e-6)


def test_shrink_invalidates_resident_reuse():
    c = cfg_small()
    store = HostEmbeddingStore(c)
    mgr = FeedPassManager(store)
    keys = _keys(0, 64)
    ws1 = mgr.begin_pass(keys)
    idx = ws1.translate(keys)
    t = np.array(ws1.table)
    t[idx, 2] = 5.0
    mgr.end_pass(ws1, jax.numpy.asarray(t))
    evicted = store.shrink(min_show=0.5)       # all shows are 0 → all out
    assert evicted == len(keys)
    ws2 = mgr.begin_pass(keys)                 # must NOT reuse stale rows
    assert mgr.last_fresh_rows == len(keys)
    idx2 = ws2.translate(keys)
    rows = np.asarray(ws2.table)[idx2]
    np.testing.assert_allclose(rows[:, 2], 0.0)  # fresh init, not 5.0


def test_eval_pass_reuses_but_never_inserts_or_retains():
    c = cfg_small()
    store = HostEmbeddingStore(c)
    mgr = FeedPassManager(store)
    train_keys = _keys(0, 100)
    ws1 = mgr.begin_pass(train_keys)
    idx = ws1.translate(train_keys)
    t = np.array(ws1.table)
    t[idx, 2] = 7.0
    mgr.end_pass(ws1, jax.numpy.asarray(t))
    n_before = len(store)
    eval_keys = np.concatenate([train_keys[:50], _keys(7000, 7020)])
    ws_eval = mgr.begin_pass(eval_keys, test_mode=True)
    assert len(store) == n_before              # unseen keys NOT inserted
    # resident rows visible to eval carry trained values
    idxe = ws_eval.translate(train_keys[:50])
    np.testing.assert_allclose(np.asarray(ws_eval.table)[idxe, 2], 7.0)
    assert mgr.last_reused_rows == 50
    # eval did not replace the retained train working set
    ws3 = mgr.begin_pass(train_keys)
    assert mgr.last_fresh_rows == 0
    assert mgr.last_reused_rows == len(train_keys)


def test_reuse_on_sharded_mesh():
    mesh = make_mesh(4)
    c = cfg_small()
    store = HostEmbeddingStore(c)
    mgr = FeedPassManager(store, mesh)
    p1 = _keys(0, 300)
    ws1 = mgr.begin_pass(p1)
    assert ws1.n_shards == 4
    idx = ws1.translate(p1)
    t = np.array(ws1.table)
    t[idx, 2] += 2.0
    mgr.end_pass(ws1, jax.device_put(t, ws1.table.sharding))
    p2 = np.concatenate([p1[30:], _keys(8000, 8030)])
    ws2 = mgr.begin_pass(p2)
    assert ws2.n_shards == 4
    idx2 = ws2.translate(p1[30:])
    np.testing.assert_allclose(np.asarray(ws2.table)[idx2, 2], 2.0)
    np.testing.assert_allclose(
        np.asarray(ws2.table)[ws2.translate(_keys(8000, 8030)),
                              :c.row_width],
        store.get_rows(_keys(8000, 8030)), rtol=1e-6)


def test_feed_error_surfaces_at_wait():
    c = cfg_small()
    store = HostEmbeddingStore(c)
    mgr = FeedPassManager(store)
    ws = mgr.begin_pass(_keys(0, 10))
    ws.translate(_keys(0, 10))
    mgr.end_pass(ws, ws.table)
    bad = np.array([1], dtype=np.float64)      # wrong dtype → astype ok...
    # simulate a failing store fetch by closing over a poisoned store call
    orig = store.lookup_or_init

    def boom(keys):
        raise RuntimeError("feed fetch failed")

    store.lookup_or_init = boom
    try:
        mgr.begin_feed_pass(_keys(10, 20))
        with pytest.raises(RuntimeError, match="feed fetch failed"):
            mgr.wait_feed_pass_done()
    finally:
        store.lookup_or_init = orig
