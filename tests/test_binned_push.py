"""Binned (scatter-free) push kernel — ops/pallas_kernels.binned_push.

CPU coverage runs the Pallas interpreter; parity is against the XLA
scatter+update path (summation ORDER differs, so tolerances not bitwise).
The real-TPU Mosaic path is exercised by bench.py and measured there
(see the kernel's module comment for numbers).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import flags
from paddlebox_tpu.embedding import sharded
from paddlebox_tpu.embedding.config import EmbeddingConfig
from paddlebox_tpu.native.key_index import block_plan
from paddlebox_tpu.ops import pallas_kernels as pk

N, TOK = 8192, 3000


def _xla_push(table, idx, grads, shows, clks, cfg):
    old = flags.binned_push
    flags.binned_push = False
    try:
        return np.asarray(jax.jit(
            lambda *a: sharded.push(*a, cfg))(table, idx, grads, shows,
                                              clks))
    finally:
        flags.binned_push = old


def _case(cfg, seed=0, n_rows=N, tok=TOK, skew=False):
    rng = np.random.default_rng(seed)
    if skew:
        # half the tokens hammer 20 hot rows in one super-block
        hot = rng.integers(0, 20, size=tok // 2)
        cold = rng.integers(0, n_rows, size=tok - tok // 2)
        idx = np.concatenate([hot, cold]).astype(np.int32)
    else:
        idx = rng.integers(0, n_rows, size=tok).astype(np.int32)
    grads = rng.normal(size=(tok, cfg.grad_width)).astype(np.float32)
    shows = np.ones(tok, np.float32)
    clks = (rng.random(tok) < 0.3).astype(np.float32)
    table = (rng.normal(size=(n_rows, cfg.row_width)) * 0.01
             ).astype(np.float32)
    return (jnp.asarray(table), jnp.asarray(idx), jnp.asarray(grads),
            jnp.asarray(shows), jnp.asarray(clks))


@pytest.mark.parametrize("opt", ["sgd", "adagrad", "adam", "ftrl"])
def test_parity_vs_xla_scatter(opt):
    cfg = EmbeddingConfig(dim=4, optimizer=opt, learning_rate=0.1)
    table, idx, grads, shows, clks = _case(cfg)
    want = _xla_push(table, idx, grads, shows, clks, cfg)
    got = np.asarray(pk.binned_push(table, idx, grads, shows, clks, cfg,
                                    interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_parity_with_host_plan_and_skew():
    cfg = EmbeddingConfig(dim=8, optimizer="adagrad", learning_rate=0.05)
    table, idx, grads, shows, clks = _case(cfg, seed=3, skew=True)
    want = _xla_push(table, idx, grads, shows, clks, cfg)
    SB, NB = pk.binned_push_geometry(cfg, N)
    plan_np = block_plan(np.asarray(idx), SB, NB)
    plan = tuple(jnp.asarray(a) for a in plan_np)
    got = np.asarray(pk.binned_push(table, idx, grads, shows, clks, cfg,
                                    plan=plan, interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_untouched_rows_bit_identical():
    """Rows no token references must keep their exact bits (stateful
    optimizers would otherwise decay momentum everywhere)."""
    cfg = EmbeddingConfig(dim=4, optimizer="adam")
    table, idx, grads, shows, clks = _case(cfg, seed=7, tok=200)
    got = np.asarray(pk.binned_push(table, idx, grads, shows, clks, cfg,
                                    interpret=True))
    touched = np.zeros(N, bool)
    touched[np.asarray(idx)] = True
    np.testing.assert_array_equal(got[~touched], np.asarray(table)[~touched])


def test_out_of_range_tokens_dropped():
    """idx >= n_rows (the routed path's empty-lane convention) must be
    dropped, matching the XLA path's mode='drop'."""
    cfg = EmbeddingConfig(dim=4, optimizer="sgd", learning_rate=1.0)
    table, idx, grads, shows, clks = _case(cfg, seed=9, tok=512)
    idx = jnp.asarray(np.where(np.arange(512) % 3 == 0, N, np.asarray(idx))
                      .astype(np.int32))
    want = _xla_push(table, idx, grads, shows, clks, cfg)
    got = np.asarray(pk.binned_push(table, idx, grads, shows, clks, cfg,
                                    interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_geometry_and_support():
    cfg = EmbeddingConfig(dim=8)
    # adaptive SB: nearest dividing block to SB* ~ sqrt(3 * G * n_rows)
    assert pk.binned_push_geometry(cfg, 524288) == (4096, 128)   # G=8
    assert pk.binned_push_geometry(cfg, 524289) is None  # odd row count
    assert pk.binned_push_geometry(cfg, 129 * 4096) == (4096, 129)
    # wide payloads (PP > 64 -> G=1): the KERNEL covers them (planes are
    # built in-kernel, so n_split no longer constrains the packed width
    # — the reference's full embedx envelope, box_wrapper.cc:444-461),
    # but the DISPATCH keeps the scatter there: measured faster in-step
    # (binned_push_supported docstring), so no host plan is built
    wide = EmbeddingConfig(dim=64)  # grad_width 65 -> PP 72 -> G=1
    assert pk._bp_geometry(wide, 524288) == (68, 72, 1, 2048)
    assert pk.binned_push_geometry(wide, 524288) is None
    very_wide = EmbeddingConfig(dim=280)  # PP 288 > 128: >128-lane acc
    assert pk._bp_geometry(very_wide, 524288) is not None
    # PP=24 (dim 16): G=4
    assert pk.binned_push_geometry(EmbeddingConfig(dim=16),
                                   524288) == (2048, 256)
    # big tables take bigger blocks (fewer grid steps)
    assert pk.binned_push_geometry(EmbeddingConfig(dim=16),
                                   262 * 32768) == (8192, 1048)
    # quant tables and non-TPU backends keep the XLA path
    assert not pk.binned_push_supported(jnp.zeros((4096, 13)), cfg) \
        or jax.default_backend() == "tpu"


def test_block_plan_native_matches_numpy_fallback():
    rng = np.random.default_rng(0)
    idx = rng.integers(0, 528384, size=50_000).astype(np.int32)
    SB, NB = 4096, 129
    order, rstart, end = block_plan(idx, SB, NB)
    # a valid grouping: every position appears once, blocks contiguous
    assert np.array_equal(np.sort(order), np.arange(len(idx)))
    bk = idx[order] // SB
    assert (np.diff(bk) >= 0).all()
    counts = np.bincount(idx // SB, minlength=NB)
    ends = np.cumsum(counts)
    np.testing.assert_array_equal(end, ends)
    np.testing.assert_array_equal(rstart, ((ends - counts) // 8) * 8)


def test_geometry_non_pow2_lane_groups():
    """PP=24 widths (e.g. dim=16: grad 17 -> P 20 -> PP 24) must round G
    down to a power of two (ADVICE r2) instead of losing the kernel."""
    cfg = EmbeddingConfig(dim=16)
    geom = pk._bp_geometry(cfg, 524288)
    assert geom is not None
    P, PP, G, SB = geom
    assert PP == 24 and G == 4 and SB % G == 0


# ---------------------------------------------------------------------------
# host dedup plan + device pre-merge (DedupKeysAndFillIdx + PushMergeCopy,
# box_wrapper_impl.h:103 / box_wrapper.cu:630-830)
# ---------------------------------------------------------------------------

from paddlebox_tpu.native.key_index import dedup_plan  # noqa: E402


def _dedup_5plan(idx_np, n_rows, cfg):
    geom = pk.binned_push_geometry(cfg, n_rows)
    SB, NB = geom if geom is not None else (n_rows, 1)
    o, u, s, r, e = dedup_plan(idx_np, n_rows, SB, NB)
    Z = np.zeros(0, np.int32)
    if geom is None:
        r, e = Z, Z
    return tuple(jnp.asarray(a) for a in (o, r, e, u, s))


def test_dedup_plan_properties():
    """Plan invariants both backends must hold: sorted grouping, exact
    segment runs, ascending distinct pad lanes, zero-width pad
    segments, out-of-range ids in the sentinel tail."""
    rng = np.random.default_rng(5)
    n_rows = 4096
    idx = rng.integers(-3, n_rows + 7, size=9000).astype(np.int32)
    order, uniq, segend, rstart, end = dedup_plan(idx, n_rows, 512, 8)
    r = np.where((idx < 0) | (idx >= n_rows), n_rows, idx)
    sr = r[order]
    assert np.array_equal(np.sort(order), np.arange(len(idx)))
    assert (np.diff(sr) >= 0).all()
    starts = np.concatenate([[0], segend[:-1]])
    u = int((uniq < n_rows).sum())
    for i in range(0, u, max(1, u // 37)):      # sampled segment check
        assert (sr[starts[i]:segend[i]] == uniq[i]).all()
    assert (np.diff(uniq.astype(np.int64)) > 0).all()
    assert (segend[u:] == starts[u:]).all()
    # unique-lane block windows cover exactly the in-block lanes
    for b in range(8):
        lanes = uniq[rstart[b]:end[b]]
        in_blk = lanes[(lanes >= 0) & (lanes < n_rows)]
        assert ((in_blk // 512) <= b).all()
        assert (uniq[:u] // 512 == b).sum() == \
            ((in_blk // 512) == b).sum()


@pytest.mark.parametrize("dim", [4, 64])
def test_premerge_parity_scatter_engine(dim):
    """push() with a 5-plan (pre-merge + sorted-unique scatter) must
    match the plain per-token scatter path — summation order differs
    (cumsum-diff), so tolerances, not bitwise."""
    cfg = EmbeddingConfig(dim=dim, optimizer="adagrad", learning_rate=0.05)
    n_rows = 4096
    table, idx, grads, shows, clks = _case(cfg, seed=11, n_rows=n_rows,
                                           tok=5000, skew=True)
    want = _xla_push(table, idx, grads, shows, clks, cfg)
    plan = _dedup_5plan(np.asarray(idx), n_rows, cfg)
    old = flags.binned_push
    flags.binned_push = False        # CPU: force the scatter engine
    try:
        got = np.asarray(jax.jit(
            lambda *a: sharded.push(*a, cfg, plan=plan))(
                table, idx, grads, shows, clks))
    finally:
        flags.binned_push = old
    np.testing.assert_allclose(got, want, atol=5e-5, rtol=5e-4)


def test_premerge_parity_kernel_engine():
    """Pre-merged unique lanes through the binned kernel (interpret
    mode) must match the per-token scatter reference."""
    cfg = EmbeddingConfig(dim=8, optimizer="adagrad", learning_rate=0.05)
    table, idx, grads, shows, clks = _case(cfg, seed=13, skew=True)
    want = _xla_push(table, idx, grads, shows, clks, cfg)
    SB, NB = pk.binned_push_geometry(cfg, N)
    o, u, s, r, e = dedup_plan(np.asarray(idx), N, SB, NB)
    plan5 = tuple(jnp.asarray(a) for a in (o, r, e, u, s))
    uniq, mg, ms, mc, kplan = sharded.plan_premerge(
        idx, grads, shows, clks, plan5)
    got = np.asarray(pk.binned_push(table, uniq, mg, ms, mc, cfg,
                                    plan=kplan, interpret=True))
    np.testing.assert_allclose(got, want, atol=5e-5, rtol=5e-4)


def test_premerge_counts_and_drops():
    """Pre-merged show/clk sums equal per-row token sums; out-of-range
    and pad lanes contribute nothing."""
    cfg = EmbeddingConfig(dim=4, optimizer="sgd", learning_rate=1.0)
    rng = np.random.default_rng(17)
    n_rows, tok = 512, 3000
    idx_np = rng.integers(0, n_rows + 40, size=tok).astype(np.int32)
    idx = jnp.asarray(idx_np)
    grads = jnp.asarray(rng.normal(size=(tok, cfg.grad_width))
                        .astype(np.float32))
    shows = jnp.asarray(np.ones(tok, np.float32))
    clks = jnp.asarray((rng.random(tok) < 0.4).astype(np.float32))
    plan = _dedup_5plan(idx_np, n_rows, cfg)
    uniq, mg, ms, mc, _ = jax.jit(sharded.plan_premerge)(
        idx, grads, shows, clks, plan)
    uniq, ms, mc = map(np.asarray, (uniq, ms, mc))
    valid = idx_np < n_rows
    want_shows = np.bincount(idx_np[valid], minlength=n_rows)
    u = int((uniq < n_rows).sum())
    got_shows = np.zeros(n_rows)
    got_shows[uniq[:u]] = ms[:u]
    np.testing.assert_allclose(got_shows, want_shows, atol=1e-4)
    assert np.abs(ms[u:]).max(initial=0) == 0
    assert np.abs(np.asarray(mg)[u:]).max(initial=0) == 0


def test_parity_dim16_pow2_groups():
    cfg = EmbeddingConfig(dim=16, optimizer="adagrad", learning_rate=0.05)
    table, idx, grads, shows, clks = _case(cfg, seed=5)
    want = _xla_push(table, idx, grads, shows, clks, cfg)
    got = np.asarray(pk.binned_push(table, idx, grads, shows, clks, cfg,
                                    interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("dim", [64, 128])
def test_parity_wide_dims(dim):
    """The reference dispatches embedx up to 280 (box_wrapper.cc:444-461);
    wide rows must run the same kernel (G=1, >128-lane acc for dim>=128),
    not fall back to the scatter (VERDICT r3 missing #1)."""
    cfg = EmbeddingConfig(dim=dim, optimizer="adagrad", learning_rate=0.05)
    table, idx, grads, shows, clks = _case(cfg, seed=11, tok=800)
    want = _xla_push(table, idx, grads, shows, clks, cfg)
    got = np.asarray(pk.binned_push(table, idx, grads, shows, clks, cfg,
                                    interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_merge_acc_matches_scatter_acc():
    """binned_merge_acc's contract is the scatter-add accumulator
    exactly (quantized tables build their dequant->update->requant pass
    on top of it): same sums, same touch counts, out-of-range dropped."""
    cfg = EmbeddingConfig(dim=8, optimizer="adagrad")
    _, idx, grads, shows, clks = _case(cfg, seed=21, tok=1500)
    idx = jnp.asarray(np.where(np.arange(1500) % 7 == 0, N,
                               np.asarray(idx)).astype(np.int32))
    payload = np.concatenate(
        [np.asarray(grads), np.asarray(shows)[:, None],
         np.asarray(clks)[:, None], np.ones((1500, 1), np.float32)],
        axis=1)
    want = np.zeros((N, cfg.grad_width + 3), np.float32)
    ii = np.asarray(idx)
    keep = ii < N
    np.add.at(want, ii[keep], payload[keep])
    got = np.asarray(pk.binned_merge_acc(idx, grads, shows, clks, cfg, N,
                                         interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)
    # touch counts are exact integers
    np.testing.assert_array_equal(got[:, -1], want[:, -1])


def test_quant_push_binned_wiring(monkeypatch):
    """The quantized push's binned branch end-to-end (gate, vma/plan
    plumbing, requant over the kernel acc) against the quant scatter
    path — backend-gated off on CPU, so force the gate and run the
    kernel in interpret mode."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.embedding import quant, sharded

    cfg = EmbeddingConfig(dim=8, optimizer="adagrad", learning_rate=0.05,
                          storage="int16")
    rng = np.random.default_rng(31)
    tok = 600
    idx = jnp.asarray(rng.integers(0, N, size=tok).astype(np.int32))
    grads = jnp.asarray(rng.normal(size=(tok, cfg.grad_width))
                        .astype(np.float32) * 0.01)
    shows = jnp.ones(tok, jnp.float32)
    clks = jnp.zeros(tok, jnp.float32)
    host = (rng.normal(size=(N, cfg.row_width)) * 0.01).astype(np.float32)
    want_tbl = sharded.push(quant.device_table(host.copy(), cfg, None),
                            idx, grads, shows, clks, cfg)

    monkeypatch.setattr(pk, "binned_acc_supported", lambda c, n: True)
    orig_acc = pk.binned_merge_acc
    monkeypatch.setattr(
        pk, "binned_merge_acc",
        lambda *a, **k: orig_acc(*a, **{**k, "interpret": True}))
    old = flags.binned_push
    flags.binned_push = True
    try:
        got_tbl = sharded.push(quant.device_table(host.copy(), cfg, None),
                               idx, grads, shows, clks, cfg)
    finally:
        flags.binned_push = old
    want = quant.decode_rows_np(np.asarray(want_tbl.fp),
                                np.asarray(want_tbl.qx), cfg)
    got = quant.decode_rows_np(np.asarray(got_tbl.fp),
                               np.asarray(got_tbl.qx), cfg)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_parity_wide_with_host_plan():
    cfg = EmbeddingConfig(dim=64, optimizer="sgd", learning_rate=0.1)
    table, idx, grads, shows, clks = _case(cfg, seed=13, tok=800)
    want = _xla_push(table, idx, grads, shows, clks, cfg)
    SB = pk._bp_geometry(cfg, N)[3]
    NB = N // SB
    plan_np = block_plan(np.asarray(idx), SB, NB)
    plan = tuple(jnp.asarray(a) for a in plan_np)
    got = np.asarray(pk.binned_push(table, idx, grads, shows, clks, cfg,
                                    plan=plan, interpret=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)
