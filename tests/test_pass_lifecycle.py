"""Day/pass orchestration: BoxPS lifecycle + FleetUtil save/load round-trips.

Mirrors the reference's day loop (SURVEY.md §3.4): set_date → begin_pass →
train → end_pass(save_delta) → save day base model; resume from the newest
donefile entry.
"""

import numpy as np
import pytest

from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.fleet import BoxPS, FleetUtil
from paddlebox_tpu.models import DNNCTRModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig
from tests.test_train_e2e import NUM_SLOTS, synth_dataset


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


def _make_trainer(mesh, schema, store, seed=0):
    model = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                        hidden=(16,))
    return Trainer(model, store, schema, mesh,
                   TrainerConfig(global_batch_size=64, auc_buckets=1 << 10),
                   seed=seed)


def test_day_loop_save_load_resume(mesh8, tmp_path):
    ds, schema = synth_dataset(512, seed=3)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    tr = _make_trainer(mesh8, schema, store)
    box = BoxPS(store)
    util = FleetUtil(str(tmp_path))

    day = 20260729
    box.set_date(day)
    for pass_id in (1, 2):
        box.begin_pass()
        tr.train_pass(ds)
        info = box.end_pass()
        assert info["pass_id"] == pass_id
        util.save_delta_model(store, (tr.params, tr.opt_state), day, pass_id)
    util.save_model(store, (tr.params, tr.opt_state), day)

    # donefiles recorded both planes
    assert util.latest("base_model.donefile")["day"] == day
    assert util.latest("delta_model.donefile")["pass"] == 2

    # resume into a FRESH store/trainer from the newest base model
    store2, (params2, opt2), got_day = util.load_model(
        (tr.params, tr.opt_state))
    assert got_day == day
    assert len(store2) == len(store)
    keys = ds.unique_keys()
    np.testing.assert_allclose(store2.get_rows(keys), store.get_rows(keys))
    import jax
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        params2, tr.params)

    # resumed trainer keeps training without error
    tr2 = _make_trainer(mesh8, schema, store2)
    tr2.params, tr2.opt_state = params2, opt2
    out = tr2.train_pass(ds)
    assert np.isfinite(out["loss_mean"])


def test_delta_log_replay(tmp_path):
    """save_base → train-ish mutations → save_delta → load replays deltas."""
    cfg = EmbeddingConfig(dim=2)
    store = HostEmbeddingStore(cfg)
    keys = np.arange(1, 50, dtype=np.uint64)
    store.lookup_or_init(keys)
    path = str(tmp_path / "sparse")
    store.save_base(path)
    rows = store.get_rows(keys)
    rows[:, 2] += 1.0
    store.write_back(keys, rows)
    store.save_delta(path)
    loaded = HostEmbeddingStore.load(path)
    np.testing.assert_allclose(loaded.get_rows(keys), store.get_rows(keys))


def test_midday_crash_recovery(tmp_path):
    """Yesterday's base + today's pass deltas, no base for today yet (crash
    mid-day): load_model must replay today's deltas on top of yesterday."""
    cfg = EmbeddingConfig(dim=2)
    store = HostEmbeddingStore(cfg)
    util = FleetUtil(str(tmp_path))
    keys = np.arange(1, 30, dtype=np.uint64)
    store.lookup_or_init(keys)
    dense = {"w": np.zeros(3, dtype=np.float32)}
    util.save_model(store, dense, day=1)

    # day 2: two passes of mutations, deltas only — then "crash"
    for p in (1, 2):
        rows = store.get_rows(keys)
        rows[:, 2] += p
        store.write_back(keys, rows)
        dense = {"w": np.full(3, float(p), dtype=np.float32)}
        util.save_delta_model(store, dense, day=2, pass_id=p)
        # delta dir is self-contained: sparse plane + dense plane together
        import os
        d = util.delta_dir(2, p)
        assert os.path.exists(os.path.join(d, "dense.npz"))
        assert any(f.startswith("delta-")
                   for f in os.listdir(os.path.join(d, "sparse")))

    store2, dense2, day = util.load_model({"w": np.zeros(3, dtype=np.float32)})
    assert day == 2
    np.testing.assert_allclose(store2.get_rows(keys), store.get_rows(keys))
    np.testing.assert_allclose(np.asarray(dense2["w"]), 2.0)


def test_phase_flip_gates_metrics():
    store = HostEmbeddingStore(EmbeddingConfig(dim=2))
    box = BoxPS(store)
    box.init_metric("join_auc", phase=1, n_buckets=64)
    box.init_metric("update_auc", phase=0, n_buckets=64)
    preds = np.array([0.2, 0.8]); labels = np.array([0.0, 1.0])
    box.metrics.add_data("join_auc", preds, labels)
    box.metrics.add_data("update_auc", preds, labels)
    assert box.get_metric_msg("join_auc")["size"] == 2
    assert box.get_metric_msg("update_auc")["size"] == 0
    box.flip_phase()
    box.metrics.add_data("update_auc", preds, labels)
    assert box.get_metric_msg("update_auc")["size"] == 2


def test_evicted_then_recreated_key_survives_delta_replay(tmp_path):
    """shrink() tombstones a key; re-creating it must cancel the tombstone so
    delta replay does not delete the live row."""
    cfg = EmbeddingConfig(dim=2)
    store = HostEmbeddingStore(cfg)
    keys = np.array([7, 8], dtype=np.uint64)
    rows = store.lookup_or_init(keys)
    rows[:, 0] = 5.0  # shows
    store.write_back(keys, rows)
    path = str(tmp_path / "sp")
    store.save_base(path)
    store.shrink(min_show=10.0)          # evicts both
    assert len(store) == 0
    rows = store.lookup_or_init(keys[:1])  # re-create key 7
    rows[:, 2] = 3.25
    store.write_back(keys[:1], rows)
    store.save_delta(path)
    loaded = HostEmbeddingStore.load(path)
    assert len(loaded) == 1              # 8 stays evicted, 7 lives
    np.testing.assert_allclose(loaded.get_rows(keys[:1])[:, 2], 3.25)


def test_auc_accumulator_matches_single_state():
    import jax
    from paddlebox_tpu.metrics import auc as auc_lib
    rng = np.random.default_rng(0)
    acc = auc_lib.AucAccumulator(256, drain_every=3)
    ref = auc_lib.new_state(256)
    fn = jax.jit(auc_lib.auc_update)
    for _ in range(10):
        p = rng.random(64).astype(np.float32)
        y = (rng.random(64) < 0.4).astype(np.float32)
        acc.update(fn, p, y)
        ref = fn(ref, p, y)
    a, b = acc.compute(), auc_lib.auc_compute(ref)
    for k in ("auc", "mae", "size"):
        assert abs(a[k] - b[k]) < 1e-5, (k, a[k], b[k])


def test_begin_end_pass_guards():
    box = BoxPS(HostEmbeddingStore(EmbeddingConfig(dim=2)))
    with pytest.raises(RuntimeError):
        box.end_pass()
    box.begin_pass()
    with pytest.raises(RuntimeError):
        box.begin_pass()
    box.end_pass()
