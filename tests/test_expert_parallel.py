"""Expert parallelism: all_to_all MoE dispatch == dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddlebox_tpu.parallel import expert as ep


def _setup(num_experts, d_model=16, d_hidden=32, batch=64, seed=0):
    mesh = ep.make_ep_mesh(8)
    params = ep.init_moe(jax.random.PRNGKey(seed), num_experts, d_model,
                         d_hidden)
    x = jnp.asarray(np.random.default_rng(seed).normal(
        size=(batch, d_model)).astype(np.float32))
    return mesh, params, x


@pytest.mark.parametrize("num_experts,top_k", [(8, 1), (8, 2), (16, 2),
                                               (32, 1)])
def test_moe_matches_dense_reference(num_experts, top_k):
    mesh, params, x = _setup(num_experts)
    want = ep.moe_reference(params, x, top_k=top_k)
    # capacity high enough that nothing drops → exact parity
    fn = ep.make_moe(mesh, num_experts, top_k=top_k, capacity_factor=64.0)
    assert ep.dropped_tokens(params, x, 8, top_k, 64.0) == 0
    got = fn(ep.shard_moe_params(mesh, params), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_capacity_drops_are_bounded_and_masked():
    mesh, params, x = _setup(8, batch=64)
    fn = ep.make_moe(mesh, 8, top_k=1, capacity_factor=0.25)
    got = np.asarray(fn(ep.shard_moe_params(mesh, params), x))
    dense = np.asarray(ep.moe_reference(params, x, top_k=1))
    # surviving rows match the dense value; dropped rows are exactly zero
    match = np.isclose(got, dense, rtol=2e-4, atol=2e-5).all(axis=1)
    zero = (got == 0.0).all(axis=1)
    assert (match | zero).all()
    assert zero.sum() > 0            # capacity 0.25 must actually drop
    assert match.sum() > 0


def test_moe_gradients_flow():
    mesh, params, x = _setup(8, batch=32)
    fn = ep.make_moe(mesh, 8, top_k=2, capacity_factor=64.0)
    sharded = ep.shard_moe_params(mesh, params)

    g = jax.grad(lambda p: jnp.sum(fn(p, x) ** 2))(sharded)
    assert float(jnp.abs(g["w1"]).sum()) > 0
    assert float(jnp.abs(g["gate"]).sum()) > 0


def test_indivisible_experts_raise():
    mesh, params, x = _setup(8)
    with pytest.raises(ValueError):
        ep.make_moe(mesh, 12)
