"""Subprocess driver for the serving-side observability loop (ISSUE 19).

A REAL serving process: its own hub + JsonlSink telemetry stream, a
standing ``serving`` trace scope (no pass lifecycle ever runs here),
a ServingServer tailing a donefile some TRAINING process published, and
a BatchingFrontend driving sampled request traffic through it. With
``PBTPU_TRACE=1`` and ``PBTPU_SERVING_TRACE_SAMPLE=1`` every batch opens
``serve/wait`` + ``serve/score`` spans, the score spans carrying the
donefile-propagated publish trace ids — the parent test merges this
stream with the trainer's and asserts the request spans parent-link to
the publish span ACROSS the process boundary. Before exiting, delayed
labels join the pending scores and one serving window record commits.

Usage: python tests/serving_obs_worker.py SERVE_ROOT TELEMETRY_DIR
       [--requests N]
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
TESTS = os.path.join(REPO, "tests")
if TESTS not in sys.path:
    sys.path.insert(0, TESTS)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mockfs  # noqa: E402
from paddlebox_tpu import monitor  # noqa: E402
from paddlebox_tpu.monitor import trace as trace_lib  # noqa: E402
from paddlebox_tpu.serving import (BatchingFrontend,  # noqa: E402
                                   ServingServer)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("serve_root")
    ap.add_argument("telemetry_dir")
    ap.add_argument("--requests", type=int, default=32)
    args = ap.parse_args()

    mockfs.register_from_env()
    h = monitor.hub()
    h.enable(monitor.JsonlSink(
        os.path.join(args.telemetry_dir, "events.jsonl")))
    # the pass-less process opens the standing serving scope (the same
    # call ServingServer.start() makes) — this worker drives poll_once
    # synchronously so the request count below is deterministic
    trace_lib.ensure_service("serving")

    from test_train_e2e import synth_dataset
    ds, _schema = synth_dataset(128)
    pb = next(iter(ds.batches(batch_size=64)))
    lc, lw, _ = pb.schema.float_split_cols("label")
    floats = np.concatenate([pb.floats[:, :lc], pb.floats[:, lc + lw:]],
                            axis=1)
    labels = pb.floats[:, lc:lc + lw].reshape(-1)

    srv = ServingServer(args.serve_root, poll_s=0.05)
    applied = srv.poll_once()
    assert srv.active is not None, "no version loadable from the root"

    n = int(args.requests)
    fe = BatchingFrontend(srv, max_batch=n, max_wait_s=0.02).start()
    try:
        futs = [fe.submit(pb.ids[i].astype(np.uint64), pb.mask[i],
                          floats[i]) for i in range(n)]
        probs = np.asarray([f.result(timeout=60) for f in futs])
    finally:
        fe.stop()
    joined = srv.observe_labels(labels[:n])
    rec = srv.commit_window(force=True)
    h.disable()

    print(json.dumps({
        "applied": applied, "version": srv.active.version,
        "served": int(srv._served), "scored": int(probs.size),
        "joined": {str(k): v for k, v in joined.items()},
        "window": {"requests": rec["requests"],
                   "p99_ms": rec["p99_ms"],
                   "versions": sorted(rec["versions"])},
        "frontend": fe.stats(),
    }), flush=True)


if __name__ == "__main__":
    main()
