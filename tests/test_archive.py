"""Binary archive (.pbar) roundtrip + dataset integration."""

import numpy as np
import pytest

from paddlebox_tpu.data import (DataFeedSchema, Slot, SlotType, SlotDataset,
                                archive_filelist, read_archive, write_archive)
from paddlebox_tpu.data.parser import _parse_python


def make_schema():
    return DataFeedSchema([
        Slot("label", SlotType.FLOAT, max_len=1),
        Slot("dense", SlotType.FLOAT, max_len=2),
        Slot("s0", SlotType.UINT64, max_len=3),
        Slot("s1", SlotType.UINT64, max_len=2),
    ], batch_size=4)


def make_lines(n, seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        parts = [f"1 {rng.integers(0, 2)}", f"2 {rng.random():.4f} {rng.random():.4f}"]
        for _s in range(2):
            ln = int(rng.integers(1, 4))
            parts.append(f"{ln} " + " ".join(
                str(int(k)) for k in rng.integers(0, 1 << 40, ln)))
        lines.append(" ".join(parts))
    return lines


def test_roundtrip(tmp_path):
    schema = make_schema()
    batch = _parse_python(make_lines(32, seed=5), schema, with_ins_id=False)
    p = str(tmp_path / "x.pbar")
    write_archive(p, batch)
    got = read_archive(p, schema)
    assert got.num == batch.num
    for a, b in zip(got.sparse_values, batch.sparse_values):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(got.sparse_offsets, batch.sparse_offsets):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(got.float_values, batch.float_values):
        np.testing.assert_array_equal(a, b)


def test_schema_mismatch_rejected(tmp_path):
    schema = make_schema()
    batch = _parse_python(make_lines(4), schema, with_ins_id=False)
    p = str(tmp_path / "x.pbar")
    write_archive(p, batch)
    other = DataFeedSchema([Slot("label", SlotType.FLOAT, max_len=1),
                            Slot("zz", SlotType.UINT64, max_len=2)])
    with pytest.raises(ValueError, match="do not match schema"):
        read_archive(p, other)


def test_dataset_loads_archives(tmp_path):
    schema = make_schema()
    texts = []
    for i in range(2):
        p = tmp_path / f"part-{i}.txt"
        p.write_text("\n".join(make_lines(16, seed=i)) + "\n")
        texts.append(str(p))
    pbars = archive_filelist(texts, schema, str(tmp_path / "arch"))
    assert all(f.endswith(".pbar") for f in pbars)

    ds_txt = SlotDataset(schema)
    ds_txt.set_filelist(texts)
    ds_txt.load_into_memory(global_shuffle=False)
    ds_bin = SlotDataset(schema)
    ds_bin.set_filelist(pbars)
    ds_bin.load_into_memory(global_shuffle=False)
    assert ds_bin.num_examples == ds_txt.num_examples
    np.testing.assert_array_equal(
        np.sort(np.concatenate(ds_bin.records.sparse_values)),
        np.sort(np.concatenate(ds_txt.records.sparse_values)))


def test_float_width_mismatch_rejected(tmp_path):
    schema = make_schema()
    batch = _parse_python(make_lines(4), schema, with_ins_id=False)
    p = str(tmp_path / "x.pbar")
    write_archive(p, batch)
    wider = DataFeedSchema([
        Slot("label", SlotType.FLOAT, max_len=1),
        Slot("dense", SlotType.FLOAT, max_len=3),  # was 2 when archived
        Slot("s0", SlotType.UINT64, max_len=3),
        Slot("s1", SlotType.UINT64, max_len=2),
    ], batch_size=4)
    with pytest.raises(ValueError, match="stale archive"):
        read_archive(p, wider)
