"""Fused scatter-accumulate push vs the XLA scatter reference.

The Pallas kernel runs in interpret mode on CPU (like gather_pool /
binned_push); the reference is sharded.push's scatter engine — scatter-add
merge into a full-table accumulator + one fused update pass. The parity
discipline is test_exchange.py's: gathers and the row-wise optimizer move
exact bits, so parity is asserted bit-for-bit under EXACT arithmetic
(lattice grads + a power-of-two SGD step), pinning lane routing, the
premerge, pad skipping, and the in-kernel update exactly; an adagrad
companion bounds the compile-fusion ulp variance at allclose. Covers the
engine resolver (auto classes, forced values + legacy aliases, quantized
tables filtered), the pad-clobber regression the predicated write-back
exists for, empty/all-pad batches, the 2-shard routed apply (premerged
lanes routed then cross-device-merged), and the per-engine floor
statements in step_probe.push_floor_analysis.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddlebox_tpu.config import flags
from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                     PassWorkingSet, exchange, quant,
                                     sharded)
from paddlebox_tpu.native.key_index import dedup_plan
from paddlebox_tpu.ops import pallas_kernels as pk
from paddlebox_tpu.parallel import make_mesh


@pytest.fixture(scope="module")
def mesh2():
    return make_mesh(2)


@pytest.fixture()
def engine_flag():
    old = flags.push_engine
    yield
    flags.push_engine = old


def _cfg(**kw):
    kw.setdefault("dim", 4)
    kw.setdefault("optimizer", "sgd")
    kw.setdefault("learning_rate", 0.0625)   # power of two: exact step
    return EmbeddingConfig(**kw)


def _table(cfg, n_rows, seed=0, pad_cols=0):
    rng = np.random.default_rng(seed)
    t = (rng.integers(-512, 512, size=(n_rows, cfg.row_width + pad_cols))
         / 1024.0).astype(np.float32)
    t[:, 0] = rng.integers(0, 20, size=n_rows)       # show
    t[:, 1] = rng.integers(0, 5, size=n_rows)        # clk
    t[0] = 0.0                                       # null-row contract
    return jnp.asarray(t)


def _tokens(cfg, n_rows, n_tok, seed=1, dup_mod=None):
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, n_rows, size=n_tok).astype(np.int32)
    if dup_mod:
        idx = (idx % dup_mod).astype(np.int32)
    grads = (rng.integers(-512, 512, size=(n_tok, cfg.grad_width))
             / 1024.0).astype(np.float32)
    shows = (idx > 0).astype(np.float32)
    clks = (rng.integers(0, 2, n_tok) * shows).astype(np.float32)
    grads[idx == 0] = 0.0                            # null rows carry zeros
    return idx, grads, shows, clks


def _premerged(cfg, idx, grads, shows, clks, n_rows):
    """Host dedup plan + device premerge — the lanes the fused engine
    consumes in production (one lane per unique row, pads out-of-range)."""
    o, u, s, r, e = dedup_plan(idx, n_rows, n_rows, 1)
    dplan = tuple(map(jnp.asarray, (o, np.zeros(0, np.int32),
                                    np.zeros(0, np.int32), u, s)))
    uniq, mg, ms, mc, kplan = sharded.plan_premerge(
        jnp.asarray(idx), jnp.asarray(grads), jnp.asarray(shows),
        jnp.asarray(clks), dplan)
    return uniq, mg, ms, mc, kplan


# ---------------------------------------------------------------------------
# kernel parity (interpret mode — hardware-free, SURVEY.md §4)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dim,dup_mod", [
    (4, None),        # narrow
    (4, 8),           # duplicate-heavy (the multi-hot merge shape)
    (64, None),       # wide rows (the dim64 floor point's class)
])
def test_kernel_interpret_bit_identical_to_scatter(dim, dup_mod):
    c = _cfg(dim=dim)
    table = _table(c, 64)
    idx, grads, shows, clks = _tokens(c, 64, 300, dup_mod=dup_mod)
    ref = np.asarray(sharded.push(table, jnp.asarray(idx),
                                  jnp.asarray(grads), jnp.asarray(shows),
                                  jnp.asarray(clks), c))
    uniq, mg, ms, mc, _ = _premerged(c, idx, grads, shows, clks, 64)
    out = pk.scatter_accumulate(table, uniq, mg, ms, mc, c,
                                interpret=True)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_kernel_matches_jnp_reference_bitwise():
    """The off-TPU production path (jnp reference) and the kernel are
    the same math — a drift between the two copies must fail here, not
    corrupt a CPU-validated run silently."""
    c = _cfg()
    table = _table(c, 64)
    idx, grads, shows, clks = _tokens(c, 64, 200, seed=5)
    uniq, mg, ms, mc, _ = _premerged(c, idx, grads, shows, clks, 64)
    out_k = pk.scatter_accumulate(table, uniq, mg, ms, mc, c,
                                  interpret=True)
    out_j = pk.scatter_accumulate(table, uniq, mg, ms, mc, c)  # jnp (CPU)
    np.testing.assert_array_equal(np.asarray(out_k), np.asarray(out_j))


def test_kernel_adagrad_close():
    """Adagrad companion (the test_exchange discipline): sqrt/divide
    fuses differently across program shapes — bounded, not bitwise."""
    c = _cfg(optimizer="adagrad", learning_rate=0.05)
    table = _table(c, 64, seed=2)
    idx, grads, shows, clks = _tokens(c, 64, 200, seed=7)
    ref = np.asarray(sharded.push(table, jnp.asarray(idx),
                                  jnp.asarray(grads), jnp.asarray(shows),
                                  jnp.asarray(clks), c))
    uniq, mg, ms, mc, _ = _premerged(c, idx, grads, shows, clks, 64)
    out = pk.scatter_accumulate(table, uniq, mg, ms, mc, c,
                                interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5,
                               atol=1e-6)


def test_untouched_rows_keep_exact_bits_and_pads_never_write():
    """Rows no lane names keep their exact bits, and pad lanes (out of
    range OR zero-touch) never issue a write — including the clobber
    case the predicated write-back exists for: pads clamp their read to
    row 0 while a REAL row-0 lane updates it; an unconditional clamped
    write would race the real update with stale bits."""
    c = _cfg()
    n = 64
    table = _table(c, n, seed=3)
    # one real row-0 lane (zero payload — the premerged null lane), two
    # real rows, then out-of-range pads and an in-range zero-touch pad
    idx = np.array([0, 3, 9, n, n + 1, 0], np.int32)
    tch = np.array([1, 1, 1, 1, 1, 0], np.float32)
    grads = np.zeros((6, c.grad_width), np.float32)
    grads[1:3] = 0.25
    shows = np.array([0, 1, 1, 1, 1, 0], np.float32)
    clks = np.zeros(6, np.float32)
    for interpret in (True, None):       # kernel and jnp reference
        out = np.asarray(pk.scatter_accumulate(
            table, jnp.asarray(idx), jnp.asarray(grads),
            jnp.asarray(shows), jnp.asarray(clks), c,
            touched=jnp.asarray(tch), interpret=interpret))
        ref = np.asarray(sharded.push(
            table, jnp.asarray(idx[:3]), jnp.asarray(grads[:3]),
            jnp.asarray(shows[:3]), jnp.asarray(clks[:3]), c))
        np.testing.assert_array_equal(out, ref)
        # row 0 held its zero bits through the concurrent pad reads
        np.testing.assert_array_equal(out[0], 0.0)
        untouched = np.setdiff1d(np.arange(n), idx[:3])
        np.testing.assert_array_equal(out[untouched],
                                      np.asarray(table)[untouched])


def test_all_pad_batch_leaves_table_bit_identical():
    """A fully-masked batch premerges to the null lane + pads: the only
    write is row 0's zero-payload update, a fixed point — the table is
    bit-identical after the push (empty-batch contract)."""
    c = _cfg()
    table = _table(c, 64, seed=4)
    idx = np.zeros(100, np.int32)                 # every token masked
    grads = np.zeros((100, c.grad_width), np.float32)
    shows = np.zeros(100, np.float32)
    clks = np.zeros(100, np.float32)
    uniq, mg, ms, mc, _ = _premerged(c, idx, grads, shows, clks, 64)
    out = pk.scatter_accumulate(table, uniq, mg, ms, mc, c,
                                interpret=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(table))


def test_padded_table_width_columns_pass_through():
    """Physical tables padded past row_width (table_pad_width): pad
    columns ride apply_updates untouched, same as the scatter engine."""
    c = _cfg()
    table = _table(c, 64, seed=6, pad_cols=5)
    idx, grads, shows, clks = _tokens(c, 64, 120, seed=8)
    ref = np.asarray(sharded.push(table, jnp.asarray(idx),
                                  jnp.asarray(grads), jnp.asarray(shows),
                                  jnp.asarray(clks), c))
    uniq, mg, ms, mc, _ = _premerged(c, idx, grads, shows, clks, 64)
    out = pk.scatter_accumulate(table, uniq, mg, ms, mc, c,
                                interpret=True)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_geometry_bounds():
    assert pk.scatter_accumulate_geometry(64, 13) is not None
    assert pk.scatter_accumulate_geometry(64, 512) is not None
    assert pk.scatter_accumulate_geometry(64, 513) is None   # width cap
    assert pk.scatter_accumulate_geometry(0, 13) is None


# ---------------------------------------------------------------------------
# engine resolver (THE selection function — compiled dispatch == record)
# ---------------------------------------------------------------------------

def test_resolver_forced_and_aliases(engine_flag):
    c = _cfg()
    for spelling in ("scatter_accumulate", "fused"):
        flags.push_engine = spelling
        assert pk.resolve_push_engine(c, 64, premerged=True) == \
            "scatter_accumulate"
        # the fused engine REQUIRES premerged unique lanes — forced
        # without them falls back to the scatter, recorded truthfully
        assert pk.resolve_push_engine(c, 64, premerged=False) == \
            "xla_scatter"
        # quantized tables filtered (the fused engine updates f32 rows)
        assert pk.resolve_push_engine(c, 64, premerged=True,
                                      storage_f32=False) == "xla_scatter"
        # width past the per-row-DMA cap filtered
        assert pk.resolve_push_engine(c, 64, premerged=True,
                                      table_width=1024) == "xla_scatter"
    for spelling in ("scatter", "xla_scatter"):
        flags.push_engine = spelling
        assert pk.resolve_push_engine(c, 64, premerged=True) == \
            "xla_scatter"
    flags.push_engine = "nope"
    with pytest.raises(ValueError, match="push_engine"):
        pk.resolve_push_engine(c, 64, premerged=True)


def test_resolver_auto_classes(engine_flag):
    """Auto off-TPU never picks a kernel engine (CPU production runs the
    scatter; the jnp fused path is a forced parity/A/B tool only)."""
    flags.push_engine = "auto"
    c = _cfg()
    assert pk.resolve_push_engine(c, 4096, premerged=True) == \
        "xla_scatter"
    assert pk.resolve_push_engine(c, 4096, premerged=False) == \
        "xla_scatter"


def test_forced_fused_disables_binned_geometry(engine_flag):
    """binned_push_geometry must not hand out block windows the fused
    dispatch will never consume (wasted host plan + H2D)."""
    c = _cfg(dim=8, optimizer="adagrad", learning_rate=0.05)
    flags.push_engine = "auto"
    base = pk._bp_geometry(c, 1 << 16)
    assert base is not None and base[2] >= 2      # binned-eligible class
    flags.push_engine = "scatter_accumulate"
    assert pk.binned_push_geometry(c, 1 << 16) is None
    flags.push_engine = "xla_scatter"
    assert pk.binned_push_geometry(c, 1 << 16) is None


def test_push_dispatch_forced_fused_bit_identical(engine_flag):
    """sharded.push's dispatch (the resolver's verdict) routes premerged
    lanes through the fused engine — bit-identical to the scatter path."""
    c = _cfg()
    table = _table(c, 64, seed=9)
    idx, grads, shows, clks = _tokens(c, 64, 150, seed=10)
    ref = np.asarray(sharded.push(table, jnp.asarray(idx),
                                  jnp.asarray(grads), jnp.asarray(shows),
                                  jnp.asarray(clks), c))
    uniq, mg, ms, mc, kplan = _premerged(c, idx, grads, shows, clks, 64)
    flags.push_engine = "scatter_accumulate"
    out = sharded.push(table, uniq, mg, ms, mc, c, plan=kplan,
                       premerged=True)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_quant_table_keeps_scatter_engines(engine_flag):
    """A quantized table must never reach the fused engine even when
    forced — the dispatch falls back and stays correct."""
    c = _cfg(storage="int8", dim=8)
    store = HostEmbeddingStore(c)
    rng = np.random.default_rng(11)
    keys = rng.choice(1 << 30, size=40, replace=False).astype(np.uint64)
    ws = PassWorkingSet.begin_pass(store, keys, make_mesh(1))
    assert quant.is_quant(ws.table)
    idx, grads, shows, clks = _tokens(c, ws.num_keys, 80, seed=12)
    ref = sharded.push(ws.table, jnp.asarray(idx), jnp.asarray(grads),
                       jnp.asarray(shows), jnp.asarray(clks), c)
    flags.push_engine = "scatter_accumulate"
    out = sharded.push(ws.table, jnp.asarray(idx), jnp.asarray(grads),
                       jnp.asarray(shows), jnp.asarray(clks), c)
    np.testing.assert_array_equal(np.asarray(out.fp), np.asarray(ref.fp))
    np.testing.assert_array_equal(np.asarray(out.qx), np.asarray(ref.qx))


# ---------------------------------------------------------------------------
# routed apply (the same kernel serves the exchange — test_exchange's
# lattice-grad discipline)
# ---------------------------------------------------------------------------

def _device_plans(idx_flat, n_rows, n_dev):
    parts = [dedup_plan(a, n_rows, n_rows, 1)
             for a in idx_flat.reshape(n_dev, -1)]
    Z = jnp.zeros(0, jnp.int32)
    return (jnp.asarray(np.concatenate([p[0] for p in parts])), Z, Z,
            jnp.asarray(np.concatenate([p[1] for p in parts])),
            jnp.asarray(np.concatenate([p[2] for p in parts])))


def _ws(cfg, n_keys, mesh):
    store = HostEmbeddingStore(cfg)
    keys = np.random.default_rng(7).choice(
        1 << 40, size=n_keys, replace=False).astype(np.uint64)
    return store, PassWorkingSet.begin_pass(store, keys, mesh)


def test_routed_fused_bit_identical_to_single_shard(mesh2, engine_flag):
    """2-shard routed apply under the fused engine: per-source premerge
    → f32 wire → cross-device lane merge → scatter_accumulate equals the
    single-shard scatter push bit-for-bit under exact arithmetic."""
    c = _cfg()
    store, ws = _ws(c, 60, mesh2)
    rng = np.random.default_rng(13)
    idx = rng.integers(0, ws.num_keys + 1, size=64).astype(np.int32)
    grads = (rng.integers(-512, 512, size=(64, c.grad_width))
             / 1024.0).astype(np.float32)
    shows = (idx > 0).astype(np.float32)
    clks = (rng.integers(0, 2, 64) * shows).astype(np.float32)
    grads[idx == 0] = 0.0
    plan = _device_plans(idx, ws.padded_rows, 2)
    args = tuple(map(jnp.asarray, (idx, grads, shows, clks)))
    want = np.asarray(sharded.push(ws.table, *args, c))

    flags.push_engine = "scatter_accumulate"

    def body(tshard, i, g, sh, ck, *p):
        return exchange.routed_push(tshard, i, g, sh, ck, c, ("dp",),
                                    2.0, wire="f32", plan=p)

    out = jax.jit(jax.shard_map(
        body, mesh=mesh2, in_specs=(P("dp"),) * 10,
        out_specs=P("dp")))(ws.table, *args, *plan)
    np.testing.assert_array_equal(np.asarray(out), want)


def test_routed_fused_premerged_deferred_bit_identical(mesh2,
                                                       engine_flag):
    """The deferred-apply form (PR-2 PushOperandStager program): the
    step premerges onto unique lanes and the apply routes them through
    the fused tail — bit-identical to the inline fused exchange."""
    c = _cfg()
    store, ws = _ws(c, 60, mesh2)
    rng = np.random.default_rng(15)
    idx = rng.integers(0, ws.num_keys + 1, size=64).astype(np.int32)
    grads = (rng.integers(-512, 512, size=(64, c.grad_width))
             / 1024.0).astype(np.float32)
    shows = (idx > 0).astype(np.float32)
    clks = (rng.integers(0, 2, 64) * shows).astype(np.float32)
    grads[idx == 0] = 0.0
    plan = _device_plans(idx, ws.padded_rows, 2)
    args = tuple(map(jnp.asarray, (idx, grads, shows, clks)))
    want = np.asarray(sharded.push(ws.table, *args, c))

    flags.push_engine = "scatter_accumulate"

    def deferred(tshard, i, g, sh, ck, *p):
        mg, ms, mc = sharded.deferred_push_operands(i, g, sh, ck, p)
        return exchange.routed_push(tshard, p[3], mg, ms, mc, c, ("dp",),
                                    2.0, wire="f32", premerged=True)

    out = jax.jit(jax.shard_map(
        deferred, mesh=mesh2, in_specs=(P("dp"),) * 10,
        out_specs=P("dp")))(ws.table, *args, *plan)
    np.testing.assert_array_equal(np.asarray(out), want)


# ---------------------------------------------------------------------------
# trainer end-to-end (forced fused engine on the single-shard CPU path:
# the host plan + in-step premerge + fused jnp apply, incl. the deferred
# push-overlap program)
# ---------------------------------------------------------------------------

def _trainer_fixture(seed=3):
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.data.parser import parse_multislot_lines
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.train import Trainer, TrainerConfig

    num_slots, vocab = 3, 40
    rng = np.random.default_rng(21)
    schema = DataFeedSchema.ctr(num_sparse=num_slots, num_float=1,
                                batch_size=16, max_len=2)
    lines = []
    for _ in range(64):
        parts = [f"1 {int(rng.random() < 0.3)}", f"1 {rng.normal():.4f}"]
        for s in range(num_slots):
            k = rng.integers(1, 3)
            ids = rng.integers(0, vocab, size=k) + s * 1000003
            parts.append(f"{len(ids)} {' '.join(str(i) for i in ids)}")
        lines.append(" ".join(parts))
    ds = SlotDataset(schema)
    ds.records = parse_multislot_lines(lines, schema)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4, learning_rate=0.1))
    model = DeepFMModel(num_slots=num_slots, emb_dim=4, dense_dim=1,
                        hidden=(8,))
    tr = Trainer(model, store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=16), seed=seed)
    return tr, ds, store


@pytest.mark.skipif(not hasattr(jax, "shard_map"),
                    reason="the jitted step needs jax.shard_map "
                           "(same bar as the suite's trainer tests)")
def test_trainer_forced_fused_matches_auto(engine_flag):
    """Full train_pass parity: forcing the fused engine (host dedup plan
    forced on, in-step premerge, jnp fused apply — incl. the deferred
    push-overlap apply program) reproduces the auto engine's losses and
    persisted rows (pooling/merge are linear; adagrad-free SGD-like
    parity is not available here, so bounded like the fused-pull test)."""

    def run(engine):
        flags.push_engine = engine
        tr, ds, store = _trainer_fixture()
        if engine == "scatter_accumulate":
            assert tr._use_plan          # forced fused engages the plan
        out = tr.train_pass(ds)
        tr.flush_sparse()
        keys = ds.unique_keys()
        return out, store.peek_rows(np.unique(keys))

    out_f, rows_f = run("scatter_accumulate")
    out_a, rows_a = run("auto")
    assert abs(out_f["loss_mean"] - out_a["loss_mean"]) < 1e-5
    assert abs(out_f["auc"] - out_a["auc"]) < 1e-6
    np.testing.assert_allclose(rows_f, rows_a, rtol=1e-5, atol=1e-6)


def test_trainer_records_push_engine(engine_flag):
    """The trainer's resolver helper (the bench/flight record source)
    names the engine the compiled dispatch contains."""
    tr, ds, store = _trainer_fixture()
    keys = ds.unique_keys()
    ws = PassWorkingSet.begin_pass(store, np.unique(keys), tr.mesh)
    flags.push_engine = "auto"
    assert tr.resolved_push_engine(ws) == "xla_scatter"   # CPU auto
    flags.push_engine = "scatter_accumulate"
    tr2, ds2, store2 = _trainer_fixture(seed=4)
    keys2 = ds2.unique_keys()
    ws2 = PassWorkingSet.begin_pass(store2, np.unique(keys2), tr2.mesh)
    assert tr2.push_premerged(ws2)
    assert tr2.resolved_push_engine(ws2) == "scatter_accumulate"


# ---------------------------------------------------------------------------
# per-engine floor statements (step_probe.push_floor_analysis)
# ---------------------------------------------------------------------------

def test_push_floor_per_engine_statements(engine_flag):
    from paddlebox_tpu.utils.step_probe import (finalize_push_floor,
                                                push_floor_analysis)
    c = _cfg(dim=8, optimizer="adagrad", learning_rate=0.05)
    peaks = (1.97e14, 8.2e11)                # v5e-style peak table
    fl = push_floor_analysis(c, 1 << 16, 213_000, peaks=peaks,
                             premerged=True, unique_lanes=80_000)
    # every candidate engine at this geometry carries a floor + closure
    assert set(fl["engines"]) == set(pk.PUSH_ENGINES)
    assert fl["engine"] in pk.PUSH_ENGINES
    for e in fl["engines"].values():
        assert "closed" in e and e["floor_seconds"] > 0
    # the fused engine's floor scales with unique lanes, not the table —
    # at this geometry it must undercut the O(table) engines
    sa = fl["engines"]["scatter_accumulate"]["floor_seconds"]
    assert sa < fl["engines"]["xla_scatter"]["floor_seconds"]
    assert fl["best_engine"] == "scatter_accumulate"
    # measured far off the floor: the active closure names the gap and
    # every engine statement closes independently
    finalize_push_floor(fl, measured_push=1.0)
    assert isinstance(fl["closed"], str) and fl["closed"].startswith(
        "measured")
    assert all(isinstance(e["closed"], str)
               for e in fl["engines"].values())
    finalize_push_floor(fl, measured_push=sa * 2)
    assert fl["engines"]["scatter_accumulate"]["closed"] is True


def test_push_floor_unpremerged_names_the_premerge_requirement():
    from paddlebox_tpu.utils.step_probe import push_floor_analysis
    c = _cfg(dim=8, optimizer="adagrad", learning_rate=0.05)
    fl = push_floor_analysis(c, 1 << 16, 213_000, peaks=(1.97e14, 8.2e11),
                             premerged=False)
    assert "premerged" in fl["engines"]["scatter_accumulate"]["note"]


def test_binned_enable_knob_never_silently_voids_a_force(engine_flag):
    """flags.binned_push=False is an ablation knob, not a second silent
    gate on an explicit force: the forced binned_kernel resolution must
    not depend on it (geometry + backend are the contract — on CPU both
    settings fall back identically), and the floor's candidate entry
    names the knob so a doctor suggestion is actionable."""
    from paddlebox_tpu.utils.step_probe import push_floor_analysis
    c = _cfg(dim=8, optimizer="adagrad", learning_rate=0.05)
    flags.push_engine = "binned_kernel"
    old = flags.binned_push
    try:
        flags.binned_push = True
        with_knob = pk.resolve_push_engine(c, 1 << 16, premerged=False)
        flags.binned_push = False
        without = pk.resolve_push_engine(c, 1 << 16, premerged=False)
        assert with_knob == without
        flags.push_engine = "auto"
        fl = push_floor_analysis(c, 1 << 16, 213_000,
                                 peaks=(1.97e14, 8.2e11))
        assert "binned_push" in fl["engines"]["binned_kernel"]["note"]
    finally:
        flags.binned_push = old
