"""Bench regression gate (BENCH_BEST.json) + the --dryrun tier-1 smoke.

Round 5 shipped a reproducible 1.87x headline regression inside a green
artifact. The gate makes that class of failure impossible: every recorded
number is compared against the best recorded value per metric, a >10%
unwaived regression fails audit_ok and the exit code, and the CPU dryrun
exercises the gate + stage-attribution + push-floor code paths on every
PR instead of only on-chip.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_PY = os.path.join(REPO, "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("_bench_mod", BENCH_PY)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_gate_trips_on_unwaived_regression(bench):
    best = {"device_kind": None, "threshold": 0.10,
            "metrics": {"headline_eps": 1000.0, "matrix.a": 500.0}}
    g = bench.apply_regression_gate(
        {"headline_eps": 850.0, "matrix.a": 510.0}, best, "cpu")
    assert not g["ok"]
    assert g["regressed"] == ["headline_eps"]
    assert g["lines"]["headline_eps"].startswith("REGRESS(")
    assert g["lines"]["matrix.a"].startswith("ok(")


def test_gate_honors_waiver_note(bench):
    best = {"device_kind": None,
            "metrics": {"headline_eps": 1000.0},
            "waivers": {"headline_eps": "known tunnel variance"}}
    g = bench.apply_regression_gate({"headline_eps": 500.0}, best, "cpu")
    assert g["ok"]
    assert "waived: known tunnel variance" in g["lines"]["headline_eps"]


def test_gate_within_threshold_passes(bench):
    best = {"device_kind": None, "metrics": {"headline_eps": 1000.0}}
    g = bench.apply_regression_gate({"headline_eps": 905.0}, best, "cpu")
    assert g["ok"]


def test_gate_skips_foreign_hardware_and_missing_best(bench):
    best = {"device_kind": "TPU v5 lite",
            "metrics": {"headline_eps": 1000.0}}
    g = bench.apply_regression_gate({"headline_eps": 1.0}, best, "cpu")
    assert g["ok"] and "skipped" in g
    assert bench.apply_regression_gate({}, None, "cpu")["ok"]


def test_gate_reports_missing_and_new_metrics(bench):
    best = {"device_kind": None, "metrics": {"gone_metric": 10.0}}
    g = bench.apply_regression_gate({"new_metric": 5.0}, best, "cpu")
    assert g["ok"]
    assert "missing" in g["lines"]["gone_metric"]
    assert "new" in g["lines"]["new_metric"]


def test_collect_gate_metrics_namespace(bench):
    detail = {
        "matrix": {"kstep_f32": {"examples_per_sec_per_chip": 7.0},
                   "broken": {"error": "boom"}},
        "e2e": {"examples_per_sec_per_chip": 3.0},
        "host": {"derived_max_feed_eps_per_chip": 9.0},
    }
    m = bench.collect_gate_metrics(11.0, detail)
    assert m == {"headline_eps": 11.0, "matrix.kstep_f32": 7.0,
                 "e2e_eps": 3.0, "host.derived_max_feed_eps": 9.0}


def test_collect_gate_metrics_serving_points(bench):
    """The serving drill's publish/swap/latency numbers land in the gate
    namespace (ISSUE 7); a failed drill ({'error': …}) contributes
    nothing instead of poisoning the namespace."""
    detail = {"matrix": {"serving": {
        "publish_seconds": 0.8, "swap_pause_ms": 0.02, "p99_ms": 12.5,
        "p50_ms": 4.0, "serve_eps": 900.0}}}
    m = bench.collect_gate_metrics(1.0, detail)
    assert m["serving.publish_seconds"] == 0.8
    assert m["serving.swap_pause_ms"] == 0.02
    assert m["serving.p99_ms"] == 12.5
    assert "serving.p50_ms" not in m      # only the three gated points
    m2 = bench.collect_gate_metrics(1.0,
                                    {"matrix": {"serving":
                                                {"error": "boom"}}})
    assert not any(k.startswith("serving.") for k in m2)


def test_collect_gate_metrics_serving_split_point(bench):
    """The version-split drill gates exactly shadow_p99_ms (ISSUE 19) —
    the AUC/KL attribution rides the artifact, not the gate; a failed
    drill contributes nothing."""
    detail = {"matrix": {"serving_split": {
        "shadow_p99_ms": 9.5, "shadow_p50_ms": 3.0, "stable_auc": 0.8,
        "candidate_auc": 0.79, "score_kl": 0.01, "requests": 256}}}
    m = bench.collect_gate_metrics(1.0, detail)
    assert m["serving_split.shadow_p99_ms"] == 9.5
    assert not any(k.startswith("serving_split.")
                   for k in m if k != "serving_split.shadow_p99_ms")
    m2 = bench.collect_gate_metrics(
        1.0, {"matrix": {"serving_split": {"error": "boom"}}})
    assert not any(k.startswith("serving_split.") for k in m2)


def test_collect_gate_metrics_serving_fleet_point(bench):
    """The fleet drill gates exactly p99_ms + swap_convergence_s
    (ISSUE 20) — the hedge/governor attribution rides the artifact, not
    the gate; a failed drill contributes nothing."""
    detail = {"matrix": {"serving_fleet": {
        "p99_ms": 12.5, "swap_convergence_s": 0.4, "p50_ms": 3.0,
        "hedges": 9, "hedges_won": 9, "promote_decision": "hold",
        "requests": 128}}}
    m = bench.collect_gate_metrics(1.0, detail)
    assert m["serving_fleet.p99_ms"] == 12.5
    assert m["serving_fleet.swap_convergence_s"] == 0.4
    assert not any(k.startswith("serving_fleet.") for k in m
                   if k not in ("serving_fleet.p99_ms",
                                "serving_fleet.swap_convergence_s"))
    m2 = bench.collect_gate_metrics(
        1.0, {"matrix": {"serving_fleet": {"error": "boom"}}})
    assert not any(k.startswith("serving_fleet.") for k in m2)


def test_gate_bare_s_is_lower_is_better_but_per_s_is_not(bench):
    """Bare ``_s`` metrics (the fleet's swap convergence) gate in the
    latency direction while ``_per_s`` stays throughput: a slower
    convergence regresses, and a FASTER fetch rate must not read as a
    regression through the suffix test."""
    best = {"device_kind": None, "threshold": 0.10,
            "metrics": {"serving_fleet.swap_convergence_s": 2.0,
                        "spill_10x.fetch_keys_per_s": 5000.0}}
    g = bench.apply_regression_gate(
        {"serving_fleet.swap_convergence_s": 8.0,
         "spill_10x.fetch_keys_per_s": 9000.0}, best, "cpu")
    assert not g["ok"]
    assert g["regressed"] == ["serving_fleet.swap_convergence_s"]
    assert g["lines"]["spill_10x.fetch_keys_per_s"].startswith("ok(+80%")
    g2 = bench.apply_regression_gate(
        {"serving_fleet.swap_convergence_s": 0.5,
         "spill_10x.fetch_keys_per_s": 2000.0}, best, "cpu")
    assert g2["regressed"] == ["spill_10x.fetch_keys_per_s"]
    assert g2["lines"][
        "serving_fleet.swap_convergence_s"].startswith("ok(+300%")
    # sub-floor convergence walls clamp like the other latency points:
    # a 3x swing under 0.05s is timer noise, not a regression
    g3 = bench.apply_regression_gate(
        {"serving_fleet.swap_convergence_s": 0.03,
         "spill_10x.fetch_keys_per_s": 5000.0},
        {"device_kind": None,
         "metrics": {"serving_fleet.swap_convergence_s": 0.01,
                     "spill_10x.fetch_keys_per_s": 5000.0}}, "cpu")
    assert g3["ok"]


def test_gate_latency_metrics_are_lower_is_better(bench):
    """Metrics named *_ms / *_seconds gate in the latency direction: a
    HIGHER current value regresses, a lower one is an improvement —
    throughput metrics keep the original direction in the same pass."""
    best = {"device_kind": None, "threshold": 0.10,
            "metrics": {"serving.p99_ms": 10.0,
                        "serving.publish_seconds": 2.0,
                        "headline_eps": 1000.0}}
    g = bench.apply_regression_gate(
        {"serving.p99_ms": 20.0, "serving.publish_seconds": 1.0,
         "headline_eps": 1000.0}, best, "cpu")
    assert not g["ok"] and g["regressed"] == ["serving.p99_ms"]
    assert g["lines"]["serving.p99_ms"].startswith("REGRESS(-50%")
    assert g["lines"]["serving.publish_seconds"].startswith("ok(+100%")
    g2 = bench.apply_regression_gate(
        {"serving.p99_ms": 10.5, "headline_eps": 1000.0,
         "serving.publish_seconds": 2.0}, best, "cpu")
    assert g2["ok"]                       # within threshold both ways


def test_gate_latency_floor_ignores_timer_noise(bench):
    """Sub-floor latencies (the swap pause is one attribute rebind,
    sub-µs) are timer noise: a 3x relative swing below the floor must not
    trip the gate, while a real-scale regression past it still does."""
    best = {"device_kind": "cpu",
            "metrics": {"serving.swap_pause_ms": 0.0003,
                        "serving.p99_ms": 10.0}}
    g = bench.apply_regression_gate(
        {"serving.swap_pause_ms": 0.0009, "serving.p99_ms": 10.0},
        best, "cpu")
    assert g["ok"] and g["lines"]["serving.swap_pause_ms"].startswith("ok")
    g2 = bench.apply_regression_gate(
        {"serving.swap_pause_ms": 5.0, "serving.p99_ms": 10.0}, best, "cpu")
    assert not g2["ok"] and g2["regressed"] == ["serving.swap_pause_ms"]


def test_committed_bench_best_is_wellformed():
    with open(os.path.join(REPO, "BENCH_BEST.json")) as f:
        best = json.load(f)
    assert best["device_kind"] == "TPU v5 lite"
    assert 0 < best["threshold"] <= 0.5
    assert best["metrics"]["headline_eps"] > 1e6, \
        "the recorded best headline predates the round-5 regression"
    for name, note in best.get("waivers", {}).items():
        assert name in best["metrics"] and len(note) > 10


def test_bench_dryrun_smoke():
    """`bench.py --dryrun` (tier-1): the gate + attribution + floor code
    paths run on CPU at tiny geometry; the gate must trip on an injected
    synthetic regression and the process must exit 0 with every check
    green."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS="")
    r = subprocess.run([sys.executable, BENCH_PY, "--dryrun"],
                       capture_output=True, text=True, env=env,
                       timeout=560, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == "bench_dryrun" and out["ok"]
    assert out["checks"]["gate_trips_on_regression"]
    assert out["checks"]["waiver_untrips"]
    assert out["checks"]["attribution_ok"]
    assert out["checks"]["floor_ok"]
    # the per-point push-engine record (ISSUE 13): the resolver's
    # verdict is recorded per matrix point and the floor carries the
    # per-candidate-engine closure statements the doctor names concrete
    # flags.push_engine forces from
    assert out["checks"]["push_engine_recorded"]
    assert out["push_engine"] in ("xla_scatter", "binned_kernel",
                                  "scatter_accumulate")
    assert out["push_overlap"] == "on"
    assert "stages" in out and "sparse_push" in out["stages"]
    assert out["gate_example_lines"]["headline_eps"].startswith("REGRESS")
    # the serving drill's points must exist in the artifact (ISSUE 7):
    # publish timed, hot-swap paused-and-measured, tail latency recorded,
    # zero failed requests across the swap
    assert out["checks"]["serving_fields"], out.get("serving")
    assert out["checks"]["latency_gate_trips_lower_is_better"]
    assert out["serving"]["publish_seconds"] > 0
    assert out["serving"]["swap_pause_ms"] > 0
    assert out["serving"]["p99_ms"] > 0
    # the version-split point must exist with per-version attribution
    # (ISSUE 19): shadow tail latency gate-held, AUC/score-KL recorded,
    # schema-valid serving record, the three serving rules evaluated
    assert out["checks"]["serving_obs_fields"], out.get("serving_split")
    assert out["serving_split"]["shadow_p99_ms"] > 0
    assert 0 <= out["serving_split"]["stable_auc"] <= 1
    assert out["serving_split"]["score_kl"] >= 0
    assert set(out["serving_split"]["doctor_rules"]) == {
        "version-regression", "p99-burn", "swap-regression"}
    # the fleet point must exist with its acceptance property
    # (ISSUE 20): routed tail held UNDER the injected slow replica by
    # hedging, fleet-wide swap convergence timed, the governor's hold
    # recorded, and the fleet-degraded rule fired off that hold — so
    # serving_fleet enters the BENCH_BEST gate from day one
    assert out["checks"]["fleet_fields"], out.get("serving_fleet")
    assert out["checks"]["convergence_gate_trips_lower_is_better"]
    sf = out["serving_fleet"]
    assert 0 < sf["p99_ms"] < 150.0
    assert sf["swap_convergence_s"] > 0
    assert sf["hedges_won"] >= 1
    assert sf["promote_decision"] == "hold"
    assert sf["doctor_rules"] == {"fleet-degraded": "fired"}
    # the sharded-exchange matrix points must exist with their identity
    # fields (ISSUE 10): table_layout/exchange_wire/shard count recorded,
    # dedup ratio measured — so sharded points enter the BENCH_BEST gate
    # from day one
    assert out["checks"]["sharded_fields"], out.get("sharded")
    assert out["sharded"]["table_layout"] == "sharded"
    assert out["sharded"]["exchange_wire"] == "f32"
    assert out["sharded"]["table_shards"] == 2
    assert 0 < out["sharded"]["dedup_ratio"] <= 1.0
    # the tiered-table point must exist with its acceptance property
    # (ISSUE 11): a working set >= 10x the RAM cache budget through the
    # sharded+spill path, and the show-count-weighted policy's hot-tier
    # hit rate beating the direct-mapped last-wins baseline on the SAME
    # traffic — so spill_10x enters the BENCH_BEST gate from day one
    assert out["checks"]["spill_fields"], out.get("spill")
    assert out["spill"]["hot_hit_rate"] > out["spill"]["direct_hot_hit_rate"]
    assert out["spill"]["fetch_keys_per_s"] > 0
    # the set-associative geometry point (PR 17): on the adversarial
    # colliding stream the N-way cache must beat direct-mapped at the
    # SAME row budget with byte-identical row files, and the baseline
    # must show the conflict misses that explain the gap — so the
    # spill_assoc point enters the BENCH_BEST gate from day one
    assert out["checks"]["assoc_fields"], out.get("spill_assoc")
    sa = out["spill_assoc"]
    assert sa["assoc"] == 4
    assert sa["assoc_hit_rate"] > sa["direct_hit_rate"]
    assert sa["conflict_misses_direct"] > 0
    assert sa["parity"] is True
    # the world-trace embed (ISSUE 15): a traced probe pass merged into
    # a Chrome-trace summary with a publish flow edge, and the span-
    # level data reached the doctor's cross-rank-flow rule
    assert out["checks"]["trace_embedded"]
