"""Cross-process multi-host training (VERDICT round-1 missing #6).

Two real worker processes `jax.distributed.initialize` into ONE global
4-device mesh (2 processes x 2 virtual CPU devices, gloo cross-process
collectives) and run the full sharded train_pass — embedding table sharded
over all four devices, routed all_to_all lookups crossing the process
boundary, dense pmean riding the same mesh — after a TCP global shuffle and
FileStore-rendezvous control plane. Loss/AUC/store state must match the
identical recipe run single-process on a same-shape local mesh.

Reference pattern: test_collective_base.py:141 (_run_cluster spawns trainer
subprocesses with real NCCL over loopback).
"""

import json
import os
import sys

import numpy as np
import pytest

import jax

import multihost_train_common as common
from paddlebox_tpu.data.parser import parse_multislot_lines
from paddlebox_tpu.data.slot_record import SlotRecordBatch
from paddlebox_tpu.distributed.launch import launch
from paddlebox_tpu.parallel import make_mesh

# capability check, not a version pin: the workers simulate "2 local
# devices per process" through jax.config.update("jax_num_cpu_devices",
# 2) (distributed/role_maker.init_distributed) — a jax build without
# that config option raises "Unrecognized config option" inside every
# worker before the mesh even forms. Named skip > 2 opaque subprocess
# tracebacks (ISSUE 20 satellite: environmental, not a product bug).
if not hasattr(jax.config, "jax_num_cpu_devices"):
    pytest.skip("this jax build lacks the jax_num_cpu_devices config "
                "option (the 2-virtual-cpu-devices-per-worker "
                "simulation cannot start)", allow_module_level=True)

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _reference_run():
    """Same recipe, single process, same (2, 2) global mesh shape."""
    parts = [parse_multislot_lines(common.make_lines(r), common.make_schema(),
                                   with_ins_id=True)
             for r in range(common.WORLD)]
    records = common.sort_by_ins_id(SlotRecordBatch.concat(parts))
    import jax
    mesh = make_mesh(num_devices=4, num_nodes=2,
                     devices=jax.devices()[:4])
    return common.run_training(mesh, records, common.make_schema())


@pytest.mark.slow
def test_two_process_global_mesh_train_parity(tmp_path):
    env = {
        "PBTPU_TEST_WORKDIR": str(tmp_path),
        # workers must not inherit the conftest's 8-device XLA_FLAGS: each
        # configures its own 2 local devices via jax_num_cpu_devices
        "XLA_FLAGS": "",
    }
    code = launch(common.WORLD,
                  [sys.executable,
                   os.path.join(TESTS_DIR, "multihost_train_worker.py")],
                  store_dir=str(tmp_path / "store"), base_env=env)
    assert code == 0
    with open(tmp_path / "result.json") as f:
        multi = json.load(f)

    single = _reference_run()

    assert multi["pass0_steps"] == single["pass0_steps"] == (
        common.WORLD * common.EXAMPLES_PER_RANK // common.BATCH)
    # same global mesh shape + same global batches -> near-bit parity
    for k in ("pass0_loss_first", "pass0_loss_mean", "pass1_loss_mean"):
        assert multi[k] == pytest.approx(single[k], rel=2e-5), (k, multi, single)
    for k in ("pass0_auc", "pass1_auc"):
        assert multi[k] == pytest.approx(single[k], abs=2e-4), (k, multi, single)
    # training moved (not a degenerate parity of constants)
    assert multi["pass1_auc"] > 0.6
    assert multi["pass1_loss_mean"] < multi["pass0_loss_first"]
    # the flushed host stores agree on the learned sparse state
    assert multi["store_keys"] == single["store_keys"]
    assert multi["store_show_sum"] == pytest.approx(single["store_show_sum"])
    assert multi["store_w_sum"] == pytest.approx(single["store_w_sum"],
                                                 rel=1e-4)
