"""Worker process for the elastic shrink-to-N−1 kill matrix (ISSUE 6).

Launched (N processes, ``fail_stop=False``) by tests/test_elastic.py.
Every rank loads the SAME full dataset (ins_id = 1..n) and partitions
each pass deterministically over the live member list through the
persistent shuffle RNG — identical state on every rank at every pass
boundary — so after a rank loss the survivors know exactly which records
the departed rank owned and re-route its unconsumed tail among
themselves with zero exchange traffic (``SlotDataset.reroute_records``).

The failure-response loop is the production shape:

  try: partition → begin_pass → train_pass → end_pass(checkpointer)
  except PeerFailureError:
      world, cursor = trainer.recover_world(world, e, ckpt, box)
      # drain + mid-pass drain-snapshot + generation-sealed re-formation
      # + coordinated election over the survivors + restore; continue
      # the pass from the elected cursor with the dead tail re-routed

Outputs per ORIGINAL rank r (under PBTPU_TEST_WORKDIR):
  out_{r}.npz       final dense/sparse/metric planes + global AUC
  info_{r}.json     elected cursor, final generation/members, reroute ids
  consumed_{r}.json per-pass consumed ins_ids of the SURVIVING timeline
  events_{r}.jsonl  telemetry (world_resize / reform_* / peer_* events)

Env knobs (see tests/test_elastic.py):
  PBTPU_ELASTIC_ROOT        snapshot roots base (per-rank subdir)
  PBTPU_ELASTIC_PASSES      pass count (default 3)
  PBTPU_ELASTIC_N           dataset size (default 768 → 8 steps/rank @ 3)
  PBTPU_ELASTIC_MIDPASS     mid-pass snapshot cadence (default 2)
  PBTPU_ELASTIC_STEP_SLEEP  per-step sleep (slows passes so detection
                            lands MID-pass — the mid-cursor reroute path)
  PBTPU_ELASTIC_LOST_S      watchdog lost_after seconds (default 2.0)
  PBTPU_FAULTPOINT(+_ONLY_RANK/_AFTER)   first victim's kill
  PBTPU_FAULTPOINT2(+_RANK/_AFTER)       second victim (in-reform kills)
  PBTPU_ELASTIC_SIM         JSON {"orig_members": [...], "dead": [...],
                            "elected": [q, m]} — SIMULATED-shrink golden:
                            no kill, no reform; replay the exact record
                            schedule the recovered world trained, at N−1
                            from the start. Final planes must be
                            bit-identical to the survivors of the real
                            killed run.

Elastic GROW mode (ISSUE 18) — the self-healing e2e:
  PBTPU_ELASTIC_GROW=1      launcher spawns train_world+1 processes; the
                            extra one is a REPLACEMENT that joins via
                            ElasticWorld.admit while the incumbents train
  PBTPU_ELASTIC_TRAIN_WORLD the training world size (launcher ranks
                            below it are incumbents; at/above are
                            joiners)
  PBTPU_ELASTIC_JOINER_AS   the ORIGINAL rank identity the joiner
                            assumes (the dead rank's: its checkpoint
                            root, its seed, its shard of every pass)

The grow flow: the victim dies mid-pass → the survivors shrink (gen 1,
degraded) via recover_world → at the next pass boundary their
RemediationController's poll_grow — gated on the REAL doctor
heartbeat-gap finding over the hub — all-gathers the joiner's admit
registration, re-forms WITH it (gen 2, full world), and the coordinated
resume election rolls every rank (newcomer included, restoring from the
dead rank's snapshots) back to the last common pass boundary. Training
then continues at full world: the final planes must be BIT-IDENTICAL to
a never-failed run of the same world size.
"""

import json
import os
import sys
import time
import traceback

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from crash_worker import synth  # noqa: E402
from paddlebox_tpu import monitor  # noqa: E402
from paddlebox_tpu.config import set_flags  # noqa: E402
from paddlebox_tpu.data import SlotDataset  # noqa: E402
from paddlebox_tpu.data.slot_record import SlotRecordBatch  # noqa: E402
from paddlebox_tpu.distributed import RoleMaker  # noqa: E402
from paddlebox_tpu.distributed.resilience import (ElasticWorld,  # noqa: E402
                                                  PeerFailureError,
                                                  WorldFencedError,
                                                  coordinated_resume)
from paddlebox_tpu.embedding import (EmbeddingConfig,  # noqa: E402
                                     HostEmbeddingStore)
from paddlebox_tpu.fleet import BoxPS  # noqa: E402
from paddlebox_tpu.models import DNNCTRModel  # noqa: E402
from paddlebox_tpu.parallel import make_mesh  # noqa: E402
from paddlebox_tpu.train import Trainer, TrainerConfig  # noqa: E402
from paddlebox_tpu.utils import faultpoint  # noqa: E402
from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer  # noqa: E402

NUM_SLOTS = 3
BS = 32


def _ds_for(schema, records) -> SlotDataset:
    d = SlotDataset(schema)
    d.records = records
    return d


def _concat(parts):
    parts = [p for p in parts if p is not None and p.num > 0]
    return SlotRecordBatch.concat(parts) if parts else None


def build_pass_records(ds, base, me, members, old_members=None, skip=0):
    """One pass's record stream for rank ``me``, via the shared RNG.

    Draws EXACTLY: one permutation (the pass order), plus — when
    continuing a shrunk pass (``skip`` > 0 over the ``old_members``
    partition) — one reroute draw per departed-rank tail, in sorted
    departed order. Every rank (live survivors AND the simulated golden)
    performs the same draws in the same order, so the cursor stays in
    lockstep. Returns (records_or_None, own_head_ids): records to train
    with skip_steps=0, and the ins_ids of the already-consumed own head
    (the elected cursor's m batches)."""
    ds.records = base
    ds.local_shuffle()                    # the pass's permutation draw
    if skip > 0 and old_members is not None:
        shards = ds.member_shards(len(old_members))
        own = shards[old_members.index(me)]
        head = min(skip * BS, own.num)
        own_head_ids = [int(i) for i in own.ins_id[:head]]
        own_tail = own.select(np.arange(head, own.num))
        adopted = []
        for d in sorted(set(old_members) - set(members)):
            dsh = shards[old_members.index(d)]
            dhead = min(skip * BS, dsh.num)
            tail = dsh.select(np.arange(dhead, dsh.num))
            routed = ds.reroute_records(tail, len(members))
            adopted.append(routed[members.index(me)])
        return _concat([own_tail] + adopted), own_head_ids
    shards = ds.member_shards(len(members))
    return shards[members.index(me)], []


def reroute_info(ds_probe, base, me, members, old_members, skip,
                 shuffle_state):
    """Recompute (on a throwaway RNG clone) what build_pass_records will
    assign, for the exactly-once audit: the departed ranks' head ids
    (consumed-by-the-departed per the elected cursor), their re-routed
    tail ids, and the ids THIS rank adopts."""
    probe = SlotDataset(ds_probe.schema)
    probe.set_shuffle_state(shuffle_state)
    probe.records = base
    probe.local_shuffle()
    shards = probe.member_shards(len(old_members))
    dead_head, dead_tail, adopted = [], [], []
    for d in sorted(set(old_members) - set(members)):
        dsh = shards[old_members.index(d)]
        dhead = min(skip * BS, dsh.num)
        dead_head += [int(i) for i in dsh.ins_id[:dhead]]
        tail = dsh.select(np.arange(dhead, dsh.num))
        dead_tail += [int(i) for i in tail.ins_id]
        routed = probe.reroute_records(tail, len(members))
        mine = routed[members.index(me)]
        if mine is not None:
            adopted += [int(i) for i in mine.ins_id]
    return {"dead_head_ids": dead_head, "dead_tail_ids": dead_tail,
            "adopted_ids": adopted}


def global_auc(col, metrics, name="job_auc") -> float:
    st = metrics.get_state(name)
    pos = np.asarray(col.all_reduce(np.asarray(st["pos"], np.float64)))
    neg = np.asarray(col.all_reduce(np.asarray(st["neg"], np.float64)))
    p, n = pos.sum(), neg.sum()
    if p == 0 or n == 0:
        return float("nan")
    neg_below = np.concatenate([[0.0], np.cumsum(neg)[:-1]])
    return float((pos * (neg_below + neg / 2)).sum() / (p * n))


def run(log) -> None:
    rm = RoleMaker.from_env()
    work = os.environ["PBTPU_TEST_WORKDIR"]
    passes = int(os.environ.get("PBTPU_ELASTIC_PASSES", "3"))
    n_ex = int(os.environ.get("PBTPU_ELASTIC_N", "768"))
    midpass = int(os.environ.get("PBTPU_ELASTIC_MIDPASS", "2"))
    step_sleep = float(os.environ.get("PBTPU_ELASTIC_STEP_SLEEP", "0"))
    lost_s = float(os.environ.get("PBTPU_ELASTIC_LOST_S", "2.0"))
    sim = os.environ.get("PBTPU_ELASTIC_SIM", "")
    sim = json.loads(sim) if sim else None
    grow = os.environ.get("PBTPU_ELASTIC_GROW", "") == "1"
    train_world = int(os.environ.get("PBTPU_ELASTIC_TRAIN_WORLD", "0") or 0)

    # ---- identity: launcher rank vs ORIGINAL rank -------------------------
    joiner = False
    if sim is not None:
        orig_members = sorted(sim["orig_members"])
        survivors = [r for r in orig_members if r not in set(sim["dead"])]
        me = survivors[rm.rank]           # sim rank i IS survivor i
        members = list(survivors)
    elif grow:
        orig_members = list(range(train_world))
        members = list(orig_members)
        joiner = rm.rank >= train_world
        # the replacement assumes the DEAD rank's original identity: its
        # checkpoint root, its trainer seed, its shard of every pass
        me = (int(os.environ["PBTPU_ELASTIC_JOINER_AS"]) if joiner
              else rm.rank)
    else:
        me = rm.rank
        orig_members = list(range(rm.world_size))
        members = list(orig_members)

    # victim arming: each process keeps only ITS designated fault point.
    # The joiner shares the victim's original-rank identity, so it must
    # never inherit the victim's kill.
    only = os.environ.get("PBTPU_FAULTPOINT_ONLY_RANK", "")
    if joiner or (only and only != str(me)):
        faultpoint.disarm()
    fp2, fp2_rank = (os.environ.get("PBTPU_FAULTPOINT2", ""),
                     os.environ.get("PBTPU_FAULTPOINT2_RANK", ""))
    if fp2 and (fp2_rank == "joiner" if joiner else fp2_rank == str(me)):
        faultpoint.arm(fp2, "kill",
                       int(os.environ.get("PBTPU_FAULTPOINT2_AFTER", "0")))

    # the joiner's event stream must not interleave with the dead
    # original rank's (same assumed identity, different process)
    monitor.hub().enable(monitor.JsonlSink(os.path.join(
        work, f"events_{me}{'_joiner' if joiner else ''}.jsonl")))
    if grow:
        set_flags(self_healing=True, self_healing_sustain=1)

    # ---- deterministic shared dataset: ins_id = 1..n ----------------------
    ds, schema = synth(n=n_ex, seed=11)
    base = ds.records
    base.ins_id = np.arange(1, n_ex + 1, dtype=np.uint64)

    store = HostEmbeddingStore(EmbeddingConfig(dim=4, learning_rate=0.05))
    tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                             hidden=(8,)),
                 store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=BS, dense_lr=2e-3,
                               auc_buckets=1 << 8),
                 seed=7 + me)
    box = BoxPS(store)
    box.set_date(20260801)
    box.init_metric("job_auc", n_buckets=128)
    ckpt = PassCheckpointer(
        os.path.join(os.environ["PBTPU_ELASTIC_ROOT"], f"rank{me}"),
        keep_last_n=4, base_every=2)
    if midpass > 0:
        tr.enable_midpass_snapshots(ckpt, midpass, box, metrics=box.metrics)

    if joiner:
        # the replacement process: join the (by now degraded) live world
        # as a NEW rank — blocks until the incumbents' poll_grow admits
        # it at a pass boundary
        world = ElasticWorld.admit(
            rm.base_store(150.0), me, timeout_s=150.0,
            heartbeat_interval_s=0.15, lost_after_s=lost_s,
            stall_after_s=90.0, reform_timeout_s=8.0,
            collectives_timeout_s=60.0, initial_world=train_world)
        log(f"admitted at gen {world.gen} members {world.members}")
        box.attach_collectives(world.collectives,
                               heartbeat=world.heartbeat)
        tr.peer_check = world.check
    elif sim is None:
        if grow:
            # incumbents of a launcher that spawned train_world+joiners:
            # generation 0 spans only the TRAINING members
            world = ElasticWorld(
                rm.base_store(60.0), me, orig_members,
                heartbeat_interval_s=0.15, lost_after_s=lost_s,
                stall_after_s=90.0, reform_timeout_s=8.0,
                collectives_timeout_s=60.0)
        else:
            world = rm.elastic_world(
                timeout_s=60, heartbeat_interval_s=0.15,
                lost_after_s=lost_s, stall_after_s=90.0,
                reform_timeout_s=8.0)
        # warmup grace: pass 1 compiles the step programs, and N jax
        # processes compiling on few cores can starve a publisher thread
        # past a tight lost_after — a mutual false-positive would fence
        # half the world. Generous until the first pass boundary;
        # re-formed worlds keep the tight constructor value (compile is
        # long done by then).
        world.heartbeat.lost_after_s = max(lost_s, 10.0)
        box.attach_collectives(world.collectives,
                               heartbeat=world.heartbeat)
        if step_sleep > 0:
            tr.peer_check = lambda: (time.sleep(step_sleep), world.check())
        else:
            tr.peer_check = world.check
    else:
        world = None
        col = rm.collectives(timeout_s=60)
        box.attach_collectives(col)

    # ---- schedule bookkeeping --------------------------------------------
    consumed: dict[int, list[int]] = {}
    info: dict = {"rank": me, "orig_members": orig_members,
                  "elected": None, "mid_steps": 0, "gen": 0,
                  "members": members, "reroute": None, "fenced": False,
                  "min_world_exit": False}
    init_shuffle_state = ds.shuffle_state()
    p = 1
    skip = 0
    old_members: list[int] | None = None
    sim_q, sim_m = ((int(sim["elected"][0]), int(sim["elected"][1]))
                    if sim is not None else (None, None))
    # the production binding: BoxPS.end_pass runs this controller's
    # boundary step each pass; the incumbents additionally drive its
    # grow poll between passes
    ctl = None
    if grow:
        bound = tr.enable_self_healing()
        if not joiner:
            ctl = bound
    hb_grace = world is not None      # generous lost_after until cleared

    if joiner:
        # compile grace: the newcomer compiles its step programs during
        # its first trained pass
        world.heartbeat.lost_after_s = max(lost_s, 10.0)
        # the same election the incumbents run inside poll_grow: the
        # grown world stands on one snapshot — the newcomer restores the
        # DEAD rank's newest snapshot that is intact everywhere
        cursor = coordinated_resume(ckpt, tr, world.collectives,
                                    box=box, metrics=box.metrics)
        members = list(world.members)
        info.update(gen=world.gen, members=members, admitted=True)
        if cursor is not None:
            info["elected"] = cursor.get("elected")
            if cursor.get("shuffle_state"):
                ds.set_shuffle_state(cursor["shuffle_state"])
            p = int(cursor["pass_id"]) + 1
            skip = int(cursor.get("mid_steps") or 0)

    def train_one(recs, skip_steps=0):
        dsp = _ds_for(schema, recs)
        return tr.train_pass(dsp, metrics=box.metrics,
                             skip_steps=skip_steps)

    grow_polls = int(os.environ.get("PBTPU_ELASTIC_GROW_POLLS", "600"))
    while p <= passes:
        try:
            if (ctl is not None and world is not None
                    and world.world < world.initial_world):
                # a degraded pass boundary: the remediation controller
                # polls for a replacement under the REAL doctor
                # heartbeat-gap finding. The poll COUNT (not wall time)
                # bounds the wait so every member abandons it on the
                # same all-gather round; on timeout training continues
                # degraded. Inside the try: a joiner dying mid-admit
                # surfaces as PeerFailureError and takes the normal
                # recovery path.
                new_world = world
                for _ in range(grow_polls):
                    new_world, cursor = ctl.poll_grow(
                        world, box=box, checkpointer=ckpt,
                        metrics=box.metrics)
                    if new_world is not world:
                        break
                    time.sleep(0.1)
                if new_world is not world:
                    world = new_world
                    members = list(world.members)
                    tr.peer_check = world.check
                    world.heartbeat.lost_after_s = max(lost_s, 10.0)
                    hb_grace = True       # the newcomer compiles now
                    info.update(gen=world.gen, members=members,
                                grew=True)
                    log(f"grew to gen {world.gen} members {members}")
                    if cursor is None:
                        # no common snapshot: whole-world fresh start
                        consumed.clear()
                        ds.set_shuffle_state(init_shuffle_state)
                        p, skip, old_members = 1, 0, None
                    else:
                        # the grown world stands on the newest snapshot
                        # intact on EVERY rank (the newcomer's is the
                        # dead rank's last boundary) — roll back to it
                        # and retrain at full world
                        info["elected"] = cursor.get("elected")
                        q = int(cursor["pass_id"])
                        if cursor.get("shuffle_state"):
                            ds.set_shuffle_state(cursor["shuffle_state"])
                        consumed = {pp: v for pp, v in consumed.items()
                                    if pp <= q}
                        p, skip, old_members = q + 1, 0, None
                else:
                    log("no replacement appeared; continuing degraded")
            pre_state = ds.shuffle_state()
            tr.midpass_cursor_extra = {"shuffle_state": pre_state}
            if sim is not None:
                # golden schedule, from the observed elected cursor: the
                # pre-kill passes partition over the ORIGINAL world (each
                # survivor trains only its own shard — the departed
                # rank's state never reached the survivors); the kill
                # pass trains the own head then the re-routed
                # continuation; later passes partition over survivors
                if p <= sim_q:
                    pass_members, pass_old, pass_skip = orig_members, \
                        None, 0
                elif p == sim_q + 1 and sim_m > 0:
                    pass_members, pass_old, pass_skip = members, \
                        orig_members, sim_m
                else:
                    pass_members, pass_old, pass_skip = members, None, 0
            else:
                pass_members, pass_old, pass_skip = members, \
                    old_members, skip
            sim_kill_pass = (sim is not None and pass_old is not None)
            if sim_kill_pass:
                # head of the OWN old-partition shard first (the state
                # the real run restored to), then the continuation — one
                # box pass, two train segments, same math/step count
                probe = _ds_for(schema, base)
                probe.set_shuffle_state(pre_state)
                probe.local_shuffle()
                own_full = probe.member_shards(
                    len(pass_old))[pass_old.index(me)]
                head = own_full.select(
                    np.arange(0, min(pass_skip * BS, own_full.num)))
                recs, _ = build_pass_records(
                    ds, base, me, pass_members, old_members=pass_old,
                    skip=pass_skip)
                box.begin_pass()
                ids = []
                if head.num >= BS:
                    out = train_one(head)
                    ids += [int(i)
                            for i in head.ins_id[:out["steps"] * BS]]
                if recs is not None and recs.num >= BS:
                    out = train_one(recs)
                    ids += [int(i)
                            for i in recs.ins_id[:out["steps"] * BS]]
                consumed[p] = sorted(set(ids))
                box.end_pass(checkpointer=ckpt, trainer=tr, dataset=ds)
                p += 1
                continue
            recs, own_head = build_pass_records(
                ds, base, me, pass_members, old_members=pass_old,
                skip=pass_skip)
            this_skip = pass_skip if pass_old is None else 0
            box.begin_pass()
            if recs is not None and recs.num >= BS:
                out = train_one(recs, skip_steps=this_skip)
                hi = (this_skip + out["steps"]) * BS
                ids = [int(i) for i in recs.ins_id[:hi]]
            else:
                ids = []
            # record BEFORE end_pass: its barrier may raise on a dead
            # peer, and the trained pass must stay accounted (the
            # election rollback truncates as needed)
            consumed[p] = sorted(set(consumed.get(p, []) + ids
                                     + own_head))
            box.end_pass(checkpointer=ckpt, trainer=tr, dataset=ds)
            skip = 0
            old_members = None
            if hb_grace and world is not None:
                world.heartbeat.lost_after_s = lost_s   # grace over
                hb_grace = False
            p += 1
        except PeerFailureError as e:
            log(f"peer failure in pass {p}: {e}")
            pre_members = list(world.members)
            try:
                new_world, cursor = tr.recover_world(
                    world, e, ckpt, box, metrics=box.metrics)
            except WorldFencedError as fe:
                log(f"fenced during recovery: {fe}")
                info["fenced"] = True
                break
            if new_world is None:
                info["min_world_exit"] = True
                break
            world = new_world
            members = list(world.members)
            if step_sleep > 0:
                tr.peer_check = lambda: (time.sleep(step_sleep),
                                         world.check())
            else:
                tr.peer_check = world.check
            info.update(gen=world.gen, members=members)
            # post-shrink snapshots: mid cursors of re-routed passes
            # would be ambiguous across member sets — pass boundaries
            # only from here (the continued run is short)
            tr.enable_midpass_snapshots(ckpt, 0, box)
            if cursor is None:
                # no common snapshot: whole-world fresh start
                consumed.clear()
                ds.set_shuffle_state(init_shuffle_state)
                p, skip, old_members = 1, 0, None
                continue
            info["elected"] = cursor.get("elected")
            q, m = int(cursor["pass_id"]), int(cursor.get("mid_steps")
                                               or 0)
            info["mid_steps"] = m
            if cursor.get("shuffle_state"):
                ds.set_shuffle_state(cursor["shuffle_state"])
            # the surviving timeline: passes <= q stand; the kill pass
            # q+1 keeps only the own-head consumption (m batches) —
            # departed-head consumption belongs to the departed rank
            consumed = {pp: v for pp, v in consumed.items() if pp <= q}
            p = q + 1
            skip = m
            old_members = pre_members if m > 0 else None
            if m > 0 and cursor.get("shuffle_state"):
                info["reroute"] = reroute_info(
                    ds, base, me, members, old_members, m,
                    cursor["shuffle_state"])
        except WorldFencedError as e:
            log(f"fenced: {e}")
            info["fenced"] = True
            break

    # ---- final dump -------------------------------------------------------
    col = world.collectives if world is not None else col
    if not info["fenced"] and not info["min_world_exit"]:
        info["global_auc"] = global_auc(col, box.metrics)
        tr.flush_sparse()
        keys = np.sort(np.asarray(base.unique_keys(), dtype=np.uint64))
        rows = store.get_rows(keys)
        dense = {f"p{i}": np.asarray(leaf) for i, leaf in
                 enumerate(jax.tree_util.tree_leaves(
                     {"params": tr.params, "opt": tr.opt_state}))}
        met = box.metrics.get_state("job_auc")
        np.savez(os.path.join(work, f"out_{me}.npz"),
                 keys=keys, rows=rows,
                 global_step=np.int64(tr.global_step),
                 pass_id=np.int64(box.pass_id),
                 met_pos=np.asarray(met["pos"]),
                 met_neg=np.asarray(met["neg"]), **dense)
        col.barrier("done")
    with open(os.path.join(work, f"info_{me}.json"), "w") as f:
        json.dump(info, f)
    with open(os.path.join(work, f"consumed_{me}.json"), "w") as f:
        json.dump({str(k): v for k, v in consumed.items()}, f)
    if world is not None:
        world.close()
    monitor.hub().disable()
    log("done")


def main() -> None:
    work = os.environ["PBTPU_TEST_WORKDIR"]
    os.makedirs(work, exist_ok=True)
    rank = os.environ.get("PBTPU_TRAINER_ID", "?")

    def log(msg):
        print(f"elastic rank {rank}: {msg}", flush=True)

    try:
        run(log)
    except BaseException as e:
        with open(os.path.join(work, f"err_{rank}.txt"), "w") as f:
            f.write(f"{type(e).__name__}: {e}\n")
            f.write(traceback.format_exc())
        monitor.hub().disable()
        raise


if __name__ == "__main__":
    main()
