"""pblint's own test suite: per-rule fixture snippets proving each rule
fires on a violation, stays quiet on the fixed form, and is suppressed by
a waiver WITH a reason — plus the cross-file checks (unregistered
faultpoint, registered-but-untested faultpoint, phantom/dead flags), the
waiver grammar, the CLI surface, and the baseline machinery.

Fixtures build a miniature project in tmp_path with the same shape as
the real tree (a ``paddlebox_tpu`` package dir with config.py and
utils/faultpoint.py, a ``tests/`` dir) so the default :class:`Project`
path conventions apply unchanged.
"""

from __future__ import annotations

import json
import os
import textwrap

import pytest

from paddlebox_tpu.analysis import lint as lint_cli
from paddlebox_tpu.analysis.core import Linter, Project, load_baseline
from paddlebox_tpu.analysis.rules import ALL_RULES

# ---------------------------------------------------------------------------
# fixture project scaffolding
# ---------------------------------------------------------------------------

MINI_CONFIG = '''
import dataclasses


@dataclasses.dataclass
class Flags:
    live_flag: int = 1
    dead_flag: int = 2
    set_only_flag: int = 3
    # pblint: disable=flag-audit -- reserved for the frobnicator arc
    waived_flag: int = 4


flags = Flags()


def set_flags(**kw):
    for k, v in kw.items():
        setattr(flags, k, v)
'''

MINI_FAULTPOINT = '''
POINTS: tuple = (
    "tested.point",
    "untested.point",
    "sub.registry.point",
)

ELASTIC_POINTS: tuple = (
    "sub.registry.point",
)


def hit(name):
    pass


def arm(name, action="kill"):
    pass
'''

MINI_TEST = '''
from paddlebox_tpu.utils import faultpoint


def test_literal_reference():
    assert faultpoint is not None
    point = "tested.point"


def test_registry_parametrized():
    for p in faultpoint.ELASTIC_POINTS:
        assert p
'''


def make_project(tmp_path, files: dict[str, str],
                 config: str = MINI_CONFIG,
                 faultpoint: str = MINI_FAULTPOINT,
                 test_src: str = MINI_TEST) -> Project:
    """Write a miniature repo; ``files`` maps repo-relative path -> source."""
    all_files = {
        "paddlebox_tpu/__init__.py": "",
        "paddlebox_tpu/config.py": config,
        "paddlebox_tpu/utils/__init__.py": "",
        "paddlebox_tpu/utils/faultpoint.py": faultpoint,
        "tests/test_ref.py": test_src,
    }
    all_files.update(files)
    for rel, src in all_files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Project(root=str(tmp_path))


def run_lint(project: Project, paths=("paddlebox_tpu",), rules=None,
             baseline=None):
    linter = Linter(project, rules)
    return linter.lint(list(paths), baseline=baseline)


def by_rule(result, rule):
    return [f for f in result.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# durable-write
# ---------------------------------------------------------------------------

DURABLE_SRC = '''
import os

from paddlebox_tpu.utils.checkpoint import atomic_file


def bad(path):
    with open(path, "wb") as f:          # VIOLATION
        f.write(b"x")


def good_atomic(path):
    with atomic_file(path) as tmp:
        with open(tmp, "wb") as f:       # sanctioned: atomic_file handle
            f.write(b"x")


def good_local_idiom(path):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:           # sanctioned: tmp->fsync->replace
        f.write(b"x")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def reads_are_fine(path):
    with open(path, "rb") as f:
        return f.read()


def waived(path):
    # pblint: disable=durable-write -- scratch file, durability by caller
    with open(path, "w") as f:
        f.write("x")
'''


def test_durable_write_rule(tmp_path):
    proj = make_project(tmp_path, {
        "paddlebox_tpu/data/__init__.py": "",
        "paddlebox_tpu/data/archive.py": DURABLE_SRC,   # durability module
        "paddlebox_tpu/other.py": 'def f(p):\n    open(p, "w").write("x")\n',
    })
    res = run_lint(proj)
    hits = by_rule(res, "durable-write")
    # exactly the one raw write in the durability module; the non-
    # durability module's raw write is out of scope for THIS rule
    assert len(hits) == 1
    assert hits[0].file == "paddlebox_tpu/data/archive.py"
    assert "raw open" in hits[0].message
    # the waived site is reported as waived, with its reason
    assert any(f.rule == "durable-write" and "scratch file" in r
               for f, r in res.waived)


def test_durable_write_idiom_sanctions_only_the_replaced_tmp(tmp_path):
    # a function that carries the tmp->fsync->replace idiom for ONE file
    # must not get a blanket pass for a second raw write to another path
    proj = make_project(tmp_path, {
        "paddlebox_tpu/data/__init__.py": "",
        "paddlebox_tpu/data/archive.py": '''
import os


def mixed(path, other):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:           # sanctioned: replaced below
        f.write(b"x")
        os.fsync(f.fileno())
    os.replace(tmp, path)
    with open(other, "w") as f:          # VIOLATION: never replaced
        f.write("y")
''',
    })
    res = run_lint(proj)
    hits = by_rule(res, "durable-write")
    assert len(hits) == 1
    assert hits[0].line == 11            # the `open(other, ...)` line


def test_durable_write_fleet_prefix(tmp_path):
    proj = make_project(tmp_path, {
        "paddlebox_tpu/fleet/__init__.py": "",
        "paddlebox_tpu/fleet/boxps.py":
            'def f(p):\n    open(p, "w").write("x")\n',
    })
    res = run_lint(proj)
    assert len(by_rule(res, "durable-write")) == 1   # fleet/ is a prefix


# ---------------------------------------------------------------------------
# faultpoint-registry
# ---------------------------------------------------------------------------

FAULTPOINT_SRC = '''
from paddlebox_tpu.utils import faultpoint


def g(save):
    faultpoint.hit("tested.point")            # registered + tested
    faultpoint.hit("not.registered")          # VIOLATION
    save("x", fault_point="also.not.there")   # VIOLATION (kwarg form)
    faultpoint.hit(compute_name())            # non-literal: plumbing, skip


def compute_name():
    return "tested.point"
'''


def test_faultpoint_registry_rule(tmp_path):
    proj = make_project(tmp_path, {
        "paddlebox_tpu/mod.py": FAULTPOINT_SRC,
    })
    res = run_lint(proj)
    hits = by_rule(res, "faultpoint-registry")
    msgs = {(f.file, f.line): f.message for f in hits}
    unregistered = [m for m in msgs.values() if "not in the closed" in m]
    assert len(unregistered) == 2          # hit-literal + fault_point kwarg
    # cross-file: untested.point has no literal AND its only registry
    # (POINTS) is not referenced by a test -> finding at the registry line
    untested = [f for f in hits if "registered but no test" in f.message]
    assert [f.file for f in untested] == [
        "paddlebox_tpu/utils/faultpoint.py"]
    assert "untested.point" in untested[0].message
    # sub.registry.point is covered by the ELASTIC_POINTS parametrization;
    # tested.point by its literal — neither may appear
    joined = " ".join(f.message for f in untested)
    assert "sub.registry.point" not in joined
    assert "'tested.point'" not in joined


def test_faultpoint_untested_fires_without_registry_ref(tmp_path):
    # a test file with no literal and no registry reference: every point
    # is untested
    proj = make_project(tmp_path, {"paddlebox_tpu/mod.py": "x = 1\n"},
                        test_src="def test_nothing():\n    assert True\n")
    res = run_lint(proj)
    untested = [f for f in by_rule(res, "faultpoint-registry")
                if "registered but no test" in f.message]
    assert len(untested) == 3


# ---------------------------------------------------------------------------
# thread-context
# ---------------------------------------------------------------------------

THREAD_SRC = '''
import threading
from threading import Thread as T


def f():
    a = threading.Thread(target=f)       # VIOLATION
    b = T(target=f)                      # VIOLATION (aliased import)
    # pblint: disable=thread-context -- must NOT inherit pass context:
    # this worker outlives the pass scope by design
    c = threading.Thread(target=f)
    return a, b, c
'''


def test_thread_context_rule(tmp_path):
    proj = make_project(tmp_path, {
        "paddlebox_tpu/mod.py": THREAD_SRC,
        "paddlebox_tpu/monitor/__init__.py": "",
        # the sanctioned wrapper itself is exempt
        "paddlebox_tpu/monitor/context.py":
            "import threading\n\n"
            "def spawn(target):\n"
            "    return threading.Thread(target=target)\n",
    })
    res = run_lint(proj)
    hits = by_rule(res, "thread-context")
    assert len(hits) == 2
    assert all(f.file == "paddlebox_tpu/mod.py" for f in hits)
    assert any(f.rule == "thread-context" and "NOT inherit" in r
               for f, r in res.waived)


# ---------------------------------------------------------------------------
# donefile-discipline
# ---------------------------------------------------------------------------

DONEFILE_SRC = '''
import os

DONEFILE = "model.donefile"


def announce(fs, fleet, tmp):
    fleet.append_donefile(DONEFILE, {})              # sanctioned API
    fs.write_text("out/model.donefile", "x")         # VIOLATION
    path = "root/" + DONEFILE
    fs.put(tmp, path)                                # VIOLATION (taint)
    with open("a.donefile", "a") as f:               # VIOLATION
        f.write("x")
    os.replace(tmp, "ordinary.txt")                  # unrelated target: ok
    fs.get("root/model.donefile", tmp)               # reads are fine
'''


def test_donefile_discipline_rule(tmp_path):
    proj = make_project(tmp_path, {
        "paddlebox_tpu/mod.py": DONEFILE_SRC,
        "paddlebox_tpu/fleet/__init__.py": "",
        # the sanctioned writer may use raw primitives
        "paddlebox_tpu/fleet/fleet_util.py":
            'def append_donefile(fs, name, entry):\n'
            '    fs.write_text(name + ".donefile", "line")\n',
    })
    res = run_lint(proj)
    hits = by_rule(res, "donefile-discipline")
    assert len(hits) == 3
    assert all(f.file == "paddlebox_tpu/mod.py" for f in hits)


# ---------------------------------------------------------------------------
# flag-audit
# ---------------------------------------------------------------------------

FLAGS_SRC = '''
from paddlebox_tpu.config import flags, set_flags


def f():
    a = flags.live_flag                  # resolves: fine
    b = flags.phantom_flag               # VIOLATION: no such field
    set_flags(set_only_flag=9)           # write, not a read
    return a, b
'''


def test_flag_audit_rule(tmp_path):
    proj = make_project(tmp_path, {"paddlebox_tpu/mod.py": FLAGS_SRC})
    res = run_lint(proj)
    hits = by_rule(res, "flag-audit")
    phantom = [f for f in hits if "phantom" in f.message]
    assert len(phantom) == 1 and "phantom_flag" in phantom[0].message
    dead = {f.message.split("'")[1] for f in hits
            if "never read" in f.message}
    # dead_flag: no reference at all; set_only_flag: written but never
    # READ — both dead. live_flag is read; waived_flag carries a waiver.
    assert dead == {"dead_flag", "set_only_flag"}
    assert all(f.file == "paddlebox_tpu/config.py" for f in hits
               if "never read" in f.message)
    assert any(f.rule == "flag-audit" and "frobnicator" in r
               for f, r in res.waived)


def test_flag_audit_counts_reads_from_tests(tmp_path):
    # a flag read ONLY by a test still counts as read (tests are
    # reference scope), keeping the dead-flag check about the whole tree
    proj = make_project(
        tmp_path, {"paddlebox_tpu/mod.py": "x = 1\n"},
        test_src="from paddlebox_tpu.config import flags\n\n"
                 "def test_f():\n    assert flags.dead_flag\n")
    res = run_lint(proj)
    dead = {f.message.split("'")[1]
            for f in by_rule(res, "flag-audit") if "never read" in f.message}
    assert "dead_flag" not in dead


# ---------------------------------------------------------------------------
# silent-except
# ---------------------------------------------------------------------------

SILENT_SRC = '''
def f(q, monitor):
    try:
        q.get()
    except KeyError:
        pass                             # VIOLATION

    try:
        q.get()
    except OSError:
        # a comment does not make it accounted
        pass                             # VIOLATION

    try:
        q.get()
    except ValueError:
        monitor.counter_add("q.errors")  # counted: fine

    try:
        q.get()
    # pblint: disable=silent-except -- the queue owner already latched it
    except RuntimeError:
        pass
'''


def test_silent_except_rule(tmp_path):
    proj = make_project(tmp_path, {"paddlebox_tpu/mod.py": SILENT_SRC})
    res = run_lint(proj)
    hits = by_rule(res, "silent-except")
    assert len(hits) == 2
    assert any(f.rule == "silent-except" and "latched" in r
               for f, r in res.waived)


# ---------------------------------------------------------------------------
# event-registry
# ---------------------------------------------------------------------------

EVENT_REGISTRY = '''
EVENT_NAMES: tuple = (
    "registered_event",
)

SPAN_NAMES: tuple = (
    "registered_span",
)
'''

EVENT_SRC = '''
from paddlebox_tpu import monitor
from paddlebox_tpu.monitor import event as mon_event


def f(hub, name):
    monitor.event("registered_event", x=1)       # fine
    mon_event("registered_event")                # aliased import: fine
    with monitor.span("registered_span"):        # fine
        pass
    monitor.event("rogue_event")                 # VIOLATION
    hub.event("rogue_hub_event")                 # VIOLATION (method call)
    with monitor.span("rogue_span"):             # VIOLATION
        pass
    monitor.event(name)                          # VIOLATION: non-literal
    # pblint: disable=event-registry -- name iterates registered
    # literals in the caller
    monitor.event(name, y=2)
'''


def test_event_registry_rule(tmp_path):
    proj = make_project(tmp_path, {
        "paddlebox_tpu/monitor/__init__.py": "",
        "paddlebox_tpu/monitor/names.py": EVENT_REGISTRY,
        "paddlebox_tpu/mod.py": EVENT_SRC})
    res = run_lint(proj)
    hits = by_rule(res, "event-registry")
    assert len(hits) == 4
    msgs = " ".join(f.message for f in hits)
    for rogue in ("rogue_event", "rogue_hub_event", "rogue_span"):
        assert rogue in msgs
    assert sum("not a string literal" in f.message for f in hits) == 1
    assert "registered_event" not in msgs
    assert any(f.rule == "event-registry" and "iterates" in r
               for f, r in res.waived)


def test_event_registry_silent_without_registry(tmp_path):
    # a project without monitor/names.py has no event namespace contract
    # — the rule must not invent one
    proj = make_project(tmp_path, {"paddlebox_tpu/mod.py": EVENT_SRC})
    assert by_rule(run_lint(proj), "event-registry") == []


# ---------------------------------------------------------------------------
# waiver grammar
# ---------------------------------------------------------------------------

def test_waiver_without_reason_is_bad_and_not_honored(tmp_path):
    proj = make_project(tmp_path, {
        "paddlebox_tpu/mod.py":
            "def f(q):\n"
            "    try:\n"
            "        q.get()\n"
            "    except OSError:  # pblint: disable=silent-except\n"
            "        pass\n",
    })
    res = run_lint(proj)
    rules = {f.rule for f in res.findings}
    # the reasonless waiver is itself a finding AND suppresses nothing
    assert "bad-waiver" in rules and "silent-except" in rules


def test_waiver_with_unknown_rule_is_bad(tmp_path):
    proj = make_project(tmp_path, {
        "paddlebox_tpu/mod.py":
            "# pblint: disable=no-such-rule -- because\nx = 1\n",
    })
    res = run_lint(proj)
    bad = by_rule(res, "bad-waiver")
    assert len(bad) == 1 and "no-such-rule" in bad[0].message


def test_trailing_waiver_and_multi_rule(tmp_path):
    proj = make_project(tmp_path, {
        "paddlebox_tpu/data/__init__.py": "",
        "paddlebox_tpu/data/archive.py":
            "def f(p):\n"
            "    with open(p + '.donefile', 'w') as fh:  "
            "# pblint: disable=durable-write,donefile-discipline -- "
            "fixture covers both rules at one site\n"
            "        fh.write('x')\n",
    })
    res = run_lint(proj)
    assert not by_rule(res, "durable-write")
    assert not by_rule(res, "donefile-discipline")
    assert {"durable-write", "donefile-discipline"} <= {
        f.rule for f, _ in res.waived}


def test_unparseable_file_is_a_finding(tmp_path):
    proj = make_project(tmp_path, {
        "paddlebox_tpu/mod.py": "def broken(:\n",
    })
    res = run_lint(proj)
    pe = by_rule(res, "parse-error")
    assert len(pe) == 1 and pe[0].file == "paddlebox_tpu/mod.py"


# ---------------------------------------------------------------------------
# CLI surface + baseline machinery
# ---------------------------------------------------------------------------

def _cli(tmp_path, *argv):
    return lint_cli.main(["--root", str(tmp_path), *argv])


def test_cli_exit_codes_and_format(tmp_path, capsys):
    make_project(tmp_path, {"paddlebox_tpu/mod.py": THREAD_SRC})
    rc = _cli(tmp_path, "paddlebox_tpu", "--rules", "thread-context")
    out = capsys.readouterr().out
    assert rc == 1
    # one `file:line rule message` line per finding + the summary
    lines = [ln for ln in out.splitlines() if " thread-context " in ln]
    assert len(lines) == 2
    fname, line = lines[0].split(":", 1)[0], lines[0].split(":", 2)[1]
    assert fname == "paddlebox_tpu/mod.py" and line.split()[0].isdigit()
    assert "2 finding(s), 1 waived" in out

    rc = _cli(tmp_path, "paddlebox_tpu", "--rules", "silent-except")
    assert rc == 0                       # narrowed run: no thread findings
    assert _cli(tmp_path, "paddlebox_tpu", "--rules", "nope") == 2


def test_cli_json_output(tmp_path, capsys):
    make_project(tmp_path, {"paddlebox_tpu/mod.py": THREAD_SRC})
    rc = _cli(tmp_path, "paddlebox_tpu", "--rules", "thread-context",
              "--json")
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1 and doc["clean"] is False
    assert len(doc["findings"]) == 2 and len(doc["waived"]) == 1
    assert {"file", "line", "rule", "message"} <= set(doc["findings"][0])


def test_cli_list_rules(capsys):
    assert lint_cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for cls in ALL_RULES:
        assert cls.id in out
    assert len(ALL_RULES) >= 6


def test_baseline_round_trip(tmp_path, capsys):
    make_project(tmp_path, {"paddlebox_tpu/mod.py": THREAD_SRC})
    base = tmp_path / "baseline.json"
    rc = _cli(tmp_path, "paddlebox_tpu", "--write-baseline", str(base))
    assert rc == 0
    assert load_baseline(str(base))      # non-empty accepted set
    # with the baseline applied the same tree is green...
    capsys.readouterr()
    rc = _cli(tmp_path, "paddlebox_tpu", "--baseline", str(base))
    out = capsys.readouterr().out
    assert rc == 0 and "0 finding(s)" in out and " baselined" in out
    # ...but a NEW violation still fails
    (tmp_path / "paddlebox_tpu" / "mod2.py").write_text(SILENT_SRC)
    rc = _cli(tmp_path, "paddlebox_tpu", "--baseline", str(base))
    assert rc == 1


def test_baseline_rejects_wrong_version(tmp_path):
    p = tmp_path / "b.json"
    p.write_text('{"version": 99, "findings": []}')
    with pytest.raises(ValueError):
        load_baseline(str(p))


def test_nonexistent_path_is_an_error_not_a_clean_run(tmp_path, capsys):
    # a typo'd path must never report '0 findings across 0 files' green
    make_project(tmp_path, {"paddlebox_tpu/mod.py": "x = 1\n"})
    rc = _cli(tmp_path, "paddlebox_tpu/no/such/dir")
    assert rc == 2
    assert "matched no .py files" in capsys.readouterr().err


def test_cwd_relative_path_fallback(tmp_path, monkeypatch, capsys):
    # a path that does not exist under the repo root but does exist
    # relative to the CWD (e.g. `cd tests && lint ../paddlebox_tpu`)
    # resolves instead of silently matching nothing
    make_project(tmp_path, {"paddlebox_tpu/mod.py": THREAD_SRC})
    sub = tmp_path / "somewhere"
    sub.mkdir()
    monkeypatch.chdir(sub)
    rc = _cli(tmp_path, "../paddlebox_tpu/mod.py",
              "--rules", "thread-context")
    assert rc == 1
    assert "2 finding(s)" in capsys.readouterr().out


def test_project_discovery_walks_up(tmp_path):
    make_project(tmp_path, {"paddlebox_tpu/mod.py": "x = 1\n"})
    proj = Project.discover(str(tmp_path / "paddlebox_tpu" / "mod.py"))
    assert os.path.samefile(proj.root, str(tmp_path))
