"""KeyIndex: native C++ backend vs dict fallback parity."""

import numpy as np
import pytest

from paddlebox_tpu.native.key_index import KeyIndex, native_available


@pytest.mark.parametrize("force_python", [True, False])
def test_basic_ops(force_python):
    if not force_python and not native_available():
        pytest.skip("native lib unavailable")
    ki = KeyIndex(16, force_python=force_python)
    keys = np.array([5, 7, 5, 9, 7, 11], dtype=np.uint64)
    idx, added = ki.lookup_or_insert(keys)
    assert idx.tolist() == [0, 1, 0, 2, 1, 3]
    assert added == 4 and len(ki) == 4
    assert ki.lookup(np.array([9, 99], np.uint64)).tolist() == [2, -1]
    ki.rebuild(np.array([11, 5], np.uint64))
    assert len(ki) == 2
    assert ki.lookup(np.array([11, 5, 7], np.uint64)).tolist() == [0, 1, -1]


def test_backends_agree_on_random_workload():
    if not native_available():
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(0)
    a = KeyIndex(64, force_python=False)
    b = KeyIndex(64, force_python=True)
    for step in range(5):
        keys = rng.choice(1 << 48, size=5000).astype(np.uint64)
        ia, na = a.lookup_or_insert(keys)
        ib, nb = b.lookup_or_insert(keys)
        np.testing.assert_array_equal(ia, ib)
        assert na == nb and len(a) == len(b)
        probe = rng.choice(1 << 48, size=1000).astype(np.uint64)
        np.testing.assert_array_equal(a.lookup(probe), b.lookup(probe))
    keep = rng.choice(1 << 48, size=2000).astype(np.uint64)
    keep = np.unique(keep)
    a.rebuild(keep)
    b.rebuild(keep)
    np.testing.assert_array_equal(a.lookup(keep), b.lookup(keep))


def test_growth_through_many_resizes():
    if not native_available():
        pytest.skip("native lib unavailable")
    ki = KeyIndex(4)
    big = (np.arange(200_000, dtype=np.uint64) * np.uint64(2654435761)
           + np.uint64(1))
    idx, added = ki.lookup_or_insert(big)
    assert added == len(np.unique(big)) == len(ki)
    np.testing.assert_array_equal(ki.lookup(big), idx)


def test_sentinel_key_max_uint64():
    """2^64-1 collides with the native free-slot sentinel; both backends
    must treat it as an ordinary key."""
    sent = np.array([0xFFFFFFFFFFFFFFFF], np.uint64)
    for fp in ([True, False] if native_available() else [True]):
        ki = KeyIndex(8, force_python=fp)
        assert ki.lookup(sent).tolist() == [-1]
        idx, added = ki.lookup_or_insert(
            np.array([7, 0xFFFFFFFFFFFFFFFF, 7, 0xFFFFFFFFFFFFFFFF],
                     np.uint64))
        assert idx.tolist() == [0, 1, 0, 1] and added == 2
        assert ki.lookup(sent).tolist() == [1]
        ki.rebuild(np.array([0xFFFFFFFFFFFFFFFF, 3], np.uint64))
        assert ki.lookup(sent).tolist() == [0] and len(ki) == 2


def test_rebuild_duplicate_keys_last_occurrence_wins():
    dup = np.array([5, 5, 7], np.uint64)
    for fp in ([True, False] if native_available() else [True]):
        ki = KeyIndex(8, force_python=fp)
        ki.rebuild(dup)
        assert len(ki) == 2, fp
        assert ki.lookup(np.array([5, 7], np.uint64)).tolist() == [1, 2], fp


def test_store_works_on_python_fallback(monkeypatch):
    """The store must behave identically when the native lib is absent."""
    import paddlebox_tpu.native.key_index as kim
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore

    monkeypatch.setenv("PBTPU_NO_NATIVE_BUILD", "1")
    monkeypatch.setattr(kim, "get_lib", lambda: None)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    keys = np.array([3, 9, 3, 27], np.uint64)
    rows = store.lookup_or_init(keys)
    assert rows.shape == (4, store.cfg.row_width)
    np.testing.assert_array_equal(rows[0], rows[2])
    assert len(store) == 3
    got = store.get_rows(np.array([27], np.uint64))
    np.testing.assert_array_equal(got[0], rows[3])
