"""Test harness: simulate an 8-device TPU mesh on CPU.

Mirrors the reference's test strategy (SURVEY.md §4): distributed paths must be
testable without real hardware, so every test runs on the CPU backend with 8
virtual XLA devices (`--xla_force_host_platform_device_count`).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# NOTE: x64 stays OFF — device code must work with TPU-default 32-bit ints.
# Raw uint64 feature signs live host-side only (numpy); the pass working set
# translates them to dense int32 indices before anything reaches jit
# (SURVEY.md §7 design stance).

import jax  # noqa: E402

# The environment's sitecustomize force-registers the axon TPU plugin and
# overwrites JAX_PLATFORMS; config.update after import wins over it.
jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) >= 8, (
    "tests expect >=8 virtual CPU devices; XLA_FLAGS not applied?")

# ---------------------------------------------------------------------------
# Thread-leak tracking: a full-suite run accumulates process state across
# ~300 tests in one interpreter; a test that leaves worker threads running
# degrades every later test and has produced fatal interpreter aborts deep
# into the suite (VERDICT r3 weak #1). Mirrors the reference's isolation
# discipline for distributed tests (test_dist_base.py runs them in child
# processes). Any test that ends with more live threads than it started
# with FAILS here, naming the leaked threads — leaks get fixed at the
# source instead of poisoning the 50 tests after them.
# ---------------------------------------------------------------------------

import threading  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _no_thread_leaks(request):
    before = set(threading.enumerate())
    yield
    # give short-lived shutdown paths a moment to finish joining
    leaked = [t for t in threading.enumerate()
              if t not in before and t.is_alive()]
    if leaked:
        import time
        deadline = time.time() + 2.0
        while leaked and time.time() < deadline:
            time.sleep(0.05)
            leaked = [t for t in leaked if t.is_alive()]
    if leaked:
        names = sorted(t.name for t in leaked)
        pytest.fail(
            f"test leaked {len(leaked)} live thread(s): {names} — join or "
            f"close them before returning (leaked threads accumulate "
            f"across the suite and abort the interpreter)", pytrace=False)
