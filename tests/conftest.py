"""Test harness: simulate an 8-device TPU mesh on CPU.

Mirrors the reference's test strategy (SURVEY.md §4): distributed paths must be
testable without real hardware, so every test runs on the CPU backend with 8
virtual XLA devices (`--xla_force_host_platform_device_count`).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# NOTE: x64 stays OFF — device code must work with TPU-default 32-bit ints.
# Raw uint64 feature signs live host-side only (numpy); the pass working set
# translates them to dense int32 indices before anything reaches jit
# (SURVEY.md §7 design stance).

import jax  # noqa: E402

# The environment's sitecustomize force-registers the axon TPU plugin and
# overwrites JAX_PLATFORMS; config.update after import wins over it.
jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) >= 8, (
    "tests expect >=8 virtual CPU devices; XLA_FLAGS not applied?")
