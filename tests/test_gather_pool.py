"""Fused gather-pool pull vs the unfused fused_seqpool_cvm reference.

The Pallas kernel runs in interpret mode on CPU (like binned_push); the
reference is the unfused path the models otherwise take — a full-row
gather + per-token filter/quant + per-slot sum pool. Covers forward
parity over the reference kernel family's knobs (per-slot show/clk
thresholds, embed-threshold filter, quant gating), the edge geometries
(empty slots, all-pad batches, duplicate-heavy multi-hot), and grad
parity through the custom VJP.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import flags
from paddlebox_tpu.embedding import sharded
from paddlebox_tpu.embedding.config import EmbeddingConfig
from paddlebox_tpu.ops import pallas_kernels
from paddlebox_tpu.ops.seqpool_cvm import (PooledSlots,
                                           fused_gather_seqpool_cvm,
                                           fused_seqpool_cvm)


def _mk(B=4, S=3, L=2, dim=4, n=64, seed=0, mask_p=0.7):
    """Table with counter-like show/clk (CVM logs need nonneg pools) and
    the NULL-row contract (row 0 all zeros, like a pass working set)."""
    cfg = EmbeddingConfig(dim=dim, optimizer="adagrad", learning_rate=0.05)
    rng = np.random.default_rng(seed)
    table = rng.normal(size=(n, cfg.row_width)).astype(np.float32)
    table[:, 0] = rng.integers(0, 20, size=n)        # show
    table[:, 1] = rng.integers(0, 5, size=n)         # clk
    table[0] = 0.0
    idx = rng.integers(1, n, size=(B, S * L)).astype(np.int32)
    mask = rng.random((B, S * L)) < mask_p
    seg = np.repeat(np.arange(S, dtype=np.int32), L)
    return cfg, jnp.asarray(table), idx, mask, seg


def _ref_pulled(table, idx, mask, cfg):
    """The unfused pull the models otherwise see (grad-transparent: the
    trainer never differentiates through lookup's optimization barrier,
    so the reference uses the plain gather)."""
    B, T = idx.shape
    idx0 = jnp.asarray(np.where(mask, idx, 0)).reshape(-1)
    P = cfg.pull_width
    return jnp.take(table, idx0, axis=0)[:, :P].reshape(B, T, P)


@pytest.mark.parametrize("B,S,L,dim", [
    (4, 3, 2, 4),      # multi-hot
    (8, 5, 1, 4),      # one-hot (L=1), >8 in-flight DMAs per tile
    (4, 2, 3, 128),    # wide rows: >128-lane gathered scratch
])
def test_kernel_interpret_matches_reference_pool(B, S, L, dim):
    cfg, table, idx, mask, seg = _mk(B=B, S=S, L=L, dim=dim)
    idx0 = np.where(mask, idx, 0).astype(np.int32)
    out = pallas_kernels.gather_pool(table, jnp.asarray(idx0), cfg, S, L,
                                     interpret=True)
    P = cfg.pull_width
    ref = np.asarray(table)[idx0.reshape(-1), :P].reshape(B, S, L, P).sum(
        axis=2)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("use_cvm", [True, False])
def test_fused_op_forward_parity(use_cvm):
    cfg, table, idx, mask, seg = _mk()
    got = fused_gather_seqpool_cvm(table, jnp.asarray(idx),
                                   jnp.asarray(mask), seg, 3, cfg,
                                   use_cvm=use_cvm, interpret=True)
    want = fused_seqpool_cvm(_ref_pulled(table, idx, mask, cfg),
                             jnp.asarray(mask), seg, 3, use_cvm=use_cvm)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_fused_op_per_slot_thresholds_and_quant():
    cfg, table, idx, mask, seg = _mk(seed=2)
    thr = np.array([0.5, -1.0, 3.0], np.float32)   # per-slot diff-thres
    kw = dict(need_filter=True, threshold=thr, show_coeff=0.3,
              clk_coeff=0.9, embed_threshold=0.4, quant_ratio=8)
    got = fused_gather_seqpool_cvm(table, jnp.asarray(idx),
                                   jnp.asarray(mask), seg, 3, cfg,
                                   interpret=True, **kw)
    want = fused_seqpool_cvm(_ref_pulled(table, idx, mask, cfg),
                             jnp.asarray(mask), seg, 3, **kw)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    # quant gating off on the same inputs must differ from on (the knob
    # does something) and still match its own reference
    kw_off = dict(kw, quant_ratio=0)
    got_off = fused_gather_seqpool_cvm(table, jnp.asarray(idx),
                                       jnp.asarray(mask), seg, 3, cfg,
                                       interpret=True, **kw_off)
    want_off = fused_seqpool_cvm(_ref_pulled(table, idx, mask, cfg),
                                 jnp.asarray(mask), seg, 3, **kw_off)
    np.testing.assert_allclose(np.asarray(got_off), np.asarray(want_off),
                               rtol=1e-6, atol=1e-6)
    assert np.abs(np.asarray(got) - np.asarray(got_off)).max() > 0


def test_fused_op_empty_slots_and_all_pad():
    cfg, table, idx, mask, seg = _mk(seed=3)
    mask = mask.copy()
    mask[0, :] = False            # all-pad example
    mask[:, 2:4] = False          # slot 1 empty in every example
    got = fused_gather_seqpool_cvm(table, jnp.asarray(idx),
                                   jnp.asarray(mask), seg, 3, cfg,
                                   use_cvm=True, flatten=False,
                                   interpret=True)
    want = fused_seqpool_cvm(_ref_pulled(table, idx, mask, cfg),
                             jnp.asarray(mask), seg, 3, use_cvm=True,
                             flatten=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    # empty segments pool to the zero row: log(0+1)=0 CVM columns, zero
    # embedx
    np.testing.assert_array_equal(np.asarray(got)[0], 0.0)
    np.testing.assert_array_equal(np.asarray(got)[:, 1, :], 0.0)
    # fully-masked batch
    none = np.zeros_like(mask)
    got0 = fused_gather_seqpool_cvm(table, jnp.asarray(idx),
                                    jnp.asarray(none), seg, 3, cfg,
                                    interpret=True)
    np.testing.assert_array_equal(np.asarray(got0), 0.0)


@pytest.mark.parametrize("need_filter,embed_threshold",
                         [(False, 0.0), (True, 0.0),
                          (False, 0.3), (True, 0.3)])
def test_fused_op_grad_parity(need_filter, embed_threshold):
    """Grad parity through the custom VJP vs the unfused autodiff
    reference — including the duplicate-heavy merge (every token drawn
    from 8 rows, so the VJP's dedup path actually folds duplicates) and
    the embed_threshold drop mask (the VJP re-derives the forward's keep
    predicate from the raw rows; a predicate drift between the copies
    must fail here, not corrupt training silently)."""
    cfg, table, idx, mask, seg = _mk(B=6, S=3, L=4, n=64, seed=4)
    idx = (idx % 8 + 1).astype(np.int32)          # heavy duplication
    kw = dict(need_filter=need_filter, threshold=0.5,
              embed_threshold=embed_threshold)
    w = jnp.asarray(np.random.default_rng(5).normal(
        size=(6, 3 * cfg.pull_width)).astype(np.float32))

    def fused_loss(t):
        out = fused_gather_seqpool_cvm(t, jnp.asarray(idx),
                                       jnp.asarray(mask), seg, 3, cfg,
                                       interpret=True, **kw)
        return jnp.sum(out * w)

    def ref_loss(t):
        out = fused_seqpool_cvm(_ref_pulled(t, idx, mask, cfg),
                                jnp.asarray(mask), seg, 3, **kw)
        return jnp.sum(out * w)

    g_fused = jax.grad(fused_loss)(table)
    g_ref = jax.grad(ref_loss)(table)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-5)


def test_pooled_slots_rejects_per_token_filters():
    pooled = PooledSlots(jnp.zeros((2, 3, 7), jnp.float32))
    with pytest.raises(ValueError, match="PooledSlots"):
        fused_seqpool_cvm(pooled, None, np.zeros(3, np.int64), 3,
                          need_filter=True)


def test_fused_op_rejects_create_threshold_configs():
    """Gated pulls (mf/expand create thresholds) would silently skip
    gate_pull through the fused gather — must raise, not diverge."""
    cfg, table, idx, mask, seg = _mk()
    gated = EmbeddingConfig(dim=4, optimizer="adagrad",
                            mf_create_threshold=2.0)
    with pytest.raises(ValueError, match="gate_pull"):
        fused_gather_seqpool_cvm(table, jnp.asarray(idx),
                                 jnp.asarray(mask), seg, 3, gated,
                                 interpret=True)


def test_pooled_grad_tokens_matches_unfused_expansion():
    """The trainer's backward half: expanding the pooled cotangent per
    token must equal the unfused path's per-token gpull[..., 2:]."""
    cfg, table, idx, mask, seg = _mk(B=5, S=3, L=2, seed=6)
    B, T = idx.shape
    rng = np.random.default_rng(7)
    gpooled = jnp.asarray(rng.normal(
        size=(B, 3, cfg.pull_width)).astype(np.float32))
    got = sharded.pooled_grad_tokens(gpooled, jnp.asarray(mask), seg, 3)
    # unfused: each token's pull cotangent is its slot's pooled row
    # masked — pooling is a per-segment sum
    want = (np.asarray(gpooled)[:, np.asarray(seg), 2:]
            * mask[..., None]).reshape(B * T, cfg.grad_width)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6,
                               atol=1e-6)


def test_fused_pull_pool_reference_path_matches_lookup():
    """CPU (no kernel geometry on this backend): fused_pull_pool must be
    the exact lookup + reshape-sum, quant storage included."""
    cfg, table, idx, mask, seg = _mk(B=4, S=3, L=2)
    idx0 = jnp.asarray(np.where(mask, idx, 0))
    got = sharded.fused_pull_pool(table, idx0, cfg, 3, 2)
    want = sharded.lookup(table, idx0.reshape(-1), cfg).reshape(
        4, 3, 2, cfg.pull_width).sum(axis=2)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_gather_pool_geometry_bounds():
    # the tile divides the batch (odd batches degrade to BB=1, still
    # valid); absurd widths fall back
    assert pallas_kernels.gather_pool_geometry(8, 3, 2, 13) is not None
    assert pallas_kernels.gather_pool_geometry(7, 3, 2, 13) == 1
    assert pallas_kernels.gather_pool_geometry(8, 3, 2, 1024) is None
    # wide rows shrink the tile instead of overflowing VMEM
    bb = pallas_kernels.gather_pool_geometry(4096, 26, 4, 128)
    assert bb is not None and 4096 % bb == 0


def test_gather_pool_geometry_lanes_table_retune():
    """The routed path's received-lane geometry (ISSUE 13 satellite):
    the gather source is the cap*D x pull_width lane array, not the
    n_rows x row_width HBM table the 64-row cap was tuned on — narrow
    lane sources take bigger batch tiles (fewer grid prologues), the
    same VMEM budget rule still bounds wide ones."""
    # narrow received lanes: the tile cap doubles past the HBM tuning
    bb_hbm = pallas_kernels.gather_pool_geometry(256, 3, 2, 13)
    bb_lan = pallas_kernels.gather_pool_geometry(256, 3, 2, 13,
                                                 lanes_table=True)
    assert bb_hbm == 64 and bb_lan == 128
    # the budget rule is unchanged: wide lane sources shrink the tile
    wide = pallas_kernels.gather_pool_geometry(4096, 26, 4, 128,
                                               lanes_table=True)
    assert wide is not None and wide <= 64
    assert pallas_kernels.gather_pool_geometry(8, 3, 2, 1024,
                                               lanes_table=True) is None


def test_gather_pool_kernel_parity_at_lanes_table_tile():
    """Kernel parity at a lanes-table tile the HBM cap would never pick
    (BB=128): the retuned geometry must change only the tiling, never
    the pooled sums."""
    cfg, table, idx, mask, seg = _mk(B=128, S=1, L=1, dim=4, n=64,
                                     seed=9)
    idx0 = np.where(mask, idx, 0).astype(np.int32)
    assert pallas_kernels.gather_pool_geometry(
        128, 1, 1, int(table.shape[1]), lanes_table=True) == 128
    out = pallas_kernels.gather_pool(table, jnp.asarray(idx0), cfg, 1, 1,
                                     lanes_table=True, interpret=True)
    P = cfg.pull_width
    ref = np.asarray(table)[idx0.reshape(-1), :P].reshape(
        128, 1, 1, P).sum(axis=2)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6,
                               atol=1e-6)


def _trainer_fixture(engine_flag, seed=3):
    from paddlebox_tpu.data import DataFeedSchema, SlotDataset
    from paddlebox_tpu.data.parser import parse_multislot_lines
    from paddlebox_tpu.embedding import HostEmbeddingStore
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig

    num_slots, vocab = 3, 40
    rng = np.random.default_rng(11)
    schema = DataFeedSchema.ctr(num_sparse=num_slots, num_float=1,
                                batch_size=16, max_len=2)
    lines = []
    for _ in range(64):
        parts = [f"1 {int(rng.random() < 0.3)}", f"1 {rng.normal():.4f}"]
        for s in range(num_slots):
            k = rng.integers(1, 3)
            ids = rng.integers(0, vocab, size=k) + s * 1000003
            parts.append(f"{len(ids)} {' '.join(str(i) for i in ids)}")
        lines.append(" ".join(parts))
    ds = SlotDataset(schema)
    ds.records = parse_multislot_lines(lines, schema)
    old = flags.fused_gather_pool
    flags.fused_gather_pool = engine_flag
    try:
        store = HostEmbeddingStore(EmbeddingConfig(dim=4,
                                                   learning_rate=0.1))
        model = DeepFMModel(num_slots=num_slots, emb_dim=4, dense_dim=1,
                            hidden=(8,))
        tr = Trainer(model, store, schema, make_mesh(1),
                     TrainerConfig(global_batch_size=16), seed=seed)
    finally:
        flags.fused_gather_pool = old
    return tr, ds, store


def test_trainer_heuristic_selects_fused_for_multihot():
    tr, _, _ = _trainer_fixture("auto")
    assert tr.pull_engine == "fused_gather_pool"   # max_len 2 multi-hot
    tr_off, _, _ = _trainer_fixture("off")
    assert tr_off.pull_engine == "gather_seqpool"


def test_trainer_heuristic_single_hot_narrow_stays_unfused():
    from paddlebox_tpu.data import DataFeedSchema
    from paddlebox_tpu.embedding import HostEmbeddingStore
    from paddlebox_tpu.models import DeepFMModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig

    schema = DataFeedSchema.ctr(num_sparse=3, num_float=1, batch_size=16,
                                max_len=1)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4, learning_rate=0.1))
    tr = Trainer(DeepFMModel(num_slots=3, emb_dim=4, dense_dim=1,
                             hidden=(8,)),
                 store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=16))
    assert tr.pull_engine == "gather_seqpool"
    # wide-dim single-hot selects fused
    store_w = HostEmbeddingStore(EmbeddingConfig(dim=64,
                                                 learning_rate=0.1))
    tr_w = Trainer(DeepFMModel(num_slots=3, emb_dim=64, dense_dim=1,
                               hidden=(8,)),
                   store_w, schema, make_mesh(1),
                   TrainerConfig(global_batch_size=16))
    assert tr_w.pull_engine == "fused_gather_pool"


@pytest.mark.skipif(not hasattr(jax, "shard_map"),
                    reason="the jitted step needs jax.shard_map "
                           "(same bar as the suite's trainer tests)")
def test_trainer_fused_matches_unfused_training():
    """Full train_pass + eval_pass parity: the fused engine must produce
    the same losses, predictions, and persisted table rows as the
    unfused step (pooling is linear, so the math is identical up to
    reduction order)."""

    def run(engine_flag):
        tr, ds, store = _trainer_fixture(engine_flag)
        out = tr.train_pass(ds)
        ev = tr.eval_pass(ds)
        tr.flush_sparse()
        keys = ds.unique_keys()
        return out, ev, store.peek_rows(np.unique(keys))

    out_f, ev_f, rows_f = run("on")
    out_u, ev_u, rows_u = run("off")
    assert abs(out_f["loss_mean"] - out_u["loss_mean"]) < 1e-5
    assert abs(out_f["auc"] - out_u["auc"]) < 1e-6
    assert abs(ev_f["auc"] - ev_u["auc"]) < 1e-6
    np.testing.assert_allclose(rows_f, rows_u, rtol=1e-5, atol=1e-6)
