"""PV / rank-attention path end-to-end (VERDICT r2 missing #3).

The load-bearing ad-model pipeline: merge_by_search_id groups a page
view's ads, the pack pipeline builds rank_offset per batch
(model.batch_extras — GetRankOffset, data_feed.h:1552-1706), and
PVRankModel (rank_attention + per-slot batch_fc + MLP) trains through
the full Trainer.train_pass lifecycle on the 8-device mesh.
"""

import numpy as np
import pytest

from paddlebox_tpu.data import DataFeedSchema, SlotDataset
from paddlebox_tpu.data.slot_record import SlotRecordBatch
from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.models import PVRankModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig

NUM_SLOTS, EMB_DIM, MAX_RANK = 3, 4, 3


def synth_pv_dataset(n_pv, seed=0, schema=None):
    """Page views of 1..MAX_RANK ads. The label carries a RANK-PAIR
    interaction signal: an ad converts more when a strong peer sits at
    rank 1 — learnable only through rank_attention's pairwise params."""
    rng = np.random.default_rng(seed)
    schema = schema or DataFeedSchema.ctr(num_sparse=NUM_SLOTS,
                                          num_float=1, batch_size=32,
                                          max_len=1)
    sv = [[] for _ in range(NUM_SLOTS)]
    labels, ranks, sids, dense = [], [], [], []
    id_w = np.random.default_rng(5).normal(size=400) * 1.2
    for pv in range(n_pv):
        k = int(rng.integers(1, MAX_RANK + 1))
        ids_at_rank1 = None
        members = []
        for r in range(1, k + 1):
            ids = rng.integers(1, 400, size=NUM_SLOTS)
            if r == 1:
                ids_at_rank1 = ids
            members.append((r, ids))
        for r, ids in members:
            base = id_w[ids].sum() * 0.5
            # pairwise term: rank-1 peer's strength boosts lower ranks
            peer = id_w[ids_at_rank1].sum() * (0.8 if r > 1 else 0.0)
            p = 1.0 / (1.0 + np.exp(-(base + peer - 0.3 * r)))
            labels.append(float(rng.random() < p))
            ranks.append(r)
            sids.append(pv + 1)
            dense.append(rng.normal())
            for s in range(NUM_SLOTS):
                sv[s].append(ids[s] + s * 1000003)
    n = len(labels)
    offs = np.arange(n + 1, dtype=np.int64)
    ds = SlotDataset(schema)
    ds.records = SlotRecordBatch(
        schema=schema, num=n,
        sparse_values=[np.asarray(v, np.int64) for v in sv],
        sparse_offsets=[offs.copy() for _ in range(NUM_SLOTS)],
        float_values=[np.asarray(labels, np.float32),
                      np.asarray(dense, np.float32)],
        ins_id=np.arange(n, dtype=np.uint64),
        search_id=np.asarray(sids, np.uint64),
        rank=np.asarray(ranks, np.int32),
        cmatch=np.zeros(n, np.int32))
    return ds, schema


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


def test_pv_rank_trains_through_train_pass(mesh8):
    ds, schema = synth_pv_dataset(600)
    groups = ds.merge_by_search_id()
    assert (np.diff(groups) >= 0).all()      # PVs contiguous
    store = HostEmbeddingStore(EmbeddingConfig(dim=EMB_DIM,
                                               learning_rate=0.15))
    model = PVRankModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM, dense_dim=1,
                        hidden=(32, 16), max_rank=MAX_RANK)
    tr = Trainer(model, store, schema, mesh8,
                 TrainerConfig(global_batch_size=32))
    outs = [tr.train_pass(ds) for _ in range(4)]
    losses = [o["loss_mean"] for o in outs]
    assert losses[-1] < losses[0], losses
    assert outs[-1]["auc"] > 0.6, outs[-1]["auc"]
    # rank params actually trained
    rp = np.asarray(tr.params["rank_param"])
    assert np.abs(rp).max() > 0.02
    # eval pass runs the extras path too
    ev = tr.eval_pass(ds)
    assert ev["auc"] > 0.6


def test_packed_batches_carry_search_id(mesh8):
    ds, schema = synth_pv_dataset(40, seed=3)
    ds.merge_by_search_id()
    pb = next(iter(ds.batches(16, drop_last=True)))
    assert pb.search_id is not None and len(pb.search_id) == 16
    # rank_offset built per shard slices peers shard-locally
    model = PVRankModel(num_slots=NUM_SLOTS, emb_dim=EMB_DIM,
                        max_rank=MAX_RANK)
    (ro,) = model.batch_extras(pb, n_shards=4)
    assert ro.shape == (16, 2 * MAX_RANK + 1)
    bl = 16 // 4
    for s in range(4):
        sl = ro[s * bl:(s + 1) * bl]
        peer_idx = sl[:, 2::2]
        assert peer_idx.max(initial=0) < bl   # shard-local indices


def test_vectorized_rank_offset_matches_reference():
    from paddlebox_tpu.ops.rank_attention import (
        build_rank_offset, build_rank_offset_reference)
    rng = np.random.default_rng(0)
    for trial in range(20):
        B = int(rng.integers(1, 80))
        K = int(rng.integers(1, 6))
        groups = rng.integers(0, 12, size=B).astype(np.uint64)
        ranks = rng.integers(0, K + 2, size=B).astype(np.int32)  # incl >K
        got = build_rank_offset(ranks, groups, K)
        want = build_rank_offset_reference(ranks, groups, K)
        np.testing.assert_array_equal(got, want)
    # empty batch
    np.testing.assert_array_equal(
        build_rank_offset(np.zeros(0, np.int32), np.zeros(0, np.uint64), 3),
        np.zeros((0, 7), np.int32))
