"""Worker process for the multi-host kill→resume matrix (ISSUE 5).

Launched (2 processes) by tests/test_multihost_crash.py via
paddlebox_tpu.distributed.launch. Each rank runs its own deterministic
shard of a pass-loop training job (per-rank dataset/seed, per-rank
snapshot root — the data-parallel sparse-training shape where one
diverging or dead rank poisons the world), coordinated through the
FileStore control plane:

- run-scoped heartbeats + the dead/stalled-peer watchdog polled inside
  every barrier/collective wait (named-rank errors, peer_lost /
  peer_stalled telemetry into ``events_{rank}.jsonl``),
- lockstep pass boundaries (BoxPS.attach_collectives),
- COORDINATED resume election on startup: every rank publishes its intact
  snapshot cursors, the world restores the highest cursor every rank
  holds intact (``resume_{rank}.json`` records the elected cursor for the
  pytest side), including mid-pass cursors (skip_steps + shuffle state).

Environment knobs (set by the test):
  PBTPU_TEST_WORKDIR         output dir (npz dumps, resume/err json, events)
  PBTPU_CRASH_ROOT           snapshot roots base (per-rank subdir appended)
  PBTPU_CRASH_MIDPASS        mid-pass snapshot cadence (steps; 0 = off)
  PBTPU_CRASH_REMOTE_BASE    remote snapshot base URI (per-rank suffix)
  PBTPU_CRASH_WIPE_LOCAL_RANK  rank whose local staging root is wiped at
                               startup (replacement-host download path)
  PBTPU_FAULTPOINT_ONLY_RANK  faultpoint armed only on this rank
  PBTPU_TEST_STALL_RANK / _STALL_S   hang injection (mid pass 2)
  PBTPU_TEST_STALL_AFTER_S   watchdog stall threshold override
"""

import json
import os
import shutil
import sys
import time
import traceback

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mockfs  # noqa: E402
from crash_worker import synth  # noqa: E402
from paddlebox_tpu import monitor  # noqa: E402
from paddlebox_tpu.distributed import RoleMaker  # noqa: E402
from paddlebox_tpu.distributed.resilience import HeartbeatMonitor  # noqa: E402
from paddlebox_tpu.embedding import (EmbeddingConfig,  # noqa: E402
                                     HostEmbeddingStore)
from paddlebox_tpu.fleet import BoxPS  # noqa: E402
from paddlebox_tpu.models import DNNCTRModel  # noqa: E402
from paddlebox_tpu.parallel import make_mesh  # noqa: E402
from paddlebox_tpu.train import Trainer, TrainerConfig  # noqa: E402
from paddlebox_tpu.utils import faultpoint  # noqa: E402
from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer  # noqa: E402

PASSES = 3
NUM_SLOTS = 3


def run(rank_log) -> None:
    rm = RoleMaker.from_env()
    work = os.environ["PBTPU_TEST_WORKDIR"]
    only = os.environ.get("PBTPU_FAULTPOINT_ONLY_RANK", "")
    if only and only != str(rm.rank):
        faultpoint.disarm()
    mockfs.register_from_env()

    # telemetry: rank-tagged JSONL so the pytest side can assert the
    # resume_election / peer_lost / peer_stalled event stream
    monitor.hub().enable(monitor.JsonlSink(
        os.path.join(work, f"events_{rm.rank}.jsonl")))

    col = rm.collectives(timeout_s=90)
    # col.store is already run-id-namespaced (RoleMaker) — no hb run_id
    hb = HeartbeatMonitor(
        col.store, rm.rank, rm.world_size,
        interval_s=0.2, lost_after_s=15.0,
        stall_after_s=float(os.environ.get("PBTPU_TEST_STALL_AFTER_S",
                                           "60")))
    col.watchdog = hb

    crash_root = os.environ["PBTPU_CRASH_ROOT"]
    local_root = os.path.join(crash_root, f"rank{rm.rank}")
    if os.environ.get("PBTPU_CRASH_WIPE_LOCAL_RANK", "") == str(rm.rank):
        shutil.rmtree(local_root, ignore_errors=True)
    remote_base = os.environ.get("PBTPU_CRASH_REMOTE_BASE", "")
    midpass = int(os.environ.get("PBTPU_CRASH_MIDPASS", "0"))
    stall_rank = os.environ.get("PBTPU_TEST_STALL_RANK", "")
    stall_s = float(os.environ.get("PBTPU_TEST_STALL_S", "45"))

    ds, schema = synth(seed=11 + rm.rank)
    base = ds.records
    store = HostEmbeddingStore(EmbeddingConfig(dim=4, learning_rate=0.05))
    tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                             hidden=(8,)),
                 store, schema, make_mesh(1),
                 TrainerConfig(global_batch_size=64, dense_lr=2e-3,
                               auc_buckets=1 << 8),
                 seed=7 + rm.rank)
    box = BoxPS(store)
    box.set_date(20260801)
    box.init_metric("job_auc", n_buckets=128)
    box.attach_collectives(col, heartbeat=hb)
    if remote_base:
        ckpt = PassCheckpointer(f"{remote_base}/rank{rm.rank}",
                                keep_last_n=4, base_every=2,
                                staging_dir=local_root)
    else:
        ckpt = PassCheckpointer(local_root, keep_last_n=4, base_every=2)
    if midpass > 0:
        tr.enable_midpass_snapshots(ckpt, midpass, box, metrics=box.metrics)

    # ---- coordinated resume election --------------------------------------
    cursor = tr.resume(ckpt, box=box, collectives=col)
    skip = 0
    if cursor is not None:
        if cursor.get("shuffle_state"):
            ds.set_shuffle_state(cursor["shuffle_state"])
        skip = int(cursor.get("mid_steps") or 0)
    start = (int(cursor["pass_id"]) if cursor is not None else 0) + 1
    with open(os.path.join(work, f"resume_{rm.rank}.json"), "w") as f:
        json.dump({"rank": rm.rank,
                   "elected": None if cursor is None
                   else cursor.get("elected"),
                   "pass_id": None if cursor is None
                   else int(cursor["pass_id"]),
                   "mid_steps": skip, "start": start}, f)
    rank_log(f"resume cursor={cursor is not None} start={start} "
             f"skip={skip}")

    for p in range(start, PASSES + 1):
        tr.midpass_cursor_extra = {"shuffle_state": ds.shuffle_state()}
        ds.records = base
        ds.local_shuffle()
        box.begin_pass()
        tr.train_pass(ds, metrics=box.metrics,
                      skip_steps=(skip if p == start else 0))
        if stall_rank == str(rm.rank) and p == 2:
            # hang injection: the interpreter (and its heartbeat daemon)
            # stay alive but pass/step progress freezes — peers must name
            # this rank in a PeerStalledError instead of timing out
            rank_log(f"stalling for {stall_s}s mid pass {p}")
            time.sleep(stall_s)
        box.end_pass(checkpointer=ckpt, trainer=tr, dataset=ds)

    # ---- final-state dump -------------------------------------------------
    tr.flush_sparse()
    keys = np.sort(np.asarray(ds.unique_keys(), dtype=np.uint64))
    rows = store.get_rows(keys)
    dense = {f"p{i}": np.asarray(leaf) for i, leaf in
             enumerate(jax.tree_util.tree_leaves(
                 {"params": tr.params, "opt": tr.opt_state}))}
    met = box.metrics.get_state("job_auc")
    np.savez(os.path.join(work, f"out_{rm.rank}.npz"),
             keys=keys, rows=rows,
             global_step=np.int64(tr.global_step),
             pass_id=np.int64(box.pass_id),
             met_pos=np.asarray(met["pos"]),
             met_neg=np.asarray(met["neg"]), **dense)
    col.barrier("done")
    hb.close()
    monitor.hub().disable()
    rank_log("done")


def main() -> None:
    rm_rank = os.environ.get("PBTPU_TRAINER_ID", "?")
    work = os.environ["PBTPU_TEST_WORKDIR"]

    def rank_log(msg):
        print(f"rank {rm_rank}: {msg}", flush=True)

    try:
        run(rank_log)
    except BaseException as e:
        # surface the failure to the pytest side (launch() inherits stdio)
        with open(os.path.join(work, f"err_{rm_rank}.txt"), "w") as f:
            f.write(f"{type(e).__name__}: {e}\n")
            f.write(traceback.format_exc())
        from paddlebox_tpu import monitor as _mon
        _mon.hub().disable()   # flush the JSONL sink: peer_* events land
        raise


if __name__ == "__main__":
    main()
