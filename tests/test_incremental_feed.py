"""Incremental delta feeds, per-host shard ownership, and overlapped
spill staging (ISSUE 14).

The acceptance bar: every incremental path — stale-resident re-fetch
after a shrink/replay, the staged-feed patch plane, ownership-filtered
builds — must land BIT-IDENTICAL state to the full-rebuild feed on the
same key/mutation stream, across all four row classes (fresh / dirty /
evicted / reused), including eval peeks and flushes at pass/eval/save
boundaries.
"""

import mmap
import os
import tempfile

import numpy as np
import pytest

import jax.numpy as jnp

from paddlebox_tpu.config import flags
from paddlebox_tpu.distributed.ownership import ShardOwnership
from paddlebox_tpu.embedding import (EmbeddingConfig, HostEmbeddingStore,
                                     ShardedEmbeddingStore)
from paddlebox_tpu.embedding.feed_pass import FeedPassManager
from paddlebox_tpu.embedding.spill_store import SpillEmbeddingStore
from paddlebox_tpu.embedding.tiering import end_pass_rebalance
from paddlebox_tpu.utils import faultpoint


def cfg_small(**kw):
    kw.setdefault("dim", 4)
    kw.setdefault("optimizer", "adagrad")
    kw.setdefault("learning_rate", 0.1)
    return EmbeddingConfig(**kw)


def _keys(lo, hi):
    return np.sort(np.arange(lo, hi, dtype=np.uint64)
                   * np.uint64(2654435761) + 1)


@pytest.fixture(autouse=True)
def _restore_flags():
    inc, pre, auto = (flags.incremental_feed, flags.spill_prefetch,
                      flags.spill_cache_autotune)
    yield
    flags.incremental_feed = inc
    flags.spill_prefetch = pre
    flags.spill_cache_autotune = auto
    faultpoint.disarm()


# ---------------------------------------------------------------------------
# store-side stale-key log
# ---------------------------------------------------------------------------

def test_stale_log_pure_eviction_shrink():
    c = cfg_small()
    store = HostEmbeddingStore(c)
    keys = _keys(0, 100)
    rows = store.lookup_or_init(keys)
    rows[:50, 0] = 5.0                       # half stay warm
    store.write_back(keys, rows)
    m = store.mutation_marker()
    assert np.array_equal(store.stale_keys_since(m),
                          np.zeros(0, np.uint64))
    store.shrink(min_show=1.0, decay=1.0)    # evicts the cold half
    stale = store.stale_keys_since(m)
    assert stale is not None
    assert set(stale.tolist()) == set(keys[50:].tolist())


def test_stale_log_decay_shrink_is_unknowable():
    store = HostEmbeddingStore(cfg_small())
    keys = _keys(0, 10)
    rows = store.lookup_or_init(keys)
    rows[:, 0] = 5.0
    store.write_back(keys, rows)
    m = store.mutation_marker()
    store.shrink(min_show=1.0, decay=0.5)    # decays EVERY row
    assert store.stale_keys_since(m) is None


def test_stale_log_ingest_and_remove_and_restore():
    c = cfg_small()
    store = HostEmbeddingStore(c)
    store.lookup_or_init(_keys(0, 50))
    m = store.mutation_marker()
    # a foreign delta replay names its keys
    donor = HostEmbeddingStore(c)
    dk = _keys(10, 20)
    dr = donor.lookup_or_init(dk)
    dr[:, 2] = 7.0
    donor.write_back(dk, dr)
    with tempfile.TemporaryDirectory() as d:
        f = donor.save_delta(os.path.join(d, "delta"))
        store.apply_delta_file(f)
    stale = store.stale_keys_since(m)
    assert stale is not None and set(stale.tolist()) == set(dk.tolist())
    # a restore resets the space — unknowable from any older marker
    with tempfile.TemporaryDirectory() as d:
        donor.save_base(os.path.join(d, "base"))
        store.restore(os.path.join(d, "base"))
    assert store.stale_keys_since(m) is None


def test_stale_log_ring_rollover_degrades_to_unknown():
    from paddlebox_tpu.embedding import store as store_mod
    store = HostEmbeddingStore(cfg_small())
    store.lookup_or_init(_keys(0, 100))
    m = store.mutation_marker()
    donor = HostEmbeddingStore(cfg_small())
    dk = _keys(0, 1)
    dr = donor.lookup_or_init(dk)
    donor.write_back(dk, dr)
    with tempfile.TemporaryDirectory() as d:
        f = donor.save_delta(os.path.join(d, "delta"))
        for _ in range(store_mod._STALE_LOG_EVENTS + 1):
            store.apply_delta_file(f)
        assert store.stale_keys_since(m) is None
        # but a marker INSIDE the retained window still resolves
        m2 = store.mutation_marker()
        store.apply_delta_file(f)
        assert store.stale_keys_since(m2) is not None


def test_sharded_stale_log_union():
    c = cfg_small()
    ss = ShardedEmbeddingStore(c, 4)
    keys = _keys(0, 200)
    rows = ss.lookup_or_init(keys)
    rows[:100, 0] = 5.0
    ss.write_back(keys, rows)
    m = ss.mutation_marker()
    assert isinstance(m, tuple) and len(m) == 4
    ss.shrink(min_show=1.0, decay=1.0)
    stale = ss.stale_keys_since(m)
    assert stale is not None
    assert set(stale.tolist()) == set(keys[100:].tolist())
    assert ss.stale_keys_since((0,) * 3) is None     # foreign marker


# ---------------------------------------------------------------------------
# bit-parity: incremental vs full-rebuild feeds on one stream
# ---------------------------------------------------------------------------

def _scenario(incremental: bool, store_factory=None, replay: bool = True):
    """One mutation-heavy stream exercising every row class: reused
    (stay resident), dirty (trained on device), evicted (shrink),
    stale (foreign delta replay — host stores only), fresh (new keys +
    re-added evicted), with an eval peek and flushes at pass/eval/save
    boundaries. Returns every comparable plane for bitwise assertion."""
    flags.incremental_feed = incremental
    c = cfg_small()
    store = (store_factory or HostEmbeddingStore)(c)
    mgr = FeedPassManager(store)
    k1 = _keys(0, 400)
    ws1 = mgr.begin_pass(k1)
    idx = ws1.translate(k1)
    t = np.asarray(ws1.table).copy()
    t[idx, 0] = 3.0                          # all warm...
    t[idx[:80], 0] = 0.0                     # ...except an evictable tail
    t[idx, 2] += 1.0                         # trained w (dirty rows)
    mgr.end_pass(ws1, jnp.asarray(t))
    # pure-eviction hygiene between passes (flushes the device tier
    # first via the store's flush hooks, then mutates)
    evicted = store.shrink(min_show=1.0, decay=1.0)
    assert evicted == 80
    # foreign delta replay dirties a handful of RESIDENT keys (the
    # stale class: their device copy is void, the store wins)
    stale_keys = k1[100:110]
    if replay:
        donor = HostEmbeddingStore(c)
        dr = donor.lookup_or_init(stale_keys)
        dr[:, 2] = 42.0
        donor.write_back(stale_keys, dr)
        with tempfile.TemporaryDirectory() as d:
            store.apply_delta_file(
                donor.save_delta(os.path.join(d, "dd")))
    # pass 2: drop 100 resident keys, add fresh ones, re-add 10 evicted
    k2 = np.unique(np.concatenate([k1[180:], _keys(5000, 5100),
                                   k1[:10]]))
    # eval peek BETWEEN the mutation and the next train pass must see
    # store-authoritative bytes without inserting or flushing
    ev = mgr.begin_pass(k2, test_mode=True)
    ev_idx = ev.translate(stale_keys)
    eval_rows = np.asarray(ev.table)[ev_idx].copy()
    ws2 = mgr.begin_pass(k2)
    table2 = np.asarray(ws2.table).copy()
    mgr.end_pass(ws2, ws2.table)
    mgr.flush()                              # save-boundary flush
    with tempfile.TemporaryDirectory() as d:
        store.save_delta(os.path.join(d, "save"))
    rows = store.get_rows(np.unique(np.concatenate([k1[80:], k2])))
    mgr.close()
    return {"eval_rows": eval_rows, "table2": table2, "rows": rows,
            "fresh": mgr.last_fresh_rows, "reused": mgr.last_reused_rows}


def test_incremental_bit_parity_with_full_rebuild():
    a = _scenario(True)
    b = _scenario(False)
    np.testing.assert_array_equal(a["eval_rows"], b["eval_rows"])
    np.testing.assert_array_equal(a["table2"], b["table2"])
    np.testing.assert_array_equal(a["rows"], b["rows"])
    # and the incremental run actually reused resident rows across the
    # mutation while the full rebuild re-fetched everything
    assert a["reused"] > 0
    assert b["reused"] == 0
    assert a["fresh"] < b["fresh"]


def test_incremental_bit_parity_sharded_spill():
    def factory(c):
        from paddlebox_tpu.embedding.tiering import shard_store_factory
        td = tempfile.mkdtemp(prefix="pbtpu_incfeed_")
        return ShardedEmbeddingStore(
            c, 2, store_factory=shard_store_factory(
                tiering="spill", cache_rows=64, spill_dir=td))
    a = _scenario(True, store_factory=factory, replay=False)
    b = _scenario(False, store_factory=factory, replay=False)
    np.testing.assert_array_equal(a["table2"], b["table2"])
    np.testing.assert_array_equal(a["rows"], b["rows"])
    assert a["reused"] > 0


def test_staged_feed_survives_mutation_via_patch():
    """begin_feed_pass stages pass 2, THEN the store mutates: the staged
    transfer must be patched with the mutated rows (compact delta
    plane), not discarded — and land bit-identical to a full rebuild."""
    flags.incremental_feed = True
    c = cfg_small()
    store = HostEmbeddingStore(c)
    mgr = FeedPassManager(store)
    k1 = _keys(0, 300)
    ws1 = mgr.begin_pass(k1)
    ws1.translate(k1)
    mgr.end_pass(ws1, ws1.table)
    k2 = np.unique(np.concatenate([k1[50:], _keys(9000, 9050)]))
    mgr.begin_feed_pass(k2)
    mgr.wait_feed_pass_done()
    # mutate AFTER staging: a foreign delta rewrites rows that are (a)
    # resident, (b) freshly staged, and (c) absent from pass 2
    donor = HostEmbeddingStore(c)
    mut = np.unique(np.concatenate([k1[60:70], _keys(9000, 9010),
                                    k1[:5]]))
    dr = donor.lookup_or_init(mut)
    dr[:, 2] = 13.0
    donor.write_back(mut, dr)
    with tempfile.TemporaryDirectory() as d:
        delta = donor.save_delta(os.path.join(d, "dd"))
        store.apply_delta_file(delta)
        ws2 = mgr.begin_pass(k2)
        assert mgr.last_fresh_rows == 50     # the staging was CONSUMED
        assert mgr.last_patched_rows == 20   # resident + staged, not (c)
        idx = ws2.translate(np.concatenate([k1[60:70],
                                            _keys(9000, 9010)]))
        np.testing.assert_array_equal(np.asarray(ws2.table)[idx, 2],
                                      np.full(20, 13.0, np.float32))
        # reference: the same stream through a full rebuild
        flags.incremental_feed = False
        store_b = HostEmbeddingStore(c)
        mgr_b = FeedPassManager(store_b)
        wb1 = mgr_b.begin_pass(k1)
        wb1.translate(k1)
        mgr_b.end_pass(wb1, wb1.table)
        store_b.lookup_or_init(k2)           # staging inserted k2 fresh
        store_b.apply_delta_file(delta)
        wb2 = mgr_b.begin_pass(k2)
    np.testing.assert_array_equal(np.asarray(ws2.table),
                                  np.asarray(wb2.table))


def test_flush_after_known_mutation_keeps_unstale_rows():
    """A flush crossing a provable mutation drops ONLY the stale marks;
    every other unsynced device row still reaches the store (it used to
    drop them all)."""
    flags.incremental_feed = True
    c = cfg_small()
    store = HostEmbeddingStore(c)
    mgr = FeedPassManager(store)
    keys = _keys(0, 100)
    ws = mgr.begin_pass(keys)
    idx = ws.translate(keys)
    t = np.asarray(ws.table).copy()
    t[idx, 2] = 9.0
    mgr.end_pass(ws, jnp.asarray(t))
    donor = HostEmbeddingStore(c)
    dr = donor.lookup_or_init(keys[:10])
    dr[:, 2] = 77.0
    donor.write_back(keys[:10], dr)
    with tempfile.TemporaryDirectory() as d:
        store.apply_delta_file(donor.save_delta(os.path.join(d, "dd")))
    mgr.flush()
    # mutated rows kept the REPLAYED value; the rest flushed the device
    np.testing.assert_array_equal(store.get_rows(keys[:10])[:, 2],
                                  np.full(10, 77.0, np.float32))
    np.testing.assert_array_equal(store.get_rows(keys[10:])[:, 2],
                                  np.full(90, 9.0, np.float32))


def test_delta_stage_ioerror_leaves_manager_usable():
    flags.incremental_feed = True
    store = HostEmbeddingStore(cfg_small())
    mgr = FeedPassManager(store)
    k1 = _keys(0, 100)
    ws1 = mgr.begin_pass(k1)
    ws1.translate(k1)
    mgr.end_pass(ws1, ws1.table)
    faultpoint.arm("feed_pass.delta_stage.pre", action="ioerror")
    k2 = _keys(50, 150)
    with pytest.raises(OSError):
        mgr.begin_pass(k2)
    faultpoint.disarm()
    ws2 = mgr.begin_pass(k2)
    assert set(ws2.sorted_keys.tolist()) == set(k2.tolist())


# ---------------------------------------------------------------------------
# per-host shard ownership
# ---------------------------------------------------------------------------

def test_two_host_ownership_disjoint_cover():
    """The required 2-host split proof: the two ranks' filtered key sets
    partition the key space — disjoint, and their union is everything."""
    ss = ShardedEmbeddingStore(cfg_small(), 4)
    keys = _keys(0, 5000)
    o0 = ShardOwnership.for_store(ss, 2, 0)
    o1 = ShardOwnership.for_store(ss, 2, 1)
    k0 = o0.filter_keys(ss, keys)
    k1 = o1.filter_keys(ss, keys)
    assert len(np.intersect1d(k0, k1)) == 0
    assert set(np.concatenate([k0, k1]).tolist()) == set(keys.tolist())
    # hash partition is host-stable: both ranks agree who owns what
    assert np.array_equal(o0.owned, np.array([0, 2]))
    assert np.array_equal(o1.owned, np.array([1, 3]))
    # unsharded stores have no partition to split
    assert ShardOwnership.for_store(HostEmbeddingStore(cfg_small()),
                                    2, 0) is None


def test_feed_builds_only_owned_shards():
    ss = ShardedEmbeddingStore(cfg_small(), 4)
    keys = _keys(0, 1000)
    own = ShardOwnership.for_store(ss, 2, 0)
    mgr = FeedPassManager(ss, ownership=own)
    ws = mgr.begin_pass(keys)
    expect = own.filter_keys(ss, keys)
    assert np.array_equal(ws.sorted_keys, expect)
    assert 0 < len(expect) < len(keys)
    # the background feed filters identically, so staging matches
    k2 = _keys(100, 1100)
    mgr.begin_feed_pass(k2)
    mgr.end_pass(ws, ws.table)
    ws2 = mgr.begin_pass(k2)
    assert np.array_equal(ws2.sorted_keys, own.filter_keys(ss, k2))
    assert mgr.last_reused_rows > 0          # staging was consumed
    mgr.close()


def test_ownership_rebind_rebuilds_new_shards_only():
    """The elastic-grow hook: a world resize re-deals the shards and the
    next begin_pass builds exactly the NEW owned set (a replacement
    host fetches its shards' rows, nothing else)."""
    ss = ShardedEmbeddingStore(cfg_small(), 4)
    keys = _keys(0, 1000)
    own2 = ShardOwnership.for_store(ss, 2, 0)
    mgr = FeedPassManager(ss, ownership=own2)
    ws = mgr.begin_pass(keys)
    idx = ws.translate(ws.sorted_keys)
    t = np.asarray(ws.table).copy()
    t[idx, 2] = 4.0
    mgr.end_pass(ws, jnp.asarray(t))
    # world shrinks to 1: this host now owns every shard; the rebind
    # flushes pending rows and drops the resident set
    mgr.set_ownership(own2.with_world(1, 0))
    np.testing.assert_array_equal(
        ss.get_rows(own2.filter_keys(ss, keys))[:, 2], 4.0)
    ws_all = mgr.begin_pass(keys)
    assert np.array_equal(ws_all.sorted_keys, keys)
    mgr.close()


def test_ownership_validation():
    with pytest.raises(ValueError):
        ShardOwnership(4, 2, 2)
    with pytest.raises(ValueError):
        ShardOwnership(0, 1, 0)
    with pytest.raises(TypeError):
        ShardOwnership(4, 2, 0).filter_keys(
            HostEmbeddingStore(cfg_small()), _keys(0, 10))


# ---------------------------------------------------------------------------
# overlapped spill staging: madvise prefetch + cache autotune
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not hasattr(mmap, "MADV_WILLNEED"),
                    reason="platform has no madvise")
def test_spill_prefetch_advises_misses_only():
    flags.spill_prefetch = True
    store = SpillEmbeddingStore(cfg_small(), cache_rows=32)
    keys = _keys(0, 500)
    rows = store.lookup_or_init(keys)
    rows[:, 2] = 5.0
    store.write_back(keys, rows)
    before = store.prefetched_rows
    n = store.prefetch_rows(keys)
    assert n > 0 and store.prefetched_rows == before + n
    # unknown keys never insert, cached rows never re-advise
    assert store.prefetch_rows(_keys(9000, 9100)) == 0
    assert len(store) == 500
    # a prefetch is advisory: the values are untouched
    np.testing.assert_array_equal(store.get_rows(keys)[:, 2], 5.0)


def test_feed_pass_prefetches_spill_rows():
    flags.spill_prefetch = True
    store = SpillEmbeddingStore(cfg_small(), cache_rows=16)
    keys = _keys(0, 400)
    store.lookup_or_init(keys)               # the table exists on disk
    mgr = FeedPassManager(store)
    mgr.begin_pass(keys)                     # full build → prefetch
    if hasattr(mmap, "MADV_WILLNEED"):
        assert store.prefetched_rows > 0
    flags.spill_prefetch = False
    p0 = store.prefetched_rows
    mgr.drop()
    mgr.begin_pass(keys)
    assert store.prefetched_rows == p0       # flag gates the readahead


def test_spill_cache_autotune_grows_on_thrash_and_records():
    from paddlebox_tpu import monitor
    flags.spill_cache_autotune = True
    store = SpillEmbeddingStore(cfg_small(), cache_rows=256)
    keys = _keys(0, 4000)
    store.lookup_or_init(keys)
    hub = monitor.hub()
    hub.begin_pass(1)
    store.lookup_or_init(keys)               # thrash: 4000 keys, 256 slots
    agg = end_pass_rebalance(store)
    rec = hub.end_pass()
    assert agg["cache_resized"] == 1
    assert agg["cache_rows"] == 512          # doubled, bounded
    assert store._cache_slots == 512
    assert rec["extra"]["spill_cache_rows"] == 512
    # quiet telemetry → no resize
    hub.begin_pass(2)
    agg2 = end_pass_rebalance(store)
    hub.end_pass()
    assert agg2["cache_resized"] == 0


def test_spill_cache_autotune_off_by_default():
    flags.spill_cache_autotune = False
    store = SpillEmbeddingStore(cfg_small(), cache_rows=256)
    keys = _keys(0, 4000)
    store.lookup_or_init(keys)
    store.lookup_or_init(keys)
    agg = end_pass_rebalance(store)
    assert store._cache_slots == 256
    assert "cache_resized" not in agg
