"""Host parameter-server cluster: sparse/dense pull-push, sharding,
save/load/shrink, and the RemoteEmbeddingStore-backed trainer flow.

Tested the reference's way (test_collective_base.py / test_dist_base.py:
real localhost exchanges, no mocks) — servers run threaded in-proc, the
client speaks the actual wire protocol through real sockets.
"""

import numpy as np
import pytest

from paddlebox_tpu.distributed.ps import (PSClient, PSServer,
                                          RemoteEmbeddingStore, _pack,
                                          _unpack)
from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore


@pytest.fixture
def cluster():
    servers = [PSServer().start() for _ in range(2)]
    client = PSClient([(s.host, s.port) for s in servers])
    yield client, servers
    client.stop_servers()


def test_pack_roundtrip():
    h = {"cmd": "x", "n": 3}
    arrs = [np.arange(6, dtype=np.uint64).reshape(2, 3),
            np.ones(4, np.float32)]
    header, out = _unpack(_pack(h, arrs)[8:])
    assert header["cmd"] == "x" and header["n"] == 3
    np.testing.assert_array_equal(out[0], arrs[0])
    np.testing.assert_array_equal(out[1], arrs[1])


def test_sparse_pull_push_matches_local_store(cluster):
    client, _ = cluster
    cfg = EmbeddingConfig(dim=4, optimizer="adagrad", learning_rate=0.1)
    client.create_sparse_table("emb", cfg)
    keys = np.array([1, 2, 3, 4, 5, 1 << 50], dtype=np.uint64)

    pulled = client.pull_sparse("emb", keys)
    assert pulled.shape == (6, cfg.pull_width)

    # push some grads (with a duplicated key to exercise the merge path)
    pkeys = np.array([1, 2, 1], dtype=np.uint64)
    grads = np.ones((3, cfg.grad_width), np.float32) * 0.5
    client.push_sparse("emb", pkeys, grads, np.ones(3, np.float32),
                       np.zeros(3, np.float32))

    # local twin: same config, same ops
    local = HostEmbeddingStore(cfg)
    local.lookup_or_init(keys)
    from paddlebox_tpu.embedding.optim import apply_updates
    uniq, inv = np.unique(pkeys, return_inverse=True)
    m = np.zeros((len(uniq), cfg.grad_width + 2), np.float32)
    np.add.at(m, inv, np.concatenate(
        [grads, np.ones((3, 1), np.float32), np.zeros((3, 1), np.float32)],
        axis=1))
    rows = local.lookup_or_init(uniq)
    local.write_back(uniq, np.asarray(apply_updates(
        rows, m[:, :cfg.grad_width], m[:, cfg.grad_width],
        m[:, cfg.grad_width + 1], cfg)))

    got = client.pull_sparse("emb", keys)
    want = local.get_rows(keys)[:, :cfg.pull_width]
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
    # keys are sharded: both servers should own part of the table
    stats = client.stats()
    counts = [s["sparse"]["emb"] for s in stats]
    assert sum(counts) == 6 and all(c > 0 for c in counts)


def test_sparse_async_push_flush(cluster):
    client, _ = cluster
    cfg = EmbeddingConfig(dim=2, optimizer="sgd", learning_rate=1.0)
    client.create_sparse_table("t", cfg)
    keys = np.arange(1, 9, dtype=np.uint64)
    client.pull_sparse("t", keys)
    for _ in range(4):
        client.push_sparse("t", keys, np.ones((8, cfg.grad_width),
                                              np.float32),
                           np.ones(8, np.float32), np.zeros(8, np.float32),
                           wait=False)
    client.flush()
    got = client.pull_sparse("t", keys)
    np.testing.assert_allclose(got[:, 0], 4.0)      # shows accumulated
    np.testing.assert_allclose(got[:, 2], -4.0)     # w -= lr * sum(g)


def test_dense_table(cluster):
    client, _ = cluster
    init = np.zeros(16, np.float32)
    client.create_dense_table("mlp", init, lr=0.5)
    client.push_dense("mlp", np.ones(16, np.float32))
    # async apply: poll until the updater thread lands it
    import time
    for _ in range(100):
        got = client.pull_dense("mlp")
        if np.any(got != 0):
            break
        time.sleep(0.01)
    assert np.all(got != 0)


def test_save_load_shrink(cluster, tmp_path):
    client, servers = cluster
    cfg = EmbeddingConfig(dim=2)
    client.create_sparse_table("emb", cfg)
    keys = np.arange(1, 33, dtype=np.uint64)
    client.pull_sparse("emb", keys)
    # train half the keys so they have shows
    half = keys[:16]
    client.push_sparse("emb", half, np.ones((16, cfg.grad_width), np.float32),
                       np.ones(16, np.float32), np.zeros(16, np.float32))
    files = client.save("emb", str(tmp_path / "ck"))
    assert len(files) == 2
    before = client.pull_sparse("emb", keys)

    # evict cold rows (show < 1): the untrained half disappears
    evicted = client.shrink("emb", min_show=0.5)
    assert evicted == 16

    client.load("emb", str(tmp_path / "ck"))
    after = client.pull_sparse("emb", keys)
    np.testing.assert_allclose(after, before, rtol=1e-6)


def test_error_propagates(cluster):
    client, _ = cluster
    with pytest.raises(RuntimeError, match="not created"):
        client.pull_sparse("nope", np.array([1], dtype=np.uint64))


def test_trainer_on_remote_store(cluster):
    """Full training flow with the table on the PS cluster (DownpourWorker
    arrangement): PassWorkingSet pulls rows from the servers, trains on the
    mesh, writes rows back at end_pass."""
    import jax
    from paddlebox_tpu.data import DataFeedSchema
    from paddlebox_tpu.embedding import PassWorkingSet
    from paddlebox_tpu.models import DNNCTRModel
    from paddlebox_tpu.parallel import make_mesh
    from paddlebox_tpu.train import Trainer, TrainerConfig

    client, _ = cluster
    cfg = EmbeddingConfig(dim=4)
    store = RemoteEmbeddingStore(client, "emb_t", cfg)
    schema = DataFeedSchema.ctr(num_sparse=3, num_float=1, batch_size=16,
                                max_len=1)
    mesh = make_mesh(4)
    model = DNNCTRModel(num_slots=3, emb_dim=4, dense_dim=1, hidden=(8,))
    tr = Trainer(model, store, schema, mesh,
                 TrainerConfig(global_batch_size=16, auc_buckets=1 << 8))

    rng = np.random.default_rng(0)
    keys = rng.choice(1 << 40, 50, replace=False).astype(np.uint64)
    ws = PassWorkingSet.begin_pass(store, keys, mesh)
    T = tr.layout.total_len
    from paddlebox_tpu.parallel import mesh as mesh_lib
    sh = mesh_lib.batch_sharding(mesh)
    raw = rng.choice(keys, size=(16, T))
    idx = ws.translate(raw, np.ones((16, T), bool))
    table, dstate = ws.table, tr.pack_dense()
    args = [jax.device_put(np.asarray(a), sh) for a in
            (idx, np.ones((16, T), bool),
             rng.normal(size=(16, 1)).astype(np.float32),
             (rng.random(16) < 0.5).astype(np.float32))]
    out = tr._step_fn(table, *dstate, *args,
                      *(tr.NO_PLAN,) * 5)
    table, _, loss, _, dropped = tr.split_step_out(out)
    assert np.isfinite(float(loss))
    assert int(dropped) == 0
    ws.table = table
    ws.end_pass(store, table)
    # the trained rows landed back on the servers
    rows = store.peek_rows(keys)
    assert np.any(rows[:, 0] > 0)  # shows incremented on trained keys


def test_concurrent_pushers_striped_locks():
    """Many threads pushing overlapping key sets concurrently (the
    multi-trainer PS regime, fleet_wrapper.h:200): counters must be
    exact and sgd weights must equal the serial result — the striped
    locks may reorder same-key updates but never lose one."""
    import threading

    from paddlebox_tpu.distributed.ps import _SparseTable
    from paddlebox_tpu.embedding import EmbeddingConfig

    cfg = EmbeddingConfig(dim=4, optimizer="sgd", learning_rate=0.01)
    table = _SparseTable(cfg)
    n_threads, n_pushes, n_keys = 8, 20, 500
    rng = np.random.default_rng(0)
    keys_pool = rng.choice(1 << 40, n_keys, replace=False).astype(np.uint64)
    init_rows = table.store.lookup_or_init(keys_pool).copy()
    init_by_key = {k: r for k, r in zip(keys_pool, init_rows)}
    per_thread = []
    for t in range(n_threads):
        r = np.random.default_rng(t + 1)
        batches = []
        for _ in range(n_pushes):
            k = r.choice(keys_pool, size=64)
            g = r.normal(size=(64, cfg.grad_width)).astype(np.float32)
            batches.append((k, g))
        per_thread.append(batches)

    errors = []

    def worker(batches):
        try:
            for k, g in batches:
                table.push(k, g, np.ones(len(k), np.float32),
                           np.zeros(len(k), np.float32))
        except Exception as e:          # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(b,))
               for b in per_thread]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors

    # exact invariants: per-key show counts and sgd weight sums are
    # order-independent
    expect_show = {}
    expect_gsum = {}
    for batches in per_thread:
        for k, g in batches:
            for i, key in enumerate(k):
                expect_show[key] = expect_show.get(key, 0.0) + 1.0
                expect_gsum[key] = expect_gsum.get(
                    key, np.zeros(cfg.grad_width)) + g[i].astype(np.float64)
    touched = np.array(sorted(expect_show), dtype=np.uint64)
    rows = table.store.get_rows(touched)
    np.testing.assert_allclose(
        rows[:, 0], [expect_show[k] for k in touched], rtol=0, atol=0)
    want_w = np.stack([init_by_key[k][2:2 + cfg.grad_width]
                       - cfg.learning_rate * expect_gsum[k]
                       for k in touched])
    got_w = rows[:, 2:2 + cfg.grad_width]
    np.testing.assert_allclose(got_w, want_w, rtol=1e-4, atol=1e-5)
