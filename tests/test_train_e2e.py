"""End-to-end: synthetic CTR data → passes of training → AUC lifts off 0.5."""

import numpy as np
import pytest

from paddlebox_tpu.data import DataFeedSchema, SlotDataset
from paddlebox_tpu.data.parser import parse_multislot_lines
from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore
from paddlebox_tpu.models import DeepFMModel, DNNCTRModel
from paddlebox_tpu.parallel import make_mesh
from paddlebox_tpu.train import Trainer, TrainerConfig

NUM_SLOTS = 4
VOCAB = 50   # ids per slot


def synth_dataset(n, seed=0, schema=None):
    """CTR data with real signal: each id has a latent weight; the label is
    bernoulli(sigmoid(sum of weights)). Learnable by embeddings alone."""
    rng = np.random.default_rng(seed)
    schema = schema or DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=1,
                                          batch_size=64, max_len=2)
    id_weight = np.random.default_rng(99).normal(size=(NUM_SLOTS, VOCAB)) * 1.5
    lines = []
    for _ in range(n):
        logits = 0.0
        parts = []
        ids_per_slot = []
        for s in range(NUM_SLOTS):
            k = rng.integers(1, 3)
            ids = rng.integers(0, VOCAB, size=k)
            ids_per_slot.append(ids)
            logits += id_weight[s, ids].sum()
        dense_val = rng.normal()
        p = 1.0 / (1.0 + np.exp(-(logits * 0.8)))
        label = float(rng.random() < p)
        parts.append(f"1 {label}")
        parts.append(f"1 {dense_val:.4f}")
        for s, ids in enumerate(ids_per_slot):
            # feature signs: slot-salted so slots don't collide
            signs = [str(int(i) + s * 1000003) for i in ids]
            parts.append(f"{len(signs)} {' '.join(signs)}")
        lines.append(" ".join(parts))
    ds = SlotDataset(schema)
    ds.records = parse_multislot_lines(lines, schema)
    return ds, schema


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


@pytest.mark.parametrize("model_cls", [DNNCTRModel, DeepFMModel])
def test_training_lifts_auc(mesh8, model_cls):
    ds, schema = synth_dataset(2048)
    emb_cfg = EmbeddingConfig(dim=8, learning_rate=0.15)
    store = HostEmbeddingStore(emb_cfg)
    model = model_cls(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1,
                      hidden=(32, 16))
    tr = Trainer(model, store, schema, mesh8,
                 TrainerConfig(global_batch_size=128, dense_lr=3e-3,
                               auc_buckets=1 << 12))
    results = [tr.train_pass(ds) for _ in range(3)]
    assert results[0]["steps"] == 16
    # training must lift AUC well above chance by the last pass
    assert results[-1]["auc"] > 0.62, results
    # and reduce loss vs the start
    assert results[-1]["loss_mean"] < results[0]["loss_first"]
    # eval pass (no updates) should agree roughly with train AUC
    ev = tr.eval_pass(ds)
    assert ev["auc"] > 0.62
    # store persisted learned weights
    assert len(store) > 0
    keys = ds.unique_keys()
    rows = store.get_rows(keys[:10])
    assert np.abs(rows[:, 2]).sum() > 0  # w moved
    assert rows[:, 0].sum() > 0          # show counters accumulated


def test_eval_pass_does_not_mutate(mesh8):
    ds, schema = synth_dataset(512, seed=5)
    emb_cfg = EmbeddingConfig(dim=4)
    store = HostEmbeddingStore(emb_cfg)
    model = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                        hidden=(16,))
    tr = Trainer(model, store, schema, mesh8,
                 TrainerConfig(global_batch_size=64, auc_buckets=1 << 10))
    tr.train_pass(ds)
    before = store.get_rows(ds.unique_keys())
    n_before = len(store)
    # eval on held-out data with NOVEL keys must not grow the store
    ds_eval, _ = synth_dataset(256, seed=77)
    tr.eval_pass(ds_eval)
    assert len(store) == n_before
    after = store.get_rows(ds.unique_keys())
    np.testing.assert_array_equal(before, after)


def test_train_pass_feeds_metric_registry(mesh8):
    from paddlebox_tpu.metrics import MetricRegistry
    ds, schema = synth_dataset(256, seed=8)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    model = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                        hidden=(16,))
    tr = Trainer(model, store, schema, mesh8,
                 TrainerConfig(global_batch_size=64, auc_buckets=1 << 10))
    reg = MetricRegistry()
    reg.init_metric("pass_auc", n_buckets=256)
    tr.train_pass(ds, metrics=reg)
    assert reg.get_metric_msg("pass_auc")["size"] == 256


def test_check_nan_inf_raises(mesh8):
    # Inject a NaN dense feature — the check_nan_inf guard
    # (FLAGS_check_nan_inf, boxps_worker.cc:575-580) must trip.
    ds, schema = synth_dataset(256, seed=6)
    ds.records.float_values[1][7] = np.nan
    emb_cfg = EmbeddingConfig(dim=4)
    store = HostEmbeddingStore(emb_cfg)
    model = DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                        hidden=(16,))
    tr = Trainer(model, store, schema, mesh8,
                 TrainerConfig(global_batch_size=64, check_nan_inf=True,
                               auc_buckets=1 << 10))
    with pytest.raises(FloatingPointError):
        tr.train_pass(ds)


def test_dedup_flag_equivalence(mesh8):
    """pullpush_dedup_keys merges duplicate tokens before the all_to_all;
    results must match the non-dedup path exactly."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.embedding import EmbeddingConfig, HostEmbeddingStore

    ds, schema = synth_dataset(512, seed=11)
    results = {}
    old = flags.pullpush_dedup_keys
    try:
        for on in (True, False):
            flags.pullpush_dedup_keys = on
            store = HostEmbeddingStore(
                EmbeddingConfig(dim=8, learning_rate=0.15))
            tr = Trainer(DeepFMModel(num_slots=NUM_SLOTS, emb_dim=8,
                                     dense_dim=1, hidden=(16, 8)),
                         store, schema, mesh8,
                         TrainerConfig(global_batch_size=128,
                                       dense_lr=3e-3))
            results[on] = tr.train_pass(ds)
    finally:
        flags.pullpush_dedup_keys = old
    assert abs(results[True]["loss_mean"]
               - results[False]["loss_mean"]) < 1e-5
    assert abs(results[True]["auc"] - results[False]["auc"]) < 1e-6


def _skewed_dataset(n=128):
    """Every sparse token maps to the TOP of the sorted key range, so all
    routed traffic lands on the last shard — guaranteed lane overflow at
    capacity_factor 1.0 on a multi-shard mesh."""
    rng = np.random.default_rng(3)
    schema = DataFeedSchema.ctr(num_sparse=NUM_SLOTS, num_float=1,
                                batch_size=64, max_len=2)
    lines = []
    for _ in range(n):
        parts = [f"1 {float(rng.random() < 0.5)}", f"1 {rng.normal():.4f}"]
        for s in range(NUM_SLOTS):
            # keys in [10^12, 10^12 + 600): sort to the end of any pass
            signs = [str(10**12 + int(rng.integers(0, 600)))
                     for _ in range(2)]
            parts.append(f"2 {' '.join(signs)}")
        lines.append(" ".join(parts))
    ds = SlotDataset(schema)
    ds.records = parse_multislot_lines(lines, schema)
    return ds, schema


def test_capacity_drops_surface_and_adapt(mesh8):
    """VERDICT weak#2: over-capacity routed drops must never be silent —
    counter surfaces in the pass stats + StatRegistry, a warning fires, and
    capacity_factor adapts for the next pass (reference never drops:
    box_wrapper_impl.h:44-81 sizes buffers dynamically)."""
    import warnings
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.utils.profiler import stat_get

    ds, schema = _skewed_dataset()
    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                             hidden=(8,)),
                 store, schema, mesh8,
                 TrainerConfig(global_batch_size=64, capacity_factor=1.0))
    before = stat_get("trainer.routed_dropped")
    # the proactive preplan (test_capacity_preplan.py) would size the
    # capacity first and make this pass lossless; this test certifies
    # the adaptive BACKSTOP, so force the lossy path
    old_preplan = flags.routed_capacity_preplan
    flags.routed_capacity_preplan = False
    with warnings.catch_warnings(record=True) as wlist:
        warnings.simplefilter("always")
        try:
            out = tr.train_pass(ds)
        finally:
            flags.routed_capacity_preplan = old_preplan
    assert out["routed_dropped"] > 0
    assert stat_get("trainer.routed_dropped") > before
    assert any("all_to_all capacity" in str(w.message) for w in wlist)
    # adaptive policy doubled the factor (bounded by the shard count)
    assert tr.cfg.capacity_factor == 2.0
    # next pass at the adapted capacity is drop-free
    out2 = tr.train_pass(ds)
    assert out2["routed_dropped"] == 0


def test_capacity_drop_fatal_flag(mesh8):
    from paddlebox_tpu.config import flags

    ds, schema = _skewed_dataset(64)
    store = HostEmbeddingStore(EmbeddingConfig(dim=4))
    tr = Trainer(DNNCTRModel(num_slots=NUM_SLOTS, emb_dim=4, dense_dim=1,
                             hidden=(8,)),
                 store, schema, mesh8,
                 TrainerConfig(global_batch_size=64, capacity_factor=1.0))
    old = flags.routed_drop_fatal
    old_preplan = flags.routed_capacity_preplan
    flags.routed_drop_fatal = True
    flags.routed_capacity_preplan = False   # certify the fatal backstop
    try:
        with pytest.raises(RuntimeError, match="all_to_all capacity"):
            tr.train_pass(ds)
    finally:
        flags.routed_drop_fatal = old
        flags.routed_capacity_preplan = old_preplan


def test_train_pass_preloads_next_working_set(mesh8):
    """train_pass(preload_keys=...) stages the NEXT pass's working set on
    the feed thread while this pass trains (PreLoadIntoMemory +
    BeginFeedPass pairing); the next pass consumes the staging and reuses
    resident rows."""
    ds1, schema = synth_dataset(256, seed=1)
    ds2, _ = synth_dataset(256, seed=2, schema=schema)
    store = HostEmbeddingStore(EmbeddingConfig(dim=8, learning_rate=0.1))
    tr = Trainer(DeepFMModel(num_slots=NUM_SLOTS, emb_dim=8, dense_dim=1,
                             hidden=(16,)),
                 store, schema, mesh8,
                 TrainerConfig(global_batch_size=64))
    out1 = tr.train_pass(ds1, preload_keys=ds2.unique_keys())
    assert np.isfinite(out1["loss_mean"])
    out2 = tr.train_pass(ds2)
    assert np.isfinite(out2["loss_mean"])
    m = tr.feed_mgr
    # the staging was consumed: pass 2 reused the overlap of key sets
    shared = np.intersect1d(ds1.unique_keys(), ds2.unique_keys())
    assert m.last_reused_rows == len(shared)
    assert m.last_fresh_rows == len(ds2.unique_keys()) - len(shared)
