"""Data plane: schema, parsing, columnar batches, packing, shuffle, dataset."""

import numpy as np
import pytest

from paddlebox_tpu.data import (DataFeedSchema, PackedBatch, Slot,
                                SlotDataset, SlotRecordBatch, SlotType,
                                parse_multislot_lines)
from paddlebox_tpu.data.parser import format_multislot_example
from paddlebox_tpu.data.shuffle import (LocalShuffler, deserialize_batch,
                                        route_records, serialize_batch)
from paddlebox_tpu.data.slot_record import batch_iterator


def make_schema(num_sparse=3, max_len=4, batch_size=4):
    return DataFeedSchema.ctr(num_sparse=num_sparse, num_float=2,
                              batch_size=batch_size, max_len=max_len)


def make_lines(schema, n, seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        parts = []
        for slot in schema.slots:
            if slot.type == SlotType.FLOAT:
                vals = [f"{rng.random():.4f}"] * slot.max_len
            else:
                k = rng.integers(1, slot.max_len + 2)
                vals = [str(rng.integers(0, 10**9)) for _ in range(k)]
            parts.append(str(len(vals)))
            parts.extend(vals)
        lines.append(" ".join(parts))
    return lines


def test_parse_roundtrip_counts():
    schema = make_schema()
    lines = make_lines(schema, 10)
    batch = parse_multislot_lines(lines, schema)
    assert batch.num == 10
    assert len(batch.sparse_values) == 3
    assert len(batch.float_values) == 3  # label + 2 dense
    for offs in batch.sparse_offsets:
        assert offs.shape == (11,)
        assert offs[0] == 0
        assert np.all(np.diff(offs) >= 1)


def test_parse_exact_values():
    schema = DataFeedSchema(
        [Slot("label", SlotType.FLOAT, max_len=1),
         Slot("s0", SlotType.UINT64, max_len=3)], batch_size=2)
    lines = ["1 1.0 2 11 22", "1 0.0 3 5 6 7"]
    b = parse_multislot_lines(lines, schema)
    assert b.num == 2
    np.testing.assert_array_equal(b.sparse_values[0], [11, 22, 5, 6, 7])
    np.testing.assert_array_equal(b.sparse_offsets[0], [0, 2, 5])
    np.testing.assert_allclose(b.float_values[0], [1.0, 0.0])


def test_parse_skips_malformed_lines_with_a_name():
    """PR-8 contract: a torn/foreign line among good ones is SKIPPED with
    the reader.parse_errors counter + a warning naming it (the PR-7
    malformed-donefile-line treatment) and must not leave partial columns
    behind; an ALL-malformed input still raises (wrong schema)."""
    import warnings

    from paddlebox_tpu import monitor

    schema = DataFeedSchema(
        [Slot("label", SlotType.FLOAT, max_len=1),
         Slot("s0", SlotType.UINT64, max_len=3)], batch_size=2)
    good = ["1 1.0 2 11 22", "1 0.0 3 5 6 7"]
    hub = monitor.hub()
    hub.enable(monitor.MemorySink())
    try:
        before = hub.summary()["counters"].get("reader.parse_errors", 0)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            b = parse_multislot_lines(
                [good[0], "1 1.0 2 11", good[1]], schema)  # torn mid-slot
        assert b.num == 2
        np.testing.assert_array_equal(b.sparse_offsets[0], [0, 2, 5])
        np.testing.assert_allclose(b.float_values[0], [1.0, 0.0])
        assert any("malformed MultiSlot line 2" in str(x.message)
                   for x in w)
        after = hub.summary()["counters"].get("reader.parse_errors", 0)
        assert after == before + 1
    finally:
        hub.disable()
    with pytest.raises(ValueError, match="every line was malformed"):
        parse_multislot_lines(["1 1.0 2 11", "garbage"], schema)


def test_parse_negative_slot_length_is_malformed():
    """ln=-1 used to pass the bounds checks (empty slice, pos moving
    BACKWARDS) and emit negative sparse_lens — silent batch corruption;
    it must count as a malformed line like any other."""
    schema = DataFeedSchema(
        [Slot("a", SlotType.UINT64, max_len=3),
         Slot("b", SlotType.UINT64, max_len=3)], batch_size=2)
    import warnings
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        b = parse_multislot_lines(["-1 2 7 8", "1 4 1 5"], schema)
    assert b.num == 1                      # only the good line survives
    np.testing.assert_array_equal(b.sparse_values[0], [4])
    np.testing.assert_array_equal(b.sparse_values[1], [5])
    assert all(np.all(np.diff(off) >= 0) for off in b.sparse_offsets)


def test_pack_pads_and_truncates():
    schema = DataFeedSchema(
        [Slot("label", SlotType.FLOAT, max_len=1),
         Slot("s0", SlotType.UINT64, max_len=2)], batch_size=2)
    lines = ["1 1.0 1 7", "1 0.0 4 1 2 3 4"]
    b = parse_multislot_lines(lines, schema)
    packed = b.pack(0, 2)
    assert packed.ids.shape == (2, 2)
    np.testing.assert_array_equal(packed.ids[0], [7, 0])   # padded
    np.testing.assert_array_equal(packed.ids[1], [1, 2])   # truncated
    np.testing.assert_array_equal(packed.mask[0], [True, False])
    np.testing.assert_array_equal(packed.mask[1], [True, True])
    np.testing.assert_allclose(packed.label(), [1.0, 0.0])


def test_pack_heterogeneous_max_len():
    schema = DataFeedSchema(
        [Slot("label", SlotType.FLOAT, max_len=1),
         Slot("short", SlotType.UINT64, max_len=1),
         Slot("long", SlotType.UINT64, max_len=4)], batch_size=2)
    lines = ["1 1.0 1 9 2 5 6", "1 0.0 1 8 1 3"]
    b = parse_multislot_lines(lines, schema)
    p = b.pack(0, 2)
    assert p.ids.shape == (2, 5)        # T = 1 + 4
    lay = p.layout()
    np.testing.assert_array_equal(lay.segment_ids, [0, 1, 1, 1, 1])
    ids_long, mask_long = p.slot_ids("long")
    np.testing.assert_array_equal(ids_long[0], [5, 6, 0, 0])
    np.testing.assert_array_equal(mask_long[1], [True, False, False, False])


def test_concat_and_select():
    schema = make_schema()
    b1 = parse_multislot_lines(make_lines(schema, 5, seed=1), schema)
    b2 = parse_multislot_lines(make_lines(schema, 7, seed=2), schema)
    cat = SlotRecordBatch.concat([b1, b2])
    assert cat.num == 12
    sel = cat.select(np.array([0, 11, 5]))
    assert sel.num == 3
    # row 0 of sel == row 0 of b1; row 1 of sel == row 6 of b2
    np.testing.assert_array_equal(
        sel.sparse_values[0][:sel.sparse_offsets[0][1]],
        b1.sparse_values[0][:b1.sparse_offsets[0][1]])


def test_shuffle_preserves_multiset():
    schema = make_schema()
    b = parse_multislot_lines(make_lines(schema, 20), schema)
    sh = LocalShuffler(seed=3).shuffle(b)
    assert sh.num == b.num
    np.testing.assert_array_equal(
        np.sort(np.concatenate(sh.sparse_values)),
        np.sort(np.concatenate(b.sparse_values)))


def test_route_records_partition():
    schema = make_schema()
    b = parse_multislot_lines(make_lines(schema, 30), schema)
    b.search_id = np.arange(30, dtype=np.uint64)
    routed = route_records(b, 3, "search_id")
    assert sum(r.num for r in routed if r is not None) == 30
    for dest, sub in enumerate(routed):
        assert np.all(sub.search_id % 3 == dest)


def test_serialize_roundtrip():
    schema = make_schema()
    b = parse_multislot_lines(make_lines(schema, 8), schema)
    b2 = deserialize_batch(serialize_batch(b), schema)
    assert b2.num == b.num
    for v1, v2 in zip(b.sparse_values, b2.sparse_values):
        np.testing.assert_array_equal(v1, v2)
    for f1, f2 in zip(b.float_values, b2.float_values):
        np.testing.assert_allclose(f1, f2)


def test_batch_iterator_shapes():
    schema = make_schema(batch_size=4)
    b = parse_multislot_lines(make_lines(schema, 10), schema)
    batches = list(batch_iterator(b, 4, drop_last=True))
    assert len(batches) == 2
    assert all(isinstance(p, PackedBatch) and p.num == 4 for p in batches)


def test_dataset_end_to_end(tmp_path):
    schema = make_schema(batch_size=4)
    for i in range(3):
        (tmp_path / f"part-{i}").write_text(
            "\n".join(make_lines(schema, 8, seed=i)) + "\n")
    ds = SlotDataset(schema)
    ds.set_filelist([str(tmp_path / f"part-{i}") for i in range(3)])
    ds.set_date(20260729)
    ds.load_into_memory()
    assert ds.num_examples == 24
    keys = ds.unique_keys()
    assert keys.ndim == 1 and len(keys) > 0
    ds.prepare_train(num_shards=2)
    shard_batches = list(ds.shard_batches(0))
    assert len(shard_batches) == 3  # 12 examples / bs 4


def test_dataset_pipe_command(tmp_path):
    schema = make_schema()
    p = tmp_path / "raw"
    p.write_text("\n".join(make_lines(schema, 6)) + "\n")
    ds = SlotDataset(schema)
    ds.set_filelist([str(p)])
    ds.set_pipe_command("cat")
    ds.load_into_memory(global_shuffle=False)
    assert ds.num_examples == 6


def test_dataset_preload(tmp_path):
    schema = make_schema()
    p = tmp_path / "raw"
    p.write_text("\n".join(make_lines(schema, 6)) + "\n")
    ds = SlotDataset(schema)
    ds.set_filelist([str(p)])
    ds.preload_into_memory(global_shuffle=False)
    ds.wait_preload_done()
    assert ds.num_examples == 6


def test_format_example_roundtrip():
    schema = DataFeedSchema(
        [Slot("label", SlotType.FLOAT, max_len=1),
         Slot("s0", SlotType.UINT64, max_len=3)])
    line = format_multislot_example([("label", [1.0]), ("s0", [4, 5])], schema)
    b = parse_multislot_lines([line], schema)
    np.testing.assert_array_equal(b.sparse_values[0], [4, 5])


def test_slots_shuffle_preserves_counts():
    schema = make_schema()
    ds = SlotDataset(schema)
    ds.records = parse_multislot_lines(make_lines(schema, 12), schema)
    before = np.sort(ds.records.sparse_values[1].copy())
    ds.slots_shuffle(["slot_1"], seed=1)
    after = np.sort(ds.records.sparse_values[1])
    np.testing.assert_array_equal(before, after)


def test_slots_shuffle_moves_whole_lists():
    """Per-example value LISTS move intact between examples (reference
    data_set.cc slots_shuffle swaps value vectors, not flat values)."""
    schema = DataFeedSchema(
        [Slot("label", SlotType.FLOAT, max_len=1),
         Slot("s0", SlotType.UINT64, max_len=4)])
    # distinctive ragged lists: lengths 1..4, values tagged by example
    lines = []
    for i in range(16):
        k = (i % 4) + 1
        vals = " ".join(str(100 * i + j) for j in range(k))
        lines.append(f"1 0.0 {k} {vals}")
    ds = SlotDataset(schema)
    ds.records = parse_multislot_lines(lines, schema)
    lists_before = {
        tuple(ds.records.sparse_values[0]
              [ds.records.sparse_offsets[0][i]:
               ds.records.sparse_offsets[0][i + 1]].tolist())
        for i in range(16)}
    ds.slots_shuffle(["s0"], seed=3)
    r = ds.records
    lists_after = [
        tuple(r.sparse_values[0][r.sparse_offsets[0][i]:
                                 r.sparse_offsets[0][i + 1]].tolist())
        for i in range(16)]
    # every post-shuffle per-example list is one of the original lists,
    # unbroken — and it's a real permutation (all originals survive)
    assert set(lists_after) == lists_before
    assert len(lists_after) == 16


def test_parser_plugin_unroll_hook(tmp_path):
    """UnrollInstance equivalent: a parser plugin's `unroll` attribute runs
    once after load (data_set.cc:2356 delegates to the plugin the same way)."""
    schema = make_schema()
    lines = make_lines(schema, 6)
    p = tmp_path / "f.txt"
    p.write_text("\n".join(lines) + "\n")

    def plugin(lns, sch):
        return parse_multislot_lines(list(lns), sch)

    calls = []

    def unroll(batch):
        calls.append(batch.num)
        # duplicate every instance (a PV unroll shape)
        idx = np.repeat(np.arange(batch.num), 2)
        return batch.select(idx)

    plugin.unroll = unroll
    ds = SlotDataset(schema)
    ds.set_filelist([str(p)])
    ds.set_parser_plugin(plugin)
    ds.load_into_memory(global_shuffle=False)
    assert calls == [6]
    assert ds.num_examples == 12


def test_merge_by_ins_id():
    """MergeByInsId semantics (data_set.cc:1012): groups concatenated,
    wrong-size groups dropped under merge_size."""
    schema = DataFeedSchema([
        Slot("label", SlotType.FLOAT, max_len=1),
        Slot("s0", SlotType.UINT64, max_len=8),
    ])
    lines = ["1 1.0 2 10 11", "1 0.0 1 20", "1 1.0 2 12 13",
             "1 0.0 1 21", "1 1.0 1 30"]
    ds = SlotDataset(schema)
    ds.records = parse_multislot_lines(lines, schema)
    # assign ins_ids: rows 0,2 share A; rows 1,3 share B; row 4 alone
    ds.records.ins_id[:] = [7, 8, 7, 8, 9]

    dropped = ds.merge_by_ins_id(merge_size=2)
    assert dropped == 1          # the singleton group (ins 9)
    assert ds.num_examples == 2
    r = ds.records
    merged = {int(r.ins_id[i]):
              sorted(r.sparse_values[0][r.sparse_offsets[0][i]:
                                        r.sparse_offsets[0][i + 1]].tolist())
              for i in range(r.num)}
    assert merged[7] == [10, 11, 12, 13]
    assert merged[8] == [20, 21]

    # merge_size=0: merge everything, drop nothing
    ds2 = SlotDataset(schema)
    ds2.records = parse_multislot_lines(lines, schema)
    ds2.records.ins_id[:] = [7, 8, 7, 8, 9]
    assert ds2.merge_by_ins_id() == 0
    assert ds2.num_examples == 3
